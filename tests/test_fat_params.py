"""Property tests for the fat-sweep parameter chooser (VERDICT r2 #9-10:
auto must never pick an unsupported shape; R selection is no longer
restricted to {512, 1024})."""

import pytest

pytest.importorskip("hypothesis")  # property tests need it; skip cleanly without
from hypothesis import given, settings, strategies as st

from tpubloom.ops.sweep import (
    _packed_rows,
    choose_fat_params,
    fat_pack,
    sweep_applicable,
)


@settings(max_examples=300, deadline=None)
@given(
    log2_nb=st.integers(min_value=3, max_value=26),
    log2_b=st.integers(min_value=4, max_value=25),
    w=st.sampled_from([4, 8, 16, 32, 64]),
    kind=st.sampled_from(["insert", "presence", "counting"]),
)
def test_choose_fat_params_always_valid(log2_nb, log2_b, w, kind):
    presence = kind == "presence"
    nb, batch = 1 << log2_nb, 1 << log2_b
    out = choose_fat_params(
        nb, batch, w, presence=presence, counting=kind == "counting"
    )
    if out is None:
        return
    J, R8, S, KJ, KBJ = out
    assert J == 128 // w and nb % J == 0
    NBJ = nb // J
    assert NBJ % R8 == 0, "sub-tiles must tile the fat rows exactly"
    P8 = NBJ // R8
    assert P8 % S == 0 and P8 // S >= 2, "grid must have >= 2 steps"
    assert KJ % 8 == 0 and 16 <= KJ <= 1024
    assert KBJ % 8 == 0 and KBJ >= KJ
    lam = batch * R8 // nb
    assert KJ >= min(1024, lam), "window must cover expected occupancy"
    bodies = S * J * fat_pack(w, presence)
    volume = bodies * _packed_rows(KJ, fat_pack(w, presence)) * R8
    if presence:
        assert S * R8 <= 1024, "tile cap (1024 fat rows validated r5)"
        assert bodies <= 128, (
            "presence S*J*PACK unroll must fit Mosaic's scoped-VMEM stack "
            "(r5 extraction kernel: 128 bodies validated, OOM at 256 — "
            "benchmarks/out/presence_geom_r5.json)"
        )
        assert S * J <= 128, "slot columns fit 128 lanes"
        assert volume <= 3_500_000, (
            "presence operand-volume bound (3.41M validated, 4.19M/6.03M "
            "OOM — presence_geom_r5.json)"
        )
        if bodies > 64:
            assert volume <= 2_200_000, (
                "joint (bodies, volume) bound: 128 bodies x 3.41M is a "
                "measured Mosaic OOM (the B=8M chooser corner the clean "
                "r5 B-sweep caught — b_sweep_r5.json) while 128 x 2.10M "
                "and 64 x 3.41M both compile"
            )
    elif kind == "counting":
        assert bodies <= 128, (
            "counting bodies bound: 256 bodies OOMs even at 2.10M volume "
            "(B=8M probe, r5 — the nibble plane expansions out-stack the "
            "insert kernel at equal geometry); 128 validated"
        )
        assert volume <= 2_200_000, "counting operand-volume bound"
    else:
        assert bodies <= 256, "insert-only unroll bound (validated at 256)"
        assert volume <= 4_300_000, "insert operand-volume bound"
    # VMEM budget: windows (PACKED rows) + in/out/pres tiles with headroom
    sup_rows = _packed_rows(KBJ, fat_pack(w, presence))
    assert (
        2 * J * sup_rows * 128 * 4 + 4 * (S * R8 * 128 * 4)
        <= 12 * 1024 * 1024
    )


def test_choose_fat_params_rejects_128_lane_overflow():
    """w=128 (block_bits=4096) can't fit the 1 + W (+1) update row in 128
    lanes; choose_fat_params must return None (ADVICE r3: a forced
    insert_path='sweep' previously hit an obscure negative-pad trace
    error in _fat_stream instead of the legacy guard's ValueError)."""
    assert choose_fat_params(1 << 20, 1 << 23, 128) is None
    assert choose_fat_params(1 << 20, 1 << 23, 128, presence=True) is None
    # w=64 insert fits (1+64 <= 128) both with and without presence
    assert choose_fat_params(1 << 20, 1 << 23, 64) is not None


@settings(max_examples=100, deadline=None)
@given(
    log2_nb=st.integers(min_value=3, max_value=26),
    log2_b=st.integers(min_value=4, max_value=25),
    w=st.sampled_from([4, 8, 16, 32, 64, 128]),
)
def test_sweep_applicable_never_lies(log2_nb, log2_b, w):
    """If auto says "sweep", one of the two kernels must actually accept
    the shape (fat qualifies, or the legacy guards pass)."""
    from tpubloom.ops.sweep import choose_params

    nb, batch = 1 << log2_nb, 1 << log2_b
    if not sweep_applicable(nb, batch, w):
        return
    if choose_fat_params(nb, batch, w) is not None:
        return
    R, kmax = choose_params(nb, batch)
    assert nb % R == 0 and w + 2 <= 128 and R % 32 == 0
