"""Chaos suite (ISSUE 2): drive the stack through injected faults and
pin the hardening they exposed.

Layers covered:

* the fault framework itself — deterministic policies, env arming,
  injection counters;
* CRC32C — published test vectors (the portable NumPy slicing-by-8 path
  must equal any C accelerator bit-for-bit);
* checkpoint v2 — corrupt/torn/truncated newest generation falls back to
  the previous one, quarantines the corpse, never leaves partial files
  (tmp+rename invariant under injected fsync faults), retention GC;
* server — restore-past-corruption keeps serving and walks
  DEGRADED -> SERVING; overload shedding with ``retry_after_ms``;
  graceful-drain admission (DRAINING sheds);
* client — shed-aware retries complete every call, DeleteBatch replays
  dedup instead of double-decrementing, the circuit breaker opens after
  consecutive transport failures and closes through a half-open probe.
"""

import os
import socket
import threading
import time

import grpc
import numpy as np
import pytest

from tpubloom import checkpoint as ckpt
from tpubloom import faults
from tpubloom.config import FilterConfig
from tpubloom.filter import BloomFilter
from tpubloom.obs import counters as obs_counters
from tpubloom.server.client import BloomClient, CircuitOpenError
from tpubloom.server.protocol import BloomServiceError
from tpubloom.server.service import BloomService, build_server
from tpubloom.utils.crc32c import crc32c

# ISSUE 6: the whole chaos module runs with the runtime lock-order /
# held-while-blocking tracker armed (in-process AND subprocess servers);
# teardown asserts zero violations — see tests/conftest.py. ISSUE 13:
# additionally gated on the declared lock-ORDER manifest — an
# undeclared acquisition edge anywhere in the armed run fails the
# module too.
pytestmark = pytest.mark.usefixtures("lock_check_armed", "lock_order_manifest")


@pytest.fixture(autouse=True)
def _disarm_all():
    faults.reset()
    yield
    faults.reset()


def _rand_keys(n, rng):
    return [rng.bytes(16) for _ in range(n)]


def _filter_with_keys(cfg, n=500, seed=0):
    f = BloomFilter(cfg)
    keys = _rand_keys(n, np.random.default_rng(seed))
    f.insert_batch(keys)
    return f, keys


# -- fault framework ---------------------------------------------------------


def test_fire_is_noop_when_disarmed():
    assert faults.fire("ckpt.write") is None


def test_unknown_point_and_bad_policy_rejected():
    with pytest.raises(ValueError, match="unknown fault point"):
        faults.arm("ckpt.wirte")  # typo must fail loudly
    with pytest.raises(ValueError, match="unknown fault policy"):
        faults.arm("ckpt.write", "sometimes")
    with pytest.raises(ValueError, match="unknown fault mode"):
        faults.arm("ckpt.write", mode="explode")


def test_once_policy_fires_exactly_once():
    faults.arm("rpc.pre_handle", "once")
    with pytest.raises(faults.InjectedFault):
        faults.fire("rpc.pre_handle")
    for _ in range(5):
        assert faults.fire("rpc.pre_handle") is None
    (desc,) = faults.active()
    assert desc["fired"] == 1


def test_nth_policy_period():
    faults.arm("rpc.pre_handle", "nth:3")
    hits = []
    for i in range(1, 10):
        try:
            faults.fire("rpc.pre_handle")
        except faults.InjectedFault:
            hits.append(i)
    assert hits == [3, 6, 9]


def test_probability_policy_is_seed_deterministic():
    def run():
        faults.arm("rpc.pre_handle", "prob:0.5:seed=42")
        pattern = []
        for _ in range(64):
            try:
                faults.fire("rpc.pre_handle")
                pattern.append(0)
            except faults.InjectedFault:
                pattern.append(1)
        return pattern

    a, b = run(), run()
    assert a == b, "seeded chaos must replay byte-identically"
    assert 10 < sum(a) < 54  # and actually mix faults with passes


def test_times_cap_bounds_any_policy():
    faults.arm("rpc.pre_handle", "always", times=2)
    fired = 0
    for _ in range(10):
        try:
            faults.fire("rpc.pre_handle")
        except faults.InjectedFault:
            fired += 1
    assert fired == 2


def test_env_var_arming(monkeypatch):
    monkeypatch.setenv(
        faults.ENV_VAR, "ckpt.fsync=once, rpc.pre_handle=nth:2:times=1"
    )
    faults.load_env(force=True)
    armed = {d["point"]: d for d in faults.active()}
    assert armed["ckpt.fsync"]["times"] == 1
    assert armed["rpc.pre_handle"]["policy"] == "nth:2"


def test_injection_counters():
    before = obs_counters.get("faults_injected")
    faults.arm("ckpt.restore_read", "once")
    with pytest.raises(faults.InjectedFault):
        faults.fire("ckpt.restore_read")
    assert obs_counters.get("faults_injected") == before + 1
    assert obs_counters.get("fault_ckpt_restore_read") >= 1


# -- CRC32C ------------------------------------------------------------------


def test_crc32c_published_vectors():
    # RFC 3720 / kernel crypto test vectors
    assert crc32c(b"") == 0x00000000
    assert crc32c(b"a") == 0xC1D04330
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(bytes(32)) == 0x8A9136AA
    assert crc32c(bytes(range(32))) == 0x46DD794E
    assert (
        crc32c(b"The quick brown fox jumps over the lazy dog") == 0x22620404
    )


def test_crc32c_streaming_continuation():
    rng = np.random.default_rng(3)
    blob = rng.bytes(100_003)  # odd length: exercises the tail loop
    whole = crc32c(blob)
    assert whole == crc32c(blob[40_000:], crc32c(blob[:40_000]))
    assert whole != crc32c(blob[:-1])


# -- checkpoint v2: corruption tolerance -------------------------------------


@pytest.fixture()
def cfg():
    return FilterConfig(m=1 << 14, k=5, key_len=16, key_name="chaos")


def _flip_byte(path: str, offset: int = -3):
    blob = bytearray(open(path, "rb").read())
    blob[offset] ^= 0xFF
    open(path, "wb").write(bytes(blob))


def test_corrupt_newest_falls_back_a_generation(cfg, tmp_path):
    sink = ckpt.FileSink(str(tmp_path))
    f, keys = _filter_with_keys(cfg)
    ckpt.save(f, sink, seq=1)
    f.insert_batch([b"tail-key-0000000"])
    ckpt.save(f, sink, seq=2)

    _flip_byte(sink._path("chaos", 2))  # payload bit rot
    before = obs_counters.get("ckpt_corrupt_detected")
    g = ckpt.restore(cfg, sink)
    assert g is not None and g._restored_seq == 1
    assert g.include_batch(keys).all()
    assert obs_counters.get("ckpt_corrupt_detected") == before + 1
    # the corpse is quarantined, not deleted (post-mortem material) and a
    # re-walk goes straight to the good generation
    qfile = tmp_path / "corrupt" / "chaos.000000000002.ckpt"
    assert qfile.exists()
    assert ckpt.restore(cfg, sink)._restored_seq == 1
    assert obs_counters.get("ckpt_corrupt_detected") == before + 1


def test_header_corruption_detected(cfg, tmp_path):
    sink = ckpt.FileSink(str(tmp_path))
    f, _ = _filter_with_keys(cfg)
    ckpt.save(f, sink, seq=1)
    path = sink._path("chaos", 1)
    _flip_byte(path, offset=len(ckpt.MAGIC_V2) + 12 + 4)  # inside header
    with pytest.raises(ckpt.CheckpointCorruptError, match="header"):
        ckpt._deserialize(open(path, "rb").read())
    assert ckpt.restore(cfg, sink) is None  # only generation is corrupt


def test_truncated_blob_detected(cfg, tmp_path):
    sink = ckpt.FileSink(str(tmp_path))
    f, keys = _filter_with_keys(cfg)
    ckpt.save(f, sink, seq=1)
    ckpt.save(f, sink, seq=2)
    path = sink._path("chaos", 2)
    blob = open(path, "rb").read()
    open(path, "wb").write(blob[: len(blob) // 2])
    g = ckpt.restore(cfg, sink)
    assert g._restored_seq == 1 and g.include_batch(keys).all()


def test_torn_write_fault_caught_on_restore(cfg, tmp_path):
    """mode=torn: the write 'succeeds' but half the blob is gone — only
    the CRC walk can notice. The previous generation must win."""
    sink = ckpt.FileSink(str(tmp_path))
    f, keys = _filter_with_keys(cfg)
    ckpt.save(f, sink, seq=1)
    faults.arm("ckpt.write", "once", mode="torn")
    ckpt.save(f, sink, seq=2)  # no exception: silent corruption
    g = ckpt.restore(cfg, sink)
    assert g._restored_seq == 1 and g.include_batch(keys).all()


def test_fsync_fault_leaves_no_partial_ckpt(cfg, tmp_path):
    """Kill-mid-checkpoint invariant: a failure before fsync+rename must
    leave neither a final .ckpt nor a stale .tmp behind."""
    sink = ckpt.FileSink(str(tmp_path))
    f, keys = _filter_with_keys(cfg)
    ckpt.save(f, sink, seq=1)
    faults.arm("ckpt.fsync", "always")
    with pytest.raises(faults.InjectedFault):
        ckpt.save(f, sink, seq=2)
    faults.reset()
    names = set(os.listdir(tmp_path))
    assert names == {"chaos.000000000001.ckpt"}, names
    assert ckpt.restore(cfg, sink)._restored_seq == 1


def test_restore_read_fault_skips_generation(cfg, tmp_path):
    sink = ckpt.FileSink(str(tmp_path))
    f, keys = _filter_with_keys(cfg)
    ckpt.save(f, sink, seq=1)
    ckpt.save(f, sink, seq=2)
    before = obs_counters.get("ckpt_restore_read_errors")
    faults.arm("ckpt.restore_read", "once")
    g = ckpt.restore(cfg, sink)
    assert g._restored_seq == 1
    assert obs_counters.get("ckpt_restore_read_errors") == before + 1
    # NOT quarantined — the bytes may be fine, only the read failed
    assert not (tmp_path / "corrupt").exists()
    assert ckpt.restore(cfg, sink)._restored_seq == 2


def test_config_mismatch_is_not_skippable(cfg, tmp_path):
    """The walk must NOT paper over an operator error: a config identity
    mismatch raises even though an older (also mismatched) blob exists."""
    sink = ckpt.FileSink(str(tmp_path))
    f, _ = _filter_with_keys(cfg)
    ckpt.save(f, sink, seq=1)
    ckpt.save(f, sink, seq=2)
    with pytest.raises(ValueError, match="mismatch on k"):
        ckpt.restore(cfg.replace(k=cfg.k + 1), sink)


def test_async_checkpointer_retention_gc(cfg, tmp_path):
    sink = ckpt.FileSink(str(tmp_path))
    f, keys = _filter_with_keys(cfg)
    cp = ckpt.AsyncCheckpointer(f, sink, retain=2)
    for _ in range(5):
        assert cp.trigger()
        assert cp.flush()
    cp.close(final_checkpoint=False)
    assert len(sink.list_seqs("chaos")) == 2
    assert ckpt.restore(cfg, sink).include_batch(keys).all()


def test_v1_blob_still_restores(cfg, tmp_path):
    """Read-compat: a pre-ISSUE-2 writer's blob (TPUBLOOM1, no CRC) must
    keep restoring."""
    import json

    sink = ckpt.FileSink(str(tmp_path))
    f, keys = _filter_with_keys(cfg)
    ckpt.save(f, sink, seq=1)
    path = sink._path("chaos", 1)
    header, payload = ckpt._deserialize(open(path, "rb").read())
    header.pop("payload_len"), header.pop("payload_crc32c")
    hdr = json.dumps(header).encode()
    open(path, "wb").write(
        ckpt.MAGIC + len(hdr).to_bytes(8, "little") + hdr + payload
    )
    g = ckpt.restore(cfg, sink)
    assert g._restored_seq == 1 and g.include_batch(keys).all()


# -- server: restore-past-corruption + health walk ---------------------------


def _start(tmp_path, port=0, **service_kw):
    service = BloomService(
        sink_factory=lambda config: ckpt.FileSink(str(tmp_path)), **service_kw
    )
    srv, bound = build_server(service, f"127.0.0.1:{port}")
    srv.start()
    return srv, service, bound


def test_server_restores_past_corrupt_newest_and_recovers_health(tmp_path):
    srv, service, port = _start(tmp_path)
    client = BloomClient(f"127.0.0.1:{port}")
    client.wait_ready()
    try:
        client.create_filter("c1", capacity=50_000, error_rate=0.01)
        rng = np.random.default_rng(5)
        durable = _rand_keys(1500, rng)
        client.insert_batch("c1", durable)
        client.checkpoint("c1", wait=True)  # generation A (good)
        tail = _rand_keys(500, rng)
        client.insert_batch("c1", tail)
        client.checkpoint("c1", wait=True)  # generation B (will corrupt)
    finally:
        client.close()
        srv.stop(grace=None)
    del service

    sink = ckpt.FileSink(str(tmp_path))
    seqs = sink.list_seqs("c1")
    assert len(seqs) >= 2
    _flip_byte(sink._path("c1", seqs[0]))

    srv2, service2, port2 = _start(tmp_path)
    client = BloomClient(f"127.0.0.1:{port2}")
    client.wait_ready()
    try:
        r = client.create_filter(
            "c1", capacity=50_000, error_rate=0.01, exist_ok=True
        )
        # fell back to generation A: checkpointed keys are there, the
        # server keeps serving
        assert client.include_batch("c1", durable).all()
        h = client.health()
        assert h["status"] == "DEGRADED"
        assert "checkpoint_corrupt:c1" in h["reasons"]
        assert (tmp_path / "corrupt").exists()
        # a DEGRADED server IS serving: readiness must not hang on it
        # (only accept_degraded=False insists on fully healthy)
        assert client.wait_ready(timeout=5)["status"] == "DEGRADED"
        with pytest.raises(TimeoutError):
            client.wait_ready(timeout=0.4, poll=0.05, accept_degraded=False)
        # writes still work while degraded...
        client.insert_batch("c1", [b"while-degraded00"])
        assert client.include("c1", b"while-degraded00")
        # ...and a fresh good checkpoint clears the degradation
        client.checkpoint("c1", wait=True)
        assert client.health()["status"] == "SERVING"
    finally:
        client.close()
        srv2.stop(grace=None)


# -- server: overload shedding + drain ---------------------------------------


def _slow_wrap(service, method, delay):
    orig = getattr(service, method)

    def slow(req):
        time.sleep(delay)
        return orig(req)

    setattr(service, method, slow)


def test_shed_surfaces_retry_after_ms(tmp_path):
    srv, service, port = _start(
        tmp_path, max_in_flight=2, retry_after_ms=37
    )
    _slow_wrap(service, "QueryBatch", 0.4)
    client = BloomClient(f"127.0.0.1:{port}")
    client.wait_ready()
    raw = BloomClient(f"127.0.0.1:{port}", max_retries=0)
    try:
        client.create_filter("shed", capacity=10_000, error_rate=0.01)
        keys = [b"k%015d" % i for i in range(64)]
        client.insert_batch("shed", keys)

        sheds, oks, errs = [], [], []

        def probe():
            try:
                oks.append(raw.include_batch("shed", keys))
            except BloomServiceError as e:
                (sheds if e.code == "RESOURCE_EXHAUSTED" else errs).append(e)

        threads = [threading.Thread(target=probe) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        assert sheds, "cap 2 with 6 concurrent slow queries must shed"
        # adaptive since ISSUE 3: the hint starts at the configured base
        # and grows with the shed rate — never below the base
        assert all(
            e.details.get("retry_after_ms", 0) >= 37 for e in sheds
        )
        assert len(oks) + len(sheds) == 6
        assert service.metrics.snapshot()["counters"]["requests_shed"] >= len(
            sheds
        )
        # Health answers DURING overload (unsheddable) and reports it
        h = client.health()
        assert h["max_in_flight"] == 2
        assert "shedding" in h["reasons"] and h["status"] == "DEGRADED"
    finally:
        raw.close()
        client.close()
        srv.stop(grace=None)


def test_retrying_clients_complete_under_shed_with_no_double_deletes(tmp_path):
    """The ISSUE-2 acceptance scenario: cap 2, slow handlers, every call
    completes via shed-aware retries, and deletes apply exactly once."""
    srv, service, port = _start(
        tmp_path, max_in_flight=2, retry_after_ms=20
    )
    _slow_wrap(service, "DeleteBatch", 0.15)
    client = BloomClient(f"127.0.0.1:{port}")
    client.wait_ready()
    try:
        client.create_filter(
            "cnt", capacity=20_000, error_rate=0.01, counting=True
        )
        keys = [b"dup%013d" % i for i in range(40)]
        client.insert_batch("cnt", keys)
        client.insert_batch("cnt", keys)  # every key at count 2

        workers = []
        failures = []
        chunks = [keys[i::8] for i in range(8)]

        def delete_chunk(chunk):
            try:
                c = BloomClient(
                    f"127.0.0.1:{port}", max_retries=10, backoff_base=0.02
                )
                try:
                    c.delete_batch("cnt", chunk)  # one delete per key
                finally:
                    c.close()
            except Exception as e:  # noqa: BLE001 — collected for assert
                failures.append(e)

        for chunk in chunks:
            t = threading.Thread(target=delete_chunk, args=(chunk,))
            workers.append(t)
            t.start()
        for t in workers:
            t.join()
        assert not failures, failures
        assert service.metrics.snapshot()["counters"]["requests_shed"] > 0
        # count 2 - exactly 1 delete = 1 -> every key still present; a
        # double-applied delete would read absent here
        assert client.include_batch("cnt", keys).all()
        # and one more delete round empties them (proves the first round
        # really applied once, not zero times)
        client.delete_batch("cnt", keys)
        assert not client.include_batch("cnt", keys).any()
    finally:
        client.close()
        srv.stop(grace=None)


def test_draining_sheds_and_health_reports(tmp_path):
    srv, service, port = _start(tmp_path)
    client = BloomClient(f"127.0.0.1:{port}", max_retries=1, backoff_base=0.01)
    client.wait_ready()
    try:
        client.create_filter("d", capacity=1000, error_rate=0.01)
        service.begin_drain()
        assert client.health()["status"] == "DRAINING"
        with pytest.raises(BloomServiceError, match="DRAINING"):
            client.insert_batch("d", [b"late"])
    finally:
        client.close()
        srv.stop(grace=None)


# -- service-level delete dedup ---------------------------------------------


def test_delete_dedup_replay_answers_from_cache(tmp_path):
    service = BloomService()
    service.CreateFilter(
        {"name": "cnt", "capacity": 10_000, "error_rate": 0.01,
         "options": {"counting": True}}
    )
    keys = [b"x%015d" % i for i in range(16)]
    service.InsertBatch({"name": "cnt", "keys": keys})
    req = {"name": "cnt", "keys": keys, "rid": "rid-logical-1"}
    r1 = service.DeleteBatch(req)
    r2 = service.DeleteBatch(req)  # replay of the same logical call
    assert r1 == r2
    # single-decrement: keys were at count 1, one delete -> absent; a
    # second APPLY would have underflowed/decremented a fresh insert
    hits = service.QueryBatch({"name": "cnt", "keys": keys})
    assert not np.unpackbits(
        np.frombuffer(hits["hits"], np.uint8), count=hits["n"]
    ).any()
    service.InsertBatch({"name": "cnt", "keys": keys})
    hits = service.QueryBatch({"name": "cnt", "keys": keys})
    assert np.unpackbits(
        np.frombuffer(hits["hits"], np.uint8), count=hits["n"]
    ).all()
    assert (
        service.metrics.snapshot()["counters"]["delete_dedup_hits"] == 1
    )


def test_client_retries_delete_after_transport_loss(tmp_path):
    """Response-lost-after-apply: the first DeleteBatch applies but the
    client sees a transport error; the auto-retry replays the rid and the
    dedup cache answers — net effect exactly one decrement."""
    srv, service, port = _start(tmp_path)
    client = BloomClient(f"127.0.0.1:{port}", backoff_base=0.01)
    client.wait_ready()

    class LostResponse(grpc.RpcError):
        def code(self):
            return grpc.StatusCode.UNAVAILABLE

    real_call = client._call_once
    dropped = []

    def flaky(method, req, timeout=None):
        resp = real_call(method, req, timeout=timeout)
        if method == "DeleteBatch" and not dropped:
            dropped.append(req["rid"])
            raise LostResponse()  # the apply landed; the answer did not
        return resp

    client._call_once = flaky
    try:
        client.create_filter(
            "cnt2", capacity=10_000, error_rate=0.01, counting=True
        )
        keys = [b"y%015d" % i for i in range(16)]
        client.insert_batch("cnt2", keys)
        client.insert_batch("cnt2", keys)  # count 2
        client.delete_batch("cnt2", keys)  # applied once + replayed once
        assert dropped, "the chaos shim must have dropped one response"
        assert client.include_batch("cnt2", keys).all(), (
            "double-applied delete: replay was re-executed, not deduped"
        )
        counters = service.metrics.snapshot()["counters"]
        assert counters["delete_dedup_hits"] == 1
    finally:
        client.close()
        srv.stop(grace=None)


# -- client circuit breaker --------------------------------------------------


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_breaker_opens_after_consecutive_failures_then_recovers(tmp_path):
    port = _free_port()
    client = BloomClient(
        f"127.0.0.1:{port}",
        max_retries=0,
        timeout=2,
        breaker_threshold=2,
        breaker_cooldown=0.4,
    )
    try:
        for _ in range(2):
            with pytest.raises(grpc.RpcError):
                client.health()
        assert client.breaker.state == "open"
        # fail-fast: no network, no backoff, no timeout wait
        t0 = time.monotonic()
        with pytest.raises(CircuitOpenError):
            client.health()
        assert time.monotonic() - t0 < 0.05
        assert obs_counters.get_gauge("client_breaker_state") == 2

        # server appears on the port; once the cooldown elapses AND the
        # gRPC channel's own reconnect backoff lets a probe through, the
        # half-open probe closes the circuit (a failed probe re-opens and
        # the next cooldown retries — hence the poll loop)
        srv, service, _ = _start(tmp_path, port=port)
        try:
            deadline = time.monotonic() + 15
            h = None
            while time.monotonic() < deadline:
                try:
                    h = client.health()
                    break
                except (grpc.RpcError, CircuitOpenError):
                    time.sleep(0.2)
            assert h is not None and h["status"] == "SERVING"
            assert client.breaker.state == "closed"
            assert obs_counters.get_gauge("client_breaker_state") == 0
        finally:
            srv.stop(grace=None)
    finally:
        client.close()


def test_breaker_halfopen_failure_reopens():
    port = _free_port()
    client = BloomClient(
        f"127.0.0.1:{port}",
        max_retries=0,
        timeout=2,
        breaker_threshold=1,
        breaker_cooldown=0.2,
    )
    try:
        with pytest.raises(grpc.RpcError):
            client.health()
        assert client.breaker.state == "open"
        time.sleep(0.25)
        with pytest.raises(grpc.RpcError):  # half-open probe fails too
            client.health()
        assert client.breaker.state == "open"
    finally:
        client.close()


def test_breaker_disabled_with_zero_threshold():
    client = BloomClient(
        "127.0.0.1:1", max_retries=0, timeout=1, breaker_threshold=0
    )
    try:
        for _ in range(3):
            with pytest.raises(grpc.RpcError):
                client.health()
        assert client.breaker.state == "closed"
    finally:
        client.close()


# -- wait_ready polls Health -------------------------------------------------


def test_wait_ready_blocks_until_serving(tmp_path):
    srv, service, port = _start(tmp_path)
    client = BloomClient(f"127.0.0.1:{port}")
    try:
        h = client.wait_ready()
        assert h["status"] == "SERVING"
        service.begin_drain()  # DRAINING is never ready -> times out
        with pytest.raises(TimeoutError, match="not ready"):
            client.wait_ready(timeout=0.5, poll=0.05)
    finally:
        client.close()
        srv.stop(grace=None)


# -- SIGTERM graceful drain (real process, real signal) ----------------------

#: mirrors test_distributed's child pattern: the image's sitecustomize
#: force-sets jax_platforms to the TPU plugin, so the child must pin cpu
#: via jax.config BEFORE any backend initializes.
_SERVER_CHILD = """\
import sys
import jax
jax.config.update("jax_platforms", "cpu")
from tpubloom.server.service import main
main(sys.argv[1:])
"""


def test_sigterm_drain_checkpoints_acked_state(tmp_path):
    """Kill -TERM a real server that has acked inserts but never
    checkpointed: the drain must write a final checkpoint (acked state
    survives) and exit 0."""
    import signal
    import subprocess
    import sys as _sys

    port = _free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }
    ckpt_dir = tmp_path / "ck"
    ckpt_dir.mkdir()
    script = tmp_path / "server_child.py"
    script.write_text(_SERVER_CHILD)
    proc = subprocess.Popen(
        [_sys.executable, str(script), str(port), str(ckpt_dir)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
    )
    client = BloomClient(f"127.0.0.1:{port}")
    try:
        client.wait_ready(timeout=90)
        client.create_filter("drain", capacity=20_000, error_rate=0.01)
        keys = _rand_keys(800, np.random.default_rng(17))
        client.insert_batch("drain", keys)  # acked, NOT checkpointed

        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=60)
        assert proc.returncode == 0, f"drain exited {proc.returncode}:\n{out[-3000:]}"

        sink = ckpt.FileSink(str(ckpt_dir))
        cfg = FilterConfig.from_capacity(20_000, 0.01, key_name="drain")
        g = ckpt.restore(cfg, sink)
        assert g is not None, "drain wrote no final checkpoint"
        assert g.include_batch(keys).all(), (
            "acked-but-unflushed inserts lost across graceful drain"
        )
    finally:
        client.close()
        if proc.poll() is None:
            proc.kill()


# -- chaos smoke (tier-1 wrapper around benchmarks/faults_smoke.py) ----------


def test_faults_smoke():
    """The benchmarks/faults_smoke.py end-to-end chaos check runs in
    tier-1 so the fault hooks cannot silently rot."""
    import importlib
    import sys

    bench_dir = os.path.join(os.path.dirname(__file__), "..", "benchmarks")
    sys.path.insert(0, os.path.abspath(bench_dir))
    try:
        faults_smoke = importlib.import_module("faults_smoke")
        result = faults_smoke.run_smoke()
    finally:
        sys.path.pop(0)
    assert result["restored_past_corruption"]
    assert result["sheds"] > 0
    assert result["deletes_double_applied"] == 0


# -- per-shard fault points (ISSUE 4 satellite) ------------------------------


def _routes_of(cfg, keys):
    """Host-side shard routing of each key (mirrors the device hash)."""
    import jax.numpy as jnp

    from tpubloom.ops import hashing
    from tpubloom.utils.packing import pack_keys

    keys_u8, lengths = pack_keys(keys, cfg.key_len)
    return np.asarray(
        hashing.route_shards(
            jnp.asarray(keys_u8),
            jnp.asarray(np.maximum(lengths, 0)),
            n_shards=cfg.shards,
            seed=cfg.seed,
        )
    )


def test_shard_fault_point_predicate_partial_failure():
    """The ISSUE-4 chaos contract: ``shard.insert`` with a ``shard=N``
    predicate fails ONLY batches that route a key to shard N — every
    other shard keeps serving (partial failure, not an outage)."""
    from tpubloom.parallel.sharded import ShardedBloomFilter

    cfg = FilterConfig(m=1 << 20, k=4, key_len=16, shards=8)
    f = ShardedBloomFilter(cfg)
    rng = np.random.default_rng(11)
    keys = _rand_keys(256, rng)
    routes = _routes_of(cfg, keys)
    target = int(routes[0])
    hit = [k for k, r in zip(keys, routes) if r == target]
    miss = [k for k, r in zip(keys, routes) if r != target][:32]
    assert hit and miss, "batch did not spread over shards"

    faults.arm("shard.insert", "always", pred={"shard": target})
    # a batch touching the target shard dies...
    with pytest.raises(faults.InjectedFault):
        f.insert_batch(hit[:4])
    # ...but batches routed AROUND it land fine (partial failure)
    f.insert_batch(miss)
    assert np.asarray(f.include_batch(miss)).all()
    faults.disarm("shard.insert")

    # the query path has its own point; `once` disarms after one firing
    faults.arm("shard.query", "once", pred={"shard": target})
    assert np.asarray(f.include_batch(miss)).all()  # doesn't touch target
    with pytest.raises(faults.InjectedFault):
        f.include_batch(hit[:2])
    f.include_batch(hit[:2])  # budget spent: the shard serves again


def test_shard_fault_partial_failure_chaos_sharded_server(tmp_path):
    """Partial-failure chaos end to end: a sharded filter behind the
    server keeps answering for healthy shards while one shard's insert
    path is poisoned; the client sees a structured INTERNAL error for
    poisoned batches, not a dead server — and the shard heals when the
    fault disarms."""
    service = BloomService()
    srv, port = build_server(service, "127.0.0.1:0")
    srv.start()
    client = BloomClient(f"127.0.0.1:{port}", max_retries=0)
    cfg = FilterConfig(m=1 << 20, k=4, key_len=16, shards=8)
    try:
        client.wait_ready()
        client.create_filter(
            "sh", config={"m": 1 << 20, "k": 4, "key_len": 16, "shards": 8}
        )
        rng = np.random.default_rng(12)
        keys = _rand_keys(256, rng)
        routes = _routes_of(cfg, keys)
        target = int(routes[0])
        poisoned = [k for k, r in zip(keys, routes) if r == target][:8]
        healthy = [k for k, r in zip(keys, routes) if r != target][:64]

        faults.arm("shard.insert", "always", pred={"shard": target})
        with pytest.raises(BloomServiceError, match="INTERNAL"):
            client.insert_batch("sh", poisoned)
        client.insert_batch("sh", healthy)  # unaffected shards serve
        assert client.include_batch("sh", healthy).all()
        assert not client.include_batch("sh", poisoned).any()
        assert obs_counters.get("fault_shard_insert") >= 1

        faults.disarm("shard.insert")  # the shard heals
        client.insert_batch("sh", poisoned)
        assert client.include_batch("sh", poisoned).all()
    finally:
        client.close()
        srv.stop(grace=None)


def test_shard_fault_env_predicate_syntax(monkeypatch):
    monkeypatch.setenv(faults.ENV_VAR, "shard.insert=once:shard=3")
    faults.load_env(force=True)
    (desc,) = faults.active()
    assert desc["point"] == "shard.insert"
    assert desc["pred"] == {"shard": "3"}
    # non-matching context passes through WITHOUT consuming the budget
    assert faults.fire("shard.insert", shard=1) is None
    assert faults.fire("shard.insert") is None
    with pytest.raises(faults.InjectedFault):
        faults.fire("shard.insert", shard=3)
    assert faults.fire("shard.insert", shard=3) is None  # once spent


def test_dist_initialize_fault_point():
    from tpubloom.parallel.distributed import initialize_multihost

    faults.arm("dist.initialize", "once")
    with pytest.raises(faults.InjectedFault):
        initialize_multihost()
    topo = initialize_multihost()  # disarmed: single-host no-op
    assert topo["process_count"] >= 1


# -- ISSUE 13 (chaos-coverage closure): the response-loss + per-shard
# delete points get their own armed drives ----------------------------------


def test_rpc_post_handle_response_loss_absorbed_by_dedup():
    """``rpc.post_handle`` fires AFTER the handler applied (and the
    barrier/forward ran) but before the response encodes — the "ack lost
    in flight" case rid-dedup exists for. On a counting filter, the
    same-rid retry must answer from the cache instead of incrementing a
    second time."""
    service = BloomService()
    srv, port = build_server(service, "127.0.0.1:0")
    srv.start()
    client = BloomClient(f"127.0.0.1:{port}", max_retries=0)
    try:
        client.wait_ready()
        client.create_filter(
            "cnt", capacity=20_000, error_rate=0.01, counting=True
        )
        keys = [b"pl-%04d" % i for i in range(64)]
        req = client._encode_keys({"name": "cnt"}, keys)

        faults.arm("rpc.post_handle", "once")
        with pytest.raises(BloomServiceError, match="INTERNAL"):
            client._rpc("InsertBatch", dict(req), rid="post-handle-rid-1")
        assert obs_counters.get("fault_rpc_post_handle") >= 1
        # the apply LANDED even though the response was lost
        assert client.include_batch("cnt", keys).all()

        # same-rid retry: served from the dedup cache, no second apply
        resp = client._rpc("InsertBatch", dict(req), rid="post-handle-rid-1")
        assert resp["ok"] and resp["n"] == len(keys)
        # exactly-once proof: counts are 1, so ONE delete round empties
        client.delete_batch("cnt", keys)
        assert not client.include_batch("cnt", keys).any(), (
            "retry after rpc.post_handle double-applied the increments"
        )
    finally:
        client.close()
        srv.stop(grace=None)


def test_shard_delete_fault_point_predicate_partial_failure():
    """``shard.delete`` mirrors the insert/query chaos contract on the
    delete path: with a ``shard=N`` predicate only batches routing a key
    to shard N die, other shards keep deleting — and the poisoned
    shard's counts are untouched (no partial decrement before the
    fault: it fires host-side, before the launch)."""
    from tpubloom.parallel.sharded import ShardedBloomFilter

    cfg = FilterConfig(m=1 << 20, k=4, key_len=16, shards=8, counting=True)
    f = ShardedBloomFilter(cfg)
    rng = np.random.default_rng(13)
    keys = _rand_keys(256, rng)
    routes = _routes_of(cfg, keys)
    target = int(routes[0])
    hit = [k for k, r in zip(keys, routes) if r == target][:8]
    miss = [k for k, r in zip(keys, routes) if r != target][:32]
    assert hit and miss, "batch did not spread over shards"
    f.insert_batch(hit + miss)  # every count exactly 1

    faults.arm("shard.delete", "always", pred={"shard": target})
    # a delete touching the target shard dies WHOLE (fired pre-launch)...
    with pytest.raises(faults.InjectedFault):
        f.delete_batch(hit[:4])
    assert np.asarray(f.include_batch(hit)).all(), (
        "failed delete decremented anyway"
    )
    # ...but deletes routed around it land fine (partial failure)
    f.delete_batch(miss)
    assert not np.asarray(f.include_batch(miss)).any()
    assert obs_counters.get("fault_shard_delete") >= 1
    faults.disarm("shard.delete")

    f.delete_batch(hit)  # the shard heals: counts reach zero
    assert not np.asarray(f.include_batch(hit)).any()
