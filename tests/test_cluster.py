"""Cluster-mode suite (ISSUE 9).

Layers covered:

* slot hashing — CRC16-XMODEM vectors, hash tags, range compression,
  CRC-checked map persistence (corruption reads as "no map");
* ownership checks — MOVED/ASK/CLUSTERDOWN shapes, importing-side
  ``asking`` discipline, config-epoch rejection of stale assignments;
* the routed client — slot cache bootstrap, MOVED healing after an
  out-of-band ownership flip, hash-tag colocation;
* live migration — under concurrent client load, counting filters,
  exactly-once proof (every acked key present at the new owner, ONE
  delete round empties them), dual-write forward + import-gate dedup,
  epoch bump, source answering MOVED after the handoff;
* migration resume — an injected mid-migration crash + re-drive takes
  the op-log-tail path (no blob resend) and stays exactly-once;
* the acceptance chaos story — a real subprocess source is SIGKILLed
  mid-migration under load, restarted, and the re-driven migration
  finishes with zero lost / zero doubled acked writes
  (``test_migration_sigkill_acceptance``);
* satellites — sentinel ``TopologyEvents`` push (client re-points
  without an error round trip), ``tpubloom.obs.aggregate`` cross-node
  scrape merge, histogram exemplars linking latency buckets to slowlog
  rids, the rebalancer's planning, and the lock-order manifest diff
  (module teardown asserts every runtime acquisition edge this suite
  drives is DECLARED in ``tpubloom/analysis/lock_order.py``).
"""

import json
import os
import threading
import time

import grpc
import pytest

from tpubloom import faults
from tpubloom.cluster import slots as S
from tpubloom.cluster.client import ClusterClient
from tpubloom.cluster.node import ClusterState
from tpubloom.cluster.rebalance import even_ranges, plan_moves
from tpubloom.obs import counters as obs_counters
from tpubloom.repl import OpLog
from tpubloom.server import protocol
from tpubloom.server.client import BloomClient
from tpubloom.server.protocol import BloomServiceError
from tpubloom.server.service import BloomService, build_server

# ISSUE 6: armed lock-order / held-while-blocking tracking for the whole
# module (asserted violation-free at teardown — tests/conftest.py),
# plus the shared lock-ORDER manifest gate (ISSUE 13 moved the local
# fixture into conftest so every armed chaos module runs the same diff).
pytestmark = pytest.mark.usefixtures("lock_check_armed", "lock_order_manifest")


@pytest.fixture(autouse=True)
def _disarm_all():
    faults.reset()
    yield
    faults.reset()


def _wait(pred, timeout=30.0, poll=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(poll)
    raise AssertionError(f"timed out waiting for {msg}")


def _node(tmp_path, name, *, sink=False):
    """In-process cluster-enabled primary (op log + slot map persisted
    in the log dir)."""
    from tpubloom import checkpoint as ckpt

    d = tmp_path / name
    oplog = OpLog(str(d / "log"))
    svc = BloomService(
        sink_factory=(
            (lambda config: ckpt.FileSink(str(d / "ck"))) if sink else None
        ),
        oplog=oplog,
    )
    srv, port = build_server(svc, "127.0.0.1:0")
    srv.start()
    addr = f"127.0.0.1:{port}"
    svc.listen_address = addr
    svc.cluster = ClusterState(addr, state_dir=str(d / "log"))
    return svc, srv, addr, oplog


def _teardown(*nodes):
    for svc, srv, _addr, oplog in nodes:
        srv.stop(grace=None)
        oplog.close()
        if svc.cluster is not None:
            svc.cluster.close()


def _assign_even(nodes):
    addrs = [n[2] for n in nodes]
    ranges = even_ranges(addrs)
    for svc, _srv, _addr, _ in nodes:
        svc.ClusterSetSlot({"assign": ranges, "epoch": 1})
    return addrs


def _name_owned_by(owners_fn, addr, prefix="f"):
    for i in range(4096):
        cand = f"{prefix}-{i}"
        if owners_fn(S.key_slot(cand)) == addr:
            return cand
    raise AssertionError("no candidate name hashed to the wanted node")


# -- slot hashing + map ------------------------------------------------------


def test_crc16_and_key_slot_vectors():
    # the classic CRC16-XMODEM check value — the polynomial Redis uses
    assert S.crc16(b"123456789") == 0x31C3
    assert 0 <= S.key_slot("foo") < S.NUM_SLOTS
    assert S.key_slot("foo") == S.crc16(b"foo") % S.NUM_SLOTS
    # hash tags: a non-empty {...} body hashes alone (Redis rule)
    assert S.key_slot("user:{42}:seen") == S.key_slot("user:{42}:blocked")
    assert S.key_slot("user:{42}:seen") == S.key_slot("42")
    # empty tag and no tag hash the whole name
    assert S.key_slot("{}x") == S.crc16(b"{}x") % S.NUM_SLOTS
    assert S.key_slot(b"bytes-too") == S.crc16(b"bytes-too") % S.NUM_SLOTS


def test_ranges_roundtrip_and_store(tmp_path):
    owners = {0: "a", 1: "a", 2: "b", 4: "a", 5: "a"}
    r = S.ranges_of(owners)
    assert r == [[0, 1, "a"], [2, 2, "b"], [4, 5, "a"]]
    assert S.expand_ranges(r) == owners

    m = S.SlotMap()
    m.adopt_assignments(r, 3)
    m.migrating[2] = "c"
    store = S.SlotStore(str(tmp_path))
    store.store(m)
    loaded = S.SlotStore(str(tmp_path)).load()
    assert loaded.epoch == 3 and loaded.owners == owners
    assert loaded.migrating == {2: "c"}
    # corruption reads as "no map" (CLUSTERDOWN until re-pushed), never
    # a crash and never the wrong shard's keys
    with open(store.path, "a") as f:
        f.write("rot")
    assert S.SlotStore(str(tmp_path)).load() is None


def test_slot_map_epoch_discipline(tmp_path):
    m = S.SlotMap()
    assert m.adopt_assignments([[0, 10, "a"]], 5)
    assert not m.adopt_assignments([[0, 10, "b"]], 4)  # stale push
    assert m.owner(3) == "a"

    state = ClusterState("a", state_dir=str(tmp_path))
    state.set_slot({"assign": [[0, S.NUM_SLOTS - 1, "a"]], "epoch": 5})
    with pytest.raises(BloomServiceError, match="STALE_EPOCH"):
        state.set_slot({"assign": [[0, 10, "b"]], "epoch": 4})
    with pytest.raises(BloomServiceError, match="STALE_EPOCH"):
        state.set_slot({"slot": 1, "state": "node", "addr": "b", "epoch": 2})
    state.set_slot({"slot": 1, "state": "node", "addr": "b", "epoch": 6})
    assert state.owner(1) == "b" and state.epoch() == 6


# -- ownership checks --------------------------------------------------------


def test_moved_ask_clusterdown_shapes(tmp_path):
    a = _node(tmp_path, "a")
    b = _node(tmp_path, "b")
    try:
        # no assignment yet: every keyed call is CLUSTERDOWN
        ca = BloomClient(a[2])
        with pytest.raises(BloomServiceError, match="CLUSTERDOWN"):
            ca.create_filter("pre-map", capacity=1000, error_rate=0.01)

        addrs = _assign_even((a, b))
        name_b = _name_owned_by(a[0].cluster.owner, addrs[1], prefix="onb")
        slot_b = S.key_slot(name_b)
        # node a does not own name_b's slot: MOVED with machine-readable
        # slot + addr (what clients re-route from)
        try:
            ca.create_filter(name_b, capacity=1000, error_rate=0.01)
            raise AssertionError("expected MOVED")
        except BloomServiceError as e:
            assert e.code == "MOVED"
            assert e.details["slot"] == slot_b
            assert e.details["addr"] == addrs[1]

        # importing side only serves asking-flagged requests: park
        # slot_b's OWNERSHIP on a (both views) so b is purely importing
        for svc in (a[0], b[0]):
            svc.ClusterSetSlot(
                {"slot": slot_b, "state": "node", "addr": addrs[0],
                 "epoch": 2}
            )
        b[0].ClusterSetSlot(
            {"slot": slot_b, "state": "importing", "addr": addrs[0]}
        )
        cb = BloomClient(b[2])
        with pytest.raises(BloomServiceError, match="MOVED"):
            cb._rpc("CreateFilter",
                    {"name": name_b, "capacity": 1000, "error_rate": 0.01})
        assert cb._rpc(
            "CreateFilter",
            {"name": name_b, "capacity": 1000, "error_rate": 0.01,
             "asking": True},
        )["ok"]

        # migrating side: an existing filter serves, a missing one ASKs
        name_a = _name_owned_by(a[0].cluster.owner, addrs[0], prefix="ona")
        ca.create_filter(name_a, capacity=1000, error_rate=0.01)
        slot_a = S.key_slot(name_a)
        a[0].ClusterSetSlot(
            {"slot": slot_a, "state": "migrating", "addr": addrs[1]}
        )
        assert ca.include_batch(name_a, [b"x"]) is not None  # still served
        # a missing filter in the migrating slot answers ASK: a hash
        # tag pins the probe name to exactly that slot
        missing = f"{{{name_a}}}:gone"
        assert S.key_slot(missing) == slot_a
        try:
            ca.include_batch(missing, [b"x"])
            raise AssertionError("expected ASK")
        except BloomServiceError as e:
            assert e.code == "ASK" and e.details["addr"] == addrs[1]
    finally:
        _teardown(a, b)


# -- routed client -----------------------------------------------------------


def test_cluster_client_routing_and_moved_heal(tmp_path):
    a = _node(tmp_path, "a")
    b = _node(tmp_path, "b")
    try:
        addrs = _assign_even((a, b))
        cc = ClusterClient(startup_nodes=addrs)
        assert cc.epoch == 1

        names = [
            _name_owned_by(a[0].cluster.owner, addrs[0], prefix="ra"),
            _name_owned_by(a[0].cluster.owner, addrs[1], prefix="rb"),
        ]
        for n in names:
            cc.create_filter(n, capacity=2000, error_rate=0.01)
            cc.insert_batch(n, [b"k1", b"k2"])
            assert cc.include_batch(n, [b"k1", b"k2", b"nope"]).tolist() == [
                True, True, False,
            ]
        assert set(names) <= set(cc.list_filters())
        assert cc.stats(names[0])["n_inserted"] >= 2

        # flip names[0]'s slot to b OUT OF BAND (no migration — fresh
        # create there) and prove the client heals via MOVED
        slot = S.key_slot(names[0])
        epoch = a[0].cluster.epoch() + 1
        for svc in (a[0], b[0]):
            svc.ClusterSetSlot(
                {"slot": slot, "state": "node", "addr": addrs[1],
                 "epoch": epoch}
            )
        before = obs_counters.get("client_moved_redirects")
        cc.create_filter(names[0], capacity=2000, error_rate=0.01,
                         exist_ok=True)
        assert obs_counters.get("client_moved_redirects") > before
        assert cc.epoch == epoch
        cc.close()
    finally:
        _teardown(a, b)


def test_cluster_client_ships_keys_fixed_per_hop(tmp_path, monkeypatch):
    """ISSUE 14 satellite (the named PR-10 seam): the cluster client's
    keyed batches ride the zero-copy ``keys_fixed`` encoding through
    the per-shard connections — encoded per HOP under that shard
    client's own negotiation, for inserts, queries AND deletes."""
    from tpubloom.server.client import BloomClient

    a = _node(tmp_path, "a")
    b = _node(tmp_path, "b")
    try:
        addrs = _assign_even((a, b))
        seen: list = []
        orig = BloomClient._rpc

        def spy(self, method, req, **kw):
            if method in ("InsertBatch", "QueryBatch", "DeleteBatch"):
                seen.append((method, "keys_fixed" in req))
            return orig(self, method, req, **kw)

        monkeypatch.setattr(BloomClient, "_rpc", spy)
        cc = ClusterClient(startup_nodes=addrs)
        name = _name_owned_by(a[0].cluster.owner, addrs[0], prefix="fx")
        cc.create_filter(name, capacity=2000, error_rate=0.01, counting=True)
        keys = [b"fx-%05d" % i for i in range(16)]  # equal-width batch
        assert cc.insert_batch(name, keys) == 16
        assert cc.include_batch(name, keys).all()
        assert cc.delete_batch(name, keys) == 16
        assert not cc.include_batch(name, keys).any()
        fixed = {m for m, fx in seen if fx}
        assert fixed == {"InsertBatch", "QueryBatch", "DeleteBatch"}, seen
        cc.close()
    finally:
        _teardown(a, b)


# -- live migration ----------------------------------------------------------


def test_live_migration_under_load_exactly_once(tmp_path):
    a = _node(tmp_path, "a")
    b = _node(tmp_path, "b")
    try:
        addrs = _assign_even((a, b))
        cc = ClusterClient(startup_nodes=addrs)
        name = _name_owned_by(a[0].cluster.owner, addrs[0], prefix="cnt")
        slot = S.key_slot(name)
        cc.create_filter(name, capacity=50_000, error_rate=0.01,
                         counting=True)
        keys0 = [b"pre-%05d" % i for i in range(400)]
        cc.insert_batch(name, keys0)

        stop = threading.Event()
        acked: list = []
        failed: list = []

        def writer():
            i = 0
            while not stop.is_set():
                ks = [b"live-%04d-%02d" % (i, j) for j in range(20)]
                try:
                    cc.insert_batch(name, ks)
                    acked.append(ks)
                except Exception as e:  # noqa: BLE001
                    failed.append(repr(e))
                i += 1

        t = threading.Thread(target=writer)
        t.start()
        time.sleep(0.3)
        resp = BloomClient(addrs[0]).migrate_slot(slot, addrs[1])
        assert resp["ok"] and resp["filters_moved"] >= 1
        assert resp["epoch"] == 2
        time.sleep(0.2)
        stop.set()
        t.join()
        # the dual-write + redirect healing should make the handoff
        # invisible to the writer (transparent re-drives included)
        assert not failed, f"writer saw errors across the handoff: {failed[:3]}"

        # ownership flipped everywhere: source answers MOVED, maps agree
        assert a[0].cluster.owner(slot) == addrs[1]
        assert b[0].cluster.owner(slot) == addrs[1]
        with pytest.raises(BloomServiceError, match="MOVED"):
            BloomClient(addrs[0]).include_batch(name, [b"x"])
        # the source retired its copy (logged drop)
        assert name not in a[0]._filters

        # zero lost: every acked key present at the new owner...
        allkeys = keys0 + [k for ks in acked for k in ks]
        assert cc.include_batch(name, allkeys).all(), (
            "acked writes lost across the migration"
        )
        # ...and zero doubled: counting counts are exactly 1, so ONE
        # delete round empties every key
        cc.delete_batch(name, keys0)
        for ks in acked:
            cc.delete_batch(name, ks)
        assert not cc.include_batch(name, allkeys).any(), (
            "acked writes double-applied across the migration"
        )
        cc.close()
    finally:
        _teardown(a, b)


def test_migration_resume_takes_tail_path(tmp_path):
    """An interrupted migration re-driven: the target already holds the
    filter, so the resume probes its gate and replays only the op-log
    tail (no blob resend) — and the result is still exactly-once."""
    a = _node(tmp_path, "a")
    b = _node(tmp_path, "b")
    try:
        addrs = _assign_even((a, b))
        cc = ClusterClient(startup_nodes=addrs)
        # two hash-tagged counting filters on one source-owned slot —
        # the injected fault lands BETWEEN their installs
        tag = None
        for i in range(4096):
            if a[0].cluster.owner(S.key_slot(f"{{r{i}}}:a")) == addrs[0]:
                tag = f"r{i}"
                break
        fa, fb = f"{{{tag}}}:a", f"{{{tag}}}:b"
        slot = S.key_slot(fa)
        for n in (fa, fb):
            cc.create_filter(n, capacity=20_000, error_rate=0.01,
                             counting=True)
        keys0 = [b"r-%04d" % i for i in range(200)]
        cc.insert_batch(fa, keys0)
        cc.insert_batch(fb, keys0)

        # passes (sorted filter order): fa probe(1), fa install(2),
        # fb probe(3) ← fires — fa fully landed, fb untouched
        faults.arm("cluster.migrate_send", "nth:3", times=1)
        with pytest.raises(BloomServiceError):
            BloomClient(addrs[0]).migrate_slot(slot, addrs[1])
        faults.disarm("cluster.migrate_send")
        # marks survive: source still owns + migrating, target importing
        assert a[0].cluster.owner(slot) == addrs[0]
        assert b[0].cluster.is_importing(slot)

        # writes keep landing mid-window: fa's are dual-written live
        # (its forward is armed and the target holds its gate). fb has
        # no gate yet, so its writes park on IMPORT_NOT_READY re-drives
        # until the resumed migration installs it — run them
        # CONCURRENTLY with the re-drive to prove the park heals.
        keys1 = [b"r2-%04d" % i for i in range(150)]
        cc.insert_batch(fa, keys1)

        before = obs_counters.get("cluster_migrate_snapshots_sent")
        migrate_result: list = []
        mt = threading.Thread(
            target=lambda: migrate_result.append(
                BloomClient(addrs[0], timeout=120).migrate_slot(
                    slot, addrs[1]
                )
            )
        )
        mt.start()
        cc.insert_batch(fb, keys1)  # parks until fb's snapshot lands
        mt.join(timeout=120)
        assert migrate_result, "re-driven migration did not finish"
        resp = migrate_result[0]
        assert resp["ok"] and resp["filters_moved"] == 2
        # fa resumed via the op-log TAIL (no blob resend); only fb's
        # blob shipped
        assert resp["snapshots"] == 1
        assert resp["tail_records"] >= 1
        assert obs_counters.get("cluster_migrate_snapshots_sent") == before + 1

        allkeys = keys0 + keys1
        for n in (fa, fb):
            assert cc.include_batch(n, allkeys).all(), f"lost writes ({n})"
            cc.delete_batch(n, keys0)
            cc.delete_batch(n, keys1)
            assert not cc.include_batch(n, allkeys).any(), (
                f"tail resume double-applied records ({n})"
            )
        cc.close()
    finally:
        _teardown(a, b)


def test_migrate_apply_fault_redrive_exactly_once(tmp_path):
    """ISSUE 13 (chaos-coverage): ``cluster.migrate_apply`` armed — the
    TARGET side of a migration dies inside ``MigrateInstall``, the
    driver surfaces the error, and the re-driven migration completes
    exactly-once (counts stay 1 at the new owner)."""
    a = _node(tmp_path, "a")
    b = _node(tmp_path, "b")
    try:
        addrs = _assign_even((a, b))
        cc = ClusterClient(startup_nodes=addrs)
        name = _name_owned_by(a[0].cluster.owner, addrs[0], prefix="ma")
        slot = S.key_slot(name)
        cc.create_filter(name, capacity=20_000, error_rate=0.01,
                         counting=True)
        keys = [b"ma-%04d" % i for i in range(200)]
        cc.insert_batch(name, keys)

        before = obs_counters.get("fault_cluster_migrate_apply")
        # pass 1 is the gate PROBE (its errors are deliberately
        # swallowed — an unreachable target just means "no resume");
        # pass 2 is the blob install itself, the one that must surface
        faults.arm("cluster.migrate_apply", "nth:2", times=1)
        with pytest.raises(BloomServiceError):
            BloomClient(addrs[0]).migrate_slot(slot, addrs[1])
        assert obs_counters.get("fault_cluster_migrate_apply") == before + 1
        # the handoff did not finalize: the source still owns the slot
        assert a[0].cluster.owner(slot) == addrs[0]

        # re-drive (disarmed): completes, target owns, exactly-once
        resp = BloomClient(addrs[0], timeout=120).migrate_slot(
            slot, addrs[1]
        )
        assert resp["ok"]
        assert b[0].cluster.owner(slot) == addrs[1]
        assert cc.include_batch(name, keys).all(), "lost writes"
        cc.delete_batch(name, keys)
        assert not cc.include_batch(name, keys).any(), (
            "re-driven install double-applied records"
        )
        cc.close()
    finally:
        _teardown(a, b)


def test_migration_moves_all_hash_tagged_filters(tmp_path):
    """Hash-tagged filters share a slot and migrate together — the
    tenant-colocation story."""
    a = _node(tmp_path, "a")
    b = _node(tmp_path, "b")
    try:
        addrs = _assign_even((a, b))
        cc = ClusterClient(startup_nodes=addrs)
        tag = None
        for i in range(4096):
            if a[0].cluster.owner(S.key_slot(f"{{t{i}}}:x")) == addrs[0]:
                tag = f"t{i}"
                break
        names = [f"{{{tag}}}:seen", f"{{{tag}}}:blocked", f"{{{tag}}}:spam"]
        slot = S.key_slot(names[0])
        assert all(S.key_slot(n) == slot for n in names)
        for n in names:
            cc.create_filter(n, capacity=2000, error_rate=0.01)
            cc.insert_batch(n, [n.encode()])
        resp = BloomClient(addrs[0]).migrate_slot(slot, addrs[1])
        assert resp["filters_moved"] == 3
        for n in names:
            assert n in b[0]._filters and n not in a[0]._filters
            assert cc.include(n, n.encode())
        cc.close()
    finally:
        _teardown(a, b)


# -- rebalancer --------------------------------------------------------------


def test_even_ranges_and_plan_moves():
    r = even_ranges(["a", "b", "c"])
    assert r[0][0] == 0 and r[-1][1] == S.NUM_SLOTS - 1
    total = sum(end - start + 1 for start, end, _ in r)
    assert total == S.NUM_SLOTS

    # plan: everything on "a", target a+b -> half the slots move to b
    owners = {s: "a" for s in range(S.NUM_SLOTS)}
    moves = plan_moves(owners, ["a", "b"])
    assert len(moves) == S.NUM_SLOTS // 2
    assert all(src == "a" and dst == "b" for _s, src, dst in moves)
    # stray owners (nodes leaving the cluster) are fully drained
    owners = {0: "dead", 1: "a", 2: "a"}
    moves = plan_moves(owners, ["a", "b"])
    assert ("dead" not in {dst for _s, _src, dst in moves})
    balanced: dict = {"a": 2, "b": 0}
    for slot, src, dst in moves:
        balanced[dst] += 1
    assert balanced["b"] >= 1


def test_rebalance_cli_init_and_info(tmp_path, capsys):
    from tpubloom.cluster import rebalance

    a = _node(tmp_path, "a")
    b = _node(tmp_path, "b")
    try:
        nodes_arg = f"{a[2]},{b[2]}"
        assert rebalance.main(["init", "--nodes", nodes_arg]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["unreachable"] == [] and out["epoch"] == 1
        assert a[0].cluster.epoch() == 1 and b[0].cluster.epoch() == 1

        assert rebalance.main(["info", "--nodes", nodes_arg]) == 0
        views = json.loads(capsys.readouterr().out)
        assert views[a[2]]["enabled"] and views[b[2]]["enabled"]

        # rebalance of an already-even cluster plans zero moves
        assert rebalance.main(["rebalance", "--nodes", nodes_arg,
                               "--plan-only"]) == 0
        plan = json.loads(capsys.readouterr().out.splitlines()[0])
        assert plan["planned_moves"] == 0
    finally:
        _teardown(a, b)


# -- the acceptance chaos story: SIGKILL the source mid-migration ------------

_SERVER_CHILD = """\
import sys
import jax
jax.config.update("jax_platforms", "cpu")
from tpubloom.server.service import main
main(sys.argv[1:])
"""


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_migration_sigkill_acceptance(tmp_path):
    """The ISSUE-9 acceptance scenario: a 3-primary cluster under
    concurrent client load migrates a live slot holding counting
    filters; the migration SOURCE (a real subprocess) is injected to
    fail mid-migration and then SIGKILLed; the restarted source
    re-drives the migration (resuming via the target's import gate +
    its own replayed op log) to completion — and every acked write is
    readable EXACTLY ONCE at the new owner."""
    import signal
    import subprocess
    import sys as _sys

    from tpubloom.obs.context import new_rid

    port = _free_port()
    src_addr = f"127.0.0.1:{port}"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base_env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }
    plog = tmp_path / "src-log"
    script = tmp_path / "server_child.py"
    script.write_text(_SERVER_CHILD)
    child_args = [
        _sys.executable, str(script), str(port),
        "--cluster", "--repl-log-dir", str(plog),
        # black box armed in chaos mode (ISSUE 16): only slowlog-worthy
        # work spills at sample 0.0 — the post-mortem below reads the
        # rings the SIGKILL leaves behind
        "--trace-sample", "0.0",
    ]
    # pass 1 = filter 1's probe, 2 = its install, 3 = filter 2's probe,
    # 4 = its install → the first MigrateSlot dies with one filter
    # landed and one mid-flight
    proc = subprocess.Popen(
        child_args,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env={**base_env,
             "TPUBLOOM_FAULTS": "cluster.migrate_send=nth:4:times=1"},
    )
    t2 = _node(tmp_path, "t2")
    t3 = _node(tmp_path, "t3")
    boot = BloomClient(src_addr)
    cc = None
    try:
        boot.wait_ready(timeout=120)
        addrs = [src_addr, t2[2], t3[2]]
        ranges = even_ranges(addrs)
        boot.cluster_set_slot(assign=ranges, epoch=1)
        for n in (t2, t3):
            n[0].ClusterSetSlot({"assign": ranges, "epoch": 1})
        owners = S.expand_ranges(ranges)

        # two counting filters pinned to ONE source-owned slot (hash
        # tag), so the nth:4 fault lands between their installs
        tag = None
        for i in range(4096):
            if owners[S.key_slot(f"{{m{i}}}:a")] == src_addr:
                tag = f"m{i}"
                break
        names = [f"{{{tag}}}:a", f"{{{tag}}}:b"]
        slot = S.key_slot(names[0])
        target_addr = t2[2]

        cc = ClusterClient(
            startup_nodes=addrs, max_retries=3,
            backoff_base=0.05, backoff_max=0.5,
        )
        for n in names:
            cc.create_filter(n, capacity=50_000, error_rate=0.01,
                             counting=True)
        seed = {n: [b"seed-%s-%03d" % (n.encode(), i) for i in range(200)]
                for n in names}
        for n in names:
            cc.insert_batch(n, seed[n])
        seed_rid = cc.last_rid  # served by the source — the rid whose
        # spilled span the post-mortem must find in the dead ring

        n_batches, batch_size = 16, 15
        batches = [
            (names[i % 2], [b"acc-%03d-%03d" % (i, j)
                            for j in range(batch_size)])
            for i in range(n_batches)
        ]
        acked: list = []
        errors: list = []
        done = threading.Event()

        def writer():
            # one rid per LOGICAL batch, reused across every retry —
            # the dedup caches (rebuilt from log replay after the kill)
            # and the import gates make re-drives exactly-once
            for name, keys in batches:
                rid = new_rid()
                deadline = time.monotonic() + 240
                while True:
                    try:
                        cc._keyed(
                            "InsertBatch", {"name": name, "keys": keys},
                            rid=rid,
                        )
                        acked.append((name, keys))
                        break
                    except Exception as e:  # noqa: BLE001
                        errors.append(repr(e))
                        if time.monotonic() > deadline:
                            raise
                        time.sleep(0.2)
            done.set()

        w = threading.Thread(target=writer, daemon=True)
        w.start()
        time.sleep(0.3)

        # first migration attempt dies on the injected fault
        try:
            BloomClient(src_addr, timeout=120).migrate_slot(slot, target_addr)
            raise AssertionError("expected the injected migration failure")
        except (BloomServiceError, grpc.RpcError):
            pass
        # ... and then the whole source process dies
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)

        # post-mortem (ISSUE 16): the dead source's mmap'd black box
        # still decodes — lifecycle events plus the seed write's span
        from tpubloom.obs import blackbox as bb

        node = bb.read_node(str(plog))
        assert node is not None, "SIGKILL must leave a readable black box"
        assert "boot" in [e["kind"] for e in node["events"]]
        assert seed_rid in {s.get("rid") for s in node["spans"]}, (
            "the seed write's spilled span must survive the SIGKILL"
        )

        # restart (no injected faults): op-log replay restores the
        # filters AND the rid-dedup cache; the slot map (with its
        # migrating mark) reloads from the state dir
        proc2 = subprocess.Popen(
            child_args,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=base_env,
        )
        try:
            BloomClient(src_addr).wait_ready(timeout=120)
            resp = BloomClient(src_addr, timeout=120).migrate_slot(
                slot, target_addr
            )
            assert resp["ok"] and resp["filters_moved"] == 2

            assert done.wait(240), (
                f"writer wedged; acked={len(acked)} last={errors[-3:]}"
            )
            w.join(timeout=10)
            assert len(acked) == n_batches

            # the handoff is visible: target owns, source answers MOVED
            assert t2[0].cluster.owner(slot) == target_addr
            with pytest.raises(BloomServiceError, match="MOVED"):
                BloomClient(src_addr).include_batch(names[0], [b"x"])

            # zero lost: every seed + acked key present at the new owner
            per_name: dict = {n: list(seed[n]) for n in names}
            for name, keys in acked:
                per_name[name].extend(keys)
            for n in names:
                assert cc.include_batch(n, per_name[n]).all(), (
                    f"acked writes lost across the killed migration ({n})"
                )
            # zero doubled: ONE delete round empties every counting key
            for n in names:
                cc.delete_batch(n, seed[n])
            for name, keys in acked:
                cc.delete_batch(name, keys)
            for n in names:
                assert not cc.include_batch(n, per_name[n]).any(), (
                    f"acked writes double-applied ({n})"
                )
        finally:
            proc2.send_signal(signal.SIGTERM)
            try:
                proc2.wait(timeout=60)
            except subprocess.TimeoutExpired:
                proc2.kill()
    finally:
        if proc.poll() is None:
            proc.kill()
        boot.close()
        if cc is not None:
            cc.close()
        _teardown(t2, t3)


# -- satellites --------------------------------------------------------------


def test_topology_events_push_repoints_client(tmp_path):
    """ISSUE 9 satellite: a client subscribed to the sentinels'
    TopologyEvents stream re-points on a topology change WITHOUT an
    error-triggered refresh."""
    from tpubloom.ha.sentinel import Sentinel

    a = _node(tmp_path, "a")
    b = _node(tmp_path, "b")
    sent = Sentinel(a[2], peers=[], poll_s=0.1, down_after_s=30.0).start()
    client = None
    try:
        client = BloomClient(sentinels=[sent.address], breaker_threshold=0)
        assert client.address == a[2]
        assert client.enable_topology_push()
        before = obs_counters.get("client_topology_pushes")
        # a failover completed elsewhere: the leader announces it
        resp = sent.handle_AnnounceTopology(
            {"epoch": 2, "primary": b[2], "replicas": [a[2]]}
        )
        assert resp["adopted"]
        _wait(
            lambda: client.address == b[2],
            timeout=15,
            msg="push-driven client re-point",
        )
        assert client.epoch == 2
        assert obs_counters.get("client_topology_pushes") > before
    finally:
        if client is not None:
            client.close()
        sent.stop()
        _teardown(a, b)


def test_obs_aggregate_merges_with_node_labels(tmp_path):
    """ISSUE 9 satellite (open since PR 1): one merged scrape across
    nodes, per-node labels, headers deduped, dead nodes visible."""
    from tpubloom.obs import aggregate as agg
    from tpubloom.obs.exposition import parse_families
    from tpubloom.obs.httpd import start_metrics_server

    a = _node(tmp_path, "a")
    b = _node(tmp_path, "b")
    servers = []
    try:
        a[0].CreateFilter({"name": "agg-a", "capacity": 1000,
                           "error_rate": 0.01})
        b[0].CreateFilter({"name": "agg-b", "capacity": 1000,
                           "error_rate": 0.01})
        ms_a = start_metrics_server(a[0], port=0, host="127.0.0.1")
        ms_b = start_metrics_server(b[0], port=0, host="127.0.0.1")
        servers = [ms_a, ms_b]
        dead = f"127.0.0.1:{_free_port()}"
        nodes = [f"127.0.0.1:{ms_a.port}", f"127.0.0.1:{ms_b.port}", dead]
        merged = agg.aggregate(nodes, timeout=3.0)

        fams = parse_families(merged)
        up = fams["tpubloom_aggregate_node_up"]
        assert up[(("node", nodes[0]),)] == 1.0
        assert up[(("node", nodes[1]),)] == 1.0
        assert up[(("node", dead),)] == 0.0
        created = fams["tpubloom_filters_created_total"]
        assert created[(("node", nodes[0]),)] >= 1.0
        assert created[(("node", nodes[1]),)] >= 1.0
        # every sample line carries a node label; headers appear once
        assert merged.count("# TYPE tpubloom_uptime_seconds gauge") == 1
        per_filter = fams["tpubloom_filter_fill_ratio"]
        labels = {dict(k).get("filter") for k in per_filter}
        assert {"agg-a", "agg-b"} <= labels
    finally:
        for ms in servers:
            ms.close()
        _teardown(a, b)


def test_latency_exemplars_link_buckets_to_slowlog_rids(tmp_path):
    """ISSUE 9 satellite (ROADMAP item 6): latency buckets carry the
    newest request's rid as an OpenMetrics exemplar — the same rid the
    slowlog entry keeps, so a bucket spike walks straight to its
    request. Stock scrapes stay annotation-free."""
    import re
    import urllib.request

    from tpubloom.obs.exposition import render_service
    from tpubloom.obs.httpd import start_metrics_server

    service = BloomService()
    srv, port = build_server(service, "127.0.0.1:0")
    srv.start()
    ms = None
    try:
        client = BloomClient(f"127.0.0.1:{port}")
        client.create_filter("ex", capacity=1000, error_rate=0.01)
        client.insert_batch("ex", [b"k1", b"k2"])
        client.include_batch("ex", [b"k1"])

        plain = render_service(service)
        assert '# {rid="' not in plain
        annotated = render_service(service, exemplars=True)
        rids = set(re.findall(r'# \{rid="([^"]+)"\}', annotated))
        assert rids, "no exemplars rendered"
        slowlog_rids = {e["rid"] for e in service.slowlog.entries()}
        assert rids <= slowlog_rids, (
            "exemplar rids must be findable in the slowlog"
        )

        # the HTTP surface: ?exemplars=1 opts in, default stays 0.0.4
        ms = start_metrics_server(service, port=0, host="127.0.0.1")
        base = f"http://127.0.0.1:{ms.port}/metrics"
        with urllib.request.urlopen(base, timeout=5) as r:
            assert b'# {rid="' not in r.read()
        with urllib.request.urlopen(base + "?exemplars=1", timeout=5) as r:
            assert b'# {rid="' in r.read()
        client.close()
    finally:
        if ms is not None:
            ms.close()
        srv.stop(grace=None)


def test_cluster_smoke():
    """benchmarks/cluster_smoke.py runs in tier-1 so the horizontal-
    scaling surface cannot silently rot (and CI runs it standalone):
    3 subprocess cluster nodes must beat the single-primary baseline."""
    import importlib
    import sys

    bench_dir = os.path.join(os.path.dirname(__file__), "..", "benchmarks")
    sys.path.insert(0, os.path.abspath(bench_dir))
    try:
        cluster_smoke = importlib.import_module("cluster_smoke")
        result = cluster_smoke.run_smoke(duration_s=1.5)
    finally:
        sys.path.pop(0)
    assert result["cluster_keys_per_sec"] > result["baseline_keys_per_sec"]
    assert result["nodes"] == 3
