"""Hash bit-exactness tests (SURVEY.md §4.2 items 1-2).

Three implementations — jnp (device), NumPy (oracle), C++ (native) — must
agree with each other and with published MurmurHash3_x86_32 / FNV-1a test
vectors on every input hypothesis can dream up.
"""

import jax.numpy as jnp
import numpy as np
import pytest

pytest.importorskip("hypothesis")  # property tests need it; skip cleanly without
from hypothesis import given, settings
from hypothesis import strategies as st

from tpubloom import native
from tpubloom.cpu_ref import fnv1a_32_np, murmur3_32_np, positions_np
from tpubloom.ops import hashing
from tpubloom.utils.packing import pack_keys

# Published MurmurHash3_x86_32 test vectors (widely circulated reference
# values for Appleby's canonical implementation).
MURMUR3_VECTORS = [
    (b"", 0x00000000, 0x00000000),
    (b"", 0x00000001, 0x514E28B7),
    (b"", 0xFFFFFFFF, 0x81F16F39),
    (b"\x00\x00\x00\x00", 0x00000000, 0x2362F9DE),
    (b"a", 0x9747B28C, 0x7FA09EA6),
    (b"aa", 0x9747B28C, 0x5D211726),
    (b"aaa", 0x9747B28C, 0x283E0130),
    (b"aaaa", 0x9747B28C, 0x5A97808A),
    (b"ab", 0x9747B28C, 0x74875592),
    (b"abc", 0x9747B28C, 0xC84A62DD),
    (b"abcd", 0x9747B28C, 0xF0478627),
    (b"Hello, world!", 0x9747B28C, 0x24884CBA),
    (b"The quick brown fox jumps over the lazy dog", 0x9747B28C, 0x2FA826CD),
]

FNV1A_VECTORS = [
    (b"", 0x811C9DC5),
    (b"a", 0xE40C292C),
    (b"b", 0xE70C2DE5),
    (b"foobar", 0xBF9CF968),
]

KEY_LEN = 48  # fits every vector above


def _pack(keys):
    return pack_keys(keys, KEY_LEN)


@pytest.mark.parametrize("key,seed,want", MURMUR3_VECTORS)
def test_murmur3_published_vectors(key, seed, want):
    ks, ls = _pack([key])
    assert int(murmur3_32_np(ks, ls, seed)[0]) == want
    assert int(hashing.murmur3_32(jnp.asarray(ks), jnp.asarray(ls), seed)[0]) == want
    assert int(native.murmur3_batch(ks, ls, seed)[0]) == want


@pytest.mark.parametrize("key,want", FNV1A_VECTORS)
def test_fnv1a_published_vectors(key, want):
    ks, ls = _pack([key])
    assert int(fnv1a_32_np(ks, ls)[0]) == want
    assert int(hashing.fnv1a_32(jnp.asarray(ks), jnp.asarray(ls))[0]) == want
    assert int(native.fnv1a_batch(ks, ls)[0]) == want


@given(
    keys=st.lists(st.binary(min_size=0, max_size=KEY_LEN), min_size=1, max_size=64),
    seed=st.integers(0, 2**32 - 1),
)
@settings(max_examples=200, deadline=None)
def test_murmur3_three_way_parity(keys, seed):
    ks, ls = _pack(keys)
    ref = murmur3_32_np(ks, ls, seed)
    dev = np.asarray(hashing.murmur3_32(jnp.asarray(ks), jnp.asarray(ls), seed))
    nat = native.murmur3_batch(ks, ls, seed)
    np.testing.assert_array_equal(dev, ref)
    np.testing.assert_array_equal(nat, ref)


@given(keys=st.lists(st.binary(min_size=0, max_size=KEY_LEN), min_size=1, max_size=64))
@settings(max_examples=100, deadline=None)
def test_fnv1a_three_way_parity(keys):
    ks, ls = _pack(keys)
    ref = fnv1a_32_np(ks, ls)
    dev = np.asarray(hashing.fnv1a_32(jnp.asarray(ks), jnp.asarray(ls)))
    nat = native.fnv1a_batch(ks, ls)
    np.testing.assert_array_equal(dev, ref)
    np.testing.assert_array_equal(nat, ref)


def test_padding_never_changes_hash():
    # Same key packed into buffers of different static length must hash alike.
    key = b"tpubloom"
    for L in (8, 12, 16, 32, 48):
        ks, ls = pack_keys([key], L)
        assert int(murmur3_32_np(ks, ls, 7)[0]) == int(
            murmur3_32_np(*pack_keys([key], 64), 7)[0]
        )
        assert int(hashing.murmur3_32(jnp.asarray(ks), jnp.asarray(ls), 7)[0]) == int(
            murmur3_32_np(ks, ls, 7)[0]
        )


@pytest.mark.parametrize(
    "m", [10_000_000, 1 << 20, 1 << 32, 1 << 34, 1 << 36]
)
def test_positions_three_way_parity(m):
    """Exercises both position paths: 32-bit mod (m=10M) and 64-bit pow2
    (incl. m > 2^32, the sharded config-5 scale)."""
    rng = np.random.default_rng(42)
    keys = [rng.bytes(rng.integers(1, KEY_LEN + 1)) for _ in range(256)]
    ks, ls = _pack(keys)
    k, seed = 7, 0x9747B28C
    ref = positions_np(ks, ls, m=m, k=k, seed=seed)
    nat = native.positions_batch(ks, ls, m=m, k=k, seed=seed)
    np.testing.assert_array_equal(nat, ref)
    ph, pl = hashing.positions(jnp.asarray(ks), jnp.asarray(ls), m=m, k=k, seed=seed)
    dev = np.asarray(ph).astype(np.uint64) << np.uint64(32) | np.asarray(pl).astype(
        np.uint64
    )
    np.testing.assert_array_equal(dev, ref)
    assert ref.max() < m


def test_positions_distribution_sanity():
    # Positions should spread over the whole range, all k slots distinct for
    # most keys (odd 64-bit stride).
    m, k = 1 << 30, 10
    rng = np.random.default_rng(0)
    keys = [rng.bytes(16) for _ in range(1000)]
    ks, ls = _pack(keys)
    pos = positions_np(ks, ls, m=m, k=k, seed=1)
    # coarse uniformity: mean near m/2, both halves populated
    assert 0.45 < pos.mean() / m < 0.55
    distinct = np.array([len(set(row)) for row in pos])
    assert (distinct == k).mean() > 0.99


def test_word_bit_split():
    ph = jnp.asarray([[0, 1]], jnp.uint32)  # pos_hi=1 => pos >= 2^32
    pl = jnp.asarray([[37, 37]], jnp.uint32)
    word, bit = hashing.split_word_bit(ph, pl)
    assert int(word[0, 0]) == 37 >> 5 and int(bit[0, 0]) == 37 & 31
    assert int(word[0, 1]) == (1 << 27) | (37 >> 5)
