"""DCN-path test: a real 2-process CPU cluster through
initialize_multihost (VERDICT r2 #8 — parallel/distributed.py was
exercised by zero tests).

Two subprocesses each fake 4 CPU devices, join via a localhost
coordinator, build one 8-shard mesh spanning both processes, and run a
sharded insert + psum-OR query whose collectives cross the process
boundary (the DCN tier in miniature)."""

import os
import socket
import subprocess
import sys

import pytest

_CHILD = r"""
import sys
import jax

jax.config.update("jax_platforms", "cpu")
import numpy as np

coord, pid = sys.argv[1], int(sys.argv[2])

from tpubloom.parallel.distributed import initialize_multihost

topo = initialize_multihost(coord, 2, pid)
assert topo["process_count"] == 2, topo
assert topo["global_device_count"] == 8, topo
assert topo["local_device_count"] == 4, topo

import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from tpubloom.config import FilterConfig
from tpubloom.parallel import sharded as sh
from tpubloom.utils.packing import pack_keys

config = FilterConfig(m=1 << 16, k=5, key_len=16, shards=8)
mesh = sh.make_mesh(8)
assert mesh.devices.size == 8

insert = jax.jit(sh.make_sharded_insert_fn(config, mesh), donate_argnums=0)
query = jax.jit(sh.make_sharded_query_fn(config, mesh))

words = jax.make_array_from_callback(
    (config.shards, config.n_words_per_shard),
    NamedSharding(mesh, P(sh.AXIS, None)),
    lambda idx: np.zeros(
        (len(range(*idx[0].indices(config.shards))), config.n_words_per_shard),
        np.uint32,
    ),
)
rng = np.random.default_rng(0)  # same seed on both hosts: identical batch
present = [rng.bytes(16) for _ in range(128)]
absent = [rng.bytes(16) for _ in range(128)]
repl = NamedSharding(mesh, P())

def put(a):
    a = np.asarray(a)
    return jax.make_array_from_callback(a.shape, repl, lambda idx: a[idx])

ku, kl = pack_keys(present, config.key_len)
words = insert(words, put(ku), put(kl))
pu, plen = pack_keys(present + absent, config.key_len)
hits = query(words, put(pu), put(plen))
hits_np = np.asarray(hits)  # fully replicated -> addressable everywhere
assert hits_np[:128].all(), "cross-process sharded filter lost keys"
assert hits_np[128:].mean() < 0.05, "implausible FPR"
print(f"CHILD{pid} OK", flush=True)
jax.distributed.shutdown()
"""


def test_two_process_cpu_cluster(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        # keep the axon site dir on the path (its sitecustomize registers
        # the plugin jax insists on knowing about) AND the repo root
        "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }
    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), coord, str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("2-process cluster hung: " + " | ".join(outs))
    for pid, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0 and (
            "Multiprocess computations aren't implemented" in out
        ):
            # this jax build's CPU backend has no cross-process
            # collectives — an environment limit, not a regression (the
            # DCN path is still exercised wherever the backend supports
            # multiprocess, e.g. real TPU pods)
            pytest.skip(
                "jax CPU backend does not implement multiprocess "
                "computations in this environment"
            )
        assert p.returncode == 0, f"child {pid} failed:\n{out[-3000:]}"
        assert f"CHILD{pid} OK" in out
