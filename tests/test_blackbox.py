"""ISSUE 16: crash-forensics black box — mmap'd flight/trace rings that
survive SIGKILL, plus the fleet post-mortem CLI.

Covers the tentpole end to end:

* the **mapped ring**: append/decode roundtrip, oldest-first wraparound,
  reattach resuming the seq space under the FILE's geometry;
* the **torn-tail discipline** (the op-log framing promise): corrupting
  or truncating the last record at EVERY byte boundary of its frame
  loses exactly that record — the decoder skips it, never misreads it,
  and every earlier record still decodes;
* the **writer module**: write-through from ``flight.note``, span
  spills, oversized-record degradation (attrs dropped before the record
  is), monotone epoch stamping, disabled-by-default;
* the **satellites**: ``trace.assemble`` synthesizing a shared root
  over a multi-hop forest; sentinel election RPC spans under one
  election rid; ``ClusterClient.trace`` slot-hinted fan-out;
  ``Slowlog.would_record`` threaded through the replica apply path;
* the **CLI**: two nodes' rings + an op-log segment merged into one
  epoch-then-wall-clock fleet timeline, ``--json`` and ``--rid``;
* the **acceptance**: a real subprocess primary SIGKILLed under acked
  load leaves rings the CLI decodes into a timeline carrying the killed
  node's final flight events AND the in-flight rid's spans AND the
  op-log seq the rid committed at.

Armed under the lock tracker + lock-order manifest like the other chaos
modules — the black box must stay LOCK-FREE (its write path runs under
filter.op / service.promote / sentinel.state locks).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import msgpack
import pytest

from tpubloom import faults
from tpubloom.obs import blackbox, flight, trace
from tpubloom.obs.slowlog import Slowlog
from tpubloom.repl import record as repl_record
from tpubloom.server.client import BloomClient

pytestmark = pytest.mark.usefixtures("lock_check_armed", "lock_order_manifest")


@pytest.fixture(autouse=True)
def _blackbox_isolation():
    trace.reset_for_tests()
    flight.reset_for_tests()
    blackbox.reset_for_tests()
    faults.reset()
    yield
    trace.reset_for_tests()
    flight.reset_for_tests()
    blackbox.reset_for_tests()
    faults.reset()


def _body(i):
    return msgpack.packb({"i": i, "pad": "x" * 20}, use_bin_type=True)


# -- the mapped ring ---------------------------------------------------------


def test_ring_roundtrip_and_wraparound(tmp_path):
    path = str(tmp_path / "r.ring")
    ring = blackbox.MappedRing(path, slot_size=96, nslots=4)
    for i in range(6):
        assert ring.append(_body(i))
    ring.close()
    decoded = blackbox.read_ring(path)
    assert decoded["geometry"] == {
        "version": blackbox.VERSION, "slot_size": 96, "nslots": 4,
    }
    # 6 appends into 4 slots: the oldest two were overwritten
    assert [r["seq"] for r in decoded["records"]] == [2, 3, 4, 5]
    assert [r["i"] for r in decoded["records"]] == [2, 3, 4, 5]
    assert decoded["skipped"] == 0


def test_reattach_resumes_seq_under_file_geometry(tmp_path):
    path = str(tmp_path / "r.ring")
    ring = blackbox.MappedRing(path, slot_size=96, nslots=4)
    for i in range(3):
        assert ring.append(_body(i))
    ring.close()
    # reattach with DIFFERENT (wrong) defaults: the file's geometry must
    # win, and the seq space must resume past the pre-crash history
    ring = blackbox.MappedRing(path, slot_size=512, nslots=64)
    assert (ring.slot_size, ring.nslots) == (96, 4)
    assert ring.append(_body(99))
    ring.close()
    records = blackbox.read_ring(path)["records"]
    assert [r["seq"] for r in records] == [0, 1, 2, 3]
    assert records[-1]["i"] == 99


def test_torn_tail_every_byte_loses_exactly_that_record(tmp_path):
    """THE satellite-5 test: flip/truncate the LAST record at every byte
    boundary of its frame — the decoder must skip exactly that record
    (whole or skipped, never a misread) and keep every earlier one."""
    path = str(tmp_path / "r.ring")
    ring = blackbox.MappedRing(path, slot_size=96, nslots=4)
    for i in range(3):
        assert ring.append(_body(i))
    ring.close()
    with open(path, "rb") as f:
        clean = f.read()
    frame_len = blackbox.FRAME_HEADER + len(_body(2))
    off = blackbox.HEADER_LEN + 2 * 96  # seq 2 lives in slot 2
    baseline = blackbox.decode_ring(clean)
    assert [r["seq"] for r in baseline["records"]] == [0, 1, 2]

    for i in range(frame_len):  # corrupt each frame byte in turn
        torn = bytearray(clean)
        torn[off + i] ^= 0xFF
        decoded = blackbox.decode_ring(bytes(torn))
        assert [r["seq"] for r in decoded["records"]] == [0, 1], (
            f"flipping frame byte {i} must lose exactly record 2"
        )
        assert decoded["skipped"] == 1, f"byte {i} must count as torn"

    for i in range(frame_len):  # truncate at each boundary (a torn tail)
        decoded = blackbox.decode_ring(clean[: off + i])
        assert [r["seq"] for r in decoded["records"]] == [0, 1], (
            f"truncating at frame byte {i} must lose exactly record 2"
        )
        assert decoded["skipped"] <= 1


def test_oversized_record_degrades_then_drops(tmp_path):
    assert blackbox.configure(
        str(tmp_path), flight_slots=8, flight_slot_size=96,
        trace_slots=8, trace_slot_size=96,
    )
    # attrs too big for the slot: the record survives WITHOUT them
    flight.configure(capacity=16)
    blackbox.note_event(
        {"ts": 1.0, "kind": "shed", "attrs": {"blob": "y" * 200}}
    )
    node = blackbox.read_node(str(tmp_path))
    shed = [e for e in node["events"] if e.get("kind") == "shed"]
    assert len(shed) == 1
    assert shed[0]["truncated"] is True and "attrs" not in shed[0]
    # un-slimmable oversize: dropped, counted, never a crash
    blackbox.note_event({"ts": 1.0, "kind": "z" * 200})
    node = blackbox.read_node(str(tmp_path))
    assert not any(e.get("kind", "").startswith("z") for e in node["events"])


# -- the writer module -------------------------------------------------------


def test_disabled_by_default_and_write_through(tmp_path):
    assert not blackbox.enabled()
    flight.configure(capacity=16)
    flight.note("shed", rid="r-off")  # disarmed: a no-op write-through
    assert blackbox.read_node(str(tmp_path)) is None

    assert blackbox.configure(str(tmp_path), node={"addr": "n1:1"})
    assert blackbox.enabled()
    blackbox.set_node_meta(role="primary", epoch=4)
    blackbox.set_node_meta(epoch=2)  # epoch is monotone: stays 4
    flight.note("shed", rid="r-on")  # armed: rides flight.note unchanged
    trace.configure(sample=1.0)
    trace.record_span(
        "repl.apply", rid="r-on", start=5.0, duration_s=0.1, spill=True
    )
    trace.record_span(  # spill=False stays in the volatile ring only
        "repl.apply", rid="r-volatile", start=6.0, duration_s=0.1
    )
    node = blackbox.read_node(str(tmp_path))
    assert node["label"] == "n1:1"
    assert node["meta"]["role"] == "primary" and node["meta"]["ep"] == 4
    assert node["meta"]["pid"] == os.getpid()
    kinds = [e["kind"] for e in node["events"]]
    assert kinds == ["shed"]
    assert [s["rid"] for s in node["spans"]] == ["r-on"]
    assert node["skipped"] == 0


# -- ring snapshots on DEGRADED (ISSUE 18 satellite) -------------------------


def test_snapshot_rings_copies_and_prunes(tmp_path):
    """The live rings overwrite oldest-first; ``snapshot_rings`` must
    freeze a decodable copy next to them and keep only the newest
    ``max_snapshots`` snapshot dirs."""
    assert blackbox.snapshot_rings("noop") is None  # disarmed: declines
    assert blackbox.configure(str(tmp_path), node={"addr": "n1:1"})
    flight.configure(capacity=16)
    flight.note("boot", role="primary")
    trace.configure(sample=1.0)
    trace.record_span(
        "repl.apply", rid="r-snap", start=1.0, duration_s=0.1, spill=True
    )
    snaps = []
    for i in range(4):
        # distinct reasons keep the dir names unique even when two
        # snapshots land inside the same millisecond
        snap = blackbox.snapshot_rings(f"degraded-{i}", max_snapshots=2)
        assert snap is not None
        snaps.append(snap)
    bb_dir = os.path.join(str(tmp_path), blackbox.SUBDIR)
    kept = sorted(
        d for d in os.listdir(bb_dir)
        if d.startswith(blackbox.SNAP_PREFIX)
    )
    assert kept == sorted(os.path.basename(s) for s in snaps[-2:]), (
        "only the newest 2 snapshots survive pruning"
    )
    # a snapshot is a self-contained post-mortem: both rings decode with
    # the records that were live at freeze time
    frozen = blackbox.read_ring(
        os.path.join(snaps[-1], blackbox.TRACE_RING)
    )
    assert [r["rid"] for r in frozen["records"]] == ["r-snap"]
    events = blackbox.read_ring(
        os.path.join(snaps[-1], blackbox.FLIGHT_RING)
    )
    assert "boot" in [r.get("kind") for r in events["records"]]
    # reason tags are path-sanitized, never path components
    weird = blackbox.snapshot_rings("../esc ape", max_snapshots=8)
    assert weird is not None
    assert os.path.dirname(os.path.abspath(weird)) == os.path.abspath(bb_dir)


def test_health_degraded_flip_snapshots_rings(tmp_path):
    """SERVING -> DEGRADED freezes the rings once (the flip, not every
    DEGRADED probe): the history leading up to the incident survives
    the live rings' wraparound."""
    from tpubloom import checkpoint as ckpt
    from tpubloom.server.protocol import BloomServiceError
    from tpubloom.server.service import BloomService, build_server

    flight.configure(dump_dir=str(tmp_path / "dumps"))
    assert blackbox.configure(str(tmp_path / "state"))
    svc = BloomService(
        sink_factory=lambda c: ckpt.FileSink(str(tmp_path / "ckpt"))
    )
    srv, port = build_server(svc, "127.0.0.1:0")
    srv.start()
    c = BloomClient(f"127.0.0.1:{port}")
    bb_dir = os.path.join(str(tmp_path / "state"), blackbox.SUBDIR)

    def _snaps():
        return sorted(
            d for d in os.listdir(bb_dir)
            if d.startswith(blackbox.SNAP_PREFIX)
        )

    try:
        c.wait_ready()
        c.create_filter("t", capacity=10_000, error_rate=0.01)
        assert _snaps() == []
        faults.arm("ckpt.write", "always")
        c.insert_batch("t", [b"x"])
        try:
            c.checkpoint("t", wait=True)
        except BloomServiceError:
            pass
        assert c.health()["status"] == "DEGRADED"
        snaps = _snaps()
        assert len(snaps) == 1, "the flip must freeze the rings once"
        assert "degraded" in snaps[0]
        for fname in (blackbox.FLIGHT_RING, blackbox.TRACE_RING):
            frozen = blackbox.read_ring(os.path.join(bb_dir, snaps[0], fname))
            assert frozen["geometry"]["nslots"] > 0
        # a second DEGRADED answer is not a flip: no second snapshot
        c.health()
        assert _snaps() == snaps
    finally:
        faults.reset()
        c.close()
        srv.stop(grace=None)


def test_cli_merges_fleet_timeline_with_oplog_correlation(tmp_path, capsys):
    # node A: epoch-1 primary with an op log that committed rid r-1
    dir_a = tmp_path / "node-a"
    assert blackbox.configure(str(dir_a), node={"addr": "a:1"})
    blackbox.set_node_meta(role="primary", epoch=1)
    flight.configure(capacity=16)
    flight.note("boot", role="primary", epoch=1, addr="a:1")
    trace.configure(sample=1.0)
    trace.record_span(
        "rpc.InsertBatch", rid="r-1", start=100.0, duration_s=0.2,
        attrs={"filter": "f"}, spill=True,
    )
    blackbox.sync()
    seg = repl_record.encode_record(
        {"seq": 7, "method": "InsertBatch", "rid": "r-1",
         "req": {"name": "f"}, "ts": 100.1}
    ) + repl_record.encode_record(
        {"seq": 8, "method": "InsertBatch", "rid": "r-other",
         "req": {"name": "f"}, "ts": 100.2}
    )
    (dir_a / "oplog.00000000000000000007.seg").write_bytes(seg)
    blackbox.reset_for_tests()
    trace.reset_for_tests()

    # node B: epoch-2 replica whose records must sort AFTER epoch 1
    # despite EARLIER wall clock (skewed clocks are the normal case)
    dir_b = tmp_path / "node-b"
    assert blackbox.configure(str(dir_b), node={"addr": "b:1"})
    blackbox.set_node_meta(role="replica", epoch=2)
    flight.configure(capacity=16)
    flight.note("role_change", role="replica", epoch=2)
    trace.configure(sample=1.0)
    trace.record_span(
        "repl.apply", rid="r-1", start=50.0, duration_s=0.05, spill=True
    )
    blackbox.reset_for_tests()
    trace.reset_for_tests()

    rc = blackbox.main([str(dir_a), str(dir_b), "--json"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert {n["label"] for n in out["nodes"]} == {"a:1", "b:1"}
    eps = [e["ep"] for e in out["timeline"]]
    assert eps == sorted(eps), "fleet order is epoch-first"
    oplog = [e for e in out["timeline"] if e["type"] == "oplog"]
    # only rids the rings mention correlate — r-other stays out
    assert [e["rid"] for e in oplog] == ["r-1"]
    assert oplog[0]["oplog_seq"] == 7
    span_nodes = {
        (e["name"], e["node"])
        for e in out["timeline"] if e["type"] == "span"
    }
    assert span_nodes == {("rpc.InsertBatch", "a:1"), ("repl.apply", "b:1")}

    # --rid focuses spans but keeps lifecycle events for context
    rc = blackbox.main([str(dir_a), str(dir_b), "--json", "--rid", "r-1"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert all(
        e["type"] == "event" or e.get("rid") == "r-1"
        for e in out["timeline"]
    )
    assert any(e["type"] == "event" for e in out["timeline"])

    # the human rendering holds the same facts
    rc = blackbox.main([str(dir_a), str(dir_b)])
    assert rc == 0
    text = capsys.readouterr().out
    assert "node a:1" in text and "node b:1" in text
    assert "OPLOG seq=7" in text and "EVENT boot" in text

    rc = blackbox.main([str(tmp_path / "nowhere")])
    assert rc == 2


# -- satellite 1: assemble synthesizes the shared root -----------------------


def test_assemble_synthesizes_shared_root_for_multi_hop_forest():
    trace.configure(sample=1.0)
    h1 = trace.record_span("client.hop", rid="r-m", start=1.0,
                           duration_s=0.1, attrs={"addr": "a:1"})
    h2 = trace.record_span("client.hop", rid="r-m", start=1.2,
                           duration_s=0.3, attrs={"addr": "b:1"})
    trace.record_span("rpc.InsertBatch", rid="r-m", parent=h2,
                      start=1.25, duration_s=0.2)
    spans = trace.get_trace("r-m")
    # without the rid hint: an honest two-root forest
    plain = trace.assemble(spans)
    assert len(plain["components"]) == 2 and plain.get("synthetic") is None
    # with it: ONE tree under a synthetic client.call root
    tree = trace.assemble(spans, rid="r-m")
    synth = tree["synthetic"]
    assert synth["name"] == "client.call"
    assert synth["attrs"] == {"synthesized": True, "hops": 2}
    assert synth["start"] == 1.0
    assert synth["duration_s"] == pytest.approx(0.5)
    assert tree["roots"] == [synth["span"]]
    assert len(tree["components"]) == 1
    assert tree["parent"][h1] == synth["span"]
    assert tree["parent"][h2] == synth["span"]
    # a single-root trace stays untouched — no synthetic noise
    trace.reset_for_tests()
    trace.configure(sample=1.0)
    trace.record_span("client.hop", rid="r-s", start=1.0, duration_s=0.1)
    one = trace.assemble(trace.get_trace("r-s"), rid="r-s")
    assert one.get("synthetic") is None and len(one["roots"]) == 1


# -- satellite 2: sentinel election spans ------------------------------------


def test_sentinel_election_records_rpc_spans(tmp_path, monkeypatch):
    from tpubloom.ha.sentinel import Sentinel
    from tpubloom.ha.topology import Topology

    trace.configure(sample=1.0)
    assert blackbox.configure(str(tmp_path))
    sentinel = Sentinel("old:1", ["p1:1"], listen="127.0.0.1:0")
    sentinel.topology = Topology(epoch=3, primary="old:1",
                                 replicas=["r1:1", "old:1"])
    calls = []

    def _peer(peer, method, req, timeout=None):
        calls.append(("peer", peer, method))
        return {"granted": True} if method == "VoteDown" else {}

    def _node(addr, method, req, timeout=None):
        calls.append(("node", addr, method))
        if method == "Health":
            return {"replication": {"cursor": 9}}
        return {"ok": True}

    monkeypatch.setattr(sentinel, "_peer", _peer)
    monkeypatch.setattr(sentinel, "_node", _node)
    monkeypatch.setattr(
        sentinel, "_adopt_completed_failover", lambda: False
    )
    sentinel._attempt_failover()

    rid = sentinel.last_election_rid
    assert rid == f"election-4-{sentinel.sentinel_id[:8]}"
    spans = trace.get_trace(rid)
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)
    assert set(by_name) == {
        "sentinel.vote_down", "sentinel.promote", "sentinel.topology",
    }
    vote = by_name["sentinel.vote_down"][0]
    assert vote["attrs"] == {"peer": "p1:1", "epoch": 4,
                             "ok": True, "granted": True}
    assert by_name["sentinel.promote"][0]["attrs"]["candidate"] == "r1:1"
    assert by_name["sentinel.promote"][0]["attrs"]["ok"] is True
    assert by_name["sentinel.topology"][0]["attrs"]["ok"] is True
    assert sentinel.topology.primary == "r1:1"
    # every election span spilled: elections ARE crash forensics
    node = blackbox.read_node(str(tmp_path))
    assert {s["name"] for s in node["spans"]} == set(by_name)
    assert all(s["rid"] == rid for s in node["spans"])


# -- satellite 3: slot-hinted cross-shard trace fetch ------------------------


class _StubShard:
    def __init__(self, addr):
        self.address = addr
        self.asked = []

    def trace_get_fan(self, tid):
        self.asked.append(tid)
        return [{"rid": tid, "span": f"s-{self.address}", "parent": None,
                 "name": "rpc.InsertBatch", "start": 1.0,
                 "duration_s": 0.1}]


def _stub_cluster_client(owner_map):
    from tpubloom.cluster.client import ClusterClient
    from tpubloom.utils import locks

    cc = ClusterClient.__new__(ClusterClient)
    cc._lock = locks.named_lock("cluster.client")
    cc._kwargs = {}
    cc.last_rid = None
    cc.epoch = 1
    cc._slot_owner = owner_map
    cc._shard_clients = [_StubShard("a:1"), _StubShard("b:1")]
    cc._direct = {}
    return cc


def test_cluster_trace_slot_hint_skips_full_fan_out():
    from tpubloom.cluster import slots as slots_mod

    slot = slots_mod.key_slot("f1")
    cc = _stub_cluster_client(
        {s: ("a:1" if s == slot else "b:1") for s in range(16384)}
    )
    a, b = cc._shard_clients
    out = cc.trace("r-h", name="f1")
    assert a.asked and not b.asked, "the hint must dodge the fleet fan-out"
    assert out["rid"] == "r-h" and out["spans"]
    # same via an explicit slot number
    a.asked.clear()
    cc.trace("r-h2", slot=slot)
    assert a.asked == ["r-h2"] and not b.asked
    # no hint: the full fan-out still runs
    a.asked.clear()
    cc.trace("r-h3")
    assert a.asked and b.asked


def test_cluster_trace_hint_falls_back_on_clusterdown(monkeypatch):
    cc = _stub_cluster_client({})
    monkeypatch.setattr(cc, "refresh_slots", lambda: False)
    out = cc.trace("r-d", slot=77)  # unmapped: CLUSTERDOWN inside
    a, b = cc._shard_clients
    assert a.asked and b.asked, "an unmapped slot degrades to full fan-out"
    assert out["rid"] == "r-d"


# -- satellite 4: slowlog-worthy replica applies -----------------------------


def _stub_applier(slowlog):
    from tpubloom.repl.replica import ReplicaApplier

    class _Svc:
        oplog = None

        def __init__(self):
            self.slowlog = slowlog

        def apply_record(self, rec):
            return True

    a = ReplicaApplier.__new__(ReplicaApplier)
    a.service = _Svc()
    a.state_store = None
    a.head_seq = 0
    a.cursor = None
    a._ack = None
    a.records_applied = 0
    a.records_skipped = 0
    return a


def test_replica_apply_spills_slowlog_worthy_and_forced(tmp_path):
    assert blackbox.configure(str(tmp_path))
    # sample 0.0: armed but nothing hits — ONLY the slow/forced paths
    # may capture, exactly the chaos-suite configuration
    trace.configure(sample=0.0)
    applier = _stub_applier(Slowlog(capacity=8, threshold_s=0.0))
    applier._handle_record(
        {"seq": 1, "method": "InsertBatch", "rid": "r-slow",
         "req": {"name": "f"}, "ts": time.time()}
    )
    spans = trace.get_trace("r-slow")
    assert [s["name"] for s in spans] == ["repl.apply"]
    assert spans[0]["attrs"]["applied"] is True

    # forced wire flag: captured AND spilled, parented across the wire
    applier._handle_record(
        {"seq": 2, "method": "InsertBatch", "rid": "r-forced",
         "req": {"name": "f", "trace": {"forced": True, "span": "abcd1234"}},
         "ts": time.time()}
    )
    forced = trace.get_trace("r-forced")
    assert forced and forced[0]["parent"] == "abcd1234"

    # an apply the slowlog would NOT record stays invisible
    fast = _stub_applier(Slowlog(capacity=8, threshold_s=3600.0))
    fast._handle_record(
        {"seq": 3, "method": "InsertBatch", "rid": "r-fast",
         "req": {"name": "f"}, "ts": time.time()}
    )
    assert trace.get_trace("r-fast") == []

    node = blackbox.read_node(str(tmp_path))
    assert {s["rid"] for s in node["spans"]} == {"r-slow", "r-forced"}


# -- the acceptance: SIGKILL post-mortem -------------------------------------


_SERVER_CHILD = """\
import sys
import jax
jax.config.update("jax_platforms", "cpu")
from tpubloom.server.service import main
main(sys.argv[1:])
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _child_env():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }


def _spawn(tmp_path, script_name, args):
    script = tmp_path / script_name
    script.write_text(_SERVER_CHILD)
    return subprocess.Popen(
        [sys.executable, str(script)] + [str(a) for a in args],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=_child_env(),
    )


def test_sigkill_acceptance_postmortem_cli(tmp_path):
    """THE acceptance run: a subprocess primary killed with SIGKILL under
    acked load leaves mmap'd rings behind; the post-mortem CLI (run as
    its own process — the reader needs nothing from the dead writer)
    decodes them into a timeline carrying the killed node's final
    flight events, the last acked rid's spilled spans, and the op-log
    seq that rid committed at."""
    plog = tmp_path / "primary-log"
    port = _free_port()
    # --trace-sample 0.0 arms tracing WITHOUT sampling: spans persist
    # via the slowlog-worthy spill path alone — the configuration every
    # chaos suite runs, so this asserts the worst-case capture mode
    proc = _spawn(
        tmp_path, "primary.py",
        [port, tmp_path / "ckpt", "--repl-log-dir", plog,
         "--trace-sample", "0.0"],
    )
    acked = []
    try:
        client = BloomClient(f"127.0.0.1:{port}", timeout=30.0)
        client.wait_ready(timeout=120)
        client.create_filter("bb", capacity=50_000, error_rate=0.01)
        for i in range(6):
            keys = [b"bb-%d-%06d" % (i, j) for j in range(64)]
            assert client.insert_batch("bb", keys) == len(keys)
            acked.append(client.last_rid)
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)

    assert len(acked) == 6
    cli = subprocess.run(
        [sys.executable, "-m", "tpubloom.obs.blackbox", str(plog),
         "--json"],
        capture_output=True, text=True, env=_child_env(), timeout=120,
    )
    assert cli.returncode == 0, cli.stderr
    out = json.loads(cli.stdout)
    (node,) = out["nodes"]
    assert node["meta"]["role"] == "primary"
    assert node["meta"]["pid"] == proc.pid
    kinds = [e["kind"] for e in node["events"]]
    assert "boot" in kinds, "the ring must carry the node's lifecycle"
    span_rids = {
        s["rid"] for s in node["spans"] if s["name"] == "rpc.InsertBatch"
    }
    missing = [r for r in acked if r not in span_rids]
    assert not missing, (
        f"acked rids {missing} lost their spans to the SIGKILL"
    )
    oplog_rids = {
        e["rid"] for e in out["timeline"] if e["type"] == "oplog"
    }
    assert set(acked) <= oplog_rids, (
        "every acked rid must correlate to its committed op-log seq"
    )

    # the human timeline focuses on the final acked rid
    focus = subprocess.run(
        [sys.executable, "-m", "tpubloom.obs.blackbox", str(plog),
         "--rid", acked[-1]],
        capture_output=True, text=True, env=_child_env(), timeout=120,
    )
    assert focus.returncode == 0
    assert f"rid={acked[-1]}" in focus.stdout
    assert "EVENT boot" in focus.stdout
