"""Sketch-plane chaos (ISSUE 19 acceptance).

* **fault points** — ``cuckoo.kick`` / ``cms.update`` fire BEFORE the
  kernel mutates anything, so a failed update applies nothing and the
  retry lands exactly once (one-copy delete / exact-weight proofs);
* **the acceptance** — a real subprocess server SIGKILLed after acking
  one delete of a doubly-inserted cuckoo key, weighted CMS increments,
  and top-k adds; restarted over the same op-log dir:

  - the acked ``CFDel`` replays exactly once — the key's SECOND copy
    is still present (a doubled replay would have eaten both);
  - CMS counts are neither lost nor doubled (weighted records replay
    with their exact weights);
  - the top-k heap rebuilds to the same estimates;
  - the killed process's black-box ring is readable post-mortem via
    ``python -m tpubloom.obs.blackbox``.
"""

import json
import os
import signal
import socket
import subprocess
import sys

import pytest

from tpubloom import faults
from tpubloom.server import protocol
from tpubloom.server.client import BloomClient
from tpubloom.server.ingest import CoalesceConfig
from tpubloom.server.service import BloomService, build_server

pytestmark = pytest.mark.usefixtures("lock_check_armed", "lock_order_manifest")


@pytest.fixture(autouse=True)
def _disarm_all():
    faults.reset()
    yield
    faults.reset()


class _Server:
    def __init__(self, service):
        self.service = service
        self.server, self.port = build_server(service, "127.0.0.1:0")
        self.server.start()
        self.addr = f"127.0.0.1:{self.port}"

    def client(self, **kw) -> BloomClient:
        return BloomClient(self.addr, **kw)

    def stop(self):
        self.service.shutdown()
        self.server.stop(grace=None)


@pytest.fixture()
def coalesced_server():
    s = _Server(BloomService(
        coalesce=CoalesceConfig(max_keys=4096, max_wait_us=2000)
    ))
    yield s
    s.stop()


# -- fault-point chaos: fail-before-apply, retry exactly-once -----------------


def test_cuckoo_kick_fault_fails_flush_then_heals(coalesced_server):
    """``cuckoo.kick`` fires before the insert kernel runs: the
    coalesced flush errors, NOTHING lands, and the retry applies each
    key exactly once (one-copy delete proof: after one delete per key
    the filter is empty again)."""
    s = coalesced_server
    with s.client() as c:
        c.cf_reserve("chaos-cf", 1000)
        keys = [b"ck-%d" % j for j in range(32)]
        faults.arm("cuckoo.kick", "once")
        with pytest.raises(protocol.BloomServiceError) as ei:
            c.cf_add("chaos-cf", keys)
        assert ei.value.code == "INTERNAL"
        assert not c.cf_exists("chaos-cf", keys).any(), (
            "a failed kick batch must not have applied"
        )
        assert c.cf_add("chaos-cf", keys).all()  # heals
        assert c.cf_del("chaos-cf", keys).all()
        assert not c.cf_exists("chaos-cf", keys).any(), (
            "double-applied: one delete per key left residue"
        )


def test_cms_update_fault_fails_weighted_incr_then_heals(coalesced_server):
    """``cms.update`` fires before the scatter-add: the weighted
    increment errors with counts untouched, and the retry lands the
    exact weights once (7 stays 7, not 14)."""
    s = coalesced_server
    with s.client() as c:
        c.cms_init_by_dim("chaos-cms", 128, 4)
        faults.arm("cms.update", "once")
        with pytest.raises(protocol.BloomServiceError) as ei:
            c.cms_incrby("chaos-cms", [b"hot"], [7])
        assert ei.value.code == "INTERNAL"
        assert c.cms_query("chaos-cms", [b"hot"])[0] == 0
        counts = c.cms_incrby("chaos-cms", [b"hot"], [7])
        assert counts[0] == 7
        assert c.cms_query("chaos-cms", [b"hot"])[0] == 7


def test_cms_update_fault_fails_coalesced_unit_adds(coalesced_server):
    """Unit increments ride the coalescer as InsertBatch: an armed
    ``cms.update`` errors the whole parked flush pre-apply and the
    retry counts each key exactly once."""
    s = coalesced_server
    with s.client() as c:
        c.cms_init_by_dim("chaos-cms2", 128, 4)
        keys = [b"u-%d" % j for j in range(16)]
        faults.arm("cms.update", "once")
        with pytest.raises(protocol.BloomServiceError):
            c.cms_incrby("chaos-cms2", keys)
        assert not c.cms_query("chaos-cms2", keys).any()
        c.cms_incrby("chaos-cms2", keys)
        assert (c.cms_query("chaos-cms2", keys) == 1).all()


# -- the acceptance: SIGKILL + restart replay, per kind -----------------------

#: mirrors test_streams' child: the image's sitecustomize force-sets
#: jax_platforms to the TPU plugin, so the child must pin cpu first.
_SERVER_CHILD = """\
import sys
import jax
jax.config.update("jax_platforms", "cpu")
from tpubloom.server.service import main
main(sys.argv[1:])
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _child_env():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }


def _spawn(tmp_path, script_name, args):
    script = tmp_path / script_name
    script.write_text(_SERVER_CHILD)
    return subprocess.Popen(
        [sys.executable, str(script)] + [str(a) for a in args],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=_child_env(),
    )


def test_sigkill_replays_each_sketch_kind_exactly_once(tmp_path):
    """THE ISSUE-19 acceptance: SIGKILL a real subprocess server after
    acked sketch writes per kind; restart it over the same op-log dir;
    every replay-unsafe record applies EXACTLY once:

    * cuckoo — ``dup`` was inserted twice and deleted once pre-kill.
      After restart exactly one copy remains: a lost delete would show
      two (second delete would still leave one), a doubled delete zero.
    * cms — the acked weighted counts read back bit-identical (lost
      replay reads low, doubled reads 2x).
    * topk — the heap rebuilds to the same estimates.
    """
    plog = tmp_path / "primary-log"
    port = _free_port()
    args = [port, tmp_path / "ckpt", "--repl-log-dir", plog,
            "--coalesce-max-keys", "4096", "--coalesce-max-wait-us", "2000",
            "--trace-sample", "0.0"]
    proc = _spawn(tmp_path, "server-a.py", args)
    restarted = None

    def _dial():
        return BloomClient(
            f"127.0.0.1:{port}", timeout=30.0,
            max_retries=120, backoff_base=0.25, backoff_max=1.0,
        )

    client = _dial()
    try:
        client.wait_ready(timeout=120)
        client.cf_reserve("cf", 5000)
        client.cms_init_by_dim("cms", 128, 4)
        client.topk_reserve("tk", 3, width=128, depth=4)

        singles = [b"s-%02d" % j for j in range(16)]
        assert client.cf_add("cf", [b"dup", b"dup"] + singles).all()
        assert client.cf_del("cf", [b"dup"]).all()  # acked: one copy gone
        counts = client.cms_incrby("cms", [b"hot", b"warm"], [7, 3])
        assert counts == [7, 3]
        client.topk_add("tk", [b"hot"] * 5 + [b"cold"])
        hitters = dict(client.topk_list("tk"))
        assert hitters[b"hot"] == 5

        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
        restarted = _spawn(tmp_path, "server-b.py", args)
        # unary plane: re-dial on a fresh channel (the killed server's
        # channel can sit in gRPC reconnect backoff past the restart;
        # session-level channel survival is test_streams' acceptance)
        client.close()
        client = _dial()
        client.wait_ready(timeout=120)

        # cuckoo: exactly one copy of "dup" survived the replay
        assert client.cf_exists("cf", [b"dup"])[0], (
            "the acked delete replayed twice: both copies are gone"
        )
        assert client.cf_del("cf", [b"dup"]).all()
        assert not client.cf_exists("cf", [b"dup"])[0], (
            "the acked delete was lost: two copies survived the kill"
        )
        assert client.cf_exists("cf", singles).all()

        # cms: weighted counts neither lost nor doubled
        after = client.cms_query("cms", [b"hot", b"warm"])
        assert after.tolist() == counts

        # topk: heap rebuilt from the replayed adds, same estimates
        assert dict(client.topk_list("tk")) == hitters
    finally:
        client.close()
        for p in (proc, restarted):
            if p is not None and p.poll() is None:
                p.send_signal(signal.SIGKILL)
        for p in (proc, restarted):
            if p is not None:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    pass

    # post-mortem: the KILLED server's mmap'd black-box ring survived
    cli = subprocess.run(
        [sys.executable, "-m", "tpubloom.obs.blackbox", str(plog),
         "--json"],
        capture_output=True, text=True, env=_child_env(), timeout=120,
    )
    assert cli.returncode == 0, cli.stderr
    out = json.loads(cli.stdout)
    (node,) = out["nodes"]
    assert node["meta"]["role"] == "primary"
    assert "boot" in [e["kind"] for e in node["events"]]
