"""In-process fake Redis speaking enough RESP2 for the checkpoint sink.

Plays the role the reference's test suite gives a live local redis-server
(SURVEY.md §4.1) — no redis-server exists in this environment, so a ~100
line threaded socket server stands in. It implements PING/SET/GET/DEL/
EXISTS/SETBIT/GETBIT over a dict; SETBIT/GETBIT let tests check that our
exported bitmaps answer exactly like Redis would for the reference's
``:ruby`` driver.
"""

from __future__ import annotations

import socket
import threading


class FakeRedis:
    def __init__(self):
        self.data: dict[bytes, bytearray] = {}
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(8)
        self.port = self._srv.getsockname()[1]
        self._stop = False
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        while not self._stop:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,), daemon=True).start()

    def _handle(self, conn: socket.socket):
        buf = b""

        def read_line():
            nonlocal buf
            while b"\r\n" not in buf:
                chunk = conn.recv(65536)
                if not chunk:
                    raise ConnectionError
                buf += chunk
            line, buf = buf.split(b"\r\n", 1)
            return line

        def read_exact(n):
            nonlocal buf
            while len(buf) < n:
                chunk = conn.recv(65536)
                if not chunk:
                    raise ConnectionError
                buf += chunk
            out, buf = buf[:n], buf[n:]
            return out

        try:
            while True:
                line = read_line()
                if not line.startswith(b"*"):
                    conn.sendall(b"-ERR protocol\r\n")
                    continue
                nargs = int(line[1:])
                args = []
                for _ in range(nargs):
                    hdr = read_line()
                    assert hdr.startswith(b"$")
                    args.append(read_exact(int(hdr[1:])))
                    read_exact(2)
                conn.sendall(self._dispatch(args))
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def _dispatch(self, args: list[bytes]) -> bytes:
        cmd = args[0].upper()
        if cmd == b"PING":
            return b"+PONG\r\n"
        if cmd == b"SET":
            self.data[args[1]] = bytearray(args[2])
            return b"+OK\r\n"
        if cmd == b"GET":
            val = self.data.get(args[1])
            if val is None:
                return b"$-1\r\n"
            return b"$%d\r\n%s\r\n" % (len(val), bytes(val))
        if cmd == b"DEL":
            n = sum(1 for k in args[1:] if self.data.pop(k, None) is not None)
            return b":%d\r\n" % n
        if cmd == b"EXISTS":
            n = sum(1 for k in args[1:] if k in self.data)
            return b":%d\r\n" % n
        if cmd == b"SETBIT":
            key, off, val = args[1], int(args[2]), int(args[3])
            buf = self.data.setdefault(key, bytearray())
            byte = off >> 3
            if len(buf) <= byte:
                buf.extend(b"\x00" * (byte + 1 - len(buf)))
            old = (buf[byte] >> (7 - (off & 7))) & 1
            if val:
                buf[byte] |= 1 << (7 - (off & 7))
            else:
                buf[byte] &= ~(1 << (7 - (off & 7))) & 0xFF
            return b":%d\r\n" % old
        if cmd == b"GETBIT":
            key, off = args[1], int(args[2])
            buf = self.data.get(key, bytearray())
            byte = off >> 3
            bit = 0 if byte >= len(buf) else (buf[byte] >> (7 - (off & 7))) & 1
            return b":%d\r\n" % bit
        return b"-ERR unknown command %s\r\n" % cmd

    def close(self):
        self._stop = True
        try:
            self._srv.close()
        except OSError:
            pass
