"""Pallas partition-sweep insert (tpubloom.ops.sweep) vs the sorted-scatter
blocked path and the CPU oracle.

The sweep kernel is the TPU hot-loop replacement for XLA's serialized
scatter (SURVEY.md §6/§7 "Pallas escape hatch"); here it runs in Pallas
interpret mode on CPU, which executes the same kernel logic (DMAs,
grid, chunk loop) without Mosaic. Bit-exactness against the scatter
path on identical inputs is the contract: "auto" may pick either path
per backend and the arrays must be interchangeable.

Shapes are kept small (m = 2^22 -> 8192 blocks, P = 8..16 partitions)
so interpret mode stays fast while still exercising multi-partition
grids, DMA window alignment, padding keys, duplicate merging, and the
overflow chunk loop.
"""

import numpy as np
import pytest
from hypothesis import given, settings, strategies as st

from tpubloom import CPUBlockedBloomFilter, FilterConfig
from tpubloom.filter import make_blocked_insert_fn, make_blocked_query_fn
from tpubloom.ops.sweep import choose_params, make_sweep_insert_fn, sweep_applicable
from tpubloom.utils.packing import pack_keys

import jax.numpy as jnp
import jax


@pytest.fixture
def config():
    return FilterConfig(m=1 << 22, k=7, key_len=16, block_bits=512)


def _zeros(config):
    return jnp.zeros((config.n_blocks, config.words_per_block), jnp.uint32)


def _run_both(config, keys_u8, lengths):
    scatter = jax.jit(
        make_blocked_insert_fn(config.replace(insert_path="scatter"))
    )
    sweep = jax.jit(make_sweep_insert_fn(config, interpret=True))
    a = np.asarray(scatter(_zeros(config), keys_u8, lengths))
    b = np.asarray(sweep(_zeros(config), keys_u8, lengths))
    return a, b


def test_matches_scatter_random(config):
    rng = np.random.default_rng(0)
    keys = jnp.asarray(rng.integers(0, 256, (512, 16), dtype=np.uint8))
    lengths = jnp.full((512,), 16, jnp.int32)
    a, b = _run_both(config, keys, lengths)
    np.testing.assert_array_equal(a, b)
    assert a.any()


def test_padding_keys_set_no_bits(config):
    rng = np.random.default_rng(1)
    keys = jnp.asarray(rng.integers(0, 256, (256, 16), dtype=np.uint8))
    lengths = jnp.asarray(
        np.where(np.arange(256) % 3 == 0, -1, 16).astype(np.int32)
    )
    a, b = _run_both(config, keys, lengths)
    np.testing.assert_array_equal(a, b)


def test_duplicate_heavy_overflow_chunks(config):
    # every key identical: one partition holds the whole batch, forcing
    # ceil(n / KMAX) > 1 serial chunks in-kernel
    key = np.frombuffer(b"same-key-16bytes", dtype=np.uint8)
    keys = jnp.asarray(np.tile(key, (1024, 1)))
    lengths = jnp.full((1024,), 16, jnp.int32)
    a, b = _run_both(config, keys, lengths)
    np.testing.assert_array_equal(a, b)


def test_membership_roundtrip_against_oracle(config):
    rng = np.random.default_rng(2)
    raw = [rng.bytes(16) for _ in range(600)]
    keys_u8, lengths = pack_keys(raw, config.key_len)
    sweep = jax.jit(make_sweep_insert_fn(config, interpret=True))
    blocks = sweep(_zeros(config), jnp.asarray(keys_u8), jnp.asarray(lengths))
    oracle = CPUBlockedBloomFilter(config, use_native=False)
    oracle.insert_batch(raw)
    np.testing.assert_array_equal(np.asarray(blocks), oracle.words)
    query = jax.jit(make_blocked_query_fn(config))
    hits = query(blocks, jnp.asarray(keys_u8), jnp.asarray(lengths))
    assert np.asarray(hits).all()


@settings(max_examples=10, deadline=None)
@given(st.lists(st.binary(min_size=0, max_size=16), min_size=1, max_size=64))
def test_hypothesis_parity(keys):
    config = FilterConfig(m=1 << 22, k=5, key_len=16, block_bits=512)
    keys_u8, lengths = pack_keys(keys, config.key_len)
    a, b = _run_both(config, jnp.asarray(keys_u8), jnp.asarray(lengths))
    np.testing.assert_array_equal(a, b)


def test_choose_params_and_applicability():
    R, kmax = choose_params(1 << 23, 1 << 20)
    assert (1 << 23) % R == 0
    assert kmax % 8 == 0 and 16 <= kmax <= 1024
    # per-partition occupancy fits the window with margin
    lam = (1 << 20) // ((1 << 23) // R)
    assert kmax > lam
    assert sweep_applicable(1 << 23, 1 << 20)
    # tiny filters stay on the scatter path
    assert not sweep_applicable(64, 1 << 20)


def test_insert_path_config_validation():
    with pytest.raises(ValueError):
        FilterConfig(m=1 << 20, k=7, insert_path="nope")
    cfg = FilterConfig(m=1 << 22, k=7, block_bits=512, insert_path="scatter")
    assert cfg.insert_path == "scatter"
