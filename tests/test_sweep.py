"""Pallas partition-sweep insert (tpubloom.ops.sweep) vs the sorted-scatter
blocked path and the CPU oracle.

The sweep kernel is the TPU hot-loop replacement for XLA's serialized
scatter (SURVEY.md §6/§7 "Pallas escape hatch"); here it runs in Pallas
interpret mode on CPU, which executes the same kernel logic (DMAs,
grid, chunk loop) without Mosaic. Bit-exactness against the scatter
path on identical inputs is the contract: "auto" may pick either path
per backend and the arrays must be interchangeable.

Shapes are kept small (m = 2^22 -> 8192 blocks, P = 8..16 partitions)
so interpret mode stays fast while still exercising multi-partition
grids, DMA window alignment, padding keys, duplicate merging, and the
overflow chunk loop.
"""

import numpy as np
import pytest

pytest.importorskip("hypothesis")  # property tests need it; skip cleanly without
from hypothesis import given, settings, strategies as st

from tpubloom import CPUBlockedBloomFilter, FilterConfig
from tpubloom.filter import make_blocked_insert_fn, make_blocked_query_fn
from tpubloom.ops.sweep import choose_params, make_sweep_insert_fn, sweep_applicable
from tpubloom.utils.packing import pack_keys

import jax.numpy as jnp
import jax


@pytest.fixture
def config():
    return FilterConfig(m=1 << 22, k=7, key_len=16, block_bits=512)


def _zeros(config):
    return jnp.zeros((config.n_blocks, config.words_per_block), jnp.uint32)


def _run_both(config, keys_u8, lengths):
    scatter = jax.jit(
        make_blocked_insert_fn(config.replace(insert_path="scatter"))
    )
    sweep = jax.jit(make_sweep_insert_fn(config, interpret=True))
    a = np.asarray(scatter(_zeros(config), keys_u8, lengths))
    b = np.asarray(sweep(_zeros(config), keys_u8, lengths))
    return a, b


def test_matches_scatter_random(config):
    rng = np.random.default_rng(0)
    keys = jnp.asarray(rng.integers(0, 256, (512, 16), dtype=np.uint8))
    lengths = jnp.full((512,), 16, jnp.int32)
    a, b = _run_both(config, keys, lengths)
    np.testing.assert_array_equal(a, b)
    assert a.any()


def test_padding_keys_set_no_bits(config):
    rng = np.random.default_rng(1)
    keys = jnp.asarray(rng.integers(0, 256, (256, 16), dtype=np.uint8))
    lengths = jnp.asarray(
        np.where(np.arange(256) % 3 == 0, -1, 16).astype(np.int32)
    )
    a, b = _run_both(config, keys, lengths)
    np.testing.assert_array_equal(a, b)


def test_duplicate_heavy_overflow_chunks(config):
    # every key identical: one partition holds the whole batch, forcing
    # ceil(n / KMAX) > 1 serial chunks in-kernel
    key = np.frombuffer(b"same-key-16bytes", dtype=np.uint8)
    keys = jnp.asarray(np.tile(key, (1024, 1)))
    lengths = jnp.full((1024,), 16, jnp.int32)
    a, b = _run_both(config, keys, lengths)
    np.testing.assert_array_equal(a, b)


def test_membership_roundtrip_against_oracle(config):
    rng = np.random.default_rng(2)
    raw = [rng.bytes(16) for _ in range(600)]
    keys_u8, lengths = pack_keys(raw, config.key_len)
    sweep = jax.jit(make_sweep_insert_fn(config, interpret=True))
    blocks = sweep(_zeros(config), jnp.asarray(keys_u8), jnp.asarray(lengths))
    oracle = CPUBlockedBloomFilter(config, use_native=False)
    oracle.insert_batch(raw)
    np.testing.assert_array_equal(np.asarray(blocks), oracle.words)
    query = jax.jit(make_blocked_query_fn(config))
    hits = query(blocks, jnp.asarray(keys_u8), jnp.asarray(lengths))
    assert np.asarray(hits).all()


@settings(max_examples=10, deadline=None)
@given(st.lists(st.binary(min_size=0, max_size=16), min_size=1, max_size=64))
def test_hypothesis_parity(keys):
    config = FilterConfig(m=1 << 22, k=5, key_len=16, block_bits=512)
    keys_u8, lengths = pack_keys(keys, config.key_len)
    a, b = _run_both(config, jnp.asarray(keys_u8), jnp.asarray(lengths))
    np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("k", [1, 2, 8])  # k=2 once hit a packed/unpacked
def test_parity_small_k(k):                # ambiguity in the sort payload
    config = FilterConfig(m=1 << 22, k=k, key_len=16, block_bits=512)
    rng = np.random.default_rng(k)
    keys = jnp.asarray(rng.integers(0, 256, (256, 16), dtype=np.uint8))
    lengths = jnp.full((256,), 16, jnp.int32)
    a, b = _run_both(config, keys, lengths)
    np.testing.assert_array_equal(a, b)
    assert a.any()


def test_choose_params_and_applicability():
    R, kmax = choose_params(1 << 23, 1 << 20)
    assert (1 << 23) % R == 0
    assert kmax % 8 == 0 and 16 <= kmax <= 1024
    # per-partition occupancy fits the window with margin
    lam = (1 << 20) // ((1 << 23) // R)
    assert kmax > lam
    assert sweep_applicable(1 << 23, 1 << 20)
    # tiny filters stay on the scatter path
    assert not sweep_applicable(64, 1 << 20)
    # sparse batches stay on the scatter path too: the sweep streams the
    # whole array per call, so a scalar insert (padded to 64) into a big
    # filter must NOT resolve to it (advisor r1, medium)
    assert not sweep_applicable(1 << 23, 64)
    assert not sweep_applicable(1 << 23, 1 << 15)  # lambda < 8
    assert sweep_applicable(1 << 23, 1 << 17)  # lambda = 8, break-even+margin


def _run_test_insert(config, keys_u8, lengths, blocks):
    fn = jax.jit(make_sweep_insert_fn(config, interpret=True, with_presence=True))
    nb2, present = fn(blocks, jnp.asarray(keys_u8), jnp.asarray(lengths))
    return np.asarray(nb2), np.asarray(present)


def test_test_insert_presence_and_bits(config):
    rng = np.random.default_rng(5)
    first = [rng.bytes(16) for _ in range(300)]
    second = [rng.bytes(16) for _ in range(300)]
    k1, l1 = pack_keys(first, config.key_len)
    oracle = CPUBlockedBloomFilter(config, use_native=False)
    oracle.insert_batch(first)
    sweep = jax.jit(make_sweep_insert_fn(config, interpret=True))
    blocks = sweep(_zeros(config), jnp.asarray(k1), jnp.asarray(l1))

    # second batch = mix of already-present and fresh keys
    mixed = first[:150] + second
    k2, l2 = pack_keys(mixed, config.key_len)
    nb2, present = _run_test_insert(config, k2, l2, blocks)
    assert present[:150].all(), "pre-inserted keys must report present"
    # fresh random keys: FPR at this fill is ~0
    assert present[150:].sum() <= 2
    # bits identical to a plain insert of the same batch
    plain = np.asarray(
        sweep(
            jax.jit(make_sweep_insert_fn(config, interpret=True))(
                _zeros(config), jnp.asarray(k1), jnp.asarray(l1)
            ),
            jnp.asarray(k2),
            jnp.asarray(l2),
        )
    )
    np.testing.assert_array_equal(nb2, plain)


def test_test_insert_duplicates_report_prebatch_state(config):
    rng = np.random.default_rng(6)
    keys = [rng.bytes(16) for _ in range(64)]
    batch = keys + keys  # every key twice in ONE batch
    ku, lu = pack_keys(batch, config.key_len)
    _, present = _run_test_insert(config, ku, lu, _zeros(config))
    assert not present.any(), "duplicates see the PRE-batch (empty) state"


def test_test_insert_padding_tail(config):
    rng = np.random.default_rng(7)
    keys = [rng.bytes(16) for _ in range(100)]
    ku, lu = pack_keys(keys, config.key_len)
    ku = np.pad(ku, ((0, 28), (0, 0)))
    lu = np.pad(lu, (0, 28), constant_values=-1)
    _, present = _run_test_insert(config, ku, lu, _zeros(config))
    assert not present.any()
    assert present.shape == (128,)


def test_test_insert_overflow_falls_back(config):
    # all keys identical -> one partition overflows its window -> the
    # lax.cond gather fallback answers presence for the whole batch
    key = b"dup-key-16-bytes"
    batch = [key] * 600
    ku, lu = pack_keys(batch, config.key_len)
    _, present = _run_test_insert(config, ku, lu, _zeros(config))
    assert not present.any(), "key absent before the batch"
    # now it IS present: second identical batch must report all-True
    fn = jax.jit(make_sweep_insert_fn(config, interpret=True, with_presence=True))
    blocks, _ = fn(_zeros(config), jnp.asarray(ku), jnp.asarray(lu))
    _, present2 = fn(blocks, jnp.asarray(ku), jnp.asarray(lu))
    assert np.asarray(present2).all()


def test_filter_class_return_presence():
    config = FilterConfig(m=1 << 22, k=7, key_len=16, block_bits=512,
                          insert_path="scatter")
    from tpubloom.filter import BlockedBloomFilter

    f = BlockedBloomFilter(config)
    rng = np.random.default_rng(8)
    keys = [rng.bytes(16) for _ in range(200)]
    p1 = f.insert_batch(keys, return_presence=True)
    assert not p1.any()
    p2 = f.insert_batch(keys, return_presence=True)
    assert p2.all()


def test_insert_path_config_validation():
    with pytest.raises(ValueError):
        FilterConfig(m=1 << 20, k=7, insert_path="nope")
    cfg = FilterConfig(m=1 << 22, k=7, block_bits=512, insert_path="scatter")
    assert cfg.insert_path == "scatter"
