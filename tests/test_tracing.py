"""Tracing/profiling subsystem (SURVEY.md §5 "Tracing/profiling")."""

import glob
import os

import numpy as np

from tpubloom import BloomFilter, FilterConfig
from tpubloom.utils import tracing


def test_profile_call_produces_trace(tmp_path):
    config = FilterConfig(m=1 << 16, k=4, key_len=16)
    f = BloomFilter(config)
    rng = np.random.default_rng(0)
    keys = [rng.bytes(16) for _ in range(64)]

    def work():
        f.insert_batch(keys)
        return f.include_batch(keys)

    result, trace_dir = tracing.profile_call(work, log_dir=str(tmp_path / "tr"))
    assert result.all()
    # jax.profiler writes plugins/profile/<run>/ with xplane/trace files
    produced = glob.glob(os.path.join(trace_dir, "plugins", "profile", "*", "*"))
    assert produced, f"no trace artifacts under {trace_dir}"


def test_annotate_is_transparent():
    with tracing.annotate("span", batch=3):
        x = 1 + 1
    assert x == 2
