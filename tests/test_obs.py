"""Observability subsystem tests (ISSUE 1): exposition format, slowlog
RPC round-trip with request-id correlation, FPR-drift gauge sanity,
phase breakdown, and the O(1) histogram rewrite."""

import urllib.request

import numpy as np
import pytest

from tpubloom import checkpoint as ckpt
from tpubloom.obs import counters as obs_counters
from tpubloom.obs.context import phase, request
from tpubloom.obs.exposition import parse_families, render_service
from tpubloom.obs.httpd import start_metrics_server
from tpubloom.obs.slowlog import Slowlog, summarize_request
from tpubloom.server.client import BloomClient
from tpubloom.server.metrics import LatencyHistogram
from tpubloom.server.service import BloomService, build_server


@pytest.fixture()
def server(tmp_path):
    service = BloomService(sink_factory=lambda config: ckpt.FileSink(str(tmp_path)))
    srv, port = build_server(service, "127.0.0.1:0")
    srv.start()
    client = BloomClient(f"127.0.0.1:{port}")
    client.wait_ready()
    yield client, service
    client.close()
    srv.stop(grace=None)


# -- LatencyHistogram (satellite: O(1) observe + cumulative buckets) ---------


def test_histogram_bucket_lookup_matches_linear_scan():
    """bit_length indexing must agree with the old linear scan on every
    boundary: us in [2^(i-1), 2^i) -> bucket i, overflow -> last."""
    def linear_bucket(us):
        for i, b in enumerate(LatencyHistogram.BUCKETS):
            if us < b:
                return i
        return len(LatencyHistogram.BUCKETS)

    h = LatencyHistogram()
    probes_us = [0.0, 0.5, 1.0, 1.5, 2.0, 3.0, 4.0, 1023.0, 1024.0, 1025.0]
    probes_us += [float(2**i) for i in range(28)]
    probes_us += [float(2**i - 1) for i in range(1, 28)]
    for us in probes_us:
        h2 = LatencyHistogram()
        h2.observe(us / 1e6)
        # compare at the value observe() actually sees (the /1e6 * 1e6
        # round-trip may land an ulp off the probe)
        assert h2.counts[linear_bucket((us / 1e6) * 1e6)] == 1, (
            f"bucket drift at {us}us"
        )
        h.observe(us / 1e6)
    assert h.n == len(probes_us)
    cum = h.cumulative()
    assert cum[-1] == h.n
    assert all(b >= a for a, b in zip(cum, cum[1:])), "cumulative must be monotone"
    s = h.summary()
    assert s["n"] == h.n and "p50_us_lt" in s and "p99_us_lt" in s
    assert s["buckets_cum"] == cum


# -- slowlog core ------------------------------------------------------------


def test_slowlog_keeps_slowest_and_resets():
    sl = Slowlog(capacity=3)
    for i, d in enumerate([0.01, 0.5, 0.02, 0.9, 0.03, 0.001]):
        sl.record(method="M", duration_s=d, rid=f"r{i}", batch=i)
    got = [e["duration_s"] for e in sl.entries()]
    assert got == [0.9, 0.5, 0.03], "must keep the slowest, slowest-first"
    assert sl.entries(2) == sl.entries()[:2]
    assert sl.total_recorded == 6
    assert sl.reset() == 3 and len(sl) == 0
    sl.record(method="M", duration_s=1.0)
    assert len(sl) == 1  # records again after reset


def test_summarize_request_redacts_keys():
    s = summarize_request("InsertBatch", {"name": "urls", "keys": [b"a"] * 7,
                                          "rid": "deadbeef"})
    assert "keys[7]" in s and "deadbeef" not in s and "urls" in s


# -- exposition format -------------------------------------------------------


def test_exposition_golden_scrape_and_monotone_counters(server):
    client, service = server
    client.create_filter("expo", capacity=10_000, error_rate=0.01)
    client.insert_batch("expo", [b"k%d" % i for i in range(500)])
    client.include_batch("expo", [b"k1", b"nope"])

    text = render_service(service)
    fam = parse_families(text)
    for name in (
        "tpubloom_uptime_seconds",
        "tpubloom_keys_inserted_total",
        "tpubloom_keys_queried_total",
        "tpubloom_rpc_duration_seconds_bucket",
        "tpubloom_rpc_duration_seconds_count",
        "tpubloom_rpc_phase_seconds_bucket",
        "tpubloom_filter_fill_ratio",
        "tpubloom_filter_bits_set",
        "tpubloom_filter_estimated_fpr",
        "tpubloom_filter_predicted_fpr",
        "tpubloom_filter_fpr_drift",
        "tpubloom_slowlog_entries",
    ):
        assert name in fam, f"scrape must contain {name}"
    assert fam["tpubloom_keys_inserted_total"][()] == 500

    # histogram sanity: bucket series is cumulative and ends at _count
    buckets = {
        k: v
        for k, v in fam["tpubloom_rpc_duration_seconds_bucket"].items()
        if dict(k)["method"] == "InsertBatch"
    }
    series = [v for k, v in sorted(
        buckets.items(),
        key=lambda kv: float(dict(kv[0])["le"].replace("+Inf", "inf")),
    )]
    assert all(b >= a for a, b in zip(series, series[1:]))
    assert series[-1] == fam["tpubloom_rpc_duration_seconds_count"][
        (("method", "InsertBatch"),)
    ]

    # counters are monotone across scrapes
    client.insert_batch("expo", [b"more-%d" % i for i in range(100)])
    fam2 = parse_families(render_service(service))
    assert fam2["tpubloom_keys_inserted_total"][()] == 600
    assert (
        fam2["tpubloom_rpc_duration_seconds_count"][(("method", "InsertBatch"),)]
        > fam["tpubloom_rpc_duration_seconds_count"][(("method", "InsertBatch"),)]
    )


def test_metrics_http_endpoint(server):
    client, service = server
    client.create_filter("http", capacity=1000, error_rate=0.01)
    client.insert_batch("http", [b"a", b"b"])
    ms = start_metrics_server(service, port=0, host="127.0.0.1")
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{ms.port}/metrics", timeout=10
        ) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/plain")
            fam = parse_families(resp.read().decode())
        assert fam["tpubloom_keys_inserted_total"][()] == 2
        with urllib.request.urlopen(
            f"http://127.0.0.1:{ms.port}/healthz", timeout=10
        ) as resp:
            assert resp.status == 200
    finally:
        ms.close()


# -- slowlog RPC round-trip + request-id correlation -------------------------


def test_slowlog_rpc_roundtrip_with_rids(server):
    client, _ = server
    client.create_filter("slow", capacity=10_000, error_rate=0.01)
    rids = {}
    client.insert_batch("slow", [b"s%d" % i for i in range(256)])
    rids["InsertBatch"] = client.last_rid
    client.include_batch("slow", [b"s0", b"s1"])
    rids["QueryBatch"] = client.last_rid

    entries = client.slowlog_get()
    assert entries, "traffic must populate the slowlog"
    assert entries == sorted(entries, key=lambda e: -e["duration_s"])
    by_rid = {e["rid"]: e for e in entries}
    for method, rid in rids.items():
        assert rid in by_rid, f"{method} rid must round-trip into the slowlog"
        e = by_rid[rid]
        assert e["method"] == method
        assert e["batch"] == (256 if method == "InsertBatch" else 2)
        assert e["duration_s"] > 0 and "keys[" in e["args"]
        # phase breakdown rides along (decode is wire-level, kernel /
        # kernel_query is the device pass the filter layer recorded —
        # the read path gets its own span since ISSUE 12)
        kphase = "kernel" if method == "InsertBatch" else "kernel_query"
        assert {"decode", "host_prep", kphase, "encode"} <= set(e["phases"])
        assert sum(e["phases"].values()) <= e["duration_s"] + 1e-6

    n_before = len(client.slowlog_get())
    # >=: the SlowlogGet call above records ITSELF once it finishes
    assert client.slowlog_reset() >= n_before
    # only the reset/get RPCs themselves can be in the log afterwards
    assert {e["method"] for e in client.slowlog_get()} <= {
        "SlowlogGet", "SlowlogReset"
    }


def test_slowlog_get_n_limits(server):
    client, _ = server
    client.create_filter("lim", capacity=1000, error_rate=0.01)
    for i in range(5):
        client.insert_batch("lim", [b"x%d" % i])
    assert len(client.slowlog_get(3)) == 3


# -- gauges ------------------------------------------------------------------


def test_fpr_drift_gauge_sanity():
    """After N random inserts the observed (fill-derived) FPR must sit
    close to the analytic prediction — the drift gauge reads ~0 for an
    honest filter and random keys."""
    from tpubloom import BloomFilter, FilterConfig

    cfg = FilterConfig(m=1 << 18, k=4, key_len=16)
    f = BloomFilter(cfg)
    rng = np.random.default_rng(7)
    f.insert_batch([rng.bytes(16) for _ in range(20_000)])
    st = f.stats()
    assert 0 < st["predicted_fpr"] < 1 and 0 < st["estimated_fpr"] < 1
    assert st["estimated_fpr"] == pytest.approx(st["predicted_fpr"], rel=0.15)
    assert st["fpr_drift"] == pytest.approx(
        st["estimated_fpr"] - st["predicted_fpr"]
    )
    assert st["bits_set"] == pytest.approx(st["fill_ratio"] * cfg.m, abs=1.0)
    # duplicate inserts violate the distinct-keys sizing assumption ->
    # the drift gauge must go measurably negative (observed < predicted)
    f.insert_batch([b"dup-key"] * 4096)
    st2 = f.stats()
    assert st2["fpr_drift"] < st["fpr_drift"]


def test_sharded_per_shard_fill_gauges():
    from tpubloom import FilterConfig
    from tpubloom.parallel.sharded import ShardedBloomFilter

    cfg = FilterConfig(m=1 << 20, k=4, key_len=16, shards=8, key_name="shobs")
    f = ShardedBloomFilter(cfg)
    rng = np.random.default_rng(3)
    f.insert_batch([rng.bytes(16) for _ in range(4000)])
    fills = f.shard_fill_ratios()
    assert len(fills) == 8 and all(fl > 0 for fl in fills)
    # routing spreads uniformly: no shard way off the mean
    assert max(fills) < 3 * min(fills)
    st = f.stats()
    assert st["fill_ratio_per_shard"] == pytest.approx(fills, rel=0.01)
    assert st["fill_ratio"] == pytest.approx(float(np.mean(fills)), rel=0.05)


def test_checkpoint_gauges(tmp_path):
    from tpubloom import BloomFilter, FilterConfig

    sink = ckpt.FileSink(str(tmp_path))
    f = BloomFilter(FilterConfig(m=1 << 16, k=4, key_name="ckobs"))
    cp = ckpt.AsyncCheckpointer(f, sink, every_n_inserts=100)
    f.insert_batch([b"a", b"b"])
    cp.notify_inserts(2)
    st = cp.obs_stats()
    assert st["lag_inserts"] == 2 and st["checkpoints_written"] == 0
    assert st["age_seconds"] is None
    assert cp.trigger() and cp.flush()
    st = cp.obs_stats()
    assert st["lag_inserts"] == 0, "a manual trigger must reset the lag gauge"
    assert st["checkpoints_written"] == 1
    assert st["age_seconds"] >= 0 and st["last_duration_seconds"] > 0
    assert st["last_error"] is None
    cp.close(final_checkpoint=False)


# -- phase context (unit) ----------------------------------------------------


def test_phase_context_accumulates_and_noops():
    with phase("orphan"):  # no active request: must be a silent no-op
        pass
    with request("TestMethod") as rctx:
        with phase("kernel"):
            pass
        with phase("kernel"):
            pass
        with phase("d2h"):
            pass
    assert set(rctx.phases) == {"kernel", "d2h"}
    assert rctx.rid and len(rctx.rid) == 16


def test_global_counters_roundtrip():
    obs_counters.incr("obs_test_counter", 3)
    assert obs_counters.get("obs_test_counter") == 3
    assert obs_counters.global_counters()["obs_test_counter"] == 3


# -- the tier-1 smoke (satellite: CI/tooling) --------------------------------


def test_obs_smoke():
    """The benchmarks/obs_smoke.py end-to-end check runs in tier-1."""
    import importlib
    import os
    import sys

    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "benchmarks")
    )
    try:
        obs_smoke = importlib.import_module("obs_smoke")
        result = obs_smoke.run_smoke()
    finally:
        sys.path.pop(0)
    assert result["ok"] and result["slowlog_entries"] > 0
    assert result["insert_rid_correlated"]
