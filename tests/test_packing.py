"""Key packing + Redis-bitmap byte-order tests (SURVEY.md §5 checkpoint
compatibility: a :ruby-driver filter must be able to read a :jax-built one)."""

import numpy as np
import pytest

from tpubloom.utils.packing import (
    pack_keys,
    pack_keys_dense,
    redis_bitmap_to_words,
    words_to_redis_bitmap,
)


def test_pack_basics():
    ks, ls = pack_keys([b"abc", b"", "héllo"], 16)
    assert ks.shape == (3, 16) and ls.tolist() == [3, 0, 6]
    assert bytes(ks[0, :3]) == b"abc"
    assert ks[0, 3:].sum() == 0  # zero padding (hash-kernel contract)


def test_pack_long_key_policies():
    with pytest.raises(ValueError):
        pack_keys([b"x" * 20], 16)
    ks, ls = pack_keys([b"x" * 20], 16, key_policy="digest")
    assert ls[0] == 16  # BLAKE2b-16 digest replaces the long key
    ks2, _ = pack_keys([b"x" * 20], 16, key_policy="digest")
    np.testing.assert_array_equal(ks, ks2)  # deterministic


def test_pack_dense_zeroes_padding():
    raw = np.full((2, 8), 0xFF, np.uint8)
    ks, ls = pack_keys_dense(raw, [3, 8])
    assert ks[0, 3:].sum() == 0 and ks[1].sum() == 8 * 0xFF


def test_redis_bitmap_semantics():
    """Golden check of the SETBIT byte/bit mapping: Redis stores bit n in
    byte n>>3, bit 7-(n&7) (MSB-first)."""
    m = 64
    words = np.zeros(2, np.uint32)
    for pos in (0, 1, 7, 8, 31, 32, 63):
        words[pos >> 5] |= np.uint32(1) << np.uint32(pos & 31)
    data = words_to_redis_bitmap(words, m)
    assert len(data) == 8
    for pos in range(m):
        expected = pos in (0, 1, 7, 8, 31, 32, 63)
        redis_bit = (data[pos >> 3] >> (7 - (pos & 7))) & 1
        assert redis_bit == int(expected), f"bit {pos}"


def test_redis_bitmap_roundtrip():
    rng = np.random.default_rng(3)
    m = 1000  # not a multiple of 32: exercises truncation/zero-fill
    n_words = (m + 31) // 32
    words = rng.integers(0, 2**32, n_words, dtype=np.uint32)
    # zero bits beyond m, as a real filter would have
    tail_bits = n_words * 32 - m
    words[-1] &= np.uint32((1 << (32 - tail_bits)) - 1)
    data = words_to_redis_bitmap(words, m)
    assert len(data) == (m + 7) // 8
    back = redis_bitmap_to_words(data, m)
    np.testing.assert_array_equal(back, words)


def test_redis_bitmap_short_data():
    # Restoring from a shorter-than-m bitmap zero-fills the tail.
    words = redis_bitmap_to_words(b"\x80", 64)
    assert words[0] == 1 and words[1] == 0
