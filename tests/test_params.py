"""Parameter math tests — reference-identical (m, k) sizing (SURVEY.md §2.1)."""

import math

import pytest

from tpubloom.params import optimal_m_k, round_up_pow2, theoretical_fpr


def test_textbook_formula():
    # n=1e6, p=0.01 -> m ≈ 9.585e6 bits, k ≈ 7 — the classic worked example.
    m, k = optimal_m_k(1_000_000, 0.01)
    assert m == math.ceil(-1_000_000 * math.log(0.01) / math.log(2) ** 2)
    assert 9_585_000 < m < 9_586_000
    assert k == 7


def test_north_star_config_consistency():
    # BASELINE north star: m=2^32, k=7 at <=1% FPR. Capacity at that point:
    # n = -m ln(2)^2 / ln(p) => inserting that many keys keeps FPR <= 1%.
    m = 1 << 32
    n = int(-m * math.log(2) ** 2 / math.log(0.01))
    assert theoretical_fpr(m, 7, n) <= 0.0105


def test_k_at_least_one():
    m, k = optimal_m_k(10, 0.5)
    assert k >= 1


def test_fpr_monotone_in_n():
    m, k = 1 << 20, 7
    fprs = [theoretical_fpr(m, k, n) for n in (0, 1000, 10_000, 100_000)]
    assert fprs == sorted(fprs)
    assert fprs[0] == 0.0


def test_round_up_pow2():
    assert round_up_pow2(1) == 1
    assert round_up_pow2(3) == 4
    assert round_up_pow2(1024) == 1024
    assert round_up_pow2(1025) == 2048


@pytest.mark.parametrize("bad", [(0, 0.01), (-5, 0.01), (100, 0.0), (100, 1.0)])
def test_validation(bad):
    with pytest.raises(ValueError):
        optimal_m_k(*bad)
