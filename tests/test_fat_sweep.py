"""Fat-row sweep correctness (interpret mode on CPU) — the shipping TPU
hot loop's bit-exactness contract, exercised at shapes where
choose_fat_params actually selects it (the legacy test_sweep.py shapes
fall back to the old kernel).

Real-Mosaic validation of the same contracts runs on hardware via
benchmarks/adversarial.py (interpret mode alone is weak evidence for
this kernel family — Mosaic has miscompiled lane patterns silently)."""

import jax.numpy as jnp
import numpy as np
import pytest

from tpubloom.config import FilterConfig
from tpubloom.ops import blocked, sweep

NB, BB, K, B = 8192, 512, 7, 8192
CFG = FilterConfig(m=NB * BB, k=K, key_len=16, block_bits=BB)
W = CFG.words_per_block


def _positions(keys_u8, lengths):
    return blocked.block_positions(
        keys_u8, jnp.maximum(lengths, 0),
        n_blocks=NB, block_bits=BB, k=K, seed=CFG.seed,
        block_hash=CFG.block_hash,
    )


def _scatter_ref(blk, bit, valid):
    masks = blocked.build_masks(bit, W)
    return blocked.blocked_insert(
        jnp.zeros((NB, W), jnp.uint32), blk, masks, valid
    )


@pytest.fixture(scope="module")
def uniform_batch():
    rng = np.random.default_rng(0)
    keys = jnp.asarray(rng.integers(0, 256, (B, 16), np.uint8))
    lengths = jnp.full((B,), 16, jnp.int32)
    return keys, lengths


def test_fat_params_selected_here():
    assert sweep.choose_fat_params(NB, B, W) is not None
    assert sweep.choose_fat_params(NB, B, W, presence=True) is not None


def test_fat_insert_matches_scatter(uniform_batch):
    keys, lengths = uniform_batch
    blk, bit = _positions(keys, lengths)
    valid = jnp.ones((B,), bool)
    ref = _scatter_ref(blk, bit, valid)
    params = sweep.choose_fat_params(NB, B, W)
    out = sweep.apply_fat_updates(
        jnp.zeros((NB, W), jnp.uint32), blk, bit, valid,
        block_bits=BB, params=params, interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_fat_presence_replay_and_state(uniform_batch):
    keys, lengths = uniform_batch
    ins = sweep.make_sweep_insert_fn(CFG, interpret=True, with_presence=True)
    st, p1 = ins(jnp.zeros((NB, W), jnp.uint32), keys, lengths)
    assert int(p1.sum()) == 0, "fresh keys must not be present"
    st2, p2 = ins(st, keys, lengths)
    assert int(p2.sum()) == B, "replayed keys must all be present"
    blk, bit = _positions(keys, lengths)
    ref = _scatter_ref(blk, bit, jnp.ones((B,), bool))
    np.testing.assert_array_equal(np.asarray(st), np.asarray(ref))


def test_fat_presence_tail_padding(uniform_batch):
    """The documented contract: padding is a TAIL suffix; padded entries
    report False and valid entries keep correct, unshifted verdicts."""
    keys, lengths = uniform_batch
    lp = lengths.at[B - 100 :].set(-1)
    ins = sweep.make_sweep_insert_fn(CFG, interpret=True, with_presence=True)
    st, p1 = ins(jnp.zeros((NB, W), jnp.uint32), keys, lp)
    assert int(p1.sum()) == 0
    st2, p2 = ins(st, keys, lp)
    assert bool(np.asarray(p2)[: B - 100].all()), "valid keys shifted/lost"
    assert not np.asarray(p2)[B - 100 :].any(), "padded entries must be False"
    blk, bit = _positions(keys, lp)
    ref = _scatter_ref(blk, bit, lp >= 0)
    np.testing.assert_array_equal(np.asarray(st), np.asarray(ref))


def test_fat_duplicate_skew_falls_back_bit_exact():
    """Window overflow (duplicate skew) must route the whole batch to the
    scatter branch and stay bit-exact, presence included."""
    rng = np.random.default_rng(1)
    dup = jnp.asarray(
        np.tile(rng.integers(0, 256, (16, 16), np.uint8), (B // 16, 1))
    )
    lengths = jnp.full((B,), 16, jnp.int32)
    blk, bit = _positions(dup, lengths)
    valid = jnp.ones((B,), bool)
    ref = _scatter_ref(blk, bit, valid)
    params = sweep.choose_fat_params(NB, B, W)
    out = sweep.apply_fat_updates(
        jnp.zeros((NB, W), jnp.uint32), blk, bit, valid,
        block_bits=BB, params=params, interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    ins = sweep.make_sweep_insert_fn(CFG, interpret=True, with_presence=True)
    st, p1 = ins(jnp.zeros((NB, W), jnp.uint32), dup, lengths)
    assert int(p1.sum()) == 0
    st2, p2 = ins(st, dup, lengths)
    assert int(p2.sum()) == B
    np.testing.assert_array_equal(np.asarray(st), np.asarray(ref))


def test_fat_small_filter_feasibility_fallback():
    """choose_fat_params must try smaller R8 when the score-best one has
    no feasible grid (review finding: nb=512, batch=256 previously
    returned None although R8=32 qualifies)."""
    out = sweep.choose_fat_params(512, 256, 16)
    assert out is not None
    J, R8, S, KJ, KBJ = out
    assert (512 // J) % R8 == 0 and ((512 // J) // R8) // S >= 2
