"""Blocked (cache-line) bloom filter — device kernels vs CPU oracle.

The blocked layout is the throughput variant (tpubloom.ops.blocked); these
tests pin its position spec between the jnp kernels and the NumPy oracle,
exercise the duplicate-block merge in the insert path, and measure FPR
against the configured bound (SURVEY.md §4.2 items 1 and 4 applied to the
blocked spec).
"""

import numpy as np
import pytest

pytest.importorskip("hypothesis")  # property tests need it; skip cleanly without
from hypothesis import given, settings, strategies as st

from tpubloom import BlockedBloomFilter, CPUBlockedBloomFilter, FilterConfig
from tpubloom.params import theoretical_fpr


def _rand_keys(n, rng, length=16):
    return [rng.bytes(length) for _ in range(n)]


@pytest.fixture
def config():
    return FilterConfig(m=1 << 20, k=7, key_len=16, block_bits=512)


def test_roundtrip_and_negative(config):
    rng = np.random.default_rng(1)
    f = BlockedBloomFilter(config)
    keys = _rand_keys(500, rng)
    f.insert_batch(keys)
    assert f.include_batch(keys).all()
    absent = _rand_keys(500, rng)
    # at this fill the FPR is tiny; allow a stray hit or two
    assert f.include_batch(absent).mean() < 0.05


def test_parity_with_cpu_oracle(config):
    rng = np.random.default_rng(2)
    f = BlockedBloomFilter(config)
    o = CPUBlockedBloomFilter(config, use_native=False)  # ground truth stays NumPy
    keys = _rand_keys(2000, rng)
    f.insert_batch(keys)
    o.insert_batch(keys)
    # identical arrays bit for bit
    np.testing.assert_array_equal(f.words_logical, o.words)
    probe = keys[:100] + _rand_keys(400, rng)
    np.testing.assert_array_equal(f.include_batch(probe), o.include_batch(probe))


@settings(max_examples=25, deadline=None)
@given(
    st.lists(st.binary(min_size=0, max_size=16), min_size=1, max_size=40),
    st.lists(st.binary(min_size=0, max_size=16), min_size=1, max_size=40),
)
def test_parity_hypothesis(inserted, probes):
    config = FilterConfig(m=1 << 14, k=5, key_len=16, block_bits=256)
    f = BlockedBloomFilter(config)
    o = CPUBlockedBloomFilter(config, use_native=False)  # ground truth stays NumPy
    f.insert_batch(inserted)
    o.insert_batch(inserted)
    np.testing.assert_array_equal(f.words_logical, o.words)
    np.testing.assert_array_equal(
        f.include_batch(probes), o.include_batch(probes)
    )


def test_duplicate_blocks_in_batch_merge():
    """Many keys landing in the same block within one batch must ALL set
    their bits (the segmented row-OR dedup path)."""
    config = FilterConfig(m=1 << 10, k=4, key_len=16, block_bits=256)
    # m=1024, block_bits=256 -> only 4 blocks: heavy duplication guaranteed
    rng = np.random.default_rng(3)
    keys = _rand_keys(300, rng)
    f = BlockedBloomFilter(config)
    o = CPUBlockedBloomFilter(config, use_native=False)  # ground truth stays NumPy
    f.insert_batch(keys)
    o.insert_batch(keys)
    np.testing.assert_array_equal(f.words_logical, o.words)
    assert f.include_batch(keys).all()


def test_duplicate_keys_in_batch():
    config = FilterConfig(m=1 << 14, k=5, block_bits=512)
    f = BlockedBloomFilter(config)
    f.insert_batch([b"same-key"] * 17 + [b"other"])
    assert f.include(b"same-key")
    assert f.include(b"other")


def test_padding_rows_set_no_bits(config):
    f = BlockedBloomFilter(config)
    f.insert_batch([b"a"])  # bucket-padded to 64 internally
    o = CPUBlockedBloomFilter(config, use_native=False)  # ground truth stays NumPy
    o.insert_batch([b"a"])
    np.testing.assert_array_equal(f.words_logical, o.words)


def test_fpr_within_bound():
    """Empirical FPR at design load stays within ~2x of the flat-filter
    theory (blocked adds a small Poisson-skew excess; at 50% design load it
    must remain well under the configured bound's ballpark)."""
    config = FilterConfig(m=1 << 16, k=7, block_bits=512)
    n = 4000  # ~ m ln2 / k would be capacity; this is ~60% of that
    rng = np.random.default_rng(4)
    f = BlockedBloomFilter(config)
    f.insert_batch(_rand_keys(n, rng))
    probes = _rand_keys(20000, rng)
    fpr = f.include_batch(probes).mean()
    flat_theory = theoretical_fpr(config.m, config.k, n)
    assert fpr < max(4 * flat_theory, 1e-3), (fpr, flat_theory)


def test_serialization_roundtrip(config):
    rng = np.random.default_rng(5)
    keys = _rand_keys(1000, rng)
    f = BlockedBloomFilter(config)
    f.insert_batch(keys)
    data = f.to_bytes()
    g = BlockedBloomFilter.from_bytes(config, data)
    assert g.include_batch(keys).all()
    o = CPUBlockedBloomFilter.from_bytes(config, data)
    assert o.include_batch(keys).all()


def test_fat_storage_logical_roundtrip():
    """Fat [NB/J, 128] device storage is the SAME row-major bytes as the
    logical [NB, W] array: words_logical undoes the fold, to_bytes is
    layout-agnostic, and bytes written under either layout restore into
    the other with identical membership (filter.py fat-storage contract;
    benchmarks/RESULTS_r3.md §2 for why the device view is fat)."""
    from tpubloom.filter import blocked_storage_fat

    fat_cfg = FilterConfig(m=1 << 20, k=7, key_len=16, block_bits=512)
    assert blocked_storage_fat(fat_cfg)
    # nb=4 not divisible by J=16 -> storage stays logical
    thin_cfg = FilterConfig(m=1 << 10, k=4, key_len=16, block_bits=256)
    assert not blocked_storage_fat(thin_cfg)

    rng = np.random.default_rng(11)
    keys = _rand_keys(1200, rng)
    f = BlockedBloomFilter(fat_cfg)
    f.insert_batch(keys)
    nb, w = fat_cfg.n_blocks, fat_cfg.words_per_block
    assert f.words.shape == (nb * w // 128, 128)
    assert f.words_logical.shape == (nb, w)
    # identical bytes under both views
    assert f.words_logical.astype("<u4").tobytes() == f.to_bytes()

    o = CPUBlockedBloomFilter(fat_cfg, use_native=False)
    o.insert_batch(keys)
    np.testing.assert_array_equal(f.words_logical, o.words)

    # to_bytes/from_bytes roundtrip across device<->oracle in both directions
    g = BlockedBloomFilter.from_bytes(fat_cfg, o.to_bytes())
    assert g.words.shape == f.words.shape
    np.testing.assert_array_equal(g.words_logical, o.words)
    assert g.include_batch(keys).all()
    o2 = CPUBlockedBloomFilter.from_bytes(fat_cfg, f.to_bytes())
    np.testing.assert_array_equal(o2.words, o.words)

    # thin config: words IS the logical view
    t = BlockedBloomFilter(thin_cfg)
    t.insert_batch(keys[:100])
    assert t.words.shape == (thin_cfg.n_blocks, thin_cfg.words_per_block)
    np.testing.assert_array_equal(np.asarray(t.words), t.words_logical)


def test_clear(config):
    f = BlockedBloomFilter(config)
    f.insert_batch([b"x"])
    f.clear()
    assert not f.include(b"x")
    assert f.fill_ratio() == 0.0


def test_default_block_bits():
    f = BlockedBloomFilter(FilterConfig(m=1 << 16, k=7))
    assert f.config.block_bits == 512


def test_config_validation():
    with pytest.raises(ValueError, match="power of two"):
        FilterConfig(m=1 << 16, k=7, block_bits=300)
    # blocked counting is supported; m counts counters and must cover a
    # whole number of blocks (block_bits/4 counters each)
    with pytest.raises(ValueError, match="counters per block"):
        FilterConfig(m=64, k=7, block_bits=512, counting=True)
    assert FilterConfig(
        m=1 << 16, k=7, block_bits=512, counting=True
    ).n_blocks == (1 << 16) // 128
    with pytest.raises(ValueError, match="power-of-two m"):
        FilterConfig(m=96, k=7, block_bits=512)


def test_native_blocked_parity(config):
    """C++ fused blocked path == NumPy path, bit for bit (when built)."""
    from tpubloom import native

    if not native.available():
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(7)
    keys = _rand_keys(1500, rng) + [b"", b"x", b"abcdef"]
    a = CPUBlockedBloomFilter(config, use_native=True)
    b = CPUBlockedBloomFilter(config, use_native=False)
    a.insert_batch(keys)
    b.insert_batch(keys)
    np.testing.assert_array_equal(a.words, b.words)
    probes = keys[:200] + _rand_keys(300, rng)
    np.testing.assert_array_equal(a.include_batch(probes), b.include_batch(probes))


def test_checkpoint_roundtrip_blocked(tmp_path):
    from tpubloom import checkpoint as ckpt

    config = FilterConfig(
        m=1 << 16, k=7, block_bits=512, key_name="blk", key_len=16
    )
    rng = np.random.default_rng(6)
    keys = _rand_keys(1500, rng)
    f = BlockedBloomFilter(config)
    f.insert_batch(keys)
    sink = ckpt.FileSink(str(tmp_path))
    ckpt.save(f, sink)
    g = ckpt.restore(config, sink)
    assert isinstance(g, BlockedBloomFilter)
    assert g.include_batch(keys).all()
    np.testing.assert_array_equal(f.words_logical, g.words_logical)
    # restoring under the flat spec must be refused (different position spec)
    import pytest as _pytest

    with _pytest.raises(ValueError, match="block_bits"):
        ckpt.restore(config.replace(block_bits=0), sink)


def test_server_creates_blocked_filter():
    from tpubloom.server.service import BloomService

    svc = BloomService()
    resp = svc.CreateFilter(
        {
            "name": "blk",
            "config": FilterConfig(m=1 << 16, k=7, block_bits=512).to_dict(),
        }
    )
    assert resp["ok"]
    svc.InsertBatch({"name": "blk", "keys": [b"alpha", b"beta"]})
    hits = svc.QueryBatch({"name": "blk", "keys": [b"alpha", b"gamma"]})
    assert hits["ok"]
    bits = np.unpackbits(np.frombuffer(hits["hits"], np.uint8))[: hits["n"]]
    assert bits[0] == 1
    st = svc.Stats({"name": "blk"})["stats"]
    assert st["block_bits"] == 512


def test_identity_mismatch_treats_missing_block_bits_as_flat():
    from tpubloom.config import identity_mismatch

    a = FilterConfig(m=1 << 16, k=7)
    legacy = {"m": 1 << 16, "k": 7, "seed": a.seed, "counting": False, "shards": 1}
    assert identity_mismatch(a, legacy) is None
    b = FilterConfig(m=1 << 16, k=7, block_bits=512)
    assert identity_mismatch(b, legacy) == "block_bits"


def test_replicate_masks_128_matches_lane_concat():
    """The matmul lane replication (byte-quarter matmuls against a
    constant 0/1 weight) must be bit-exact with the concat reference it
    replaced — the concat is a ~47 ms relayout on TPU
    (benchmarks/out/query_fix_r5.json), but on CPU it is the obvious
    ground truth."""
    import jax.numpy as jnp

    from tpubloom.ops.blocked import _replicate_masks_128

    rng = np.random.default_rng(42)
    for w in (8, 16, 32):
        J = 128 // w
        masks = rng.integers(0, 1 << 32, size=(257, w), dtype=np.uint64)
        masks = masks.astype(np.uint32)
        got = np.asarray(_replicate_masks_128(jnp.asarray(masks)))
        expected = np.concatenate([masks] * J, axis=1)
        np.testing.assert_array_equal(got, expected)
