"""Failure-detection / elastic-recovery tests (SURVEY.md §5 failure row:
"gRPC health check + reconnect/backoff in the ... shim; server restart ->
restore newest checkpoint; fault-injection test: kill server mid-stream").

The "crash" is an abrupt grpc-server stop with the service state thrown
away (what a SIGKILL does to the process's memory); the "restart" is a
brand-new BloomService on the same port backed by the same checkpoint
directory. The client must ride through both failure modes on its own:
UNAVAILABLE while the port is dead (backoff+retry) and NOT_FOUND once the
new server is up (replay create -> checkpoint restore -> retry).
"""

import threading
import time

import numpy as np
import pytest

from tpubloom import checkpoint as ckpt
from tpubloom.server.client import BloomClient
from tpubloom.server.protocol import BloomServiceError
from tpubloom.server.service import BloomService, build_server


def _rand_keys(n, rng):
    return [rng.bytes(16) for _ in range(n)]


def _start(tmp_path, port=0):
    service = BloomService(sink_factory=lambda config: ckpt.FileSink(str(tmp_path)))
    srv, bound = build_server(service, f"127.0.0.1:{port}")
    srv.start()
    return srv, service, bound


def test_client_survives_server_crash_and_restart(tmp_path):
    srv, service, port = _start(tmp_path)
    client = BloomClient(f"127.0.0.1:{port}", max_retries=8, backoff_base=0.1)
    client.wait_ready()
    restarted = []  # keep the new server referenced or grpc GCs it
    try:
        client.create_filter("crashy", capacity=50_000, error_rate=0.01)
        rng = np.random.default_rng(7)
        keys = _rand_keys(2000, rng)
        client.insert_batch("crashy", keys)
        client.checkpoint("crashy", wait=True)  # durability point

        # crash: port goes dead, in-memory state (incl. the filter) is lost
        srv.stop(grace=None)
        del service

        def restart():
            time.sleep(0.6)
            restarted.append(_start(tmp_path, port))

        t = threading.Thread(target=restart, daemon=True)
        t.start()

        # issued while the port is DOWN: must backoff through UNAVAILABLE,
        # then heal NOT_FOUND by replaying the creation (-> restore)
        hits = client.include_batch("crashy", keys)
        t.join()
        assert hits.all(), "restored filter lost checkpointed keys"
        assert client.include_batch("crashy", _rand_keys(2000, rng)).mean() < 0.01
        # and writes keep working against the restored filter
        client.insert_batch("crashy", [b"post-crash"])
        assert client.include("crashy", b"post-crash")
    finally:
        client.close()
        for s, _, _ in restarted:
            s.stop(grace=None)


def test_post_checkpoint_tail_is_lost_not_corrupted(tmp_path):
    """Inserts after the last checkpoint are bounded tail loss — the
    restored filter answers consistently for everything checkpointed."""
    srv, service, port = _start(tmp_path)
    client = BloomClient(f"127.0.0.1:{port}", max_retries=8, backoff_base=0.1)
    client.wait_ready()
    srv2 = None
    try:
        client.create_filter("tail", capacity=50_000, error_rate=0.01)
        rng = np.random.default_rng(8)
        durable = _rand_keys(1000, rng)
        client.insert_batch("tail", durable)
        client.checkpoint("tail", wait=True)
        tail = _rand_keys(1000, rng)
        client.insert_batch("tail", tail)  # never checkpointed

        srv.stop(grace=None)
        del service
        srv2 = _start(tmp_path, port)  # keep referenced

        assert client.include_batch("tail", durable).all()
        # tail keys may be gone (crash-consistent semantics) — but answers
        # must be bloom-consistent: re-inserting them must make them present
        client.insert_batch("tail", tail)
        assert client.include_batch("tail", tail).all()
    finally:
        client.close()
        if srv2 is not None:
            srv2[0].stop(grace=None)


def test_not_found_without_remembered_creation_still_raises(tmp_path):
    srv, _, port = _start(tmp_path)
    client = BloomClient(f"127.0.0.1:{port}", max_retries=1)
    client.wait_ready()
    try:
        with pytest.raises(BloomServiceError, match="NOT_FOUND"):
            client.insert_batch("never-created", [b"x"])
    finally:
        client.close()
        srv.stop(grace=None)


def test_unavailable_exhausts_retries(tmp_path):
    # nothing listens on this port; backoff must give up, not hang forever
    import grpc

    client = BloomClient("127.0.0.1:1", max_retries=2, backoff_base=0.05, timeout=2)
    try:
        t0 = time.monotonic()
        with pytest.raises(grpc.RpcError):
            client.health()
        assert time.monotonic() - t0 < 30
    finally:
        client.close()
