"""Checkpoint/restore tests (SURVEY.md §4.2 item 5; BASELINE config 3's
periodic-checkpoint requirement; §5 failure-detection: bounded tail loss)."""

import time

import numpy as np
import pytest

from tests.fake_redis import FakeRedis
from tpubloom import BloomFilter, CountingBloomFilter, CPUBloomFilter, FilterConfig
from tpubloom import checkpoint as ckpt
from tpubloom.parallel.sharded import ShardedBloomFilter
from tpubloom.server.resp import RespClient, RespError


def _rand_keys(n, rng, nbytes=16):
    return [rng.bytes(nbytes) for _ in range(n)]


@pytest.fixture
def cfg(tmp_path):
    return FilterConfig(m=1 << 20, k=5, key_len=16, key_name="ckpt-test")


def test_file_roundtrip(cfg, tmp_path):
    rng = np.random.default_rng(0)
    keys = _rand_keys(2000, rng)
    f = BloomFilter(cfg)
    f.insert_batch(keys)
    sink = ckpt.FileSink(str(tmp_path))
    seq = ckpt.save(f, sink)
    g = ckpt.restore(cfg, sink)
    assert g is not None and g._restored_seq == seq
    np.testing.assert_array_equal(np.asarray(f.words), np.asarray(g.words))
    assert g.include_batch(keys).all()


def test_restore_picks_newest(cfg, tmp_path):
    sink = ckpt.FileSink(str(tmp_path))
    f = BloomFilter(cfg)
    f.insert(b"first")
    ckpt.save(f, sink, seq=100000000000)
    f.insert(b"second")
    ckpt.save(f, sink, seq=100000000001)
    g = ckpt.restore(cfg, sink)
    assert g._restored_seq == 100000000001
    assert g.include(b"first") and g.include(b"second")


def test_restore_preserves_usage_counters(cfg, tmp_path):
    sink = ckpt.FileSink(str(tmp_path))
    f = BloomFilter(cfg)
    f.insert_batch([b"a", b"b", b"c"])
    f.include_batch([b"a"])
    ckpt.save(f, sink)
    g = ckpt.restore(cfg, sink)
    assert g.n_inserted == 3 and g.n_queried == 1


def test_restore_empty_sink(cfg, tmp_path):
    assert ckpt.restore(cfg, ckpt.FileSink(str(tmp_path))) is None


def test_config_mismatch_rejected(cfg, tmp_path):
    sink = ckpt.FileSink(str(tmp_path))
    f = BloomFilter(cfg)
    f.insert(b"x")
    ckpt.save(f, sink)
    with pytest.raises(ValueError, match="mismatch on k"):
        ckpt.restore(cfg.replace(k=7), sink)


def test_shards_mismatch_rejected(tmp_path):
    cfg = FilterConfig(m=1 << 20, k=4, shards=8, key_name="sh")
    f = ShardedBloomFilter(cfg)
    f.insert(b"x")
    sink = ckpt.FileSink(str(tmp_path))
    ckpt.save(f, sink)
    with pytest.raises(ValueError, match="mismatch on shards"):
        ckpt.restore(cfg.replace(shards=4), sink)


def test_redis_sink_multi_generation_get(cfg):
    """RedisSink retains generations (ISSUE 3 satellite — it used to
    keep only the newest blob): an older retained seq restores, a seq
    never written restores as None."""
    srv = FakeRedis()
    try:
        sink = ckpt.RedisSink("127.0.0.1", srv.port)
        f = BloomFilter(cfg)
        f.insert(b"x")
        seq_a = ckpt.save(f, sink)
        f.insert(b"y")
        seq_b = ckpt.save(f, sink, seq=seq_a + 1)
        assert sink.list_seqs(cfg.key_name) == [seq_b, seq_a]
        assert ckpt.restore(cfg, sink, seq=seq_b) is not None
        old = ckpt.restore(cfg, sink, seq=seq_a)  # older generation: kept
        assert old is not None and old._restored_seq == seq_a
        assert ckpt.restore(cfg, sink, seq=seq_a - 1) is None  # never written
        sink.close()
    finally:
        srv.close()


def test_counting_roundtrip(tmp_path):
    cfg = FilterConfig(m=1 << 16, k=4, counting=True, key_name="cnt")
    f = CountingBloomFilter(cfg)
    f.insert_batch([b"a", b"b", b"a"])
    sink = ckpt.FileSink(str(tmp_path))
    ckpt.save(f, sink)
    g = ckpt.restore(cfg, sink)
    np.testing.assert_array_equal(np.asarray(f.words), np.asarray(g.words))
    g.delete(b"a")
    assert g.include(b"a")  # still one count left
    g.delete(b"a")
    assert not g.include(b"a")


def test_sharded_roundtrip(tmp_path):
    cfg = FilterConfig(m=1 << 20, k=4, shards=8, key_name="shard-ckpt")
    rng = np.random.default_rng(1)
    keys = _rand_keys(1000, rng)
    f = ShardedBloomFilter(cfg)
    f.insert_batch(keys)
    sink = ckpt.FileSink(str(tmp_path))
    ckpt.save(f, sink)
    g = ckpt.restore(cfg, sink)
    assert isinstance(g, ShardedBloomFilter)
    assert g.include_batch(keys).all()


def test_file_prune(cfg, tmp_path):
    sink = ckpt.FileSink(str(tmp_path))
    f = BloomFilter(cfg)
    for s in range(100000000000, 100000000005):
        ckpt.save(f, sink, seq=s)
    sink.prune(cfg.key_name, keep=2)
    assert sink.latest_seq(cfg.key_name) == 100000000004
    assert sink.get(cfg.key_name, 100000000000) is None


# -- RESP client + Redis sink -----------------------------------------------


def test_resp_client_basics():
    srv = FakeRedis()
    try:
        with RespClient("127.0.0.1", srv.port) as c:
            assert c.ping()
            assert c.set("k", b"\x00\x01binary\xff")
            assert c.get("k") == b"\x00\x01binary\xff"
            assert c.get("absent") is None
            assert c.exists("k") == 1
            assert c.delete("k") == 1
            assert c.exists("k") == 0
            with pytest.raises(RespError):
                c.command("BOGUS")
    finally:
        srv.close()


def test_redis_sink_roundtrip_and_ruby_driver_compat(cfg):
    """The Redis sink leaves the raw bitmap under key_name, so a reference
    :ruby driver doing GETBIT against Redis sees exactly our bits."""
    srv = FakeRedis()
    try:
        rng = np.random.default_rng(2)
        keys = _rand_keys(500, rng)
        f = BloomFilter(cfg)
        f.insert_batch(keys)
        sink = ckpt.RedisSink("127.0.0.1", srv.port)
        ckpt.save(f, sink)
        g = ckpt.restore(cfg, sink)
        np.testing.assert_array_equal(np.asarray(f.words), np.asarray(g.words))

        # GETBIT emulation of the reference's per-position query loop:
        oracle = CPUBloomFilter(cfg, use_native=False)
        from tpubloom.cpu_ref import positions_np
        from tpubloom.utils.packing import pack_keys

        ks, ls = pack_keys(keys[:50], cfg.key_len)
        pos = positions_np(ks, ls, m=cfg.m, k=cfg.k, seed=cfg.seed)
        with RespClient("127.0.0.1", srv.port) as c:
            for row in pos:
                bits = [c.command("GETBIT", cfg.key_name, int(p)) for p in row]
                assert all(b == 1 for b in bits), "ruby-driver view must see the key"
        sink.close()
    finally:
        srv.close()


def test_setbit_written_filter_readable_by_jax(cfg):
    """Reverse direction: a filter built by reference-style SETBIT commands
    restores into the device filter with identical membership."""
    srv = FakeRedis()
    try:
        oracle = CPUBloomFilter(cfg, use_native=False)
        keys = [b"ruby-key-%d" % i for i in range(200)]
        oracle.insert_batch(keys)
        from tpubloom.cpu_ref import positions_np
        from tpubloom.utils.packing import pack_keys

        ks, ls = pack_keys(keys, cfg.key_len)
        pos = positions_np(ks, ls, m=cfg.m, k=cfg.k, seed=cfg.seed)
        with RespClient("127.0.0.1", srv.port) as c:
            for p in sorted(set(int(x) for x in pos.ravel())):
                c.command("SETBIT", cfg.key_name, p, 1)
            bitmap = c.get(cfg.key_name)
        f = BloomFilter.from_redis_bitmap(cfg, bitmap)
        assert f.include_batch(keys).all()
        np.testing.assert_array_equal(np.asarray(f.words)[: len(oracle.words)][
            : oracle.words.size], oracle.words)
    finally:
        srv.close()


# -- async checkpointer ------------------------------------------------------


def test_async_checkpointer(cfg, tmp_path):
    sink = ckpt.FileSink(str(tmp_path))
    f = BloomFilter(cfg)
    cp = ckpt.AsyncCheckpointer(f, sink, every_n_inserts=1000)
    rng = np.random.default_rng(3)
    for _ in range(5):
        f.insert_batch(_rand_keys(500, rng))
        cp.notify_inserts(500)
    cp.close(final_checkpoint=True)
    assert cp.checkpoints_written >= 2
    assert cp.last_error is None
    g = ckpt.restore(cfg, sink)
    assert g is not None
    # final checkpoint captured everything
    np.testing.assert_array_equal(np.asarray(f.words), np.asarray(g.words))


def test_async_checkpointer_skips_when_busy(cfg, tmp_path):
    class SlowSink(ckpt.FileSink):
        def put(self, *a):
            time.sleep(0.2)
            super().put(*a)

    sink = SlowSink(str(tmp_path))
    f = BloomFilter(cfg)
    cp = ckpt.AsyncCheckpointer(f, sink)
    assert cp.trigger()
    assert not cp.trigger(), "second trigger while busy must be refused"
    cp.flush()
    cp.close(final_checkpoint=False)
    assert cp.checkpoints_written == 1


# -- scalable (layer-stack) checkpoints --------------------------------------


def _scalable_with_growth(tmp_path, *, block_bits=0):
    """A scalable filter pushed across >= 1 growth boundary + its keys."""
    from tpubloom.scalable import ScalableBloomFilter

    base = FilterConfig(
        m=max(64, block_bits), k=1, key_len=16, key_name="scale-ckpt",
        block_bits=block_bits,
    )
    f = ScalableBloomFilter(300, 0.01, config=base)
    rng = np.random.default_rng(7)
    keys = _rand_keys(1000, rng)  # 300-capacity base layer -> >= 2 layers
    f.insert_batch(keys)
    assert f.n_layers >= 2, "test must cross a growth boundary"
    return f, base, keys


@pytest.mark.parametrize("block_bits", [0, 512])
def test_scalable_roundtrip_across_growth(tmp_path, block_bits):
    """VERDICT r1 task 2 'Done' criterion: insert across a growth boundary
    -> save -> restore -> identical membership AND identical layer stack."""
    from tpubloom.scalable import ScalableBloomFilter

    f, base, keys = _scalable_with_growth(tmp_path, block_bits=block_bits)
    sink = ckpt.FileSink(str(tmp_path))
    seq = ckpt.save(f, sink)
    g = ckpt.restore(base, sink)
    assert isinstance(g, ScalableBloomFilter)
    assert g._restored_seq == seq
    # identical layer stack: count, per-layer config, per-layer fill, words
    assert g.n_layers == f.n_layers
    for la, lb in zip(f.layers, g.layers):
        assert la.config == lb.config
        np.testing.assert_array_equal(np.asarray(la.words), np.asarray(lb.words))
    assert g._layer_counts == f._layer_counts
    assert g.n_inserted == f.n_inserted
    # identical membership
    assert g.include_batch(keys).all()
    rng = np.random.default_rng(8)
    probe = _rand_keys(2000, rng)
    np.testing.assert_array_equal(f.include_batch(probe), g.include_batch(probe))


def test_scalable_restore_rejects_policy_mismatch(tmp_path):
    f, base, _ = _scalable_with_growth(tmp_path)
    sink = ckpt.FileSink(str(tmp_path))
    ckpt.save(f, sink)
    with pytest.raises(ValueError, match="policy mismatch on capacity"):
        ckpt.restore(base, sink, scalable_expect={"capacity": 999})
    with pytest.raises(ValueError, match="policy mismatch on tightening"):
        ckpt.restore(base, sink, scalable_expect={"tightening": 0.25})


def test_scalable_restore_rejects_base_identity_mismatch(tmp_path):
    f, base, _ = _scalable_with_growth(tmp_path)
    sink = ckpt.FileSink(str(tmp_path))
    ckpt.save(f, sink)
    with pytest.raises(ValueError, match="mismatch on base seed"):
        ckpt.restore(base.replace(seed=123), sink)


def test_scalable_async_checkpointer(tmp_path):
    """The async path snapshots the whole layer stack consistently and the
    final checkpoint captures post-growth layers."""
    from tpubloom.scalable import ScalableBloomFilter

    base = FilterConfig(m=64, k=1, key_len=16, key_name="scale-async")
    f = ScalableBloomFilter(300, 0.01, config=base)
    sink = ckpt.FileSink(str(tmp_path))
    cp = ckpt.AsyncCheckpointer(f, sink, every_n_inserts=400)
    rng = np.random.default_rng(9)
    keys = _rand_keys(1200, rng)
    for i in range(0, 1200, 200):
        f.insert_batch(keys[i : i + 200])
        cp.notify_inserts(200)
    assert cp.close(final_checkpoint=True)
    assert cp.checkpoints_written >= 2 and cp.last_error is None
    g = ckpt.restore(base, sink)
    assert g.n_layers == f.n_layers and g.n_inserted == 1200
    assert g.include_batch(keys).all()


def _strip_block_hash(tmp_path, key_name):
    """Rewrite the newest checkpoint for ``key_name`` as a pre-block_hash
    writer would have produced it: no block_hash key anywhere in the
    header (base config or per-layer configs)."""
    import json
    import pathlib

    path = max(pathlib.Path(tmp_path).glob(f"{key_name}.*.ckpt"))
    blob = path.read_bytes()
    header, payload = ckpt._deserialize(blob)
    header["config"].pop("block_hash", None)
    for d in header.get("scalable", {}).get("layer_configs", []):
        d.pop("block_hash", None)
    hdr = json.dumps(header).encode()
    path.write_bytes(ckpt.MAGIC + len(hdr).to_bytes(8, "little") + hdr + payload)


@pytest.mark.parametrize("block_bits", [0, 512])
def test_scalable_restore_pre_block_hash_header(tmp_path, block_bits):
    """Checkpoints written before block_hash existed must keep restoring:
    absent field means the layer was built with the AP in-block spec
    (blocked) / "" (flat), and _load_layers must normalize the stored
    dicts through FilterConfig.from_dict before comparing (ADVICE r2
    high finding — strict dict equality rejected every legacy stack)."""
    from tpubloom.scalable import ScalableBloomFilter

    base = FilterConfig(
        m=max(64, block_bits), k=1, key_len=16, key_name="scale-legacy",
        block_bits=block_bits, block_hash="ap" if block_bits else "auto",
    )
    f = ScalableBloomFilter(300, 0.01, config=base)
    rng = np.random.default_rng(11)
    keys = _rand_keys(1000, rng)
    f.insert_batch(keys)
    assert f.n_layers >= 2
    sink = ckpt.FileSink(str(tmp_path))
    ckpt.save(f, sink)
    _strip_block_hash(tmp_path, "scale-legacy")
    g = ckpt.restore(base, sink)
    assert isinstance(g, ScalableBloomFilter)
    assert g.n_layers == f.n_layers
    assert g.include_batch(keys).all()
    probe = _rand_keys(2000, np.random.default_rng(12))
    np.testing.assert_array_equal(f.include_batch(probe), g.include_batch(probe))


def test_scalable_legacy_header_rejects_chunk_base(tmp_path):
    """The same legacy blocked checkpoint must NOT restore into a base
    config using the chunk spec — and the refusal must come from the
    early base-identity check (block_hash is in IDENTITY_FIELDS_SCALABLE,
    ADVICE r2 medium), not a late layer-dict mismatch."""
    from tpubloom.scalable import ScalableBloomFilter

    base_ap = FilterConfig(
        m=512, k=1, key_len=16, key_name="scale-legacy2",
        block_bits=512, block_hash="ap",
    )
    f = ScalableBloomFilter(300, 0.01, config=base_ap)
    f.insert_batch(_rand_keys(500, np.random.default_rng(13)))
    sink = ckpt.FileSink(str(tmp_path))
    ckpt.save(f, sink)
    _strip_block_hash(tmp_path, "scale-legacy2")
    base_chunk = base_ap.replace(block_hash="chunk")
    with pytest.raises(ValueError, match="mismatch on base block_hash"):
        ckpt.restore(base_chunk, sink)
