"""ISSUE 15: distributed request tracing + flight recorder.

Covers the tentpole end to end:

* the **span ring**: record/lookup by trace id, flush-span LINK
  reverse-indexing, bounded eviction, deterministic per-rid sampling;
* the **request tree**: client hop → ``rpc.<Method>`` root → phase
  children + ``barrier.wait``, parented across the wire via the
  ``trace`` request field; slowlog-worthy requests captured even
  unsampled; the fully-off path records nothing and ships no wire
  field;
* the **coalescer**: one ``ingest.flush`` span per flush, LINKING every
  parked request's root span, kernel phases + the barrier as flush
  children — N-to-1 batching stays explainable;
* the **acceptance e2e**: a real subprocess primary (cluster mode +
  coalescer + ``--trace-sample 1.0``) with a real replica — one quorum
  write's assembled tree connects client hop → park/flush → kernel
  phases → commit barrier → replica apply, as ONE component; the
  primary's SIGTERM then produces a readable flight-recorder dump;
* the **flight recorder**: bounded ring, JSON dumps, and the Health
  SERVING→DEGRADED flip triggering a dump.

The module runs armed under the lock tracker + lock-order manifest like
the other chaos modules — the new ``obs.trace`` ring lock must stay a
leaf (every record/lookup site holds no other lock).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import pytest

from tpubloom import checkpoint as ckpt
from tpubloom import faults
from tpubloom.obs import flight, trace
from tpubloom.server import protocol
from tpubloom.server.client import BloomClient
from tpubloom.server.ingest import CoalesceConfig
from tpubloom.server.service import BloomService, build_server

pytestmark = pytest.mark.usefixtures("lock_check_armed", "lock_order_manifest")


@pytest.fixture(autouse=True)
def _trace_isolation():
    trace.reset_for_tests()
    flight.reset_for_tests()
    faults.reset()
    yield
    trace.reset_for_tests()
    flight.reset_for_tests()
    faults.reset()


class _Server:
    def __init__(self, service):
        self.service = service
        self.server, self.port = build_server(service, "127.0.0.1:0")
        self.server.start()
        self.addr = f"127.0.0.1:{self.port}"

    def client(self, **kw) -> BloomClient:
        return BloomClient(self.addr, **kw)

    def stop(self):
        self.server.stop(grace=None)


def _names(spans):
    return sorted(s["name"] for s in spans)


# -- the ring ----------------------------------------------------------------


def test_ring_record_lookup_links_and_eviction():
    trace.configure(sample=1.0, capacity=10)
    trace.record_span("rpc.X", rid="r1", start=1.0, duration_s=0.5)
    root = trace.record_span("rpc.Y", rid="r2", start=2.0, duration_s=0.1)
    trace.record_span(
        "phase.kernel", rid="r2", parent=root, start=2.0, duration_s=0.05
    )
    # a flush-style span in its OWN trace that links r1: r1's lookup
    # must pull the whole linking trace along
    trace.record_span(
        "ingest.flush", rid="fl-1", start=3.0, duration_s=0.2,
        links=[{"rid": "r1", "span": "aaaa"}],
    )
    trace.record_span(
        "barrier.wait", rid="fl-1", start=3.1, duration_s=0.1
    )
    got = trace.get_trace("r1")
    assert _names(got) == ["barrier.wait", "ingest.flush", "rpc.X"]
    assert _names(trace.get_trace("r2")) == ["phase.kernel", "rpc.Y"]
    # eviction: oldest traces fall out once the span budget is hit, and
    # their link index entries go with them
    for i in range(30):
        trace.record_span(f"rpc.Z{i}", rid=f"bulk-{i}", start=float(i),
                          duration_s=0.0)
    assert trace.buffer_stats()["spans"] <= 10
    assert trace.get_trace("r1") == []
    # a SINGLE trace id over the whole budget is still bounded (a
    # caller reusing one rid across many forced calls must not leak)
    trace.configure(sample=1.0, capacity=10)
    for i in range(40):
        trace.record_span(f"rpc.S{i}", rid="one-rid", start=float(i),
                          duration_s=0.0)
    assert trace.buffer_stats()["spans"] <= 10
    kept = trace.get_trace("one-rid")
    assert len(kept) <= 10 and kept[-1]["name"] == "rpc.S39"


def test_deterministic_sampling_and_off_switch():
    trace.configure(sample=1.0)
    assert trace.hit("anything")
    trace.configure(sample=0.0)
    assert not trace.hit("anything")
    # the decision is a pure function of (rid, rate) — every node that
    # sees the same rid agrees with no coordination
    trace.configure(sample=0.5)
    decisions = {rid: trace.hit(rid) for rid in ("a", "b", "c", "d", "e")}
    for rid, d in decisions.items():
        assert trace.hit(rid) == d
        assert trace.hit(rid, 0.5) == d
    # fully off: nothing records, lookups answer empty
    trace.configure(None)
    assert not trace.enabled()
    trace.record_span("rpc.X", rid="off", start=0.0, duration_s=0.0)
    assert trace.get_trace("off") == []


# -- request trees (in-process server) ----------------------------------------


def test_request_tree_client_hop_to_phases(tmp_path):
    srv = _Server(BloomService(
        sink_factory=lambda c: ckpt.FileSink(str(tmp_path)),
        trace_sample=1.0,
    ))
    try:
        c = srv.client(trace_sample=1.0)
        c.wait_ready()
        c.create_filter("t", capacity=10_000, error_rate=0.01)
        c.insert_batch("t", [b"k%d" % i for i in range(16)])
        rid = c.last_rid
        spans = c.trace_get(rid)
        names = _names(spans)
        assert "rpc.InsertBatch" in names and "client.hop" in names
        assert "phase.decode" in names and "phase.kernel" in names
        assert "barrier.wait" in names  # 0-quorum: present, ~0s
        root = next(s for s in spans if s["name"] == "rpc.InsertBatch")
        hop = next(s for s in spans if s["name"] == "client.hop")
        # the wire trace field parented the server root under the hop
        assert root["parent"] == hop["span"]
        assert root["attrs"]["filter"] == "t"
        assert root["attrs"]["code"] == "OK"
        assert root["attrs"]["batch"] == 16
        # every phase child hangs off the root — one connected tree
        tree = trace.assemble(spans)
        assert len(tree["components"]) == 1
        assert [hop["span"]] == tree["roots"]
    finally:
        srv.stop()


def test_slowlog_worthy_requests_capture_unsampled(tmp_path):
    # ring armed at rate 0.0: nothing samples, but the slowlog keeps
    # everything (threshold 0, empty heap) — so the request still lands
    srv = _Server(BloomService(
        sink_factory=lambda c: ckpt.FileSink(str(tmp_path)),
        trace_sample=0.0,
    ))
    try:
        c = srv.client()  # client tracing off: no wire field
        c.wait_ready()
        c.create_filter("t", capacity=10_000, error_rate=0.01)
        c.insert_batch("t", [b"a", b"b"])
        spans = c.trace_get(c.last_rid)
        assert "rpc.InsertBatch" in _names(spans)
        root = next(s for s in spans if s["name"] == "rpc.InsertBatch")
        assert root["parent"] is None  # no client hop: nothing propagated
    finally:
        srv.stop()


def test_tracing_off_is_wire_silent_and_records_nothing(tmp_path):
    srv = _Server(BloomService(
        sink_factory=lambda c: ckpt.FileSink(str(tmp_path)),
    ))
    try:
        c = srv.client()
        seen = []
        orig = c._call_once

        def spy(method, req, *a, **kw):
            seen.append(dict(req))
            return orig(method, req, *a, **kw)

        c._call_once = spy
        c.wait_ready()
        c.create_filter("t", capacity=10_000, error_rate=0.01)
        c.insert_batch("t", [b"a", b"b"])
        assert all("trace" not in r for r in seen), (
            "tracing off must add no wire fields"
        )
        resp = c._rpc("TraceGet", {"trace_rid": c.last_rid})
        assert resp["enabled"] is False and resp["spans"] == []
    finally:
        srv.stop()


def test_coalesced_flush_span_links_every_parked_request(tmp_path):
    srv = _Server(BloomService(
        sink_factory=lambda c: ckpt.FileSink(str(tmp_path)),
        coalesce=CoalesceConfig(max_keys=4096, max_wait_us=20_000),
        trace_sample=1.0,
    ))
    try:
        admin = srv.client()
        admin.wait_ready()
        admin.create_filter("t", capacity=50_000, error_rate=0.01)
        rids = []

        def work(i):
            cc = srv.client(trace_sample=1.0)
            cc.insert_batch(
                "t", [b"k-%d-%d" % (i, j) for j in range(64)]
            )
            rids.append(cc.last_rid)

        threads = [
            threading.Thread(target=work, args=(i,)) for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            spans = admin.trace_get(rids[0])
            if any(s["name"] == "ingest.flush" for s in spans):
                break
            time.sleep(0.05)
        names = _names(spans)
        assert "ingest.flush" in names, names
        assert "ingest.park" in names
        assert "phase.kernel" in names  # the flush's kernel phase child
        flush = next(s for s in spans if s["name"] == "ingest.flush")
        assert flush["rid"] != rids[0]  # its own trace id
        linked = {link["rid"] for link in flush["links"]}
        assert rids[0] in linked
        assert flush["attrs"]["requests"] == len(flush["links"])
        # the lookup stitched request + flush traces into ONE component
        tree = trace.assemble(spans)
        assert len(tree["components"]) == 1
        # a flush-mate's lookup finds the SAME flush span
        if len(linked) > 1:
            other = next(r for r in linked if r != rids[0])
            other_spans = admin.trace_get(other)
            assert any(
                s["name"] == "ingest.flush" and s["span"] == flush["span"]
                for s in other_spans
            )
    finally:
        srv.stop()


def test_http_trace_and_flight_views(tmp_path):
    import urllib.request

    from tpubloom.obs.httpd import start_metrics_server

    srv = _Server(BloomService(
        sink_factory=lambda c: ckpt.FileSink(str(tmp_path)),
        trace_sample=1.0,
    ))
    metrics = start_metrics_server(srv.service, port=0, host="127.0.0.1")
    try:
        c = srv.client(trace_sample=1.0)
        c.wait_ready()
        c.create_filter("t", capacity=10_000, error_rate=0.01)
        c.insert_batch("t", [b"a", b"b"])
        rid = c.last_rid
        with urllib.request.urlopen(
            f"http://127.0.0.1:{metrics.port}/trace?rid={rid}", timeout=10
        ) as resp:
            body = json.loads(resp.read().decode())
        assert body["rid"] == rid and body["enabled"] is True
        assert "rpc.InsertBatch" in {s["name"] for s in body["spans"]}
        flight.note("shed", method="probe")
        with urllib.request.urlopen(
            f"http://127.0.0.1:{metrics.port}/flight", timeout=10
        ) as resp:
            body = json.loads(resp.read().decode())
        assert any(e["kind"] == "shed" for e in body["events"])
    finally:
        metrics.close()
        srv.stop()


# -- flight recorder ----------------------------------------------------------


def test_flight_ring_bounded_snapshot_and_dump(tmp_path):
    flight.configure(dump_dir=str(tmp_path), capacity=8)
    for i in range(20):
        flight.note("shed", i=i)
    events = flight.snapshot()
    assert len(events) == 8  # bounded, newest kept
    assert events[-1]["attrs"]["i"] == 19
    path = flight.dump("ondemand", extra={"why": "test"})
    assert path is not None and os.path.isfile(path)
    payload = json.loads(open(path).read())
    assert payload["reason"] == "ondemand"
    assert payload["extra"] == {"why": "test"}
    assert len(payload["events"]) == 8
    # no dir configured -> dump declines instead of raising
    flight.reset_for_tests()
    env_dir = os.environ.pop(flight.DUMP_DIR_ENV, None)
    try:
        assert flight.dump("nowhere") is None
    finally:
        if env_dir is not None:
            os.environ[flight.DUMP_DIR_ENV] = env_dir


def test_health_degraded_flip_dumps_flight_recorder(tmp_path):
    flight.configure(dump_dir=str(tmp_path / "dumps"))
    srv = _Server(BloomService(
        sink_factory=lambda c: ckpt.FileSink(str(tmp_path / "ckpt")),
    ))
    try:
        c = srv.client()
        c.wait_ready()
        c.create_filter("t", capacity=10_000, error_rate=0.01)
        # force a checkpoint-write error -> Health DEGRADED
        faults.arm("ckpt.write", "always")
        c.insert_batch("t", [b"x"])
        try:
            c.checkpoint("t", wait=True)
        except protocol.BloomServiceError:
            pass
        h = c.health()
        assert h["status"] == "DEGRADED", h
        dumps = list((tmp_path / "dumps").glob("flight-*-degraded-*.json"))
        assert len(dumps) == 1, "the SERVING->DEGRADED flip must dump once"
        payload = json.loads(dumps[0].read_text())
        flip = [e for e in payload["events"] if e["kind"] == "health"]
        assert flip and flip[-1]["attrs"]["status"] == "DEGRADED"
        assert flip[-1]["attrs"]["reasons"]
        # a second DEGRADED Health answer is NOT a flip: no second dump
        c.health()
        assert len(
            list((tmp_path / "dumps").glob("flight-*-degraded-*.json"))
        ) == 1
    finally:
        faults.reset()
        srv.stop()


# -- the acceptance e2e: subprocess primary + replica + cluster hop ----------


_SERVER_CHILD = """\
import sys
import jax
jax.config.update("jax_platforms", "cpu")
from tpubloom.server.service import main
main(sys.argv[1:])
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn(tmp_path, script_name, args, flight_dir):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
        flight.DUMP_DIR_ENV: str(flight_dir),
    }
    script = tmp_path / script_name
    script.write_text(_SERVER_CHILD)
    return subprocess.Popen(
        [sys.executable, str(script)] + [str(a) for a in args],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
    )


def test_e2e_quorum_write_trace_and_sigterm_dump(tmp_path):
    """THE acceptance run: real subprocess primary (cluster mode +
    coalescer + oplog + --trace-sample 1.0) and replica; one quorum
    write routed through the ClusterClient assembles into a SINGLE
    connected span tree covering client hop → coalescer park/flush →
    kernel phases → commit barrier → replica apply; SIGTERMing the
    primary then writes a readable flight-recorder dump."""
    flight_dir = tmp_path / "flight"
    flight_dir.mkdir()
    pport, rport = _free_port(), _free_port()
    primary = _spawn(
        tmp_path, "primary.py",
        [pport, tmp_path / "p-ckpt",
         "--repl-log-dir", tmp_path / "p-oplog",
         "--cluster", "--coalesce-max-keys", 4096,
         "--coalesce-max-wait-us", 2000,
         "--min-replicas-to-write", 1,
         "--trace-sample", "1.0"],
        flight_dir,
    )
    replica = _spawn(
        tmp_path, "replica.py",
        [rport, tmp_path / "r-ckpt",
         "--replica-of", f"127.0.0.1:{pport}",
         "--trace-sample", "1.0"],
        flight_dir,
    )
    from tpubloom.cluster.client import ClusterClient

    try:
        paddr = f"127.0.0.1:{pport}"
        raddr = f"127.0.0.1:{rport}"
        admin = BloomClient(paddr, timeout=30.0)
        admin.wait_ready(timeout=120)
        admin.cluster_set_slot(assign=[[0, 16383, paddr]], epoch=1)
        # the quorum needs the replica CONNECTED before the write
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            h = admin.health()
            if len((h.get("replication") or {}).get("replicas") or ()) >= 1:
                break
            time.sleep(0.2)
        else:
            pytest.fail("replica never connected")

        trace.configure(sample=1.0)  # arm the TEST process's client ring
        cc = ClusterClient(
            startup_nodes=[paddr], replicas=[raddr],
            trace_sample=1.0, timeout=30.0,
        )
        cc.create_filter("e2e", capacity=50_000, error_rate=0.01)
        cc.insert_batch(
            "e2e", [b"q-%d" % i for i in range(64)], min_replicas=1
        )
        rid = cc.last_rid

        # assemble: local client spans + primary (request + flush
        # traces) + replica (apply spans under the flush trace id)
        assembled = None
        deadline = time.monotonic() + 20
        want = {"client.hop", "rpc.InsertBatch", "ingest.park",
                "ingest.flush", "phase.kernel", "barrier.wait",
                "repl.apply"}
        while time.monotonic() < deadline:
            assembled = cc.trace(rid)
            if want <= {s["name"] for s in assembled["spans"]}:
                break
            time.sleep(0.2)
        names = {s["name"] for s in assembled["spans"]}
        assert want <= names, f"missing {want - names}: {sorted(names)}"
        # ONE connected component: the rid's request tree, the flush
        # trace it links, and the replica's apply of the merged record
        assert len(assembled["components"]) == 1, assembled["components"]
        flush = next(
            s for s in assembled["spans"] if s["name"] == "ingest.flush"
        )
        assert rid in {link["rid"] for link in flush["links"]}
        apply_span = next(
            s for s in assembled["spans"] if s["name"] == "repl.apply"
        )
        # the apply is stamped with the flush's trace id (the merged
        # record's origin rid) and carries the op-log seq
        assert apply_span["rid"] == flush["rid"]
        assert apply_span["attrs"]["seq"] >= 1
        assert apply_span["attrs"]["filter"] == "e2e"
        barrier = next(
            s for s in assembled["spans"] if s["name"] == "barrier.wait"
        )
        assert barrier["parent"] == flush["span"]
        cc.close()

        # SIGTERM the primary: drain + flight dump land in the env dir
        primary.send_signal(signal.SIGTERM)
        assert primary.wait(timeout=60) == 0
        dumps = sorted(flight_dir.glob("flight-*-sigterm-*.json"))
        assert dumps, "SIGTERM must produce a flight-recorder dump"
        payload = json.loads(dumps[0].read_text())
        kinds = [e["kind"] for e in payload["events"]]
        assert "drain" in kinds
        assert payload["reason"] == "sigterm" and payload["pid"]
    finally:
        for proc in (primary, replica):
            if proc.poll() is None:
                proc.kill()
            out = proc.stdout.read() if proc.stdout else ""
            if proc.returncode not in (0, -9):
                print(out[-4000:])
