"""Sharded filter array tests on the fake 8-device CPU mesh
(BASELINE config 5 scaled down; SURVEY.md §4.2 item 3)."""

import jax
import numpy as np
import pytest

from tpubloom import CPUBloomFilter, FilterConfig
from tpubloom.cpu_ref import murmur3_32_np
from tpubloom.ops.hashing import SEED_XOR_ROUTE
from tpubloom.parallel.sharded import ShardedBloomFilter, make_mesh
from tpubloom.utils.packing import pack_keys


def _rand_keys(n, rng, nbytes=16):
    return [rng.bytes(nbytes) for _ in range(n)]


class ShardedCPURef:
    """Oracle: n independent per-shard reference filters + the routing hash
    (use_native pinned False — the ground truth must not be the C++ path).
    Handles all four layouts via the per-shard filter class; the blocked
    counting oracle is the single-device class (whose scatter fallback is
    itself oracle-pinned in test_counting_blocked)."""

    def __init__(self, config):
        self.config = config
        local = FilterConfig(
            m=config.m_per_shard, k=config.k, seed=config.seed,
            key_len=config.key_len, block_bits=config.block_bits,
            counting=config.counting,
        )
        if config.counting and config.block_bits:
            from tpubloom.filter import BlockedCountingBloomFilter

            make = lambda: BlockedCountingBloomFilter(
                local.replace(insert_path="scatter")
            )
        elif config.counting:
            make = lambda: CPUBloomFilter(local, use_native=False)
        elif config.block_bits:
            from tpubloom.cpu_ref import CPUBlockedBloomFilter

            make = lambda: CPUBlockedBloomFilter(local, use_native=False)
        else:
            make = lambda: CPUBloomFilter(local, use_native=False)
        self.filters = [make() for _ in range(config.shards)]

    def delete_batch(self, keys):
        routes = self._route(keys)
        for key, r in zip(keys, routes):
            self.filters[r].delete(key)

    def _route(self, keys):
        ks, ls = pack_keys(keys, self.config.key_len)
        return murmur3_32_np(ks, ls, self.config.seed ^ SEED_XOR_ROUTE) % np.uint32(
            self.config.shards
        )

    def insert_batch(self, keys):
        routes = self._route(keys)
        for key, r in zip(keys, routes):
            self.filters[r].insert(key)

    def include_batch(self, keys):
        routes = self._route(keys)
        return np.array(
            [self.filters[r].include(key) for key, r in zip(keys, routes)]
        )


@pytest.fixture(scope="module")
def cfg8():
    assert len(jax.devices()) == 8, "conftest must fake 8 CPU devices"
    return FilterConfig(m=1 << 20, k=5, key_len=16, shards=8)


def test_roundtrip(cfg8):
    rng = np.random.default_rng(0)
    keys = _rand_keys(2000, rng)
    f = ShardedBloomFilter(cfg8)
    f.insert_batch(keys)
    assert f.include_batch(keys).all()
    absent = _rand_keys(2000, rng)
    assert f.include_batch(absent).mean() < 0.01


def test_parity_vs_sharded_oracle(cfg8):
    """The mesh implementation and the compose-n-CPU-filters oracle agree
    bit-for-bit: same routing, same per-shard positions, same answers."""
    rng = np.random.default_rng(1)
    keys = _rand_keys(500, rng) + [b"", b"a", b"sharded-key"]
    f, o = ShardedBloomFilter(cfg8), ShardedCPURef(cfg8)
    f.insert_batch(keys)
    o.insert_batch(keys)
    dev_words = np.asarray(f.words)  # [shards, words_local]
    for s in range(cfg8.shards):
        np.testing.assert_array_equal(
            dev_words[s], o.filters[s].words, err_msg=f"shard {s} bits differ"
        )
    probe = keys + _rand_keys(500, rng)
    np.testing.assert_array_equal(f.include_batch(probe), o.include_batch(probe))


def test_all_shards_used(cfg8):
    rng = np.random.default_rng(2)
    f = ShardedBloomFilter(cfg8)
    f.insert_batch(_rand_keys(4000, rng))
    per_shard_bits = np.asarray(f.words).astype(np.uint64)
    pops = [
        int(np.unpackbits(per_shard_bits[s].astype(np.uint32).view(np.uint8)).sum())
        for s in range(cfg8.shards)
    ]
    assert all(p > 0 for p in pops), f"some shard never written: {pops}"
    # routing is roughly balanced
    assert max(pops) < 2 * min(pops)


def test_logical_shards_exceed_devices():
    # 16 shards on 8 devices: 2 shard-rows per device (config-5 shape).
    cfg = FilterConfig(m=1 << 20, k=4, key_len=16, shards=16)
    rng = np.random.default_rng(3)
    keys = _rand_keys(1000, rng)
    f = ShardedBloomFilter(cfg)
    f.insert_batch(keys)
    assert f.include_batch(keys).all()
    o = ShardedCPURef(cfg)
    o.insert_batch(keys)
    dev_words = np.asarray(f.words)
    for s in range(cfg.shards):
        np.testing.assert_array_equal(dev_words[s], o.filters[s].words)


def test_sharded_redis_bitmap_roundtrip(cfg8):
    rng = np.random.default_rng(4)
    keys = _rand_keys(1000, rng)
    f = ShardedBloomFilter(cfg8)
    f.insert_batch(keys)
    blob = f.to_redis_bitmap()
    assert len(blob) == cfg8.m // 8
    g = ShardedBloomFilter.from_redis_bitmap(cfg8, blob)
    assert g.include_batch(keys).all()
    np.testing.assert_array_equal(np.asarray(f.words), np.asarray(g.words))


def test_clear(cfg8):
    f = ShardedBloomFilter(cfg8)
    f.insert_batch([b"x", b"y"])
    f.clear()
    assert not f.include_batch([b"x", b"y"]).any()


def test_mesh_validation():
    with pytest.raises(ValueError):
        ShardedBloomFilter(FilterConfig(m=1 << 20, k=4, shards=1))
    with pytest.raises(ValueError):
        # 6 shards on 8 devices: not divisible either way
        ShardedBloomFilter(FilterConfig(m=3 * (1 << 18), k=4, shards=6))


def test_graft_entry_single():
    import __graft_entry__

    fn, args = __graft_entry__.entry()
    words, hits = jax.jit(fn)(*args)
    assert bool(np.asarray(hits).all()), "keys just inserted must be present"


def test_graft_entry_multichip():
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)


# -- blocked layout over the mesh (throughput layout x config 5) -------------


@pytest.fixture(scope="module")
def blk_cfg8():
    assert len(jax.devices()) == 8, "conftest must fake 8 CPU devices"
    return FilterConfig(m=1 << 20, k=5, key_len=16, shards=8, block_bits=512)


def test_blocked_roundtrip(blk_cfg8):
    rng = np.random.default_rng(10)
    keys = _rand_keys(2000, rng)
    f = ShardedBloomFilter(blk_cfg8)
    f.insert_batch(keys)
    assert f.include_batch(keys).all()
    absent = _rand_keys(2000, rng)
    assert f.include_batch(absent).mean() < 0.01


def test_blocked_parity_vs_oracle(blk_cfg8):
    """Mesh blocked implementation == compose-n-CPU-blocked-filters oracle,
    bit for bit (routing + per-shard block rows + answers)."""
    rng = np.random.default_rng(11)
    keys = _rand_keys(500, rng) + [b"", b"a", b"sharded-key"]
    f = ShardedBloomFilter(blk_cfg8)
    o = ShardedCPURef(blk_cfg8)
    f.insert_batch(keys)
    o.insert_batch(keys)
    dev = f.words_logical  # [shards, n_blocks_local, W]
    for s in range(blk_cfg8.shards):
        np.testing.assert_array_equal(dev[s], o.filters[s].words)
    probe = keys[:100] + _rand_keys(400, rng)
    np.testing.assert_array_equal(f.include_batch(probe), o.include_batch(probe))


def test_blocked_bytes_roundtrip(blk_cfg8):
    rng = np.random.default_rng(12)
    keys = _rand_keys(800, rng)
    f = ShardedBloomFilter(blk_cfg8)
    f.insert_batch(keys)
    g = ShardedBloomFilter.from_bytes(blk_cfg8, f.to_bytes())
    assert g.include_batch(keys).all()
    with pytest.raises(ValueError, match="not Redis-bitmap exportable"):
        f.to_redis_bitmap()


def test_blocked_checkpoint_restore(blk_cfg8, tmp_path):
    from tpubloom import checkpoint as ckpt

    cfg = blk_cfg8.replace(key_name="blk-sharded")
    rng = np.random.default_rng(13)
    keys = _rand_keys(600, rng)
    f = ShardedBloomFilter(cfg)
    f.insert_batch(keys)
    sink = ckpt.FileSink(str(tmp_path))
    ckpt.save(f, sink)
    g = ckpt.restore(cfg, sink)
    assert isinstance(g, ShardedBloomFilter)
    assert g.include_batch(keys).all()


def test_blocked_sweep_path_in_shard_map():
    """Forced sweep (Pallas interpret mode inside shard_map on the fake
    8-device mesh) matches the scatter path bit for bit — guards the
    per-device sweep hot loop that runs on real TPUs."""
    cfg = FilterConfig(
        m=1 << 25, k=5, key_len=16, block_bits=512, shards=8,
        insert_path="sweep",
    )
    f = ShardedBloomFilter(cfg, mesh=make_mesh(8))
    rng = np.random.default_rng(9)
    keys = [rng.bytes(16) for _ in range(512)]
    f.insert_batch(keys)
    assert f.include_batch(keys).all()
    g = ShardedBloomFilter(cfg.replace(insert_path="scatter"), mesh=make_mesh(8))
    g.insert_batch(keys)
    np.testing.assert_array_equal(np.asarray(f.words), np.asarray(g.words))


def test_fat_sweep_path_in_shard_map():
    """Forced sweep with a batch big enough that the per-device hot loop
    resolves to the FAT-row kernel (choose_fat_params accepts the
    local shape at B/n_dev) — bit-identical to the scatter path and the
    fat per-shard storage holds (VERDICT r3 #3: the sharded path must
    run the fat kernel, not the legacy narrow-tile one)."""
    from tpubloom.ops.sweep import choose_fat_params
    from tpubloom.parallel.sharded import local_blocked_storage_fat

    cfg = FilterConfig(
        m=1 << 25, k=5, key_len=16, block_bits=512, shards=8,
        insert_path="sweep",
    )
    assert local_blocked_storage_fat(cfg)
    local_rows = cfg.n_blocks_per_shard  # 1 shard-row per device on 8 devs
    B = 4096
    assert choose_fat_params(
        local_rows, max(1, B // 8), cfg.words_per_block
    ) is not None, "test shape must exercise the fat kernel"
    rng = np.random.default_rng(30)
    keys = [rng.bytes(16) for _ in range(B)]
    f = ShardedBloomFilter(cfg, mesh=make_mesh(8))
    f.insert_batch(keys)
    assert f.include_batch(keys).all()
    # fat per-shard storage shape
    nbl, w = cfg.n_blocks_per_shard, cfg.words_per_block
    assert np.asarray(f.words).shape == (8, nbl * w // 128, 128)
    g = ShardedBloomFilter(cfg.replace(insert_path="scatter"), mesh=make_mesh(8))
    g.insert_batch(keys)
    np.testing.assert_array_equal(np.asarray(f.words), np.asarray(g.words))
    probe = keys[:100] + [rng.bytes(16) for _ in range(400)]
    np.testing.assert_array_equal(f.include_batch(probe), g.include_batch(probe))


def test_fat_counting_sweep_path_in_shard_map():
    """Counting twin of test_fat_sweep_path_in_shard_map: the per-device
    hot loop runs the FAT counting kernel, counter-identical to the
    scatter path including deletes (VERDICT r3 #3/#4)."""
    from tpubloom.ops.sweep import choose_fat_params

    cfg = FilterConfig(
        m=1 << 25, k=5, key_len=16, block_bits=512, shards=8,
        counting=True, insert_path="sweep",
    )
    local_rows = cfg.n_blocks_per_shard
    B = 4096
    assert choose_fat_params(
        local_rows, max(1, B // 8), cfg.words_per_block
    ) is not None
    rng = np.random.default_rng(31)
    keys = [rng.bytes(16) for _ in range(B)]
    f = ShardedBloomFilter(cfg, mesh=make_mesh(8))
    f.insert_batch(keys)
    f.delete_batch(keys[: B // 4])
    assert f.include_batch(keys[B // 4 :]).all()
    g = ShardedBloomFilter(cfg.replace(insert_path="scatter"), mesh=make_mesh(8))
    g.insert_batch(keys)
    g.delete_batch(keys[: B // 4])
    np.testing.assert_array_equal(np.asarray(f.words), np.asarray(g.words))


# -- counting variants over the mesh (BASELINE configs 4 x 5) ----------------


@pytest.fixture(scope="module")
def cnt_cfg8():
    return FilterConfig(
        m=1 << 20, k=5, key_len=16, shards=8, counting=True
    )


@pytest.fixture(scope="module")
def blkcnt_cfg8():
    return FilterConfig(
        m=1 << 20, k=5, key_len=16, shards=8, counting=True, block_bits=512
    )


@pytest.mark.parametrize("layout", ["flat", "blocked"])
def test_counting_roundtrip_with_delete(layout, cnt_cfg8, blkcnt_cfg8):
    cfg = cnt_cfg8 if layout == "flat" else blkcnt_cfg8
    rng = np.random.default_rng(20)
    keys = _rand_keys(2000, rng)
    f = ShardedBloomFilter(cfg)
    f.insert_batch(keys)
    assert f.include_batch(keys).all()
    f.delete_batch(keys[:1000])
    assert f.include_batch(keys[1000:]).all(), "kept keys must stay present"
    assert f.include_batch(keys[:1000]).mean() < 0.01, "deleted keys linger"
    assert f.include_batch(_rand_keys(2000, rng)).mean() < 0.01


@pytest.mark.parametrize("layout", ["flat", "blocked"])
def test_counting_parity_vs_oracle(layout, cnt_cfg8, blkcnt_cfg8):
    """Mesh counting implementation == compose-n-reference-filters oracle,
    counter for counter, including after deletes."""
    cfg = cnt_cfg8 if layout == "flat" else blkcnt_cfg8
    rng = np.random.default_rng(21)
    keys = _rand_keys(500, rng) + [b"", b"a", b"sharded-key"]
    f, o = ShardedBloomFilter(cfg), ShardedCPURef(cfg)
    f.insert_batch(keys)
    o.insert_batch(keys)
    f.delete_batch(keys[:200])
    o.delete_batch(keys[:200])
    dev = np.asarray(f.words)  # [shards, ...local words]
    for s in range(cfg.shards):
        np.testing.assert_array_equal(
            dev[s].reshape(-1),
            np.asarray(o.filters[s].words).reshape(-1),
            err_msg=f"shard {s} counters differ",
        )
    probe = keys + _rand_keys(500, rng)
    np.testing.assert_array_equal(f.include_batch(probe), o.include_batch(probe))


def test_counting_sweep_path_in_shard_map():
    """Forced counting sweep (Pallas interpret mode inside shard_map on the
    fake 8-device mesh) matches the scatter path counter for counter,
    including deletes — guards the per-device counting sweep that runs on
    real TPUs (VERDICT r2 next-round #3)."""
    cfg = FilterConfig(
        m=1 << 25, k=5, key_len=16, block_bits=512, shards=8,
        counting=True, insert_path="sweep",
    )
    rng = np.random.default_rng(22)
    keys = [rng.bytes(16) for _ in range(512)]
    f = ShardedBloomFilter(cfg, mesh=make_mesh(8))
    f.insert_batch(keys)
    f.delete_batch(keys[:200])
    assert f.include_batch(keys[200:]).all()
    g = ShardedBloomFilter(cfg.replace(insert_path="scatter"), mesh=make_mesh(8))
    g.insert_batch(keys)
    g.delete_batch(keys[:200])
    np.testing.assert_array_equal(np.asarray(f.words), np.asarray(g.words))


@pytest.mark.parametrize("layout", ["flat", "blocked"])
def test_counting_checkpoint_restore(layout, cnt_cfg8, blkcnt_cfg8, tmp_path):
    from tpubloom import checkpoint as ckpt

    cfg = (cnt_cfg8 if layout == "flat" else blkcnt_cfg8).replace(
        key_name=f"cnt-sharded-{layout}"
    )
    rng = np.random.default_rng(23)
    keys = _rand_keys(600, rng)
    f = ShardedBloomFilter(cfg)
    f.insert_batch(keys)
    f.delete_batch(keys[:100])
    sink = ckpt.FileSink(str(tmp_path))
    ckpt.save(f, sink)
    g = ckpt.restore(cfg, sink)
    assert isinstance(g, ShardedBloomFilter)
    np.testing.assert_array_equal(np.asarray(f.words), np.asarray(g.words))
    assert g.include_batch(keys[100:]).all()
    g.delete_batch(keys[100:200])  # restored filter still supports delete
    assert g.include_batch(keys[200:]).all()


def test_counting_delete_requires_counting(cfg8):
    f = ShardedBloomFilter(cfg8)
    with pytest.raises(ValueError, match="counting"):
        f.delete_batch([b"x"])


# -- staged / packed surface (ISSUE 11) --------------------------------------


def test_staged_packed_surface_matches_list_path(cfg8):
    """insert_packed/include_packed (the ``keys_fixed`` server path) on
    a mesh filter are bit-identical to the list path, and staging
    replicates the batch across every mesh device up front."""
    f = ShardedBloomFilter(cfg8)
    keys = np.arange(512, dtype=np.uint64)
    rows = np.frombuffer(keys.tobytes(), np.uint8).reshape(512, 8)
    assert f.insert_packed(rows) == 512
    assert f.include_packed(rows).all()
    g = ShardedBloomFilter(cfg8)
    g.insert_batch([rows[i].tobytes() for i in range(512)])
    np.testing.assert_array_equal(np.asarray(f.words), np.asarray(g.words))
    # replicated H2D: the staged arrays live on all 8 devices BEFORE
    # the launch (the broadcast overlaps the previous flush's kernel)
    staged = f.stage_batch([b"abc", b"def"])
    assert len(staged[0].sharding.device_set) == 8
    assert len(staged[1].sharding.device_set) == 8


def test_packed_path_fires_shard_fault_points(cfg8):
    """Lifting the server's staged-path exclusion (ISSUE 11) must not
    lose the per-shard chaos surface: the packed/staged entry points
    fire shard.insert / shard.query BEFORE anything applies, honoring
    shard predicates."""
    from tpubloom import faults

    f = ShardedBloomFilter(cfg8)
    rows = np.frombuffer(
        np.arange(64, dtype=np.uint64).tobytes(), np.uint8
    ).reshape(64, 8)
    try:
        faults.arm("shard.insert", "once")
        with pytest.raises(faults.InjectedFault):
            f.insert_packed(rows)
        assert f.n_inserted == 0, "the fault must fire before the launch"
        faults.reset()
        assert f.insert_packed(rows) == 64
        faults.arm("shard.query", "once")
        with pytest.raises(faults.InjectedFault):
            f.include_packed(rows)
        faults.reset()
        assert f.include_packed(rows).all()
    finally:
        faults.reset()


# -- ISSUE 12: query sweep kernel in the sharded path + per-device phases -----


def test_query_sweep_path_in_shard_map():
    """The read-only query sweep inside shard_map (spd == 1, interpret
    mode on the fake mesh): verdicts identical to the gather twin —
    every key queries its in-shard row on every device, unowned
    verdicts masked by `owned` before the psum."""
    from tpubloom.ops import sweep

    cfg = FilterConfig(
        m=1 << 22, k=7, key_len=16, block_bits=512, shards=8,
        query_path="sweep",
    )
    assert sweep.choose_fat_query_params(
        cfg.n_blocks_per_shard, 4096, cfg.words_per_block
    ) is not None
    f = ShardedBloomFilter(cfg)
    g = ShardedBloomFilter(cfg.replace(query_path="gather"))
    rng = np.random.default_rng(12)
    population = [rng.bytes(16) for _ in range(4096)]
    f.insert_batch(population)
    g.insert_batch(population)
    probes = population[:1024] + [rng.bytes(16) for _ in range(1024)]
    got = f.include_batch(probes)
    want = g.include_batch(probes)
    np.testing.assert_array_equal(got, want)
    assert got[:1024].all(), "inserted keys must all be found"


def test_query_sweep_stays_off_multi_shard_devices():
    """With several shards per device the unowned keys would pile onto
    shard-row 0's windows — those geometries must keep the gather
    (documented in make_sharded_blocked_query_fn), still correct."""
    cfg = FilterConfig(
        m=1 << 22, k=7, key_len=16, block_bits=512, shards=16,
        query_path="sweep",
    )
    f = ShardedBloomFilter(cfg)  # 16 shards / 8 devices -> spd=2
    keys = [b"spd2-%d" % i for i in range(512)]
    f.insert_batch(keys)
    assert f.include_batch(keys).all()


def test_per_shard_kernel_phases_on_direct_path():
    """ROADMAP 1(c): under an active request context the mesh launch's
    single kernel span breaks into per-shard completion phases — one
    `kernel_shard<i>` per device, monotone in i (the fences run
    sequentially from one start point)."""
    from tpubloom.obs import context as obs

    cfg = FilterConfig(m=1 << 22, k=7, key_len=16, block_bits=512, shards=8)
    f = ShardedBloomFilter(cfg)
    keys = [b"phase-%d" % i for i in range(256)]
    n_dev = int(f.mesh.devices.size)
    with obs.request("InsertBatch") as ictx:
        f.insert_batch(keys)
    with obs.request("QueryBatch") as qctx:
        assert f.include_batch(keys).all()
    for ctx, kphase in ((ictx, "kernel"), (qctx, "kernel_query")):
        spans = [
            ctx.phases.get(f"kernel_shard{i}") for i in range(n_dev)
        ]
        assert all(s is not None for s in spans), (
            f"missing per-shard phases: {sorted(ctx.phases)}"
        )
        assert spans == sorted(spans), "spans must be monotone in shard index"
        assert kphase in ctx.phases
    # no context, no per-shard bookkeeping (the library path stays lean)
    f.include_batch(keys)
