"""ISSUE 11 satellites: the geometry-probe machinery in
:mod:`tpubloom.ops.sweep` — persistent on-disk cache keyed by device
kind (a second process start performs ZERO speculative probe compiles),
shape-identical probe buffers (ADVICE r5 #1), retry-once on transient
compile failures (ADVICE r5 #2), failed probes never persisted, and the
packed-KBJ bound on the validated-set fast path (ADVICE r5 #3).

All off-TPU: ``_probe_env`` / ``_probe_compile`` are the deliberate
seams — the tests monkeypatch them so the cache/signature logic runs
under the CPU backend exactly as it would on an unvalidated TPU
generation.
"""

import jax
import jax.numpy as jnp
import pytest

from tpubloom.ops import sweep

NB, BATCH, W = 1 << 17, 4096, 16


def _new_process(monkeypatch=None):
    """Simulate a fresh process: in-memory probe caches emptied, the
    on-disk cache (TPUBLOOM_CACHE_DIR) left alone."""
    sweep._GEOM_PROBE_CACHE.clear()
    sweep._GEOM_DISK_CACHE.clear()
    sweep._GEOM_DISK_LOADED.clear()


@pytest.fixture()
def fake_tpu(monkeypatch, tmp_path):
    """Pretend to be an unvalidated TPU generation with a recording
    probe; restore every module-global cache afterwards."""
    monkeypatch.setenv("TPUBLOOM_CACHE_DIR", str(tmp_path))
    monkeypatch.setattr(sweep, "_probe_env", lambda: "Fake TPU v9")
    calls = []

    def probe(fn, blocks_sds, upd_sds, starts_sds):
        calls.append(upd_sds.shape)
        return True, None

    monkeypatch.setattr(sweep, "_probe_compile", probe)
    saved = (
        dict(sweep._GEOM_PROBE_CACHE),
        dict(sweep._GEOM_DISK_CACHE),
        set(sweep._GEOM_DISK_LOADED),
    )
    _new_process()
    yield calls
    sweep._GEOM_PROBE_CACHE.clear()
    sweep._GEOM_PROBE_CACHE.update(saved[0])
    sweep._GEOM_DISK_CACHE.clear()
    sweep._GEOM_DISK_CACHE.update(saved[1])
    sweep._GEOM_DISK_LOADED.clear()
    sweep._GEOM_DISK_LOADED.update(saved[2])


def test_second_start_pays_zero_probe_compiles(fake_tpu):
    """THE acceptance gate: the first start probes (and persists), a
    simulated second process start on the same device kind answers
    every probe from disk — zero speculative compiles."""
    geom = sweep.choose_fat_params(NB, BATCH, W)
    assert geom is not None
    first = len(fake_tpu)
    assert first >= 1, "an unvalidated kind must probe at least once"
    _new_process()  # fresh process, same TPUBLOOM_CACHE_DIR
    geom2 = sweep.choose_fat_params(NB, BATCH, W)
    assert geom2 == geom
    assert len(fake_tpu) == first, (
        f"second start re-compiled {len(fake_tpu) - first} probe(s) — "
        f"the on-disk cache must absorb the cold start"
    )


def test_probe_upd_buffer_is_shape_identical_to_runtime(fake_tpu):
    """ADVICE r5 #1: the probe's update buffer must carry the REAL
    runtime row count (the _fat_stream btot for this batch), not a
    kbjp+16 stand-in."""
    geom = sweep.choose_fat_params(NB, BATCH, W)
    assert geom is not None
    J, R8, S, KJ, KBJ = geom
    pk = sweep.fat_pack(W, False)
    if pk == 1:
        expect = BATCH + KBJ + sweep._ALIGN
    else:
        expect = -(-BATCH // pk) + sweep._packed_rows(KBJ, pk) + sweep._ALIGN
    assert (expect, 128) in fake_tpu, (
        f"no probe used the runtime row count {expect}; saw {fake_tpu}"
    )


def test_failed_probe_demotes_but_is_not_persisted(monkeypatch, tmp_path):
    """A failed probe demotes THIS process (cached False in memory) but
    never lands on disk — a restart re-probes, preserving the
    transient-compile-failure escape hatch the warning documents."""
    monkeypatch.setenv("TPUBLOOM_CACHE_DIR", str(tmp_path))
    monkeypatch.setattr(sweep, "_probe_env", lambda: "Fake TPU v9")
    calls = []
    monkeypatch.setattr(
        sweep, "_probe_compile",
        lambda *a: (calls.append(1), (False, RuntimeError("OOM")))[1],
    )
    _new_process()
    with pytest.warns(RuntimeWarning, match="failed its probe"):
        geom = sweep.choose_fat_params(NB, BATCH, W)
    assert geom is None, "every candidate geometry must demote"
    n1 = len(calls)
    # same process: cached False, no re-probe
    assert sweep.choose_fat_params(NB, BATCH, W) is None
    assert len(calls) == n1
    # "restart": the failure must NOT have persisted — re-probes run
    _new_process()
    with pytest.warns(RuntimeWarning):
        sweep.choose_fat_params(NB, BATCH, W)
    assert len(calls) > n1, "a restart must re-probe failed geometries"
    _new_process()


def test_disk_put_merges_with_concurrent_writers(fake_tpu, tmp_path):
    """Fleet rolling restarts share one cache dir: a write must UNION
    with entries a sibling process landed after our load — not clobber
    the file with this process's view alone."""
    sweep._geom_disk_put("Fake TPU v9", "mine/1")
    # a "sibling process" writes its own entry directly
    from tpubloom.utils import crcjson

    path = sweep._geom_cache_path("Fake TPU v9")
    crcjson.store(path, {
        "geoms": ["sibling/2"], "salt": sweep._geom_cache_salt(),
    })
    sweep._geom_disk_put("Fake TPU v9", "mine/3")
    _new_process()
    assert sweep._geom_disk_get("Fake TPU v9", "sibling/2"), (
        "a sibling's entry was clobbered by our whole-file rewrite"
    )
    assert sweep._geom_disk_get("Fake TPU v9", "mine/1")
    assert sweep._geom_disk_get("Fake TPU v9", "mine/3")


def test_version_salt_invalidates_persisted_probes(fake_tpu, monkeypatch):
    """A persisted ok=True must not survive a code/jax upgrade: a
    geometry that no longer compiles would skip its probe and hit the
    Mosaic error at first REAL use, with no demotion path."""
    geom = sweep.choose_fat_params(NB, BATCH, W)
    assert geom is not None
    first = len(fake_tpu)
    monkeypatch.setattr(
        sweep, "_geom_cache_salt", lambda: "upgraded|jax-99.0"
    )
    _new_process()
    assert sweep.choose_fat_params(NB, BATCH, W) == geom
    assert len(fake_tpu) > first, (
        "a salt change must force re-probing, not trust stale entries"
    )


def test_probe_compile_retries_once_on_transient_failure():
    """ADVICE r5 #2 (already shipping, pinned here): one transient
    compile-service failure must not demote the geometry — the second
    attempt lands."""
    state = {"n": 0}

    def flaky(a, b, c):
        state["n"] += 1
        if state["n"] == 1:
            raise RuntimeError("HTTP 500 from the compile service")
        return a

    sds = jax.ShapeDtypeStruct((8, 128), jnp.uint32)
    ok, exc = sweep._probe_compile(flaky, sds, sds, sds)
    assert ok and state["n"] == 2


def test_validated_signature_bounds_packed_kbj(monkeypatch, tmp_path):
    """ADVICE r5 #3: the v5e validated-set fast path now also pins the
    big-fetch scratch — a geometry whose packed KBJ rows exceed what
    its (J, R8, S, KJP) signature can legitimately pair with must
    PROBE, not ride the fast path."""
    # caps derive from inverting the chooser's KJ(lambda) step function
    cap = sweep._validated_kbjp_cap("presence", (8, 512, 2, 96))
    assert cap > 0
    monkeypatch.setenv("TPUBLOOM_CACHE_DIR", str(tmp_path))
    monkeypatch.setattr(sweep, "_probe_env", lambda: "TPU v5 lite")
    probed = []
    monkeypatch.setattr(
        sweep, "_probe_compile",
        lambda *a: (probed.append(1), (True, None))[1],
    )
    _new_process()
    pk = sweep.fat_pack(16, True)
    # reconstruct an unpacked KJ whose packed rows hit the validated 96
    kj = next(
        k for k in range(16, 2048, 8) if sweep._packed_rows(k, pk) == 96
    )
    ok_kbj = next(
        b for b in range(kj, 1 << 16, 8)
        if sweep._packed_rows(b, pk) == cap
    )
    geom_ok = (8, 512, 2, kj, ok_kbj)
    assert sweep._fat_geometry_compiles(
        1 << 17, 16, geom_ok, presence=True, counting=False, batch=BATCH
    )
    assert not probed, "an in-signature geometry must skip the probe"
    big_kbj = next(
        b for b in range(ok_kbj, 1 << 20, 8)
        if sweep._packed_rows(b, pk) > cap
    )
    geom_big = (8, 512, 2, kj, big_kbj)
    assert sweep._fat_geometry_compiles(
        1 << 17, 16, geom_big, presence=True, counting=False, batch=BATCH
    )
    assert probed, "an out-of-cap KBJ must fall through to the probe"
    _new_process()
