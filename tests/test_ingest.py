"""ISSUE 10: cross-connection micro-batching ingestion scheduler.

Covers the tentpole end to end:

* concurrent-client **exactly-once** + per-request result **demux**
  (presence slices, query hits, repl_seq) through coalesced flushes;
* **barrier amortization**: one ``wait_acked`` per flush, per-request
  quorum verdicts — a barrier timeout answers ``NOT_ENOUGH_REPLICAS``
  per-request with ``applied: true`` while a weaker sibling in the SAME
  flush succeeds; the dedup re-wait path stays intact;
* the **fixed wire encoding**: zero-copy round trip, per-connection
  negotiation, msgpack twins unaffected;
* coalescer **chaos**: the ``ingest.coalesce`` / ``ingest.flush`` fault
  points fire before anything applies, so retries stay exactly-once;
* READONLY / DRAINING / MOVED semantics preserved with the coalescer on
  (they run in the wrapper before anything parks);
* satellites: forward-entry aging, per-slot traffic counters, phase
  exemplars;
* the tier-1 smoke wrapper over ``benchmarks/ingest_load.py``.

The whole module runs under the armed lock tracker (``lock_check_armed``)
and diffs the runtime acquisition graph against the declared manifest at
teardown — the new ``ingest.*`` ranks are part of the ISSUE-10 surface.
"""

import os
import threading
import time

import numpy as np
import pytest

from tpubloom import faults
from tpubloom.obs import counters as obs_counters
from tpubloom.server import protocol
from tpubloom.server.client import BloomClient
from tpubloom.server.ingest import CoalesceConfig
from tpubloom.server.service import BloomService, build_server

# ISSUE 13: the manifest gate fixture moved to tests/conftest.py —
# shared by all five armed chaos modules
pytestmark = pytest.mark.usefixtures("lock_check_armed", "lock_order_manifest")


@pytest.fixture(autouse=True)
def _disarm_all():
    faults.reset()
    yield
    faults.reset()


class _Server:
    def __init__(self, service):
        self.service = service
        self.server, self.port = build_server(service, "127.0.0.1:0")
        self.server.start()
        self.addr = f"127.0.0.1:{self.port}"

    def client(self, **kw) -> BloomClient:
        return BloomClient(self.addr, **kw)

    def stop(self):
        self.service.shutdown()
        self.server.stop(grace=None)


@pytest.fixture()
def coalesced_server():
    s = _Server(BloomService(
        coalesce=CoalesceConfig(max_keys=4096, max_wait_us=2000)
    ))
    yield s
    s.stop()


def _threads(fns):
    errs = []

    def run(fn):
        try:
            fn()
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errs.append(e)

    ts = [threading.Thread(target=run, args=(fn,)) for fn in fns]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    if errs:
        raise errs[0]


# -- exactly-once + demux -----------------------------------------------------


def test_concurrent_inserts_coalesce_exactly_once(coalesced_server):
    """N clients' inserts coalesce into shared flushes; a counting
    filter proves exactly-once (a double-applied insert survives one
    delete round), and every client's keys land."""
    s = coalesced_server
    with s.client() as admin:
        admin.create_filter(
            "cnt", capacity=200_000, error_rate=0.01, counting=True
        )

        def writer(t):
            def go():
                with s.client() as c:
                    for i in range(6):
                        keys = [b"ek-%d-%d-%d" % (t, i, j) for j in range(40)]
                        assert c.insert_batch("cnt", keys) == 40
            return go

        _threads([writer(t) for t in range(6)])
        counters = admin.stats()["counters"]
        assert counters["ingest_requests_coalesced"] >= 36
        assert counters["ingest_flushes"] >= 1
        all_keys = [
            b"ek-%d-%d-%d" % (t, i, j)
            for t in range(6) for i in range(6) for j in range(40)
        ]
        assert admin.include_batch("cnt", all_keys).all()
        admin.delete_batch("cnt", all_keys)  # 1 - 1 = 0 unless doubled
        doubled = int(admin.include_batch("cnt", all_keys).sum())
        assert doubled == 0, f"{doubled} keys double-applied in a flush"


def test_presence_demux_per_request(coalesced_server):
    """return_presence through a coalesced flush: each request's slice
    reflects ITS keys' pre-batch membership, not its flush-mates'."""
    s = coalesced_server
    with s.client() as admin:
        admin.create_filter("pres", capacity=200_000, error_rate=0.01)
        results = {}

        def writer(t):
            def go():
                with s.client() as c:
                    keys = [b"pk-%d-%d" % (t, j) for j in range(50)]
                    results[t] = (
                        c.insert_batch("pres", keys, return_presence=True),
                        c.insert_batch("pres", keys, return_presence=True),
                    )
            return go

        _threads([writer(t) for t in range(5)])
        for t, (first, second) in results.items():
            assert not first.any(), f"client {t}: fresh keys reported present"
            assert second.all(), f"client {t}: re-insert lost its own keys"


def test_query_demux_per_request(coalesced_server):
    s = coalesced_server
    with s.client() as admin:
        admin.create_filter("q", capacity=200_000, error_rate=0.01)
        present = [b"in-%d" % j for j in range(100)]
        admin.insert_batch("q", present)
        results = {}

        def reader(t):
            def go():
                with s.client() as c:
                    mine = [b"in-%d" % ((t * 7 + j) % 100) for j in range(20)]
                    absent = [b"out-%d-%d" % (t, j) for j in range(20)]
                    results[t] = (
                        c.include_batch("q", mine),
                        c.include_batch("q", absent),
                    )
            return go

        _threads([reader(t) for t in range(6)])
        for t, (hit, miss) in results.items():
            assert hit.all(), f"client {t}: present keys demuxed wrong"
            assert not miss.any(), f"client {t}: absent keys demuxed wrong"


# -- fixed wire encoding ------------------------------------------------------


def test_fixed_encoding_round_trip_and_negotiation(coalesced_server):
    s = coalesced_server
    with s.client() as c:
        c.create_filter("fx", capacity=100_000, error_rate=0.01)
        keys = np.arange(1000, 2000, dtype=np.uint64)
        assert c.insert_batch("fx", keys) == 1000
        assert c._fixed_negotiated is True  # negotiated off Health
        assert c.include_batch("fx", keys).all()
        # msgpack twins: the SAME u64s as 8-byte little-endian bins must
        # hit through a pinned-msgpack client, and vice versa
        with s.client(encoding="msgpack") as m:
            twins = [int(k).to_bytes(8, "little") for k in keys[:50]]
            assert m.include_batch("fx", twins).all()
            m.insert_batch("fx", [b"mp-only-1", b"mp-only-2"])
        assert c.include_batch(
            "fx", np.arange(5000, 5100, dtype=np.uint64)
        ).sum() <= 2  # fpr-level noise only
        # equal-width bytes lists ship fixed too
        wide = [b"W%015d" % j for j in range(64)]  # 16B == key_len
        c.insert_batch("fx", wide)
        assert c.include_batch("fx", wide).all()
        # keys WIDER than key_len (16) must fall back to msgpack so
        # key_policy applies — here policy=error, so the server errors
        # identically to the classic path
        with pytest.raises(protocol.BloomServiceError):
            c.insert_batch("fx", [b"x" * 32, b"y" * 32])


def test_wide_fixed_keys_fall_back_to_key_policy_in_flush(coalesced_server):
    """Fixed-width keys WIDER than key_len arriving at a coalesced
    flush must take the list path so key_policy (digest) applies —
    direct-path parity, not an INTERNAL from the packed staging."""
    s = coalesced_server
    with s.client() as c:
        c.create_filter(
            "wide", capacity=100_000, error_rate=0.01, key_policy="digest"
        )
        wide = [bytes([j]) * 32 for j in range(16)]  # 32B > key_len 16
        assert c.insert_batch("wide", wide) == 16
        assert c.include_batch("wide", wide).all()
        absent = [bytes([200 + j]) * 32 for j in range(10)]
        assert not c.include_batch("wide", absent).any()


def test_fixed_encoding_replicates_and_replays(tmp_path):
    """A fixed-encoded insert's op-log record replays after restart —
    the record carries the raw buffer, and the handler applies it on
    the replay path exactly once."""
    from tpubloom.repl import OpLog

    d = str(tmp_path / "log")
    svc = BloomService(oplog=OpLog(d))
    s = _Server(svc)
    keys = np.arange(500, dtype=np.uint64)
    with s.client() as c:
        c.create_filter("r", capacity=100_000, error_rate=0.01)
        c.insert_batch("r", keys)
    s.stop()
    svc.oplog.close()

    svc2 = BloomService(oplog=OpLog(d))
    stats = svc2.replay_oplog()
    assert stats["failed"] == 0 and stats["applied"] >= 2
    s2 = _Server(svc2)
    with s2.client() as c:
        assert c.include_batch("r", keys).all()
    s2.stop()
    svc2.oplog.close()


# -- barrier amortization -----------------------------------------------------


def test_flush_shares_one_barrier_with_per_request_verdicts(tmp_path):
    """Two writes in ONE flush, different quorums: the flush runs ONE
    wait (one wait_barrier observation), the min_replicas=1 request
    times out with NOT_ENOUGH_REPLICAS {applied: true, seq}, the
    min_replicas=0 sibling succeeds — and after the replica acks, a
    same-rid re-drive answers from the dedup cache and re-waits to
    success."""
    from tpubloom.repl import OpLog

    svc = BloomService(
        oplog=OpLog(str(tmp_path / "log")),
        # size-ripe at exactly the two parked requests: 2 x 64 keys
        coalesce=CoalesceConfig(max_keys=128, max_wait_us=500_000),
    )
    # a CONNECTED (but silent) replica session: the barrier must ride
    # out its budget instead of fail-fasting
    sid = svc.repl_sessions.register("silent-replica", listen="127.0.0.1:1")
    s = _Server(svc)
    try:
        with s.client() as admin:
            # counting => replay-unsafe => the rid-dedup cache holds the
            # seq-stamped response the re-drive below re-waits through
            admin.create_filter(
                "b", capacity=100_000, error_rate=0.01, counting=True
            )
            waits_before = svc.metrics.waits.n
            keys_a = [b"qa-%d" % j for j in range(64)]
            keys_b = [b"qb-%d" % j for j in range(64)]
            outcome = {}

            def strict():
                with s.client() as c:
                    try:
                        c.insert_batch(
                            "b", keys_a,
                            min_replicas=1, min_replicas_timeout_ms=300,
                        )
                        outcome["strict"] = "ok"
                    except protocol.BloomServiceError as e:
                        outcome["strict"] = e
                    outcome["strict_rid"] = c.last_rid

            def lax():
                with s.client() as c:
                    outcome["lax"] = c.insert_batch("b", keys_b)

            _threads([strict, lax])
            err = outcome["strict"]
            assert isinstance(err, protocol.BloomServiceError), (
                "the quorum-demanding write must time out (no acks)"
            )
            assert err.code == "NOT_ENOUGH_REPLICAS"
            assert err.details["applied"] is True
            assert err.details["acked"] == 0
            seq = err.details["seq"]
            assert isinstance(seq, int)
            assert outcome["lax"] == 64, "the min_replicas=0 sibling failed"
            # ONE barrier observation covered the whole flush
            assert svc.metrics.waits.n == waits_before + 1
            # the apply stands: both batches are readable
            assert admin.include_batch("b", keys_a + keys_b).all()

            # ack the flush's record, then re-drive the strict write
            # under its ORIGINAL rid: dedup answers the cached
            # (seq-stamped) response and the wrapper re-waits — success
            svc.repl_sessions.ack(sid, seq)
            with s.client() as c:
                resp = c._rpc(
                    "InsertBatch",
                    {"name": "b", "keys": keys_a, "min_replicas": 1,
                     "min_replicas_timeout_ms": 1000},
                    rid=outcome["strict_rid"],
                )
            assert resp["repl_seq"] == seq, (
                "the re-drive must answer from the dedup cache (a fresh "
                "apply would mint a NEW record seq)"
            )
            assert resp["acked_replicas"] == 1
            assert svc.metrics.counters["insert_dedup_hits"] >= 1
            # exactly-once under the re-drive: still present, one apply
            admin.create_filter(  # bare attach, sanity that nothing broke
                "b", exist_ok=True
            )
    finally:
        s.stop()
        svc.oplog.close()


def test_quorum_required_without_oplog_in_flush(coalesced_server):
    """min_replicas on a log-less server answers NOT_ENOUGH_REPLICAS
    from the coalesced path too (the direct path's contract)."""
    s = coalesced_server
    with s.client() as c:
        c.create_filter("nolog", capacity=100_000, error_rate=0.01)
        with pytest.raises(protocol.BloomServiceError) as ei:
            c.insert_batch("nolog", [b"k1"], min_replicas=1)
        assert ei.value.code == "NOT_ENOUGH_REPLICAS"
        assert ei.value.details["applied"] is True


# -- chaos --------------------------------------------------------------------


def test_ingest_flush_fault_fails_flush_then_heals(coalesced_server):
    """An injected ingest.flush fault fires BEFORE anything applies:
    every parked request errors, nothing lands, and the retry applies
    exactly once (counting-filter delete proof)."""
    s = coalesced_server
    with s.client() as c:
        c.create_filter(
            "chaos", capacity=100_000, error_rate=0.01, counting=True
        )
        keys = [b"cf-%d" % j for j in range(32)]
        faults.arm("ingest.flush", "once")
        with pytest.raises(protocol.BloomServiceError) as ei:
            c.insert_batch("chaos", keys)
        assert ei.value.code == "INTERNAL"
        assert not c.include_batch("chaos", keys).any(), (
            "a failed flush must not have applied"
        )
        assert c.insert_batch("chaos", keys) == 32  # heals
        c.delete_batch("chaos", keys)
        assert not c.include_batch("chaos", keys).any(), "double-applied"


def test_ingest_coalesce_fault_fires_pre_park(coalesced_server):
    s = coalesced_server
    with s.client() as c:
        c.create_filter("chaos2", capacity=100_000, error_rate=0.01)
        faults.arm("ingest.coalesce", "once")
        with pytest.raises(protocol.BloomServiceError):
            c.insert_batch("chaos2", [b"x"])
        assert not c.include_batch("chaos2", [b"x"]).any()
        assert c.insert_batch("chaos2", [b"x"]) == 1


# -- admission/routing semantics preserved ------------------------------------


def test_readonly_preserved_with_coalescer():
    svc = BloomService(
        read_only=True,
        coalesce=CoalesceConfig(max_keys=4096, max_wait_us=1000),
    )
    s = _Server(svc)
    try:
        with s.client() as c:
            with pytest.raises(protocol.BloomServiceError) as ei:
                c._rpc("InsertBatch", {"name": "x", "keys": [b"k"]})
            assert ei.value.code == "READONLY"
    finally:
        s.stop()


def test_draining_shed_preserved_with_coalescer(coalesced_server):
    s = coalesced_server
    with s.client() as c:
        c.create_filter("drain", capacity=100_000, error_rate=0.01)
        s.service.begin_drain()
        with pytest.raises(protocol.BloomServiceError) as ei:
            c._rpc("InsertBatch", {"name": "drain", "keys": [b"k"]})
        assert ei.value.code == "DRAINING"


def test_moved_preserved_with_coalescer(tmp_path):
    """Cluster slot checks run BEFORE the handler parks anything: an
    unowned slot answers MOVED even with the coalescer armed."""
    from tpubloom.cluster.node import ClusterState
    from tpubloom.repl import OpLog

    cluster = ClusterState("127.0.0.1:7100", state_dir=str(tmp_path))
    cluster.set_slot({"assign": [[0, 16383, "127.0.0.1:9999"]], "epoch": 1})
    svc = BloomService(
        oplog=OpLog(str(tmp_path / "log")),
        cluster=cluster,
        coalesce=CoalesceConfig(max_keys=4096, max_wait_us=1000),
    )
    s = _Server(svc)
    try:
        with s.client() as c:
            with pytest.raises(protocol.BloomServiceError) as ei:
                c._rpc("InsertBatch", {"name": "elsewhere", "keys": [b"k"]})
            assert ei.value.code == "MOVED"
            assert ei.value.details["addr"] == "127.0.0.1:9999"
    finally:
        s.stop()
        svc.oplog.close()


# -- satellites ---------------------------------------------------------------


def test_forward_entries_age_out_after_handoff(tmp_path):
    """ROADMAP 1(d): dual-write forward entries expire a TTL after the
    slot handoff finalizes — within the TTL stragglers still forward."""
    from tpubloom.cluster import slots as slots_mod
    from tpubloom.cluster.node import ClusterState

    cs = ClusterState(
        "127.0.0.1:7200", state_dir=str(tmp_path), forward_ttl_s=0.15
    )
    name = "aging-filter"
    slot = slots_mod.key_slot(name)
    cs.set_slot({"assign": [[0, 16383, "127.0.0.1:7200"]], "epoch": 1})
    cs.set_slot({"slot": slot, "state": "migrating", "addr": "127.0.0.1:7201"})
    cs.begin_forwarding(name, "127.0.0.1:7201")
    assert cs.forward_target(name) == "127.0.0.1:7201"
    before = obs_counters.get("cluster_forward_entries_expired")
    # finalize AWAY: the retirement clock starts, stragglers still served
    cs.set_slot(
        {"slot": slot, "state": "node", "addr": "127.0.0.1:7201", "epoch": 2}
    )
    assert cs.forward_target(name) == "127.0.0.1:7201", (
        "within the TTL a straggling in-flight write must still forward"
    )
    time.sleep(0.2)
    assert cs.forward_target(name) is None, "entry must expire past the TTL"
    assert obs_counters.get("cluster_forward_entries_expired") == before + 1
    # re-arming resets the clock (a re-driven migration)
    cs.begin_forwarding(name, "127.0.0.1:7202")
    assert cs.forward_target(name) == "127.0.0.1:7202"


def test_finalize_back_to_self_drops_forwards(tmp_path):
    from tpubloom.cluster import slots as slots_mod
    from tpubloom.cluster.node import ClusterState

    cs = ClusterState("127.0.0.1:7300", state_dir=str(tmp_path))
    name = "come-back"
    slot = slots_mod.key_slot(name)
    cs.set_slot({"assign": [[0, 16383, "127.0.0.1:7300"]], "epoch": 1})
    cs.begin_forwarding(name, "127.0.0.1:7301")
    cs.set_slot(
        {"slot": slot, "state": "node", "addr": "127.0.0.1:7300", "epoch": 2}
    )
    assert cs.forward_target(name) is None


def test_slot_traffic_counters(tmp_path):
    """Per-slot key-traffic counters (ROADMAP item 6): keyed RPCs on a
    cluster node mint cluster_slot_keys_total_<slot> by key count."""
    from tpubloom.cluster import slots as slots_mod
    from tpubloom.cluster.node import ClusterState
    from tpubloom.repl import OpLog

    addr = "127.0.0.1:7400"
    cluster = ClusterState(addr, state_dir=str(tmp_path))
    cluster.set_slot({"assign": [[0, 16383, addr]], "epoch": 1})
    svc = BloomService(oplog=OpLog(str(tmp_path / "log")), cluster=cluster)
    s = _Server(svc)
    try:
        with s.client() as c:
            name = "traffic"
            slot = slots_mod.key_slot(name)
            series = f"cluster_slot_keys_total_{slot}"
            before = obs_counters.get(series)
            c.create_filter(name, capacity=100_000, error_rate=0.01)
            c.insert_batch(name, [b"t-%d" % j for j in range(37)])
            c.include_batch(name, [b"t-%d" % j for j in range(11)])
            assert obs_counters.get(series) == before + 37 + 11
    finally:
        s.stop()
        svc.oplog.close()


def test_phase_histogram_exemplars(coalesced_server):
    """ROADMAP item 6 leftover: the per-RPC phase histograms carry
    rid exemplars, rendered behind /metrics?exemplars=1."""
    from tpubloom.obs.exposition import render_service

    s = coalesced_server
    with s.client() as c:
        c.create_filter("ex", capacity=100_000, error_rate=0.01)
        c.insert_batch("ex", [b"e-%d" % j for j in range(10)])
        rid = c.last_rid
    text = render_service(s.service, exemplars=True)
    phase_lines = [
        ln for ln in text.splitlines()
        if ln.startswith("tpubloom_rpc_phase_seconds_bucket") and "# {rid=" in ln
    ]
    assert phase_lines, "phase buckets must carry exemplars"
    assert any(rid in ln for ln in phase_lines), (
        "the newest request's rid must be findable in a phase exemplar"
    )
    # stock scrape untouched
    plain = render_service(s.service, exemplars=False)
    assert "# {rid=" not in plain


# -- multichip: sharded filters through the coalescer (ISSUE 11) --------------


def test_sharded_coalesced_exactly_once_under_shard_chaos(coalesced_server):
    """THE ISSUE-11 chaos acceptance: a mesh-sharded COUNTING filter
    under concurrent coalesced load with ``shard.insert`` armed — every
    acked write lands exactly once (zero lost: all acked keys readable;
    zero doubled: one delete round empties them), and the armed lock
    tracker (module fixture) reports zero violations. The fault fires
    BEFORE the shard_map launch, so a failed flush applies nothing and
    the writers' retries stay exactly-once."""
    s = coalesced_server
    with s.client() as admin:
        admin.create_filter(
            "shx", capacity=200_000, error_rate=0.01,
            shards=8, counting=True,
        )
        # predicate chaos: flushes touching shard 2 fail every 2nd pass,
        # 6 times total — guaranteed chaos AND guaranteed completion
        faults.arm("shard.insert", "nth:2", pred={"shard": 2}, times=6)
        acked: list = []
        acked_lock = threading.Lock()
        saw_fault: list = []

        def writer(t):
            def go():
                with s.client() as c:
                    for i in range(5):
                        keys = [b"sx-%d-%d-%d" % (t, i, j) for j in range(40)]
                        for _attempt in range(40):
                            try:
                                assert c.insert_batch("shx", keys) == 40
                                break
                            except protocol.BloomServiceError as e:
                                assert e.code == "INTERNAL", e
                                saw_fault.append(1)
                        else:
                            raise AssertionError("insert never succeeded")
                        with acked_lock:
                            acked.append(keys)
            return go

        try:
            _threads([writer(t) for t in range(6)])
        finally:
            faults.reset()
        assert len(acked) == 30, "every batch must eventually ack"
        flat = [k for ks in acked for k in ks]
        assert admin.include_batch("shx", flat).all(), "acked write lost"
        admin.delete_batch("shx", flat)
        doubled = int(admin.include_batch("shx", flat).sum())
        assert doubled == 0, f"{doubled} acked keys double-applied"


def test_sharded_fixed_coalesced_demux(coalesced_server):
    """Sharded filters ride the zero-copy ``keys_fixed`` encoding and
    the coalescer's per-request demux (PR-10 excluded them from both)."""
    s = coalesced_server
    with s.client() as c:
        c.create_filter("shq", capacity=200_000, error_rate=0.01, shards=8)
        present = np.arange(1000, dtype=np.uint64)
        assert c.insert_batch("shq", present) == 1000
        assert c._fixed_negotiated is True
        results = {}

        def reader(t):
            def go():
                with s.client() as rc:
                    mine = np.arange(t * 100, t * 100 + 50, dtype=np.uint64)
                    absent = mine + 500_000
                    results[t] = (
                        rc.include_batch("shq", mine),
                        rc.include_batch("shq", absent),
                    )
            return go

        _threads([reader(t) for t in range(6)])
        for t, (hit, miss) in results.items():
            assert hit.all(), f"client {t}: present keys demuxed wrong"
            assert not miss.any(), f"client {t}: absent keys demuxed wrong"
        counters = c.stats()["counters"]
        assert counters["ingest_requests_coalesced"] >= 6


def test_sharded_fixed_coalesced_replays_after_restart(tmp_path):
    """A sharded coalesced flush commits ONE merged keys_fixed record;
    after a restart the replay re-creates the mesh filter and applies
    the record exactly once (counting delete proof)."""
    from tpubloom.repl import OpLog

    d = str(tmp_path / "log")
    svc = BloomService(
        oplog=OpLog(d),
        coalesce=CoalesceConfig(max_keys=4096, max_wait_us=2000),
    )
    s = _Server(svc)
    keys = np.arange(800, dtype=np.uint64)
    with s.client() as c:
        c.create_filter(
            "shr", capacity=100_000, error_rate=0.01,
            shards=8, counting=True,
        )

        def writer(lo):
            def go():
                with s.client() as w:
                    w.insert_batch("shr", keys[lo: lo + 400])
            return go

        _threads([writer(0), writer(400)])
    s.stop()
    svc.oplog.close()

    svc2 = BloomService(oplog=OpLog(d))
    stats = svc2.replay_oplog()
    assert stats["failed"] == 0
    s2 = _Server(svc2)
    with s2.client() as c:
        assert c.include_batch("shr", keys).all()
        c.delete_batch("shr", keys)  # 1 - 1 = 0 unless replay doubled
        doubled = int(c.include_batch("shr", keys).sum())
        assert doubled == 0, f"{doubled} keys double-applied by replay"
    s2.stop()
    svc2.oplog.close()


def test_sharded_flush_barrier_and_dedup_rewait(tmp_path):
    """The one-barrier-per-flush contract holds for mesh-sharded
    filters: a strict write in a coalesced flush times out
    NOT_ENOUGH_REPLICAS {applied: true}, its lax flush-mate succeeds,
    and after the replica acks, a same-rid re-drive answers from the
    dedup cache and re-waits to success."""
    from tpubloom.repl import OpLog

    svc = BloomService(
        oplog=OpLog(str(tmp_path / "log")),
        coalesce=CoalesceConfig(max_keys=128, max_wait_us=500_000),
    )
    svc_sid = svc.repl_sessions.register("silent", listen="127.0.0.1:1")
    s = _Server(svc)
    try:
        with s.client() as admin:
            admin.create_filter(
                "shb", capacity=100_000, error_rate=0.01,
                shards=8, counting=True,
            )
            keys_a = [b"sba-%d" % j for j in range(64)]
            keys_b = [b"sbb-%d" % j for j in range(64)]
            outcome = {}

            def strict():
                with s.client() as c:
                    try:
                        c.insert_batch(
                            "shb", keys_a,
                            min_replicas=1, min_replicas_timeout_ms=300,
                        )
                        outcome["strict"] = "ok"
                    except protocol.BloomServiceError as e:
                        outcome["strict"] = e
                    outcome["strict_rid"] = c.last_rid

            def lax():
                with s.client() as c:
                    outcome["lax"] = c.insert_batch("shb", keys_b)

            _threads([strict, lax])
            err = outcome["strict"]
            assert isinstance(err, protocol.BloomServiceError)
            assert err.code == "NOT_ENOUGH_REPLICAS"
            assert err.details["applied"] is True
            seq = err.details["seq"]
            assert outcome["lax"] == 64
            assert admin.include_batch("shb", keys_a + keys_b).all()
            svc.repl_sessions.ack(svc_sid, seq)
            with s.client() as c:
                resp = c._rpc(
                    "InsertBatch",
                    {"name": "shb", "keys": keys_a, "min_replicas": 1,
                     "min_replicas_timeout_ms": 1000},
                    rid=outcome["strict_rid"],
                )
            assert resp["repl_seq"] == seq, "re-drive must hit the dedup cache"
            assert resp["acked_replicas"] == 1
    finally:
        s.stop()
        svc.oplog.close()


# -- op-sorted flushes (ISSUE 11 satellite) -----------------------------------


def test_presence_split_from_plain_inserts_in_flush(coalesced_server):
    """A parked insert run mixing presence and plain requests flushes
    as TWO op-pure launches (the plain half rides the insert-only rate
    instead of the fused one) — with correct per-request demux and the
    fused/split mix counters ticking."""
    s = coalesced_server
    with s.client() as admin:
        admin.create_filter("mix", capacity=200_000, error_rate=0.01)
        c0 = admin.stats()["counters"]
        results = {}

        def plain(t):
            def go():
                with s.client() as c:
                    keys = [b"mp-%d-%d" % (t, j) for j in range(40)]
                    results[f"plain{t}"] = c.insert_batch("mix", keys)
            return go

        def presence(t):
            def go():
                with s.client() as c:
                    keys = [b"mq-%d-%d" % (t, j) for j in range(40)]
                    first = c.insert_batch("mix", keys, return_presence=True)
                    results[f"pres{t}"] = first
            return go

        _threads([plain(0), plain(1), plain(2), presence(0), presence(1)])
        for t in range(3):
            assert results[f"plain{t}"] == 40
        for t in range(2):
            assert not results[f"pres{t}"].any(), (
                "fresh keys must report absent"
            )
        c1 = admin.stats()["counters"]
        assert c1.get("ingest_fused_flushes", 0) > c0.get(
            "ingest_fused_flushes", 0
        ), "a presence run must count as a fused launch"
        # all keys landed regardless of which launch they rode
        allk = [b"mp-%d-%d" % (t, j) for t in range(3) for j in range(40)]
        allk += [b"mq-%d-%d" % (t, j) for t in range(2) for j in range(40)]
        assert admin.include_batch("mix", allk).all()


def test_split_flush_failure_does_not_poison_applied_sibling(tmp_path):
    """Error containment across op-sorted sub-flushes: when the plain
    half of a split flush has ALREADY applied + logged and is parked on
    the completer awaiting its barrier, a failure in the presence half
    must fail ONLY the presence waiters — the plain write's client gets
    its real quorum verdict (NOT_ENOUGH_REPLICAS {applied: true}), not
    a generic INTERNAL that would invite a fresh-rid retry and a double
    apply."""
    from tpubloom.repl import OpLog

    svc = BloomService(
        oplog=OpLog(str(tmp_path / "log")),
        # both 64-key entries must co-park: ripen by size at exactly 128
        coalesce=CoalesceConfig(max_keys=128, max_wait_us=500_000),
    )
    svc.repl_sessions.register("silent", listen="127.0.0.1:1")
    s = _Server(svc)
    try:
        with s.client() as admin:
            admin.create_filter("split", capacity=100_000, error_rate=0.01)
            # the presence half of a flat filter runs include_batch +
            # insert_batch; failing the include fails the presence part
            # BEFORE anything of it applies, while the plain part is
            # already launched + logged + barrier-parked
            mf = svc._filters["split"]
            real_include = mf.filter.include_batch

            def poisoned_include(keys):
                if any(k.startswith(b"pr-") for k in keys):
                    raise RuntimeError("presence-part boom")
                return real_include(keys)

            mf.filter.include_batch = poisoned_include
            keys_plain = [b"pl-%d" % j for j in range(64)]
            keys_pres = [b"pr-%d" % j for j in range(64)]
            outcome = {}

            def plain():
                with s.client() as c:
                    try:
                        c.insert_batch(
                            "split", keys_plain,
                            min_replicas=1, min_replicas_timeout_ms=400,
                        )
                        outcome["plain"] = "ok"
                    except protocol.BloomServiceError as e:
                        outcome["plain"] = e

            def pres():
                with s.client() as c:
                    try:
                        c.insert_batch(
                            "split", keys_pres, return_presence=True
                        )
                        outcome["pres"] = "ok"
                    except protocol.BloomServiceError as e:
                        outcome["pres"] = e

            _threads([plain, pres])
            perr = outcome["pres"]
            assert isinstance(perr, protocol.BloomServiceError)
            assert perr.code == "INTERNAL"
            err = outcome["plain"]
            assert isinstance(err, protocol.BloomServiceError), (
                f"plain write got {err!r}; must reach its own barrier"
            )
            assert err.code == "NOT_ENOUGH_REPLICAS", (
                f"plain write must get its quorum verdict, got {err.code}"
            )
            assert err.details["applied"] is True
            mf.filter.include_batch = real_include
            assert admin.include_batch("split", keys_plain).all()
            assert not admin.include_batch("split", keys_pres).any(), (
                "the failed presence part must not have applied"
            )
    finally:
        s.stop()
        svc.oplog.close()


# -- drain/demotion interplay -------------------------------------------------


def test_shutdown_completes_parked_requests():
    """Drain semantics: requests parked at shutdown complete normally
    (their writers were admitted before the drain began)."""
    svc = BloomService(
        coalesce=CoalesceConfig(max_keys=1 << 20, max_wait_us=300_000)
    )
    s = _Server(svc)
    with s.client() as c:
        c.create_filter("park", capacity=100_000, error_rate=0.01)
        got = {}

        def writer():
            with s.client() as w:
                got["n"] = w.insert_batch("park", [b"p-%d" % j for j in range(8)])

        t = threading.Thread(target=writer)
        t.start()
        time.sleep(0.1)  # let it park (flush deadline is 300ms away)
        svc._coalescer.close()  # the drain path flushes parked entries
        t.join(timeout=10)
        assert not t.is_alive() and got.get("n") == 8
        # post-close submissions fall back to the direct path
        assert c.insert_batch("park", [b"direct"]) == 1
    s.stop()


def test_demotion_drains_parked_coalesced_writes(tmp_path):
    """A write PARKED in the coalescer passed the READONLY fence but
    holds no filter lock — ``become_replica``'s take-every-lock barrier
    alone would miss it. The drain hook must flush it into the OLD seq
    space before the applier takes the log over (an acked write must
    never vanish from the log across a demotion)."""
    from tpubloom.ha.promotion import become_replica
    from tpubloom.repl import OpLog

    svc = BloomService(
        oplog=OpLog(str(tmp_path / "log")),
        # flush deadline far away: the demotion must not wait it out
        coalesce=CoalesceConfig(max_keys=1 << 20, max_wait_us=400_000),
    )
    s = _Server(svc)
    try:
        with s.client() as c:
            c.create_filter("d", capacity=100_000, error_rate=0.01)
            got = {}

            def writer():
                with s.client() as w:
                    got["n"] = w.insert_batch(
                        "d", [b"parked-%d" % j for j in range(4)]
                    )

            t = threading.Thread(target=writer)
            t.start()
            time.sleep(0.15)  # let it park
            become_replica(svc, "127.0.0.1:1")  # demote NOW
            t.join(timeout=10)
            assert got.get("n") == 4, "the parked write must complete"
            logged_keys = {
                k
                for r in svc.oplog.read_from(0)
                if r["method"] == "InsertBatch"
                for k in r["req"].get("keys", [])
            }
            assert b"parked-0" in logged_keys, (
                "the drained flush must have LOGGED before the applier "
                "took the log over"
            )
    finally:
        s.stop()
        svc.oplog.close()


# -- tier-1 smoke over the load generator -------------------------------------


def test_ingest_load_smoke():
    """The ISSUE-10 acceptance bench: N coalesced connections beat one
    connection >= 2x AND the quorum run amortizes barriers across
    flushes (asserted inside run_load)."""
    import sys

    sys.path.insert(
        0,
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "benchmarks"),
    )
    import ingest_load

    out = ingest_load.run_load(duration_s=1.5)
    assert out["aggregate_keys_per_sec"] > out["single_conn_keys_per_sec"]
    assert out["wait_barrier_observations"] < out["quorum_write_requests"]


# -- delete/clear coalescing (ISSUE 12 satellite — the PR-10 seam) ------------


def test_concurrent_deletes_coalesce_exactly_once(coalesced_server):
    """N clients' deletes coalesce into shared delete-only flushes — one
    launch + one merged log record per flush — and a counting filter
    proves exactly-once: every key inserted twice and deleted once must
    still be present, deleted twice must be gone."""
    s = coalesced_server
    with s.client() as admin:
        admin.create_filter(
            "dcnt", capacity=200_000, error_rate=0.01, counting=True
        )
        all_keys = [b"dk-%d-%d" % (t, j) for t in range(6) for j in range(40)]
        admin.insert_batch("dcnt", all_keys)
        admin.insert_batch("dcnt", all_keys)  # count 2 per key
        f0 = admin.stats()["counters"].get("ingest_delete_flushes", 0)

        def deleter(t):
            def go():
                with s.client() as c:
                    keys = [b"dk-%d-%d" % (t, j) for j in range(40)]
                    c.delete_batch("dcnt", keys)
            return go

        _threads([deleter(t) for t in range(6)])
        counters = admin.stats()["counters"]
        flushes = counters.get("ingest_delete_flushes", 0) - f0
        assert flushes >= 1, "deletes never rode a delete-only flush"
        # count 2 - 1 = 1: a double-applied (or lost) delete flips this
        assert admin.include_batch("dcnt", all_keys).all(), (
            "a coalesced delete applied more than once"
        )
        _threads([deleter(t) for t in range(6)])
        gone = int(admin.include_batch("dcnt", all_keys).sum())
        assert gone == 0, f"{gone} keys survived two delete rounds"


def test_clear_coalesces_to_one_apply(coalesced_server):
    """Concurrent Clears park and collapse to ONE clear + ONE log
    append; the filter is empty afterwards and every caller gets ok."""
    s = coalesced_server
    with s.client() as admin:
        admin.create_filter("clr", capacity=100_000, error_rate=0.01)
        admin.insert_batch("clr", [b"c-%d" % i for i in range(128)])
        c0 = admin.stats()["counters"].get("ingest_clear_flushes", 0)

        def clearer():
            with s.client() as c:
                c.clear("clr")

        _threads([clearer for _ in range(5)])
        counters = admin.stats()["counters"]
        flushes = counters.get("ingest_clear_flushes", 0) - c0
        assert flushes >= 1, "clears never rode a clear-only flush"
        assert not admin.include_batch(
            "clr", [b"c-%d" % i for i in range(128)]
        ).any()


def test_coalesced_delete_replays_from_dedup(coalesced_server):
    """A same-rid retry of a coalesced delete answers from the dedup
    cache (deletes are decrements — a replay would double-apply)."""
    s = coalesced_server
    svc = s.service
    with s.client() as admin:
        admin.create_filter(
            "ddup", capacity=100_000, error_rate=0.01, counting=True
        )
        keys = [b"rk-%d" % i for i in range(32)]
        admin.insert_batch("ddup", keys)
        req = {"name": "ddup", "keys": keys, "rid": "delete-rid-1"}
        r1 = svc.DeleteBatch(dict(req))
        assert r1["ok"]
        hits0 = svc.metrics.snapshot()["counters"].get("delete_dedup_hits", 0)
        r2 = svc.DeleteBatch(dict(req))  # same-rid replay
        assert r2["ok"] and r2.get("n") == r1.get("n")
        hits1 = svc.metrics.snapshot()["counters"].get("delete_dedup_hits", 0)
        assert hits1 == hits0 + 1, "replayed delete must hit the dedup cache"
        # count 1 - 1 = 0, and NOT -1 twice: keys simply absent now
        assert not admin.include_batch("ddup", keys).any()
