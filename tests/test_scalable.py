"""Scalable (layered) bloom filter tests — growth policy, FPR bound, and
device-vs-CPU-oracle parity (SURVEY.md §2.3 scalable/layered variant)."""

import numpy as np
import pytest

from tpubloom.config import FilterConfig
from tpubloom.scalable import (
    CPUScalableBloomFilter,
    ScalableBloomFilter,
    layer_config,
)


def _rand_keys(n, rng, nbytes=16):
    return [rng.bytes(nbytes) for _ in range(n)]


def test_layer_config_policy():
    base = FilterConfig(m=64, k=1, seed=1234)
    c0, cap0 = layer_config(base, 1000, 0.01, 0)
    c1, cap1 = layer_config(base, 1000, 0.01, 1)
    c2, cap2 = layer_config(base, 1000, 0.01, 2)
    assert (cap0, cap1, cap2) == (1000, 2000, 4000)
    # tightening halves the per-layer error rate -> more bits per key and
    # larger k on deeper layers
    assert c1.m >= c0.m and c2.m >= c1.m
    assert c2.k >= c0.k
    # layer seeds differ (independent hash families)
    assert len({c0.seed, c1.seed, c2.seed}) == 3
    # m is a power of two (device fast path)
    for c in (c0, c1, c2):
        assert c.m & (c.m - 1) == 0


def test_no_false_negatives_across_growth():
    rng = np.random.default_rng(0)
    f = ScalableBloomFilter(500, 0.01)
    keys = _rand_keys(2600, rng)  # forces several growths past 500/1000 caps
    f.insert_batch(keys)
    assert f.n_layers >= 3
    assert f.include_batch(keys).all(), "scalable filter lost keys across layers"


def test_growth_splits_batches_at_capacity():
    rng = np.random.default_rng(1)
    f = ScalableBloomFilter(100, 0.01)
    f.insert_batch(_rand_keys(95, rng))
    assert f.n_layers == 1
    f.insert_batch(_rand_keys(10, rng))  # 95 + 10 > 100 -> split, push layer
    assert f.n_layers == 2
    s = f.stats()
    assert s["count_current_layer"] == 5
    assert s["capacity_current_layer"] == 200


def test_compound_fpr_within_bound():
    rng = np.random.default_rng(2)
    f = ScalableBloomFilter(2000, 0.01)
    f.insert_batch(_rand_keys(7000, rng))  # 3+ layers, all at design load
    absent = _rand_keys(20000, rng)
    fpr = f.include_batch(absent).mean()
    # compound bound: sum p*r^i < p/(1-r) = 2% for r=0.5; allow sampling slack
    assert fpr < 2.5 * f.compound_fpr_bound() + 0.005
    assert f.compound_fpr_bound() < 0.02


def test_parity_device_vs_cpu_oracle():
    """Same inserts -> identical layer stacks and identical membership."""
    rng = np.random.default_rng(3)
    keys = _rand_keys(1300, rng) + [b"", b"x", b"tpubloom-scal"]
    dev = ScalableBloomFilter(400, 0.02)
    cpu = CPUScalableBloomFilter(400, 0.02, use_native=False)
    for start in range(0, len(keys), 250):  # staggered batches
        chunk = keys[start : start + 250]
        dev.insert_batch(chunk)
        cpu.insert_batch(chunk)
    assert dev.n_layers == cpu.n_layers
    for dl, cl in zip(dev.layers, cpu.layers):
        assert dl.config == cl.config
        np.testing.assert_array_equal(dl.words_logical, cl.words)
    probe = keys + _rand_keys(1500, rng)
    np.testing.assert_array_equal(dev.include_batch(probe), cpu.include_batch(probe))


def test_clear_resets_to_single_layer():
    rng = np.random.default_rng(4)
    f = ScalableBloomFilter(100, 0.01)
    f.insert_batch(_rand_keys(350, rng))
    assert f.n_layers > 1
    f.clear()
    assert f.n_layers == 1 and f.n_inserted == 0
    assert not f.include_batch(_rand_keys(50, rng)).any()


def test_invalid_params_rejected():
    with pytest.raises(ValueError):
        ScalableBloomFilter(0, 0.01)
    with pytest.raises(ValueError):
        ScalableBloomFilter(100, 1.5)
    with pytest.raises(ValueError):
        ScalableBloomFilter(100, 0.01, growth=1)
    with pytest.raises(ValueError):
        ScalableBloomFilter(100, 0.01, tightening=1.0)


def test_blocked_layers_parity():
    """A blocked base config builds blocked layers on both variants and
    keeps them bit-interchangeable through growth."""
    import numpy as np

    from tpubloom import CPUScalableBloomFilter, FilterConfig, ScalableBloomFilter
    from tpubloom.filter import BlockedBloomFilter

    base = FilterConfig(m=512, k=1, key_len=16, block_bits=512)
    f = ScalableBloomFilter(500, 0.01, config=base)
    o = CPUScalableBloomFilter(500, 0.01, config=base, use_native=False)
    assert isinstance(f.layers[0], BlockedBloomFilter)
    rng = np.random.default_rng(5)
    keys = [rng.bytes(16) for _ in range(3000)]  # several growth steps
    f.insert_batch(keys)
    o.insert_batch(keys)
    assert len(f.layers) == len(o.layers) > 1
    for df, dc in zip(f.layers, o.layers):
        np.testing.assert_array_equal(df.words_logical, dc.words)
    probe = keys[:200] + [rng.bytes(16) for _ in range(800)]
    np.testing.assert_array_equal(f.include_batch(probe), o.include_batch(probe))
