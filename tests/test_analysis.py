"""Tests for the ISSUE-6 correctness tooling itself.

Two layers, both tested with SEEDED violations (a fixture the analyzer
must flag) and clean counterparts (which it must not):

* :mod:`tpubloom.analysis.lint` — the static AST lint. Fixture sources
  with a blocking call under a lock, a notify-before-append ordering
  bug, an unregistered fault point, an undeclared metric, and an orphan
  protocol method must each produce exactly the expected finding; the
  suppression grammar (mandatory reason, unknown check, unused allow)
  is itself linted. The real tree must lint CLEAN — that assertion IS
  the tier-1 acceptance gate for this PR.
* :mod:`tpubloom.utils.locks` — the runtime lock-order / held-while-
  blocking tracker. A seeded lock-order cycle, a two-instance self
  cycle, a ``Condition.wait`` under a foreign lock, and a
  ``note_blocking`` under a lock must each be flagged; consistent
  orderings, RLock re-entry and allowlisted (reasoned) holds must not.
  The subprocess exit-report plumbing the chaos suites rely on is
  exercised with a real child process.
"""

import json
import os
import subprocess
import sys
import textwrap
import threading

import pytest

from tpubloom.analysis import lint as L
from tpubloom.utils import locks

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# tiny injected registries so the fixtures do not depend on the real
# vocabulary (and the lint's tree mode stays off)
CONFIG_KW = dict(
    known_fault_points=frozenset({"ckpt.write", "rpc.pre_handle"}),
    counters=frozenset({"keys_inserted"}),
    gauges=frozenset({"ha_epoch"}),
    tree_checks=False,
)


def _lint_source(tmp_path, source, name="fixture.py", **overrides):
    path = tmp_path / name
    path.write_text(textwrap.dedent(source))
    config = L.LintConfig(**{**CONFIG_KW, **overrides})
    return L.lint_paths([str(path)], config)


def _checks(findings):
    return sorted(f.check for f in findings)


# -- static lint: seeded violations -------------------------------------------


def test_blocking_under_lock_flagged(tmp_path):
    findings = _lint_source(
        tmp_path,
        """
        import os

        class S:
            def bad_io(self):
                with self._lock:
                    os.fsync(3)

            def bad_wait(self):
                with self._lock:
                    self._cond.wait()
        """,
    )
    assert _checks(findings) == ["blocking-under-lock"] * 2
    assert "os.fsync" in findings[0].message


def test_barrier_under_lock_gets_its_own_check(tmp_path):
    """ISSUE 13: ``wait_acked``/``commit_barrier`` under a lock is the
    PR-5 invariant with its own name now — previously folded into
    blocking-under-lock, previously prose."""
    findings = _lint_source(
        tmp_path,
        """
        class S:
            def bad_quorum(self):
                with self.lock:
                    self.sessions.wait_acked(1, 1, 5.0)

            def bad_barrier(self):
                with self._lock:
                    self.commit_barrier(req, resp)

            def ok_outside(self):
                self.sessions.wait_acked(1, 1, 5.0)
                return self.commit_barrier(req, resp)
        """,
    )
    assert _checks(findings) == ["barrier-outside-lock"] * 2
    assert "PR-5" in findings[0].message


def test_bounded_wait_on_own_condition_clean(tmp_path):
    findings = _lint_source(
        tmp_path,
        """
        class S:
            def ok(self):
                with self._cond:
                    self._cond.wait(0.5)

            def ok_kw(self):
                with self._cond:
                    self._cond.wait_for(lambda: True, timeout=1.0)

            def ok_outside(self):
                self.sessions.wait_acked(1, 1, 5.0)
        """,
    )
    assert findings == []


def test_nested_function_does_not_inherit_lock_region(tmp_path):
    # a closure DEFINED under a lock runs when called, not where it is
    # written — it must not be treated as blocking-under-lock
    findings = _lint_source(
        tmp_path,
        """
        import os

        class S:
            def ok(self):
                with self._lock:
                    def flush_later():
                        os.fsync(3)
                    self.defer(flush_later)
        """,
    )
    assert findings == []


def test_notify_before_append_flagged(tmp_path):
    findings = _lint_source(
        tmp_path,
        """
        class S:
            def bad(self, rec):
                self.checkpointer.notify_inserts(3)
                self.oplog.append(rec)

            def good(self, rec):
                self.oplog.append(rec)
                self.checkpointer.notify_inserts(3)
        """,
    )
    assert _checks(findings) == ["notify-before-append"]
    assert "repl_seq" in findings[0].message


def test_unregistered_fault_point_flagged(tmp_path):
    findings = _lint_source(
        tmp_path,
        """
        from tpubloom import faults

        def f():
            faults.fire("ckpt.write")        # declared: clean
            faults.fire("definitely.not.declared")
        """,
    )
    assert _checks(findings) == ["fault-registry"]
    assert "definitely.not.declared" in findings[0].message


def test_undeclared_metric_flagged(tmp_path):
    findings = _lint_source(
        tmp_path,
        """
        from tpubloom.obs import counters

        def f(metrics):
            counters.incr("keys_inserted")   # declared counter: clean
            counters.set_gauge("ha_epoch", 1.0)  # declared gauge: clean
            counters.incr("mystery_series")
            counters.set_gauge("keys_inserted", 2.0)  # kind mismatch
        """,
    )
    assert _checks(findings) == ["metric-registry", "metric-registry"]
    by_msg = sorted(f.message for f in findings)
    assert "not declared" in by_msg[1]
    assert "other kind" in by_msg[0]


def test_orphan_protocol_method_flagged(tmp_path):
    # a fake repo tree whose protocol declares a method nothing implements
    server = tmp_path / "tpubloom" / "server"
    tests_dir = tmp_path / "tests"
    server.mkdir(parents=True)
    tests_dir.mkdir()
    (server / "protocol.py").write_text(
        'METHODS = ("Ping", "Ghost")\nSTREAM_METHODS = ("Watch",)\n'
    )
    (server / "service.py").write_text(
        textwrap.dedent(
            """
            class BloomService:
                def Ping(self, req):
                    return {"ok": True}

            _STREAM_BEHAVIORS = {}
            """
        )
    )
    (server / "client.py").write_text('_X = "Ping"\n')
    (tests_dir / "test_protocol_golden.py").write_text('_Y = "Ping"\n')
    findings = L.check_protocol_coverage(str(tmp_path))
    missing = sorted(f.message for f in findings)
    assert len(missing) == 5, missing  # Ghost x3, Watch x2
    assert sum("'Ghost'" in m for m in missing) == 3
    assert sum("'Watch'" in m for m in missing) == 2
    assert any("handler" in m for m in missing)
    assert any("golden" in m for m in missing)


def test_ruby_parity_flags_uncovered_and_stale(tmp_path):
    """ISSUE 12 satellite: a protocol method with no Ruby call site, one
    missing from the Ruby METHODS registry, and a stale registry entry
    must each produce exactly one finding; a covered method none."""
    server = tmp_path / "tpubloom" / "server"
    server.mkdir(parents=True)
    (server / "protocol.py").write_text('METHODS = ("Ping", "Ghost")\n')
    driver = tmp_path / L.RUBY_DRIVER_DIR
    driver.mkdir(parents=True)
    (driver / "jax.rb").write_text(
        'METHODS = %w[Ping Stale].freeze\n'
        'def ping; rpc("Ping", {}); end\n'
    )
    msgs = sorted(f.message for f in L.check_ruby_parity(str(tmp_path)))
    assert len(msgs) == 3, msgs
    assert sum("'Ghost'" in m and "call site" in m for m in msgs) == 1
    assert sum("'Ghost'" in m and "registry" in m for m in msgs) == 1
    assert sum("'Stale'" in m for m in msgs) == 1
    assert not any("'Ping'" in m for m in msgs)


def test_ruby_parity_clean_on_real_tree():
    """The real drivers cover the real protocol — part of the clean-tree
    acceptance gate (the analysis CI job runs the same check)."""
    assert L.check_ruby_parity(REPO) == []


# -- static lint: the ISSUE-13 checks ------------------------------------------


def test_donation_safety_flags_use_after_donate(tmp_path):
    """A name passed at a donated position and read again without a
    rebind is the PR-10 InFlight fence bug class; the rebind-from-the-
    call idiom (``blocks = fn(..., blocks)``) is clean."""
    findings = _lint_source(
        tmp_path,
        """
        import functools
        import jax
        import jax.experimental.pallas as pl

        def bad_kernel(starts, upd, blocks):
            fn = pl.pallas_call(kern, input_output_aliases={2: 0})
            out = fn(starts, upd, blocks)
            return blocks.sum() + out

        def good_kernel(starts, upd, blocks):
            fn = pl.pallas_call(kern, input_output_aliases={2: 0})
            blocks = fn(starts, upd, blocks)
            return blocks.sum()

        class F:
            def __init__(self, config):
                self._insert = jax.jit(make_fn(config), donate_argnums=0)

            def bad_insert(self, keys):
                out = self._insert(self.words, keys)
                return self.words

            def good_insert(self, keys):
                self.words = self._insert(self.words, keys)
                return self.words
        """,
    )
    assert _checks(findings) == ["donation-safety"] * 2
    assert "'blocks'" in findings[0].message
    assert "'self.words'" in findings[1].message


def test_donation_safety_suppression(tmp_path):
    findings = _lint_source(
        tmp_path,
        """
        import jax.experimental.pallas as pl

        def ok(starts, upd, blocks):
            fn = pl.pallas_call(kern, input_output_aliases={2: 0})
            out = fn(starts, upd, blocks)
            return blocks.shape + out  # lint: allow(donation-safety): .shape reads host metadata, never the donated device buffer
        """,
    )
    assert findings == []


def test_replay_safety_flags_uncached_mutating_handler(tmp_path):
    """A MUTATING_METHODS handler that never touches the dedup cache is
    flagged; one that does (or carries a reasoned allow on the def
    line) is clean."""
    server = tmp_path / "tpubloom" / "server"
    server.mkdir(parents=True)
    (server / "protocol.py").write_text(
        'MUTATING_METHODS = frozenset({"InsertBatch", "Clear", "Drop"})\n'
    )
    (server / "service.py").write_text(
        textwrap.dedent(
            """
            class BloomService:
                def InsertBatch(self, req):
                    cached = self._dedup_get(req.get("rid"))
                    if cached is not None:
                        return cached
                    resp = {"ok": True}
                    self._dedup_put(req.get("rid"), resp)
                    return resp

                def Clear(self, req):  # lint: allow(replay-safety): clearing twice is cleared
                    return {"ok": True}

                def Drop(self, req):
                    return {"ok": True}

                def QueryBatch(self, req):
                    return {"ok": True}
            """
        )
    )
    findings = L.check_replay_safety(str(tmp_path))
    # raw check: Drop AND Clear flagged (suppressions resolve in
    # lint_paths) — but only the mutating set, never QueryBatch
    assert sorted(f.message.split("(")[0] for f in findings) == [
        "mutating handler Clear", "mutating handler Drop",
    ]
    # through the full pipeline the def-line allow silences Clear
    config = L.LintConfig(
        **{**CONFIG_KW, "tree_checks": True, "repo_root": str(tmp_path)}
    )
    piped = [
        f
        for f in L.lint_paths([str(server / "service.py")], config)
        if f.check == "replay-safety"
    ]
    assert len(piped) == 1 and "Drop" in piped[0].message


def test_chaos_coverage_flags_unarmed_points(tmp_path):
    """A KNOWN_POINTS entry with no arm literal and no TPUBLOOM_FAULTS
    string in tests/ is dead chaos surface; armed ones (either way) and
    suppressed ones are clean."""
    faults_dir = tmp_path / "tpubloom" / "faults"
    tests_dir = tmp_path / "tests"
    faults_dir.mkdir(parents=True)
    tests_dir.mkdir()
    # fabricated point names throughout: this file itself lives under
    # tests/, so REAL names here would satisfy the real tree's arming
    # scan and mask a deleted armed test (found by review)
    (faults_dir / "__init__.py").write_text(
        textwrap.dedent(
            """
            KNOWN_POINTS = {
                "zz.armed_by_call",
                "zz.armed_by_env",
                "zz.dead_point",
                "zz.covered_elsewhere",  # lint: allow(chaos-coverage): driven by the exporter's own harness, not pytest
            }
            """
        )
    )
    (tests_dir / "test_x.py").write_text(
        textwrap.dedent(
            """
            from tpubloom import faults

            def test_a(monkeypatch):
                faults.arm("zz.armed_by_call", "once")
                monkeypatch.setenv("TPUBLOOM_FAULTS", "zz.armed_by_env=nth:2")
            """
        )
    )
    findings = L.check_chaos_coverage(str(tmp_path))
    # raw check: the suppression resolves in lint_paths, so both
    # unarmed points surface here
    assert sorted(
        f.message.split("'")[1] for f in findings
    ) == ["zz.covered_elsewhere", "zz.dead_point"]
    assert all(f.line > 0 for f in findings)  # anchored on declarations
    config = L.LintConfig(
        **{**CONFIG_KW, "tree_checks": True, "repo_root": str(tmp_path)}
    )
    piped = L.lint_paths([str(faults_dir / "__init__.py")], config)
    by_check = [f for f in piped if f.check == "chaos-coverage"]
    assert len(by_check) == 1 and "zz.dead_point" in by_check[0].message


def test_chaos_coverage_credits_benchmark_arming(tmp_path):
    """ISSUE 15 satellite (the ROADMAP item 6 seam): a point armed only
    by a benchmark harness's TPUBLOOM_FAULTS string (or faults.arm)
    under benchmarks/ is covered, not dead surface."""
    faults_dir = tmp_path / "tpubloom" / "faults"
    tests_dir = tmp_path / "tests"
    bench_dir = tmp_path / "benchmarks"
    faults_dir.mkdir(parents=True)
    tests_dir.mkdir()
    bench_dir.mkdir()
    (faults_dir / "__init__.py").write_text(
        textwrap.dedent(
            """
            KNOWN_POINTS = {
                "zz.bench_env",
                "zz.bench_call",
                "zz.still_dead",
            }
            """
        )
    )
    (bench_dir / "load_harness.py").write_text(
        textwrap.dedent(
            """
            import os
            from tpubloom import faults

            def run():
                os.environ["TPUBLOOM_FAULTS"] = "zz.bench_env=nth:3"
                faults.arm("zz.bench_call", "once")
            """
        )
    )
    findings = L.check_chaos_coverage(str(tmp_path))
    assert [f.message.split("'")[1] for f in findings] == ["zz.still_dead"]
    assert "benchmark" in findings[0].message


def test_phase_registry_flags_undeclared_and_bad_dynamic(tmp_path):
    findings = _lint_source(
        tmp_path,
        """
        from tpubloom import obs

        def f(ctx, i):
            with obs.phase("kernel"):          # declared: clean
                pass
            with obs.phase("kernel_mystery"):  # not declared
                pass
            ctx.add_phase(f"kernel_shard{i}", 0.1)   # declared prefix: clean
            ctx.add_phase(f"mystery_shard{i}", 0.1)  # undeclared prefix
        """,
        phases=frozenset({"kernel"}),
        phase_prefixes=("kernel_shard",),
    )
    assert _checks(findings) == ["phase-registry"] * 2
    msgs = sorted(f.message for f in findings)
    assert "'mystery_shard'" in msgs[0]
    assert "'kernel_mystery'" in msgs[1]


def test_trace_registry_flags_undeclared_spans_and_events(tmp_path):
    """ISSUE 15: the phase-registry pattern extended to the tracing span
    vocabulary and the flight-recorder event vocabulary."""
    findings = _lint_source(
        tmp_path,
        """
        from tpubloom.obs import flight, trace

        def f(rid, method):
            with trace.span("good.span"):              # declared: clean
                pass
            with trace.span("mystery.span"):           # not declared
                pass
            trace.record_span(f"rpc.{method}", rid=rid,
                              start=0.0, duration_s=0.0)  # prefix: clean
            trace.record_span(f"zz.{method}", rid=rid,
                              start=0.0, duration_s=0.0)  # bad prefix
            flight.note("good_event", x=1)             # declared: clean
            flight.note("mystery_event")               # not declared
        """,
        spans=frozenset({"good.span"}),
        span_prefixes=("rpc.",),
        events=frozenset({"good_event"}),
    )
    assert _checks(findings) == ["trace-registry"] * 3
    msgs = " | ".join(sorted(f.message for f in findings))
    assert "'mystery.span'" in msgs
    assert "'zz.'" in msgs
    assert "'mystery_event'" in msgs


def test_trace_registry_reverse_check(tmp_path):
    """Tree mode: declared spans/prefixes/events nobody emits are stale
    vocabulary entries."""
    pkg = tmp_path / "tpubloom" / "obs"
    pkg.mkdir(parents=True)
    (pkg / "names.py").write_text(
        'SPANS = ("client.hop", "ghost.span")\n'
        'SPAN_DYNAMIC_PREFIXES = (("rpc.", "roots"), ("zz.", "ghost"),)\n'
        'EVENTS = ("shed", "ghost_event")\n'
    )
    src = tmp_path / "emit.py"
    src.write_text(
        textwrap.dedent(
            """
            from tpubloom.obs import flight, trace

            def f(rid, method):
                with trace.span("client.hop"):
                    pass
                trace.record_span(f"rpc.{method}", rid=rid,
                                  start=0.0, duration_s=0.0)
                flight.note("shed")
            """
        )
    )
    config = L.LintConfig(
        **{
            **{k: v for k, v in CONFIG_KW.items() if k != "tree_checks"},
            "tree_checks": True,
            "repo_root": str(tmp_path),
        }
    )
    findings = L.lint_paths([str(src)], config)
    tr = sorted(
        f.message for f in findings if f.check == "trace-registry"
    )
    assert len(tr) == 3
    assert "'ghost.span'" in tr[2] or "'ghost.span'" in " ".join(tr)
    assert any("'zz.'" in m for m in tr)
    assert any("'ghost_event'" in m for m in tr)


def test_phase_registry_reverse_check(tmp_path):
    """Tree mode: a declared phase nobody emits is a stale vocabulary
    entry (the counter-registry pattern extended to phases)."""
    pkg = tmp_path / "tpubloom" / "obs"
    pkg.mkdir(parents=True)
    (pkg / "names.py").write_text(
        'PHASES = ("decode", "ghost_phase")\n'
        'PHASE_DYNAMIC_PREFIXES = (("kernel_shard", "per-device"),)\n'
    )
    src = tmp_path / "emit.py"
    src.write_text(
        "def f(ctx, i):\n"
        '    with obs.phase("decode"):\n'
        "        pass\n"
        '    ctx.add_phase(f"kernel_shard{i}", 0.1)\n'
    )
    config = L.LintConfig(
        **{
            **{k: v for k, v in CONFIG_KW.items() if k != "tree_checks"},
            "tree_checks": True,
            "repo_root": str(tmp_path),
        }
    )
    findings = L.lint_paths([str(src)], config)
    phase_findings = [f for f in findings if f.check == "phase-registry"]
    assert len(phase_findings) == 1
    assert "'ghost_phase'" in phase_findings[0].message


# -- static lint: the suppression grammar --------------------------------------


def test_reasoned_suppression_silences(tmp_path):
    findings = _lint_source(
        tmp_path,
        """
        import os

        class S:
            def allowed(self):
                with self._lock:
                    os.fsync(3)  # lint: allow(blocking-under-lock): fsync of a 12-byte marker; bounded and rare
        """,
    )
    assert findings == []


def test_suppression_on_with_line_covers_the_region(tmp_path):
    findings = _lint_source(
        tmp_path,
        """
        import os

        class S:
            def allowed(self):
                with self._lock:  # lint: allow(blocking-under-lock): the whole region is a cold shutdown path
                    os.fsync(3)
        """,
    )
    assert findings == []


def test_reasonless_suppression_is_a_finding(tmp_path):
    findings = _lint_source(
        tmp_path,
        """
        import os

        class S:
            def bad(self):
                with self._lock:
                    os.fsync(3)  # lint: allow(blocking-under-lock)
        """,
    )
    # the allow is VOID (no reason), so the original finding stands too
    assert _checks(findings) == ["blocking-under-lock", "suppression-reason"]


def test_unknown_and_unused_suppressions_are_findings(tmp_path):
    findings = _lint_source(
        tmp_path,
        """
        X = 1  # lint: allow(not-a-check): whatever
        Y = 2  # lint: allow(fault-registry): nothing here triggers it
        """,
    )
    assert _checks(findings) == ["unknown-suppression", "unused-suppression"]


def test_docstring_mention_is_not_a_suppression(tmp_path):
    findings = _lint_source(
        tmp_path,
        '''
        def doc():
            """Write `# lint: allow(blocking-under-lock): why` inline."""
        ''',
    )
    assert findings == []


# -- static lint: CLI exit codes ----------------------------------------------


def test_cli_flags_seeded_fixture(tmp_path):
    bad = tmp_path / "seeded.py"
    bad.write_text(
        "from tpubloom import faults\n"
        'faults.fire("totally.unknown.point")\n'
    )
    proc = subprocess.run(
        [sys.executable, "-m", "tpubloom.analysis.lint",
         "--no-tree-checks", "--json", str(bad)],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    findings = json.loads(proc.stdout)
    assert [f["check"] for f in findings] == ["fault-registry"]


def test_ruff_gate():
    """Baseline style gate SCOPED to the analysis subsystem (the
    ``[tool.ruff]`` include in pyproject.toml): the new code starts
    clean. Config-only wiring in images without ruff — CI installs it
    via the ``dev`` extra and runs this for real."""
    pytest.importorskip("ruff")
    proc = subprocess.run(
        [sys.executable, "-m", "ruff", "check", "."],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_clean_on_the_real_tree():
    """THE acceptance gate: the shipped tree lints clean, suppressions
    included (a reasonless or stale allow fails this too)."""
    proc = subprocess.run(
        [sys.executable, "-m", "tpubloom.analysis.lint",
         os.path.join(REPO, "tpubloom")],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# -- runtime tracker: seeded violations ----------------------------------------


@pytest.fixture
def armed():
    locks.set_enabled(True)
    locks.reset()
    yield
    locks.reset()
    locks.set_enabled(None)


def test_lock_order_cycle_detected(armed):
    a = locks.named_lock("t.a")
    b = locks.named_lock("t.b")
    with a:
        with b:
            pass
    with b:
        with a:  # closes t.a -> t.b -> t.a
            pass
    vios = locks.violations()
    assert [v["kind"] for v in vios] == ["lock-order-cycle"]
    assert "t.a" in vios[0]["message"] and "t.b" in vios[0]["message"]


def test_consistent_order_is_clean(armed):
    a = locks.named_lock("t.a")
    b = locks.named_lock("t.b")
    for _ in range(3):
        with a:
            with b:
                pass
    assert locks.violations() == []
    rep = locks.report()
    assert [(e["from"], e["to"], e["count"]) for e in rep["edges"]] == [
        ("t.a", "t.b", 3)
    ]


def test_two_instances_same_name_is_a_cycle(armed):
    # every filter's op lock shares one NAME: nesting two instances is
    # the two-threads-opposite-order deadlock in single-threaded form
    f1 = locks.named_lock("t.filter_op")
    f2 = locks.named_lock("t.filter_op")
    with f1:
        with f2:
            pass
    vios = locks.violations()
    assert [v["kind"] for v in vios] == ["lock-order-cycle"]


def test_rlock_reentry_is_clean(armed):
    r = locks.named_rlock("t.r")
    with r:
        with r:
            pass
    assert locks.violations() == []


def test_cross_thread_cycle_detected(armed):
    # the real shape: two threads, opposite nesting orders, serialized
    # by events so the test itself cannot deadlock
    a = locks.named_lock("t.x")
    b = locks.named_lock("t.y")
    first_done = threading.Event()

    def t1():
        with a:
            with b:
                pass
        first_done.set()

    def t2():
        first_done.wait(5)
        with b:
            with a:
                pass

    th1, th2 = threading.Thread(target=t1), threading.Thread(target=t2)
    th1.start(); th2.start()
    th1.join(5); th2.join(5)
    assert [v["kind"] for v in locks.violations()] == ["lock-order-cycle"]


def test_condition_wait_under_foreign_lock_flagged(armed):
    lock = locks.named_lock("t.outer")
    cond = locks.named_condition("t.cond")
    with lock:
        with cond:
            cond.wait(timeout=0.01)
    vios = locks.violations()
    assert [v["kind"] for v in vios] == ["held-while-blocking"]
    assert "t.outer" in vios[0]["message"]


def test_wait_reports_once_despite_varying_timeouts(armed):
    # retry loops wait on a SHRINKING remaining budget; the violation
    # message must not embed the value or dedup is defeated and the
    # report floods (one entry per wakeup)
    lock = locks.named_lock("t.outer")
    cond = locks.named_condition("t.cond")
    with lock:
        with cond:
            cond.wait(timeout=0.01)
            cond.wait(timeout=0.02)
            # wait_for internally loops over self.wait() — the inner
            # dispatches must not re-report what wait_for checked
            cond.wait_for(lambda: False, timeout=0.03)
    assert len(locks.violations()) == 1, locks.violations()


def test_condition_wait_alone_is_clean(armed):
    cond = locks.named_condition("t.cond")
    with cond:
        cond.wait(timeout=0.01)
        cond.wait_for(lambda: True, timeout=0.01)
    assert locks.violations() == []


def test_note_blocking_under_lock_flagged(armed):
    lock = locks.named_lock("t.held")
    locks.note_blocking("t.op")  # no lock held: clean
    with lock:
        locks.note_blocking("t.op")
    vios = locks.violations()
    assert [v["kind"] for v in vios] == ["held-while-blocking"]
    assert "t.op" in vios[0]["message"]


def test_note_blocking_allowlist_needs_reason(armed):
    lock = locks.named_lock("t.held")
    with lock:
        locks.note_blocking(
            "t.op", allow=("t.held",), reason="cold path, nothing contends"
        )
    assert locks.violations() == []
    sup = locks.report()["suppressed"]
    assert len(sup) == 1 and sup[0]["reason"]
    with pytest.raises(ValueError, match="needs a reason"):
        locks.note_blocking("t.op", allow=("t.held",))


def test_violations_deduplicate(armed):
    a = locks.named_lock("t.a")
    b = locks.named_lock("t.b")
    with a:
        with b:
            pass
    for _ in range(50):
        with b:
            with a:
                pass
    assert len(locks.violations()) == 1  # a hot loop reports once


def test_disarmed_factories_return_bare_primitives():
    locks.set_enabled(False)
    try:
        bare = locks.named_lock("t.bare")
        assert type(bare).__module__ in ("_thread", "threading")
        assert not hasattr(bare, "name")
        # disarmed note_blocking is a no-op even under nothing
        locks.note_blocking("t.op", allow=("x",))  # reasonless allow: ignored
    finally:
        locks.set_enabled(None)


def test_subprocess_exit_report(tmp_path):
    """The chaos-suite plumbing: a child process armed via the env vars
    dumps a lockcheck-<pid>.json at exit; the seeded cycle is in it."""
    child = tmp_path / "child.py"
    child.write_text(
        textwrap.dedent(
            """
            from tpubloom.utils import locks

            a = locks.named_lock("child.a")
            b = locks.named_lock("child.b")
            with a:
                with b:
                    pass
            with b:
                with a:
                    pass
            """
        )
    )
    report_dir = tmp_path / "reports"
    env = {
        **os.environ,
        locks.ENV_VAR: "1",
        locks.REPORT_DIR_ENV: str(report_dir),
        "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }
    proc = subprocess.run(
        [sys.executable, str(child)], capture_output=True, text=True, env=env,
    )
    assert proc.returncode == 0, proc.stderr
    reports = list(report_dir.glob("lockcheck-*.json"))
    assert len(reports) == 1
    rep = json.loads(reports[0].read_text())
    kinds = [v["kind"] for v in rep["violations"]]
    assert kinds == ["lock-order-cycle"]
    assert "violation(s)" in proc.stderr  # printed to stderr too


# -- lock-order manifest (ISSUE 9 satellite, ROADMAP item 7) ------------------


def test_lock_order_manifest_diff():
    """An edge outside the declared manifest is a finding; declared
    edges are clean — the "new nesting is a reviewed decision" gate."""
    from tpubloom.analysis import lock_order

    assert lock_order.diff_edges([("filter.op", "repl.oplog")]) == []
    findings = lock_order.diff_edges(
        [("filter.op", "repl.oplog"), ("repl.oplog", "filter.op")]
    )
    assert len(findings) == 1
    assert findings[0]["kind"] == "undeclared-lock-edge"
    assert findings[0]["edge"] == ["repl.oplog", "filter.op"]
    # the report-dict form (the lockcheck-<pid>.json shape)
    report = {
        "edges": [
            {"from": "service.registry", "to": "repl.oplog", "count": 3},
            {"from": "obs.counters", "to": "service.registry", "count": 1},
        ],
        "violations": [],
        "suppressed": [],
    }
    findings = lock_order.check_report(report)
    assert [f["edge"] for f in findings] == [
        ["obs.counters", "service.registry"]
    ]


def test_lock_order_manifest_covers_cluster_ranks():
    """The ISSUE-9 seeding: the cluster lock class participates in the
    manifest — migration snapshots arm the dual-write under the filter
    lock, and cluster.state is otherwise a leaf."""
    from tpubloom.analysis import lock_order

    assert ("filter.op", "cluster.state") in lock_order.ALLOWED_EDGES
    assert ("cluster.state", "obs.counters") in lock_order.ALLOWED_EDGES
    # nothing is declared acquirable under cluster.state except the
    # counters bookkeeping — node→node RPCs must run lock-free
    inners = {
        inner for outer, inner in lock_order.ALLOWED_EDGES
        if outer == "cluster.state"
    }
    assert inners == {"obs.counters"}


def test_lock_order_cli(tmp_path, capsys):
    from tpubloom.analysis import lock_order

    clean = tmp_path / "lockcheck-1.json"
    clean.write_text(json.dumps({
        "edges": [{"from": "filter.op", "to": "repl.oplog", "count": 1}],
        "violations": [], "suppressed": [],
    }))
    assert lock_order.main([str(tmp_path)]) == 0
    capsys.readouterr()

    dirty = tmp_path / "lockcheck-2.json"
    dirty.write_text(json.dumps({
        "edges": [{"from": "repl.oplog", "to": "service.registry",
                   "count": 1}],
        "violations": [], "suppressed": [],
    }))
    assert lock_order.main([str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "undeclared-lock-edge" in out and "repl.oplog" in out

    assert lock_order.main(["--list"]) == 0
    listed = capsys.readouterr().out
    assert "filter.op -> repl.oplog" in listed


# -- unified driver (ISSUE 13 tentpole) ---------------------------------------


def test_unified_driver_clean_on_the_real_tree(tmp_path):
    """THE acceptance gate: ``python -m tpubloom.analysis`` exits 0 on
    the shipped tree with all checks enabled — static lint AND the
    lock-order diff over a (clean) runtime report."""
    report = tmp_path / "lockcheck-1.json"
    report.write_text(json.dumps({
        "edges": [{"from": "filter.op", "to": "repl.oplog", "count": 3}],
        "violations": [], "suppressed": [],
    }))
    proc = subprocess.run(
        [sys.executable, "-m", "tpubloom.analysis", "--json",
         "--reports", str(tmp_path)],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    result = json.loads(proc.stdout)
    assert result["lint"] == [] and result["lock_order"] == []
    assert result["reports_checked"] == 1


def test_unified_driver_fails_on_undeclared_edge_or_violation(tmp_path):
    """One exit code covers BOTH halves: an undeclared runtime edge (or
    a recorded violation) in any collected report fails the driver even
    though the static tree is clean."""
    report = tmp_path / "lockcheck-2.json"
    report.write_text(json.dumps({
        "edges": [{"from": "repl.oplog", "to": "filter.op", "count": 1}],
        "violations": [
            {"kind": "lock-order-cycle", "message": "t.a -> t.b -> t.a",
             "site": "x.py:1"},
        ],
        "suppressed": [],
    }))
    proc = subprocess.run(
        [sys.executable, "-m", "tpubloom.analysis", "--json",
         "--reports", str(tmp_path)],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    result = json.loads(proc.stdout)
    kinds = sorted(f["kind"] for f in result["lock_order"])
    assert kinds == ["runtime-lock-order-cycle", "undeclared-lock-edge"]


def test_unified_driver_explicit_empty_reports_is_a_finding(tmp_path):
    """CI wiring rot must not look like a pass: --reports pointing at a
    dir with no lockcheck files is itself a finding (while NO --reports
    and no env var runs the static half alone, exit 0 on clean)."""
    empty = tmp_path / "empty"
    empty.mkdir()
    proc = subprocess.run(
        [sys.executable, "-m", "tpubloom.analysis", "--json",
         "--reports", str(empty)],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 1
    result = json.loads(proc.stdout)
    assert [f["kind"] for f in result["lock_order"]] == ["no-reports"]


def test_manifest_prune_left_no_speculative_selfcontradictions():
    """ISSUE 13 spot checks on the pruned manifest: the applier's call
    lock guards stream/ack HANDLES only (its old apply-path edges are
    gone), and the truncation sweep's replica-cursor floor IS declared
    (the latent hole the audit closed)."""
    from tpubloom.analysis import lock_order

    E = lock_order.ALLOWED_EDGES
    assert ("repl.applier_call", "repl.ack_sender") in E
    assert ("repl.applier_call", "filter.op") not in E
    assert ("repl.applier_call", "repl.oplog") not in E
    assert ("filter.op", "repl.sessions") in E  # min_cursor under _log_op
    assert ("filter.op", "obs.metrics") in E   # truncation count
    # pruned X->obs.counters family: counters moved outside these locks
    for outer in ("faults.registry", "obs.slowlog", "service.dedup",
                  "ckpt.trigger", "repl.monitor_hub", "sentinel.topo_events"):
        assert (outer, "obs.counters") not in E, outer
