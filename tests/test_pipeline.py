"""Streaming pipeline + fault injection tests (BASELINE config 3 scaled
down; SURVEY.md §5 failure row: kill mid-stream, resume, bounded tail loss)."""

import numpy as np
import pytest

from tpubloom import BloomFilter, FilterConfig
from tpubloom import checkpoint as ckpt
from tpubloom.parallel.pipeline import StreamInserter, resume_offset


def _key_stream(start, stop):
    for i in range(start, stop):
        yield b"stream-key-%012d" % i


@pytest.fixture
def cfg():
    return FilterConfig(m=1 << 22, k=5, key_len=24, key_name="stream")


def test_stream_insert_all_present(cfg):
    f = BloomFilter(cfg)
    ins = StreamInserter(f, batch_size=1024)
    stats = ins.run(_key_stream(0, 10_000))
    assert stats["inserted"] == 10_000
    probe = list(_key_stream(0, 10_000))
    assert f.include_batch(probe).all()
    assert not f.include_batch([b"absent-%d" % i for i in range(1000)]).any()


def test_stream_partial_batches_and_limit(cfg):
    f = BloomFilter(cfg)
    ins = StreamInserter(f, batch_size=1000)
    stats = ins.run(_key_stream(0, 2500), limit=2300)  # forces ragged batches
    assert stats["inserted"] == 2300
    assert f.include_batch(list(_key_stream(0, 2300))).all()
    # reentrant continuation from the same iterator position semantics
    stats2 = ins.run(_key_stream(2300, 3000))
    assert stats2["stream_offset"] == 3000


def test_periodic_checkpoints_with_offsets(cfg, tmp_path):
    """VERDICT r5 Weak #1 deflake: one uninterrupted run() left the
    written-count assertion at the mercy of the writer thread keeping up
    under full-suite load (a trigger that fires while a write is in
    flight is deferred, so a lagging writer legally coalesces periodic
    checkpoints). Driving the stream in checkpoint_every-sized chunks
    and poll-syncing on flush() between chunks pins one landed write per
    interval without any wall-clock sleeps."""
    sink = ckpt.FileSink(str(tmp_path))
    f = BloomFilter(cfg)
    ins = StreamInserter(f, batch_size=500, sink=sink, checkpoint_every=2000)
    for lo in range(0, 10_000, 2000):
        ins.run(_key_stream(lo, lo + 2000))
        # event/poll sync: the interval's write must land before the next
        # chunk, making checkpoints_written deterministic under any load
        assert ins.checkpointer.flush(timeout=120), "checkpoint write stuck"
    assert ins.checkpointer.checkpoints_written == 5
    ins.close(final_checkpoint=True)
    assert ins.checkpointer.checkpoints_written >= 3
    g = ckpt.restore(cfg, sink)
    off = resume_offset(g)
    assert 0 < off <= 10_000
    # recovery contract: everything before the recorded offset is present
    assert g.include_batch(list(_key_stream(0, off))).all()


def test_crash_recovery_bounded_tail_loss(cfg, tmp_path):
    """Simulated crash: the process dies mid-stream (we just stop feeding
    and drop the objects without a final checkpoint). The newest checkpoint
    must cover its recorded offset, and replay from there reconverges."""
    sink = ckpt.FileSink(str(tmp_path))
    f = BloomFilter(cfg)
    ins = StreamInserter(f, batch_size=500, sink=sink, checkpoint_every=3000)
    ins.run(_key_stream(0, 8000))
    ins.checkpointer.flush()  # let the in-flight write land, then "crash"
    del f, ins

    g = ckpt.restore(cfg, sink)
    assert g is not None
    off = resume_offset(g)
    assert off >= 3000, "at least one periodic checkpoint must have landed"
    # Contract: tail loss ~ checkpoint_every + one batch. A trigger that
    # fires while the previous write is still in flight is deferred to the
    # next batch, so allow one extra interval of slack — under full-suite
    # load on the 1-core host the writer thread can lag that far.
    assert 8000 - off <= 2 * 3000 + 500, "tail loss must be bounded by the contract"
    assert g.include_batch(list(_key_stream(0, off))).all()

    # resume: replay from the offset (idempotent), continue to 12000
    ins2 = StreamInserter(
        g, batch_size=500, sink=sink, checkpoint_every=3000, start_offset=off
    )
    ins2.run(_key_stream(off, 12_000))
    ins2.close()
    assert g.include_batch(list(_key_stream(0, 12_000))).all()
    assert not g.include_batch([b"no-%d" % i for i in range(500)]).any()


def test_stream_into_sharded(cfg):
    from tpubloom.parallel.sharded import ShardedBloomFilter

    scfg = cfg.replace(shards=8, key_name="stream-sharded")
    f = ShardedBloomFilter(scfg)
    ins = StreamInserter(f, batch_size=512)
    ins.run(_key_stream(0, 4000))
    assert f.include_batch(list(_key_stream(0, 4000))).all()


def test_prefetch_overlap_identical_state(tmp_path):
    """prefetch (background pack + early H2D) must not change results:
    same stream -> bit-identical filter vs the synchronous path."""
    import numpy as np

    from tpubloom import BloomFilter, FilterConfig
    from tpubloom.parallel.pipeline import StreamInserter

    cfg = FilterConfig(m=1 << 18, k=5, key_len=16)
    rng = np.random.default_rng(42)
    keys = [rng.bytes(16) for _ in range(20_000)]
    a, b = BloomFilter(cfg), BloomFilter(cfg)
    sa = StreamInserter(a, batch_size=1 << 12).run(iter(keys))
    sb = StreamInserter(b, batch_size=1 << 12, prefetch=3).run(iter(keys))
    assert sa["inserted"] == sb["inserted"] == len(keys)
    np.testing.assert_array_equal(np.asarray(a.words), np.asarray(b.words))


def test_prefetch_propagates_pack_errors():
    import pytest as _pytest

    from tpubloom import BloomFilter, FilterConfig
    from tpubloom.parallel.pipeline import StreamInserter

    cfg = FilterConfig(m=1 << 16, k=4, key_len=16)  # key_policy=error
    f = BloomFilter(cfg)
    bad = [b"x" * 64]  # longer than key_len -> pack_keys raises
    with _pytest.raises(ValueError):
        StreamInserter(f, batch_size=8, prefetch=2).run(iter(bad))
