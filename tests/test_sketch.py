"""Sketch plane (ISSUE 19): cuckoo filters + count-min / top-k sketches
as pluggable filter kinds.

Layers covered:

* the cuckoo kernels — insert/query/delete round trips, the fixed-trip
  kick bound, the honest-FULL invariant (ok count == occupied slots:
  a failed kick chain unwinds its evictions EXACTLY, no collateral
  damage), multiset insert + one-copy delete;
* the count-min kernels — estimates are an upper bound on the exact
  multiset counts, within the classic 2N/width error bound for the
  configured depth, duplicate keys within one batch accumulate;
* config + kind registry — validation, identity round trips, old
  (pre-kind) checkpoint headers defaulting to "bloom";
* checkpoint round trips per kind, including the top-k heap riding the
  header's extra block;
* the inherited planes — sketch kinds coalesce (keys_fixed demux,
  per-request FULL slices), replicate under a ``min_replicas=1``
  quorum, and migrate via ``MigrateSlot`` with counts intact;
* fault points — ``cuckoo.kick`` / ``cms.update`` fire per update
  batch (armed here; the SIGKILL acceptance lives in
  tests/test_sketch_chaos.py).
"""

import collections
import os
import sys

import numpy as np
import pytest

from tpubloom import checkpoint as ckpt
from tpubloom import faults
from tpubloom.cluster import slots as S
from tpubloom.cluster.node import ClusterState
from tpubloom.cluster.rebalance import even_ranges
from tpubloom.config import FilterConfig, identity_mismatch
from tpubloom.ops import cuckoo as ops_cuckoo
from tpubloom.repl import OpLog, ReplicaApplier
from tpubloom.server.client import BloomClient
from tpubloom.server.ingest import CoalesceConfig
from tpubloom.server.service import BloomService, build_server
from tpubloom.sketch import registry
from tpubloom.sketch.cms import CountMinSketch, TopKSketch
from tpubloom.sketch.cuckoo import CuckooFilter

pytestmark = pytest.mark.usefixtures("lock_check_armed", "lock_order_manifest")


@pytest.fixture(autouse=True)
def _disarm_all():
    faults.reset()
    yield
    faults.reset()


def _cuckoo(m=256, seed=7, name="cf"):
    return CuckooFilter(FilterConfig(m=m, k=2, seed=seed, kind="cuckoo",
                                     key_name=name))


def _cms(width=64, depth=4, seed=7, name="cms"):
    return CountMinSketch(FilterConfig(m=width, k=depth, seed=seed,
                                       kind="cms", key_name=name))


def _topk(width=64, depth=4, topk=3, seed=7, name="tk"):
    return TopKSketch(FilterConfig(m=width, k=depth, seed=seed, kind="topk",
                                   topk=topk, key_name=name))


# -- cuckoo kernels ----------------------------------------------------------


def test_cuckoo_alt_bucket_is_an_involution():
    import jax.numpy as jnp

    mask = 63
    b = jnp.arange(64, dtype=jnp.uint32)
    for fp in (1, 77, 0xFFFF):
        fps = jnp.full((64,), fp, jnp.uint32)
        alt = ops_cuckoo.alt_bucket(b, fps, mask)
        back = ops_cuckoo.alt_bucket(alt, fps, mask)
        assert (np.asarray(back) == np.asarray(b)).all(), (
            "alt(alt(b)) must be b — the kick chain depends on it"
        )


def test_cuckoo_round_trip_no_false_negatives():
    f = _cuckoo(m=1024)
    keys = [b"ck-%04d" % i for i in range(500)]
    f.insert_batch(keys)
    flags = f.take_insert_flags()
    assert flags is not None and flags.all(), "half-full table must accept all"
    assert f.include_batch(keys).all(), "cuckoo has NO false negatives"
    absent = [b"zz-%04d" % i for i in range(500)]
    fpr = f.include_batch(absent).mean()
    assert fpr < 0.05, f"16-bit fingerprints should keep FPR tiny, got {fpr}"


def test_cuckoo_full_is_honest_and_unwinds_exactly():
    """Overfill a tiny table: every reported ok MUST correspond to a
    stored fingerprint (ok count == occupied slots — a failed chain
    that left moved copies behind would break ==), every accepted key
    remains queryable, and per-key kicks respect MAX_KICKS."""
    f = _cuckoo(m=64)  # 16 buckets * 4 slots
    keys = [b"fill-%03d" % i for i in range(128)]  # 2x capacity
    f.insert_batch(keys)
    flags = f.take_insert_flags()
    assert flags is not None and not flags.all(), "overfill must reject"
    assert flags.any(), "a 2x overfill should still land many keys"
    occupied = int(round(f.fill_ratio() * f.config.m))
    assert int(flags.sum()) == occupied, (
        "honest FULL: accepted count must equal occupied slots exactly "
        f"(ok={int(flags.sum())}, occupied={occupied})"
    )
    accepted = [k for k, ok in zip(keys, flags) if ok]
    assert f.include_batch(accepted).all(), (
        "keys acked by the insert must be queryable — no false negatives"
    )


def test_cuckoo_kick_bound_is_static():
    """The kick loop is a fixed-trip fori_loop: whatever the batch, the
    per-batch kick total is bounded by B * MAX_KICKS (no unbounded
    retry loop to hang a TPU step)."""
    from tpubloom.obs import counters as obs_counters

    before = obs_counters.get("cuckoo_kicks_total")
    f = _cuckoo(m=64)
    keys = [b"kb-%03d" % i for i in range(200)]
    f.insert_batch(keys)
    f.take_insert_flags()
    kicks = obs_counters.get("cuckoo_kicks_total") - before
    assert 0 <= kicks <= 200 * ops_cuckoo.MAX_KICKS


def test_cuckoo_multiset_insert_and_one_copy_delete():
    f = _cuckoo(m=256)
    f.insert_batch([b"dup", b"dup", b"other"])
    f.take_insert_flags()
    # delete ONE copy: still present (the second copy remains)
    out = f.delete_batch([b"dup"])
    assert out[0], "a stored copy existed"
    assert f.include_batch([b"dup"])[0], "second copy must survive"
    out = f.delete_batch([b"dup"])
    assert out[0]
    assert not f.include_batch([b"dup"])[0], "both copies deleted -> gone"
    assert f.include_batch([b"other"])[0], "unrelated key untouched"
    # deleting an absent key reports existed=False
    assert not f.delete_batch([b"never-stored"])[0]


def test_cuckoo_kick_chain_property_random_batches():
    """Property sweep (hypothesis when available, seeded fallback
    otherwise): for random batch sizes and key sets on a small table,
    the honest-FULL invariant holds after every batch."""
    hyp = pytest.importorskip("hypothesis")
    st = pytest.importorskip("hypothesis.strategies")

    @hyp.given(st.integers(min_value=1, max_value=120), st.integers(0, 2**16))
    @hyp.settings(max_examples=20, deadline=None)
    def prop(n, salt):
        f = _cuckoo(m=64, seed=3)
        f.insert_batch([b"p-%d-%d" % (salt, i) for i in range(n)])
        flags = f.take_insert_flags()
        occupied = int(round(f.fill_ratio() * f.config.m))
        assert int(flags.sum()) == occupied

    prop()


def test_cuckoo_invariant_seeded_sweep():
    """The same invariant, deterministic (runs whether or not
    hypothesis is installed)."""
    for n, salt in [(1, 0), (17, 1), (63, 2), (64, 3), (100, 4), (120, 5)]:
        f = _cuckoo(m=64, seed=3)
        f.insert_batch([b"p-%d-%d" % (salt, i) for i in range(n)])
        flags = f.take_insert_flags()
        occupied = int(round(f.fill_ratio() * f.config.m))
        assert int(flags.sum()) == occupied, (n, salt)


# -- count-min kernels -------------------------------------------------------


def test_cms_estimates_bound_exact_counts():
    """est >= truth always; est - truth <= 2N/width for every queried
    key with overwhelming probability at depth 4 (the classic CMS
    bound, deterministic here via the fixed seed)."""
    width, depth = 64, 4
    f = _cms(width=width, depth=depth)
    rng = np.random.default_rng(11)
    stream = [b"k-%02d" % rng.integers(0, 30) for _ in range(600)]
    for off in range(0, len(stream), 100):
        f.insert_batch(stream[off:off + 100])
    truth = collections.Counter(stream)
    keys = sorted(truth)
    est = f.estimate_batch(keys)
    n = len(stream)
    for k, e in zip(keys, est):
        assert e >= truth[k], f"CMS must never undercount ({k})"
        assert e - truth[k] <= 2 * n / width + 1, (
            f"error bound blown for {k}: est={e} true={truth[k]}"
        )
    # absent key: estimate is small (bounded by the same error term)
    absent = f.estimate_batch([b"never-seen"])[0]
    assert absent <= 2 * n / width + 1


def test_cms_duplicates_within_one_batch_accumulate():
    f = _cms()
    f.insert_batch([b"x", b"x", b"x", b"y"])
    est = f.estimate_batch([b"x", b"y"])
    assert est[0] >= 3 and est[1] >= 1


def test_cms_weighted_increments_and_validation():
    f = _cms()
    out = f.increment_batch([b"a", b"b"], [10, 3])
    assert out[0] >= 10 and out[1] >= 3
    with pytest.raises(ValueError, match="increments"):
        f.increment_batch([b"a"], [1, 2])
    with pytest.raises(ValueError, match="u32"):
        f.increment_batch([b"a"], [-1])


def test_topk_heap_tracks_heavy_hitters():
    f = _topk(topk=2)
    f.insert_batch([b"hot"] * 5 + [b"warm"] * 3 + [b"cold"])
    items = f.topk_list()
    assert [k for k, _ in items] == [b"hot", b"warm"]
    assert items[0][1] >= 5 and items[1][1] >= 3
    # serialization round trip (the checkpoint extra block)
    g = _topk(topk=2)
    g.load_sketch_extra(f.sketch_extra())
    assert g.topk_list() == items


# -- config + registry -------------------------------------------------------


def test_filter_config_kind_validation():
    with pytest.raises(ValueError, match="kind"):
        FilterConfig(m=64, k=2, kind="hyperloglog")
    with pytest.raises(ValueError, match="bloom-family"):
        FilterConfig(m=64, k=2, kind="cuckoo", counting=True)
    with pytest.raises(ValueError, match="bloom-family"):
        FilterConfig(m=64, k=2, kind="cms", block_bits=9)
    with pytest.raises(ValueError, match="power-of-two"):
        FilterConfig(m=96, k=2, kind="cuckoo")
    with pytest.raises(ValueError, match="topk"):
        FilterConfig(m=64, k=2, kind="topk")  # topk kind needs topk > 0
    with pytest.raises(ValueError, match="topk"):
        FilterConfig(m=64, k=2, kind="cms", topk=5)
    # the happy paths
    FilterConfig(m=64, k=2, kind="cuckoo")
    FilterConfig(m=64, k=4, kind="cms")
    FilterConfig(m=64, k=4, kind="topk", topk=3)


def test_registry_surface():
    assert set(registry.sketch_kinds()) == {"cuckoo", "cms", "topk"}
    cf = FilterConfig(m=64, k=2, kind="cuckoo")
    bl = FilterConfig(m=64, k=2)
    assert registry.kind_of(cf) == "cuckoo" and registry.kind_of(bl) == "bloom"
    assert registry.is_sketch(cf) and not registry.is_sketch(bl)
    assert registry.blob_format(cf) == "sketch_cuckoo_le_words"
    assert registry.replay_unsafe_insert(cf)
    assert not registry.replay_unsafe_insert(bl)
    assert registry.supports_delete(cf)
    assert not registry.supports_delete(FilterConfig(m=64, k=4, kind="cms"))
    assert isinstance(registry.build(cf), CuckooFilter)
    with pytest.raises(ValueError, match="unknown"):
        registry.spec("hyperloglog")
    # dict-shaped configs (checkpoint headers) resolve the same way
    assert registry.kind_of({"kind": "cms"}) == "cms"
    assert registry.kind_of({}) == "bloom"


def test_identity_accepts_pre_kind_headers():
    """A checkpoint header written before ISSUE 19 has no kind/topk
    field: identity must read it as a bloom filter, not a mismatch."""
    config = FilterConfig(m=1024, k=4, seed=3)
    old_header = {f: getattr(config, f) for f in ("m", "k", "seed",
                                                  "counting", "shards",
                                                  "block_bits", "block_hash")}
    assert identity_mismatch(old_header, config) is None
    newer = FilterConfig(m=1024, k=4, seed=3, kind="cms")
    assert identity_mismatch(old_header, newer) == "kind"


# -- checkpoint round trips --------------------------------------------------


def _restart_with_sink(tmp_path, build):
    """Run ``build`` against a service, checkpoint, then restore into a
    fresh service over the same sink directory."""
    def sink_factory(config):
        return ckpt.FileSink(str(tmp_path / "sink"))

    svc = BloomService(sink_factory=sink_factory)
    srv, port = build_server(svc, "127.0.0.1:0")
    srv.start()
    c = BloomClient(f"127.0.0.1:{port}")
    try:
        build(c)
    finally:
        c.close()
        srv.stop(grace=None)
    svc2 = BloomService(sink_factory=sink_factory)
    srv2, port2 = build_server(svc2, "127.0.0.1:0")
    srv2.start()
    return svc2, srv2, BloomClient(f"127.0.0.1:{port2}")


def test_cuckoo_checkpoint_round_trip(tmp_path):
    def build(c):
        c.cf_reserve("cf", 500)
        assert c.cf_add("cf", [b"a", b"b", b"c"]).all()
        c.checkpoint("cf", wait=True)

    svc2, srv2, c2 = _restart_with_sink(tmp_path, build)
    try:
        c2.cf_reserve("cf", 500)  # attach restores the checkpoint
        assert c2.cf_exists("cf", [b"a", b"b", b"c"]).all()
        assert c2.cf_del("cf", [b"b"]).all()
        hits = c2.cf_exists("cf", [b"a", b"b", b"c"])
        assert hits[0] and not hits[1] and hits[2]
    finally:
        c2.close()
        srv2.stop(grace=None)


def test_topk_checkpoint_round_trip_carries_heap(tmp_path):
    def build(c):
        c.topk_reserve("tk", 2, width=64, depth=4)
        c.topk_add("tk", [b"hot"] * 4 + [b"warm"] * 2 + [b"cold"])
        c.checkpoint("tk", wait=True)

    svc2, srv2, c2 = _restart_with_sink(tmp_path, build)
    try:
        c2.topk_reserve("tk", 2, width=64, depth=4)
        items = c2.topk_list("tk")
        assert [k for k, _ in items] == [b"hot", b"warm"]
        assert items[0][1] >= 4
        # the counter grid restored too, not just the heap
        est = c2.cms_query("tk", [b"hot"])
        assert est[0] >= 4
    finally:
        c2.close()
        srv2.stop(grace=None)


def test_checkpoint_blob_round_trip_and_kind_guard():
    """snapshot_blob/restore_blob per kind, and a blob must refuse to
    hydrate into a config of a different kind."""
    cf = _cuckoo(m=64)
    cf.insert_batch([b"x"])
    cf.take_insert_flags()
    _, _, blob = ckpt.snapshot_blob(cf)
    header, _ = ckpt._deserialize(blob)
    assert header["format"] == "sketch_cuckoo_le_words"
    restored = ckpt.restore_blob(blob)
    assert isinstance(restored, CuckooFilter)
    assert restored.include_batch([b"x"])[0]
    with pytest.raises(ValueError, match="mismatch"):
        ckpt.restore_blob(
            blob, FilterConfig(m=64, k=4, kind="cms", key_name="cf")
        )
    # cms + topk blobs round-trip too (heap via the extra block)
    tk = _topk(topk=2)
    tk.insert_batch([b"hot", b"hot", b"cold"])
    _, _, tblob = ckpt.snapshot_blob(tk)
    theader, _ = ckpt._deserialize(tblob)
    assert theader["format"] == "sketch_topk_le_words"
    trestored = ckpt.restore_blob(tblob)
    assert isinstance(trestored, TopKSketch)
    assert trestored.topk_list() == tk.topk_list()


# -- inherited planes: coalescer / replication / migration -------------------


def test_sketch_kinds_ride_the_coalescer_with_keys_fixed(tmp_path):
    """Concurrent fixed-width batches coalesce into shared flushes;
    per-request demux returns each caller's own verdicts (cuckoo FULL
    slices per entry), and the registry's replay-unsafe classification
    keeps rid dedup armed on the coalesced path."""
    import threading

    svc = BloomService(coalesce=CoalesceConfig(max_keys=4096, max_wait_us=2000))
    srv, port = build_server(svc, "127.0.0.1:0")
    srv.start()
    addr = f"127.0.0.1:{port}"
    try:
        with BloomClient(addr) as admin:
            admin.cf_reserve("cf", 100_000)
            admin.cms_init_by_dim("cms", 2048, 4)

            errs = []

            def writer(t):
                try:
                    with BloomClient(addr) as c:
                        for i in range(4):
                            ks = np.arange(t * 1000 + i * 100,
                                           t * 1000 + i * 100 + 50,
                                           dtype=np.uint64)
                            assert c.cf_add("cf", ks).all()
                            c.cms_incrby("cms", ks)
                except BaseException as e:  # noqa: BLE001
                    errs.append(e)

            ts = [threading.Thread(target=writer, args=(t,)) for t in range(4)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert not errs, errs[:1]

            counters = admin.stats()["counters"]
            assert counters.get("ingest_requests_coalesced", 0) >= 1, (
                "sketch batches must ride the coalescer, not fall back"
            )
            for t in range(4):
                ks = np.arange(t * 1000, t * 1000 + 50, dtype=np.uint64)
                assert admin.cf_exists("cf", ks).all()
                assert (admin.cms_query("cms", ks) >= 1).all()
    finally:
        srv.stop(grace=None)


def test_cuckoo_full_verdicts_demuxed_per_request(tmp_path):
    """A tiny cuckoo table overfilled through the coalesced path: the
    response's ``full`` bitmap flags exactly the rejected keys, and
    accepted ones are queryable."""
    svc = BloomService(coalesce=CoalesceConfig(max_keys=4096, max_wait_us=2000))
    srv, port = build_server(svc, "127.0.0.1:0")
    srv.start()
    try:
        with BloomClient(f"127.0.0.1:{port}") as c:
            c.create_filter("cf", config={"kind": "cuckoo", "m": 64, "k": 2})
            keys = [b"of-%03d" % i for i in range(128)]
            added = c.cf_add("cf", keys)
            assert not added.all() and added.any()
            accepted = [k for k, ok in zip(keys, added) if ok]
            assert c.cf_exists("cf", accepted).all()
    finally:
        srv.stop(grace=None)


def test_sketch_replicates_under_min_replicas_quorum(tmp_path):
    """cf_add / weighted CMSIncrBy under ``min_replicas=1``: the quorum
    ack means the record IS applied on the replica — membership and
    counts agree there."""
    oplog = OpLog(str(tmp_path / "plog"))
    psvc = BloomService(oplog=oplog)
    psrv, pport = build_server(psvc, "127.0.0.1:0")
    psrv.start()
    psvc.listen_address = f"127.0.0.1:{pport}"
    rsvc = BloomService(read_only=True)
    rsrv, rport = build_server(rsvc, "127.0.0.1:0")
    rsrv.start()
    rsvc.listen_address = f"127.0.0.1:{rport}"
    applier = ReplicaApplier(
        rsvc, f"127.0.0.1:{pport}", reconnect_base=0.05,
        listen_address=rsvc.listen_address,
    ).start()
    c = BloomClient(f"127.0.0.1:{pport}")
    rc = BloomClient(f"127.0.0.1:{rport}")
    try:
        c.wait_ready()
        c.cf_reserve("cf", 1000)
        c.cms_init_by_dim("cms", 64, 4)
        # warm the replica's first-apply jit compile outside the barrier
        c.cf_add("cf", [b"warmup"])
        assert applier.wait_for_seq(oplog.last_seq, 60), applier.status()

        assert c.cf_add(
            "cf", [b"r1", b"r2"], min_replicas=1,
            min_replicas_timeout_ms=30_000,
        ).all()
        assert rc.cf_exists("cf", [b"r1", b"r2"]).all()

        counts = c.cms_incrby("cms", [b"hh"], [7], min_replicas=1,
                              min_replicas_timeout_ms=30_000)
        assert counts[0] >= 7
        assert rc.cms_query("cms", [b"hh"])[0] == counts[0], (
            "weighted increments must replay with their exact weights"
        )

        # deletes replicate too (cuckoo one-copy semantics preserved)
        assert c.cf_del("cf", [b"r1"], min_replicas=1,
                        min_replicas_timeout_ms=30_000).all()
        hits = rc.cf_exists("cf", [b"r1", b"r2"])
        assert not hits[0] and hits[1]

        # replica refuses sketch writes like any write; the client
        # follows the READONLY redirect to the upstream primary
        # (Redis-MOVED-style), so the write lands there instead
        assert rc.cf_add("cf", [b"redirected"]).all()
        assert rc.address == psvc.listen_address
        assert c.cf_exists("cf", [b"redirected"])[0]
    finally:
        c.close()
        rc.close()
        applier.stop()
        rsrv.stop(grace=None)
        psrv.stop(grace=None)
        oplog.close()


def _cluster_node(tmp_path, name):
    d = tmp_path / name
    oplog = OpLog(str(d / "log"))
    svc = BloomService(oplog=oplog)
    srv, port = build_server(svc, "127.0.0.1:0")
    srv.start()
    addr = f"127.0.0.1:{port}"
    svc.listen_address = addr
    svc.cluster = ClusterState(addr, state_dir=str(d / "log"))
    return svc, srv, addr, oplog


def test_sketch_filters_migrate_via_migrate_slot(tmp_path):
    """A cuckoo filter and a top-k sketch in one slot survive a
    MigrateSlot handoff: membership, one-copy delete semantics and the
    heavy-hitter heap all present at the target."""
    a = _cluster_node(tmp_path, "a")
    b = _cluster_node(tmp_path, "b")
    try:
        addrs = [a[2], b[2]]
        ranges = even_ranges(addrs)
        for svc, _srv, _addr, _ in (a, b):
            svc.ClusterSetSlot({"assign": ranges, "epoch": 1})
        # one hash-tagged pair so both filters share a slot owned by a
        name_cf = name_tk = None
        for i in range(4096):
            tag = "{mig-%d}" % i
            if a[0].cluster.owner(S.key_slot(tag)) == addrs[0]:
                name_cf, name_tk = tag + "cf", tag + "tk"
                break
        assert name_cf is not None
        slot = S.key_slot(name_cf)

        ca = BloomClient(addrs[0])
        ca.cf_reserve(name_cf, 1000)
        ca.topk_reserve(name_tk, 2, width=64, depth=4)
        assert ca.cf_add(name_cf, [b"m1", b"m2", b"m2"]).all()
        ca.topk_add(name_tk, [b"hot"] * 4 + [b"cold"])

        resp = ca.migrate_slot(slot, addrs[1])
        assert resp["ok"] and resp["filters_moved"] >= 2
        ca.close()

        cb = BloomClient(addrs[1])
        try:
            assert cb.cf_exists(name_cf, [b"m1", b"m2"]).all()
            # multiset copies moved intact: two deletes to empty m2
            assert cb.cf_del(name_cf, [b"m2"])[0]
            assert cb.cf_exists(name_cf, [b"m2"])[0]
            assert cb.cf_del(name_cf, [b"m2"])[0]
            assert not cb.cf_exists(name_cf, [b"m2"])[0]
            items = cb.topk_list(name_tk)
            assert items and items[0][0] == b"hot" and items[0][1] >= 4
        finally:
            cb.close()
    finally:
        for svc, srv, _addr, oplog in (a, b):
            srv.stop(grace=None)
            oplog.close()
            if svc.cluster is not None:
                svc.cluster.close()


# -- fault points ------------------------------------------------------------


def test_sketch_fault_points_fire():
    faults.arm("cuckoo.kick", mode="raise")
    f = _cuckoo()
    with pytest.raises(faults.InjectedFault):
        f.insert_batch([b"x"])
    faults.reset()
    faults.arm("cms.update", mode="raise")
    g = _cms()
    with pytest.raises(faults.InjectedFault):
        g.insert_batch([b"x"])
    with pytest.raises(faults.InjectedFault):
        g.increment_batch([b"x"], [2])
    faults.reset()
    # disarmed: both paths run clean
    f.insert_batch([b"x"])
    g.insert_batch([b"x"])


# -- tier-1 smoke over the sketch bench ---------------------------------------


def test_sketch_bench_smoke():
    """The sketch kinds must actually ride the coalescer on a live
    subprocess server — merged flushes, honest presence, conserved CMS
    mass (anti-gaming asserts inside run_load)."""
    sys.path.insert(
        0,
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "benchmarks"),
    )
    import sketch_smoke

    out = sketch_smoke.run_load(duration_s=1.5)
    assert out["cf_requests_per_flush"] > sketch_smoke.FLUSH_GATE
    assert out["cms_keys_incremented"] >= (
        sketch_smoke.CONNECTIONS * sketch_smoke.BATCH
    )
