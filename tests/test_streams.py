"""Streaming ingest plane suite (ISSUE 18).

Layers covered:

* **e2e round trip** — persistent ``InsertStream``/``QueryStream``
  sessions through the coalescer: per-frame acks carry the full
  unary-shaped verdicts (n / presence / hits), acks pipelined under the
  credit window, ``stream_connected_current`` back to zero on close;
* **per-frame gates** — error verdicts (NOT_FOUND, READONLY) ride the
  ack for THEIR frame and never kill the stream: the frames after a
  rejected one still apply;
* **chaos** — ``stream.recv`` (frame dropped before anything applied)
  and ``stream.ack`` (ack lost AFTER the apply): both kill the stream
  mid-flight; the client session reconnects and replays only unacked
  frames under their ORIGINAL rids, the rid→response dedup cache turns
  the already-applied replay into a cache hit, and a counting filter
  proves exactly-once (one delete fully clears every key);
* **the acceptance** — a real subprocess server SIGKILLed with a
  stream's frames in flight, restarted over the same op-log dir: the
  session replays the unacked tail, every frame acks OK, every key is
  readable EXACTLY once on a counting filter, and the killed process's
  black-box ring (PR 16) is readable post-mortem.

Armed under the lock tracker + lock-order manifest like the other
chaos modules (tests/conftest.py).
"""

import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from tpubloom import faults
from tpubloom.obs import counters as obs_counters
from tpubloom.server.client import BloomClient
from tpubloom.server.ingest import CoalesceConfig
from tpubloom.server.protocol import BloomServiceError
from tpubloom.server.service import BloomService, build_server

pytestmark = pytest.mark.usefixtures("lock_check_armed", "lock_order_manifest")


@pytest.fixture(autouse=True)
def _disarm_all():
    faults.reset()
    yield
    faults.reset()


class _Server:
    def __init__(self, service):
        self.service = service
        self.server, self.port = build_server(service, "127.0.0.1:0")
        self.server.start()
        self.addr = f"127.0.0.1:{self.port}"

    def client(self, **kw) -> BloomClient:
        return BloomClient(self.addr, **kw)

    def stop(self):
        self.service.shutdown()
        self.server.stop(grace=None)


@pytest.fixture()
def coalesced_server():
    s = _Server(BloomService(
        coalesce=CoalesceConfig(max_keys=4096, max_wait_us=2000)
    ))
    yield s
    s.stop()


def _counters(service):
    return service.metrics.snapshot()["counters"]


# -- e2e round trip ----------------------------------------------------------


def test_insert_and_query_stream_roundtrip(coalesced_server):
    svc = coalesced_server.service
    c = coalesced_server.client()
    try:
        c.wait_ready()
        c.create_filter("s", capacity=100_000, error_rate=0.01)
        frames = {
            i: [b"st-%02d-%04d" % (i, j) for j in range(32)]
            for i in range(40)
        }
        with c.insert_stream("s", return_presence=True) as ins:
            seqs = {i: ins.send(keys) for i, keys in frames.items()}
            resps = ins.drain(timeout=60)
            assert len(resps) == len(frames)
            assert obs_counters.get_gauge("stream_connected_current") >= 1
            for i, seq in seqs.items():
                r = ins.result(seq)
                assert r["ok"] and r["n"] == len(frames[i])
                # fresh keys: presence is all-absent for the frame
                bits = np.unpackbits(
                    np.frombuffer(r["presence"], dtype=np.uint8)
                )[: len(frames[i])]
                assert not bits.any()
        with c.query_stream("s") as qs:
            seq_hit = qs.send(frames[0])
            seq_miss = qs.send([b"absent-%04d" % j for j in range(32)])
            qs.drain(timeout=60)
            hits = np.unpackbits(np.frombuffer(
                qs.result(seq_hit)["hits"], dtype=np.uint8
            ))[:32]
            misses = np.unpackbits(np.frombuffer(
                qs.result(seq_miss)["hits"], dtype=np.uint8
            ))[:32]
        assert hits.all() and not misses.any()
        counters = _counters(svc)
        assert counters.get("stream_frames_total", 0) >= 42
        assert counters.get("stream_acks_total", 0) >= 42
        assert counters.get("stream_InsertStream_opened", 0) >= 1
        assert counters.get("stream_QueryStream_opened", 0) >= 1
        # both sessions closed: the gauge must come back to zero
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if obs_counters.get_gauge("stream_connected_current") == 0:
                break
            time.sleep(0.02)
        assert obs_counters.get_gauge("stream_connected_current") == 0
    finally:
        c.close()


def test_streamed_frames_ride_the_coalescer(coalesced_server):
    """Concurrent streamed frames park like unary requests and flush as
    shared device launches — the plane feeds the PR-10 coalescer, it
    does not bypass it."""
    import threading

    svc = coalesced_server.service
    c = coalesced_server.client()
    try:
        c.wait_ready()
        c.create_filter("co", capacity=100_000, error_rate=0.01)
        f0 = _counters(svc).get("ingest_flushes", 0)
        r0 = _counters(svc).get("ingest_requests_coalesced", 0)

        def pump(t):
            cc = coalesced_server.client()
            try:
                with cc.insert_stream("co") as s:
                    for i in range(24):
                        s.send([b"co-%d-%d-%04d" % (t, i, j)
                                for j in range(16)])
                    s.drain(timeout=60)
            finally:
                cc.close()

        ts = [threading.Thread(target=pump, args=(t,)) for t in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        counters = _counters(svc)
        flushes = counters.get("ingest_flushes", 0) - f0
        parked = counters.get("ingest_requests_coalesced", 0) - r0
        assert parked >= 96, "streamed frames must park in the coalescer"
        assert flushes < parked, (
            f"{flushes} flushes for {parked} parked frames — frames "
            f"must share launches"
        )
    finally:
        c.close()


# -- per-frame gates ---------------------------------------------------------


def test_error_verdicts_do_not_kill_the_stream(coalesced_server):
    c = coalesced_server.client()
    try:
        c.wait_ready()
        c.create_filter("ok", capacity=10_000, error_rate=0.01)
        with c.insert_stream("ok") as s:
            good1 = s.send([b"a", b"b"])
            # mid-stream frame against a missing filter: ITS ack is the
            # error — the session keeps flowing
            bad = s.send([b"x"], name="no-such-filter")
            good2 = s.send([b"c", b"d"])
            s.drain(timeout=60)
            assert s.result(good1)["n"] == 2
            with pytest.raises(BloomServiceError, match="NOT_FOUND"):
                s.result(bad)
            assert s.result(good2)["n"] == 2
        assert c.include("ok", b"c")
    finally:
        c.close()


def test_readonly_replica_rejects_streamed_inserts():
    srv = _Server(BloomService(read_only=True))
    c = srv.client()
    try:
        c.wait_ready()
        before = _counters(srv.service).get("readonly_rejected", 0)
        with c.insert_stream("any") as s:
            seq = s.send([b"k"])
            with pytest.raises(BloomServiceError, match="READONLY"):
                s.result(seq, timeout=30)
        assert _counters(srv.service).get("readonly_rejected", 0) > before
    finally:
        c.close()
        srv.stop()


# -- chaos: mid-stream kill, reconnect, exactly-once replay ------------------


def _exactly_once(client, name, frames):
    """Counting-filter proof: every key present, ONE delete clears it —
    a double-applied frame would leave count 2 and survive the delete."""
    for keys in frames.values():
        assert client.include_batch(name, keys).all()
        client.delete_batch(name, keys)
        assert not client.include_batch(name, keys).any(), (
            "a replayed frame applied twice (count survived one delete)"
        )


def test_stream_recv_fault_reconnect_replays_unapplied(coalesced_server):
    """``stream.recv`` kills the stream BEFORE the frame touches
    anything: the session reconnects and the replay is the first (and
    only) apply."""
    svc = coalesced_server.service
    c = coalesced_server.client()
    try:
        c.wait_ready()
        c.create_filter("cnt", capacity=50_000, error_rate=0.01,
                        counting=True)
        frames = {
            i: [b"rv-%02d-%04d" % (i, j) for j in range(16)]
            for i in range(8)
        }
        with c.insert_stream("cnt") as s:
            for i in range(4):
                s.send(frames[i])
            s.drain(timeout=60)
            faults.arm("stream.recv", "once")
            for i in range(4, 8):
                s.send(frames[i])
            resps = s.drain(timeout=120)
        assert len(resps) == 8
        assert all(r.get("ok") for r in resps)
        assert obs_counters.get("fault_stream_recv") >= 1
        _exactly_once(c, "cnt", frames)
    finally:
        c.close()


def test_stream_ack_loss_after_apply_dedups_replay(coalesced_server):
    """``stream.ack`` kills the stream AFTER the flush applied but
    before the ack reached the client — the replayed frame (same rid)
    must hit the dedup cache, not re-apply."""
    svc = coalesced_server.service
    c = coalesced_server.client()
    try:
        c.wait_ready()
        c.create_filter("cnt", capacity=50_000, error_rate=0.01,
                        counting=True)
        frames = {0: [b"ak-%04d" % j for j in range(32)]}
        faults.arm("stream.ack", "once")
        with c.insert_stream("cnt") as s:
            seq = s.send(frames[0])
            s.drain(timeout=120)
            r = s.result(seq)
            assert r["ok"] and r["n"] == 32
        assert obs_counters.get("fault_stream_ack") >= 1
        assert _counters(svc).get("stream_frame_dedup_hits", 0) >= 1, (
            "the applied-then-lost frame's replay must be a dedup hit"
        )
        _exactly_once(c, "cnt", frames)
    finally:
        c.close()


# -- the acceptance: SIGKILL mid-stream --------------------------------------

#: mirrors test_blackbox's child: the image's sitecustomize force-sets
#: jax_platforms to the TPU plugin, so the child must pin cpu first.
_SERVER_CHILD = """\
import sys
import jax
jax.config.update("jax_platforms", "cpu")
from tpubloom.server.service import main
main(sys.argv[1:])
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _child_env():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }


def _spawn(tmp_path, script_name, args):
    script = tmp_path / script_name
    script.write_text(_SERVER_CHILD)
    return subprocess.Popen(
        [sys.executable, str(script)] + [str(a) for a in args],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=_child_env(),
    )


def test_sigkill_midstream_replay_is_exactly_once(tmp_path):
    """THE ISSUE-18 acceptance: SIGKILL a real subprocess server with a
    stream's frames in flight; restart it over the same op-log dir; the
    session replays ONLY the unacked frames under their original rids;
    the restarted server's dedup cache (re-seeded from the merged log
    records' ``parts``) absorbs any frame whose first flight already
    committed — every frame acks OK and a counting filter holds every
    key EXACTLY once. The killed process's black-box ring is readable
    post-mortem."""
    plog = tmp_path / "primary-log"
    port = _free_port()
    args = [port, tmp_path / "ckpt", "--repl-log-dir", plog,
            "--coalesce-max-keys", "4096", "--coalesce-max-wait-us", "2000",
            "--trace-sample", "0.0"]
    proc = _spawn(tmp_path, "server-a.py", args)
    restarted = None
    # a server restart takes seconds (jax import): give the session a
    # reconnect budget that outlasts it
    client = BloomClient(
        f"127.0.0.1:{port}", timeout=30.0,
        max_retries=120, backoff_base=0.25, backoff_max=1.0,
    )
    frames = {
        i: [b"sk-%02d-%04d" % (i, j) for j in range(32)] for i in range(24)
    }
    try:
        client.wait_ready(timeout=120)
        client.create_filter("cnt", capacity=50_000, error_rate=0.01,
                             counting=True)
        s = client.insert_stream("cnt")
        seqs = {}
        for i in range(12):
            seqs[i] = s.send(frames[i])
        s.drain(timeout=120)  # first half fully acked by server A
        for i in range(12, 24):
            seqs[i] = s.send(frames[i])
        # kill mid-stream: the tail is in flight — parked, mid-flush,
        # or acked-but-undelivered, depending on the race we lose
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
        restarted = _spawn(tmp_path, "server-b.py", args)
        resps = s.drain(timeout=300)
        assert len(resps) == 24
        for i, seq in seqs.items():
            r = s.result(seq)
            assert r.get("ok") and r.get("n") == len(frames[i]), (i, r)
        s.close()
        _exactly_once(client, "cnt", frames)
    finally:
        client.close()
        for p in (proc, restarted):
            if p is not None and p.poll() is None:
                p.send_signal(signal.SIGKILL)
        for p in (proc, restarted):
            if p is not None:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    pass

    # post-mortem (PR 16): the KILLED server's mmap'd ring survived and
    # identifies the process that owned the stream's first half
    import json

    cli = subprocess.run(
        [sys.executable, "-m", "tpubloom.obs.blackbox", str(plog),
         "--json"],
        capture_output=True, text=True, env=_child_env(), timeout=120,
    )
    assert cli.returncode == 0, cli.stderr
    out = json.loads(cli.stdout)
    (node,) = out["nodes"]
    assert node["meta"]["role"] == "primary"
    assert "boot" in [e["kind"] for e in node["events"]]


# -- tier-1 smoke over the streaming bench phase ------------------------------


def test_streaming_bench_smoke():
    """The ISSUE-18 acceptance gate, tier-1 sized: the bidi plane must
    move frames at least as fast as unary on the same server, with
    every counted frame actually received AND acked (anti-gaming
    asserts inside run_load)."""
    sys.path.insert(
        0,
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "benchmarks"),
    )
    import ingest_load

    out = ingest_load.run_load(duration_s=1.5, quorum=False)
    assert out["streaming_vs_unary"] >= ingest_load.STREAM_GATE
    assert out["stream_frames_recv"] >= out["stream_frames_sent"]
    assert out["stream_acks_recv"] >= out["stream_frames_sent"]
