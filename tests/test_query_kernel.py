"""ISSUE 12: the dedicated read-only query sweep kernel.

Bit-parity of ``tpubloom.ops.sweep``'s query path against the XLA
gather reference (interpret mode on CPU — real-Mosaic validation runs
on hardware via benchmarks/adversarial.py, like every kernel in this
family), across bb ∈ {256, 512}, duplicate-skew keys (the
overflow→gather fallback), tail padding, fat + logical storage, and the
packed ``keys_fixed`` input path; plus the ``query_path`` funnel, the
launch-mix counters, the query kind of the geometry-probe machinery,
and the tier-1 smoke over ``benchmarks/query_load.py``.
"""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from tpubloom.config import FilterConfig
from tpubloom.filter import BlockedBloomFilter, make_blocked_query_fn
from tpubloom.obs import counters as obs_counters
from tpubloom.ops import blocked, sweep

NB, BB, K, B = 8192, 512, 7, 8192
CFG = FilterConfig(m=NB * BB, k=K, key_len=16, block_bits=BB)
W = CFG.words_per_block


def _positions(cfg, keys_u8, lengths):
    return blocked.block_positions(
        keys_u8, jnp.maximum(lengths, 0),
        n_blocks=cfg.n_blocks, block_bits=cfg.block_bits, k=cfg.k,
        seed=cfg.seed, block_hash=cfg.block_hash,
    )


def _gather_ref(cfg, state, keys, lengths):
    blk, bit = _positions(cfg, keys, lengths)
    masks = blocked.build_masks(bit, cfg.words_per_block)
    return jnp.all((state[blk] & masks) == masks, axis=-1) & (lengths >= 0)


@pytest.fixture(scope="module")
def populated():
    """A half-populated filter + the batch that populated it."""
    rng = np.random.default_rng(0)
    keys = jnp.asarray(rng.integers(0, 256, (B, 16), np.uint8))
    lengths = jnp.full((B,), 16, jnp.int32)
    blk, bit = _positions(CFG, keys, lengths)
    masks = blocked.build_masks(bit, W)
    state = blocked.blocked_insert(
        jnp.zeros((NB, W), jnp.uint32), blk, masks, jnp.arange(B) < B // 2
    )
    return state, keys, lengths


def test_query_params_selected_for_north_star():
    """THE path-selection gate: the north-star serving shape must
    resolve to the dedicated query kernel on a TPU backend (chooser
    math is backend-independent; the probe no-ops off-TPU)."""
    north = FilterConfig(m=1 << 32, k=7, key_len=16, block_bits=512)
    assert sweep.resolve_query_path(north, 1 << 23, backend="tpu") == "sweep"
    assert sweep.choose_fat_query_params(north.n_blocks, 1 << 23, 16) is not None
    # off-TPU auto resolves to gather — the kernel only lowers on TPU
    assert sweep.resolve_query_path(north, 1 << 23, backend="cpu") == "gather"
    # forced paths pass through the funnel untouched
    assert (
        sweep.resolve_query_path(north.replace(query_path="gather"), 1 << 23)
        == "gather"
    )


def test_query_lambda_exceeds_presence_lambda():
    """The chooser's point (ISSUE 12): with the update/delta scoped-VMEM
    buffers gone, query geometries run AT LEAST the lambda the fused
    presence chooser picks at the same shape."""
    north = FilterConfig(m=1 << 32, k=7, key_len=16, block_bits=512)
    nb = north.n_blocks
    q = sweep.choose_fat_query_params(nb, 1 << 23, 16)
    p = sweep.choose_fat_params(nb, 1 << 23, 16, presence=True)
    assert q is not None and p is not None
    lam_q = (1 << 23) * q[1] // nb
    lam_p = (1 << 23) * p[1] // nb
    assert lam_q >= lam_p


def test_sweep_query_matches_gather_bb512(populated):
    state, keys, lengths = populated
    qfn = sweep.make_sweep_query_fn(CFG, interpret=True)
    got = qfn(state, keys, lengths)
    ref = _gather_ref(CFG, state, keys, lengths)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    assert int(np.asarray(got).sum()) == B // 2


def test_sweep_query_matches_gather_bb256():
    nb, bb = 16384, 256
    cfg = FilterConfig(m=nb * bb, k=5, key_len=16, block_bits=bb)
    w = cfg.words_per_block
    assert sweep.choose_fat_query_params(nb, B, w) is not None
    rng = np.random.default_rng(1)
    keys = jnp.asarray(rng.integers(0, 256, (B, 16), np.uint8))
    lengths = jnp.full((B,), 16, jnp.int32)
    blk, bit = _positions(cfg, keys, lengths)
    masks = blocked.build_masks(bit, w)
    state = blocked.blocked_insert(
        jnp.zeros((nb, w), jnp.uint32), blk, masks, jnp.arange(B) < B // 3
    )
    got = sweep.make_sweep_query_fn(cfg, interpret=True)(state, keys, lengths)
    ref = _gather_ref(cfg, state, keys, lengths)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_duplicate_skew_falls_back_bit_exact(populated):
    """Window overflow (duplicate skew) must route the whole batch to
    the gather branch and stay verdict-exact."""
    state, _, lengths = populated
    rng = np.random.default_rng(2)
    dup = jnp.asarray(
        np.tile(rng.integers(0, 256, (16, 16), np.uint8), (B // 16, 1))
    )
    got = sweep.make_sweep_query_fn(CFG, interpret=True)(state, dup, lengths)
    ref = _gather_ref(CFG, state, dup, lengths)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_tail_padding_reports_false(populated):
    """The documented contract: padding is a TAIL suffix; padded entries
    report False and valid entries keep unshifted verdicts."""
    state, keys, lengths = populated
    lp = lengths.at[B - 100:].set(-1)
    got = sweep.make_sweep_query_fn(CFG, interpret=True)(state, keys, lp)
    ref = _gather_ref(CFG, state, keys, lp)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    assert not np.asarray(got)[B - 100:].any()


def test_fat_storage_view_parity(populated):
    state, keys, lengths = populated
    fat = state.reshape(NB * W // 128, 128)
    got = sweep.make_sweep_query_fn(CFG, interpret=True, storage_fat=True)(
        fat, keys, lengths
    )
    ref = _gather_ref(CFG, state, keys, lengths)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_query_is_read_only(populated):
    """The kernel must never touch the array (no write-back, no
    donation): the storage bytes are identical after a query."""
    state, keys, lengths = populated
    before = np.asarray(state).copy()
    sweep.make_sweep_query_fn(CFG, interpret=True)(state, keys, lengths)
    np.testing.assert_array_equal(np.asarray(state), before)


def test_forced_sweep_small_batch_demotes_to_gather():
    """A served filter sees arbitrary batch sizes: query_path='sweep'
    FORCED must answer small batches (below the kernel's lambda floor)
    via the gather, not error — found live by the PR-12 verify drive
    (a 64-key include_batch through the server 500'd). The demotion is
    visible in the launch-mix counters."""
    cfg = FilterConfig(
        m=NB * BB, k=K, key_len=16, block_bits=BB, query_path="sweep"
    )
    assert sweep.effective_query_path(cfg, 64) == "gather"
    f = BlockedBloomFilter(cfg)
    f.insert_batch([b"small-%d" % i for i in range(32)])
    g0 = obs_counters.get("query_gather_launches")
    assert f.include_batch([b"small-%d" % i for i in range(32)]).all()
    assert obs_counters.get("query_gather_launches") == g0 + 1
    # big batches still ride the kernel
    assert sweep.effective_query_path(cfg, B) == "sweep"


def test_forced_sweep_on_unsupported_shape_raises():
    cfg = FilterConfig(
        m=1 << 16, k=7, key_len=16, block_bits=512, query_path="sweep"
    )
    qfn = sweep.make_sweep_query_fn(cfg, interpret=True)
    state = jnp.zeros((cfg.n_blocks, cfg.words_per_block), jnp.uint32)
    keys = jnp.zeros((64, 16), jnp.uint8)
    with pytest.raises(ValueError, match="query_path='gather'"):
        qfn(state, keys, jnp.full((64,), 16, jnp.int32))


def test_filter_include_paths_ride_query_kernel(populated):
    """End-to-end through BlockedBloomFilter: query_path='sweep' forced
    (interpret on CPU) — include_batch AND the packed keys_fixed path
    (include_packed) answer identically to a gather-path twin, and the
    launch-mix counters record the resolved path."""
    cfg = FilterConfig(
        m=NB * BB, k=K, key_len=16, block_bits=BB, query_path="sweep"
    )
    f_sweep = BlockedBloomFilter(cfg)
    f_gather = BlockedBloomFilter(cfg.replace(query_path="gather"))
    rng = np.random.default_rng(3)
    population = [rng.bytes(8) for _ in range(4096)]
    f_sweep.insert_batch(population)
    f_gather.insert_batch(population)
    probes = population[:1024] + [rng.bytes(8) for _ in range(1024)]
    s0 = obs_counters.get("query_sweep_launches")
    got = f_sweep.include_batch(probes)
    assert obs_counters.get("query_sweep_launches") == s0 + 1
    want = f_gather.include_batch(probes)
    np.testing.assert_array_equal(got, want)
    assert got[:1024].all()
    # packed fixed-width input (the `fixed` wire encoding's server path)
    rows = np.frombuffer(b"".join(probes), np.uint8).reshape(len(probes), 8)
    got_p = f_sweep.include_packed(rows)
    want_p = f_gather.include_packed(rows)
    np.testing.assert_array_equal(got_p, want_p)
    np.testing.assert_array_equal(got_p, got)
    g0 = obs_counters.get("query_gather_launches")
    f_gather.include_batch(probes[:64])
    assert obs_counters.get("query_gather_launches") == g0 + 1


def test_make_blocked_query_fn_routes_through_funnel(populated):
    """The pure-fn layer: query_path='sweep' builds the kernel path,
    'gather' the gather path — identical verdicts (what 'auto' switches
    between at trace time)."""
    state, keys, lengths = populated
    got = make_blocked_query_fn(CFG.replace(query_path="sweep"))(
        state, keys, lengths
    )
    want = make_blocked_query_fn(CFG.replace(query_path="gather"))(
        state, keys, lengths
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_query_probe_rides_probe_and_disk_cache(monkeypatch, tmp_path):
    """The query chooser entry reuses the PR-11 probe machinery: on an
    unvalidated device kind every query geometry probe-compiles once,
    persists ok=True, and a simulated second process start answers from
    disk with zero compiles."""
    monkeypatch.setenv("TPUBLOOM_CACHE_DIR", str(tmp_path))
    monkeypatch.setattr(sweep, "_probe_env", lambda: "Fake TPU v9")
    calls = []
    monkeypatch.setattr(
        sweep, "_probe_compile",
        lambda fn, *sds: (calls.append(getattr(fn, "func", fn).__name__),
                          (True, None))[1],
    )
    saved = (
        dict(sweep._GEOM_PROBE_CACHE),
        dict(sweep._GEOM_DISK_CACHE),
        set(sweep._GEOM_DISK_LOADED),
    )
    try:
        sweep._GEOM_PROBE_CACHE.clear()
        sweep._GEOM_DISK_CACHE.clear()
        sweep._GEOM_DISK_LOADED.clear()
        geom = sweep.choose_fat_query_params(1 << 17, 4096, 16)
        assert geom is not None
        assert calls and all(n == "fat_sweep_query" for n in calls), (
            f"query probes must compile the QUERY kernel, saw {calls}"
        )
        first = len(calls)
        sweep._GEOM_PROBE_CACHE.clear()
        sweep._GEOM_DISK_CACHE.clear()
        sweep._GEOM_DISK_LOADED.clear()
        assert sweep.choose_fat_query_params(1 << 17, 4096, 16) == geom
        assert len(calls) == first, "second start must answer from disk"
    finally:
        sweep._GEOM_PROBE_CACHE.clear()
        sweep._GEOM_PROBE_CACHE.update(saved[0])
        sweep._GEOM_DISK_CACHE.clear()
        sweep._GEOM_DISK_CACHE.update(saved[1])
        sweep._GEOM_DISK_LOADED.clear()
        sweep._GEOM_DISK_LOADED.update(saved[2])


# -- tier-1 smoke over the load gate ------------------------------------------


def test_query_load_smoke():
    """The ISSUE-12 acceptance bench: query kernel path selected for the
    north-star shape + bit-exact vs the XLA reference + coalesced query
    throughput >= the per-request path (asserted inside run_load)."""
    import sys

    sys.path.insert(
        0,
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "benchmarks"),
    )
    import query_load

    out = query_load.run_load(duration_s=1.5)
    assert out["north_star_query_path"] == "sweep"
    assert out["coalesced_vs_per_request"] >= query_load.GATE
    assert out["requests_per_flush"] > 1.5
