"""Pin params.blocked_fpr against measured FPR (VERDICT r2 #5).

The analytic model (Poisson mixture over per-block loads + Stirling
distinct-position distribution + AP family floor) silently mis-advises
every capacity decision if wrong, so every cell of the
fill x block_bits x block_hash matrix is measured: insert n keys chosen
for a target fill, probe absent keys, and require the observed count to
sit inside a Poisson-wide band around model * probes.

ops/blocked.py cites this file as the model's measurement anchor.
"""

import math

import numpy as np
import pytest

from tpubloom import FilterConfig
from tpubloom.filter import BlockedBloomFilter
from tpubloom.params import blocked_fpr, theoretical_fpr

M = 1 << 20
K = 4
PROBES = 1 << 19  # 512k, in 2 batches
CHUNK = 1 << 18


def _n_for_fill(fill: float) -> int:
    """n with expected overall fill (1 - e^{-k n / m}) == fill."""
    return int(-M * math.log(1.0 - fill) / K)


def _measure_fpr(config: FilterConfig, n: int, seed: int) -> float:
    rng = np.random.default_rng(seed)
    f = BlockedBloomFilter(config)
    lengths = np.full(n, 16, np.int32)
    f.insert_arrays(rng.integers(0, 256, (n, 16), np.uint8), lengths)
    hits = 0
    for i in range(PROBES // CHUNK):
        probe = rng.integers(0, 256, (CHUNK, 16), np.uint8)
        pl = np.full(CHUNK, 16, np.int32)
        hits += int(np.asarray(f.include_arrays(probe, pl)).sum())
    return hits / PROBES, hits


@pytest.mark.parametrize("block_hash", ["chunk", "ap"])
@pytest.mark.parametrize("block_bits", [256, 512, 1024])
@pytest.mark.parametrize("fill", [0.05, 0.15, 0.30])
def test_blocked_fpr_model_matches_measurement(fill, block_bits, block_hash):
    n = _n_for_fill(fill)
    config = FilterConfig(
        m=M, k=K, key_len=16, block_bits=block_bits, block_hash=block_hash
    )
    model = blocked_fpr(
        n, m=M, k=K, block_bits=block_bits, block_hash=block_hash
    )
    observed, hits = _measure_fpr(config, n, seed=hash((fill, block_bits, block_hash)) & 0xFFFF)
    expect = model * PROBES
    # Poisson-wide acceptance: 6 sigma + 35% model tolerance + a floor of
    # 8 counts for the near-zero cells
    tol = max(6.0 * math.sqrt(max(expect, 1.0)), 0.35 * expect, 8.0)
    assert abs(hits - expect) <= tol, (
        f"fill={fill} b={block_bits} hash={block_hash}: measured {hits} "
        f"hits vs model {expect:.1f} (±{tol:.1f}) over {PROBES} probes "
        f"(observed FPR {observed:.2e}, model {model:.2e})"
    )


def test_model_orderings():
    """Structural facts the model must reproduce: blocked >= flat at equal
    fill (Jensen), ap >= chunk (family floor), floor linear in load."""
    n = _n_for_fill(0.15)
    for b in (256, 512, 1024):
        chunk = blocked_fpr(n, m=M, k=K, block_bits=b, block_hash="chunk")
        ap = blocked_fpr(n, m=M, k=K, block_bits=b, block_hash="ap")
        flat = theoretical_fpr(M, K, n)
        assert chunk >= flat * 0.98, (b, chunk, flat)
        assert ap > chunk, (b, ap, chunk)
        # the AP floor term alone: lam * 4 / b^2
        lam = n / (M // b)
        assert ap - chunk >= 0.5 * lam * 4.0 / (b * b)


def test_model_validates_inputs():
    with pytest.raises(ValueError, match="power of two"):
        blocked_fpr(10, m=M, k=K, block_bits=12)
    with pytest.raises(ValueError, match="power of two"):
        blocked_fpr(10, m=M, k=K, block_bits=0)
    assert blocked_fpr(0, m=M, k=K, block_bits=512) == 0.0


def test_ap_device_vs_oracle_parity():
    """Explicit block_hash='ap' device path == pure-NumPy oracle bit for
    bit (the legacy spec that keeps old checkpoints readable — VERDICT r2
    weak #4: it was only ever exercised via the default)."""
    from tpubloom.cpu_ref import CPUBlockedBloomFilter

    config = FilterConfig(
        m=1 << 16, k=5, key_len=16, block_bits=512, block_hash="ap"
    )
    rng = np.random.default_rng(3)
    keys = [rng.bytes(16) for _ in range(2000)] + [b"", b"a", "unicode-✓"]
    f = BlockedBloomFilter(config)
    o = CPUBlockedBloomFilter(config, use_native=False)
    f.insert_batch(keys)
    o.insert_batch(keys)
    np.testing.assert_array_equal(f.words_logical, o.words)
    probe = keys + [rng.bytes(16) for _ in range(2000)]
    np.testing.assert_array_equal(f.include_batch(probe), o.include_batch(probe))


def test_plain_blocked_pre_block_hash_checkpoint_restores_as_ap(tmp_path):
    """A blocked checkpoint whose header predates the block_hash field
    must restore as the AP spec (config.from_dict mapping) — and refuse a
    chunk-config restore with a clear identity error."""
    import json

    from tpubloom import checkpoint as ckpt

    ap_cfg = FilterConfig(
        m=1 << 16, k=5, key_len=16, block_bits=512, block_hash="ap",
        key_name="legacy-blk",
    )
    rng = np.random.default_rng(4)
    keys = [rng.bytes(16) for _ in range(1500)]
    f = BlockedBloomFilter(ap_cfg)
    f.insert_batch(keys)
    sink = ckpt.FileSink(str(tmp_path))
    ckpt.save(f, sink)
    # strip the field as a pre-block_hash writer would have
    import pathlib

    path = max(pathlib.Path(tmp_path).glob("legacy-blk.*.ckpt"))
    blob = path.read_bytes()
    header, payload = ckpt._deserialize(blob)
    header["config"].pop("block_hash")
    hdr = json.dumps(header).encode()
    path.write_bytes(ckpt.MAGIC + len(hdr).to_bytes(8, "little") + hdr + payload)

    g = ckpt.restore(ap_cfg, sink)
    assert isinstance(g, BlockedBloomFilter)
    assert g.config.block_hash == "ap"
    assert g.include_batch(keys).all()
    np.testing.assert_array_equal(f.words_logical, g.words_logical)

    with pytest.raises(ValueError, match="mismatch on block_hash"):
        ckpt.restore(ap_cfg.replace(block_hash="chunk"), sink)
