"""ISSUE 11: multichip data-parallel serving — tier-1 smoke over
``benchmarks/multichip_load.py`` (the cluster_smoke pattern).

The bench spawns real subprocess servers on a forced 8-device CPU mesh,
serves ONE mesh-sharded filter through the ingestion coalescer, and
GATES: coalesced sharded ingest >= the per-request sharded path,
multi-connection aggregate >= 2x a single connection, and an
anti-gaming requests/flush assert — all with a re-measure-once guard.
It skips clean when the backend cannot host a mesh.
"""

import os
import sys

import pytest


def test_multichip_load_smoke():
    sys.path.insert(
        0,
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "benchmarks"),
    )
    import multichip_load

    out = multichip_load.run_load(duration_s=1.5)
    if out.get("skipped"):
        pytest.skip(out["skipped"])
    # the hard gates (>=2x single, >= per-request path, requests/flush)
    # are asserted inside run_load; pin the headline's shape here
    assert out["devices"] >= 2
    assert out["keys_per_sec_pod"] > out["single_conn_keys_per_sec"]
    assert out["scaling_vs_per_request"] >= 1.0
