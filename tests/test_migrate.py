"""Flat -> blocked migration tool tests (VERDICT r2 next-round #4: the
flat layout's explicit compat-only stance needs a tested migration path)."""

import subprocess
import sys

import numpy as np
import pytest

from tpubloom import BloomFilter, FilterConfig
from tpubloom import checkpoint as ckpt
from tpubloom.filter import BlockedBloomFilter
from tpubloom.migrate import migrate_checkpoint


def _rand_keys(n, rng):
    return [rng.bytes(16) for _ in range(n)]


@pytest.fixture
def flat_ckpt(tmp_path):
    cfg = FilterConfig(m=1 << 20, k=5, key_len=16, key_name="compat")
    rng = np.random.default_rng(0)
    keys = _rand_keys(3000, rng)
    f = BloomFilter(cfg)
    f.insert_batch(keys)
    sink = ckpt.FileSink(str(tmp_path))
    ckpt.save(f, sink)
    return cfg, sink, keys


def test_migrate_roundtrip(flat_ckpt):
    cfg, sink, keys = flat_ckpt
    summary = migrate_checkpoint(
        sink, iter(keys), src_config=cfg, batch_size=512
    )
    assert summary["migrated"] == len(keys) and summary["missing"] == 0
    dst_config = FilterConfig.from_dict(summary["dst_config"])
    assert dst_config.key_name == "compat.blocked"
    g = ckpt.restore(dst_config, sink)
    assert isinstance(g, BlockedBloomFilter)
    assert g.include_batch(keys).all(), "migrated filter lost keys"
    rng = np.random.default_rng(1)
    assert g.include_batch(_rand_keys(3000, rng)).mean() < 0.01


def test_migrate_rejects_foreign_stream(flat_ckpt):
    """A stream that is not the filter's source must fail fast (the
    migrated filter would otherwise silently answer differently)."""
    cfg, sink, keys = flat_ckpt
    rng = np.random.default_rng(2)
    bad = keys[:100] + _rand_keys(50, rng)
    with pytest.raises(ValueError, match="not this filter's source"):
        migrate_checkpoint(sink, iter(bad), src_config=cfg, batch_size=64)


def test_migrate_lenient_superset(flat_ckpt):
    cfg, sink, keys = flat_ckpt
    rng = np.random.default_rng(3)
    extra = _rand_keys(40, rng)
    summary = migrate_checkpoint(
        sink, iter(keys + extra), src_config=cfg, strict=False,
        dst_key_name="compat.blk2",
    )
    # FPR can leak a few extras in; every true key must migrate
    assert summary["migrated"] >= len(keys)
    assert summary["missing"] + summary["migrated"] == len(keys) + len(extra)
    dst_config = FilterConfig.from_dict(summary["dst_config"])
    g = ckpt.restore(dst_config, sink)
    assert g.include_batch(keys).all()


def test_migrate_rejects_non_flat_source(tmp_path):
    sink = ckpt.FileSink(str(tmp_path))
    blocked = FilterConfig(m=1 << 20, k=5, block_bits=512)
    with pytest.raises(ValueError, match="flat single-device"):
        migrate_checkpoint(sink, iter([]), src_config=blocked)


def test_migrate_cli(flat_ckpt, tmp_path):
    cfg, sink, keys = flat_ckpt
    hexfile = tmp_path / "keys.txt"
    # newline-delimited: hex-encode (raw random bytes may contain \n)
    hexkeys = [k.hex().encode() for k in keys]
    hexfile.write_bytes(b"\n".join(hexkeys) + b"\n")
    # the hex strings are what we migrate — insert them into a fresh flat
    # filter so the CLI's stream matches its source filter (hex doubles
    # the length, so this filter uses key_len=32)
    cli_cfg = cfg.replace(key_name="clikeys", key_len=32)
    f = BloomFilter(cli_cfg)
    f.insert_batch(hexkeys)
    ckpt.save(f, sink)
    out = subprocess.run(
        [
            sys.executable, "-m", "tpubloom.migrate",
            "--src", str(sink.directory), "--key-name", "clikeys",
            "--m", str(cfg.m), "--k", str(cfg.k), "--key-len", "32",
            "--keys", str(hexfile),
        ],
        capture_output=True, text=True, timeout=600,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stderr[-2000:]
    import json

    summary = json.loads(out.stdout.strip().splitlines()[-1])
    assert summary["migrated"] == len(hexkeys)
