"""ISSUE 14: multi-tenant filter paging — eviction/hydration chaos suite.

Covers the tentpole end to end:

* **round-robin through a small residency budget**: N ≫ budget tenants
  all serve correctly, every write readable after its tenant was
  evicted and re-hydrated, counting filters prove exactly-once across
  the paging cycle (a double-applied insert would survive one delete);
* **hydration under concurrent load**: concurrent writers/readers
  racing a tenant's eviction + re-hydration never see a torn filter
  and never lose an acked write;
* **the COLD tier**: a warm pool of ~zero bytes demotes every eviction
  straight to checkpoint-only, so hydration restores from the sink;
* **quotas + fairness** (PR-2 shed path): a thrashing cold tenant is
  shed with ``RESOURCE_EXHAUSTED`` + ``retry_after_ms`` while the hot
  set keeps serving;
* **fault points** ``storage.evict`` (aborts the eviction cleanly —
  tenant stays resident and serving) and ``storage.hydrate`` (request
  errors, retry re-hydrates, exactly-once preserved);
* **SIGKILL during eviction loses nothing**: a real subprocess server
  churning evictions under acked load is killed mid-flight and
  restarted — every acked write is readable exactly once;
* **op-log interplay**: the checkpoint-keyed truncation sweep respects
  paged tenants' durable floor, replay hydrates and restart recovers;
  ``apply_record`` hydrates an evicted tenant instead of skipping the
  record as "unknown filter".

The whole module runs under the armed lock tracker (``lock_check_armed``)
and diffs the runtime acquisition graph against the declared manifest at
teardown — the new ``storage.state`` ranks are part of the ISSUE-14
surface.
"""

import os
import threading
import time

import numpy as np
import pytest

from tpubloom import checkpoint as ckpt
from tpubloom import faults
from tpubloom.obs import counters as obs_counters
from tpubloom.server import protocol
from tpubloom.server.client import BloomClient
from tpubloom.server.service import BloomService, build_server
from tpubloom.storage import StorageConfig

pytestmark = pytest.mark.usefixtures("lock_check_armed", "lock_order_manifest")


@pytest.fixture(autouse=True)
def _disarm_all():
    faults.reset()
    yield
    faults.reset()


class _Server:
    def __init__(self, service):
        self.service = service
        self.server, self.port = build_server(service, "127.0.0.1:0")
        self.server.start()
        self.addr = f"127.0.0.1:{self.port}"

    def client(self, **kw) -> BloomClient:
        return BloomClient(self.addr, **kw)

    def stop(self):
        self.service.shutdown()
        self.server.stop(grace=None)
        if self.service.oplog is not None:
            self.service.oplog.close()


def _service(tmp_path, *, oplog=False, sub="", **storage_kw):
    kw = {}
    if oplog:
        from tpubloom.repl import OpLog

        # tiny segments so the truncation test's ~100 records span
        # several — whole-segment GC has something to drop
        kw["oplog"] = OpLog(str(tmp_path / f"oplog{sub}"), segment_bytes=512)
    ckpt_dir = str(tmp_path / f"ckpt{sub}")
    return BloomService(
        sink_factory=lambda config: ckpt.FileSink(ckpt_dir),
        storage=StorageConfig(**storage_kw),
        **kw,
    )


def _hits(client, name, keys):
    return np.asarray(client.include_batch(name, keys), dtype=bool)


def _mk(client, name, *, counting=False, capacity=5000):
    client.create_filter(
        name, capacity=capacity, error_rate=0.01, counting=counting
    )


# -- residency round-robin + exactly-once -------------------------------------


def test_round_robin_through_small_budget(tmp_path):
    """12 tenants through a 3-filter budget: every write readable after
    its tenant was evicted + re-hydrated, residency gauge honors the
    budget, hydration latency histogram fills."""
    s = _Server(_service(tmp_path, max_resident_filters=3))
    try:
        with s.client() as c:
            names = [f"rr-{i}" for i in range(12)]
            for n in names:
                _mk(c, n)
            for rnd in range(2):
                for n in names:
                    assert c.insert_batch(n, [b"%s-%d" % (n.encode(), rnd)]) == 1
            for rnd in range(2):
                for n in names:
                    assert _hits(c, n, [b"%s-%d" % (n.encode(), rnd)]).all()
            assert obs_counters.get("storage_hydrations_total") > 0
            assert obs_counters.get("storage_evictions_total") > 0
            assert len(s.service._filters) <= 3
            assert s.service.metrics.hydrations.n > 0
            # paging is transparent to the control plane too
            assert set(c.list_filters()) >= set(names)
            h = c.health()
            assert h["storage"]["tenants"] == 12
            assert h["storage"]["resident"] <= 3
    finally:
        s.stop()


def test_counting_exactly_once_across_paging(tmp_path):
    """The acceptance proof shape: acked counting inserts survive an
    evict/hydrate cycle exactly once — one delete round empties them."""
    s = _Server(_service(tmp_path, max_resident_filters=2))
    try:
        with s.client() as c:
            _mk(c, "cnt", counting=True)
            keys = [b"eo-%d" % i for i in range(50)]
            assert c.insert_batch("cnt", keys) == 50
            # force cnt out of residency: the eviction rank is KEY-
            # weighted heat, so the fills must out-traffic cnt's 50
            for i in range(4):
                _mk(c, f"fill-{i}")
                c.insert_batch(
                    f"fill-{i}", [b"fx-%d-%d" % (i, j) for j in range(80)]
                )
            assert "cnt" not in s.service._filters, "cnt should be evicted"
            # readable after re-hydration...
            assert _hits(c, "cnt", keys).all()
            # ...and exactly once: a double-applied insert would survive
            # this single delete round
            assert c.delete_batch("cnt", keys) == 50
            assert not _hits(c, "cnt", keys).any()
    finally:
        s.stop()


def test_cold_tier_roundtrip(tmp_path):
    """warm_pool_bytes≈0 demotes every eviction straight to COLD —
    hydration must restore from the checkpoint sink, not host RAM."""
    s = _Server(
        _service(tmp_path, max_resident_filters=2, warm_pool_bytes=1)
    )
    try:
        with s.client() as c:
            _mk(c, "cold-a", counting=True)
            assert c.insert_batch("cold-a", [b"ca-1", b"ca-2"]) == 2
            for i in range(3):
                _mk(c, f"cb-{i}")
                c.insert_batch(
                    f"cb-{i}", [b"y-%d-%d" % (i, j) for j in range(10)]
                )
            assert "cold-a" not in s.service._filters
            assert s.service.storage.summary()["cold"] >= 1
            assert obs_counters.get("storage_warm_demotions") > 0
            assert _hits(c, "cold-a", [b"ca-1", b"ca-2"]).all()
            assert c.delete_batch("cold-a", [b"ca-1", b"ca-2"]) == 2
            assert not _hits(c, "cold-a", [b"ca-1", b"ca-2"]).any()
    finally:
        s.stop()


def test_hydrate_under_concurrent_load_exactly_once(tmp_path):
    """Concurrent writers + readers racing the eviction/hydration cycle:
    every acked write serves exactly once (counting proof), no request
    ever sees a torn filter (all responses are either correct or a
    structured error, and here none error). The hydration concurrency
    cap is raised out of the way — this test targets paging
    correctness under churn, not shed pacing (the quota test covers
    that), and on a 1-core runner a shed storm can exhaust a client's
    retry budget."""
    s = _Server(
        _service(tmp_path, max_resident_filters=2,
                 hydration_max_concurrent=16)
    )
    try:
        with s.client() as admin:
            _mk(admin, "hot", counting=True)
            for i in range(3):
                _mk(admin, f"churn-{i}")
            acked: list = []
            acked_lock = threading.Lock()
            errors: list = []

            def writer(t):
                try:
                    with s.client() as c:
                        for i in range(8):
                            keys = [b"w-%d-%d-%d" % (t, i, j) for j in range(10)]
                            assert c.insert_batch("hot", keys) == 10
                            with acked_lock:
                                acked.extend(keys)
                except BaseException as e:  # noqa: BLE001
                    errors.append(repr(e))

            def churner(t):
                try:
                    with s.client() as c:
                        for i in range(12):
                            # knock "hot" out of residency repeatedly
                            c.insert_batch(f"churn-{t % 3}", [b"c-%d-%d" % (t, i)])
                            c.include_batch(f"churn-{(t + 1) % 3}", [b"zz"])
                except BaseException as e:  # noqa: BLE001
                    errors.append(repr(e))

            threads = [
                threading.Thread(target=writer, args=(t,)) for t in range(3)
            ] + [
                threading.Thread(target=churner, args=(t,)) for t in range(3)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert not errors, errors
            assert len(acked) == 3 * 8 * 10
            assert obs_counters.get("storage_hydrations_total") > 0
            assert _hits(admin, "hot", acked).all()
            assert admin.delete_batch("hot", acked) == len(acked)
            assert not _hits(admin, "hot", acked).any()
    finally:
        s.stop()


# -- quotas + fairness (PR-2 shed path) ---------------------------------------


def test_quota_exceeded_sheds_while_hot_serves(tmp_path):
    """A cold tenant thrashing past its hydration quota sheds with
    RESOURCE_EXHAUSTED + retry_after_ms; the hot (resident) tenant
    keeps serving untouched."""
    s = _Server(
        _service(
            tmp_path,
            max_resident_filters=2,
            tenant_hydrations_per_min=2,
        )
    )
    try:
        with s.client() as c:
            _mk(c, "hot")
            _mk(c, "thrash")
            _mk(c, "pump")
            c.insert_batch("hot", [b"h-1"])
            shed = None
            for i in range(8):
                # alternate pump/thrash so "thrash" keeps falling out of
                # residency and re-hydrating — the token bucket (2/min)
                # runs dry within the loop
                c.insert_batch("hot", [b"h-keep"])  # keep hot hottest
                try:
                    c._call_once(
                        "QueryBatch", {"name": "thrash", "keys": [b"t"]}
                    )
                except protocol.BloomServiceError as e:
                    shed = e
                    break
                c.insert_batch("hot", [b"h-keep2"])
                c._call_once("QueryBatch", {"name": "pump", "keys": [b"p"]})
            assert shed is not None, "thrashing tenant never shed"
            assert shed.code == "RESOURCE_EXHAUSTED"
            assert shed.details.get("retry_after_ms") is not None
            assert shed.details.get("tenant") == "thrash"
            assert obs_counters.get("storage_hydrations_shed") > 0
            # the hot set is untouched: resident, serving, no hydration
            assert "hot" in s.service._filters
            assert _hits(c, "hot", [b"h-1"]).all()
    finally:
        s.stop()


# -- fault points -------------------------------------------------------------


def test_storage_evict_fault_aborts_cleanly(tmp_path):
    """An injected storage.evict fault aborts the eviction — the victim
    stays resident AND serving; the budget catches up on the next
    pressure event once disarmed."""
    s = _Server(_service(tmp_path, max_resident_filters=2))
    try:
        with s.client() as c:
            _mk(c, "a")
            _mk(c, "b")
            c.insert_batch("a", [b"a-1"])
            faults.arm("storage.evict", "once")
            _mk(c, "over")  # budget pass fires the armed fault
            assert obs_counters.get("fault_storage_evict") >= 1
            # aborted: all three still resident, all serving
            assert len(s.service._filters) == 3
            assert _hits(c, "a", [b"a-1"]).all()
            # disarmed: the next pressure event pages back down
            _mk(c, "over2")
            assert len(s.service._filters) <= 2
    finally:
        s.stop()


def test_storage_hydrate_fault_retry_exactly_once(tmp_path):
    """An injected storage.hydrate fault errors the faulting request;
    the retry re-hydrates, and counting counts prove the failed attempt
    applied nothing."""
    s = _Server(_service(tmp_path, max_resident_filters=2))
    try:
        with s.client() as c:
            _mk(c, "cnt", counting=True)
            assert c.insert_batch("cnt", [b"k1", b"k2"]) == 2
            for i in range(3):
                _mk(c, f"pad-{i}")
                c.insert_batch(
                    f"pad-{i}", [b"x-%d-%d" % (i, j) for j in range(10)]
                )
            assert "cnt" not in s.service._filters
            faults.arm("storage.hydrate", "once")
            with pytest.raises(protocol.BloomServiceError) as ei:
                c._call_once("QueryBatch", {"name": "cnt", "keys": [b"k1"]})
            assert ei.value.code == "INTERNAL"
            assert obs_counters.get("fault_storage_hydrate") >= 1
            # retry succeeds; exactly-once: one delete round empties
            assert _hits(c, "cnt", [b"k1", b"k2"]).all()
            assert c.delete_batch("cnt", [b"k1", b"k2"]) == 2
            assert not _hits(c, "cnt", [b"k1", b"k2"]).any()
    finally:
        s.stop()


# -- op-log interplay ---------------------------------------------------------


def test_truncation_respects_paged_floor_and_restart_recovers(tmp_path):
    """The checkpoint-keyed truncation sweep keeps running with paged
    tenants (their eviction landed a durable generation = a real
    floor), and a restart replay rebuilds the evicted tenant's acked
    state from checkpoint + manifest."""
    svc = _service(tmp_path, oplog=True, max_resident_filters=2)
    try:
        svc.CreateFilter(
            {"name": "aa", "capacity": 5000, "error_rate": 0.01,
             "options": {"counting": True}}
        )
        # 20 separate RECORDS (not one batch): aa's durable floor at
        # eviction must sit past a few 512-byte segments, or whole-
        # segment GC has nothing droppable below it
        for i in range(20):
            svc.InsertBatch({"name": "aa", "keys": [b"aa-%d" % i]})
        # push aa out of residency (heat is key-weighted: direct handler
        # calls bypass the wrapper's touch, so only hydration recency
        # counts here — aa, never re-hydrated, ranks coldest)
        for i in range(3):
            svc.CreateFilter(
                {"name": f"bb-{i}", "capacity": 5000, "error_rate": 0.01,
                 "options": {"checkpoint_every": 8}}
            )
            svc.InsertBatch({"name": f"bb-{i}", "keys": [b"pad-%d" % i]})
        assert "aa" not in svc._filters
        # hammer a resident tenant past the truncation cadence; land a
        # checkpoint for every RESIDENT so the sweep has floors to key
        # on — the paged tenants' floors come from their evictions
        for i in range(80):
            svc.InsertBatch({"name": "bb-0", "keys": [b"bb-%d" % i]})
        with svc._lock:
            resident = list(svc._filters.values())
        for mf in resident:
            with mf.lock:
                mf.checkpointer.trigger()
            assert mf.checkpointer.flush()
        svc._maybe_truncate_log()
        # the paged tenants' durable floors did NOT pin the log: their
        # evictions landed generations, so GC actually ran
        assert (
            svc.metrics.snapshot()["counters"].get("repl_log_truncations", 0)
            >= 1
        )
    finally:
        svc.shutdown()
        svc.oplog.close()
    # restart over the same dirs: replay + manifest must bring aa back
    svc2 = _service(tmp_path, oplog=True, max_resident_filters=2)
    try:
        svc2.replay_oplog()
        q = svc2.QueryBatch({"name": "aa", "keys": [b"aa-%d" % i for i in range(20)]})
        hits = np.unpackbits(np.frombuffer(q["hits"], np.uint8), count=20)
        assert hits.all(), "acked writes lost across evict + restart"
        # exactly once: one delete round empties
        svc2.DeleteBatch({"name": "aa", "keys": [b"aa-%d" % i for i in range(20)]})
        q = svc2.QueryBatch({"name": "aa", "keys": [b"aa-%d" % i for i in range(20)]})
        assert not np.unpackbits(
            np.frombuffer(q["hits"], np.uint8), count=20
        ).any()
    finally:
        svc2.shutdown()
        svc2.oplog.close()


def test_apply_record_hydrates_evicted_tenant(tmp_path):
    """A replayed/streamed record naming an EVICTED tenant hydrates it
    and applies — instead of skipping as 'unknown filter' (which on a
    replica would silently lose the record)."""
    svc = _service(tmp_path, oplog=True, max_resident_filters=2)
    try:
        svc.CreateFilter({"name": "ap", "capacity": 5000, "error_rate": 0.01})
        for i in range(3):
            svc.CreateFilter(
                {"name": f"ap-fill-{i}", "capacity": 5000, "error_rate": 0.01}
            )
            svc.InsertBatch({"name": f"ap-fill-{i}", "keys": [b"x"]})
        assert "ap" not in svc._filters
        seq = svc.oplog.last_seq + 100
        svc._replaying = True  # mimic the replay context apply_record runs in
        try:
            applied = svc.apply_record(
                {"method": "InsertBatch", "seq": seq,
                 "req": {"name": "ap", "keys": [b"from-record"]}}
            )
        finally:
            svc._replaying = False
        assert applied is True
        q = svc.QueryBatch({"name": "ap", "keys": [b"from-record"]})
        assert np.unpackbits(np.frombuffer(q["hits"], np.uint8), count=1)[0]
        assert svc._filters["ap"].applied_seq == seq
    finally:
        svc.shutdown()
        svc.oplog.close()


# -- SIGKILL during eviction (subprocess acceptance) --------------------------


_SERVER_CHILD = """\
import sys
import jax
jax.config.update("jax_platforms", "cpu")
from tpubloom.server.service import main
main(sys.argv[1:])
"""


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_child(tmp_path, port):
    import subprocess
    import sys as _sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }
    script = tmp_path / "server_child.py"
    script.write_text(_SERVER_CHILD)
    return subprocess.Popen(
        [
            _sys.executable, str(script), str(port), str(tmp_path / "ckpt"),
            "--repl-log-dir", str(tmp_path / "oplog"),
            "--max-resident-filters", "2",
            # black box armed in chaos mode (ISSUE 16): the post-mortem
            # reads the rings the SIGKILL leaves in the oplog state dir
            "--trace-sample", "0.0",
        ],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
    )


def test_sigkill_during_eviction_loses_nothing(tmp_path):
    """The ISSUE-14 crash acceptance: a real subprocess server churning
    evictions under acked counting-filter load is SIGKILLed mid-churn
    and restarted over the same dirs — every acked write is readable
    EXACTLY once (one delete round empties them). Whatever instant the
    kill hits (snapshot taken, registry popped, final checkpoint
    half-written), recovery runs through the ordinary manifest +
    checkpoint + op-log-tail replay."""
    import signal
    import subprocess

    port = _free_port()
    proc = _spawn_child(tmp_path, port)
    names = [f"sk-{i}" for i in range(6)]
    acked: dict = {n: [] for n in names}
    rids: list = []  # acked insert rids, oldest first (the early ones
    # are slowlog-worthy on a fresh server, so their spans spill)
    proc2 = None
    try:
        with BloomClient(f"127.0.0.1:{port}") as c:
            c.wait_ready(timeout=120)
            for n in names:
                c.create_filter(
                    n, capacity=5000, error_rate=0.01, counting=True
                )
            stop = threading.Event()
            errors: list = []

            def writer():
                i = 0
                with BloomClient(f"127.0.0.1:{port}") as wc:
                    while not stop.is_set():
                        n = names[i % len(names)]
                        keys = [b"%s-%d" % (n.encode(), i)]
                        try:
                            wc.insert_batch(n, keys)
                            acked[n].extend(keys)
                            rids.append(wc.last_rid)
                        except Exception as e:  # noqa: BLE001
                            errors.append(repr(e))
                            return
                        i += 1

            t = threading.Thread(target=writer, daemon=True)
            t.start()
            # wait until the paging machinery is demonstrably churning
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                stats = c.stats()
                hyd = stats["process_counters"].get(
                    "storage_hydrations_total", 0
                )
                if hyd >= 8 and sum(len(v) for v in acked.values()) >= 30:
                    break
                time.sleep(0.1)
            else:
                pytest.fail(f"paging never churned; errors={errors}")
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
        stop.set()
        t.join(timeout=10)

        # post-mortem (ISSUE 16): the killed server's mmap'd black box
        # must still decode — its boot + eviction-churn lifecycle and
        # the earliest acked rids' spilled spans
        from tpubloom.obs import blackbox as bb

        node = bb.read_node(str(tmp_path / "oplog"))
        assert node is not None, "SIGKILL must leave a readable black box"
        kinds = {e["kind"] for e in node["events"]}
        assert "boot" in kinds
        assert "eviction" in kinds, (
            "the paging churn's eviction events must be in the dead ring"
        )
        dead_rids = {s.get("rid") for s in node["spans"]}
        assert rids and rids[0] in dead_rids, (
            "the first acked insert's span must have spilled"
        )

        # restart over the same dirs; replay must bring every acked
        # write back — exactly once
        port2 = _free_port()
        proc2 = subprocess.Popen(
            [
                __import__("sys").executable,
                str(tmp_path / "server_child.py"), str(port2),
                str(tmp_path / "ckpt"),
                "--repl-log-dir", str(tmp_path / "oplog"),
                "--max-resident-filters", "2",
            ],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env={
                **os.environ,
                "JAX_PLATFORMS": "cpu",
                "PYTHONPATH": os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__))
                ) + os.pathsep + os.environ.get("PYTHONPATH", ""),
            },
        )
        with BloomClient(f"127.0.0.1:{port2}") as c2:
            c2.wait_ready(timeout=120)
            total = 0
            for n in names:
                keys = acked[n]
                if not keys:
                    continue
                total += len(keys)
                hits = np.asarray(c2.include_batch(n, keys), dtype=bool)
                missing = [k for k, h in zip(keys, hits) if not h]
                assert not missing, (
                    f"{n}: {len(missing)} acked write(s) lost, e.g. "
                    f"{missing[:3]}"
                )
                # exactly once: one delete round empties
                c2.delete_batch(n, keys)
                assert not np.asarray(
                    c2.include_batch(n, keys), dtype=bool
                ).any(), f"{n}: a write applied twice (survived one delete)"
            assert total >= 30
    finally:
        for p in (proc, proc2):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait(timeout=10)


# -- tier-1 smoke wrapper over the benchmark gate -----------------------------


def test_storage_load_smoke():
    """The benchmarks/storage_smoke.py gate in tier-1: N ≫ budget
    tenants round-robin through a small residency budget on a real
    subprocess server — correctness + hydration histogram + aggregate
    throughput floor."""
    import importlib.util
    import pathlib

    path = (
        pathlib.Path(__file__).resolve().parent.parent
        / "benchmarks" / "storage_smoke.py"
    )
    spec = importlib.util.spec_from_file_location("storage_smoke", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    report = mod.main()
    assert report["ok"], report
