"""Test harness config: run JAX on CPU with 8 fake devices.

SURVEY.md §4.2 item 3 — multi-chip without a cluster:
``xla_force_host_platform_device_count=8`` fakes 8 devices so
shard_map/collective tests run anywhere, replacing the reference's
"just need a local redis-server" property.

Note: this image's sitecustomize registers the axon TPU plugin and
force-sets ``jax_platforms="axon,cpu"`` via ``jax.config.update`` (which
overrides the JAX_PLATFORMS env var), so we must update the config back to
"cpu" *after* importing jax but *before* any backend initializes —
otherwise every test process tries to grab the single TPU tunnel.
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
