"""Test harness config: run JAX on CPU with 8 fake devices.

SURVEY.md §4.2 item 3 — multi-chip without a cluster:
``xla_force_host_platform_device_count=8`` fakes 8 devices so
shard_map/collective tests run anywhere, replacing the reference's
"just need a local redis-server" property.

Note: this image's sitecustomize registers the axon TPU plugin and
force-sets ``jax_platforms="axon,cpu"`` via ``jax.config.update`` (which
overrides the JAX_PLATFORMS env var), so we must update the config back to
"cpu" *after* importing jax but *before* any backend initializes —
otherwise every test process tries to grab the single TPU tunnel.
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture(scope="module")
def lock_check_armed(tmp_path_factory):
    """ISSUE 6: arm the runtime lock-order / held-while-blocking tracker
    (:mod:`tpubloom.utils.locks`) for a whole chaos module.

    In-process services are covered by ``set_enabled(True)`` — every lock
    constructed while the module runs is a tracked, named lock feeding
    the acquisition graph. Caveat: module-level singleton locks
    (``faults._lock``, ``obs.counters._lock``, ``native``'s build lock)
    are constructed at import/collection time, so in a local run without
    ``TPUBLOOM_LOCK_CHECK=1`` in the environment they stay bare and
    untracked; the CI chaos shard exports the env var, which is where
    those singletons get full coverage. Subprocess servers (the
    SIGKILL-failover and
    drain scenarios spawn real children) inherit ``TPUBLOOM_LOCK_CHECK``
    plus a report directory through ``os.environ``; each child that
    exits cleanly dumps a ``lockcheck-<pid>.json`` report there
    (SIGKILLed children can't — that's fine, their locks were tracked
    until the kill and the survivors' reports still land).

    Teardown asserts ZERO violations across the in-process tracker and
    every subprocess report — a new lock-order cycle or a blocking call
    under a registry/filter lock anywhere in the chaos run fails the
    module, which is the ISSUE-6 acceptance gate."""
    from pathlib import Path

    from tpubloom.utils import locks

    # ISSUE 13: when the environment already names a report dir (the CI
    # chaos shard sets one so the reports survive as artifacts and the
    # analysis job replays them through `python -m tpubloom.analysis`),
    # keep collecting there instead of a throwaway tmp dir. All armed
    # modules then share one dir — each teardown re-diffs earlier
    # modules' (clean) reports, which is harmless and makes the gate
    # fleet-wide rather than per-module.
    preset = os.environ.get(locks.REPORT_DIR_ENV)
    if preset:
        report_dir = Path(preset)
        report_dir.mkdir(parents=True, exist_ok=True)
        # stale reports from an EARLIER pytest run (a developer's
        # exported env var, a reused runner) would be re-diffed against
        # today's manifest and fail a clean tree — clear them ONCE per
        # process, so the armed modules of THIS run still accumulate
        # into the shared dir for the CI artifact
        if not getattr(lock_check_armed, "_preset_cleared", False):
            lock_check_armed._preset_cleared = True
            for stale in report_dir.glob("lockcheck-*.json"):
                stale.unlink()
    else:
        report_dir = tmp_path_factory.mktemp("lockcheck")
    saved = {
        k: os.environ.get(k) for k in (locks.ENV_VAR, locks.REPORT_DIR_ENV)
    }
    os.environ[locks.ENV_VAR] = "1"
    os.environ[locks.REPORT_DIR_ENV] = str(report_dir)
    locks.set_enabled(True)
    locks.reset()
    yield
    vios = list(locks.violations())
    locks.set_enabled(None)
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    for path in sorted(report_dir.glob("lockcheck-*.json")):
        rep = json.loads(path.read_text())
        vios.extend(
            {**v, "subprocess": path.name} for v in rep["violations"]
        )
    assert not vios, (
        "lock-check violations recorded during the module:\n"
        + "\n".join(
            f"  [{v.get('subprocess', 'in-process')}] {v['kind']}: "
            f"{v['message']} @ {v['site']}"
            for v in vios
        )
    )


@pytest.fixture(scope="module")
def lock_order_manifest(lock_check_armed):
    """ISSUE 13: the lock-ORDER closure gate, shared by every armed
    chaos module (faults/ha/sync_repl joined cluster/ingest this PR).
    After the whole armed module ran, every acquisition edge in the
    runtime graph — the in-process tracker AND the subprocess exit
    reports — must be DECLARED in the lock-order manifest
    (``tpubloom/analysis/lock_order.py``). An undeclared edge anywhere
    in the armed fleet is a test failure: new lock nesting is a
    reviewed design decision, not an accident discovered at 3am.

    Depends on ``lock_check_armed`` so this teardown runs FIRST (while
    the tracker is still armed and the report dir env var still
    points at this module's collected subprocess reports)."""
    import glob

    from tpubloom.analysis import lock_order
    from tpubloom.utils import locks

    yield
    findings = lock_order.check_live()
    report_dir = os.environ.get(locks.REPORT_DIR_ENV, "")
    if report_dir and os.path.isdir(report_dir):
        for path in sorted(
            glob.glob(os.path.join(report_dir, "lockcheck-*.json"))
        ):
            with open(path) as f:
                findings.extend(
                    {**v, "report": os.path.basename(path)}
                    for v in lock_order.check_report(json.load(f))
                )
    assert not findings, (
        "undeclared lock-order edges (declare deliberately in "
        "tpubloom/analysis/lock_order.py or fix the nesting):\n"
        + "\n".join(f"  {f['message']}" for f in findings)
    )
