"""Synchronous-replication suite (ISSUE 5).

Layers covered:

* the ack path — replicas report applied cursors on the client-streaming
  ``ReplAck`` RPC; ``ReplicaSessions`` tracks per-replica acked seqs;
* the ``Wait`` RPC — Redis ``WAIT`` parity: achieved-count answers, no
  errors on short counts, keyed to the caller's last-write ``repl_seq``;
* the commit barrier — ``min_replicas_to_write`` (server default) and
  per-request ``min_replicas``: writes block after the op-log append
  until the quorum acked, timeout → ``NOT_ENOUGH_REPLICAS`` with
  ``applied: True`` (Redis semantics — no rollback), fast-fail when
  fewer replicas are even connected, Health ``DEGRADED``;
* chaos — ack-loss (``repl.ack`` drops frames in flight; the periodic
  re-ack heals on disarm), ack-stream kill (``repl.ack_recv``; the
  replica re-opens on heartbeat), slow/dead replica (write times out,
  then succeeds once the replica catches back up), and the
  dedup-replay contract (a NOT_ENOUGH_REPLICAS retry under the same
  rid re-WAITS on the same record instead of double-applying);
* observability — ``repl_acked_seq{replica}``, ``wait_blocked_current``,
  the ``wait_barrier_seconds`` histogram;
* the acceptance chaos story — with ``min_replicas=1``, SIGKILL a real
  subprocess primary the instant a quorum-acked batch returns; after
  sentinel failover every acked element is on the new primary with the
  client's rid re-drive DISABLED (``test_quorum_acked_survives_
  sigkill_without_redrive``) — and a ``min_replicas=0`` control run
  proves the barrier is what provides the guarantee
  (``test_async_control_loses_unreplicated_write``).
"""

import os
import threading
import time

import pytest

from tpubloom import faults
from tpubloom.obs import counters as obs_counters
from tpubloom.obs.exposition import parse_families, render_service
from tpubloom.repl import OpLog, ReplicaApplier
from tpubloom.server.client import BloomClient, fetch_topology
from tpubloom.server.protocol import BloomServiceError
from tpubloom.server.service import BloomService, build_server

# ISSUE 6: armed lock-order / held-while-blocking tracking for the whole
# module (asserted violation-free at teardown — tests/conftest.py).
# ISSUE 13: plus the lock-ORDER manifest gate — every runtime
# acquisition edge this module drives must be declared.
pytestmark = pytest.mark.usefixtures("lock_check_armed", "lock_order_manifest")


@pytest.fixture(autouse=True)
def _disarm_all():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture(autouse=True)
def _blackbox_reset():
    """ISSUE 18 satellite: replicas arm the process-global black box
    from their state dir — unmap between tests so a ring in one test's
    tmp_path never absorbs the next test's records."""
    from tpubloom.obs import blackbox

    blackbox.reset_for_tests()
    yield
    blackbox.reset_for_tests()


def _wait(pred, timeout=30.0, poll=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(poll)
    raise AssertionError(f"timed out waiting for {msg}")


def _primary(tmp_path, name="plog", **kwargs):
    oplog = OpLog(str(tmp_path / name))
    svc = BloomService(oplog=oplog, **kwargs)
    srv, port = build_server(svc, "127.0.0.1:0")
    srv.start()
    svc.listen_address = f"127.0.0.1:{port}"
    return svc, srv, port, oplog


def _replica(tmp_path, upstream_port, name=None, chained=False):
    oplog = OpLog(str(tmp_path / name)) if chained else None
    svc = BloomService(oplog=oplog, read_only=True)
    srv, port = build_server(svc, "127.0.0.1:0")
    srv.start()
    svc.listen_address = f"127.0.0.1:{port}"
    applier = ReplicaApplier(
        svc,
        f"127.0.0.1:{upstream_port}",
        reconnect_base=0.05,
        listen_address=svc.listen_address,
    ).start()
    return svc, srv, port, applier


def _warm(client, applier, oplog, name="cnt"):
    """One async write + catch-up so the replica's first-apply jit
    compile never lands inside a barrier timeout window."""
    client.insert_batch(name, [b"warmup"])
    assert applier.wait_for_seq(oplog.last_seq, 60), applier.status()


# -- Wait RPC (WAIT parity) --------------------------------------------------


def test_wait_reports_counts_never_errors(tmp_path):
    psvc, psrv, pport, poplog = _primary(tmp_path)
    c = BloomClient(f"127.0.0.1:{pport}")
    try:
        c.wait_ready()
        c.create_filter("f", capacity=1000, error_rate=0.01)
        assert c.last_write_seq == 1  # mutating responses carry repl_seq
        # no replicas: 0 achieved, immediately for numreplicas=0 ...
        assert c.wait(0) == 0
        # ... and after the timeout (not an error) for numreplicas=1
        t0 = time.monotonic()
        assert c.wait(1, timeout_ms=200) == 0
        assert 0.15 <= time.monotonic() - t0 < 5.0
    finally:
        c.close()
        psrv.stop(grace=None)
        poplog.close()


def test_wait_on_replica_unsupported(tmp_path):
    psvc, psrv, pport, poplog = _primary(tmp_path)
    rsvc, rsrv, rport, applier = _replica(tmp_path, pport)
    rc = BloomClient(f"127.0.0.1:{rport}")
    try:
        with pytest.raises(BloomServiceError, match="UNSUPPORTED"):
            rc.wait(1, timeout_ms=100)
    finally:
        rc.close()
        applier.stop()
        rsrv.stop(grace=None)
        psrv.stop(grace=None)
        poplog.close()


# -- acks + commit barrier ---------------------------------------------------


def test_quorum_write_acks_and_wait_counts(tmp_path):
    psvc, psrv, pport, poplog = _primary(tmp_path)
    c = BloomClient(f"127.0.0.1:{pport}")
    rsvc, rsrv, rport, applier = _replica(tmp_path, pport)
    try:
        c.wait_ready()
        c.create_filter("cnt", capacity=10_000, error_rate=0.01,
                        counting=True)
        _warm(c, applier, poplog)
        # quorum-acked write: blocks until the replica acked its record
        resp = c._rpc(
            "InsertBatch",
            {"name": "cnt", "keys": [b"q1"], "min_replicas": 1,
             "min_replicas_timeout_ms": 30_000},
        )
        assert resp["acked_replicas"] == 1
        seq = resp["repl_seq"]
        assert c.last_write_seq == seq
        # the acked record IS on the replica (that is what the ack means)
        rcheck = BloomClient(f"127.0.0.1:{rport}")
        assert rcheck.include("cnt", b"q1")
        rcheck.close()
        # WAIT agrees, and per-replica gauges/histogram surfaced in obs
        assert c.wait(1, timeout_ms=5000) == 1
        fam = parse_families(render_service(psvc))
        acked = fam["tpubloom_repl_acked_seq"]
        assert any(v >= seq for v in acked.values()), acked
        assert "tpubloom_wait_barrier_seconds_count" in fam
        assert ("tpubloom_wait_blocked_current" in fam)
        h = psvc.Health({})
        assert h["status"] == "SERVING", h
        sess = h["replication"]["replicas"][0]
        assert sess["acked"] >= seq
    finally:
        c.close()
        applier.stop()
        rsrv.stop(grace=None)
        psrv.stop(grace=None)
        poplog.close()


def test_barrier_fast_fails_without_connected_replicas(tmp_path):
    """Redis min-replicas-to-write parity: an isolated primary refuses
    quorum writes in microseconds (and Health says why), but the op DID
    apply locally (WAIT semantics — no rollback)."""
    psvc, psrv, pport, poplog = _primary(
        tmp_path, min_replicas_to_write=1
    )
    c = BloomClient(f"127.0.0.1:{pport}")
    try:
        c.wait_ready(accept_degraded=True)
        t0 = time.monotonic()
        with pytest.raises(BloomServiceError, match="NOT_ENOUGH_REPLICAS") as ei:
            c.create_filter("f", capacity=1000, error_rate=0.01)
        assert time.monotonic() - t0 < 0.5, "fast-fail path waited"
        assert ei.value.details["applied"] is True
        assert ei.value.details["connected"] == 0
        # applied locally despite the refusal — and Health is DEGRADED
        # with both the standing config gap and the fresh quorum failure
        assert "f" in c.list_filters()
        h = c.health()
        assert h["status"] == "DEGRADED"
        assert "min_replicas:0/1" in h["reasons"]
        assert "not_enough_replicas" in h["reasons"]
        # NO-OP mutating RPCs log nothing, so the quorum has nothing to
        # say about them: an exist_ok attach to the existing filter and
        # a drop of a missing one must NOT bounce with
        # NOT_ENOUGH_REPLICAS (the Ruby driver attaches on every boot)
        resp = c.create_filter("f", exist_ok=True)
        assert resp["existed"]
        assert not c.drop_filter("missing-filter")["existed"]
    finally:
        c.close()
        psrv.stop(grace=None)
        poplog.close()


def test_min_replicas_requires_an_oplog(tmp_path):
    svc = BloomService()  # no op log: nothing a replica could ever ack
    srv, port = build_server(svc, "127.0.0.1:0")
    srv.start()
    c = BloomClient(f"127.0.0.1:{port}")
    try:
        c.wait_ready()
        c.create_filter("f", capacity=1000, error_rate=0.01)
        with pytest.raises(BloomServiceError, match="NOT_ENOUGH_REPLICAS"):
            c.insert_batch("f", [b"x"], min_replicas=1)
    finally:
        c.close()
        srv.stop(grace=None)


def test_per_request_override_only_strengthens(tmp_path):
    """The server default and the request quorum compose as max():
    a request can demand MORE durability than the config, not less."""
    psvc, psrv, pport, poplog = _primary(
        tmp_path, min_replicas_to_write=1,
        # a replica's FIRST apply pays the jit compile — the barrier
        # budget must absorb it on this CPU image
        min_replicas_max_lag_ms=60_000,
    )
    c = BloomClient(f"127.0.0.1:{pport}")
    rsvc, rsrv, rport, applier = _replica(tmp_path, pport)
    try:
        _wait(lambda: psvc.repl_sessions.count() == 1, msg="replica connect")
        c.wait_ready()
        c.create_filter("cnt", capacity=10_000, error_rate=0.01,
                        counting=True)
        _warm(c, applier, poplog)
        # server default (1) satisfied by the one replica
        c.insert_batch("cnt", [b"a"])
        # min_replicas=0 cannot weaken the server's 1 → still waits,
        # still succeeds
        c.insert_batch("cnt", [b"b"], min_replicas=0)
        # a stronger per-request quorum than the topology has fast-fails
        with pytest.raises(BloomServiceError, match="NOT_ENOUGH_REPLICAS") as ei:
            c.insert_batch("cnt", [b"c"], min_replicas=2)
        assert ei.value.details["needed"] == 2
        assert ei.value.details["connected"] == 1
    finally:
        c.close()
        applier.stop()
        rsrv.stop(grace=None)
        psrv.stop(grace=None)
        poplog.close()


# -- chaos: ack loss, stream kill, slow replica ------------------------------


def test_ack_loss_blocks_write_then_reack_heals(tmp_path):
    """Arm ``repl.ack`` (frames dropped in flight): a quorum write times
    out with NOT_ENOUGH_REPLICAS even though the replica APPLIED the
    record; Wait reports the honest count under the loss; disarming
    heals through the periodic re-ack with no new records needed."""
    psvc, psrv, pport, poplog = _primary(tmp_path)
    c = BloomClient(f"127.0.0.1:{pport}")
    rsvc, rsrv, rport, applier = _replica(tmp_path, pport)
    try:
        c.wait_ready()
        c.create_filter("cnt", capacity=10_000, error_rate=0.01,
                        counting=True)
        _warm(c, applier, poplog)
        c.insert_batch("cnt", [b"pre"], min_replicas=1,
                       min_replicas_timeout_ms=30_000)

        faults.arm("repl.ack", "always")
        with pytest.raises(BloomServiceError, match="NOT_ENOUGH_REPLICAS") as ei:
            c.insert_batch("cnt", [b"lost-ack"], min_replicas=1,
                           min_replicas_timeout_ms=700)
        lost_seq = ei.value.details["seq"]
        assert ei.value.details["applied"] is True
        # the replica applied it — only the ACK was lost
        assert applier.wait_for_seq(lost_seq, 30)
        rcheck = BloomClient(f"127.0.0.1:{rport}")
        assert rcheck.include("cnt", b"lost-ack")
        rcheck.close()
        # Wait is accurate under the injected loss: 0 replicas acked
        assert c.wait(1, timeout_ms=300, seq=lost_seq) == 0
        assert obs_counters.get("repl_acks_dropped") > 0
        # min_replicas_timeout_ms=0 is a PROBE: fail immediately unless
        # the quorum already acked — an explicit zero must not fall back
        # to the server's default budget
        t0 = time.monotonic()
        with pytest.raises(BloomServiceError, match="NOT_ENOUGH_REPLICAS"):
            c.insert_batch("cnt", [b"probe"], min_replicas=1,
                           min_replicas_timeout_ms=0)
        assert time.monotonic() - t0 < 0.5

        faults.reset()
        # no new writes: the periodic re-ack alone must close the gap
        _wait(
            lambda: psvc.repl_sessions.count_acked(lost_seq) == 1,
            timeout=10,
            msg="re-ack heal",
        )
        assert c.wait(1, timeout_ms=5000, seq=lost_seq) == 1
        # and quorum writes flow again
        c.insert_batch("cnt", [b"post-heal"], min_replicas=1,
                       min_replicas_timeout_ms=30_000)
    finally:
        c.close()
        applier.stop()
        rsrv.stop(grace=None)
        psrv.stop(grace=None)
        poplog.close()


def test_ack_stream_kill_reopens_on_heartbeat(tmp_path):
    """Arm ``repl.ack_recv`` once: the primary kills the ack RPC
    mid-stream; the replica notices at its next heartbeat, re-opens the
    stream under the same session, and re-sends its cursor."""
    psvc, psrv, pport, poplog = _primary(tmp_path)
    c = BloomClient(f"127.0.0.1:{pport}")
    rsvc, rsrv, rport, applier = _replica(tmp_path, pport)
    try:
        c.wait_ready()
        c.create_filter("cnt", capacity=10_000, error_rate=0.01,
                        counting=True)
        _warm(c, applier, poplog)
        before = obs_counters.get("repl_ack_stream_reopened")
        faults.arm("repl.ack_recv", "once")
        # this write's ack frame detonates the fault server-side
        try:
            c.insert_batch("cnt", [b"boom"], min_replicas=1,
                           min_replicas_timeout_ms=700)
        except BloomServiceError:
            pass  # the barrier may or may not catch the re-sent ack
        _wait(
            lambda: obs_counters.get("repl_ack_stream_reopened") > before,
            timeout=15,
            msg="ack stream reopen",
        )
        # fully healed: quorum writes succeed again
        c.insert_batch("cnt", [b"after"], min_replicas=1,
                       min_replicas_timeout_ms=30_000)
        assert c.wait(1, timeout_ms=5000) == 1
    finally:
        c.close()
        applier.stop()
        rsrv.stop(grace=None)
        psrv.stop(grace=None)
        poplog.close()


def test_slow_replica_times_out_then_catches_up(tmp_path):
    """The ISSUE-5 satellite case end to end: a dead/slow replica makes
    the quorum write time out; once a replica reconnects and catches up,
    the SAME logical write (same rid, dedup replay) succeeds without
    double-applying."""
    psvc, psrv, pport, poplog = _primary(tmp_path)
    c = BloomClient(f"127.0.0.1:{pport}")
    rsvc, rsrv, rport, applier = _replica(tmp_path, pport)
    try:
        c.wait_ready()
        c.create_filter("cnt", capacity=10_000, error_rate=0.01,
                        counting=True)
        _warm(c, applier, poplog)
        applier.stop()  # the replica goes dark
        _wait(lambda: psvc.repl_sessions.count() == 0, msg="session drop")

        with pytest.raises(BloomServiceError, match="NOT_ENOUGH_REPLICAS"):
            c.insert_batch("cnt", [b"stuck"], min_replicas=1,
                           min_replicas_timeout_ms=400)
        rid = c.last_rid

        # replica comes back and catches up
        applier2 = ReplicaApplier(
            rsvc,
            f"127.0.0.1:{pport}",
            reconnect_base=0.05,
            initial_cursor=applier.cursor,
            initial_log_id=applier.log_id,
        ).start()
        try:
            assert applier2.wait_for_seq(poplog.last_seq, 30), (
                applier2.status()
            )
            # re-drive the SAME rid: dedup answers the cached response
            # and the barrier re-waits on the ORIGINAL record — now
            # acked, so it succeeds; the count stays exactly 1
            resp = c._call_once(
                "InsertBatch",
                {"name": "cnt", "keys": [b"stuck"], "rid": rid,
                 "min_replicas": 1, "min_replicas_timeout_ms": 30_000},
            )
            assert resp["acked_replicas"] == 1
            c.delete_batch("cnt", [b"stuck"])
            assert not c.include("cnt", b"stuck"), (
                "the dedup replay double-applied the quorum write"
            )
        finally:
            applier2.stop()
    finally:
        c.close()
        rsrv.stop(grace=None)
        psrv.stop(grace=None)
        poplog.close()


def test_ack_age_gate_counts_only_fresh_acks():
    """ISSUE 6 satellite (Redis min-replicas-max-lag parity): a replica
    that acked a seq and then went SILENT stops counting toward an
    age-gated quorum — its cursor is history, not durability."""
    from tpubloom.repl.primary import ReplicaSessions

    sess = ReplicaSessions()
    sid = sess.register("test-peer")
    sess.ack(sid, 5)
    assert sess.count_acked(5) == 1
    assert sess.count_acked(5, max_age=10.0) == 1
    time.sleep(0.15)
    # unaged counting still sees the ack; the freshness gate does not
    assert sess.count_acked(5) == 1
    assert sess.count_acked(5, max_age=0.1) == 0
    # an age-gated barrier on a stale-but-connected replica TIMES OUT
    # (it does not fast-fail: the session is connected) and reports the
    # fresh count, without a notify ever arriving — the wait re-polls
    # freshness on its own clock
    t0 = time.monotonic()
    assert sess.wait_acked(5, 1, 0.3, max_age=0.1) == 0
    assert time.monotonic() - t0 < 5.0
    # an idle re-ack of the SAME seq refreshes acked_at: fresh again
    sess.ack(sid, 5)
    assert sess.count_acked(5, max_age=0.1) == 1
    assert sess.wait_acked(5, 1, 0.3, max_age=0.1) == 1


def test_zero_lag_budget_disables_freshness_gate(tmp_path):
    """Redis ``min-replicas-max-lag 0`` = the lag check is DISABLED,
    not infinitely strict: quorum writes against a healthy replica must
    succeed (and not busy-spin the barrier into a guaranteed timeout)."""
    from tpubloom.repl.primary import ReplicaSessions

    # unit: max_age=0 counts like no gate at all
    sess = ReplicaSessions()
    sid = sess.register("test-peer")
    sess.ack(sid, 4)
    time.sleep(0.05)
    assert sess.count_acked(4, max_age=0) == 1
    assert sess.wait_acked(4, 1, 0.2, max_age=0.0) == 1

    # service: a 0 lag budget still lets a healthy quorum write through
    psvc, psrv, pport, poplog = _primary(tmp_path, min_replicas_max_lag_ms=0)
    c = BloomClient(f"127.0.0.1:{pport}")
    rsvc, rsrv, rport, applier = _replica(tmp_path, pport)
    try:
        c.wait_ready()
        c.create_filter("cnt", capacity=10_000, error_rate=0.01,
                        counting=True)
        _warm(c, applier, poplog)
        resp = c._rpc(
            "InsertBatch",
            {"name": "cnt", "keys": [b"z1"], "min_replicas": 1,
             "min_replicas_timeout_ms": 30_000},
        )
        assert resp["acked_replicas"] == 1
        # no explicit wait budget: the default normally reuses the lag
        # budget, but lag 0 must fall back to the stock budget instead
        # of turning every quorum write into a 0ms instant probe
        resp = c._rpc(
            "InsertBatch",
            {"name": "cnt", "keys": [b"z2"], "min_replicas": 1},
        )
        assert resp["acked_replicas"] == 1
    finally:
        c.close()
        applier.stop()
        rsrv.stop(grace=None)
        psrv.stop(grace=None)
        poplog.close()


def test_idle_reack_wakes_age_gated_waiter():
    """A quorum waiter blocked on FRESHNESS (seq already acked, frame
    too old) must wake on the idle re-ack that refreshes it — the
    re-ack advances no seq, so this pins the waiters-present notify."""
    from tpubloom.repl.primary import ReplicaSessions

    sess = ReplicaSessions()
    sid = sess.register("test-peer")
    sess.ack(sid, 3)
    time.sleep(0.2)  # the ack frame goes stale for a 0.15s budget
    got: list = []
    t = threading.Thread(
        target=lambda: got.append(sess.wait_acked(3, 1, 5.0, max_age=0.15)),
        daemon=True,
    )
    t.start()
    time.sleep(0.05)
    sess.ack(sid, 3)  # idle re-ack: same seq, fresh frame
    t.join(timeout=10)
    assert got == [1], got


def test_dedup_rewait_rejects_stale_acks(tmp_path):
    """The service-level customer of the freshness gate: a dedup-cache
    replay re-waits on its original record's seq — which the replica
    acked LONG AGO before going silent. Without the age gate the stale
    cursor would satisfy the quorum forever; with it the barrier answers
    NOT_ENOUGH_REPLICAS and names the stale ack, and a healed replica
    (acks flowing again) satisfies the same re-drive."""
    psvc, psrv, pport, poplog = _primary(
        tmp_path, min_replicas_max_lag_ms=300
    )
    c = BloomClient(f"127.0.0.1:{pport}")
    rsvc, rsrv, rport, applier = _replica(tmp_path, pport)
    try:
        c.wait_ready()
        c.create_filter("cnt", capacity=10_000, error_rate=0.01,
                        counting=True)
        _warm(c, applier, poplog)
        resp = c._rpc(
            "InsertBatch",
            {"name": "cnt", "keys": [b"fresh1"], "min_replicas": 1,
             "min_replicas_timeout_ms": 30_000},
        )
        assert resp["acked_replicas"] == 1
        rid = c.last_rid
        # the replica stays CONNECTED but every ack frame (including the
        # 0.5s periodic idle re-acks) is dropped in flight: acked_at
        # ages past the 300ms lag budget while the acked seq stands
        faults.arm("repl.ack", "always")
        time.sleep(0.8)
        with pytest.raises(BloomServiceError, match="NOT_ENOUGH_REPLICAS") as ei:
            c._call_once(
                "InsertBatch",
                {"name": "cnt", "keys": [b"fresh1"], "rid": rid,
                 "min_replicas": 1, "min_replicas_timeout_ms": 500},
            )
        details = ei.value.details
        assert details["applied"] is True
        assert details.get("stale_acks", 0) >= 1, (
            f"the failure must name the stale ack, got {details}"
        )
        counters = psvc.metrics.snapshot()["counters"]
        assert counters.get("quorum_stale_acks", 0) >= 1, counters
        # heal: acks flow again, the periodic re-ack refreshes acked_at,
        # and the SAME rid re-drive now passes the freshness gate
        faults.reset()
        resp = c._call_once(
            "InsertBatch",
            {"name": "cnt", "keys": [b"fresh1"], "rid": rid,
             "min_replicas": 1, "min_replicas_timeout_ms": 30_000},
        )
        assert resp["acked_replicas"] == 1
        # dedup replay: applied exactly once
        c.delete_batch("cnt", [b"fresh1"])
        assert not c.include("cnt", b"fresh1")
    finally:
        c.close()
        applier.stop()
        rsrv.stop(grace=None)
        psrv.stop(grace=None)
        poplog.close()


def test_barrier_unblocks_when_last_replica_disconnects(tmp_path):
    """A quorum made unattainable MID-WAIT (the last replica
    disconnects while the barrier is blocked) must fail immediately,
    not sleep out the whole timeout budget."""
    psvc, psrv, pport, poplog = _primary(tmp_path)
    c = BloomClient(f"127.0.0.1:{pport}")
    rsvc, rsrv, rport, applier = _replica(tmp_path, pport)
    try:
        c.wait_ready()
        c.create_filter("cnt", capacity=10_000, error_rate=0.01,
                        counting=True)
        _warm(c, applier, poplog)
        faults.arm("repl.ack", "always")  # acks never arrive
        result: dict = {}

        def writer():
            try:
                c.insert_batch("cnt", [b"midwait"], min_replicas=1,
                               min_replicas_timeout_ms=20_000)
                result["outcome"] = "ok"
            except BloomServiceError as e:
                result["outcome"] = e.code

        t0 = time.monotonic()
        t = threading.Thread(target=writer, daemon=True)
        t.start()
        _wait(
            lambda: obs_counters.get_gauge("wait_blocked_current") > 0,
            msg="barrier blocked",
        )
        applier.stop()  # the quorum just became unattainable
        t.join(timeout=10)
        assert not t.is_alive(), "barrier slept out its 20s budget"
        assert result["outcome"] == "NOT_ENOUGH_REPLICAS"
        assert time.monotonic() - t0 < 10
    finally:
        c.close()
        applier.stop()
        rsrv.stop(grace=None)
        psrv.stop(grace=None)
        poplog.close()


def test_sync_replica_blackbox_covers_quorum_applies(tmp_path):
    """ISSUE 18 satellite: an in-process sync replica given a state
    store arms the PR-16 black box there, so the post-mortem of a
    quorum write covers the REPLICA side too — the ring names the node
    (role/addr/upstream) and carries the forced ``repl.apply`` spans
    behind the ack the barrier waited on."""
    from tpubloom.obs import blackbox as bb
    from tpubloom.repl.replica import ReplicaStateStore

    psvc, psrv, pport, poplog = _primary(
        tmp_path, min_replicas_to_write=1, trace_sample=1.0
    )
    rsvc = BloomService(read_only=True)
    rsrv, rport = build_server(rsvc, "127.0.0.1:0")
    rsrv.start()
    rsvc.listen_address = f"127.0.0.1:{rport}"
    state_dir = str(tmp_path / "replica-state")
    applier = ReplicaApplier(
        rsvc,
        f"127.0.0.1:{pport}",
        reconnect_base=0.05,
        state_store=ReplicaStateStore(state_dir),
        listen_address=rsvc.listen_address,
    ).start()
    c = BloomClient(f"127.0.0.1:{pport}", trace_sample=1.0)
    try:
        assert bb.enabled(), "a state store alone must arm the black box"
        c.wait_ready()
        c.create_filter("cnt", capacity=10_000, error_rate=0.01,
                        counting=True)
        _warm(c, applier, poplog)
        # the barrier releases only after THIS replica acked the apply
        resp = c._call_once(
            "InsertBatch",
            {"name": "cnt", "keys": [b"quorum-bb"], "min_replicas": 1,
             "min_replicas_timeout_ms": 30_000,
             "trace": {"forced": True}},
        )
        assert resp["acked_replicas"] >= 1
        assert applier.wait_for_seq(poplog.last_seq, 60), applier.status()
    finally:
        c.close()
        applier.stop()
        rsrv.stop(grace=None)
        psrv.stop(grace=None)
        poplog.close()
    node = bb.read_node(state_dir)
    assert node is not None, "replica state dir must hold a black box"
    assert node["meta"].get("role") == "replica"
    assert node["meta"].get("addr") == f"127.0.0.1:{rport}"
    assert node["meta"].get("primary") == f"127.0.0.1:{pport}"
    applies = [s for s in node["spans"] if s.get("name") == "repl.apply"]
    assert any(
        s.get("attrs", {}).get("filter") == "cnt" for s in applies
    ), "the quorum-acked apply must have spilled into the replica ring"


# -- the acceptance chaos story ----------------------------------------------

#: mirrors test_ha's child pattern: the image's sitecustomize force-sets
#: jax_platforms to the TPU plugin, so the child must pin cpu first.
_SERVER_CHILD = """\
import sys
import jax
jax.config.update("jax_platforms", "cpu")
from tpubloom.server.service import main
main(sys.argv[1:])
"""


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _sentinel_trio(pport):
    from tpubloom.ha.sentinel import Sentinel

    sents = [
        Sentinel(
            f"127.0.0.1:{pport}",
            peers=[],
            poll_s=0.1,
            down_after_s=0.5,
            failover_cooldown_s=0.5,
        )
        for _ in range(3)
    ]
    for s in sents:
        s.peers.extend(x.address for x in sents if x is not s)
        s.quorum = 2
    for s in sents:
        s.start()
    return sents


def test_quorum_acked_survives_sigkill_without_redrive(tmp_path):
    """The ISSUE-5 acceptance scenario: batches written under
    ``min_replicas=1``; the primary (a real process) is SIGKILLed the
    instant the last quorum-acked batch returns; the sentinel quorum
    promotes the most-caught-up replica — and every acked element is
    readable on the new primary with the client's rid re-drive
    DISABLED. The quorum ack is the guarantee now, not the PR-4
    client-side patch."""
    import signal
    import subprocess
    import sys as _sys

    port = _free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }
    script = tmp_path / "server_child.py"
    script.write_text(_SERVER_CHILD)
    proc = subprocess.Popen(
        [_sys.executable, str(script), str(port),
         "--repl-log-dir", str(tmp_path / "primary-log"),
         # black box armed in chaos mode (ISSUE 16): sample 0.0 spills
         # only slowlog-worthy work — what the post-mortem below reads
         "--trace-sample", "0.0"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
    )
    boot = BloomClient(f"127.0.0.1:{port}")
    sents = []
    r1 = r2 = None
    try:
        boot.wait_ready(timeout=120)
        boot.create_filter(
            "cnt", capacity=50_000, error_rate=0.01, counting=True
        )
        r1 = _replica(tmp_path, port, name="r1log", chained=True)
        r2 = _replica(tmp_path, port, name="r2log", chained=True)
        sents = _sentinel_trio(port)
        _wait(
            lambda: len(sents[0].handle_Topology({})["replicas"]) == 2,
            msg="replica discovery",
        )
        # warm the replicas' jit outside any barrier window (the client
        # tracks the subprocess primary's log seq via repl_seq)
        boot.insert_batch("cnt", [b"warmup"])
        for r in (r1, r2):
            assert r[3].wait_for_seq(boot.last_write_seq, 60), r[3].status()

        batches = [
            [b"acc-%03d-%03d" % (i, j) for j in range(20)] for i in range(6)
        ]
        for keys in batches:
            boot.insert_batch(
                "cnt", keys, min_replicas=1, min_replicas_timeout_ms=60_000
            )
        last_rid = boot.last_rid
        # the last quorum-acked batch JUST returned: kill the primary NOW
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)

        # post-mortem (ISSUE 16): the killed primary's mmap'd black box
        # must carry its lifecycle AND the final quorum-acked batch's
        # spilled spans — the write it acked the instant it died
        from tpubloom.obs import blackbox as bb

        node = bb.read_node(str(tmp_path / "primary-log"))
        assert node is not None, "SIGKILL must leave a readable black box"
        assert node["meta"].get("role") == "primary"
        assert "boot" in [e["kind"] for e in node["events"]]
        assert last_rid in {s.get("rid") for s in node["spans"]}, (
            "the final quorum-acked rid's span must have spilled"
        )

        _wait(
            lambda: any(s.failovers for s in sents),
            timeout=90,
            msg="sentinel failover",
        )
        # fetch_topology answers from the FIRST sentinel that responds,
        # which may not be the election leader — its view flips only
        # when the leader's AnnounceTopology lands, so poll for the
        # new primary instead of asserting on one snapshot
        topo = None

        def _new_primary():
            nonlocal topo
            topo = fetch_topology([s.address for s in sents])
            return (
                topo is not None
                and topo["primary"] != f"127.0.0.1:{port}"
            )

        _wait(_new_primary, timeout=30, msg="topology announce")

        # re-drive DISABLED: a fresh client only READS the new primary —
        # every quorum-acked element must already be there, because the
        # ack proves it reached a replica and the sentinel's
        # most-caught-up election (highest cursor) picks a winner whose
        # log contains every record ANY replica acked
        fresh = BloomClient(topo["primary"], max_retries=0)
        all_keys = [k for b in batches for k in b]
        hits = fresh.include_batch("cnt", all_keys)
        assert hits.all(), (
            f"{int((~hits).sum())} quorum-acked key(s) missing on the "
            f"promotion winner"
        )
        fresh.close()
    finally:
        if proc.poll() is None:
            proc.kill()
        for s in sents:
            s.stop()
        for r in (r1, r2):
            if r is None:
                continue
            svc, srv, _, app = r
            if svc.replica_applier is not None:
                svc.replica_applier.stop()
            app.stop()
            srv.stop(grace=None)
            if svc.oplog is not None:
                svc.oplog.close()
        boot.close()


def test_async_control_loses_unreplicated_write(tmp_path):
    """The control run the acceptance criterion demands: with the
    barrier OFF (min_replicas=0) an acked write that never replicated is
    GONE after a primary crash + promotion — proving the quorum ack, not
    luck, is what the sigkill test's guarantee rests on. And with the
    barrier ON in the same topology, the write is refused rather than
    falsely acked."""
    psvc, psrv, pport, poplog = _primary(tmp_path)
    c = BloomClient(f"127.0.0.1:{pport}")
    rsvc, rsrv, rport, applier = _replica(
        tmp_path, pport, name="rlog", chained=True
    )
    try:
        c.wait_ready()
        c.create_filter("cnt", capacity=10_000, error_rate=0.001,
                        counting=True)
        _warm(c, applier, poplog)
        b0 = [b"dur-%03d" % i for i in range(20)]
        b1 = [b"gone-%03d" % i for i in range(20)]
        # B0: quorum-acked — provably on the replica
        c.insert_batch("cnt", b0, min_replicas=1,
                       min_replicas_timeout_ms=30_000)
        # the replica goes deaf BEFORE B1
        applier.stop()
        _wait(lambda: psvc.repl_sessions.count() == 0, msg="session drop")
        # B1: async ack (min_replicas=0) — the primary alone has it
        c.insert_batch("cnt", b1)
        # barrier honesty: the same write under min_replicas=1 is
        # REFUSED (fast-fail), not falsely acked
        with pytest.raises(BloomServiceError, match="NOT_ENOUGH_REPLICAS"):
            c.insert_batch("cnt", [b"refused"], min_replicas=1,
                           min_replicas_timeout_ms=400)

        # primary "crashes"; the replica is promoted
        psrv.stop(grace=None)
        rc = BloomClient(f"127.0.0.1:{rport}")
        resp = rc.promote()
        assert resp["ok"] and not resp["already_primary"]
        hits0 = rc.include_batch("cnt", b0)
        assert hits0.all(), "quorum-acked batch lost despite the barrier"
        hits1 = rc.include_batch("cnt", b1)
        assert not hits1.all(), (
            "the async-acked batch survived — the control cannot "
            "distinguish the barrier from plain replication luck"
        )
        rc.close()
    finally:
        c.close()
        if rsvc.replica_applier is not None:
            rsvc.replica_applier.stop()
        applier.stop()
        rsrv.stop(grace=None)
        psrv.stop(grace=None)
        poplog.close()
        if rsvc.oplog is not None:
            rsvc.oplog.close()


def test_wait_smoke():
    """benchmarks/wait_smoke.py runs in tier-1 so the durability surface
    cannot silently rot (and CI runs it standalone)."""
    import importlib
    import sys

    bench_dir = os.path.join(os.path.dirname(__file__), "..", "benchmarks")
    sys.path.insert(0, os.path.abspath(bench_dir))
    try:
        wait_smoke = importlib.import_module("wait_smoke")
        result = wait_smoke.run_smoke()
    finally:
        sys.path.pop(0)
    assert result["wait_nreplicas"] == 2
    assert set(result["mean_ms"]) == {"0", "1", "2"}
    assert set(result["overhead_ms"]) == {"1", "2"}
