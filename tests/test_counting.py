"""Counting bloom filter tests (BASELINE config 4: 4-bit counters,
insert/delete/query mix, exercises scatter-add)."""

import numpy as np
import pytest

pytest.importorskip("hypothesis")  # property tests need it; skip cleanly without
from hypothesis import given, settings
from hypothesis import strategies as st

from tpubloom import CountingBloomFilter, CPUBloomFilter, FilterConfig


@pytest.fixture(scope="module")
def cfg():
    return FilterConfig(m=1 << 20, k=5, key_len=16, counting=True)


def _rand_keys(n, rng, nbytes=16):
    return [rng.bytes(nbytes) for _ in range(n)]


def test_insert_delete_query_mix(cfg):
    rng = np.random.default_rng(0)
    keep = _rand_keys(500, rng)
    drop = _rand_keys(500, rng)
    f = CountingBloomFilter(cfg)
    f.insert_batch(keep + drop)
    assert f.include_batch(keep + drop).all()
    f.delete_batch(drop)
    assert f.include_batch(keep).all(), "deleting other keys must not evict"
    # deleted keys are (almost surely) gone at this load factor
    assert f.include_batch(drop).mean() < 0.05


def test_parity_vs_oracle(cfg):
    rng = np.random.default_rng(1)
    keys = _rand_keys(400, rng)
    dup = keys[:50]  # duplicates within one batch
    f, o = CountingBloomFilter(cfg), CPUBloomFilter(cfg)
    for batch in (keys + dup, dup):
        f.insert_batch(batch)
        o.insert_batch(batch)
    np.testing.assert_array_equal(np.asarray(f.words), o.words)
    f.delete_batch(dup + dup[:10])
    o.delete_batch(dup + dup[:10])
    np.testing.assert_array_equal(np.asarray(f.words), o.words)
    probe = keys + _rand_keys(400, rng)
    np.testing.assert_array_equal(f.include_batch(probe), o.include_batch(probe))


def test_saturation_at_15(cfg):
    f, o = CountingBloomFilter(cfg), CPUBloomFilter(cfg)
    key = [b"hot-key"]
    for _ in range(20):  # 20 > 15: counters must saturate, not wrap
        f.insert_batch(key)
        o.insert_batch(key)
    np.testing.assert_array_equal(np.asarray(f.words), o.words)
    assert f.include(b"hot-key")
    vals = np.asarray(f.words)
    nibbles = np.concatenate([(vals >> (4 * i)) & 15 for i in range(8)])
    assert nibbles.max() == 15


def test_saturated_batch_single_shot(cfg):
    # 20 copies of the same key in ONE batch — multiplicity clamps in-kernel.
    f, o = CountingBloomFilter(cfg), CPUBloomFilter(cfg)
    f.insert_batch([b"dup"] * 20)
    o.insert_batch([b"dup"] * 20)
    np.testing.assert_array_equal(np.asarray(f.words), o.words)


def test_delete_floors_at_zero(cfg):
    f, o = CountingBloomFilter(cfg), CPUBloomFilter(cfg)
    f.insert_batch([b"once"])
    o.insert_batch([b"once"])
    for _ in range(3):  # over-delete
        f.delete_batch([b"once"])
        o.delete_batch([b"once"])
    np.testing.assert_array_equal(np.asarray(f.words), o.words)
    assert np.asarray(f.words).sum() == 0
    assert not f.include(b"once")


def test_counting_roundtrip_bytes(cfg):
    rng = np.random.default_rng(2)
    keys = _rand_keys(200, rng)
    f = CountingBloomFilter(cfg)
    f.insert_batch(keys)
    g = CountingBloomFilter.from_bytes(cfg, f.to_bytes())
    assert g.include_batch(keys).all()
    np.testing.assert_array_equal(np.asarray(f.words), np.asarray(g.words))


@given(
    ops=st.lists(
        st.tuples(st.booleans(), st.lists(st.binary(min_size=1, max_size=8), min_size=1, max_size=30)),
        min_size=1,
        max_size=6,
    )
)
@settings(max_examples=20, deadline=None)
def test_hypothesis_op_sequences(ops):
    cfg = FilterConfig(m=1 << 14, k=3, key_len=8, counting=True)
    f, o = CountingBloomFilter(cfg), CPUBloomFilter(cfg)
    for is_delete, keys in ops:
        if is_delete:
            f.delete_batch(keys)
            o.delete_batch(keys)
        else:
            f.insert_batch(keys)
            o.insert_batch(keys)
        np.testing.assert_array_equal(np.asarray(f.words), o.words)
