"""Blocked counting filter: sweep kernel (interpret) vs the flat-counting
fallback, saturation semantics, and the class surface.

The blocked counting layout stores all k 4-bit counters of a key in one
block; its array is bit-identical to the flat counting layout applied at
positions ``blk * counters_per_block + c`` — so the fallback path (which
literally calls ops.counting.counter_update on the raveled array, whose
semantics are pinned against cpu_ref) is the ground truth here.
"""

import numpy as np
import pytest

pytest.importorskip("hypothesis")  # property tests need it; skip cleanly without
from hypothesis import given, settings, strategies as st

import jax
import jax.numpy as jnp

from tpubloom import BlockedCountingBloomFilter, FilterConfig
from tpubloom.filter import make_blocked_counter_fn, make_blocked_counting_query_fn
from tpubloom.ops.sweep import make_sweep_counter_fn
from tpubloom.utils.packing import pack_keys


@pytest.fixture
def config():
    return FilterConfig(m=1 << 20, k=5, key_len=16, counting=True, block_bits=512)


def _zeros(config):
    return jnp.zeros((config.n_blocks, config.words_per_block), jnp.uint32)


def _pair(config, increment):
    fb = jax.jit(
        make_blocked_counter_fn(
            config.replace(insert_path="scatter"), increment=increment
        )
    )
    sw = jax.jit(
        make_sweep_counter_fn(config, increment=increment, interpret=True)
    )
    return fb, sw


def test_sweep_matches_fallback_insert_delete(config):
    rng = np.random.default_rng(0)
    keys = jnp.asarray(rng.integers(0, 256, (512, 16), dtype=np.uint8))
    lengths = jnp.asarray(
        np.where(np.arange(512) % 7 == 0, -1, 16).astype(np.int32)
    )
    fb_i, sw_i = _pair(config, True)
    fb_d, sw_d = _pair(config, False)
    a = fb_i(_zeros(config), keys, lengths)
    b = sw_i(_zeros(config), keys, lengths)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert np.asarray(a).any()
    a = fb_d(a, keys[:100], lengths[:100])
    b = sw_d(b, keys[:100], lengths[:100])
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_saturation_and_floor(config):
    # 40 copies of one key in one batch: counters clamp at 15 (one clamp
    # against the pre-batch value), then a 40-copy delete floors at 0
    key = np.frombuffer(b"the-counted-key!", dtype=np.uint8)
    keys = jnp.asarray(np.tile(key, (40, 1)))
    lengths = jnp.full((40,), 16, jnp.int32)
    fb_i, sw_i = _pair(config, True)
    fb_d, sw_d = _pair(config, False)
    a = fb_i(_zeros(config), keys, lengths)
    b = sw_i(_zeros(config), keys, lengths)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    nz = np.asarray(a)[np.asarray(a) != 0]
    # every touched nibble saturated at 15 (k distinct counters, or
    # collided counters still clamp at 15)
    for word in nz:
        for shift in range(0, 32, 4):
            nib = (word >> shift) & 15
            assert nib in (0, 15)
    a = fb_d(a, keys, lengths)
    b = sw_d(b, keys, lengths)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.asarray(a).any()


def test_class_surface_and_roundtrip(config):
    f = BlockedCountingBloomFilter(config)
    rng = np.random.default_rng(2)
    keys = [rng.bytes(16) for _ in range(400)]
    f.insert_batch(keys)
    assert f.include_batch(keys).all()
    f.delete_batch(keys[:200])
    assert f.include_batch(keys[200:]).all()
    assert f.include_batch(keys[:200]).mean() < 0.05
    g = BlockedCountingBloomFilter.from_bytes(config, f.to_bytes())
    np.testing.assert_array_equal(
        f.include_batch(keys), g.include_batch(keys)
    )


def test_fat_counting_kernel_parity(config):
    """The fat-row counting kernel (interpret) == flat-counting fallback
    at a shape choose_fat_params accepts, including within-batch
    duplicate skew, saturation, and delete floor — and via BOTH the
    logical and the fat storage entry (storage_fat=True is what the
    filter class actually uses)."""
    from tpubloom.ops.sweep import choose_fat_params

    B = 1024
    assert choose_fat_params(config.n_blocks, B, config.words_per_block)
    rng = np.random.default_rng(7)
    base = rng.integers(0, 256, (B, 16), dtype=np.uint8)
    # heavy duplication: 1/4 of the batch is one repeated key
    base[: B // 4] = base[0]
    keys = jnp.asarray(base)
    lengths = jnp.full((B,), 16, jnp.int32)
    fb_i, sw_i = _pair(config, True)
    fb_d, sw_d = _pair(config, False)
    a = fb_i(_zeros(config), keys, lengths)
    b = sw_i(_zeros(config), keys, lengths)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    a = fb_d(a, keys, lengths)
    b = sw_d(b, keys, lengths)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.asarray(a).any()  # floor back to empty

    # fat storage entry: same bytes through the [NB/J, 128] view
    sw_fat = jax.jit(
        make_sweep_counter_fn(config, increment=True, interpret=True,
                              storage_fat=True)
    )
    J = 128 // config.words_per_block
    fat0 = jnp.zeros(
        (config.n_blocks // J, 128), jnp.uint32
    )
    c = sw_fat(fat0, keys, lengths)
    expect = np.asarray(fb_i(_zeros(config), keys, lengths))
    np.testing.assert_array_equal(
        np.asarray(c).reshape(expect.shape), expect
    )


def test_blocked_counting_class_uses_fat_storage(config):
    """BlockedCountingBloomFilter holds fat [NB/J, 128] device storage
    (round-4 change mirroring BlockedBloomFilter), words_logical undoes
    it, and to_bytes/from_bytes stay layout-agnostic."""
    from tpubloom.filter import blocked_storage_fat

    assert blocked_storage_fat(config)
    f = BlockedCountingBloomFilter(config)
    nb, w = config.n_blocks, config.words_per_block
    assert f.words.shape == (nb * w // 128, 128)
    assert f.words_logical.shape == (nb, w)
    rng = np.random.default_rng(8)
    keys = [rng.bytes(16) for _ in range(600)]
    f.insert_batch(keys)
    assert f.include_batch(keys).all()
    assert f.words_logical.astype("<u4").tobytes() == f.to_bytes()
    g = BlockedCountingBloomFilter.from_bytes(config, f.to_bytes())
    assert g.words.shape == f.words.shape
    assert g.include_batch(keys).all()
    g.delete_batch(keys)
    assert not g.include_batch(keys).any()


def test_query_requires_all_counters(config):
    # membership requires ALL k counters nonzero — craft the array by
    # hand: with every counter of the key set, membership holds; zeroing
    # any single one of them must flip it to False
    from tpubloom.ops import blocked

    key = b"all-counters-key"
    ku, kl = pack_keys([key], config.key_len)
    blk, cpos = jax.jit(
        lambda k_, l_: blocked.block_positions(
            k_, l_,
            n_blocks=config.n_blocks,
            block_bits=config.counters_per_block,
            k=config.k,
            seed=config.seed,
            block_hash=config.block_hash,
        )
    )(jnp.asarray(ku), jnp.asarray(kl))
    blk = int(np.asarray(blk)[0])
    counters = sorted(set(int(c) for c in np.asarray(cpos)[0]))
    query = jax.jit(make_blocked_counting_query_fn(config))

    def words_with(counters_set):
        w = np.zeros((config.n_blocks, config.words_per_block), np.uint32)
        for c in counters_set:
            w[blk, c >> 3] |= np.uint32(1) << np.uint32(4 * (c & 7))
        return jnp.asarray(w)

    assert bool(np.asarray(query(words_with(counters), ku, kl))[0])
    for drop in counters:
        present = np.asarray(
            query(words_with([c for c in counters if c != drop]), ku, kl)
        )[0]
        assert not present, f"missing counter {drop} must fail membership"


def test_checkpoint_restore_builds_blocked_counting(config, tmp_path):
    # config-driven restore must reconstruct the BLOCKED counting variant
    # (a flat CountingBloomFilter would use the wrong position spec)
    from tpubloom import checkpoint as ckpt

    f = BlockedCountingBloomFilter(config)
    rng = np.random.default_rng(3)
    keys = [rng.bytes(16) for _ in range(300)]
    f.insert_batch(keys)
    sink = ckpt.FileSink(str(tmp_path))
    ckpt.save(f, sink)
    g = ckpt.restore(config, sink)
    assert isinstance(g, BlockedCountingBloomFilter)
    assert g.include_batch(keys).all()
    g.delete_batch(keys)
    assert not g.include_batch(keys).any()


@settings(max_examples=10, deadline=None)
@given(
    st.lists(
        st.tuples(st.booleans(), st.binary(min_size=1, max_size=16)),
        min_size=1,
        max_size=48,
    )
)
def test_hypothesis_op_parity(ops):
    config = FilterConfig(
        m=1 << 20, k=4, key_len=16, counting=True, block_bits=512
    )
    fb_i, sw_i = _pair(config, True)
    fb_d, sw_d = _pair(config, False)
    a = _zeros(config)
    b = _zeros(config)
    for is_delete, key in ops:
        ku, kl = pack_keys([key], config.key_len)
        ku, kl = jnp.asarray(ku), jnp.asarray(kl)
        if is_delete:
            a, b = fb_d(a, ku, kl), sw_d(b, ku, kl)
        else:
            a, b = fb_i(a, ku, kl), sw_i(b, ku, kl)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
