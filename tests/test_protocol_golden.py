"""Ruby wire-format conformance: golden msgpack bytes for every
protocol.METHODS entry, replayed RAW against a live server (VERDICT r2
#7 — a server field rename must not ship silently against Ruby users).

The request bytes are committed as hex literals captured from the exact
encoding `clients/ruby/.../jax.rb` produces (`payload.to_msgpack`:
insertion-ordered maps, UTF-8 strings as msgpack str, binary strings as
msgpack bin) — msgpack-ruby and msgpack-python with use_bin_type=True
agree on this format, which the first test pins. The replay then speaks
the bytes over real gRPC and checks every response field the Ruby driver
reads (ok / error.code / n / hits / presence / seq / stats), including
the MSB-first hit packing its unpack_bits assumes."""

import numpy as np
import pytest

import grpc
import msgpack

from tpubloom import checkpoint as ckpt
from tpubloom.server import protocol
from tpubloom.server.service import BloomService, build_server

#: method -> (wire path method, hex of the exact request bytes jax.rb sends)
GOLDEN = {
    "Health": ("Health", "80"),
    "CreateFilter": (
        "CreateFilter",
        "85a46e616d65a6676f6c64656ea865786973745f6f6bc3a86361706163697479cd03e8aa6572726f725f72617465cb3f847ae147ae147ba76f7074696f6e7380",
    ),
    "CreateFilter_counting": (
        "CreateFilter",
        "85a46e616d65aa676f6c64656e2d636e74a865786973745f6f6bc3a86361706163697479cd03e8aa6572726f725f72617465cb3f847ae147ae147ba76f7074696f6e7381a8636f756e74696e67c3",
    ),
    "InsertBatch": (
        "InsertBatch",
        "82a46e616d65a6676f6c64656ea46b65797392c4040001feffa8746578742d6b6579",
    ),
    "InsertBatch_presence": (
        "InsertBatch",
        "83a46e616d65a6676f6c64656ea46b65797392c4040001feffa8746578742d6b6579af72657475726e5f70726573656e6365c3",
    ),
    # fixed wire encoding (ISSUE 10): two u64 keys (1, 2) as ONE raw
    # little-endian buffer, and a query of {1, 2, 999999} the same way —
    # the exact bytes the negotiated Ruby/Python clients produce
    "InsertBatch_fixed": (
        "InsertBatch",
        "82a46e616d65a6676f6c64656eaa6b6579735f666978656483a464617461c41001000000000000000200000000000000a5776964746808a16e02",
    ),
    "QueryBatch_fixed": (
        "QueryBatch",
        "82a46e616d65a6676f6c64656eaa6b6579735f666978656483a464617461c418010000000000000002000000000000003f420f0000000000a5776964746808a16e03",
    ),
    "QueryBatch": (
        "QueryBatch",
        "82a46e616d65a6676f6c64656ea46b65797393c4040001feffa8746578742d6b6579a6616273656e74",
    ),
    "InsertBatch_cnt": (
        "InsertBatch",
        "82a46e616d65aa676f6c64656e2d636e74a46b65797392c404636b2d31c404636b2d32",
    ),
    "DeleteBatch": (
        "DeleteBatch",
        "82a46e616d65aa676f6c64656e2d636e74a46b65797391c404636b2d32",
    ),
    "Stats": ("Stats", "81a46e616d65a6676f6c64656e"),
    "Checkpoint": ("Checkpoint", "82a46e616d65a6676f6c64656ea477616974c3"),
    "Clear": ("Clear", "81a46e616d65a6676f6c64656e"),
    "ListFilters": ("ListFilters", "80"),
    "DropFilter": ("DropFilter", "81a46e616d65aa676f6c64656e2d636e74"),
    "SlowlogGet": ("SlowlogGet", "81a16e0a"),
    "SlowlogReset": ("SlowlogReset", "80"),
    # distributed tracing (ISSUE 15): a trace lookup is a read-only
    # ring query — safe to replay raw on any server; with tracing
    # disabled it answers enabled:false + an empty span list, which is
    # exactly the shape the Ruby driver's trace_get parses. The lookup
    # key is trace_rid (the bare rid field is the transport correlation
    # id clients stamp per call, which would clobber it).
    "TraceGet": (
        "TraceGet", "81a974726163655f726964aa676f6c64656e2d726964"
    ),
    # HA verbs (ISSUE 4): a bare Promote and REPLICAOF NO ONE are both
    # idempotent no-ops on a primary — safe to replay raw
    "Promote": ("Promote", "80"),
    "ReplicaOf": ("ReplicaOf", "81a77072696d617279a64e4f204f4e45"),
    # durability probe (ISSUE 5): numreplicas=0 answers immediately with
    # the achieved count — safe to replay raw on any primary
    "Wait": ("Wait", "82ab6e756d7265706c6963617300aa74696d656f75745f6d7332"),
    # cluster verbs (ISSUE 9): on a NON-cluster server ClusterSlots
    # answers enabled:false and the admin/migration verbs answer the
    # structured CLUSTER_DISABLED error — both safe to replay raw, and
    # both shapes the cluster clients parse
    "ClusterSlots": ("ClusterSlots", "80"),
    "ClusterSetSlot": (
        "ClusterSetSlot", "82a4736c6f7407a57374617465a6737461626c65"
    ),
    "MigrateSlot": (
        "MigrateSlot", "82a4736c6f7407a6746172676574ab3132372e302e302e313a31"
    ),
    "MigrateInstall": (
        "MigrateInstall", "82a46e616d65a6676f6c64656ea570726f6265c3"
    ),
    # sketch plane (ISSUE 19): RedisBloom CF.*/CMS.*/TOPK.* parity verbs
    # — the exact bytes the Ruby driver's cf_*/cms_*/topk_* helpers send
    "CFReserve": (
        "CFReserve",
        "83a46e616d65a9676f6c64656e2d6366a8636170616369747964a865786973745f6f6bc3",
    ),
    "CFAdd": (
        "CFAdd",
        "82a46e616d65a9676f6c64656e2d6366a46b65797392c40463662d31c40463662d32",
    ),
    "CFDel": (
        "CFDel",
        "82a46e616d65a9676f6c64656e2d6366a46b65797391c40463662d32",
    ),
    "CFExists": (
        "CFExists",
        "82a46e616d65a9676f6c64656e2d6366a46b65797393c40463662d31c40463662d32c406616273656e74",
    ),
    "CMSInitByDim": (
        "CMSInitByDim",
        "84a46e616d65aa676f6c64656e2d636d73a5776964746840a5646570746803a865786973745f6f6bc3",
    ),
    "CMSIncrBy": (
        "CMSIncrBy",
        "83a46e616d65aa676f6c64656e2d636d73a46b65797392c4036b2d61c4036b2d62aa696e6372656d656e7473920502",
    ),
    "CMSQuery": (
        "CMSQuery",
        "82a46e616d65aa676f6c64656e2d636d73a46b65797393c4036b2d61c4036b2d62c406616273656e74",
    ),
    "TopKReserve": (
        "TopKReserve",
        "85a46e616d65ab676f6c64656e2d746f706ba4746f706b02a5776964746840a5646570746803a865786973745f6f6bc3",
    ),
    "TopKAdd": (
        "TopKAdd",
        "82a46e616d65ab676f6c64656e2d746f706ba46b65797393c403686f74c403686f74c404636f6c64",
    ),
    "TopKList": ("TopKList", "81a46e616d65ab676f6c64656e2d746f706b"),
}

#: one ``ReplAck`` client-streaming frame (ISSUE 5) — the exact bytes a
#: replica's ack sender ships: session id from the sync frame + the
#: newest fully-applied op seq
GOLDEN_ACK_FRAME = "82a373696400a373657107"
GOLDEN_ACK_FRAME_DICT = {"sid": 0, "seq": 7}

#: server-streaming requests (ISSUE 6 — the project lint's protocol-
#: coverage check found these two uncovered): a cursor-less ReplStream
#: open (full resync) and a name-filtered Monitor subscription
GOLDEN_STREAM = {
    "ReplStream": ("ReplStream", "80"),
    "Monitor": ("Monitor", "81a46e616d65a6676f6c64656e"),
}
GOLDEN_STREAM_DICTS = {
    "ReplStream": {},
    "Monitor": {"name": "golden"},
}

#: bidi streaming ingest frames (ISSUE 18): one seq-stamped data frame
#: per method — the exact bytes the Ruby driver's stream_frames (and the
#: Python StreamSession) produce. The server's side of the contract
#: (hello + seq-echoing ack frames wrapping the full unary-shaped resp)
#: is asserted live in test_golden_bidi_replay.
GOLDEN_BIDI = {
    "InsertStream": (
        "InsertStream",
        "84a373657101a3726964b1676f6c64656e2d73747265616d2d726964a46e616d65"
        "a6676f6c64656ea46b65797392c404736b2d31a4736b2d32",
    ),
    "QueryStream": (
        "QueryStream",
        "84a373657101a3726964b1676f6c64656e2d73747265616d2d726964a46e616d65"
        "a6676f6c64656ea46b65797392c404736b2d31a6616273656e74",
    ),
}
GOLDEN_BIDI_DICTS = {
    "InsertStream": {"seq": 1, "rid": "golden-stream-rid",
                     "name": "golden", "keys": [b"sk-1", "sk-2"]},
    "QueryStream": {"seq": 1, "rid": "golden-stream-rid",
                    "name": "golden", "keys": [b"sk-1", "absent"]},
}

#: the dict each fixture encodes (the pin below keeps python<->ruby
#: encodings provably in sync; regenerate hex from these on change)
GOLDEN_DICTS = {
    "Health": {},
    "CreateFilter": {"name": "golden", "exist_ok": True, "capacity": 1000,
                     "error_rate": 0.01, "options": {}},
    "CreateFilter_counting": {"name": "golden-cnt", "exist_ok": True,
                              "capacity": 1000, "error_rate": 0.01,
                              "options": {"counting": True}},
    "InsertBatch": {"name": "golden", "keys": [b"\x00\x01\xfe\xff", "text-key"]},
    "InsertBatch_presence": {"name": "golden",
                             "keys": [b"\x00\x01\xfe\xff", "text-key"],
                             "return_presence": True},
    "InsertBatch_fixed": {
        "name": "golden",
        "keys_fixed": {
            "data": (1).to_bytes(8, "little") + (2).to_bytes(8, "little"),
            "width": 8, "n": 2,
        },
    },
    "QueryBatch_fixed": {
        "name": "golden",
        "keys_fixed": {
            "data": (1).to_bytes(8, "little") + (2).to_bytes(8, "little")
            + (999999).to_bytes(8, "little"),
            "width": 8, "n": 3,
        },
    },
    "QueryBatch": {"name": "golden",
                   "keys": [b"\x00\x01\xfe\xff", "text-key", "absent"]},
    "InsertBatch_cnt": {"name": "golden-cnt", "keys": [b"ck-1", b"ck-2"]},
    "DeleteBatch": {"name": "golden-cnt", "keys": [b"ck-2"]},
    "Stats": {"name": "golden"},
    "Checkpoint": {"name": "golden", "wait": True},
    "Clear": {"name": "golden"},
    "ListFilters": {},
    "DropFilter": {"name": "golden-cnt"},
    "SlowlogGet": {"n": 10},
    "SlowlogReset": {},
    "TraceGet": {"trace_rid": "golden-rid"},
    "Promote": {},
    "ReplicaOf": {"primary": "NO ONE"},
    "Wait": {"numreplicas": 0, "timeout_ms": 50},
    "ClusterSlots": {},
    "ClusterSetSlot": {"slot": 7, "state": "stable"},
    "MigrateSlot": {"slot": 7, "target": "127.0.0.1:1"},
    "MigrateInstall": {"name": "golden", "probe": True},
    "CFReserve": {"name": "golden-cf", "capacity": 100, "exist_ok": True},
    "CFAdd": {"name": "golden-cf", "keys": [b"cf-1", b"cf-2"]},
    "CFDel": {"name": "golden-cf", "keys": [b"cf-2"]},
    "CFExists": {"name": "golden-cf",
                 "keys": [b"cf-1", b"cf-2", b"absent"]},
    "CMSInitByDim": {"name": "golden-cms", "width": 64, "depth": 3,
                     "exist_ok": True},
    "CMSIncrBy": {"name": "golden-cms", "keys": [b"k-a", b"k-b"],
                  "increments": [5, 2]},
    "CMSQuery": {"name": "golden-cms",
                 "keys": [b"k-a", b"k-b", b"absent"]},
    "TopKReserve": {"name": "golden-topk", "topk": 2, "width": 64,
                    "depth": 3, "exist_ok": True},
    "TopKAdd": {"name": "golden-topk", "keys": [b"hot", b"hot", b"cold"]},
    "TopKList": {"name": "golden-topk"},
}


def test_every_method_has_a_golden():
    covered = {m for m, _ in GOLDEN.values()}
    assert covered == set(protocol.METHODS), (
        "golden fixtures must cover every protocol method; missing: "
        f"{set(protocol.METHODS) - covered}"
    )
    stream_covered = {m for m, _ in GOLDEN_STREAM.values()}
    assert stream_covered == set(protocol.STREAM_METHODS), (
        "golden fixtures must cover every streaming method; missing: "
        f"{set(protocol.STREAM_METHODS) - stream_covered}"
    )
    bidi_covered = {m for m, _ in GOLDEN_BIDI.values()}
    assert bidi_covered == set(protocol.BIDI_STREAM_METHODS), (
        "golden fixtures must cover every bidi stream method; missing: "
        f"{set(protocol.BIDI_STREAM_METHODS) - bidi_covered}"
    )


def test_golden_bytes_match_ruby_encoding():
    """msgpack-python with use_bin_type=True produces the msgpack-ruby
    format (str for UTF-8 strings, bin for binary) — the committed hex is
    the contract; if this fails, the wire format changed."""
    for name, (_, hexbytes) in GOLDEN.items():
        assert msgpack.packb(
            GOLDEN_DICTS[name], use_bin_type=True
        ).hex() == hexbytes, f"fixture {name} drifted"
    assert msgpack.packb(
        GOLDEN_ACK_FRAME_DICT, use_bin_type=True
    ).hex() == GOLDEN_ACK_FRAME, "ReplAck frame fixture drifted"
    for name, (_, hexbytes) in GOLDEN_STREAM.items():
        assert msgpack.packb(
            GOLDEN_STREAM_DICTS[name], use_bin_type=True
        ).hex() == hexbytes, f"stream fixture {name} drifted"
    for name, (_, hexbytes) in GOLDEN_BIDI.items():
        assert msgpack.packb(
            GOLDEN_BIDI_DICTS[name], use_bin_type=True
        ).hex() == hexbytes, f"bidi fixture {name} drifted"


@pytest.fixture()
def raw_service_server(tmp_path):
    service = BloomService(sink_factory=lambda config: ckpt.FileSink(str(tmp_path)))
    srv, port = build_server(service, "127.0.0.1:0")
    srv.start()
    channel = grpc.insecure_channel(f"127.0.0.1:{port}")
    yield channel, service
    channel.close()
    srv.stop(grace=None)


@pytest.fixture()
def raw_server(raw_service_server):
    channel, _ = raw_service_server
    return channel


def _call(channel, method, hexbytes):
    fn = channel.unary_unary(
        protocol.method_path(method),
        request_serializer=lambda b: b,
        response_deserializer=lambda b: b,
    )
    return msgpack.unpackb(fn(bytes.fromhex(hexbytes)), raw=False)


def test_golden_replay_against_live_server(raw_server):
    ch = raw_server

    r = _call(ch, *GOLDEN["Health"])
    assert r["ok"] and "backend" in r and "devices" in r

    assert _call(ch, *GOLDEN["CreateFilter"])["ok"]
    assert _call(ch, *GOLDEN["CreateFilter_counting"])["ok"]

    r = _call(ch, *GOLDEN["ListFilters"])
    assert r["ok"] and sorted(r["filters"]) == ["golden", "golden-cnt"]

    r = _call(ch, *GOLDEN["InsertBatch"])
    assert r["ok"] and r["n"] == 2

    # presence bytes: MSB-first packbits, n announces the valid prefix
    r = _call(ch, *GOLDEN["InsertBatch_presence"])
    assert r["ok"] and r["n"] == 2 and isinstance(r["presence"], bytes)
    bits = np.unpackbits(
        np.frombuffer(r["presence"], np.uint8), bitorder="big"
    )[: r["n"]]
    assert bits.all(), "keys inserted by the previous golden must be present"

    r = _call(ch, *GOLDEN["QueryBatch"])
    assert r["ok"] and r["n"] == 3 and isinstance(r["hits"], bytes)
    bits = np.unpackbits(np.frombuffer(r["hits"], np.uint8), bitorder="big")[:3]
    assert bits[0] and bits[1] and not bits[2]

    # fixed wire encoding (ISSUE 10): the raw-buffer insert round-trips
    # through the raw-buffer query AND through the msgpack twin — a u64
    # shipped fixed must hit the same positions as its 8-byte bin form
    r = _call(ch, *GOLDEN["InsertBatch_fixed"])
    assert r["ok"] and r["n"] == 2
    r = _call(ch, *GOLDEN["QueryBatch_fixed"])
    assert r["ok"] and r["n"] == 3
    bits = np.unpackbits(np.frombuffer(r["hits"], np.uint8), bitorder="big")[:3]
    assert bits[0] and bits[1] and not bits[2]
    twin = msgpack.packb(
        {"name": "golden",
         "keys": [(1).to_bytes(8, "little"), (2).to_bytes(8, "little")]},
        use_bin_type=True,
    )
    fn = ch.unary_unary(
        protocol.method_path("QueryBatch"),
        request_serializer=lambda b: b,
        response_deserializer=lambda b: b,
    )
    r = msgpack.unpackb(fn(twin), raw=False)
    bits = np.unpackbits(np.frombuffer(r["hits"], np.uint8), bitorder="big")[:2]
    assert bits.all(), "fixed-inserted keys must hit via the msgpack twin"

    assert _call(ch, *GOLDEN["InsertBatch_cnt"])["ok"]
    assert _call(ch, *GOLDEN["DeleteBatch"])["ok"]

    r = _call(ch, *GOLDEN["Stats"])
    assert r["ok"] and "n_inserted" in r["stats"]

    r = _call(ch, *GOLDEN["Checkpoint"])
    assert r["ok"] and isinstance(r["seq"], int)

    assert _call(ch, *GOLDEN["Clear"])["ok"]
    r = _call(ch, *GOLDEN["QueryBatch"])
    bits = np.unpackbits(np.frombuffer(r["hits"], np.uint8), bitorder="big")[:3]
    assert not bits.any(), "cleared filter must answer no"

    assert _call(ch, *GOLDEN["DropFilter"])["ok"]
    r = _call(ch, *GOLDEN["ListFilters"])
    assert r["filters"] == ["golden"]

    # slowlog parity RPCs: every request above was recorded (no rid in
    # the raw golden bytes -> the server generated one per request)
    # HA verbs: on a primary both are idempotent acknowledgements (the
    # Ruby driver reads ok/epoch)
    r = _call(ch, *GOLDEN["Promote"])
    assert r["ok"] and r["already_primary"] and isinstance(r["epoch"], int)
    r = _call(ch, *GOLDEN["ReplicaOf"])
    assert r["ok"] and r["already_primary"]

    # Wait (ISSUE 5): numreplicas=0 reports the achieved count (0 here —
    # no replicas) without blocking; the Ruby driver reads ok/nreplicas
    r = _call(ch, *GOLDEN["Wait"])
    assert r["ok"] and r["nreplicas"] == 0 and isinstance(r["seq"], int)

    # cluster verbs (ISSUE 9) on a NON-cluster server: ClusterSlots
    # probes cleanly (enabled false), admin/migration verbs answer the
    # structured CLUSTER_DISABLED error the cluster clients parse
    r = _call(ch, *GOLDEN["ClusterSlots"])
    assert r["ok"] and r["enabled"] is False and r["ranges"] == []
    for fixture in ("ClusterSetSlot", "MigrateSlot", "MigrateInstall"):
        r = _call(ch, *GOLDEN[fixture])
        assert r["ok"] is False, fixture
        assert r["error"]["code"] == "CLUSTER_DISABLED", fixture

    r = _call(ch, *GOLDEN["SlowlogGet"])
    assert r["ok"] and len(r["entries"]) > 0
    e = r["entries"][0]
    assert {"id", "time", "method", "rid", "duration_s", "batch", "args",
            "phases"} <= set(e)
    assert e["method"] in protocol.METHODS and e["rid"]
    r = _call(ch, *GOLDEN["SlowlogReset"])
    assert r["ok"] and r["cleared"] > 0

    # TraceGet (ISSUE 15): with tracing disabled (this server's
    # default) the lookup still answers the structured shape —
    # enabled:false + an empty span list, never an error
    r = _call(ch, *GOLDEN["TraceGet"])
    assert r["ok"] and r["rid"] == "golden-rid"
    assert r["enabled"] is False and r["spans"] == []

    # error shape the Ruby driver's rpc_once parses
    bad = msgpack.packb({"name": "missing-filter", "keys": [b"x"]},
                        use_bin_type=True)
    fn = raw_server.unary_unary(
        protocol.method_path("QueryBatch"),
        request_serializer=lambda b: b,
        response_deserializer=lambda b: b,
    )
    r = msgpack.unpackb(fn(bad), raw=False)
    assert r["ok"] is False and r["error"]["code"] == "NOT_FOUND"
    assert isinstance(r["error"]["message"], str)


def test_golden_sketch_replay(raw_service_server):
    """Sketch-plane goldens (ISSUE 19) replayed RAW against a live
    server: every CF.*/CMS.*/TOPK.* response field the Ruby driver
    reads, plus the WRONG_TYPE / READONLY / CLUSTER_DISABLED error
    shapes kind-specific verbs answer."""
    ch, service = raw_service_server

    # cuckoo: reserve -> add -> exists -> del -> exists
    assert _call(ch, *GOLDEN["CFReserve"])["ok"]
    r = _call(ch, *GOLDEN["CFAdd"])
    assert r["ok"] and r["n"] == 2
    assert "full" not in r, "a near-empty table must reject nothing"
    r = _call(ch, *GOLDEN["CFExists"])
    assert r["ok"] and r["n"] == 3 and isinstance(r["hits"], bytes)
    bits = np.unpackbits(np.frombuffer(r["hits"], np.uint8), bitorder="big")[:3]
    assert bits[0] and bits[1] and not bits[2]
    r = _call(ch, *GOLDEN["CFDel"])
    assert r["ok"] and r["n"] == 1 and isinstance(r["deleted"], bytes)
    bits = np.unpackbits(np.frombuffer(r["deleted"], np.uint8), bitorder="big")
    assert bits[0], "cf-2 was stored — its delete must report existed"
    r = _call(ch, *GOLDEN["CFExists"])
    bits = np.unpackbits(np.frombuffer(r["hits"], np.uint8), bitorder="big")[:3]
    assert bits[0] and not bits[1], "deleted key must be gone (no FN before)"

    # count-min: init -> weighted incrby answers post-update estimates
    assert _call(ch, *GOLDEN["CMSInitByDim"])["ok"]
    r = _call(ch, *GOLDEN["CMSIncrBy"])
    assert r["ok"] and r["n"] == 2
    assert r["counts"][0] >= 5 and r["counts"][1] >= 2
    r = _call(ch, *GOLDEN["CMSQuery"])
    assert r["ok"] and r["n"] == 3 and len(r["counts"]) == 3
    assert r["counts"][0] >= 5 and r["counts"][1] >= 2

    # top-k: reserve -> add unit counts -> list heavy hitters
    assert _call(ch, *GOLDEN["TopKReserve"])["ok"]
    r = _call(ch, *GOLDEN["TopKAdd"])
    assert r["ok"] and r["n"] == 3
    r = _call(ch, *GOLDEN["TopKList"])
    assert r["ok"] and len(r["items"]) >= 1
    top = r["items"][0]
    assert top["key"] == b"hot" and top["count"] >= 2

    # WRONG_TYPE (Redis WRONGTYPE parity): a CF verb on a CMS key
    wrong = msgpack.packb(
        {"name": "golden-cms", "keys": [b"x"]}, use_bin_type=True
    )
    fn = ch.unary_unary(
        protocol.method_path("CFAdd"),
        request_serializer=lambda b: b,
        response_deserializer=lambda b: b,
    )
    r = msgpack.unpackb(fn(wrong), raw=False)
    assert r["ok"] is False and r["error"]["code"] == "WRONG_TYPE"

    # READONLY: the mutating sketch verbs answer the same structured
    # refusal bloom writes do on a replica (the driver's failover path)
    service.read_only = True
    try:
        for fixture in ("CFAdd", "CFDel", "CMSIncrBy", "TopKAdd"):
            r = _call(ch, *GOLDEN[fixture])
            assert r["ok"] is False, fixture
            assert r["error"]["code"] == "READONLY", fixture
        # read verbs keep serving on a replica
        assert _call(ch, *GOLDEN["CFExists"])["ok"]
        assert _call(ch, *GOLDEN["CMSQuery"])["ok"]
        assert _call(ch, *GOLDEN["TopKList"])["ok"]
    finally:
        service.read_only = False


def test_golden_sketch_cluster_disabled(raw_server):
    """Keyed sketch verbs on a NON-cluster server: no slot check, no
    CLUSTER_DISABLED — they serve like any keyed bloom verb (the
    cluster error shape is reserved for the admin/migration verbs,
    asserted in test_golden_replay_against_live_server)."""
    assert _call(raw_server, *GOLDEN["CFReserve"])["ok"]
    r = _call(raw_server, *GOLDEN["CFAdd"])
    assert r["ok"] and r["n"] == 2


def test_golden_stream_replay(tmp_path):
    """ReplStream + Monitor golden requests replayed RAW (ISSUE 6): the
    frame kinds and the fields replicas/monitor clients read must hold."""
    from tpubloom.repl import OpLog

    service = BloomService(
        sink_factory=lambda config: None,
        oplog=OpLog(str(tmp_path / "oplog")),
    )
    srv, port = build_server(service, "127.0.0.1:0")
    srv.start()
    channel = grpc.insecure_channel(f"127.0.0.1:{port}")
    try:
        assert _call(channel, *GOLDEN["CreateFilter"])["ok"]
        assert _call(channel, *GOLDEN["InsertBatch"])["ok"]

        # ReplStream, cursor-less: full_sync_begin -> snapshot per
        # filter -> full_sync_end carrying cursor/log_id/epoch/sid
        method, hexbytes = GOLDEN_STREAM["ReplStream"]
        call = channel.unary_stream(
            protocol.method_path(method),
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )(bytes.fromhex(hexbytes), timeout=10)
        frames = []
        for raw in call:
            frames.append(msgpack.unpackb(raw, raw=False))
            if frames[-1]["kind"] == "full_sync_end":
                break
        call.cancel()
        kinds = [f["kind"] for f in frames]
        assert kinds[0] == "full_sync_begin" and kinds[-1] == "full_sync_end"
        assert frames[0]["filters"] == ["golden"]
        snap = next(f for f in frames if f["kind"] == "snapshot")
        assert snap["name"] == "golden" and isinstance(snap["blob"], bytes)
        assert isinstance(snap["applied_seq"], int)
        end = frames[-1]
        assert {"cursor", "log_id", "epoch", "sid"} <= set(end)

        # Monitor, name-filtered: hello first, then one op event per
        # matching finished request
        method, hexbytes = GOLDEN_STREAM["Monitor"]
        call = channel.unary_stream(
            protocol.method_path(method),
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )(bytes.fromhex(hexbytes), timeout=10)
        it = iter(call)
        hello = msgpack.unpackb(next(it), raw=False)
        assert hello["kind"] == "hello" and hello["filter"] == "golden"
        assert _call(channel, *GOLDEN["QueryBatch"])["ok"]
        event = None
        for raw in it:
            frame = msgpack.unpackb(raw, raw=False)
            if frame["kind"] == "op":
                event = frame
                break
        call.cancel()
        assert event is not None
        assert event["method"] == "QueryBatch" and event["name"] == "golden"
        assert {"ts", "rid", "batch", "duration_s", "ok"} <= set(event)
    finally:
        channel.close()
        srv.stop(grace=None)
        service.oplog.close()


def test_golden_bidi_replay(raw_server):
    """InsertStream/QueryStream golden frames replayed RAW (ISSUE 18):
    the server must answer hello (with a credit grant) first, then one
    ack per data frame echoing its seq and wrapping the full
    unary-shaped response — the exact frames the Ruby driver's
    stream_frames parses."""
    ch = raw_server
    assert _call(ch, *GOLDEN["CreateFilter"])["ok"]

    def bidi(method, hexbytes):
        call = ch.stream_stream(
            protocol.method_path(method),
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )(iter([bytes.fromhex(hexbytes)]), timeout=30)
        return [msgpack.unpackb(raw, raw=False) for raw in call]

    frames = bidi(*GOLDEN_BIDI["InsertStream"])
    assert frames[0]["kind"] == "hello"
    assert isinstance(frames[0]["credit"], int) and frames[0]["credit"] >= 1
    acks = [f for f in frames[1:] if f["kind"] == "ack"]
    assert len(acks) == 1
    assert acks[0]["seq"] == GOLDEN_BIDI_DICTS["InsertStream"]["seq"]
    assert isinstance(acks[0]["credit"], int) and acks[0]["credit"] >= 1
    resp = acks[0]["resp"]
    assert resp["ok"] and resp["n"] == 2

    frames = bidi(*GOLDEN_BIDI["QueryStream"])
    assert frames[0]["kind"] == "hello"
    (ack,) = [f for f in frames[1:] if f["kind"] == "ack"]
    assert ack["seq"] == 1
    resp = ack["resp"]
    assert resp["ok"] and resp["n"] == 2 and isinstance(resp["hits"], bytes)
    bits = np.unpackbits(
        np.frombuffer(resp["hits"], np.uint8), bitorder="big"
    )[:2]
    assert bits[0] and not bits[1], (
        "streamed insert must be queryable via the stream; 'absent' must miss"
    )


def test_golden_ack_frame_replay(raw_service_server):
    """The ReplAck client-streaming frame a replica's ack sender ships,
    replayed RAW: the committed bytes must land on the session's acked
    cursor, and a Wait gated on that seq must count the replica."""
    channel, service = raw_service_server
    sid = service.repl_sessions.register("golden-peer", listen="127.0.0.1:9")
    assert sid == 0, "fresh registry must hand out sid 0 (the frame pins it)"
    fn = channel.stream_unary(
        protocol.method_path("ReplAck"),
        request_serializer=lambda b: b,
        response_deserializer=lambda b: b,
    )
    resp = msgpack.unpackb(
        fn(iter([bytes.fromhex(GOLDEN_ACK_FRAME)])), raw=False
    )
    assert resp["ok"] and resp["frames"] == 1
    (sess,) = service.repl_sessions.describe()
    assert sess["acked"] == GOLDEN_ACK_FRAME_DICT["seq"]
    # the ack is immediately visible to the durability gate
    wait_req = msgpack.packb(
        {"numreplicas": 1, "timeout_ms": 500,
         "seq": GOLDEN_ACK_FRAME_DICT["seq"]},
        use_bin_type=True,
    )
    wfn = channel.unary_unary(
        protocol.method_path("Wait"),
        request_serializer=lambda b: b,
        response_deserializer=lambda b: b,
    )
    r = msgpack.unpackb(wfn(wait_req), raw=False)
    assert r["ok"] and r["nreplicas"] == 1
