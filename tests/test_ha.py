"""High-availability suite (ISSUE 4).

Layers covered:

* topology primitives — CRC-checked epoch store, op-log seq seeding,
  identity alias (replid2 parity), full-resync reset;
* promotion — bare replica (fresh log adoption) and chained replica
  (cheap: the local log IS the adopted log), epoch bump + persistence,
  idempotence, STALE_EPOCH fencing of old-epoch promotions/writes;
* ``ReplicaOf`` — survivor re-pointing with alias partial resync,
  ``NO ONE`` == promote, live-primary demotion;
* chained replication — re-append in the upstream seq space, downstream
  ``ReplStream`` serving, exactly-once across the chain;
* replica cursor persistence — a replica restart partial-resyncs from
  local checkpoints + ``repl_cursor.json`` instead of full-resyncing;
* batched stream frames — zlib-coalesced records behind the negotiated
  capability, same exactly-once guarantees;
* sentinel — quorum SDOWN→ODOWN vote, most-caught-up promotion,
  survivor re-pointing, stale-primary fencing, no-quorum safety;
* topology-aware client — sentinel resolution, failover redirect,
  STALE_EPOCH refresh;
* the acceptance chaos story — SIGKILL the primary under concurrent
  client load, sentinel failover, client redirect, counting-filter
  proof of zero lost / zero doubled acknowledged writes, and fencing of
  the restarted stale primary (``test_failover_sigkill_acceptance``).
"""

import os
import threading
import time

import numpy as np
import pytest

from tpubloom import checkpoint as ckpt
from tpubloom import faults
from tpubloom.ha import EpochStore, Topology
from tpubloom.ha.sentinel import Sentinel
from tpubloom.obs import counters as obs_counters
from tpubloom.repl import (
    OpLog,
    ReplicaApplier,
    ReplicaStateStore,
    bootstrap_from_local,
)
from tpubloom.server.client import BloomClient, fetch_topology
from tpubloom.server.protocol import BloomServiceError
from tpubloom.server.service import BloomService, build_server

# ISSUE 6: armed lock-order / held-while-blocking tracking for the whole
# module (asserted violation-free at teardown — tests/conftest.py).
# ISSUE 13: plus the lock-ORDER manifest gate — every runtime
# acquisition edge this module drives must be declared.
pytestmark = pytest.mark.usefixtures("lock_check_armed", "lock_order_manifest")


@pytest.fixture(autouse=True)
def _disarm_all():
    faults.reset()
    yield
    faults.reset()


def _wait(pred, timeout=30.0, poll=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(poll)
    raise AssertionError(f"timed out waiting for {msg}")


def _primary(tmp_path, name="plog", sink=None, **kwargs):
    oplog = OpLog(str(tmp_path / name))
    svc = BloomService(
        sink_factory=(lambda config: ckpt.FileSink(sink)) if sink else None,
        oplog=oplog,
        **kwargs,
    )
    srv, port = build_server(svc, "127.0.0.1:0")
    srv.start()
    svc.listen_address = f"127.0.0.1:{port}"
    return svc, srv, port, oplog


def _replica(tmp_path, upstream_port, name=None, chained=False, **svc_kwargs):
    oplog = OpLog(str(tmp_path / name)) if chained else None
    svc = BloomService(oplog=oplog, read_only=True, **svc_kwargs)
    srv, port = build_server(svc, "127.0.0.1:0")
    srv.start()
    svc.listen_address = f"127.0.0.1:{port}"
    applier = ReplicaApplier(
        svc,
        f"127.0.0.1:{upstream_port}",
        reconnect_base=0.05,
        listen_address=svc.listen_address,
    ).start()
    return svc, srv, port, applier


# -- topology primitives -----------------------------------------------------


def test_epoch_store_roundtrip_and_corruption(tmp_path):
    store = EpochStore(str(tmp_path))
    assert store.load() == 0
    store.store(5)
    assert store.load() == 5
    assert EpochStore(str(tmp_path)).load() == 5  # fresh reader
    with open(store.path, "a") as f:
        f.write("rot")
    # corrupt reads as 0 — the fence-me-harder direction, never a crash
    assert store.load() == 0


def test_topology_adopt_epoch_discipline():
    topo = Topology(epoch=3, primary="a:1", replicas=["b:2"])
    assert not topo.adopt(Topology(epoch=3, primary="c:3"))  # same epoch
    assert not topo.adopt(Topology(epoch=2, primary="c:3"))  # older
    assert topo.adopt(Topology(epoch=4, primary="c:3", replicas=["a:1"]))
    assert topo.primary == "c:3" and topo.epoch == 4


def test_oplog_seed_alias_and_reset(tmp_path):
    d = str(tmp_path / "log")
    lg = OpLog(d, start_seq=10)
    assert lg.last_seq == 10 and lg.append("Clear", {"name": "f"}) == 11
    lg.set_alias("old-primary-id", 10)
    lg.close()

    lg2 = OpLog(d)  # alias persists a restart
    assert lg2.alias_id == "old-primary-id" and lg2.alias_upto == 10
    # exactly-caught-up survivor resumes through the alias...
    assert lg2.resumable(10, "old-primary-id")
    # ...but a cursor BELOW the seed has no records to stream from here
    assert not lg2.resumable(9, "old-primary-id")
    # a cursor past the alias window (divergence risk) must full-resync
    lg2.set_alias("old-primary-id", 10)
    assert not lg2.resumable(11, "old-primary-id")
    assert lg2.resumable(11, lg2.log_id)

    old_id = lg2.log_id
    lg2.reset_to(40)  # full-resync reset: wipe + reseed + new identity
    assert lg2.last_seq == 40 and lg2.log_id != old_id
    assert lg2.alias_id is None
    assert lg2.append("Clear", {"name": "f"}) == 41
    assert not lg2.resumable(41, old_id)
    lg2.close()


def test_oplog_append_record_verbatim_and_gap(tmp_path):
    lg = OpLog(str(tmp_path / "log"), start_seq=5)
    rec = {"seq": 6, "method": "Clear", "rid": "r", "req": {"name": "f"},
           "ts": 1.0}
    assert lg.append_record(rec)
    assert not lg.append_record(rec)  # dup (partial-resync overlap)
    got = list(lg.read_from(5))
    assert got == [rec]
    with pytest.raises(ValueError, match="gap"):
        lg.append_record({**rec, "seq": 8})
    lg.close()


# -- promotion ---------------------------------------------------------------


def test_promote_bare_replica_adopts_fresh_log(tmp_path):
    psvc, psrv, pport, poplog = _primary(tmp_path)
    pc = BloomClient(f"127.0.0.1:{pport}")
    pc.wait_ready()
    keys = [b"p%015d" % i for i in range(100)]
    pc.create_filter("cnt", capacity=10_000, error_rate=0.01, counting=True)
    pc.insert_batch("cnt", keys)

    rsvc, rsrv, rport, applier = _replica(tmp_path, pport)
    rc = BloomClient(f"127.0.0.1:{rport}")
    try:
        assert applier.wait_for_seq(poplog.last_seq, 30), applier.status()
        with pytest.raises(BloomServiceError, match="NO_LOG_DIR"):
            rc.promote()  # bare replica needs a log dir to adopt
        adopt_dir = str(tmp_path / "adopted")
        resp = rc.promote(repl_log_dir=adopt_dir)
        assert resp["epoch"] == 1 and not resp["already_primary"]
        assert resp["adopted_seq"] == poplog.last_seq
        # promoted: accepts writes, logs them in the adopted seq space
        h = rc.health()
        assert h["role"] == "primary" and h["epoch"] == 1
        rc.insert_batch("cnt", [b"after"])
        assert rsvc.oplog.last_seq == poplog.last_seq + 1
        assert rsvc.oplog.directory == adopt_dir
        # the epoch persisted beside the adopted log
        assert EpochStore(adopt_dir).load() == 1
        # idempotent re-promote; stale pinned epoch rejected (raw call:
        # the stock client would heal by adopting the advertised epoch)
        assert rc.promote()["already_primary"]
        with pytest.raises(BloomServiceError, match="STALE_EPOCH"):
            rc._call_once("Promote", {"epoch": 0})
        # a fresh-log restart of the promoted node replays its manifest:
        # the pre-promotion keys must have been manifest-seeded
        manifest = rsvc._manifest_read()
        assert manifest is not None and "cnt" in manifest
    finally:
        rc.close()
        pc.close()
        rsrv.stop(grace=None)
        psrv.stop(grace=None)
        poplog.close()


def test_chained_replica_serves_downstream_and_promotes_cheap(tmp_path):
    """Chain primary→mid→leaf; every link sees every write exactly once;
    promoting the mid node costs nothing (its log IS the adopted log)
    and the old primary's OTHER replica partial-resyncs via the alias."""
    psvc, psrv, pport, poplog = _primary(tmp_path)
    pc = BloomClient(f"127.0.0.1:{pport}")
    pc.wait_ready()
    keys = [b"c%015d" % i for i in range(200)]
    pc.create_filter("cnt", capacity=20_000, error_rate=0.01, counting=True)
    pc.insert_batch("cnt", keys)

    mid_svc, mid_srv, mid_port, mid_app = _replica(
        tmp_path, pport, name="midlog", chained=True
    )
    sib_svc, sib_srv, sib_port, sib_app = _replica(tmp_path, pport)
    # leaf chains off the MID node (its ReplStream serves downstream)
    leaf_svc, leaf_srv, leaf_port, leaf_app = _replica(tmp_path, mid_port)
    lc = BloomClient(f"127.0.0.1:{leaf_port}")
    mc = BloomClient(f"127.0.0.1:{mid_port}")
    try:
        assert mid_app.wait_for_seq(poplog.last_seq, 30), mid_app.status()
        # the chained log lives in the upstream seq space (the initial
        # full resync seeds it at the resync cursor; LIVE records are
        # re-appended verbatim)
        assert mid_svc.oplog.last_seq == poplog.last_seq
        before_re = obs_counters.get("repl_records_reappended")
        live = [b"live-%07d" % i for i in range(30)]
        pc.insert_batch("cnt", live)
        assert mid_app.wait_for_seq(poplog.last_seq, 30)
        assert obs_counters.get("repl_records_reappended") > before_re
        assert leaf_app.wait_for_seq(mid_svc.oplog.last_seq, 30)
        assert lc.include_batch("cnt", keys).all()
        assert lc.include_batch("cnt", live).all()
        assert sib_app.wait_for_seq(poplog.last_seq, 30)

        # promote mid; survivors re-point; alias gives partial resync
        resp = mc.promote()
        assert not resp["already_primary"] and resp["epoch"] == 1
        sc = BloomClient(f"127.0.0.1:{sib_port}")
        sc._rpc("ReplicaOf", {"primary": f"127.0.0.1:{mid_port}",
                              "epoch": 1})
        new_sib = sib_svc.replica_applier
        assert new_sib is not sib_app
        mc.insert_batch("cnt", [b"post-promote"])
        assert new_sib.wait_for_seq(mid_svc.oplog.last_seq, 30), (
            new_sib.status()
        )
        assert new_sib.partial_syncs >= 1 and new_sib.full_syncs == 0, (
            "survivor paid a full resync despite the identity alias"
        )
        # the mid→leaf link just keeps streaming (same log identity)
        assert leaf_app.wait_for_seq(mid_svc.oplog.last_seq, 30)
        assert lc.include("cnt", b"post-promote")
        assert sc.include("cnt", b"post-promote")
        # exactly-once along the whole (re-shaped) topology
        mc.delete_batch("cnt", keys)
        assert new_sib.wait_for_seq(mid_svc.oplog.last_seq, 30)
        assert leaf_app.wait_for_seq(mid_svc.oplog.last_seq, 30)
        for cl in (mc, sc, lc):
            assert not cl.include_batch("cnt", keys).any(), (
                "double-applied records after promotion"
            )
        sc.close()
    finally:
        for app in (leaf_app, mid_app, sib_app, sib_svc.replica_applier):
            if app is not None:
                app.stop()
        for cl in (lc, mc, pc):
            cl.close()
        for srv in (leaf_srv, sib_srv, mid_srv, psrv):
            srv.stop(grace=None)
        poplog.close()
        for svc in (mid_svc, leaf_svc):
            if svc.oplog is not None:
                svc.oplog.close()


def test_replicaof_no_one_promotes_and_demotion_fences_writes(tmp_path):
    psvc, psrv, pport, poplog = _primary(tmp_path)
    pc = BloomClient(f"127.0.0.1:{pport}")
    pc.wait_ready()
    pc.create_filter("f", capacity=1000, error_rate=0.01)
    pc.insert_batch("f", [b"seed"])

    rsvc, rsrv, rport, applier = _replica(
        tmp_path, pport, name="rlog", chained=True
    )
    rc = BloomClient(f"127.0.0.1:{rport}")
    try:
        assert applier.wait_for_seq(poplog.last_seq, 30)
        # REPLICAOF NO ONE == promote
        resp = rc.replica_of("NO ONE")
        assert resp["ok"] and rc.health()["role"] == "primary"
        # demote the OLD primary onto the new one: writes fence instantly
        resp = pc.replica_of(f"127.0.0.1:{rport}", epoch=rsvc.epoch)
        assert resp["was_primary"]
        fresh = BloomClient(f"127.0.0.1:{pport}", max_retries=0)
        with pytest.raises(BloomServiceError, match="READONLY"):
            fresh._call_once("InsertBatch", {"name": "f", "keys": [b"x"]})
        fresh.close()
        # and it syncs content from the new primary
        rc.insert_batch("f", [b"from-new-primary"])
        demoted = psvc.replica_applier
        assert demoted is not None
        assert demoted.wait_for_seq(rsvc.oplog.last_seq, 30), (
            demoted.status()
        )
        check = BloomClient(f"127.0.0.1:{pport}")
        assert check.include("f", b"from-new-primary")
        check.close()
        # stale ReplicaOf (older epoch) is rejected (raw call: the stock
        # client would heal by adopting the advertised epoch)
        with pytest.raises(BloomServiceError, match="STALE_EPOCH"):
            pc._call_once(
                "ReplicaOf", {"primary": "127.0.0.1:1", "epoch": 0}
            )
    finally:
        if psvc.replica_applier is not None:
            psvc.replica_applier.stop()
        rc.close()
        pc.close()
        rsrv.stop(grace=None)
        psrv.stop(grace=None)
        poplog.close()
        if rsvc.oplog is not None:
            rsvc.oplog.close()


def test_promotion_during_partial_resync_stays_exactly_once(tmp_path):
    """Kill the stream mid-batch; promote WHILE the link is lost (the
    reconnect-in-flight case): the promoted node must adopt exactly what
    it applied, and counting counts prove nothing doubled or vanished."""
    psvc, psrv, pport, poplog = _primary(tmp_path)
    pc = BloomClient(f"127.0.0.1:{pport}")
    pc.wait_ready()
    keys = [b"m%015d" % i for i in range(150)]
    pc.create_filter("cnt", capacity=20_000, error_rate=0.01, counting=True)
    pc.insert_batch("cnt", keys)

    rsvc, rsrv, rport, applier = _replica(
        tmp_path, pport, name="rlog", chained=True
    )
    rc = BloomClient(f"127.0.0.1:{rport}")
    try:
        assert applier.wait_for_seq(poplog.last_seq, 30)
        faults.arm("repl.stream_send", "once")
        pc.insert_batch("cnt", keys[:50])  # count -> 2 for those
        _wait(
            lambda: applier.link in ("lost", "connecting")
            or applier.partial_syncs > 0,
            msg="stream break",
        )
        resp = rc.promote()  # mid-resync promotion
        assert resp["ok"]
        # whatever the applier had applied is the adopted history; the
        # client now re-drives the batch against the new primary with
        # the SAME rid — dedup/seq-gating must keep counts exact.
        applied_second = rsvc.oplog.last_seq >= poplog.last_seq
        if not applied_second:
            rc.insert_batch("cnt", keys[:50])
        rc.delete_batch("cnt", keys[:50])  # 2 - 1 = 1
        rc.delete_batch("cnt", keys)       # 1 - 1 = 0
        assert not rc.include_batch("cnt", keys).any(), (
            "promotion mid-resync lost or doubled records"
        )
    finally:
        rc.close()
        pc.close()
        rsrv.stop(grace=None)
        psrv.stop(grace=None)
        poplog.close()
        if rsvc.oplog is not None:
            rsvc.oplog.close()


# -- replica cursor persistence (satellite) ----------------------------------


def test_replica_restart_partial_resyncs_from_local_state(tmp_path):
    """PR-3 follow-up closed: a replica with local checkpoints + the
    CRC-checked ``repl_cursor.json`` restarts into a PARTIAL resync —
    no full snapshot transfer — and stays exactly-once."""
    psvc, psrv, pport, poplog = _primary(tmp_path)
    pc = BloomClient(f"127.0.0.1:{pport}")
    pc.wait_ready()
    keys = [b"r%015d" % i for i in range(120)]
    pc.create_filter("cnt", capacity=20_000, error_rate=0.01, counting=True)
    pc.insert_batch("cnt", keys)

    state_dir = str(tmp_path / "replica-state")
    sink_dir = str(tmp_path / "replica-ckpt")
    store = ReplicaStateStore(state_dir)

    def make_replica_service():
        svc = BloomService(
            sink_factory=lambda config: ckpt.FileSink(sink_dir),
            read_only=True,
        )
        svc._manifest_dir = state_dir
        svc.replica_state_store = store
        return svc

    rsvc = make_replica_service()
    rsrv, rport = build_server(rsvc, "127.0.0.1:0")
    rsrv.start()
    applier = ReplicaApplier(
        rsvc, f"127.0.0.1:{pport}", reconnect_base=0.05, state_store=store
    ).start()
    try:
        assert applier.wait_for_seq(poplog.last_seq, 30), applier.status()
        assert applier.full_syncs == 1
        # checkpoint locally so restart has state to restore
        rsvc.Checkpoint({"name": "cnt", "wait": True})
        applier.stop()
        rsrv.stop(grace=None)
        assert store.load() is not None  # cursor persisted on stop

        # writes continue while the replica is down
        pc.insert_batch("cnt", [b"while-down"])

        # "restart": fresh service, same sink/manifest/cursor state
        rsvc2 = make_replica_service()
        cursor, log_id = bootstrap_from_local(rsvc2, store)
        assert cursor is not None and log_id == poplog.log_id
        rsrv2, rport2 = build_server(rsvc2, "127.0.0.1:0")
        rsrv2.start()
        applier2 = ReplicaApplier(
            rsvc2,
            f"127.0.0.1:{pport}",
            reconnect_base=0.05,
            state_store=store,
            initial_cursor=cursor,
            initial_log_id=log_id,
        ).start()
        try:
            assert applier2.wait_for_seq(poplog.last_seq, 30), (
                applier2.status()
            )
            assert applier2.full_syncs == 0, (
                "restart paid a full resync despite local state"
            )
            assert applier2.partial_syncs == 1
            rc = BloomClient(f"127.0.0.1:{rport2}")
            assert rc.include("cnt", b"while-down")
            # exactly-once across restart + partial resync
            pc.delete_batch("cnt", keys)
            assert applier2.wait_for_seq(poplog.last_seq, 30)
            assert not rc.include_batch("cnt", keys).any(), (
                "records double-applied across the replica restart"
            )
            rc.close()
        finally:
            applier2.stop()
            rsrv2.stop(grace=None)
    finally:
        pc.close()
        psrv.stop(grace=None)
        poplog.close()


def test_replica_cursor_file_corruption_forces_full_resync(tmp_path):
    store = ReplicaStateStore(str(tmp_path))
    store.store(42, "someid")
    assert store.load() == {"cursor": 42, "log_id": "someid"}
    with open(store.path, "a") as f:
        f.write("zzz")
    assert store.load() is None  # corrupt -> no cursor -> full resync


# -- batched stream frames (satellite) ---------------------------------------


def test_batched_stream_frames_roundtrip_exactly_once(tmp_path):
    """--repl-batch-bytes + the negotiated capability coalesce a record
    tail into zlib frames; content and exactly-once semantics
    unchanged. The tail is built deterministically: sync, disconnect,
    accumulate 64 records, reconnect with the carried cursor (partial
    resync streams the whole backlog at once)."""
    oplog = OpLog(str(tmp_path / "plog"))
    psvc = BloomService(oplog=oplog, repl_batch_bytes=2048)
    psrv, pport = build_server(psvc, "127.0.0.1:0")
    psrv.start()
    pc = BloomClient(f"127.0.0.1:{pport}")
    pc.wait_ready()
    keys = [b"b%015d" % i for i in range(64)]
    pc.create_filter("cnt", capacity=20_000, error_rate=0.01, counting=True)

    rsvc, rsrv, rport, applier = _replica(tmp_path, pport)
    rc = BloomClient(f"127.0.0.1:{rport}")
    try:
        assert applier.wait_for_seq(oplog.last_seq, 30), applier.status()
        applier.stop()  # disconnect; the backlog accumulates
        for k in keys:
            pc.insert_batch("cnt", [k])

        before = obs_counters.get("repl_stream_batched_frames")
        applier2 = ReplicaApplier(
            rsvc,
            f"127.0.0.1:{pport}",
            reconnect_base=0.05,
            initial_cursor=applier.cursor,
            initial_log_id=applier.log_id,
        ).start()
        try:
            assert applier2.wait_for_seq(oplog.last_seq, 30), (
                applier2.status()
            )
            assert applier2.partial_syncs == 1
            assert obs_counters.get("repl_stream_batched_frames") > before
            assert obs_counters.get("repl_batched_frames_received") > 0
            # compression actually compressed (repeated msgpack keys)
            raw = obs_counters.get("repl_stream_batched_bytes_raw")
            wire = obs_counters.get("repl_stream_batched_bytes_wire")
            assert 0 < wire < raw
            assert rc.include_batch("cnt", keys).all()
            pc.delete_batch("cnt", keys)
            assert applier2.wait_for_seq(oplog.last_seq, 30)
            assert not rc.include_batch("cnt", keys).any(), (
                "batched frames double-applied records"
            )
        finally:
            applier2.stop()
    finally:
        rc.close()
        pc.close()
        rsrv.stop(grace=None)
        psrv.stop(grace=None)
        oplog.close()


# -- sentinel ----------------------------------------------------------------


def _sentinel_trio(pport, **kwargs):
    defaults = dict(poll_s=0.1, down_after_s=0.5, failover_cooldown_s=0.5)
    defaults.update(kwargs)
    sents = [
        Sentinel(f"127.0.0.1:{pport}", peers=[], **defaults) for _ in range(3)
    ]
    for s in sents:
        s.peers.extend(x.address for x in sents if x is not s)
        s.quorum = 2
    for s in sents:
        s.start()
    return sents


def test_sentinel_quorum_failover_promotes_most_caught_up(tmp_path):
    """The coordinator story end to end, in-process: SDOWN→ODOWN vote,
    most-caught-up pick, survivor re-point, client redirect via
    sentinels, and fencing of the restarted stale primary."""
    psvc, psrv, pport, poplog = _primary(tmp_path)
    pc = BloomClient(f"127.0.0.1:{pport}")
    pc.wait_ready()
    keys = [b"q%015d" % i for i in range(200)]
    pc.create_filter("cnt", capacity=20_000, error_rate=0.01, counting=True)
    pc.insert_batch("cnt", keys)

    r1 = _replica(tmp_path, pport, name="r1log", chained=True)
    r2 = _replica(tmp_path, pport, name="r2log", chained=True)
    sents = _sentinel_trio(pport)
    try:
        for _, _, _, app in (r1, r2):
            assert app.wait_for_seq(poplog.last_seq, 30)
        _wait(
            lambda: len(sents[0].handle_Topology({})["replicas"]) == 2,
            msg="replica discovery",
        )
        # make r2 lag so the pick is meaningful
        r2[3].stop()
        pc.insert_batch("cnt", [b"fresh-%d" % i for i in range(40)])
        assert r1[3].wait_for_seq(poplog.last_seq, 30)

        psrv.stop(grace=None)  # the primary dies
        _wait(
            lambda: any(s.failovers for s in sents),
            timeout=25,
            msg="failover",
        )
        time.sleep(1.5)  # would-be dueling second election window
        assert sum(s.failovers for s in sents) == 1
        leader = next(s for s in sents if s.failovers)
        topo = leader.handle_Topology({})
        assert topo["primary"] == r1[0].listen_address, (
            "sentinel promoted a lagging replica over the caught-up one"
        )

        # topology-aware client: resolves + writes against the new primary
        c = BloomClient(
            sentinels=[s.address for s in sents],
            max_retries=3,
            backoff_base=0.05,
        )
        c.insert_batch("cnt", [b"post-failover"])
        assert c.address == r1[0].listen_address
        assert c.epoch == topo["epoch"]

        # the lagging survivor was re-pointed and catches up
        new_app = r2[0].replica_applier
        assert new_app is not None and new_app is not r2[3]
        assert new_app.wait_for_seq(r1[0].oplog.last_seq, 30), (
            new_app.status()
        )
        rc2 = BloomClient(f"127.0.0.1:{r2[2]}")
        assert rc2.include("cnt", b"post-failover")
        rc2.close()

        # fencing: the old primary restarts (stale epoch) on its old port
        back_oplog = OpLog(psvc.oplog.directory)
        back_svc = BloomService(oplog=back_oplog)
        back_svc.replay_oplog()
        back_svc.listen_address = f"127.0.0.1:{pport}"
        back_srv, back_port = build_server(back_svc, f"127.0.0.1:{pport}")
        assert back_port == pport
        back_srv.start()
        assert not back_svc.read_only and back_svc.epoch == 0
        _wait(lambda: back_svc.read_only, timeout=20, msg="fencing")
        h = BloomClient(f"127.0.0.1:{pport}").health()
        assert h["role"] == "replica" and h["epoch"] == topo["epoch"]
        assert back_svc.replica_applier.wait_for_seq(
            r1[0].oplog.last_seq, 30
        ), back_svc.replica_applier.status()
        fc = BloomClient(f"127.0.0.1:{pport}")
        assert fc.include("cnt", b"post-failover")
        fc.close()
        back_svc.replica_applier.stop()
        back_srv.stop(grace=None)
        back_oplog.close()
        c.close()
    finally:
        for s in sents:
            s.stop()
        for svc, srv, _, app in (r1, r2):
            if svc.replica_applier is not None:
                svc.replica_applier.stop()
            app.stop()
            srv.stop(grace=None)
            if svc.oplog is not None:
                svc.oplog.close()
        pc.close()
        poplog.close()


def test_sentinel_without_quorum_never_fails_over(tmp_path):
    """One vote of a required two must NOT promote — a partitioned
    minority sentinel cannot split-brain the deployment."""
    psvc, psrv, pport, poplog = _primary(tmp_path)
    pc = BloomClient(f"127.0.0.1:{pport}")
    pc.wait_ready()
    r1 = _replica(tmp_path, pport, name="r1log", chained=True)
    lone = Sentinel(
        f"127.0.0.1:{pport}",
        peers=["127.0.0.1:1"],  # unreachable peer
        quorum=2,
        poll_s=0.1,
        down_after_s=0.3,
        failover_cooldown_s=0.3,
    ).start()
    try:
        _wait(
            lambda: len(lone.handle_Topology({})["replicas"]) == 1,
            msg="discovery",
        )
        psrv.stop(grace=None)
        time.sleep(3.0)  # several election attempts' worth
        assert lone.failovers == 0
        assert r1[0].read_only, "replica was promoted without quorum"
        assert lone.handle_Topology({})["primary"] == f"127.0.0.1:{pport}"
    finally:
        lone.stop()
        r1[3].stop()
        r1[1].stop(grace=None)
        if r1[0].oplog is not None:
            r1[0].oplog.close()
        pc.close()
        poplog.close()


def test_sentinel_state_persists_across_restart(tmp_path):
    """ISSUE-5 satellite (closes the PR-4 follow-up): with --state-dir,
    a sentinel restart remembers the failover history — the adopted
    topology epoch/primary AND the one-vote-per-epoch discipline — so a
    full-quorum restart cannot re-grant spent epochs or resume watching
    the pre-failover primary."""
    state = str(tmp_path / "sentinel-state")
    s = Sentinel("127.0.0.1:1", peers=[], quorum=2, state_dir=state)
    # a completed failover announced by a peer leader
    s.handle_AnnounceTopology(
        {"epoch": 7, "primary": "127.0.0.1:9", "replicas": ["127.0.0.1:8"],
         "fenced": "127.0.0.1:1"}
    )
    # and a vote granted in a later election
    s._sdown = True
    assert s.handle_VoteDown({"epoch": 8, "primary": "127.0.0.1:9"})["granted"]

    # "restart": a fresh Sentinel over the same state dir
    s2 = Sentinel("127.0.0.1:1", peers=[], quorum=2, state_dir=state)
    topo = s2.handle_Topology({})
    assert topo["epoch"] == 7 and topo["primary"] == "127.0.0.1:9"
    assert "127.0.0.1:8" in topo["replicas"]
    # the fenced-primary watchlist survives too — a stale primary that
    # reappears AFTER the restart must still get demoted on sight
    assert "127.0.0.1:1" in s2._fence_watch
    # the spent vote survives: epoch 8 cannot be granted twice...
    s2._sdown = True
    assert not s2.handle_VoteDown(
        {"epoch": 8, "primary": "127.0.0.1:9"}
    )["granted"]
    # ...but a genuinely newer epoch can
    assert s2.handle_VoteDown({"epoch": 9, "primary": "127.0.0.1:9"})["granted"]

    # corruption reads as absent — fall back to --watch, never crash
    store_path = s2._state_store.path
    with open(store_path, "a") as f:
        f.write("rot")
    s3 = Sentinel("127.0.0.1:1", peers=[], quorum=2, state_dir=state)
    assert s3.handle_Topology({})["primary"] == "127.0.0.1:1"
    assert s3._last_vote_epoch == 0


def test_sentinel_vote_rules():
    s = Sentinel("127.0.0.1:1", peers=[], quorum=2)
    # not sdown -> no grant
    resp = s.handle_VoteDown({"epoch": 1, "primary": "127.0.0.1:1"})
    assert not resp["granted"]
    s._sdown = True
    # wrong primary -> no grant
    assert not s.handle_VoteDown(
        {"epoch": 1, "primary": "elsewhere:9"}
    )["granted"]
    # proper request -> granted, and the epoch is spent (vote once)
    assert s.handle_VoteDown({"epoch": 1, "primary": "127.0.0.1:1"})["granted"]
    assert not s.handle_VoteDown(
        {"epoch": 1, "primary": "127.0.0.1:1"}
    )["granted"]
    # a newer epoch is grantable again
    assert s.handle_VoteDown({"epoch": 2, "primary": "127.0.0.1:1"})["granted"]


# -- topology-aware client ---------------------------------------------------


def test_client_static_topology_and_stale_epoch_recovery(tmp_path):
    psvc, psrv, pport, poplog = _primary(tmp_path)
    try:
        psvc.adopt_epoch(3)
        # a client under an OLD epoch view: first write bounces with
        # STALE_EPOCH, the client adopts the server's epoch and retries
        c = BloomClient(
            topology={
                "epoch": 1,
                "primary": f"127.0.0.1:{pport}",
                "replicas": [],
            }
        )
        c.wait_ready()
        before = obs_counters.get("client_topology_refreshes")
        c.create_filter("t", capacity=1000, error_rate=0.01)
        c.insert_batch("t", [b"x"])
        assert c.epoch == 3
        assert c.include("t", b"x")
        assert (
            psvc.metrics.snapshot()["counters"]["stale_epoch_rejected"] >= 1
        )
        assert obs_counters.get("client_topology_refreshes") == before
        c.close()
    finally:
        psrv.stop(grace=None)
        poplog.close()


def test_fetch_topology_none_when_unreachable():
    assert fetch_topology(["127.0.0.1:1"], timeout=0.3) is None


# -- CLI ---------------------------------------------------------------------


def test_promote_cli_subcommand(tmp_path, capsys):
    from tpubloom.server.service import main as server_main

    psvc, psrv, pport, poplog = _primary(tmp_path)
    pc = BloomClient(f"127.0.0.1:{pport}")
    pc.wait_ready()
    pc.create_filter("f", capacity=1000, error_rate=0.01)
    rsvc, rsrv, rport, applier = _replica(
        tmp_path, pport, name="rlog", chained=True
    )
    try:
        assert applier.wait_for_seq(poplog.last_seq, 30)
        with pytest.raises(SystemExit) as e:
            server_main(["promote", f"127.0.0.1:{rport}"])
        assert e.value.code == 0
        out = capsys.readouterr().out
        assert '"epoch": 1' in out
        assert rsvc.read_only is False
    finally:
        pc.close()
        rsrv.stop(grace=None)
        psrv.stop(grace=None)
        poplog.close()
        if rsvc.oplog is not None:
            rsvc.oplog.close()


# -- the acceptance chaos story ----------------------------------------------

#: mirrors test_faults' child pattern: the image's sitecustomize force-sets
#: jax_platforms to the TPU plugin, so the child must pin cpu first.
_SERVER_CHILD = """\
import sys
import jax
jax.config.update("jax_platforms", "cpu")
from tpubloom.server.service import main
main(sys.argv[1:])
"""


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_failover_sigkill_acceptance(tmp_path):
    """The ISSUE-4 acceptance scenario: SIGKILL the primary (a real
    process) under concurrent client load → the sentinel quorum promotes
    the most-caught-up replica → the surviving replica re-points via
    ReplicaOf → the client completes every batch through its sentinel
    view — and counting-filter counts prove zero lost / zero doubled
    acknowledged writes. The restarted old primary (stale epoch) is
    fenced back to replica."""
    import signal
    import subprocess
    import sys as _sys

    port = _free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }
    plog = tmp_path / "primary-log"
    script = tmp_path / "server_child.py"
    script.write_text(_SERVER_CHILD)
    child_args = [
        _sys.executable, str(script), str(port),
        "--repl-log-dir", str(plog),
        # black box armed in chaos mode (ISSUE 16): sample 0.0 means
        # only slowlog-worthy/forced work spills — the worst case the
        # post-mortem below must still decode after the SIGKILL
        "--trace-sample", "0.0",
    ]
    proc = subprocess.Popen(
        child_args,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
    )
    boot = BloomClient(f"127.0.0.1:{port}")
    sents = []
    r1 = r2 = None
    try:
        boot.wait_ready(timeout=120)
        boot.create_filter(
            "cnt", capacity=50_000, error_rate=0.01, counting=True
        )
        r1 = _replica(tmp_path, port, name="r1log", chained=True)
        r2 = _replica(tmp_path, port, name="r2log", chained=True)
        sents = _sentinel_trio(port)
        _wait(
            lambda: len(sents[0].handle_Topology({})["replicas"]) == 2,
            msg="replica discovery",
        )

        client = BloomClient(
            sentinels=[s.address for s in sents],
            max_retries=8,
            backoff_base=0.1,
            backoff_max=1.0,
            breaker_threshold=0,
        )
        n_batches, batch_size = 30, 20
        batches = [
            [b"acc-%03d-%03d" % (i, j) for j in range(batch_size)]
            for i in range(n_batches)
        ]
        acked: list = []  # (batch_index, rid)
        errors: list = []
        killed = threading.Event()

        def writer():
            for i, keys in enumerate(batches):
                if i == 8:
                    killed.set()  # signal the main thread to SIGKILL
                try:
                    client.insert_batch("cnt", keys)
                    acked.append((i, client.last_rid))
                    continue
                except Exception as e:  # noqa: BLE001
                    errors.append((i, repr(e)))
                # the logical call exhausted its budget mid-failover:
                # keep re-driving with the SAME rid (a fresh rid could
                # double-apply a landed-but-unacked batch; the fixed
                # one answers from the dedup cache instead)
                rid = client.last_rid
                while True:
                    try:
                        client.refresh_topology()
                        client._call_once(
                            "InsertBatch",
                            {"name": "cnt", "keys": keys, "rid": rid},
                        )
                        acked.append((i, rid))
                        break
                    except Exception as e:  # noqa: BLE001
                        errors.append((i, repr(e)))
                        if len(errors) > 300:
                            raise
                        time.sleep(0.2)

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        assert killed.wait(60), "writer never reached the kill point"
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
        t.join(timeout=180)
        assert not t.is_alive(), (
            f"writer wedged; acked={len(acked)} errors={errors[-3:]}"
        )
        assert len(acked) == n_batches, (
            f"client failed to complete all batches: {len(acked)}; "
            f"errors={errors[-3:]}"
        )

        # post-mortem (ISSUE 16): the SIGKILLed primary ran no handler,
        # but its mmap'd black box survives — it must decode into the
        # node's lifecycle and the pre-kill batches' spilled spans
        from tpubloom.obs import blackbox as bb

        node = bb.read_node(str(plog))
        assert node is not None, "SIGKILL must leave a readable black box"
        assert node["meta"].get("role") == "primary"
        assert "boot" in [e["kind"] for e in node["events"]]
        dead_rids = {s.get("rid") for s in node["spans"]}
        pre_kill = [rid for i, rid in acked if i < 8]
        assert pre_kill and set(pre_kill) <= dead_rids, (
            "pre-kill acked rids must have spilled spans in the dead "
            "primary's ring"
        )
        assert bb.merge_timeline([node], rid=pre_kill[-1])

        # the failover happened and the client followed it
        topo = fetch_topology([s.address for s in sents])
        assert topo is not None and topo["primary"] != f"127.0.0.1:{port}"
        new_primary = topo["primary"]
        assert client.address == new_primary

        # re-drive EVERY acked batch with its ORIGINAL rid against the
        # new primary: a batch that replicated before the kill answers
        # from the rid-dedup cache (no double), a batch whose ack raced
        # the kill applies now (no loss) — this is exactly the PR-2
        # dedup contract the ISSUE pins.
        redrive = BloomClient(new_primary)
        for i, rid in acked:
            redrive._call_once(
                "InsertBatch",
                {"name": "cnt", "keys": batches[i], "rid": rid},
            )

        # zero lost: every acknowledged key is present
        all_keys = [k for b in batches for k in b]
        assert redrive.include_batch("cnt", all_keys).all(), (
            "acknowledged writes lost across the failover"
        )
        # zero doubled: counting counts are exactly 1 -> one delete
        # round empties every key
        for i, _ in acked:
            redrive.delete_batch("cnt", batches[i])
        assert not redrive.include_batch("cnt", all_keys).any(), (
            "acknowledged writes double-applied across the failover"
        )
        redrive.close()

        # restart the old primary: stale epoch -> fenced to replica
        proc2 = subprocess.Popen(
            child_args,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        try:
            fence_probe = BloomClient(f"127.0.0.1:{port}")
            fence_probe.wait_ready(timeout=120)
            _wait(
                lambda: fence_probe.health()["role"] == "replica",
                timeout=30,
                msg="stale-primary fencing",
            )
            h = fence_probe.health()
            assert h["epoch"] == topo["epoch"]
            fence_probe.close()
        finally:
            proc2.send_signal(signal.SIGTERM)
            try:
                proc2.wait(timeout=60)
            except subprocess.TimeoutExpired:
                proc2.kill()
        client.close()
    finally:
        if proc.poll() is None:
            proc.kill()
        for s in sents:
            s.stop()
        for r in (r1, r2):
            if r is None:
                continue
            svc, srv, _, app = r
            if svc.replica_applier is not None:
                svc.replica_applier.stop()
            app.stop()
            srv.stop(grace=None)
            if svc.oplog is not None:
                svc.oplog.close()
        boot.close()


def test_ha_smoke():
    """benchmarks/ha_smoke.py runs in tier-1 so the failover surface
    cannot silently rot (and CI runs it standalone)."""
    import importlib
    import sys

    bench_dir = os.path.join(os.path.dirname(__file__), "..", "benchmarks")
    sys.path.insert(0, os.path.abspath(bench_dir))
    try:
        ha_smoke = importlib.import_module("ha_smoke")
        result = ha_smoke.run_smoke()
    finally:
        sys.path.pop(0)
    assert result["failovers"] >= 1
    assert result["lost_acked"] == 0
    assert result["double_applied"] == 0
    assert result["failover_seconds"] < 30


# -- review-hardening regressions --------------------------------------------


def test_demotion_never_drops_acked_writes_from_the_log(tmp_path):
    """Review finding: an in-flight write that passed the READONLY check
    before a demotion fence must still land in the op log (become_replica
    drains writers before the applier takes the log over) — every write
    the client saw acked is a record."""
    from tpubloom.ha.promotion import become_replica

    psvc, psrv, pport, poplog = _primary(tmp_path)
    pc = BloomClient(f"127.0.0.1:{pport}", max_retries=0)
    pc.wait_ready()
    pc.create_filter("d", capacity=10_000, error_rate=0.01)
    acked = []
    stop = threading.Event()

    def writer():
        i = 0
        while not stop.is_set():
            key = b"w%014d" % i
            try:
                pc.insert_batch("d", [key])
            except Exception:  # noqa: BLE001 — READONLY fence, or the
                # client's auto-redirect chasing the (bogus) new primary
                return
            acked.append(key)
            i += 1

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    _wait(lambda: len(acked) > 5, msg="writer warm-up")
    try:
        # demote mid-stream (the target primary need not be reachable —
        # the drain + log handoff is what's under test)
        become_replica(psvc, "127.0.0.1:1")
        stop.set()
        t.join(timeout=30)
        assert not t.is_alive()
        logged = {
            k
            for r in poplog.read_from(0)
            if r["method"] == "InsertBatch"
            for k in r["req"]["keys"]
        }
        missing = [k for k in acked if k not in logged]
        assert not missing, (
            f"{len(missing)} acked write(s) vanished from the log across "
            f"the demotion fence, e.g. {missing[:3]}"
        )
    finally:
        stop.set()
        if psvc.replica_applier is not None:
            psvc.replica_applier.stop()
        pc.close()
        psrv.stop(grace=None)
        poplog.close()


def test_chained_replica_log_is_truncated(tmp_path, monkeypatch):
    """Review finding: the truncation sweep must run on the reappend
    path too, or a chained replica's log grows without bound."""
    from tpubloom.server import service as service_mod

    monkeypatch.setattr(service_mod, "TRUNCATE_EVERY_APPENDS", 4)
    psvc, psrv, pport, poplog = _primary(tmp_path)
    pc = BloomClient(f"127.0.0.1:{pport}")
    pc.wait_ready()
    pc.create_filter("t", capacity=10_000, error_rate=0.01)

    roplog = OpLog(str(tmp_path / "rlog"), segment_bytes=256)
    rsvc = BloomService(
        sink_factory=lambda config: ckpt.FileSink(str(tmp_path / "rck")),
        oplog=roplog,
        read_only=True,
    )
    rsrv, rport = build_server(rsvc, "127.0.0.1:0")
    rsrv.start()
    applier = ReplicaApplier(
        rsvc, f"127.0.0.1:{pport}", reconnect_base=0.05
    ).start()
    try:
        for i in range(16):
            pc.insert_batch("t", [b"a%05d" % i])
        assert applier.wait_for_seq(poplog.last_seq, 30), applier.status()
        assert roplog.stats()["segments"] > 1  # there IS something to GC
        rsvc.Checkpoint({"name": "t", "wait": True})  # covers everything
        for i in range(16):  # reappends drive the sweep past the ckpt
            pc.insert_batch("t", [b"b%05d" % i])
        assert applier.wait_for_seq(poplog.last_seq, 30), applier.status()
        assert roplog.first_seq > 1, (
            "chained replica log never truncated despite a covering "
            "checkpoint"
        )
    finally:
        applier.stop()
        pc.close()
        rsrv.stop(grace=None)
        psrv.stop(grace=None)
        poplog.close()
        roplog.close()


def test_client_sentinels_unreachable_raises_no_topology():
    """Review finding: sentinel-resolved construction must not silently
    fall back to localhost when no sentinel answers."""
    with pytest.raises(BloomServiceError, match="NO_TOPOLOGY"):
        BloomClient(sentinels=["127.0.0.1:1"])
    # an explicit address stays a valid fallback
    c = BloomClient("127.0.0.1:2", sentinels=["127.0.0.1:1"])
    assert c.address == "127.0.0.1:2"
    c.close()


# -- ISSUE 13 (chaos-coverage closure): the promotion / vote / chained
# re-append fault points get their own armed drives --------------------------


def test_promote_fault_point_aborts_promotion_cleanly(tmp_path):
    """``ha.promote`` fires at the very top of replica→primary
    promotion: an armed firing must abort the flip BEFORE any state
    changed — the node stays a fenced read-only replica and a later
    (disarmed) promote succeeds from scratch."""
    psvc, psrv, pport, poplog = _primary(tmp_path)
    pc = BloomClient(f"127.0.0.1:{pport}")
    pc.wait_ready()
    pc.create_filter("cnt", capacity=10_000, error_rate=0.01, counting=True)
    pc.insert_batch("cnt", [b"k%015d" % i for i in range(50)])

    rsvc, rsrv, rport, applier = _replica(
        tmp_path, pport, name="chainlog", chained=True
    )
    rc = BloomClient(f"127.0.0.1:{rport}")
    try:
        assert applier.wait_for_seq(poplog.last_seq, 30), applier.status()

        faults.arm("ha.promote", "always")
        with pytest.raises(BloomServiceError, match="INTERNAL"):
            rc.promote()
        # nothing flipped: still a fenced replica on epoch 0
        h = rc.health()
        assert h["role"] == "replica" and rsvc.read_only
        assert obs_counters.get("fault_ha_promote") >= 1
        # raw call (the stock client would auto-redirect to the primary):
        # the node itself still fences writes
        with pytest.raises(BloomServiceError, match="READONLY"):
            rc._call_once("InsertBatch", {"name": "cnt", "keys": [b"fenced"]})

        faults.disarm("ha.promote")  # the aborted promotion re-drives
        resp = rc.promote()
        assert resp["ok"] and not resp["already_primary"]
        assert rc.health()["role"] == "primary"
        rc.insert_batch("cnt", [b"post-promo-write"])
        assert rc.include("cnt", b"post-promo-write")
    finally:
        applier.stop()
        rc.close()
        pc.close()
        rsrv.stop(grace=None)
        psrv.stop(grace=None)
        poplog.close()
        if rsvc.oplog is not None:
            rsvc.oplog.close()


def test_vote_fault_point_injects_into_grant_path():
    """``ha.vote`` armed: the grant path dies mid-election (the caller
    sees a dead peer, exactly what the quorum loop tolerates) and the
    vote is NOT spent — once disarmed the same epoch is still
    grantable, so an injected vote failure cannot silently burn the
    term the way a granted-then-lost frame would."""
    s = Sentinel("127.0.0.1:1", peers=[], quorum=2)
    s._sdown = True
    faults.arm("ha.vote", "once")
    with pytest.raises(faults.InjectedFault):
        s.handle_VoteDown({"epoch": 1, "primary": "127.0.0.1:1"})
    assert obs_counters.get("fault_ha_vote") >= 1
    # the fault fired BEFORE the vote registered: epoch 1 is still live
    assert s.handle_VoteDown({"epoch": 1, "primary": "127.0.0.1:1"})["granted"]
    # and the term discipline still holds afterwards
    assert not s.handle_VoteDown(
        {"epoch": 1, "primary": "127.0.0.1:1"}
    )["granted"]


def test_chained_reappend_fault_heals_exactly_once(tmp_path):
    """``repl.reappend`` armed on a chained replica: the write-ahead
    re-append dies, the applier reconnects, and the re-delivered record
    lands in the local log + filter exactly once (the chained log keeps
    the upstream seq space gap-free)."""
    psvc, psrv, pport, poplog = _primary(tmp_path)
    pc = BloomClient(f"127.0.0.1:{pport}")
    pc.wait_ready()
    keys = [b"r%015d" % i for i in range(150)]
    pc.create_filter("cnt", capacity=20_000, error_rate=0.01, counting=True)
    pc.insert_batch("cnt", keys)

    mid_svc, mid_srv, mid_port, mid_app = _replica(
        tmp_path, pport, name="midlog", chained=True
    )
    mc = BloomClient(f"127.0.0.1:{mid_port}")
    try:
        assert mid_app.wait_for_seq(poplog.last_seq, 30), mid_app.status()
        assert mid_svc.oplog.last_seq == poplog.last_seq

        before = obs_counters.get("fault_repl_reappend")
        faults.arm("repl.reappend", "once")
        live = [b"live-%07d" % i for i in range(40)]
        pc.insert_batch("cnt", live)
        assert mid_app.wait_for_seq(poplog.last_seq, 30), mid_app.status()
        assert obs_counters.get("fault_repl_reappend") == before + 1
        # the chained log re-converged on the upstream seq space
        assert mid_svc.oplog.last_seq == poplog.last_seq
        assert mc.include_batch("cnt", live).all()

        # exactly-once: ONE delete round empties every count
        pc.delete_batch("cnt", keys + live)
        assert mid_app.wait_for_seq(poplog.last_seq, 30)
        assert not mc.include_batch("cnt", keys + live).any(), (
            "re-delivered record double-applied through the chained log"
        )
    finally:
        mid_app.stop()
        mc.close()
        pc.close()
        mid_srv.stop(grace=None)
        psrv.stop(grace=None)
        poplog.close()
        if mid_svc.oplog is not None:
            mid_svc.oplog.close()
