"""Replication & changefeed suite (ISSUE 3).

Layers covered:

* record framing — CRC round-trip, torn-tail detection;
* OpLog — append/replay, crash-recovery truncation, segment rolling +
  checkpoint-keyed truncation;
* server — op-log appends at commit points, startup replay over
  restored checkpoints (AOF parity), checkpoint-seq gating (nothing
  applies twice), READONLY rejection on replicas;
* primary→replica streaming — full resync (snapshot + tail), live
  tailing to ``repl_lag_seq == 0``, kill-the-stream-mid-batch chaos via
  ``repl.stream_send`` with counting-filter exactly-once proof,
  replica-side NOT_FOUND-free reads, MONITOR stream filtering;
* client — read-preference routing to replicas with primary fallback,
  READONLY→primary redirect;
* satellites — RedisSink multi-generation restore walk, adaptive
  ``retry_after_ms`` growth under load, InsertBatch rid-dedup,
  inspect-quarantine CLI + quarantine size cap.
"""

import os
import threading
import time

import numpy as np
import pytest

from tpubloom import checkpoint as ckpt
from tpubloom import faults
from tpubloom.config import FilterConfig
from tpubloom.obs import counters as obs_counters
from tpubloom.repl import OpLog, encode_record, scan_buffer
from tpubloom.repl.log import DEFAULT_SEGMENT_BYTES
from tpubloom.repl.record import decode_record
from tpubloom.repl.replica import ReplicaApplier
from tpubloom.server.client import BloomClient
from tpubloom.server.protocol import BloomServiceError
from tpubloom.server.service import BloomService, build_server

from tests.fake_redis import FakeRedis


@pytest.fixture(autouse=True)
def _disarm_all():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture(autouse=True)
def _blackbox_reset():
    """ISSUE 18 satellite: replicas now arm the process-global black box
    from their state dir — drop the mapping between tests so one test's
    replica ring (in a soon-deleted tmp_path) never absorbs the next
    test's records."""
    from tpubloom.obs import blackbox

    blackbox.reset_for_tests()
    yield
    blackbox.reset_for_tests()


def _rand_keys(n, rng):
    return [rng.bytes(16) for _ in range(n)]


# -- record framing ----------------------------------------------------------


def test_record_roundtrip():
    rec = {
        "seq": 7,
        "method": "InsertBatch",
        "rid": "abc",
        "req": {"name": "f", "keys": [b"\x00k1", b"k2"]},
        "ts": 123.5,
    }
    frame = encode_record(rec)
    decoded, end = decode_record(frame)
    assert decoded == rec and end == len(frame)


def test_scan_buffer_detects_torn_tail():
    frames = b"".join(
        encode_record({"seq": i, "method": "Clear", "rid": None,
                       "req": {"name": "f"}, "ts": 0.0})
        for i in range(1, 4)
    )
    records, valid, clean = scan_buffer(frames)
    assert [r["seq"] for r in records] == [1, 2, 3] and clean

    # tear the last record: only the intact prefix survives
    torn = frames[:-5]
    records, valid, clean = scan_buffer(torn)
    assert [r["seq"] for r in records] == [1, 2] and not clean
    assert torn[:valid] == frames[: valid]

    # flip a body byte: CRC catches it at that record
    rotted = bytearray(frames)
    rotted[-3] ^= 0xFF
    records, _, clean = scan_buffer(bytes(rotted))
    assert [r["seq"] for r in records] == [1, 2] and not clean


# -- OpLog -------------------------------------------------------------------


def test_oplog_append_read_and_recovery(tmp_path):
    d = str(tmp_path / "log")
    lg = OpLog(d)
    for i in range(10):
        lg.append("InsertBatch", {"name": "f", "keys": [b"k%d" % i]},
                  rid="r%d" % i)
    assert lg.last_seq == 10 and lg.first_seq == 1
    recs = list(lg.read_from(4))
    assert [r["seq"] for r in recs] == [5, 6, 7, 8, 9, 10]
    assert recs[0]["req"]["keys"] == [b"k4"] and recs[0]["rid"] == "r4"
    lg.close()

    # clean reopen continues the sequence
    lg2 = OpLog(d)
    assert lg2.last_seq == 10
    assert lg2.append("Clear", {"name": "f"}) == 11
    lg2.close()


def test_oplog_torn_tail_truncated_on_recovery(tmp_path):
    d = str(tmp_path / "log")
    lg = OpLog(d)
    for i in range(5):
        lg.append("Clear", {"name": "f"})
    # pick the SEGMENT file — listdir order is filesystem-dependent and
    # the dir also holds oplog.id (truncating that leaves the log whole)
    seg = os.path.join(
        d, next(f for f in sorted(os.listdir(d)) if f.endswith(".seg"))
    )
    lg.close()
    size = os.path.getsize(seg)
    with open(seg, "r+b") as f:
        f.truncate(size - 7)  # crash mid-append
    before = obs_counters.get("repl_log_torn_tail_truncated")
    lg2 = OpLog(d)
    assert lg2.last_seq == 4  # record 5 was torn off
    assert obs_counters.get("repl_log_torn_tail_truncated") == before + 1
    assert lg2.append("Clear", {"name": "f"}) == 5  # seq reuses the hole
    lg2.close()


def test_oplog_segments_roll_and_truncate(tmp_path):
    d = str(tmp_path / "log")
    lg = OpLog(d, segment_bytes=256)  # tiny: rolls every few records
    for i in range(40):
        lg.append("InsertBatch", {"name": "f", "keys": [b"key-%04d" % i]})
    st = lg.stats()
    assert st["segments"] > 2 and st["last_seq"] == 40
    # records <= 20 covered by a (hypothetical) checkpoint: whole
    # segments below the safe point drop, the tail stays readable
    removed = lg.truncate_to(20)
    assert removed >= 1
    assert lg.first_seq > 1
    remaining = [r["seq"] for r in lg.read_from(0)]
    assert remaining == sorted(remaining) and remaining[-1] == 40
    # nothing past the safe point is gone — the replay tail is complete
    assert set(range(21, 41)).issubset(remaining)
    recs = [r["seq"] for r in lg.read_from(25)]
    assert recs == list(range(26, 41))
    lg.close()


def test_oplog_wait_for(tmp_path):
    lg = OpLog(str(tmp_path / "log"))
    assert not lg.wait_for(1, timeout=0.05)
    t = threading.Thread(
        target=lambda: (time.sleep(0.05),
                        lg.append("Clear", {"name": "f"})),
    )
    t.start()
    assert lg.wait_for(1, timeout=5.0)
    t.join()
    lg.close()


# -- server: op-log commit points + AOF-parity replay ------------------------


def _server(tmp_path, subdir="ckpt", **kwargs):
    sink_dir = str(tmp_path / subdir)
    service = BloomService(
        sink_factory=lambda config: ckpt.FileSink(sink_dir), **kwargs
    )
    srv, port = build_server(service, "127.0.0.1:0")
    srv.start()
    return srv, service, port


def test_mutations_append_and_replay_restores_state(tmp_path):
    oplog = OpLog(str(tmp_path / "log"))
    srv, service, port = _server(tmp_path, oplog=oplog)
    client = BloomClient(f"127.0.0.1:{port}")
    client.wait_ready()
    rng = np.random.default_rng(0)
    keys = _rand_keys(300, rng)
    client.create_filter("cnt", capacity=10_000, error_rate=0.01,
                         counting=True)
    client.insert_batch("cnt", keys)
    client.delete_batch("cnt", keys[:100])
    client.create_filter("gone", capacity=1000, error_rate=0.01)
    client.drop_filter("gone")
    assert oplog.last_seq == 5  # create, insert, delete, create, drop
    client.close()
    srv.stop(grace=None)
    oplog.close()

    # "crash": no checkpoint ever landed — the log alone rebuilds state
    oplog2 = OpLog(str(tmp_path / "log"))
    service2 = BloomService(
        sink_factory=lambda config: ckpt.FileSink(str(tmp_path / "ckpt")),
        oplog=oplog2,
    )
    stats = service2.replay_oplog()
    assert stats["applied"] == 5 and stats["failed"] == 0
    srv2, port2 = build_server(service2, "127.0.0.1:0")
    srv2.start()
    c2 = BloomClient(f"127.0.0.1:{port2}")
    c2.wait_ready()
    assert c2.list_filters() == ["cnt"]
    assert c2.include_batch("cnt", keys[100:]).all()
    # counting counts survived exactly: one more delete empties them —
    # a double-applied insert replay would leave them present
    c2.delete_batch("cnt", keys[100:])
    assert not c2.include_batch("cnt", keys[100:]).any()
    c2.close()
    srv2.stop(grace=None)
    oplog2.close()


def test_replay_is_gated_by_checkpoint_repl_seq(tmp_path):
    """A checkpoint that landed AFTER some ops must make their replay a
    no-op (the repl_seq stamp in the header gates them) — otherwise a
    restart double-increments counting filters."""
    oplog = OpLog(str(tmp_path / "log"))
    srv, service, port = _server(tmp_path, oplog=oplog)
    client = BloomClient(f"127.0.0.1:{port}")
    client.wait_ready()
    keys = [b"g%015d" % i for i in range(64)]
    client.create_filter("cnt", capacity=10_000, error_rate=0.01,
                         counting=True)
    client.insert_batch("cnt", keys)          # seq 2 (counts -> 1)
    client.checkpoint("cnt", wait=True)       # covers seq 2
    client.insert_batch("cnt", [b"tail-key"])  # seq 3: after the ckpt
    client.close()
    srv.stop(grace=None)
    oplog.close()

    oplog2 = OpLog(str(tmp_path / "log"))
    service2 = BloomService(
        sink_factory=lambda config: ckpt.FileSink(str(tmp_path / "ckpt")),
        oplog=oplog2,
    )
    stats = service2.replay_oplog()
    # create applies (restores the checkpoint), insert@2 skips, tail applies
    assert stats["skipped"] >= 1, stats
    mf = service2._filters["cnt"]
    assert mf.applied_seq == 3
    # counts stayed exactly 1: one delete -> gone
    service2.DeleteBatch({"name": "cnt", "keys": keys})
    hits = service2.QueryBatch({"name": "cnt", "keys": keys})
    assert not np.unpackbits(
        np.frombuffer(hits["hits"], np.uint8), count=hits["n"]
    ).any()
    hits = service2.QueryBatch({"name": "cnt", "keys": [b"tail-key"]})
    assert np.unpackbits(
        np.frombuffer(hits["hits"], np.uint8), count=1
    ).all()
    service2.shutdown()
    oplog2.close()


def test_checkpoint_keyed_log_truncation(tmp_path):
    oplog = OpLog(str(tmp_path / "log"), segment_bytes=512)
    srv, service, port = _server(tmp_path, oplog=oplog)
    client = BloomClient(f"127.0.0.1:{port}")
    client.wait_ready()
    client.create_filter("t", capacity=10_000, error_rate=0.01)
    for i in range(30):
        client.insert_batch("t", [b"key-%06d" % i])
    assert oplog.stats()["segments"] > 2
    client.checkpoint("t", wait=True)  # covers every op so far
    first_before = oplog.first_seq
    service._maybe_truncate_log()
    assert oplog.first_seq > first_before
    # everything still needed for replay is intact
    tail = [r["seq"] for r in oplog.read_from(0)]
    assert tail == sorted(tail) and tail[-1] == oplog.last_seq
    client.close()
    srv.stop(grace=None)
    oplog.close()


def test_checkpoint_triggered_by_logged_batch_carries_its_seq(tmp_path):
    """A checkpoint fired by notify_inserts for the very batch it
    snapshots must stamp THAT batch's seq — otherwise a crash-replay
    re-applies the batch over state that already contains it (review
    finding on the _log_op/notify ordering)."""
    oplog = OpLog(str(tmp_path / "log"))
    sink_dir = str(tmp_path / "ckpt")
    service = BloomService(
        sink_factory=lambda config: ckpt.FileSink(sink_dir), oplog=oplog
    )
    service.CreateFilter(
        {"name": "cnt", "capacity": 10_000, "error_rate": 0.01,
         "options": {"counting": True, "checkpoint_every": 64}}
    )
    keys = [b"n%015d" % i for i in range(64)]
    service.InsertBatch({"name": "cnt", "keys": keys})  # seq 2, triggers
    mf = service._filters["cnt"]
    assert mf.checkpointer.flush()
    assert mf.checkpointer.last_landed_meta["repl_seq"] == 2
    service.shutdown()
    oplog.close()

    # crash-replay: the insert must be gated by the checkpoint stamp
    oplog2 = OpLog(str(tmp_path / "log"))
    service2 = BloomService(
        sink_factory=lambda config: ckpt.FileSink(sink_dir), oplog=oplog2
    )
    stats = service2.replay_oplog()
    assert stats["skipped"] >= 1, stats
    service2.DeleteBatch({"name": "cnt", "keys": keys})
    hits = service2.QueryBatch({"name": "cnt", "keys": keys})
    assert not np.unpackbits(
        np.frombuffer(hits["hits"], np.uint8), count=hits["n"]
    ).any(), "insert replay double-incremented past its own checkpoint"
    service2.shutdown()
    oplog2.close()


def test_log_id_rotates_on_rewind_and_forces_full_resync(tmp_path):
    """Redis-replid parity: a cursor is only resumable against the same
    log identity; recovery that lost records rotates it."""
    d = str(tmp_path / "log")
    lg = OpLog(d)
    id1 = lg.log_id
    for _ in range(4):
        lg.append("Clear", {"name": "f"})
    lg.close()
    lg2 = OpLog(d)
    assert lg2.log_id == id1  # clean reopen: same identity
    seg = [f for f in os.listdir(d) if f.endswith(".seg")][0]
    lg2.close()
    path = os.path.join(d, seg)
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) - 3)  # lose the tail record
    lg3 = OpLog(d)
    assert lg3.log_id != id1  # seq space rewound -> new identity

    # a stale-id cursor gets a full resync even though the seq "exists"
    from tpubloom.repl.primary import repl_stream

    class _Ctx:
        def is_active(self):
            return False

        def peer(self):
            return "test"

    service = BloomService(oplog=lg3)
    gen = repl_stream(service, {"cursor": 1, "log_id": id1}, _Ctx())
    assert next(gen)["kind"] == "full_sync_begin"
    gen.close()
    gen = repl_stream(service, {"cursor": 1, "log_id": lg3.log_id}, _Ctx())
    assert next(gen)["kind"] == "partial_sync"
    gen.close()
    lg3.close()


def test_full_resync_tail_does_not_replay_stale_drop(tmp_path):
    """Review finding: with several filters, the full-resync tail starts
    at the OLDEST snapshot seq — a Drop record older than a re-created
    filter's snapshot must be gated, or the replica drops fresh state
    (and loops full resyncs forever on the restored create)."""
    oplog = OpLog(str(tmp_path / "log"))
    psrv, psvc, pport = _server(tmp_path, oplog=oplog)
    pc = BloomClient(f"127.0.0.1:{pport}")
    pc.wait_ready()
    keys = [b"s%015d" % i for i in range(64)]
    pc.create_filter("idle", capacity=1000, error_rate=0.01)   # seq 1
    pc.create_filter("busy", capacity=10_000, error_rate=0.01)  # seq 2
    pc.insert_batch("busy", keys)                               # seq 3
    pc.checkpoint("busy", wait=True)
    pc.drop_filter("busy")                                      # seq 4
    pc.create_filter("busy", capacity=10_000, error_rate=0.01)  # seq 5 (restored)

    rsvc = BloomService(read_only=True)
    rsrv, rport = build_server(rsvc, "127.0.0.1:0")
    rsrv.start()
    applier = ReplicaApplier(
        rsvc, f"127.0.0.1:{pport}", reconnect_base=0.05
    ).start()
    rc = BloomClient(f"127.0.0.1:{rport}")
    try:
        assert applier.wait_for_seq(oplog.last_seq, 30), applier.status()
        time.sleep(0.3)  # a resync loop would show up as more full syncs
        assert applier.full_syncs == 1, applier.status()
        assert sorted(rc.list_filters()) == ["busy", "idle"]
        assert rc.include_batch("busy", keys).all(), (
            "stale Drop record deleted the re-created filter's state"
        )
    finally:
        applier.stop()
        rc.close()
        pc.close()
        rsrv.stop(grace=None)
        psrv.stop(grace=None)
        oplog.close()


def test_truncation_sweep_from_create_drop_does_not_deadlock(
    tmp_path, monkeypatch
):
    """Review finding: the truncation sweep re-takes service._lock, which
    CreateFilter/DropFilter hold at their commit points — every append
    must stay deadlock-free even when each one tries to sweep."""
    from tpubloom.server import service as service_mod

    monkeypatch.setattr(service_mod, "TRUNCATE_EVERY_APPENDS", 1)
    oplog = OpLog(str(tmp_path / "log"), segment_bytes=128)
    service = BloomService(
        sink_factory=lambda config: ckpt.FileSink(str(tmp_path / "ckpt")),
        oplog=oplog,
    )
    done = threading.Event()

    def drive():
        for i in range(4):
            service.CreateFilter(
                {"name": f"f{i}", "capacity": 1000, "error_rate": 0.01}
            )
            service.InsertBatch({"name": f"f{i}", "keys": [b"k%d" % i]})
        for i in range(4):
            service.DropFilter({"name": f"f{i}"})
        done.set()

    t = threading.Thread(target=drive, daemon=True)
    t.start()
    t.join(timeout=60)
    assert done.is_set(), "create/drop deadlocked against the log sweep"
    service.shutdown()
    oplog.close()


def test_manifest_restores_filter_whose_create_was_truncated(tmp_path):
    """Review finding: truncation can drop a filter's CreateFilter record
    while its post-checkpoint records remain — replay must still bring
    the filter back (creation manifest) or acked writes are lost."""
    oplog = OpLog(str(tmp_path / "log"), segment_bytes=256)
    srv, service, port = _server(tmp_path, oplog=oplog)
    client = BloomClient(f"127.0.0.1:{port}")
    client.wait_ready()
    client.create_filter("m", capacity=10_000, error_rate=0.01)  # seq 1
    base = [b"base-%06d" % i for i in range(20)]
    for k in base:
        client.insert_batch("m", [k])
    client.checkpoint("m", wait=True)  # covers everything so far
    service._maybe_truncate_log()
    assert oplog.first_seq > 1, "create record should be truncated away"
    client.insert_batch("m", [b"tail-after-ckpt"])  # NOT checkpointed
    client.close()
    srv.stop(grace=None)
    oplog.close()

    oplog2 = OpLog(str(tmp_path / "log"), segment_bytes=256)
    service2 = BloomService(
        sink_factory=lambda config: ckpt.FileSink(str(tmp_path / "ckpt")),
        oplog=oplog2,
    )
    stats = service2.replay_oplog()
    assert stats["restored_from_manifest"] == 1, stats
    assert "m" in service2._filters, "manifest did not re-create the filter"
    hits = service2.QueryBatch({"name": "m", "keys": base + [b"tail-after-ckpt"]})
    got = np.unpackbits(np.frombuffer(hits["hits"], np.uint8), count=hits["n"])
    assert got.all(), "acked writes lost across truncation + restart"
    service2.shutdown()
    oplog2.close()


def test_replica_fresh_create_does_not_resurrect_local_checkpoint(tmp_path):
    """Review finding: a replica applying a FRESH CreateFilter record
    must not restore its own stale local checkpoint of a previous
    same-name filter (restore-on-create defaults True)."""
    oplog = OpLog(str(tmp_path / "log"))
    psvc = BloomService(oplog=oplog)  # primary WITHOUT sinks: creates stay fresh
    psrv, pport = build_server(psvc, "127.0.0.1:0")
    psrv.start()
    pc = BloomClient(f"127.0.0.1:{pport}")
    pc.wait_ready()
    keys = [b"old-%012d" % i for i in range(32)]
    pc.create_filter("a", capacity=10_000, error_rate=0.01,
                     checkpoint_every=16)
    pc.insert_batch("a", keys)

    rsink = str(tmp_path / "replica-ckpt")
    rsvc = BloomService(
        sink_factory=lambda config: ckpt.FileSink(rsink), read_only=True
    )
    rsrv, rport = build_server(rsvc, "127.0.0.1:0")
    rsrv.start()
    applier = ReplicaApplier(
        rsvc, f"127.0.0.1:{pport}", reconnect_base=0.05
    ).start()
    rc = BloomClient(f"127.0.0.1:{rport}")
    try:
        assert applier.wait_for_seq(oplog.last_seq, 30), applier.status()
        assert rc.include_batch("a", keys).all()
        # the replica checkpointed the old contents into ITS OWN sink
        pc.drop_filter("a")   # replica drop -> final local checkpoint too
        pc.create_filter("a", capacity=10_000, error_rate=0.01,
                         checkpoint_every=16)  # fresh on the primary
        assert applier.wait_for_seq(oplog.last_seq, 30), applier.status()
        assert ckpt.FileSink(rsink).list_seqs("a"), (
            "test setup: replica never checkpointed locally"
        )
        assert not rc.include_batch("a", keys).any(), (
            "replica resurrected dropped keys from its local checkpoint"
        )
    finally:
        applier.stop()
        rc.close()
        pc.close()
        rsrv.stop(grace=None)
        psrv.stop(grace=None)
        oplog.close()


def test_append_failure_failstops_writes_and_degrades_health(tmp_path):
    """Review finding: an op applied in memory whose log append fails
    leaves the primary ahead of its own log — further writes must be
    fail-stopped (Redis MISCONF parity) and Health must say why; reads
    keep serving."""
    oplog = OpLog(str(tmp_path / "log"))
    srv, service, port = _server(tmp_path, oplog=oplog)
    client = BloomClient(f"127.0.0.1:{port}", max_retries=0)
    client.wait_ready()
    try:
        client.create_filter("fs", capacity=1000, error_rate=0.01)
        client.insert_batch("fs", [b"before"])
        faults.arm("repl.append", "once")
        with pytest.raises(BloomServiceError, match="INTERNAL"):
            client.insert_batch("fs", [b"lost"])
        # writes are now fail-stopped with a structured error...
        with pytest.raises(BloomServiceError, match="LOG_WRITE_FAILED"):
            client.insert_batch("fs", [b"after"])
        h = client.health()
        assert h["status"] == "DEGRADED"
        assert "oplog_append_error" in h["reasons"]
        # ...but reads keep serving
        assert client.include("fs", b"before")
    finally:
        client.close()
        srv.stop(grace=None)
        oplog.close()


def test_replayed_insert_checkpoint_carries_record_seq(tmp_path):
    """Review finding (replay-path mirror of the notify ordering fix): a
    checkpoint triggered DURING replay by the replayed batch itself must
    stamp that record's seq."""
    oplog = OpLog(str(tmp_path / "log"))
    sink_dir = str(tmp_path / "ckpt")
    service = BloomService(
        sink_factory=lambda config: ckpt.FileSink(sink_dir), oplog=oplog
    )
    service.CreateFilter(
        {"name": "r", "capacity": 10_000, "error_rate": 0.01,
         "options": {"counting": True, "checkpoint_every": 64}}
    )
    keys = [b"rp%014d" % i for i in range(64)]
    service.InsertBatch({"name": "r", "keys": keys})  # seq 2
    # crash WITHOUT the checkpoint landing: nuke the sink
    service._filters["r"].checkpointer.close(final_checkpoint=False)
    for fn in os.listdir(sink_dir):
        os.unlink(os.path.join(sink_dir, fn))
    oplog.close()

    oplog2 = OpLog(str(tmp_path / "log"))
    service2 = BloomService(
        sink_factory=lambda config: ckpt.FileSink(sink_dir), oplog=oplog2
    )
    service2.replay_oplog()  # the replayed insert re-triggers a checkpoint
    mf = service2._filters["r"]
    assert mf.checkpointer.flush()
    assert mf.checkpointer.last_landed_meta["repl_seq"] == 2

    # third generation: crash again and replay over THAT checkpoint —
    # counts must stay exactly 1
    service2.shutdown()
    oplog2.close()
    oplog3 = OpLog(str(tmp_path / "log"))
    service3 = BloomService(
        sink_factory=lambda config: ckpt.FileSink(sink_dir), oplog=oplog3
    )
    stats = service3.replay_oplog()
    assert stats["failed"] == 0
    service3.DeleteBatch({"name": "r", "keys": keys})
    hits = service3.QueryBatch({"name": "r", "keys": keys})
    assert not np.unpackbits(
        np.frombuffer(hits["hits"], np.uint8), count=hits["n"]
    ).any(), "replay-triggered checkpoint stamped a stale repl_seq"
    service3.shutdown()
    oplog3.close()


def test_full_resync_tail_includes_creates_after_plan(tmp_path):
    """Review finding (reproduced): the resync tail cursor must be
    clamped to the log head at plan time — a CreateFilter committed
    between the plan freeze and the snapshot stamps is not in the
    announced filter list, so skipping its record would silently lose
    the filter on the replica forever."""
    from tpubloom.repl.primary import repl_stream

    class _LiveCtx:
        def is_active(self):
            return True

        def peer(self):
            return "test"

    oplog = OpLog(str(tmp_path / "log"))
    service = BloomService(oplog=oplog)
    service.CreateFilter({"name": "f1", "capacity": 1000,
                          "error_rate": 0.01})                    # seq 1
    service.InsertBatch({"name": "f1", "keys": [b"a"]})           # seq 2
    gen = repl_stream(service, {}, _LiveCtx(), heartbeat_s=0.05)
    begin = next(gen)  # plan frozen here, before these commits:
    assert begin["kind"] == "full_sync_begin" and begin["filters"] == ["f1"]
    service.CreateFilter({"name": "f2", "capacity": 1000,
                          "error_rate": 0.01})                    # seq 3
    service.InsertBatch({"name": "f1", "keys": [b"b"]})           # seq 4
    msg = next(gen)
    while msg["kind"] != "full_sync_end":
        msg = next(gen)
    assert msg["cursor"] <= 2, (
        f"tail cursor {msg['cursor']} skips the concurrent create (seq 3)"
    )
    recs = []
    while len(recs) < 2:
        msg = next(gen)
        if msg["kind"] == "record":
            recs.append(msg)
    assert [r["seq"] for r in recs] == [3, 4]
    assert recs[0]["method"] == "CreateFilter"
    assert recs[0]["req"]["name"] == "f2"
    gen.close()
    oplog.close()


# -- replica end-to-end (the acceptance scenario) ----------------------------


def test_replica_end_to_end_with_mid_stream_kill(tmp_path):
    """Acceptance: primary + K keys -> replica syncs to lag 0 and answers
    QueryBatch identically; killing the stream mid-batch and reconnecting
    double-applies nothing (counting counts unchanged on replay)."""
    oplog = OpLog(str(tmp_path / "log"))
    psrv, psvc, pport = _server(tmp_path, oplog=oplog)
    pc = BloomClient(f"127.0.0.1:{pport}")
    pc.wait_ready()
    rng = np.random.default_rng(3)
    keys = _rand_keys(500, rng)
    pc.create_filter("cnt", capacity=20_000, error_rate=0.01, counting=True)
    pc.insert_batch("cnt", keys)  # every count exactly 1

    rsvc = BloomService(read_only=True)
    rsrv, rport = build_server(rsvc, "127.0.0.1:0")
    rsrv.start()
    applier = ReplicaApplier(
        rsvc, f"127.0.0.1:{pport}", reconnect_base=0.05
    ).start()
    rc = BloomClient(f"127.0.0.1:{rport}")
    try:
        assert applier.wait_caught_up(30), applier.status()
        assert obs_counters.get_gauge("repl_lag_seq") == 0
        assert applier.full_syncs == 1

        # identical membership, replica-side
        assert rc.include_batch("cnt", keys).all()
        absent = _rand_keys(500, rng)
        np.testing.assert_array_equal(
            rc.include_batch("cnt", absent), pc.include_batch("cnt", absent)
        )
        assert rc.health()["role"] == "replica"

        # kill the stream mid-batch; the reconnect must not double-apply
        faults.arm("repl.stream_send", "once")
        pc.insert_batch("cnt", _rand_keys(100, rng))
        deadline = time.monotonic() + 30
        while applier.partial_syncs == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert applier.partial_syncs >= 1, applier.status()
        assert applier.wait_caught_up(30), applier.status()

        # exactly-once proof: counts are still 1, so ONE delete empties
        pc.delete_batch("cnt", keys)
        assert applier.wait_for_seq(oplog.last_seq, 30), applier.status()
        assert not rc.include_batch("cnt", keys).any(), (
            "replayed records double-applied on the replica"
        )
    finally:
        applier.stop()
        rc.close()
        pc.close()
        rsrv.stop(grace=None)
        psrv.stop(grace=None)
        oplog.close()


def test_replica_survives_injected_apply_fault_exactly_once(tmp_path):
    """ISSUE 13 (chaos-coverage): ``repl.apply`` armed on the replica —
    a record's apply handler dies mid-stream, the applier reconnects
    with its cursor, and the seq-gated re-delivery applies the record
    EXACTLY once (counting counts stay 1)."""
    oplog = OpLog(str(tmp_path / "log"))
    psrv, psvc, pport = _server(tmp_path, oplog=oplog)
    pc = BloomClient(f"127.0.0.1:{pport}")
    pc.wait_ready()
    rng = np.random.default_rng(7)
    keys = _rand_keys(300, rng)
    pc.create_filter("cnt", capacity=20_000, error_rate=0.01, counting=True)
    pc.insert_batch("cnt", keys)

    rsvc = BloomService(read_only=True)
    rsrv, rport = build_server(rsvc, "127.0.0.1:0")
    rsrv.start()
    applier = ReplicaApplier(
        rsvc, f"127.0.0.1:{pport}", reconnect_base=0.05
    ).start()
    rc = BloomClient(f"127.0.0.1:{rport}")
    try:
        assert applier.wait_caught_up(30), applier.status()

        # poison the NEXT apply: the stream dies inside the handler,
        # the reconnect re-delivers from the cursor
        before = obs_counters.get("fault_repl_apply")
        faults.arm("repl.apply", "once")
        extra = _rand_keys(100, rng)
        pc.insert_batch("cnt", extra)
        assert applier.wait_for_seq(oplog.last_seq, 30), applier.status()
        assert obs_counters.get("fault_repl_apply") == before + 1
        assert rc.include_batch("cnt", extra).all()

        # exactly-once proof: every count is 1, ONE delete round empties
        pc.delete_batch("cnt", keys + extra)
        assert applier.wait_for_seq(oplog.last_seq, 30), applier.status()
        assert not rc.include_batch("cnt", keys + extra).any(), (
            "re-delivered record double-applied past the injected fault"
        )
    finally:
        applier.stop()
        rc.close()
        pc.close()
        rsrv.stop(grace=None)
        psrv.stop(grace=None)
        oplog.close()


def test_replica_full_resync_on_restored_create(tmp_path):
    """A CreateFilter that bootstrapped from a checkpoint the replica
    does not have forces a full resync (the record alone cannot carry
    those bytes)."""
    oplog = OpLog(str(tmp_path / "log"))
    psrv, psvc, pport = _server(tmp_path, oplog=oplog)
    pc = BloomClient(f"127.0.0.1:{pport}")
    pc.wait_ready()
    keys = [b"r%015d" % i for i in range(128)]
    pc.create_filter("warm", capacity=10_000, error_rate=0.01)
    pc.insert_batch("warm", keys)

    rsvc = BloomService(read_only=True)
    rsrv, rport = build_server(rsvc, "127.0.0.1:0")
    rsrv.start()
    applier = ReplicaApplier(
        rsvc, f"127.0.0.1:{pport}", reconnect_base=0.05
    ).start()
    rc = BloomClient(f"127.0.0.1:{rport}")
    try:
        assert applier.wait_caught_up(30)
        pc.drop_filter("warm")  # final checkpoint lands in the sink
        # recreate: restores from checkpoint -> record is resync-marked
        pc.create_filter("warm", capacity=10_000, error_rate=0.01)
        deadline = time.monotonic() + 30
        while applier.full_syncs < 2 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert applier.full_syncs >= 2, applier.status()
        assert applier.wait_for_seq(oplog.last_seq, 30), applier.status()
        assert rc.include_batch("warm", keys).all()
    finally:
        applier.stop()
        rc.close()
        pc.close()
        rsrv.stop(grace=None)
        psrv.stop(grace=None)
        oplog.close()


def test_readonly_rejection_and_redirect(tmp_path):
    # bare replica (no known primary): structured READONLY surfaces
    rsvc = BloomService(read_only=True)
    rsrv, rport = build_server(rsvc, "127.0.0.1:0")
    rsrv.start()
    rc = BloomClient(f"127.0.0.1:{rport}")
    try:
        rc.wait_ready()
        with pytest.raises(BloomServiceError, match="READONLY"):
            rc.insert_batch("any", [b"x"])
        with pytest.raises(BloomServiceError, match="READONLY"):
            rc.create_filter("any", capacity=100, error_rate=0.1)
    finally:
        rc.close()
        rsrv.stop(grace=None)

    # replica that knows its primary: the client follows the redirect
    oplog = OpLog(str(tmp_path / "log"))
    psrv, psvc, pport = _server(tmp_path, oplog=oplog)
    rsvc2 = BloomService(read_only=True)
    rsrv2, rport2 = build_server(rsvc2, "127.0.0.1:0")
    rsrv2.start()
    applier = ReplicaApplier(rsvc2, f"127.0.0.1:{pport}").start()
    # the client was (mis)pointed at the replica — writes still land
    c = BloomClient(f"127.0.0.1:{rport2}")
    try:
        c.wait_ready()
        c.create_filter("redir", capacity=1000, error_rate=0.01)
        c.insert_batch("redir", [b"via-redirect"])
        assert c.address == f"127.0.0.1:{pport}"  # followed the redirect
        assert obs_counters.get("client_primary_redirects") >= 1
        assert applier.wait_for_seq(oplog.last_seq, 30), applier.status()
        # ...and the replica serves the write back
        rdirect = BloomClient(f"127.0.0.1:{rport2}")
        assert rdirect.include("redir", b"via-redirect")
        rdirect.close()
    finally:
        applier.stop()
        c.close()
        psrv.stop(grace=None)
        rsrv2.stop(grace=None)
        oplog.close()


def test_client_read_preference_routes_to_replica(tmp_path):
    oplog = OpLog(str(tmp_path / "log"))
    psrv, psvc, pport = _server(tmp_path, oplog=oplog)
    rsvc = BloomService(read_only=True)
    rsrv, rport = build_server(rsvc, "127.0.0.1:0")
    rsrv.start()
    applier = ReplicaApplier(rsvc, f"127.0.0.1:{pport}").start()
    client = BloomClient(
        f"127.0.0.1:{pport}",
        replicas=[f"127.0.0.1:{rport}"],
        read_preference="replica",
    )
    try:
        client.wait_ready()
        keys = [b"rp%014d" % i for i in range(64)]
        client.create_filter("route", capacity=10_000, error_rate=0.01)
        client.insert_batch("route", keys)
        assert applier.wait_for_seq(oplog.last_seq, 30), applier.status()
        assert client.include_batch("route", keys).all()
        # the replica served the read, the primary served the write
        assert rsvc.metrics.snapshot()["counters"]["keys_queried"] >= 64
        assert psvc.metrics.snapshot()["counters"]["keys_inserted"] == 64
        # replica down -> reads fall back to the primary, not to errors
        applier.stop()
        rsrv.stop(grace=None)
        assert client.include_batch("route", keys).all()
        assert obs_counters.get("client_replica_fallbacks") >= 1
    finally:
        client.close()
        psrv.stop(grace=None)
        oplog.close()


def test_replica_blackbox_arms_from_state_store(tmp_path):
    """ISSUE 18 satellite: a replica given any durable state dir arms
    the PR-16 black box there — post-mortems of killed replicas stop
    depending on the server entrypoint having plumbed a log dir."""
    from tpubloom.obs import blackbox as bb
    from tpubloom.repl.replica import ReplicaStateStore

    oplog = OpLog(str(tmp_path / "log"))
    psrv, psvc, pport = _server(tmp_path, oplog=oplog, trace_sample=1.0)
    rsvc = BloomService(read_only=True)
    rsrv, rport = build_server(rsvc, "127.0.0.1:0")
    rsrv.start()
    rsvc.listen_address = f"127.0.0.1:{rport}"
    state_dir = str(tmp_path / "replica-state")
    applier = ReplicaApplier(
        rsvc,
        f"127.0.0.1:{pport}",
        reconnect_base=0.05,
        state_store=ReplicaStateStore(state_dir),
        listen_address=rsvc.listen_address,
    ).start()
    pc = BloomClient(f"127.0.0.1:{pport}", trace_sample=1.0)
    try:
        assert bb.enabled(), "a state store alone must arm the black box"
        pc.wait_ready()
        pc.create_filter("bbx", capacity=10_000, error_rate=0.01)
        # forced traces spill repl.apply spans into the replica's ring
        pc.insert_batch("bbx", [b"bb-%03d" % i for i in range(64)])
        assert applier.wait_for_seq(oplog.last_seq, 60), applier.status()
    finally:
        applier.stop()
        pc.close()
        rsrv.stop(grace=None)
        psrv.stop(grace=None)
        oplog.close()
    # the ring is readable post-mortem (no live process needed) and
    # identifies WHO wrote it — role, announced address, upstream
    node = bb.read_node(state_dir)
    assert node is not None, "replica state dir must hold a black box"
    assert node["meta"].get("role") == "replica"
    assert node["meta"].get("addr") == f"127.0.0.1:{rport}"
    assert node["meta"].get("primary") == f"127.0.0.1:{pport}"
    applies = [s for s in node["spans"] if s.get("name") == "repl.apply"]
    assert applies, "forced applies must spill into the replica's ring"
    assert any(
        s.get("attrs", {}).get("filter") == "bbx" for s in applies
    )


# -- MONITOR parity ----------------------------------------------------------


def test_monitor_stream_filters_by_name(tmp_path):
    srv, service, port = _server(tmp_path)
    client = BloomClient(f"127.0.0.1:{port}")
    other = BloomClient(f"127.0.0.1:{port}")
    try:
        client.wait_ready()
        client.create_filter("a", capacity=1000, error_rate=0.01)
        client.create_filter("b", capacity=1000, error_rate=0.01)
        mon = other.monitor("a")
        it = iter(mon)
        assert next(it)["kind"] == "hello"
        client.insert_batch("b", [b"not-mine"])
        client.insert_batch("a", [b"mine"])
        client.include_batch("a", [b"mine"])
        seen = []
        for msg in it:
            if msg["kind"] == "op":
                seen.append(msg)
                if len(seen) == 2:
                    break
        mon.cancel()
        assert [m["method"] for m in seen] == ["InsertBatch", "QueryBatch"]
        assert all(m["name"] == "a" for m in seen)
        assert seen[0]["rid"] and seen[0]["batch"] == 1
    finally:
        other.close()
        client.close()
        srv.stop(grace=None)


# -- satellites --------------------------------------------------------------


@pytest.fixture()
def fake_redis():
    r = FakeRedis()
    yield r
    r.close()


def test_redis_sink_multi_generation_walk(fake_redis):
    """RedisSink keeps N generations + list_seqs: the corrupt-newest
    restore walk works there like on a FileSink (PR-2 follow-up)."""
    from tpubloom.filter import BloomFilter

    cfg = FilterConfig(m=1 << 16, k=4, key_name="rsink")
    sink = ckpt.RedisSink("127.0.0.1", fake_redis.port)
    f = BloomFilter(cfg)
    keys_a = [b"a%015d" % i for i in range(100)]
    f.insert_batch(keys_a)
    seq_a = ckpt.save(f, sink)
    f.insert_batch([b"b%015d" % i for i in range(50)])
    seq_b = ckpt.save(f, sink, seq=seq_a + 1)
    assert sink.list_seqs("rsink") == [seq_b, seq_a]

    # corrupt the newest generation in place
    gen_key = f"rsink:tpubloom.ckpt:{seq_b:012d}".encode()
    blob = bytearray(fake_redis.data[gen_key])
    blob[-4] ^= 0xFF
    fake_redis.data[gen_key] = blob

    before = obs_counters.get("ckpt_corrupt_detected")
    restored = ckpt.restore(cfg, sink)
    assert restored is not None
    assert restored._restored_seq == seq_a  # fell back a generation
    assert np.asarray(restored.include_batch(keys_a)).all()
    assert obs_counters.get("ckpt_corrupt_detected") == before + 1
    # the corpse was quarantined out of the index, preserved for autopsy
    assert sink.list_seqs("rsink") == [seq_a]
    assert f"rsink:tpubloom.ckpt.corrupt:{seq_b:012d}".encode() in fake_redis.data

    # retention GC parity
    for i in range(6):
        ckpt.save(f, sink, seq=seq_b + 1 + i)
    assert sink.prune("rsink", keep=2) > 0
    assert len(sink.list_seqs("rsink")) == 2
    sink.close()


def test_redis_sink_legacy_single_blob_still_restores(fake_redis):
    """Sinks written before the index existed restore through the legacy
    key fallback."""
    from tpubloom.filter import BloomFilter

    cfg = FilterConfig(m=1 << 16, k=4, key_name="legacy")
    sink = ckpt.RedisSink("127.0.0.1", fake_redis.port)
    f = BloomFilter(cfg)
    f.insert_batch([b"x%015d" % i for i in range(64)])
    seq = ckpt.save(f, sink)
    # simulate the pre-ISSUE-3 layout: only bitmap + legacy blob keys
    fake_redis.data.pop(b"legacy:tpubloom.ckpt.seqs")
    fake_redis.data.pop(f"legacy:tpubloom.ckpt:{seq:012d}".encode())
    assert sink.list_seqs("legacy") == [seq]
    restored = ckpt.restore(cfg, sink)
    assert restored is not None and restored._restored_seq == seq
    sink.close()


def test_adaptive_retry_after_grows_under_load():
    """The ISSUE-3 satellite contract: the hint starts at the base and
    grows while sheds keep arriving (pressure), then decays back."""
    service = BloomService(max_in_flight=1, retry_after_ms=20)
    assert service.admit("QueryBatch") is None  # occupy the only slot
    hints = []
    for _ in range(8):
        shed = service.admit("QueryBatch")
        assert shed is not None
        hints.append(shed["error"]["details"]["retry_after_ms"])
    assert hints[0] == 20  # first shed of a burst: the configured base
    assert hints[-1] > hints[0]  # grows under sustained load
    assert hints == sorted(hints)  # monotone while hammering
    assert hints[-1] <= 20 * 32  # capped
    # decay: after a quiet second the hint returns toward the base
    time.sleep(1.2)
    shed = service.admit("QueryBatch")
    assert shed["error"]["details"]["retry_after_ms"] < hints[-1]
    service.release("QueryBatch")


def test_counting_insert_dedup_replay_answers_from_cache():
    """rid-replayed counting InsertBatch must not double-increment
    (shared machinery with DeleteBatch; also what makes it retryable on
    UNAVAILABLE)."""
    service = BloomService()
    service.CreateFilter(
        {"name": "cnt", "capacity": 10_000, "error_rate": 0.01,
         "options": {"counting": True}}
    )
    keys = [b"i%015d" % i for i in range(16)]
    req = {"name": "cnt", "keys": keys, "rid": "rid-ins-1"}
    r1 = service.InsertBatch(req)
    r2 = service.InsertBatch(req)  # replay of the same logical call
    assert r1 == r2
    assert service.metrics.snapshot()["counters"]["insert_dedup_hits"] == 1
    # counts stayed at 1: one delete -> absent (a double increment would
    # leave them present)
    service.DeleteBatch({"name": "cnt", "keys": keys, "rid": "rid-del-1"})
    hits = service.QueryBatch({"name": "cnt", "keys": keys})
    assert not np.unpackbits(
        np.frombuffer(hits["hits"], np.uint8), count=hits["n"]
    ).any()


def test_presence_insert_dedup_replays_cached_bits(tmp_path):
    service = BloomService()
    service.CreateFilter(
        {"name": "p", "config": {"m": 1 << 18, "k": 4, "block_bits": 512}}
    )
    keys = [b"p%015d" % i for i in range(32)]
    req = {"name": "p", "keys": keys, "return_presence": True,
           "rid": "rid-pres-1"}
    r1 = service.InsertBatch(req)
    assert not np.unpackbits(
        np.frombuffer(r1["presence"], np.uint8), count=32
    ).any()
    r2 = service.InsertBatch(req)  # replay: cached bits, NOT all-present
    assert r1 == r2


def test_plain_insert_not_cached():
    """Idempotent inserts skip the cache — replaying them is harmless
    and cache slots are better spent on the non-idempotent ops."""
    service = BloomService(dedup_capacity=8)
    service.CreateFilter({"name": "f", "capacity": 1000, "error_rate": 0.01})
    req = {"name": "f", "keys": [b"x"], "rid": "rid-plain"}
    service.InsertBatch(req)
    assert "rid-plain" not in service._dedup


def test_inspect_quarantine_cli(tmp_path, capsys):
    from tpubloom.filter import BloomFilter
    from tpubloom.server.service import main as server_main

    d = str(tmp_path / "ckpt")
    sink = ckpt.FileSink(d)
    cfg = FilterConfig(m=1 << 16, k=4, key_name="q")
    f = BloomFilter(cfg)
    f.insert_batch([b"k%015d" % i for i in range(32)])
    seq_a = ckpt.save(f, sink)
    seq_b = ckpt.save(f, sink, seq=seq_a + 1)
    path = sink._path("q", seq_b)
    blob = bytearray(open(path, "rb").read())
    blob[-3] ^= 0xFF
    open(path, "wb").write(bytes(blob))
    assert ckpt.restore(cfg, sink) is not None  # quarantines seq_b

    report = ckpt.inspect_quarantine(d)
    assert len(report["entries"]) == 1
    entry = report["entries"][0]
    assert "CRC32C mismatch" in entry["diagnosis"]
    assert entry["header"]["seq"] == seq_b  # header survived for autopsy

    # the CLI path: list, then purge
    with pytest.raises(SystemExit) as e:
        server_main(["inspect-quarantine", d])
    assert e.value.code == 0
    assert "CRC32C mismatch" in capsys.readouterr().out
    with pytest.raises(SystemExit) as e:
        server_main(["inspect-quarantine", d, "--purge", "--json"])
    assert e.value.code == 0
    assert '"purged": 1' in capsys.readouterr().out
    assert ckpt.inspect_quarantine(d)["entries"] == []


def test_quarantine_size_cap_evicts_oldest(tmp_path):
    from tpubloom.filter import BloomFilter

    d = str(tmp_path / "ckpt")
    cfg = FilterConfig(m=1 << 16, k=4, key_name="cap")
    f = BloomFilter(cfg)
    f.insert_batch([b"c%015d" % i for i in range(16)])
    _, _, blob = ckpt.snapshot_blob(f)
    torn = blob[: len(blob) // 2]
    # cap fits two torn blobs but not three: the third quarantine must
    # evict the oldest corpse
    sink = ckpt.FileSink(d, quarantine_max_bytes=2 * len(torn) + 16)
    for i, seq in enumerate([100, 200, 300]):
        sink.put("cap", seq, torn)
        os.utime(sink._path("cap", seq), (1000 + i, 1000 + i))
        assert sink.quarantine("cap", seq) is not None
    qdir = os.path.join(d, ckpt.FileSink.CORRUPT_SUBDIR)
    left = sorted(os.listdir(qdir))
    assert len(left) == 2  # oldest evicted
    assert f"cap.{100:012d}.ckpt" not in left
    assert obs_counters.get("ckpt_quarantine_evicted") >= 1


def test_repl_smoke():
    """benchmarks/repl_smoke.py end-to-end check runs in tier-1 so the
    replication surface cannot silently rot."""
    import importlib
    import sys

    bench_dir = os.path.join(os.path.dirname(__file__), "..", "benchmarks")
    sys.path.insert(0, os.path.abspath(bench_dir))
    try:
        repl_smoke = importlib.import_module("repl_smoke")
        result = repl_smoke.run_smoke()
    finally:
        sys.path.pop(0)
    assert result["replica_caught_up"]
    assert result["double_applied"] == 0
    assert result["monitor_events"] >= 1
