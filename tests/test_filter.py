"""BloomFilter end-to-end tests vs the CPU oracle (SURVEY.md §4.2;
BASELINE config 1: 1M random 16-byte keys, m=10M bits, k=7 — scaled down
for CI speed, the full config runs in benchmarks/)."""

import numpy as np
import pytest

pytest.importorskip("hypothesis")  # property tests need it; skip cleanly without
from hypothesis import given, settings
from hypothesis import strategies as st

from tpubloom import BloomFilter, CPUBloomFilter, FilterConfig
from tpubloom.params import theoretical_fpr


def _rand_keys(n, rng, nbytes=16):
    return [rng.bytes(nbytes) for _ in range(n)]


@pytest.fixture(scope="module")
def config1():
    # BASELINE config 1 shape: m=10M (non-pow2 -> 32-bit path), k=7.
    return FilterConfig(m=10_000_000, k=7, key_len=16)


def test_roundtrip_no_false_negatives(config1):
    rng = np.random.default_rng(0)
    keys = _rand_keys(5000, rng)
    f = BloomFilter(config1)
    f.insert_batch(keys)
    assert f.include_batch(keys).all(), "bloom filters never have false negatives"


def test_absent_keys_mostly_absent(config1):
    rng = np.random.default_rng(1)
    f = BloomFilter(config1)
    f.insert_batch(_rand_keys(5000, rng))
    absent = _rand_keys(5000, rng)
    fpr = f.include_batch(absent).mean()
    assert fpr < 0.01  # 5k keys in 10M bits: theoretical FPR ~ 0


def test_membership_parity_vs_oracle(config1):
    """Bit-for-bit: device filter and CPU oracle answer identically, and the
    underlying bit arrays are identical (SURVEY.md §4.2 item 6)."""
    rng = np.random.default_rng(2)
    keys = _rand_keys(2000, rng) + [b"", b"a", b"tpubloom" * 2]
    keys += keys[:17]  # duplicates in the same batch
    f = BloomFilter(config1)
    o = CPUBloomFilter(config1)
    f.insert_batch(keys)
    o.insert_batch(keys)
    np.testing.assert_array_equal(np.asarray(f.words), o.words)
    probe = keys + _rand_keys(2000, rng)
    np.testing.assert_array_equal(f.include_batch(probe), o.include_batch(probe))


@pytest.mark.parametrize("m", [1 << 20, 1 << 21])
def test_statistical_fpr(m):
    """Observed FPR tracks (1-e^{-kn/m})^k within slack (SURVEY.md §4.2.4)."""
    k, n = 7, 100_000
    f = BloomFilter(FilterConfig(m=m, k=k, key_len=16))
    rng = np.random.default_rng(4)
    f.insert_batch(_rand_keys(n, rng))
    probes = _rand_keys(50_000, rng)
    observed = float(f.include_batch(probes).mean())
    expected = theoretical_fpr(m, k, n)
    assert observed < expected * 1.5 + 1e-4
    if expected > 1e-3:
        assert observed > expected * 0.5


def test_pow2_path_parity():
    cfg = FilterConfig(m=1 << 22, k=5, key_len=16)
    rng = np.random.default_rng(5)
    keys = _rand_keys(3000, rng)
    f, o = BloomFilter(cfg), CPUBloomFilter(cfg)
    f.insert_batch(keys)
    o.insert_batch(keys)
    np.testing.assert_array_equal(np.asarray(f.words), o.words)
    probe = _rand_keys(3000, rng) + keys[:100]
    np.testing.assert_array_equal(f.include_batch(probe), o.include_batch(probe))


def test_scalar_api_and_clear(config1):
    f = BloomFilter(config1)
    f.insert(b"hello")
    f.insert("héllo-str")
    assert f.include(b"hello") and f.include("héllo-str")
    assert b"hello" in f
    assert not f.include(b"absent-key")
    f.clear()
    assert not f.include(b"hello")
    assert f.n_inserted == 0


def test_variable_length_and_empty_keys(config1):
    f, o = BloomFilter(config1), CPUBloomFilter(config1)
    keys = [b"", b"a", b"ab", b"abc", b"abcd", b"abcde", b"0123456789abcdef"]
    f.insert_batch(keys)
    o.insert_batch(keys)
    np.testing.assert_array_equal(np.asarray(f.words), o.words)
    assert f.include_batch(keys).all()


@given(
    keys=st.lists(st.binary(min_size=0, max_size=16), min_size=1, max_size=100),
    probes=st.lists(st.binary(min_size=0, max_size=16), min_size=1, max_size=100),
)
@settings(max_examples=30, deadline=None)
def test_hypothesis_parity(keys, probes):
    cfg = FilterConfig(m=1 << 16, k=4, key_len=16)
    f, o = BloomFilter(cfg), CPUBloomFilter(cfg)
    f.insert_batch(keys)
    o.insert_batch(keys)
    np.testing.assert_array_equal(f.include_batch(probes), o.include_batch(probes))


def test_redis_bitmap_interop(config1):
    """A :jax-built filter exported as a Redis bitmap answers identically
    when re-imported by the CPU oracle, and vice versa."""
    rng = np.random.default_rng(6)
    keys = _rand_keys(1000, rng)
    f = BloomFilter(config1)
    f.insert_batch(keys)
    o = CPUBloomFilter.from_redis_bitmap(config1, f.to_redis_bitmap())
    assert o.include_batch(keys).all()
    np.testing.assert_array_equal(o.words, np.asarray(f.words))
    f2 = BloomFilter.from_redis_bitmap(config1, o.to_redis_bitmap())
    assert f2.include_batch(keys).all()


def test_fill_ratio_and_stats(config1):
    f = BloomFilter(config1)
    rng = np.random.default_rng(7)
    f.insert_batch(_rand_keys(10_000, rng))
    s = f.stats()
    expect_fill = 1 - np.exp(-7 * 10_000 / 10_000_000)
    assert abs(s["fill_ratio"] - expect_fill) / expect_fill < 0.05
    assert s["n_inserted"] == 10_000


def test_big_m_virtual_34bit():
    """m=2^34 (config 3 scale) positions exceed u32 — exercise the 64-bit
    path end to end on CPU with a sparse probe set (2 GiB array is fine on
    host RAM)."""
    cfg = FilterConfig(m=1 << 34, k=3, key_len=16)
    f = BloomFilter(cfg)
    keys = [b"key-%d" % i for i in range(100)]
    f.insert_batch(keys)
    assert f.include_batch(keys).all()
    assert not f.include_batch([b"absent-%d" % i for i in range(100)]).any()
