"""gRPC server/client integration tests (SURVEY.md §1 L4 boundary; the
batch API the BASELINE north star adds). Real grpc over localhost."""

import numpy as np
import pytest

from tpubloom import checkpoint as ckpt
from tpubloom.server.client import BloomClient
from tpubloom.server.protocol import BloomServiceError
from tpubloom.server.service import BloomService, build_server


@pytest.fixture()
def server(tmp_path):
    service = BloomService(sink_factory=lambda config: ckpt.FileSink(str(tmp_path)))
    srv, port = build_server(service, "127.0.0.1:0")
    srv.start()
    client = BloomClient(f"127.0.0.1:{port}")
    client.wait_ready()
    yield client, service, tmp_path
    client.close()
    srv.stop(grace=None)


def _rand_keys(n, rng):
    return [rng.bytes(16) for _ in range(n)]


def test_health(server):
    client, _, _ = server
    h = client.health()
    assert h["ok"] and h["backend"] == "cpu" and len(h["devices"]) == 8


def test_end_to_end_roundtrip(server):
    client, _, _ = server
    client.create_filter("urls", capacity=100_000, error_rate=0.01)
    assert client.list_filters() == ["urls"]
    rng = np.random.default_rng(0)
    keys = _rand_keys(5000, rng)
    assert client.insert_batch("urls", keys) == 5000
    assert client.include_batch("urls", keys).all()
    absent = _rand_keys(5000, rng)
    assert client.include_batch("urls", absent).mean() < 0.01
    st = client.stats("urls")
    assert st["n_inserted"] == 5000 and st["fill_ratio"] > 0
    client.clear("urls")
    assert not client.include_batch("urls", keys[:100]).any()


def test_insert_with_presence(server):
    client, _, _ = server
    client.create_filter(
        "dedup",
        config={"m": 1 << 22, "k": 7, "key_len": 16, "block_bits": 512},
    )
    rng = np.random.default_rng(1)
    keys = _rand_keys(2000, rng)
    p1 = client.insert_batch("dedup", keys, return_presence=True)
    assert p1.dtype == bool and p1.shape == (2000,)
    assert not p1.any()
    p2 = client.insert_batch("dedup", keys[:500] + _rand_keys(500, rng),
                             return_presence=True)
    assert p2[:500].all()
    assert p2[500:].sum() <= 2  # fresh keys: ~no false positives
    # plain (non-blocked) filters take the query-then-insert fallback
    client.create_filter("plain", capacity=10_000, error_rate=0.01)
    q1 = client.insert_batch("plain", keys[:100], return_presence=True)
    assert not q1.any()
    q2 = client.insert_batch("plain", keys[:100], return_presence=True)
    assert q2.all()


def test_scalar_and_str_keys(server):
    client, _, _ = server
    client.create_filter("mix", capacity=1000, error_rate=0.01)
    client.insert("mix", "héllo")
    assert client.include("mix", "héllo")
    assert not client.include("mix", "absent")


def test_counting_filter_via_server(server):
    client, _, _ = server
    client.create_filter(
        "cnt", config={"m": 1 << 16, "k": 4, "counting": True}
    )
    client.insert_batch("cnt", [b"a", b"b"])
    client.delete_batch("cnt", [b"b"])
    assert client.include("cnt", b"a") and not client.include("cnt", b"b")


def test_delete_on_plain_filter_rejected(server):
    client, _, _ = server
    client.create_filter("plain", capacity=1000, error_rate=0.01)
    with pytest.raises(BloomServiceError, match="UNSUPPORTED"):
        client.delete_batch("plain", [b"x"])


def test_errors(server):
    client, _, _ = server
    with pytest.raises(BloomServiceError, match="NOT_FOUND"):
        client.insert_batch("ghost", [b"x"])
    client.create_filter("dup", capacity=100, error_rate=0.1)
    with pytest.raises(BloomServiceError, match="ALREADY_EXISTS"):
        client.create_filter("dup", capacity=100, error_rate=0.1)
    assert client.create_filter("dup", capacity=100, error_rate=0.1, exist_ok=True)[
        "existed"
    ]


def test_exist_ok_config_mismatch_rejected(server):
    client, _, _ = server
    client.create_filter("cfgchk", config={"m": 1 << 16, "k": 4})
    with pytest.raises(BloomServiceError, match="CONFIG_MISMATCH"):
        client.create_filter("cfgchk", config={"m": 1 << 18, "k": 4}, exist_ok=True)
    resp = client.create_filter("cfgchk", config={"m": 1 << 16, "k": 4}, exist_ok=True)
    assert resp["existed"] and resp["config"]["m"] == 1 << 16


def test_checkpoint_restart_cycle(server):
    """Server restart restores the newest checkpoint (SURVEY.md §5 failure
    row: server restart -> restore newest checkpoint)."""
    client, service, tmp_path = server
    client.create_filter("persist", capacity=10_000, error_rate=0.01)
    rng = np.random.default_rng(1)
    keys = _rand_keys(1000, rng)
    client.insert_batch("persist", keys)
    seq = client.checkpoint("persist", wait=True)["seq"]
    assert seq > 0
    # simulate restart: drop from memory (final checkpoint), recreate
    client.drop_filter("persist")
    assert client.list_filters() == []
    resp = client.create_filter("persist", capacity=10_000, error_rate=0.01)
    assert resp["restored_seq"] is not None
    assert client.include_batch("persist", keys).all()


def test_sharded_filter_via_server(server):
    client, _, _ = server
    client.create_filter(
        "sharded", config={"m": 1 << 20, "k": 4, "shards": 8}
    )
    rng = np.random.default_rng(2)
    keys = _rand_keys(2000, rng)
    client.insert_batch("sharded", keys)
    assert client.include_batch("sharded", keys).all()
    st = client.stats("sharded")
    assert st["shards"] == 8


def test_server_metrics(server):
    client, _, _ = server
    client.create_filter("m1", capacity=1000, error_rate=0.01)
    client.insert_batch("m1", [b"k1", b"k2"])
    client.include_batch("m1", [b"k1"])
    snap = client.stats()
    assert snap["counters"]["keys_inserted"] == 2
    assert snap["counters"]["keys_queried"] == 1
    assert snap["latency"]["InsertBatch"]["n"] >= 1


def test_scalable_filter_via_server(server):
    """Scalable create/insert/grow/query/stats over the wire (VERDICT r1
    task 2: CreateFilter branch + server test)."""
    client, _, _ = server
    resp = client.create_filter(
        "scale", capacity=300, error_rate=0.01, scalable=True
    )
    assert resp["scalable"]["growth"] == 2
    rng = np.random.default_rng(11)
    keys = _rand_keys(1000, rng)
    client.insert_batch("scale", keys)  # crosses a growth boundary
    assert client.include_batch("scale", keys).all()
    st = client.stats("scale")
    assert st["n_layers"] >= 2 and st["n_inserted"] == 1000
    absent = _rand_keys(2000, rng)
    assert client.include_batch("scale", absent).mean() < 0.03


def test_scalable_checkpoint_restart_cycle(server):
    """Drop (final checkpoint) -> recreate restores the full layer stack."""
    client, _, _ = server
    client.create_filter("scale-p", capacity=300, error_rate=0.01, scalable=True)
    rng = np.random.default_rng(12)
    keys = _rand_keys(1000, rng)
    client.insert_batch("scale-p", keys)
    n_layers = client.stats("scale-p")["n_layers"]
    assert n_layers >= 2
    client.drop_filter("scale-p")
    resp = client.create_filter(
        "scale-p", capacity=300, error_rate=0.01, scalable=True
    )
    assert resp["restored_seq"] is not None
    assert client.stats("scale-p")["n_layers"] == n_layers
    assert client.include_batch("scale-p", keys).all()


def test_scalable_mismatches_rejected(server):
    client, _, _ = server
    client.create_filter("sc-m", capacity=300, error_rate=0.01, scalable=True)
    # scalable vs fixed-size mismatch on exist_ok attach
    with pytest.raises(BloomServiceError, match="CONFIG_MISMATCH"):
        client.create_filter("sc-m", capacity=300, error_rate=0.01, exist_ok=True)
    # policy mismatch on exist_ok attach
    with pytest.raises(BloomServiceError, match="CONFIG_MISMATCH"):
        client.create_filter(
            "sc-m", capacity=999, error_rate=0.01, scalable=True, exist_ok=True
        )
    # matching attach succeeds and echoes the policy
    resp = client.create_filter(
        "sc-m", capacity=300, error_rate=0.01, scalable=True, exist_ok=True
    )
    assert resp["existed"] and resp["scalable"]["capacity"] == 300
    # dropping leaves a scalable checkpoint; recreating as fixed-size must
    # be refused rather than silently misread
    client.drop_filter("sc-m")
    with pytest.raises(BloomServiceError, match="CKPT_MISMATCH"):
        client.create_filter("sc-m", capacity=300, error_rate=0.01)


def test_scalable_bare_attach_and_policy_drift(server):
    """A bare exist_ok attach (no capacity) adopts the existing scalable
    filter; a changed growth default is still caught (r2 review finding)."""
    client, _, _ = server
    client.create_filter("sc-b", capacity=300, error_rate=0.01, scalable=True)
    resp = client.create_filter("sc-b", scalable=True, exist_ok=True)
    assert resp["existed"] and resp["scalable"]["capacity"] == 300
    with pytest.raises(BloomServiceError, match="CONFIG_MISMATCH"):
        client.create_filter("sc-b", scalable=True, growth=4, exist_ok=True)


def test_sharded_counting_filter_via_server(server, tmp_path):
    """configs 4 x 5 over the L4 boundary: create a sharded counting
    filter, insert/delete/query, restart-restore as the SAME class (the
    server's CreateFilter routing and checkpoint.restore must agree)."""
    client, service, _ = server
    cfg = {
        "m": 1 << 16, "k": 4, "key_len": 16, "shards": 8, "counting": True,
        "block_bits": 512,
    }
    client.create_filter("shcnt", config=cfg)
    from tpubloom.parallel.sharded import ShardedBloomFilter

    assert isinstance(service._filters["shcnt"].filter, ShardedBloomFilter)
    rng = np.random.default_rng(5)
    keys = _rand_keys(500, rng)
    client.insert_batch("shcnt", keys)
    client.delete_batch("shcnt", keys[:200])
    assert client.include_batch("shcnt", keys[200:]).all()
    assert client.include_batch("shcnt", keys[:200]).mean() < 0.05
    client.checkpoint("shcnt")
    service2 = BloomService(
        sink_factory=lambda config: ckpt.FileSink(str(tmp_path))
    )
    srv2, port2 = build_server(service2, "127.0.0.1:0")
    srv2.start()
    try:
        c2 = BloomClient(f"127.0.0.1:{port2}")
        c2.wait_ready()
        c2.create_filter("shcnt", config=cfg)  # restore-on-create
        assert isinstance(
            service2._filters["shcnt"].filter, ShardedBloomFilter
        )
        assert c2.include_batch("shcnt", keys[200:]).all()
        c2.delete_batch("shcnt", keys[200:300])  # restored: delete works
        assert c2.include_batch("shcnt", keys[300:]).all()
        c2.close()
    finally:
        srv2.stop(grace=None)


def test_delete_on_sharded_plain_filter_rejected(server):
    client, _, _ = server
    client.create_filter(
        "shplain", config={"m": 1 << 16, "k": 4, "key_len": 16, "shards": 8}
    )
    with pytest.raises(BloomServiceError, match="UNSUPPORTED"):
        client.delete_batch("shplain", [b"x"])
