#!/usr/bin/env python
"""sweep3 prototype — HISTORICAL (round-3 evidence; its kernel graduated
into tpubloom/ops/sweep.py as the shipping fat sweep — do not use for
current numbers, see benchmarks/RESULTS_r4.md).

Fat-row (128-lane) partition sweep.

hbm_probe.py measured the decisive fact: Pallas DMA of this chip moves
[*, 16]-lane tiles at ~35 GB/s but [*, 128]-lane tiles at ~150-190 GB/s
(the (8, 128) DMA tiling wastes 8x on narrow tiles). The block array
[NB, 16] is the SAME row-major memory as [NB/8, 128], so the sweep can
run entirely on fat rows:

* keys sort by skey = (blk % 8) * NB8 + (blk >> 3): eight substreams,
  one per block-column j; substream j's updates land in lanes
  [16j, 16j+16) of the fat rows, so each substream's delta is produced
  independently and lane-concatenated — no sublane<->lane moves.
* placement one-hot is over FAT rows (R8 of them), so the cnt matmul
  shrinks ~8x per window vs the block-row design at equal coverage.
* presence (test-and-insert) via G = bits @ tilebits^T (one int8 matmul
  per window) + tiny VPU rowsums — no per-slot extraction matmuls.

Timing: long chains forced to host values (bur can lie on this stack).
"""

from __future__ import annotations

import functools
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpubloom.config import FilterConfig
from tpubloom.ops import blocked
from tpubloom.ops.sweep import (
    _ALIGN,
    _pack_positions,
    _unpack_positions,
    apply_blocked_updates,
)

LOG2M = 32
B = 1 << 22
KEY_LEN = 16
STEPS = 32

config = FilterConfig(m=1 << LOG2M, k=7, key_len=KEY_LEN, block_bits=512)
NB, W, K, BB = config.n_blocks, config.words_per_block, config.k, config.block_bits
NB8 = NB // 8
lengths = jnp.full((B,), KEY_LEN, jnp.int32)


def _u32(x):
    return jnp.asarray(x, jnp.uint32)


def _expand_bits(m, KMAX, W):
    """[KMAX, W] packed words -> [KMAX, W*32] 0/1 planes (b-major)."""
    colC = lax.broadcasted_iota(jnp.int32, (KMAX, W * 32), 1)
    rep = jnp.concatenate([m] * 32, axis=1)
    return (rep >> (colC // W).astype(jnp.uint32)) & _u32(1)


def _pack_512_to_16(present_bf16, W):
    """[R8, 512] 0/1 bf16 bit-planes -> [R8, W] u32 words (exact matmuls)."""
    ccol = lax.broadcasted_iota(jnp.int32, (W * 32, 4 * W), 0)
    hcol = lax.broadcasted_iota(jnp.int32, (W * 32, 4 * W), 1)
    b_of_c = ccol // W
    w_of_c = lax.rem(ccol, W)
    pack_w = jnp.where(
        (w_of_c + (b_of_c // 8) * W) == hcol,
        (1 << lax.rem(b_of_c, 8)).astype(jnp.float32),
        jnp.float32(0),
    ).astype(jnp.bfloat16)
    quarters = lax.dot_general(
        present_bf16, pack_w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(jnp.bfloat16)
    qcol = lax.broadcasted_iota(jnp.int32, (4 * W, W), 0)
    wcol = lax.broadcasted_iota(jnp.int32, (4 * W, W), 1)
    q_of = qcol // W
    w_of = lax.rem(qcol, W)
    comb_lo = jnp.where(
        (w_of == wcol) & (q_of < 2),
        jnp.where(q_of == 0, jnp.float32(1), jnp.float32(256)),
        jnp.float32(0),
    ).astype(jnp.bfloat16)
    comb_hi = jnp.where(
        (w_of == wcol) & (q_of >= 2),
        jnp.where(q_of == 2, jnp.float32(1), jnp.float32(256)),
        jnp.float32(0),
    ).astype(jnp.bfloat16)
    lo = lax.dot_general(
        quarters, comb_lo, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    hi = lax.dot_general(
        quarters, comb_hi, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return lo.astype(jnp.int32).astype(jnp.uint32) | (
        hi.astype(jnp.int32).astype(jnp.uint32) << _u32(16)
    )


def _kernel3(
    starts_ref,  # SMEM [8 * P8 + 1] i32
    upd_ref,  # ANY [Btot, 128]
    blocks_ref,  # VMEM [S * R8, 128] (fat rows)
    *rest,  # out_ref [, pres_ref], sup_ref, sems
    R8: int,
    S: int,
    KJ: int,
    KBJ: int,
    P8: int,
    W: int,
    PRES: bool,
):
    if PRES:
        out_ref, pres_ref, sup_ref, sems = rest
    else:
        out_ref, sup_ref, sems = rest
        pres_ref = None
    p = pl.program_id(0)
    num_p = pl.num_programs(0)

    def a_big(j, pp):
        return (starts_ref[j * P8 + pp * S] // _ALIGN) * _ALIGN

    def fetch(slot, pp):
        for j in range(8):
            pltpu.make_async_copy(
                upd_ref.at[pl.ds(a_big(j, pp), KBJ), :],
                sup_ref.at[slot, j],
                sems.at[slot, j],
            ).start()

    def wait(slot):
        for j in range(8):
            pltpu.make_async_copy(
                upd_ref.at[pl.ds(0, KBJ), :],
                sup_ref.at[slot, j],
                sems.at[slot, j],
            ).wait()

    slot = lax.rem(p, 2)

    @pl.when(p == 0)
    def _():
        fetch(0, 0)

    @pl.when(p + 1 < num_p)
    def _():
        fetch(1 - slot, p + 1)

    wait(slot)
    pres_acc = jnp.zeros((KJ, 128), jnp.uint32) if PRES else None
    for t in range(S):
        sl = pl.ds(t * R8, R8)
        tile = blocks_ref[sl, :]  # [R8, 128] pre-update fat rows
        base_rf = (p * S + t) * R8
        deltas = []
        for j in range(8):
            qi = j * P8 + p * S + t
            rel = (starts_ref[qi] // _ALIGN) * _ALIGN - a_big(j, p)
            rel = jnp.clip(rel, 0, KBJ - KJ)
            sub = sup_ref[slot, j, pl.ds(rel, KJ), :]
            skey0 = j * NB8 + base_rf
            rl = (sub[:, 0:1] - _u32(skey0)).astype(jnp.int32)
            colsR = lax.broadcasted_iota(jnp.int32, (KJ, R8), 1)
            oh_f32 = jnp.where(rl == colsR, jnp.float32(1), jnp.float32(0))
            oh8 = oh_f32.astype(jnp.int8)
            m = sub[:, 1 : W + 1]
            bits = _expand_bits(m, KJ, W)
            bits8 = bits.astype(jnp.int8)
            cnt = lax.dot_general(
                oh8, bits8, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )  # [R8, 512]
            present = jnp.where(cnt > 0, jnp.float32(1), jnp.float32(0)).astype(
                jnp.bfloat16
            )
            deltas.append(_pack_512_to_16(present, W))
            if PRES:
                # pre-update membership: G[s, r] = popcount(mask_s AND
                # oldrow_r) via one int8 matmul; slot s hits iff
                # G[s, rl(s)] == popcount(mask_s)
                tj = tile[:, j * W : (j + 1) * W]  # [R8, W]
                tilebits = _expand_bits(tj, R8, W).astype(jnp.int8)
                G = lax.dot_general(
                    bits8, tilebits, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.int32,
                )  # [KJ, R8]
                hit = jnp.sum(
                    G * oh_f32.astype(jnp.int32), axis=1, keepdims=True
                )
                npos = jnp.sum(bits.astype(jnp.int32), axis=1, keepdims=True)
                idxp1 = sub[:, W + 1 : W + 2]
                aq = a_big(j, p) + rel
                ipos = lax.broadcasted_iota(jnp.int32, (KJ, 1), 0) + aq
                real = (
                    (ipos >= starts_ref[qi])
                    & (ipos < starts_ref[qi + 1])
                    & (idxp1 > 0)
                )
                hbit = jnp.where(hit == npos, _u32(0x80000000), _u32(0))
                v = jnp.where(real, idxp1 | hbit, _u32(0))
                colp = lax.broadcasted_iota(jnp.int32, (KJ, 128), 1)
                pres_acc = pres_acc | jnp.where(colp == t * 8 + j, v, _u32(0))
        delta_fat = jnp.concatenate(deltas, axis=1)  # [R8, 128]
        out_ref[sl, :] = tile | delta_fat
    if PRES:
        pres_ref[:] = pres_acc


def sweep3_insert(blocks_fat, upd, starts, *, R8, S, KJ, KBJ, PRES=False):
    NB8_, L = blocks_fat.shape
    assert L == 128
    P8 = NB8_ // R8
    P = P8 // S
    out_shape = jax.ShapeDtypeStruct((NB8_, 128), jnp.uint32)
    out_spec = pl.BlockSpec((S * R8, 128), lambda p, *_: (p, 0))
    if PRES:
        out_shape = (
            out_shape,
            jax.ShapeDtypeStruct((P * KJ, 128), jnp.uint32),
        )
        out_spec = (out_spec, pl.BlockSpec((KJ, 128), lambda p, *_: (p, 0)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(P,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((S * R8, 128), lambda p, *_: (p, 0)),
        ],
        out_specs=out_spec,
        scratch_shapes=[
            pltpu.VMEM((2, 8, KBJ, 128), jnp.uint32),
            pltpu.SemaphoreType.DMA((2, 8)),
        ],
    )
    fn = pl.pallas_call(
        functools.partial(
            _kernel3, R8=R8, S=S, KJ=KJ, KBJ=KBJ, P8=P8, W=W, PRES=PRES
        ),
        out_shape=out_shape,
        grid_spec=grid_spec,
        input_output_aliases={2: 0},
    )
    return fn(starts, upd, blocks_fat)


def build_stream3(keys, R8, KBJ):
    """Sorted substream update stream: skey = (blk%8)*NB8 + blk>>3."""
    P8 = NB8 // R8
    blk, bit = blocked.block_positions(
        keys, lengths, n_blocks=NB, block_bits=BB, k=K, seed=config.seed,
        block_hash=config.block_hash,
    )
    blk = blk.astype(jnp.uint32)
    skey = (blk & _u32(7)) * _u32(NB8) + (blk >> _u32(3))
    cols, nbits, packed = _pack_positions(bit, BB, K)
    idx0 = jnp.arange(1, B + 1, dtype=jnp.uint32)
    sorted_cols = lax.sort((skey,) + cols + (idx0,), num_keys=1)
    ss = sorted_cols[0].astype(jnp.int32)
    bit_sorted = _unpack_positions(sorted_cols[1:-1], BB, K, nbits, packed)
    masks = blocked.build_masks(bit_sorted, W)
    bounds = (
        jnp.arange(8 * P8 + 1, dtype=jnp.int32)
        .reshape(-1)
    )
    # boundary q of substream j at skey j*NB8 + q*R8; flatten j-major
    jj = bounds // P8
    qq = bounds % P8
    tgt = jnp.where(bounds == 8 * P8, 8 * NB8, jj * NB8 + qq * R8)
    starts = jnp.searchsorted(ss, tgt.astype(jnp.int32)).astype(jnp.int32)
    pad = KBJ + _ALIGN
    upd = jnp.zeros((B + pad, 128), jnp.uint32)
    upd = upd.at[:, 0].set(
        jnp.concatenate(
            [ss.astype(jnp.uint32), jnp.full((pad,), 8 * NB8, jnp.uint32)]
        )
    )
    upd = upd.at[:B, 1 : W + 1].set(masks)
    upd = upd.at[:B, W + 1].set(sorted_cols[-1])
    return starts, upd


def check_windows3(starts, S, KJ, KBJ, P8):
    s = np.asarray(starts).astype(np.int64)
    a_big = np.empty(8 * P8, np.int64)
    for j in range(8):
        seg = (s[j * P8 : (j + 1) * P8 : S] // _ALIGN) * _ALIGN
        a_big[j * P8 : (j + 1) * P8] = np.repeat(seg, S)
    a = (s[:-1] // _ALIGN) * _ALIGN
    rel = np.clip(a - a_big, 0, KBJ - KJ)
    aa = a_big + rel
    span = s[1:] - aa  # rows window [aa, aa+KJ) must cover
    return int(span.max())


def unsort_presence(presb, starts, R8, S, KJ, KBJ, P8):
    """Device-side: pres tiles -> bool[B] in original key order."""
    s = starts.astype(jnp.int32)
    P = P8 // S
    # stream position of slot (global q, i): a(q) + i
    jq = jnp.arange(8 * P8, dtype=jnp.int32)
    j = jq // P8
    q = jq % P8
    p0 = q // S
    t = q % S
    big_idx = j * P8 + p0 * S
    a_big = (s[big_idx] // _ALIGN) * _ALIGN
    a = a_big + jnp.clip((s[jq] // _ALIGN) * _ALIGN - a_big, 0, KBJ - KJ)
    # v values: presb[p0*KJ + i, t*8 + j] -> row-gather from a
    # [P*128, KJ] transpose so each (j, q) window is one row
    presT = presb.reshape(P, KJ, 128).transpose(0, 2, 1).reshape(P * 128, KJ)
    v = presT[p0 * 128 + t * 8 + j]  # [8*P8, KJ]
    vkey = jnp.where(
        v == 0,
        _u32(0xFFFFFFFE),
        ((v & _u32(0x7FFFFFFF)) << _u32(1)) | (v >> _u32(31)),
    ).reshape(-1)
    (skey,) = lax.sort((vkey,), num_keys=1)
    return (skey[:B] & _u32(1)) == 1


def run_variant(name, starts, upd, *, R8, S, KJ, KBJ, PRES, ref_state=None):
    def step(state, upd, starts):
        out = sweep3_insert(
            state, upd, starts, R8=R8, S=S, KJ=KJ, KBJ=KBJ, PRES=PRES
        )
        if PRES:
            out, presb = out
            return out, jnp.sum(out[:: NB8 // 64], dtype=jnp.uint32) + jnp.sum(
                presb[:: max(1, presb.shape[0] // 64)], dtype=jnp.uint32
            )
        return out, jnp.sum(out[:: NB8 // 64], dtype=jnp.uint32)

    jit = jax.jit(step, donate_argnums=(0,))
    state = jnp.zeros((NB8, 128), jnp.uint32)
    t0 = time.perf_counter()
    state, carry = jit(state, upd, starts)
    _ = int(np.asarray(carry))
    compile_s = time.perf_counter() - t0
    ok = None
    if ref_state is not None:
        ok = bool(
            jnp.array_equal(state[:: NB8 // 4096], ref_state[:: NB8 // 4096])
        ) and bool(
            jnp.array_equal(state[1 :: NB8 // 1024], ref_state[1 :: NB8 // 1024])
        )
    t0 = time.perf_counter()
    for _ in range(STEPS):
        state, carry = jit(state, upd, starts)
    _ = int(np.asarray(carry))
    dt = (time.perf_counter() - t0) / STEPS
    P = NB8 // (R8 * S)
    print(
        json.dumps(
            {
                "variant": name, "R8": R8, "S": S, "KJ": KJ, "KBJ": KBJ,
                "grid": P, "ms": round(dt * 1e3, 3),
                "keys_per_sec": round(B / dt),
                "compile_s": round(compile_s, 1),
                "matches_shipping": ok,
            }
        ),
        flush=True,
    )
    del state
    return None


def main():
    rng = np.random.default_rng(0)
    keys = jax.device_put(rng.integers(0, 256, (B, KEY_LEN), np.uint8))

    blk, bit = blocked.block_positions(
        keys, lengths, n_blocks=NB, block_bits=BB, k=K, seed=config.seed,
        block_hash=config.block_hash,
    )
    ref_state = jax.jit(
        lambda b, bl, bi: apply_blocked_updates(
            b, bl, bi, jnp.ones((B,), bool), block_bits=BB, interpret=False
        )
    )(jnp.zeros((NB, W), jnp.uint32), blk, bit)
    ref_fat = ref_state.reshape(NB8, 128)
    ref_fat.block_until_ready()

    variants = [
        # (name, R8, S, pres)
        ("fat R8=256 S4 +pres", 256, 4, True),
        ("fat R8=128 S8 +pres", 128, 8, True),
        ("fat R8=512 S2 +pres", 512, 2, True),
        ("fat R8=256 S4", 256, 4, False),
        ("fat R8=256 S8 +pres", 256, 8, True),
    ]
    built = {}
    for name, r8, s, pres in variants:
        lam = B * r8 // NB  # per (j, q) window
        KJ = max(16, (lam + max(16, int(8 * lam**0.5)) + 7) // 8 * 8)
        lam_big = lam * s
        KBJ = ((lam_big + KJ + 64 + 7) // 8) * 8
        P8 = NB8 // r8
        key_ = (r8, KBJ)
        if key_ not in built:
            starts, upd = jax.jit(lambda kk: build_stream3(kk, r8, KBJ))(keys)
            starts.block_until_ready()
            built[key_] = (starts, upd)
        starts, upd = built[key_]
        span = check_windows3(starts, s, KJ, KBJ, P8)
        if span > KJ:
            print(json.dumps({"variant": name, "skip": "window overflow",
                              "span": span, "KJ": KJ}), flush=True)
            continue
        try:
            run_variant(
                name, starts, upd, R8=r8, S=s, KJ=KJ, KBJ=KBJ, PRES=pres,
                ref_state=ref_fat,
            )
        except Exception as e:
            print(json.dumps({"variant": name, "error": repr(e)[:300]}),
                  flush=True)

    # presence correctness: insert the same stream into the JUST-updated
    # state — every valid key must report present
    name, r8, s = "presence replay check", 64, 8
    lam = B * r8 // NB
    KJ = max(16, (lam + max(16, int(8 * lam**0.5)) + 7) // 8 * 8)
    KBJ = ((lam * s + KJ + 64 + 7) // 8) * 8
    P8 = NB8 // r8
    starts, upd = built[(r8, KBJ)]
    state = jnp.zeros((NB8, 128), jnp.uint32)
    state, presb = jax.jit(
        lambda st, u, ss: sweep3_insert(
            st, u, ss, R8=r8, S=s, KJ=KJ, KBJ=KBJ, PRES=True
        )
    )(state, upd, starts)
    pres1 = jax.jit(
        lambda pb, ss: unsort_presence(pb, ss, r8, s, KJ, KBJ, P8)
    )(presb, starts)
    state2, presb2 = jax.jit(
        lambda st, u, ss: sweep3_insert(
            st, u, ss, R8=r8, S=s, KJ=KJ, KBJ=KBJ, PRES=True
        )
    )(state, upd, starts)
    pres2 = jax.jit(
        lambda pb, ss: unsort_presence(pb, ss, r8, s, KJ, KBJ, P8)
    )(presb2, starts)
    n1 = int(jnp.sum(pres1))
    n2 = int(jnp.sum(pres2))
    print(json.dumps({
        "check": "presence replay",
        "first_pass_present": n1,
        "second_pass_present": n2,
        "expect_second": B,
        "ok": n2 == B,
    }), flush=True)


if __name__ == "__main__":
    main()
