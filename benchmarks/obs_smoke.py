#!/usr/bin/env python
"""Observability smoke: boot the full server stack on CPU, drive it, and
assert the operator surface is actually there.

What it checks (the ISSUE-1 acceptance list, end to end):

* a real gRPC server + the background metrics HTTP thread come up;
* insert/query batches flow through the wire protocol;
* ``GET /metrics`` parses as Prometheus text format and contains
  ``tpubloom_keys_inserted_total``, per-RPC latency buckets, fill-ratio
  and checkpoint-lag gauges, and the per-phase histogram;
* ``SlowlogGet`` returns entries whose request ids match the ids the
  client generated.

Run directly (``python benchmarks/obs_smoke.py`` — prints one JSON line)
or via tier-1 (``tests/test_obs.py::test_obs_smoke`` imports
:func:`run_smoke`). Fast: small batches, CPU backend, ephemeral ports.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import urllib.request


def run_smoke() -> dict:
    """Drive the stack; returns summary facts (raises on any failure)."""
    from tpubloom import checkpoint as ckpt
    from tpubloom.obs.exposition import parse_families
    from tpubloom.obs.httpd import start_metrics_server
    from tpubloom.server.client import BloomClient
    from tpubloom.server.service import BloomService, build_server

    ckpt_dir = tempfile.mkdtemp(prefix="tpubloom-obs-smoke-")
    service = BloomService(sink_factory=lambda config: ckpt.FileSink(ckpt_dir))
    server, port = build_server(service, "127.0.0.1:0")
    server.start()
    metrics_server = start_metrics_server(service, port=0, host="127.0.0.1")
    try:
        client = BloomClient(f"127.0.0.1:{port}")
        client.wait_ready()
        client.create_filter(
            "smoke", capacity=50_000, error_rate=0.01, checkpoint_every=1000
        )
        keys = [b"smoke-key-%06d" % i for i in range(2048)]
        assert client.insert_batch("smoke", keys) == len(keys)
        insert_rid = client.last_rid
        assert client.include_batch("smoke", keys[:256]).all()
        client.checkpoint("smoke", wait=True)

        with urllib.request.urlopen(
            f"http://127.0.0.1:{metrics_server.port}/metrics", timeout=10
        ) as resp:
            assert resp.status == 200
            text = resp.read().decode()
        families = parse_families(text)

        required = [
            "tpubloom_keys_inserted_total",
            "tpubloom_rpc_duration_seconds_bucket",
            "tpubloom_rpc_phase_seconds_bucket",
            "tpubloom_filter_fill_ratio",
            "tpubloom_filter_fpr_drift",
            "tpubloom_checkpoint_lag_inserts",
            "tpubloom_checkpoint_age_seconds",
            "tpubloom_slowlog_entries",
        ]
        missing = [name for name in required if name not in families]
        assert not missing, f"/metrics scrape is missing {missing}"
        assert families["tpubloom_keys_inserted_total"][()] == len(keys)

        entries = client.slowlog_get()
        assert entries, "slowlog must be non-empty after traffic"
        rids = {e["rid"] for e in entries}
        assert insert_rid in rids, "client rid must appear in the slowlog"
        phased = [e for e in entries if e["method"] == "InsertBatch"]
        assert phased and {"decode", "host_prep", "kernel"} <= set(
            phased[0]["phases"]
        )
        return {
            "ok": True,
            "metrics_families": len(families),
            "scrape_bytes": len(text),
            "slowlog_entries": len(entries),
            "insert_rid_correlated": True,
            "keys_inserted_total": int(
                families["tpubloom_keys_inserted_total"][()]
            ),
        }
    finally:
        metrics_server.close()
        server.stop(grace=None)


def main() -> None:
    print(json.dumps(run_smoke()))


if __name__ == "__main__":
    # standalone runs must not grab the TPU tunnel (same reason as
    # tests/conftest.py); set before jax initializes a backend
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    main()
