#!/usr/bin/env python
"""Observability smoke: boot the full server stack on CPU, drive it, and
assert the operator surface is actually there.

What it checks (the ISSUE-1 acceptance list, end to end):

* a real gRPC server + the background metrics HTTP thread come up;
* insert/query batches flow through the wire protocol;
* ``GET /metrics`` parses as Prometheus text format and contains
  ``tpubloom_keys_inserted_total``, per-RPC latency buckets, fill-ratio
  and checkpoint-lag gauges, and the per-phase histogram;
* ``SlowlogGet`` returns entries whose request ids match the ids the
  client generated;
* tracing (ISSUE 15): the sampling-OFF path ships NO wire fields and
  pays no measurable overhead (insert throughput with the ring armed at
  1.0 must stay within a generous factor of the off path — re-measured
  once like the other perf gates), and the sampling-ON path produces a
  span tree (``rpc.InsertBatch`` root + phase children) retrievable by
  rid via ``TraceGet``;
* crash-forensics black box (ISSUE 16): disabled by default (the
  disabled path is the same one-truthy-check note path the phases
  above measure), and with the mmap'd rings armed the write-through +
  slowlog-worthy span spills stay within the same generous overhead
  bound — plus the spilled ring decodes cleanly via ``read_node``.

Run directly (``python benchmarks/obs_smoke.py`` — prints one JSON line)
or via tier-1 (``tests/test_obs.py::test_obs_smoke`` imports
:func:`run_smoke`). Fast: small batches, CPU backend, ephemeral ports.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
import urllib.request


def run_smoke() -> dict:
    """Drive the stack; returns summary facts (raises on any failure)."""
    from tpubloom import checkpoint as ckpt
    from tpubloom.obs.exposition import parse_families
    from tpubloom.obs.httpd import start_metrics_server
    from tpubloom.server.client import BloomClient
    from tpubloom.server.service import BloomService, build_server

    ckpt_dir = tempfile.mkdtemp(prefix="tpubloom-obs-smoke-")
    service = BloomService(sink_factory=lambda config: ckpt.FileSink(ckpt_dir))
    server, port = build_server(service, "127.0.0.1:0")
    server.start()
    metrics_server = start_metrics_server(service, port=0, host="127.0.0.1")
    try:
        client = BloomClient(f"127.0.0.1:{port}")
        client.wait_ready()
        client.create_filter(
            "smoke", capacity=50_000, error_rate=0.01, checkpoint_every=1000
        )
        keys = [b"smoke-key-%06d" % i for i in range(2048)]
        assert client.insert_batch("smoke", keys) == len(keys)
        insert_rid = client.last_rid
        assert client.include_batch("smoke", keys[:256]).all()
        client.checkpoint("smoke", wait=True)

        with urllib.request.urlopen(
            f"http://127.0.0.1:{metrics_server.port}/metrics", timeout=10
        ) as resp:
            assert resp.status == 200
            text = resp.read().decode()
        families = parse_families(text)

        required = [
            "tpubloom_keys_inserted_total",
            "tpubloom_rpc_duration_seconds_bucket",
            "tpubloom_rpc_phase_seconds_bucket",
            "tpubloom_filter_fill_ratio",
            "tpubloom_filter_fpr_drift",
            "tpubloom_checkpoint_lag_inserts",
            "tpubloom_checkpoint_age_seconds",
            "tpubloom_slowlog_entries",
        ]
        missing = [name for name in required if name not in families]
        assert not missing, f"/metrics scrape is missing {missing}"
        assert families["tpubloom_keys_inserted_total"][()] == len(keys)

        entries = client.slowlog_get()
        assert entries, "slowlog must be non-empty after traffic"
        rids = {e["rid"] for e in entries}
        assert insert_rid in rids, "client rid must appear in the slowlog"
        phased = [e for e in entries if e["method"] == "InsertBatch"]
        assert phased and {"decode", "host_prep", "kernel"} <= set(
            phased[0]["phases"]
        )

        # -- tracing phase (ISSUE 15) ---------------------------------
        from tpubloom.obs import trace as trace_mod

        def measure(cl, tag):
            # equal-length tags keep every run on ONE padded key shape —
            # the warm-up batch eats the jit compile so neither side's
            # window measures compilation (the re-learned PR-10 lesson)
            batch = [b"trace-%s-%%06d" % tag % i for i in range(256)]
            cl.insert_batch("smoke", batch)
            t0 = time.perf_counter()
            for _ in range(20):
                cl.insert_batch("smoke", batch)
            return 20 / (time.perf_counter() - t0)

        # sampling OFF (this server booted without a trace knob): the
        # client must stamp NO wire field and the ring must stay off
        assert not trace_mod.enabled()
        seen_reqs = []
        orig_call = client._call_once

        def spy(method, req, *a, **kw):
            seen_reqs.append(dict(req))
            return orig_call(method, req, *a, **kw)

        client._call_once = spy
        off_rate = measure(client, b"of0")
        client._call_once = orig_call
        assert seen_reqs and all("trace" not in r for r in seen_reqs), (
            "the sampling-off path must add no wire fields"
        )
        off_rid = client.last_rid
        assert client._rpc("TraceGet", {"trace_rid": off_rid}) == {
            "ok": True, "rid": off_rid, "enabled": False, "spans": [],
        }

        # sampling ON at 1.0: spans land; overhead stays bounded.
        # Generous bound + re-measure-once — this is an anti-regression
        # gate on a noisy shared runner, not a microbenchmark.
        trace_mod.configure(sample=1.0)
        traced_client = BloomClient(f"127.0.0.1:{port}", trace_sample=1.0)
        try:
            on_rate = measure(traced_client, b"on0")
            if on_rate < 0.5 * off_rate:
                # re-measure BOTH sides honestly: the off baseline must
                # run with the ring disarmed again — at sample 1.0 the
                # server captures the untraced client's requests too,
                # and a traced-vs-traced comparison would pass exactly
                # when a real regression triggered this branch
                trace_mod.configure(None)
                off_rate = measure(client, b"of1")
                trace_mod.configure(sample=1.0)
                on_rate = measure(traced_client, b"on1")
            assert on_rate >= 0.4 * off_rate, (
                f"tracing overhead out of bounds: on={on_rate:.1f}/s "
                f"vs off={off_rate:.1f}/s"
            )
            spans = traced_client.trace_get(traced_client.last_rid)
            span_names = {s["name"] for s in spans}
            assert {"rpc.InsertBatch", "client.hop",
                    "phase.kernel"} <= span_names, span_names
        finally:
            trace_mod.reset_for_tests()

        # -- crash-forensics black box (ISSUE 16) ---------------------
        # disabled is the default and the disabled path is the same
        # one-truthy-check-per-note path the earlier phases already
        # measured; the gate here bounds the ENABLED cost: mmap'd
        # write-through flight notes plus forced/slow span spills.
        from tpubloom.obs import blackbox as bb_mod

        assert not bb_mod.enabled(), "black box must be off by default"
        bb_dir = tempfile.mkdtemp(prefix="tpubloom-obs-smoke-bb-")
        try:
            bb_off_rate = measure(client, b"bf0")
            assert bb_mod.configure(bb_dir, node={"addr": "smoke"})
            # sample 0.0 arms the ring without sampling anything: only
            # the slow-probe path captures — and a freshly reset slowlog
            # makes the first timed batches all slowlog-worthy, so the
            # window measures real spills, not an idle ring
            trace_mod.configure(sample=0.0)
            service.slowlog.reset()
            bb_on_rate = measure(client, b"bn0")
            if bb_on_rate < 0.5 * bb_off_rate:
                trace_mod.reset_for_tests()
                bb_off_rate = measure(client, b"bf1")
                trace_mod.configure(sample=0.0)
                service.slowlog.reset()
                bb_on_rate = measure(client, b"bn1")
            assert bb_on_rate >= 0.4 * bb_off_rate, (
                f"black-box overhead out of bounds: on={bb_on_rate:.1f}/s "
                f"vs off={bb_off_rate:.1f}/s"
            )
            bb_mod.sync()
            node = bb_mod.read_node(bb_dir)
            assert node["spans"], "slowlog-worthy spans must have spilled"
            assert node["meta"].get("pid") == os.getpid()
            assert not node["skipped"], "a live ring must decode cleanly"
        finally:
            trace_mod.reset_for_tests()
            bb_mod.reset_for_tests()

        return {
            "ok": True,
            "metrics_families": len(families),
            "scrape_bytes": len(text),
            "slowlog_entries": len(entries),
            "insert_rid_correlated": True,
            "keys_inserted_total": int(
                families["tpubloom_keys_inserted_total"][()]
            ),
            "trace_off_wire_clean": True,
            "trace_off_rate_per_s": round(off_rate, 1),
            "trace_on_rate_per_s": round(on_rate, 1),
            "trace_overhead_ratio": round(on_rate / off_rate, 3),
            "trace_spans_sampled": len(spans),
            "blackbox_off_rate_per_s": round(bb_off_rate, 1),
            "blackbox_on_rate_per_s": round(bb_on_rate, 1),
            "blackbox_overhead_ratio": round(bb_on_rate / bb_off_rate, 3),
            "blackbox_spans_spilled": len(node["spans"]),
        }
    finally:
        metrics_server.close()
        server.stop(grace=None)


def main() -> None:
    print(json.dumps(run_smoke()))


if __name__ == "__main__":
    # standalone runs must not grab the TPU tunnel (same reason as
    # tests/conftest.py); set before jax initializes a backend
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    main()
