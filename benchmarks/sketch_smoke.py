#!/usr/bin/env python
"""Sketch-plane smoke bench (ISSUE 19).

The sketch kinds (cuckoo / count-min / top-k) ride the SAME ingestion
coalescer, op-log, and wire path as bloom filters — this smoke proves
the ride is real on a live subprocess server, not just unit-tested:

* ``cf_keys_per_sec`` / ``cms_keys_per_sec`` — aggregate rate of N
  connections hammering ``CFAdd`` / unit ``CMSIncrBy`` through the
  coalescer;
* ``cf_requests_per_flush`` — THE gate (``> 1.5``, re-measured once
  with a doubled window like ingest_load's): concurrent sketch writes
  must park and flush as one device launch, otherwise the sketch plane
  silently fell off the coalescer's amortization;
* anti-gaming — a sample of every connection's keys must be PRESENT in
  the cuckoo filter afterwards (no false negatives), the server's
  ``cms_keys_incremented`` counter must cover every key the CMS rate
  counted, and the hottest key of a skewed stream must surface in
  ``TOPK.LIST`` with an estimate >= its true count.

Run directly (prints one JSON line) or via tier-1
(``tests/test_sketch.py::test_sketch_bench_smoke``).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np

#: concurrent connections per hammer phase.
CONNECTIONS = 8
#: keys per request — small on purpose (per-REQUEST overhead is what
#: the coalescer amortizes).
BATCH = 64
#: acceptance gate: sketch writes must actually coalesce.
FLUSH_GATE = 1.5

_CHILD = """\
import sys
import jax
jax.config.update("jax_platforms", "cpu")
from tpubloom.server.service import main
main(sys.argv[1:])
"""


def _spawn(tmpdir: str, extra_args: list) -> tuple:
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import ingest_load

    return ingest_load._spawn(tmpdir, 0, extra_args, child_src=_CHILD)


def _hammer(addr: str, insert, duration_s: float) -> tuple:
    """Aggregate keys/sec of CONNECTIONS writer threads calling
    ``insert(client, thread, iteration)`` (each inserting BATCH disjoint
    u64-derived keys), plus each thread's first key batch for the
    presence anti-gaming check."""
    from tpubloom.server.client import BloomClient

    clients = [BloomClient(addr) for _ in range(CONNECTIONS)]
    stop = time.monotonic() + duration_s
    counts = [0] * CONNECTIONS
    first: list = [None] * CONNECTIONS

    def worker(t):
        c = clients[t]
        i = 0
        while time.monotonic() < stop:
            keys = insert(c, t, i)
            if first[t] is None:
                first[t] = keys
            counts[t] += BATCH
            i += 1

    ts = [threading.Thread(target=worker, args=(t,))
          for t in range(CONNECTIONS)]
    t0 = time.perf_counter()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    rate = sum(counts) / (time.perf_counter() - t0)
    for c in clients:
        c.close()
    return rate, [f for f in first if f is not None]


def _keys(t: int, i: int, plane: int) -> np.ndarray:
    return (np.arange(BATCH, dtype=np.uint64) + i * BATCH
            + (t + 1) * (1 << 40) + plane * (1 << 52))


def _counters(client) -> dict:
    # ingest_* live in the service Metrics map, the sketch kernel
    # counters in the process-global obs registry — merge both views
    snap = client.stats()
    return {**snap.get("process_counters", {}), **snap["counters"]}


def _measure_cf(addr: str, boot, duration_s: float) -> dict:
    f0 = _counters(boot).get("ingest_flushes", 0)
    r0 = _counters(boot).get("ingest_requests_coalesced", 0)

    def insert(c, t, i):
        keys = _keys(t, i, 0)
        c.cf_add("cf", keys)
        return keys

    rate, first = _hammer(addr, insert, duration_s)
    c1 = _counters(boot)
    flushes = c1.get("ingest_flushes", 0) - f0
    requests = c1.get("ingest_requests_coalesced", 0) - r0
    return {
        "cf_keys_per_sec": round(rate),
        "cf_requests_per_flush": round(requests / max(flushes, 1), 2),
        "_cf_first": first,
    }


def _measure_cms(addr: str, boot, duration_s: float) -> dict:
    k0 = _counters(boot).get("cms_keys_incremented", 0)

    def insert(c, t, i):
        keys = _keys(t, i, 1)
        c.cms_incrby("cms", keys)  # unit adds: the coalesced path
        return keys

    rate, first = _hammer(addr, insert, duration_s)
    counted = round(rate * duration_s)  # approximate; exact is below
    incremented = _counters(boot).get("cms_keys_incremented", 0) - k0
    return {
        "cms_keys_per_sec": round(rate),
        "cms_keys_incremented": incremented,
        "_cms_counted": counted,
        "_cms_first": first,
    }


def _warm(boot, verb, name: str, plane: int) -> None:
    """Compile the jit buckets a coalesced sketch flush can produce
    (merged sizes pad to powers of two up to CONNECTIONS*BATCH) so the
    measured window is ingest time, not XLA compiles."""
    size = BATCH
    while size <= CONNECTIONS * BATCH:
        verb(name, np.arange(size, dtype=np.uint64)
             + plane * (1 << 52) + (1 << 56) + size)
        size *= 2


def run_load(duration_s: float = 2.0) -> dict:
    import tempfile

    from tpubloom.server.client import BloomClient

    tmpdir = tempfile.mkdtemp(prefix="tpubloom-sketch-smoke-")
    out: dict = {
        "connections": CONNECTIONS, "batch": BATCH,
        "duration_s": duration_s,
    }
    proc, addr = _spawn(
        tmpdir, ["--coalesce-max-keys", "16384",
                 "--coalesce-max-wait-us", "2000"],
    )
    try:
        # generous timeout: the first cuckoo flush pays the kick-loop
        # XLA compile
        boot = BloomClient(addr, timeout=60.0)
        boot.wait_ready(timeout=180.0)
        # table sized small ON PURPOSE: the CPU backend's kick fori_loop
        # carries the whole table per batch (measured ~O(m) per flush:
        # 65ms at 2^16 slots, 2.8s at 2^20), so a big table would turn
        # the window into one flush. 40k capacity still clears what a CI
        # window inserts, and FULL would fail the presence gate honestly.
        boot.cf_reserve("cf", 40_000)
        boot.cms_init_by_dim("cms", 8192, 4)
        boot.topk_reserve("tk", 4, width=2048, depth=5)
        _warm(boot, boot.cf_add, "cf", 0)
        _warm(boot, lambda n, k: boot.cms_incrby(n, k), "cms", 1)

        out.update(_measure_cf(addr, boot, duration_s))
        if out["cf_requests_per_flush"] <= FLUSH_GATE:
            # one re-measure with a doubled window before failing (a
            # scheduler hiccup in a short window can starve the park)
            out["remeasured"] = True
            out.update(_measure_cf(addr, boot, duration_s * 2))
        out.update(_measure_cms(addr, boot, duration_s))

        # anti-gaming: presence of every connection's first batch (a
        # rate counted off writes that never landed cannot clear this)
        cf_first = out.pop("_cf_first")
        for keys in cf_first:
            assert boot.cf_exists("cf", keys).all(), (
                "cuckoo inserts counted by the rate are not present"
            )
        cms_first = out.pop("_cms_first")
        for keys in cms_first:
            assert (boot.cms_query("cms", keys) >= 1).all(), (
                "CMS unit increments counted by the rate read back 0"
            )
        out.pop("_cms_counted")
        assert out["cms_keys_incremented"] >= CONNECTIONS * BATCH, (
            f"server counted only {out['cms_keys_incremented']} CMS key "
            f"increments over a {duration_s}s hammer"
        )
        assert out["cf_requests_per_flush"] > FLUSH_GATE, (
            f"only {out['cf_requests_per_flush']} sketch requests/flush "
            f"— CFAdd writes are not riding the coalescer's "
            f"amortization (gate {FLUSH_GATE})"
        )

        # top-k: a skewed stream's hottest key must surface with an
        # estimate >= its true count (count-min never underestimates)
        hot = np.full(256, 7, dtype=np.uint64)
        cold = np.arange(64, dtype=np.uint64) + (1 << 30)
        boot.topk_add("tk", np.concatenate([hot, cold]))
        hitters = dict(boot.topk_list("tk"))
        key7 = np.asarray([7], dtype=np.uint64).tobytes()
        assert key7 in hitters and hitters[key7] >= 256, (
            f"hottest key missing from TOPK.LIST: {hitters}"
        )
        out["topk_hot_estimate"] = int(hitters[key7])
        boot.close()
    finally:
        if proc.poll() is None:
            proc.send_signal(signal.SIGKILL)
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            pass
    return out


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    print(json.dumps(run_load()))
