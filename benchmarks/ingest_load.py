#!/usr/bin/env python
"""Saturating multi-connection ingest load generator (ISSUE 10).

Makes aggregate END-TO-END keys/sec a first-class tracked metric instead
of "tunnel weather": a real subprocess server (so the measurement
includes gRPC, decode, scheduling — everything a production client
pays), one warm connection measured alone, then N concurrent
connections hammering the same filter through the ingestion coalescer
with the zero-copy ``fixed`` wire encoding.

What the numbers mean:

* ``single_conn_keys_per_sec`` — one connection's ping-pong rate: every
  request pays the full per-request cost (rtt + decode + lock + jit
  dispatch + the coalesce window) serially;
* ``aggregate_keys_per_sec`` — N connections, coalesced: concurrent
  requests park and flush as ONE device launch, so the per-request
  fixed costs amortize across the flush;
* ``scaling_vs_single`` — aggregate / single. THE acceptance gate
  (``>= 2.0``, re-measured once with a doubled window before failing,
  like cluster_smoke's): on one shared filter the lock-serialized
  per-request path barely scales with connections (measured ~1.3x on
  this CPU image — every request runs its own kernel under the op
  lock), so clearing 2x is the coalescer's amortization, not thread
  parallelism;
* ``requests_per_flush`` — how many RPCs each device launch served
  (from the server's ingest counters; asserted > 1.5 so the gate can't
  pass without actual coalescing);
* ``scaling_vs_linear`` — aggregate / (N x single), informational. On
  a REAL TPU the host-side per-request cost dominates and this is the
  number to chase; on the CPU CI image the "device" is the same cores
  the handlers run on, so per-key kernel cost (~3us/key measured)
  bounds any single-dispatcher aggregate.

A **streaming phase** (ISSUE 18) pits the persistent bidi ingest plane
against unary on the same server: N ``insert_stream`` sessions pumping
``BATCH``-key frames under credit flow control vs N unary connections
sending the same frames. The gate is ``streaming_vs_unary >= 1.0``
(re-measured once with a doubled window, like the coalesce gate) — a
long-lived stream pays no per-request channel bookkeeping, so falling
BELOW unary means the ack pump or credit path regressed. Anti-gaming:
every frame the rate counts must show up in the server's
``stream_frames_total`` / ``stream_acks_total`` deltas — the clock only
stops after ``drain()``, so unacked frames can't inflate the number.

A second phase (skippable via ``quorum=False``) runs a primary+replica
pair with ``--min-replicas-to-write 1``: the commit barrier must run
once per FLUSH, not once per write — the run asserts barrier
observations (``wait_barrier`` histogram count) land well below the
quorum-write count, the "N quorum writes, one WAIT" amortization.

Run directly (prints one JSON line) or via tier-1
(``tests/test_ingest.py::test_ingest_load_smoke``).
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np

#: concurrent connections in the aggregate phase.
CONNECTIONS = 8
#: keys per request — small on purpose: the gap this closes is
#: per-REQUEST overhead, and tiny requests are what real multi-tenant
#: front-ends send.
BATCH = 64
#: acceptance gate: N coalesced connections must beat ONE connection's
#: rate by this factor (the lock-serialized path measures ~1.3x here).
GATE = 2.0
#: streaming gate (ISSUE 18): bidi stream frames/sec vs unary frames/sec
#: on the same server — the persistent plane must at least match unary.
STREAM_GATE = 1.0

_CHILD = """\
import sys
import jax
jax.config.update("jax_platforms", "cpu")
from tpubloom.server.service import main
main(sys.argv[1:])
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn(
    tmpdir: str, idx: int, extra_args: list, *,
    child_src: str = None, env_extra: dict = None, env_drop: tuple = (),
) -> tuple:
    """Boot one subprocess server. ``child_src`` overrides the CPU-pinned
    default script; ``env_extra``/``env_drop`` adjust the child env
    (multichip_load uses them for the forced device mesh, the native-
    backend mode, and to strip debug instrumentation its perf gates
    must not measure)."""
    port = _free_port()
    script = os.path.join(tmpdir, f"child-{idx}.py")
    with open(script, "w") as f:
        f.write(child_src or _CHILD)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }
    for k in env_drop:
        env.pop(k, None)
    if env_extra:
        env.update(env_extra)
    proc = subprocess.Popen(
        [sys.executable, script, str(port), *extra_args],
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT, env=env,
    )
    return proc, f"127.0.0.1:{port}"


def _hammer(
    addr: str, name: str, threads: int, duration_s: float,
    *, tolerate: tuple = (),
) -> float:
    """Aggregate keys/sec of `threads` writer CONNECTIONS (one client =
    one channel each) inserting disjoint u64 batches. ``tolerate`` names
    error codes to ride through (the quorum phase tolerates
    NOT_ENOUGH_REPLICAS: the write APPLIED — Redis WAIT semantics — and
    a slow CI box stalling one barrier must not kill the run)."""
    from tpubloom.server import protocol
    from tpubloom.server.client import BloomClient

    clients = [BloomClient(addr) for _ in range(threads)]
    for c in clients:  # negotiate + warm the channel outside the window
        c.insert_batch(name, np.arange(BATCH, dtype=np.uint64))
    stop = time.monotonic() + duration_s
    counts = [0] * threads

    def worker(t):
        c = clients[t]
        base = np.arange(BATCH, dtype=np.uint64) + (t + 1) * (1 << 40)
        i = 0
        while time.monotonic() < stop:
            try:
                c.insert_batch(name, base + i * BATCH)
            except protocol.BloomServiceError as e:
                if e.code not in tolerate:
                    raise
            counts[t] += BATCH
            i += 1

    ts = [threading.Thread(target=worker, args=(t,)) for t in range(threads)]
    t0 = time.perf_counter()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    rate = sum(counts) / (time.perf_counter() - t0)
    for c in clients:
        c.close()
    return rate


def _stream_hammer(
    addr: str, name: str, threads: int, duration_s: float
) -> tuple:
    """(frames/sec, frames sent) over `threads` persistent bidi
    InsertStream sessions, each pumping BATCH-key frames as fast as the
    server's credit window admits them. The clock stops only after every
    session DRAINED — a frame counts when its ack arrived, the same
    contract the unary hammer's response-wait gives."""
    from tpubloom.server.client import BloomClient

    clients = [BloomClient(addr) for _ in range(threads)]
    for c in clients:  # negotiate + warm the channel outside the window
        c.insert_batch(name, np.arange(BATCH, dtype=np.uint64))
    stop = time.monotonic() + duration_s
    counts = [0] * threads

    def worker(t):
        c = clients[t]
        base = np.arange(BATCH, dtype=np.uint64) + (t + 1) * (1 << 44)
        sent = 0
        with c.insert_stream(name) as s:
            while time.monotonic() < stop:
                s.send(base + sent * BATCH)
                sent += 1
            s.drain(timeout=120)
        counts[t] = sent

    ts = [threading.Thread(target=worker, args=(t,)) for t in range(threads)]
    t0 = time.perf_counter()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    elapsed = time.perf_counter() - t0
    for c in clients:
        c.close()
    return sum(counts) / elapsed, sum(counts)


def _stream_counters(client) -> tuple:
    counters = client.stats()["counters"]
    return (
        counters.get("stream_frames_total", 0),
        counters.get("stream_acks_total", 0),
    )


def _measure_streaming(addr: str, name: str, duration_s: float,
                       stats_client) -> dict:
    unary = _hammer(addr, name, CONNECTIONS, duration_s)
    f0, a0 = _stream_counters(stats_client)
    stream_rate, frames_sent = _stream_hammer(
        addr, name, CONNECTIONS, duration_s
    )
    f1, a1 = _stream_counters(stats_client)
    unary_frames = unary / BATCH
    return {
        "unary_frames_per_sec": round(unary_frames),
        "stream_frames_per_sec": round(stream_rate),
        "streaming_vs_unary": round(stream_rate / unary_frames, 3),
        "stream_frames_sent": frames_sent,
        "stream_frames_recv": f1 - f0,
        "stream_acks_recv": a1 - a0,
    }


def _warm_buckets(client, name: str, up_to: int = None) -> None:
    """Compile every jit bucket a coalesced flush can produce (merged
    sizes pad to powers of two in [BATCH, up_to]) — without
    this the aggregate window eats one ~0.4s XLA compile per new shape
    and the measurement is compile time, not ingest time (the same
    lesson cluster_smoke's warm-up comment pins). Unary ping-pong with
    CONNECTIONS in flight can merge at most CONNECTIONS*BATCH keys (the
    default); the streaming phase pipelines a 32-frame window per
    session, so its flushes grow to the coalescer's max-keys cap and it
    warms that far."""
    from tpubloom.server import protocol

    size = BATCH
    while size <= (up_to or CONNECTIONS * BATCH):
        try:
            client.insert_batch(
                name, np.arange(size, dtype=np.uint64) + (1 << 50) + size
            )
        except protocol.BloomServiceError as e:
            if e.code != "NOT_ENOUGH_REPLICAS":  # applied; compile landed
                raise
        size *= 2


def _ingest_counters(client) -> tuple:
    counters = client.stats()["counters"]
    return (
        counters.get("ingest_flushes", 0),
        counters.get("ingest_requests_coalesced", 0),
    )


def _measure(addr: str, name: str, duration_s: float, stats_client) -> dict:
    single = _hammer(addr, name, 1, duration_s)
    f0, r0 = _ingest_counters(stats_client)
    aggregate = _hammer(addr, name, CONNECTIONS, duration_s)
    f1, r1 = _ingest_counters(stats_client)
    return {
        "single_conn_keys_per_sec": round(single),
        "aggregate_keys_per_sec": round(aggregate),
        "scaling_vs_single": round(aggregate / single, 3),
        "scaling_vs_linear": round(aggregate / (CONNECTIONS * single), 3),
        "ingest_flushes": f1 - f0,
        # requests/flush over the AGGREGATE window only (the single-
        # connection phase is 1/flush by construction)
        "requests_per_flush": round((r1 - r0) / max(f1 - f0, 1), 2),
    }


def run_load(
    duration_s: float = 2.0,
    *,
    quorum: bool = True,
    coalesce_args: tuple = ("--coalesce-max-keys", "16384",
                            "--coalesce-max-wait-us", "2000"),
) -> dict:
    import tempfile

    from tpubloom.server import protocol
    from tpubloom.server.client import BloomClient

    tmpdir = tempfile.mkdtemp(prefix="tpubloom-ingest-load-")
    procs: list = []
    out: dict = {
        "connections": CONNECTIONS, "batch": BATCH,
        "duration_s": duration_s,
    }
    try:
        proc, addr = _spawn(tmpdir, 0, list(coalesce_args))
        procs.append(proc)
        boot = BloomClient(addr)
        boot.wait_ready(timeout=180.0)
        boot.create_filter("ingest", capacity=1_000_000, error_rate=0.01)
        _warm_buckets(boot, "ingest")

        out.update(_measure(addr, "ingest", duration_s, boot))
        if out["scaling_vs_single"] < GATE or out["requests_per_flush"] <= 1.5:
            # one re-measure with a doubled window before failing: on a
            # small shared CI runner a scheduler hiccup inside a 2s
            # window can flip the comparison with no code defect
            out["remeasured"] = True
            out.update(_measure(addr, "ingest", duration_s * 2, boot))
        # streaming plane (ISSUE 18): same server, same frames — the
        # persistent stream must at least match unary frame throughput.
        # Pipelined windows park enough to hit the coalescer's max-keys
        # cap, so the jit buckets up to it must be warm first.
        max_keys = BATCH
        for flag, value in zip(coalesce_args, coalesce_args[1:]):
            if flag == "--coalesce-max-keys":
                max_keys = int(value)
        _warm_buckets(boot, "ingest", up_to=max_keys)
        out.update(_measure_streaming(addr, "ingest", duration_s, boot))
        if out["streaming_vs_unary"] < STREAM_GATE:
            out["stream_remeasured"] = True
            out.update(
                _measure_streaming(addr, "ingest", duration_s * 2, boot)
            )
        boot.close()
        assert out["streaming_vs_unary"] >= STREAM_GATE, (
            f"bidi streaming moved {out['stream_frames_per_sec']} "
            f"frames/s vs {out['unary_frames_per_sec']} unary — a "
            f"persistent stream below unary means the ack pump or "
            f"credit path regressed (gate {STREAM_GATE}x)"
        )
        # anti-gaming: every frame the rate counted must have been
        # RECEIVED and ACKED by the server during the window — a rate
        # computed off unsent/unacked frames cannot clear this
        assert out["stream_frames_recv"] >= out["stream_frames_sent"], (
            f"server received {out['stream_frames_recv']} stream frames "
            f"but the rate counted {out['stream_frames_sent']}"
        )
        assert out["stream_acks_recv"] >= out["stream_frames_sent"], (
            f"server acked {out['stream_acks_recv']} stream frames "
            f"but the rate counted {out['stream_frames_sent']}"
        )
        assert out["scaling_vs_single"] >= GATE, (
            f"coalesced aggregate ({out['aggregate_keys_per_sec']} keys/s "
            f"over {CONNECTIONS} connections) is only "
            f"{out['scaling_vs_single']}x the single-connection rate "
            f"({out['single_conn_keys_per_sec']}) — coalescing must "
            f"amortize per-request decode+launch (gate {GATE}x)"
        )
        assert out["requests_per_flush"] > 1.5, (
            f"only {out['requests_per_flush']} requests/flush — the "
            f"aggregate gate passed without actual coalescing"
        )

        if quorum:
            # barrier amortization: primary + one replica, every write
            # quorum-gated — the coalesced flush must pay ONE wait per
            # flush, not one per request
            pproc, paddr = _spawn(
                tmpdir, 1,
                [os.path.join(tmpdir, "ckpt-p"),
                 "--repl-log-dir", os.path.join(tmpdir, "log-p"),
                 "--min-replicas-to-write", "1",
                 # generous barrier budget: under the armed lock tracker
                 # (CI chaos shard) replica applies slow down and a 1s
                 # default budget flakes with no code defect
                 "--min-replicas-max-lag-ms", "5000",
                 *coalesce_args],
            )
            procs.append(pproc)
            pc = BloomClient(paddr)
            pc.wait_ready(timeout=180.0)
            rproc, raddr = _spawn(
                tmpdir, 2,
                [os.path.join(tmpdir, "ckpt-r"), "--replica-of", paddr],
            )
            procs.append(rproc)
            BloomClient(raddr).wait_ready(timeout=180.0)
            deadline = time.monotonic() + 60
            while True:  # wait for the replica to connect + ack
                if pc.health().get("replication", {}).get("replicas"):
                    break
                assert time.monotonic() < deadline, "replica never connected"
                time.sleep(0.2)
            try:
                pc.create_filter("q", capacity=1_000_000, error_rate=0.01)
            except protocol.BloomServiceError as e:
                # applied either way (WAIT semantics) — attach instead
                if e.code != "NOT_ENOUGH_REPLICAS":
                    raise
                pc.create_filter(
                    "q", capacity=1_000_000, error_rate=0.01, exist_ok=True
                )
            _warm_buckets(pc, "q")
            waits0 = pc.stats()["wait_barrier"].get("n", 0)
            r0 = pc.stats()["counters"].get("ingest_requests_coalesced", 0)
            q = _hammer(
                paddr, "q", CONNECTIONS, duration_s,
                tolerate=("NOT_ENOUGH_REPLICAS",),
            )
            stats = pc.stats()
            waits = stats["wait_barrier"].get("n", 0) - waits0
            requests = (
                stats["counters"].get("ingest_requests_coalesced", 0) - r0
            )
            out["quorum_keys_per_sec"] = round(q)
            out["quorum_write_requests"] = requests
            out["wait_barrier_observations"] = waits
            out["writes_per_barrier"] = round(requests / max(waits, 1), 2)
            assert waits < requests, (
                f"{waits} barrier waits for {requests} quorum write "
                f"requests — a coalesced flush must share ONE barrier "
                f"across its parked writes"
            )
            pc.close()
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.send_signal(signal.SIGKILL)
        for proc in procs:
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                pass
    return out


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    print(json.dumps(run_load()))
