#!/usr/bin/env python
"""HA smoke: 3-node promote-under-load on CPU — the failover surface's
canary (ISSUE 4), wired into tier-1 (``tests/test_ha.py::test_ha_smoke``)
and CI.

What it drives:

* a primary (op log) + two **chained** replicas (``--replica-of`` +
  ``--repl-log-dir`` equivalents) + a 3-sentinel quorum;
* a writer hammers counting-filter ``InsertBatch`` (each batch a fresh
  key set, one rid per logical batch) while the primary is stopped
  mid-load;
* the sentinels agree SDOWN→ODOWN, promote the most-caught-up replica,
  re-point the survivor; the topology-aware client refreshes off the
  sentinels and completes every batch;
* **failover time-to-first-successful-write** is measured from the
  primary's death to the first batch acked by the new primary;
* the counting-filter proof: every acked batch re-driven with its
  original rid is a dedup hit or a heal, all keys present exactly once
  (one delete round empties them) — zero lost, zero doubled.

Run directly (``python benchmarks/ha_smoke.py`` — prints one JSON line)
or via tier-1.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time


def run_smoke() -> dict:
    """Drive the 3-node failover scenario; returns summary facts
    (raises on any failure)."""
    from tpubloom import faults
    from tpubloom.ha.sentinel import Sentinel
    from tpubloom.repl import OpLog, ReplicaApplier
    from tpubloom.server.client import BloomClient
    from tpubloom.server.service import BloomService, build_server

    faults.reset()
    out: dict = {}
    cleanup: list = []  # LIFO even on failure — leaked grpc servers hang exit

    def make_primary():
        oplog = OpLog(tempfile.mkdtemp(prefix="tpubloom-ha-smoke-p-"))
        svc = BloomService(oplog=oplog)
        srv, port = build_server(svc, "127.0.0.1:0")
        srv.start()
        svc.listen_address = f"127.0.0.1:{port}"
        cleanup.append(lambda: srv.stop(grace=None))
        cleanup.append(oplog.close)
        return svc, srv, port, oplog

    def make_chained_replica(pport):
        oplog = OpLog(tempfile.mkdtemp(prefix="tpubloom-ha-smoke-r-"))
        svc = BloomService(oplog=oplog, read_only=True)
        srv, port = build_server(svc, "127.0.0.1:0")
        srv.start()
        svc.listen_address = f"127.0.0.1:{port}"
        app = ReplicaApplier(
            svc,
            f"127.0.0.1:{pport}",
            reconnect_base=0.05,
            listen_address=svc.listen_address,
        ).start()
        cleanup.append(lambda: srv.stop(grace=None))
        cleanup.append(oplog.close)
        cleanup.append(
            lambda: (svc.replica_applier or app).stop()
        )
        return svc, srv, port, app

    try:
        psvc, psrv, pport, poplog = make_primary()
        boot = BloomClient(f"127.0.0.1:{pport}")
        cleanup.append(boot.close)
        boot.wait_ready()
        boot.create_filter(
            "smoke", capacity=50_000, error_rate=0.01, counting=True
        )
        replicas = [make_chained_replica(pport) for _ in range(2)]
        for svc, _, _, app in replicas:
            assert app.wait_for_seq(poplog.last_seq, 30), app.status()

        sents = [
            Sentinel(
                f"127.0.0.1:{pport}",
                peers=[],
                poll_s=0.1,
                down_after_s=0.5,
                failover_cooldown_s=0.5,
            )
            for _ in range(3)
        ]
        for s in sents:
            s.peers.extend(x.address for x in sents if x is not s)
            s.quorum = 2
        for s in sents:
            s.start()
            cleanup.append(s.stop)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if len(sents[0].handle_Topology({})["replicas"]) == 2:
                break
            time.sleep(0.05)
        assert len(sents[0].handle_Topology({})["replicas"]) == 2

        client = BloomClient(
            sentinels=[s.address for s in sents],
            max_retries=8,
            backoff_base=0.1,
            backoff_max=1.0,
            breaker_threshold=0,
        )
        cleanup.append(client.close)

        n_batches, batch_size = 24, 25
        batches = [
            [b"smoke-%03d-%03d" % (i, j) for j in range(batch_size)]
            for i in range(n_batches)
        ]
        acked: list = []
        kill_at = 6
        killed = threading.Event()
        kill_time = [0.0]
        first_post_kill_ack = [0.0]

        def writer():
            for i, keys in enumerate(batches):
                if i == kill_at:
                    killed.set()
                try:
                    client.insert_batch("smoke", keys)
                except Exception:  # noqa: BLE001 — re-drive, SAME rid
                    rid = client.last_rid
                    while True:
                        try:
                            client.refresh_topology()
                            client._call_once(
                                "InsertBatch",
                                {"name": "smoke", "keys": keys, "rid": rid},
                            )
                            break
                        except Exception:  # noqa: BLE001
                            time.sleep(0.2)
                acked.append((i, client.last_rid))
                if kill_time[0] and not first_post_kill_ack[0]:
                    first_post_kill_ack[0] = time.monotonic()

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        assert killed.wait(60), "writer never reached the kill point"
        kill_time[0] = time.monotonic()
        psrv.stop(grace=None)  # the primary "crashes"
        poplog.close()
        t.join(timeout=120)
        assert not t.is_alive(), "writer wedged during failover"
        assert len(acked) == n_batches

        out["failovers"] = sum(s.failovers for s in sents)
        assert out["failovers"] >= 1, "no sentinel led a failover"
        leader = next(s for s in sents if s.failovers)
        topo = leader.handle_Topology({})
        out["new_primary"] = topo["primary"]
        out["epoch"] = topo["epoch"]
        out["failover_seconds"] = round(
            first_post_kill_ack[0] - kill_time[0], 3
        )

        # proof: re-drive every acked batch with its original rid, then
        # count exactness with one delete round
        redrive = BloomClient(topo["primary"])
        cleanup.append(redrive.close)
        for i, rid in acked:
            redrive._call_once(
                "InsertBatch",
                {"name": "smoke", "keys": batches[i], "rid": rid},
            )
        all_keys = [k for b in batches for k in b]
        present = redrive.include_batch("smoke", all_keys)
        out["lost_acked"] = int((~present).sum())
        assert out["lost_acked"] == 0, f"{out['lost_acked']} acked keys lost"
        for i, _ in acked:
            redrive.delete_batch("smoke", batches[i])
        leftovers = redrive.include_batch("smoke", all_keys)
        out["double_applied"] = int(leftovers.sum())
        assert out["double_applied"] == 0, (
            f"{out['double_applied']} keys double-applied"
        )

        # the surviving replica follows the new primary
        survivor = next(
            r for r in replicas if r[0].listen_address != topo["primary"]
        )
        new_app = survivor[0].replica_applier
        assert new_app is not None
        new_primary_svc = next(
            r[0] for r in replicas if r[0].listen_address == topo["primary"]
        )
        assert new_app.wait_for_seq(new_primary_svc.oplog.last_seq, 30), (
            new_app.status()
        )
        out["survivor_partial_syncs"] = new_app.partial_syncs
    finally:
        for fn in reversed(cleanup):
            try:
                fn()
            except Exception:  # noqa: BLE001
                pass
    return out


def main() -> int:
    if os.environ.get("JAX_PLATFORMS") is None:
        os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    result = run_smoke()
    print(json.dumps({"ok": True, **result}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
