#!/usr/bin/env python
"""The five BASELINE.json benchmark configs as runnable scripts.

Usage: python benchmarks/run.py --config N [--scale F] [--platform cpu|tpu]

Each config prints one JSON line. --scale shrinks key counts (and for
device configs the filter size) so every config can be smoke-run on the
1-core CPU backend; --scale 1.0 on a real v5e chip is the acceptance
matrix (BASELINE.md). Defaults to a small scale on CPU.

| config | workload                                   | pins                         |
|--------|--------------------------------------------|------------------------------|
| 1      | 1M random 16B keys, m=10M, k=7             | CPU reference driver (C++)   |
| 2      | 100M-key URL dedup, m=2^30, k=10           | single-chip batched kernels  |
| 3      | 1B-key stream, m=2^34, periodic checkpoint | streaming + checkpoint       |
| 4      | counting insert/delete/query mix, m=2^30   | scatter-add kernel           |
| 5      | 64-shard array, m=2^36 total               | shard_map + all-reduce-OR    |
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _gen_keys(n: int, nbytes: int = 16, seed: int = 0):
    import numpy as np

    rng = np.random.default_rng(seed)
    raw = rng.integers(0, 256, size=(n, nbytes), dtype=np.uint8)
    return raw, np.full(n, nbytes, dtype=np.int32)


def config1(scale: float) -> dict:
    """CPU reference driver (the reference's :ruby-driver role, C++ hot
    path): 1M keys, m=10M, k=7 — the measured CPU baseline the TPU numbers
    are compared against."""
    import numpy as np

    from tpubloom import CPUBloomFilter, FilterConfig, native

    n = int(1_000_000 * scale)
    cfg = FilterConfig(m=10_000_000, k=7, key_len=16)
    f = CPUBloomFilter(cfg)  # auto-uses native when built
    keys_u8, lengths = _gen_keys(n)
    keys = [bytes(k) for k in keys_u8]
    t0 = time.perf_counter()
    f.insert_batch(keys)
    t_insert = time.perf_counter() - t0
    t0 = time.perf_counter()
    hits = f.include_batch(keys)
    t_query = time.perf_counter() - t0
    assert hits.all()
    return {
        "config": 1,
        "driver": "native-c++" if f.use_native else "numpy",
        "n": n,
        "insert_keys_per_sec": round(n / t_insert),
        "query_keys_per_sec": round(n / t_query),
        "combined_keys_per_sec": round(n / (t_insert + t_query)),
    }


def config2(scale: float, layout: str = "flat") -> dict:
    """URL-dedup: batched inserts then mixed-hit queries on one device."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpubloom import BlockedBloomFilter, BloomFilter, FilterConfig

    n = int(100_000_000 * scale)
    nq = int(10_000_000 * scale)
    log2m = 30 if scale >= 0.1 else 24
    if layout == "blocked":
        cfg = FilterConfig(m=1 << log2m, k=10, key_len=16, block_bits=512)
        f = BlockedBloomFilter(cfg)
    else:
        cfg = FilterConfig(m=1 << log2m, k=10, key_len=16)
        f = BloomFilter(cfg)
    # B=1M, measured optimum at THIS shape (r5): the m=2^30 array is 8x
    # smaller than the north-star's, so whole-array-stream amortization
    # saturates by B=1M and larger batches only pay the sorts'
    # super-linear growth — B=8M measured 45.3M insert / 27.2M query
    # vs 60.7M / 33.1M at B=1M (config2_r5.json keeps the B=1M run).
    # The north-star m=2^32 shape is the opposite (b_sweep_r5.json).
    B = min(1 << 20, max(1 << 12, n // 8))
    # the whole insert stream runs inside ONE jit (lax.fori_loop over
    # device-generated batches): per-batch eager dispatch through the
    # axon tunnel costs seconds of RTT each and measured 80x slower
    # than the device work itself
    from jax import lax as _lax

    full_steps, tail = divmod(n, B)
    lengths = jnp.full((B,), 16, jnp.int32)

    def _keys(seed):
        return jax.random.bits(jax.random.key(seed), (B, 16), jnp.uint8)

    # jit the loop around the PURE insert kernel
    from tpubloom.filter import (
        blocked_storage_fat,
        make_blocked_insert_fn,
        make_insert_fn as _mk_flat,
    )

    if layout == "blocked":
        pure_insert = make_blocked_insert_fn(
            cfg, storage_fat=blocked_storage_fat(cfg)
        )
    else:
        pure_insert = _mk_flat(cfg)

    def _loop(words, n_steps):
        def body(i, w):
            return pure_insert(w, _keys(i), lengths)

        return _lax.fori_loop(0, n_steps, body, words)

    loop_jit = jax.jit(_loop, static_argnums=1, donate_argnums=0)
    def _tail_insert():
        # masked tail batch (its own jit cache entry): exactly `tail`
        # real keys land, the rest carry length -1 and set no bits
        iota = jnp.arange(B, dtype=jnp.int32)
        f.insert_arrays(
            _keys(full_steps), jnp.where(iota < tail, 16, -1), n_valid=tail
        )

    # warm-up compile UNTIMED: inserts are idempotent ORs of the same
    # seeded batches, so a full warm pass + clear leaves the timed pass
    # measuring steady-state device work (the fori_loop body compile is
    # tens of seconds and would otherwise dominate)
    f.words = loop_jit(f.words, full_steps)
    int(np.asarray(f.words.ravel()[0]))
    f.clear()
    t0 = time.perf_counter()
    f.words = loop_jit(f.words, full_steps)
    f.n_inserted += full_steps * B
    # to-value fence: block_until_ready can return early on this stack
    # (benchmarks/RESULTS_r3.md §1)
    int(np.asarray(f.words.ravel()[0]))
    t_insert = time.perf_counter() - t0
    n_timed = full_steps * B
    if tail:
        # the tail's single eager dispatch costs seconds of tunnel RTT
        # on this stack — insert it (the queries and fill ratio see all
        # n keys) but OUTSIDE the timed window, which reports the
        # steady-state rate over the n_timed loop keys
        _tail_insert()
        int(np.asarray(f.words.ravel()[0]))
    # mixed-hit queries: half present (replay seed 0), half absent — one
    # jitted loop, XOR-accumulated so the fence waits for ALL
    if layout == "blocked":
        from tpubloom.filter import make_blocked_query_fn

        pure_query = make_blocked_query_fn(
            cfg, storage_fat=blocked_storage_fat(cfg)
        )
    else:
        from tpubloom.filter import make_query_fn as _mk_q

        pure_query = _mk_q(cfg)
    q_steps = max(1, nq // B)

    def _qloop(words):
        def body(i, acc):
            ku = jax.random.bits(
                jax.random.key(jnp.where(i % 2 == 0, 0, 10**6)),
                (B, 16), jnp.uint8,
            )
            return acc ^ pure_query(words, ku, lengths)

        return _lax.fori_loop(
            0, q_steps, body, jnp.zeros((B,), bool)
        )

    qloop_jit = jax.jit(_qloop)
    acc = qloop_jit(f.words)  # warm-up compile untimed
    int(np.asarray(jnp.sum(acc.astype(jnp.uint32))))
    t0 = time.perf_counter()
    acc = qloop_jit(f.words)
    int(np.asarray(jnp.sum(acc.astype(jnp.uint32))))  # to-value fence
    t_query = time.perf_counter() - t0
    qdone = q_steps * B
    return {
        "config": 2,
        "layout": layout,
        "m": cfg.m,
        "n_insert": n,
        "n_insert_timed": n_timed,
        "n_query": qdone,
        "insert_keys_per_sec": round(n_timed / t_insert),
        "query_keys_per_sec": round(qdone / t_query),
        "fill_ratio": round(f.fill_ratio(), 4),
    }


def config3(scale: float) -> dict:
    """Streaming insert with periodic checkpoints (tmp-dir file sink)."""
    import tempfile

    from tpubloom import BloomFilter, FilterConfig
    from tpubloom import checkpoint as ckpt
    from tpubloom.parallel.pipeline import StreamInserter

    n = int(1_000_000_000 * scale)
    log2m = 34 if scale >= 0.1 else 24
    cfg = FilterConfig(m=1 << log2m, k=7, key_len=28, key_name="stream-bench")
    f = BloomFilter(cfg)
    with tempfile.TemporaryDirectory() as td:
        sink = ckpt.FileSink(td)
        ins = StreamInserter(
            f, batch_size=1 << 16, sink=sink, checkpoint_every=max(n // 10, 1 << 16)
        )
        t0 = time.perf_counter()
        stats = ins.run((b"warc-record-%014d" % i for i in range(n)))
        elapsed = time.perf_counter() - t0
        ins.close()
        return {
            "config": 3,
            "m": cfg.m,
            "n": n,
            "stream_keys_per_sec": round(n / elapsed),
            "checkpoints_written": ins.checkpointer.checkpoints_written,
        }


def config4(scale: float, layout: str = "flat") -> dict:
    """Counting filter insert/delete/query mix. ``--layout blocked``
    selects the blocked counting variant (Pallas sweep hot loop on TPU,
    ~6x the flat scatter rate on v5e)."""
    import numpy as np

    from tpubloom import BlockedCountingBloomFilter, CountingBloomFilter, FilterConfig

    n = int(10_000_000 * scale)
    log2m = 30 if scale >= 0.1 else 22
    if layout == "blocked":
        cfg = FilterConfig(
            m=1 << log2m, k=7, key_len=16, counting=True, block_bits=512
        )
        f = BlockedCountingBloomFilter(cfg)
    else:
        cfg = FilterConfig(m=1 << log2m, k=7, key_len=16, counting=True)
        f = CountingBloomFilter(cfg)
    keys_u8, _ = _gen_keys(n)
    keys = [bytes(k) for k in keys_u8]
    half = keys[: n // 2]
    t0 = time.perf_counter()
    f.insert_batch(keys)
    f.delete_batch(half)
    hits = f.include_batch(keys)
    elapsed = time.perf_counter() - t0
    assert hits[n // 2 :].all()
    return {
        "config": 4,
        "layout": layout,
        "m": cfg.m,
        "ops": 2 * n + n // 2,
        "ops_per_sec": round((2 * n + n // 2) / elapsed),
    }


def config5(scale: float, layout: str = "flat") -> dict:
    """64-shard filter array over the available mesh."""
    import jax
    import numpy as np

    from tpubloom import FilterConfig
    from tpubloom.parallel.sharded import ShardedBloomFilter

    n = int(10_000_000 * scale)
    n_dev = len(jax.devices())
    log2m = 36 if scale >= 0.1 and n_dev >= 8 else 24
    cfg = FilterConfig(
        m=1 << log2m, k=7, key_len=16, shards=64,
        block_bits=512 if layout == "blocked" else 0,
    )
    f = ShardedBloomFilter(cfg)
    keys_u8, lengths = _gen_keys(min(n, 1 << 18))
    t0 = time.perf_counter()
    done = 0
    while done < n:
        f.insert_arrays(keys_u8, lengths)  # idempotent re-insert: rate only
        done += len(keys_u8)
    f.block_until_ready()
    t_insert = time.perf_counter() - t0
    t0 = time.perf_counter()
    hits = np.asarray(f.include_arrays(keys_u8, lengths))
    t_query = time.perf_counter() - t0
    assert hits.all()
    return {
        "config": 5,
        "layout": layout,
        "m": cfg.m,
        "shards": 64,
        "devices": n_dev,
        "insert_keys_per_sec": round(done / t_insert),
        "query_keys_per_sec": round(len(keys_u8) / t_query),
    }


CONFIGS = {1: config1, 2: config2, 3: config3, 4: config4, 5: config5}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", type=int, required=True, choices=sorted(CONFIGS))
    ap.add_argument("--scale", type=float, default=None)
    ap.add_argument("--platform", choices=["cpu", "tpu"], default=None)
    ap.add_argument(
        "--layout", choices=["flat", "blocked"], default="flat",
        help="filter layout for device configs 2, 4 and 5",
    )
    args = ap.parse_args()

    import jax

    if args.platform == "cpu" or (
        args.platform is None and "cpu" in os.environ.get("JAX_PLATFORMS", "")
    ):
        jax.config.update("jax_platforms", "cpu")
    on_tpu = jax.default_backend() not in ("cpu",)
    scale = args.scale if args.scale is not None else (1.0 if on_tpu else 0.001)

    if args.config in (2, 4, 5):
        result = CONFIGS[args.config](scale, layout=args.layout)
    else:
        result = CONFIGS[args.config](scale)
    result["scale"] = scale
    result["platform"] = jax.default_backend()
    print(json.dumps(result))


if __name__ == "__main__":
    main()
