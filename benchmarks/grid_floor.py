#!/usr/bin/env python
"""Grid-step floor scaling: is the 2.45us/step cost fixed per step (then
bigger tiles amortize it) or blocks-bandwidth (then it scales with R)?

Runs the A0 kernel (no update DMA; out = blocks | scalar) at tile sizes
R in {512, 1024, 2048, 4096}, plus a no-aliasing variant at R=512.
"""

from __future__ import annotations

import functools
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpubloom.config import FilterConfig

LOG2M = 32
STEPS = 8
config = FilterConfig(m=1 << LOG2M, k=7, key_len=16, block_bits=512)
NB, W = config.n_blocks, config.words_per_block


def _u32(x):
    return jnp.asarray(x, jnp.uint32)


def _kernel(starts_ref, blocks_ref, out_ref):
    p = pl.program_id(0)
    out_ref[:] = blocks_ref[:] | _u32(starts_ref[p])


def run(R, alias=True):
    P = NB // R
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(P,),
        in_specs=[pl.BlockSpec((R, W), lambda p, *_: (p, 0))],
        out_specs=pl.BlockSpec((R, W), lambda p, *_: (p, 0)),
    )
    fn = pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((NB, W), jnp.uint32),
        grid_spec=grid_spec,
        input_output_aliases={1: 0} if alias else {},
    )
    starts = jnp.zeros((P + 1,), jnp.int32)

    def step(state, starts):
        out = fn(starts, state)
        return out, jnp.sum(out[:: NB // 64], dtype=jnp.uint32)

    jit = jax.jit(step, donate_argnums=(0,))
    state = jnp.zeros((NB, W), jnp.uint32)
    state, carry = jit(state, starts)
    carry.block_until_ready()
    t0 = time.perf_counter()
    for _ in range(STEPS):
        state, carry = jit(state, starts)
    carry.block_until_ready()
    dt = (time.perf_counter() - t0) / STEPS
    print(
        json.dumps(
            {
                "R": R, "P": P, "alias": alias,
                "ms": round(dt * 1e3, 3),
                "us_per_step": round(dt / P * 1e6, 3),
                "eff_GBps_inout": round(2 * NB * W * 4 / dt / 1e9, 1),
            }
        ),
        flush=True,
    )


def main():
    for R in (512, 1024, 2048, 4096, 8192):
        run(R)
    run(512, alias=False)


if __name__ == "__main__":
    main()
