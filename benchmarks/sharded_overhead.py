#!/usr/bin/env python
"""shard_map overhead on real hardware (VERDICT r3 #3 "done =" clause).

Compares, on the ONE real chip, the same total work:

  * single-chip fat-sweep insert on a BlockedBloomFilter of m total bits
  * ShardedBloomFilter on a 1-device mesh with 2 logical shards (routing
    hash + shard_map + per-device fat kernel + psum-OR query assembly)

Any difference is the sharded machinery's cost: the routing murmur pass,
the owned-mask plumbing, shard_map tracing overhead, and the psum (a
no-op collective on a 1-device mesh). Device-generated keys, to-value
timing. Writes benchmarks/out/sharded_overhead_r5.json.
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpubloom.config import FilterConfig
from tpubloom.filter import make_blocked_insert_fn, make_blocked_query_fn
from tpubloom.parallel import sharded as sh

LOG2M = 30  # 128 MiB of bits -> 2 x 64 MiB shards
B = 1 << 22
KEY_LEN = 16
STEPS = 8
OUT_PATH = os.path.join(
    os.path.dirname(__file__), "out", "sharded_overhead_r5.json"
)
_rows = []


def emit(obj):
    print(json.dumps(obj), flush=True)
    _rows.append(obj)


def _measure(step, state0, steps=STEPS):
    jit = jax.jit(step, donate_argnums=(0,))
    t0 = time.perf_counter()
    state, carry = jit(state0, jnp.uint32(0), 0)
    int(np.asarray(carry))
    compile_s = time.perf_counter() - t0
    state, carry = jit(state, carry, 1)
    int(np.asarray(carry))
    t0 = time.perf_counter()
    for i in range(2, 2 + steps):
        state, carry = jit(state, carry, i)
    int(np.asarray(carry))
    dt = (time.perf_counter() - t0) / steps
    del state, carry
    return dt, compile_s


def keygen(carry, i):
    return jax.random.bits(
        jax.random.key(i ^ (carry & 0xFFFF)), (B, KEY_LEN), jnp.uint8
    )


def main():
    lengths = jnp.full((B,), KEY_LEN, jnp.int32)

    # single chip, fat storage
    cfg1 = FilterConfig(m=1 << LOG2M, k=7, key_len=KEY_LEN, block_bits=512)
    ins1 = make_blocked_insert_fn(cfg1, storage_fat=True)
    qry1 = make_blocked_query_fn(cfg1, storage_fat=True)
    fat_shape = (cfg1.n_blocks * cfg1.words_per_block // 128, 128)

    def step1(state, carry, i):
        keys = keygen(carry, i)
        state = ins1(state, keys, lengths)
        hits = qry1(state, keys, lengths)
        return state, jnp.sum(hits.astype(jnp.uint32))

    dt1, c1 = _measure(step1, jnp.zeros(fat_shape, jnp.uint32))
    emit({
        "variant": "single-chip fat insert+query",
        "m": cfg1.m, "B": B,
        "ms_per_step": round(dt1 * 1e3, 2),
        "pairs_per_sec": round(B / dt1),
        "compile_s": round(c1, 1),
    })

    # 1-device mesh, 2 logical shards, same total m
    cfg2 = FilterConfig(
        m=1 << LOG2M, k=7, key_len=KEY_LEN, block_bits=512, shards=2
    )
    mesh = sh.make_mesh(2, jax.devices()[:1])
    ins2 = sh.make_sharded_blocked_insert_fn(cfg2, mesh)
    qry2 = sh.make_sharded_blocked_query_fn(cfg2, mesh)
    fat_local = cfg2.n_blocks_per_shard * cfg2.words_per_block // 128
    from jax.sharding import NamedSharding, PartitionSpec as P

    words0 = jax.device_put(
        jnp.zeros((2, fat_local, 128), jnp.uint32),
        NamedSharding(mesh, P(sh.AXIS, None, None)),
    )

    def step2(state, carry, i):
        keys = keygen(carry, i)
        state = ins2(state, keys, lengths)
        hits = qry2(state, keys, lengths)
        return state, jnp.sum(hits.astype(jnp.uint32))

    dt2, c2 = _measure(step2, words0)
    emit({
        "variant": "sharded (1-device mesh, 2 shards) insert+query",
        "m": cfg2.m, "B": B,
        "ms_per_step": round(dt2 * 1e3, 2),
        "pairs_per_sec": round(B / dt2),
        "compile_s": round(c2, 1),
        "overhead_vs_single_pct": round((dt2 / dt1 - 1) * 100, 1),
        "fat_local_storage": True,
    })
    os.makedirs(os.path.dirname(OUT_PATH), exist_ok=True)
    with open(OUT_PATH, "w") as f:
        for r in _rows:
            f.write(json.dumps(r) + "\n")


if __name__ == "__main__":
    main()
