#!/usr/bin/env python
"""Presence-kernel geometry sweep on REAL hardware (round 5).

The r4 chooser caps (``S*J*PACK <= 64`` bodies, per-body operand volume
<= 1.05M for presence) were measured against the OLD presence machinery
(G matmul + [R8, 512] tile bit expansion). The r5 extraction presence
kernel ([KJC, R8] @ [R8, 8W] int8 + nibble compares) has a much smaller
scoped-VMEM footprint, so geometries the old kernel OOMed may now
compile — and the r5 profile shows presence paying 2x the grid steps of
insert-only (S=2 vs S=4 at R8=256). This probes candidate (R8, S)
pairs directly: compile (Mosaic OOM surfaces as an exception), verify
(fresh-batch presence all-false, replay all-true, final bits identical
across geometries), and time a donated chain.

Results feed choose_fat_params' presence caps; the probe is the
measurement those constants cite.

Run: PYTHONPATH=/root/repo:$PYTHONPATH timeout 3000 python benchmarks/presence_geom.py
Writes benchmarks/out/presence_geom_r5.json.
"""

from __future__ import annotations

import json
import math
import os
import time
import traceback

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tpubloom.config import FilterConfig
from tpubloom.ops import blocked
from tpubloom.ops.sweep import (
    _fat_stream,
    _fat_unsort_presence,
    _pack_positions,
    _packed_rows,
    _unpack_positions,
    fat_pack,
    fat_sweep_insert,
)

LOG2M = 32
B = 1 << 22
KEY_LEN = 16
STEPS = 8

config = FilterConfig(m=1 << LOG2M, k=7, key_len=KEY_LEN, block_bits=512)
NB, W, K, BB = config.n_blocks, config.words_per_block, config.k, config.block_bits
J = 128 // W
NBJ = NB // J
FAT_SHAPE = (NBJ, 128)
PACK = fat_pack(W, True)

CANDIDATES = [  # (R8, S)
    (256, 2),  # shipping r4/r5 geometry
    (256, 4),  # insert-only's S — blocked by the old bodies<=64 cap
    (256, 8),
    (512, 1),
    (512, 2),
    (128, 4),
    (1024, 1),
]

OUT_PATH = os.path.join(os.path.dirname(__file__), "out", "presence_geom_r5.json")
_rows = []


def emit(obj):
    print(json.dumps(obj), flush=True)
    _rows.append(obj)
    os.makedirs(os.path.dirname(OUT_PATH), exist_ok=True)
    with open(OUT_PATH, "w") as f:
        for r in _rows:
            f.write(json.dumps(r) + "\n")


def _u32(x):
    return jnp.asarray(x, jnp.uint32)


def _kj_kbj(R8, S):
    lam = B * R8 // NB
    kj = max(16, (lam + max(16, int(8 * math.sqrt(lam))) + 7) // 8 * 8)
    kbj = ((lam * S + kj + 64 + 7) // 8) * 8
    return kj, kbj


def _stream_for(R8, KBJ, keys):
    lengths = jnp.full((B,), KEY_LEN, jnp.int32)
    blk, bit = blocked.block_positions(
        keys, lengths, n_blocks=NB, block_bits=BB, k=K, seed=config.seed,
        block_hash=config.block_hash,
    )
    P8 = NBJ // R8
    j_of = (blk % J).astype(jnp.uint32)
    rf_of = (blk // J).astype(jnp.uint32)
    skey = j_of * NBJ + rf_of
    cols, nbits, packed = _pack_positions(bit, BB, K)
    idx0 = jnp.arange(1, B + 1, dtype=jnp.uint32)
    sorted_cols = lax.sort((skey,) + cols + (idx0,), num_keys=1)
    ss = sorted_cols[0]
    bit_sorted = _unpack_positions(sorted_cols[1:-1], BB, K, nbits, packed)
    masks = blocked.build_masks(bit_sorted, W)
    return _fat_stream(
        ss, masks, sorted_cols[-1], J=J, NBJ=NBJ, P8=P8, R8=R8, KBJ=KBJ,
        W=W, pack=PACK,
    )


def main():
    emit({
        "shape": {
            "m": config.m, "k": K, "B": B, "block_bits": BB, "J": J,
            "pack": PACK, "platform": jax.default_backend(),
            "device": str(jax.devices()[0]),
            "timing": "to-value chained loop, donated state",
        }
    })
    keys = jax.device_put(
        np.random.default_rng(0).integers(0, 256, (B, KEY_LEN), np.uint8)
    )
    ref_fat = None
    for R8, S in CANDIDATES:
        P8 = NBJ // R8
        if P8 % S or (P8 // S) < 2:
            emit({"R8": R8, "S": S, "skip": "grid shape"})
            continue
        KJ, KBJ = _kj_kbj(R8, S)
        row = {"R8": R8, "S": S, "KJ": KJ, "KBJ": KBJ,
               "bodies": S * J * PACK,
               "volume": S * J * PACK * _packed_rows(KJ, PACK) * R8}
        try:
            upd, starts = jax.jit(
                lambda k, R8=R8, KBJ=KBJ: _stream_for(R8, KBJ, k)
            )(keys)
            kjc = PACK * _packed_rows(KJ, PACK)

            def step(state, u, st):
                new_fat, presb = fat_sweep_insert(
                    state, u, st, J=J, R8=R8, S=S, KJ=KJ, KBJ=KBJ, W=W,
                    with_presence=True, pack=PACK,
                )
                pres = _fat_unsort_presence(
                    presb, st, B, J=J, NBJ=NBJ, P8=P8, R8=R8, S=S,
                    KJ=kjc, KBJ=KBJ,
                )
                return new_fat, jnp.sum(pres.astype(jnp.uint32))

            jit = jax.jit(step, donate_argnums=(0,))
            t0 = time.perf_counter()
            state, n1 = jit(jnp.zeros(FAT_SHAPE, jnp.uint32), upd, starts)
            n1 = int(np.asarray(n1))
            row["compile_s"] = round(time.perf_counter() - t0, 1)
            state, n2 = jit(state, upd, starts)
            n2 = int(np.asarray(n2))
            row["pres_pass1"] = n1  # fresh batch: expect 0
            row["pres_pass2"] = n2  # replay: expect B
            if ref_fat is None:
                ref_fat = np.asarray(state)
                row["bits_vs_ref"] = "is-ref"
            else:
                row["bits_vs_ref"] = bool((np.asarray(state) == ref_fat).all())
            t0 = time.perf_counter()
            acc = None
            for i in range(STEPS):
                state, acc = jit(state, upd, starts)
            int(np.asarray(acc))
            dt = (time.perf_counter() - t0) / STEPS
            row["ms_per_step"] = round(dt * 1e3, 3)
            row["ok"] = (n1 == 0) and (n2 == B) and row["bits_vs_ref"] in (
                True, "is-ref"
            )
            del state
        except Exception as e:  # Mosaic OOM / lowering errors land here
            row["error"] = "".join(
                traceback.format_exception_only(type(e), e)
            )[:400]
            row["ok"] = False
        emit(row)


if __name__ == "__main__":
    main()
