#!/usr/bin/env python
"""Fused-step throughput vs batch size and keygen cost (VERDICT r3 #2b:
multi-batch amortization — bigger B amortizes the blocks stream and
shrinks relative window slack; plus: how much of the step is benchmark
keygen, not filter work).

Variants at m=2^32, k=7, blocked512, fat storage, presence fused:
  B in {2M, 4M, 8M}  x  keygen in {rng_bits, xor_fold}

xor_fold derives each step's keys from ONE persistent random buffer by
XOR-folding the step index into every 4-byte word — distinct uniform
keys per step at ~1 read of the buffer instead of a full threefry pass
(the filter still hashes all 16 bytes of every key; only the synthetic
key SOURCE gets cheaper, which is benchmark scaffolding, not filter
work).

To-value timing, >= 8 chained steps. Writes benchmarks/out/b_sweep_r5.json.
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpubloom.config import FilterConfig
from tpubloom.filter import make_blocked_test_insert_fn

KEY_LEN = 16
OUT_PATH = os.path.join(os.path.dirname(__file__), "out", "b_sweep_r5.json")
_rows = []


def emit(obj):
    print(json.dumps(obj), flush=True)
    _rows.append(obj)


def run(B, keygen_mode, steps=8):
    config = FilterConfig(m=1 << 32, k=7, key_len=KEY_LEN, block_bits=512)
    fat_rows = config.n_blocks * config.words_per_block // 128
    lengths = jnp.full((B,), KEY_LEN, jnp.int32)
    fn = make_blocked_test_insert_fn(config, storage_fat=True)
    base = jax.random.bits(jax.random.key(99), (B, KEY_LEN // 4), jnp.uint32)

    def step(state, carry, i):
        if keygen_mode == "rng_bits":
            keys = jax.random.bits(
                jax.random.key(i ^ (carry & 0xFFFF)), (B, KEY_LEN), jnp.uint8
            )
        else:  # xor_fold
            mixed = base ^ (
                jnp.uint32(i) * jnp.uint32(0x9E3779B9) ^ (carry & jnp.uint32(0xFFFF))
            )
            keys = jax.lax.bitcast_convert_type(mixed, jnp.uint8).reshape(
                B, KEY_LEN
            )
        state, present = fn(state, keys, lengths)
        return state, jnp.sum(present.astype(jnp.uint32))

    jit = jax.jit(step, donate_argnums=(0,))
    state = jnp.zeros((fat_rows, 128), jnp.uint32)
    t0 = time.perf_counter()
    state, carry = jit(state, jnp.uint32(0), 0)
    int(np.asarray(carry))
    compile_s = time.perf_counter() - t0
    state, carry = jit(state, carry, 1)
    int(np.asarray(carry))
    t0 = time.perf_counter()
    for i in range(2, 2 + steps):
        state, carry = jit(state, carry, i)
    int(np.asarray(carry))
    dt = (time.perf_counter() - t0) / steps
    emit({
        "B": B,
        "keygen": keygen_mode,
        "ms_per_step": round(dt * 1e3, 2),
        "fused_keys_per_sec": round(B / dt),
        "compile_s": round(compile_s, 1),
    })
    del state, carry


def main():
    emit({
        "shape": "m=2^32 k=7 blocked512 fat, fused test-and-insert",
        "platform": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "timing": "to-value, 8 chained steps",
    })
    # r5 (VERDICT r4 Weak #2): clean, uncontended re-run at B ∈ {4M, 8M,
    # 16M}. B=8M/16M double as the round-3 2(b) "accumulate N sorted
    # streams, sweep once" design: merging two sorted 4M streams on
    # device IS a full 8M-row sort (no cheaper merge primitive exists),
    # so the B row measures exactly that amortization.
    for B in (1 << 22, 1 << 23, 1 << 24):
        for mode in ("rng_bits", "xor_fold"):
            try:
                run(B, mode)
            except Exception as e:  # noqa: BLE001
                emit({"B": B, "keygen": mode, "error": str(e)[:300]})
    os.makedirs(os.path.dirname(OUT_PATH), exist_ok=True)
    with open(OUT_PATH, "w") as f:
        for r in _rows:
            f.write(json.dumps(r) + "\n")


if __name__ == "__main__":
    main()
