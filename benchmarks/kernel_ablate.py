#!/usr/bin/env python
"""In-kernel stage ablation of the partition sweep (VERDICT r1 task 1).

The R-sweep showed the kernel is per-partition-overhead-bound (per-step
time has a ~2us floor; MXU math alone predicts ~1us/step at R=512), so
this measures cumulative kernel variants at the north-star shape to
locate the microseconds:

  A stream-only     out = blocks | broadcast(buf row)  (grid + both DMAs)
  B +onehot+bits    one-hot row match + bit-plane expansion, trivial use
  C merge-free      delta_cnt = oh^T @ bits -> >0 -> pack matmuls (no
                    same-row merge machinery at all)
  D current         the shipping kernel (same/cnts/first merge)

C is also a candidate replacement: fewer stages, no [KMAX,KMAX] block.
Run: PYTHONPATH=... timeout 900 python benchmarks/kernel_ablate.py
"""

from __future__ import annotations

import functools
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpubloom.config import FilterConfig
from tpubloom.ops import blocked
from tpubloom.ops.sweep import (
    _ALIGN,
    _pack_positions,
    _stream_scaffold,
    _unpack_positions,
    choose_params,
    sweep_insert,
)

LOG2M = 32
B = 1 << 22
KEY_LEN = 16
STEPS = 8

config = FilterConfig(m=1 << LOG2M, k=7, key_len=KEY_LEN, block_bits=512)
NB, W, K, BB = config.n_blocks, config.words_per_block, config.k, config.block_bits
R, KMAX = choose_params(NB, B)
P = NB // R
lengths = jnp.full((B,), KEY_LEN, jnp.int32)


def _u32(x):
    return jnp.asarray(x, jnp.uint32)


def _ablate_kernel(
    starts_ref, upd_ref, blocks_ref, out_ref, sup_ref, sems,
    *, R, KMAX, W, LEVEL,
):
    p = pl.program_id(0)
    num_p = pl.num_programs(0)
    s0 = starts_ref[p]
    off0 = (s0 // _ALIGN) * _ALIGN

    def fetch(slot, off):
        cp = pltpu.make_async_copy(
            upd_ref.at[pl.ds(off, KMAX), :], sup_ref.at[slot], sems.at[slot]
        )
        cp.start()
        return cp

    def wait(slot):
        pltpu.make_async_copy(
            upd_ref.at[pl.ds(0, KMAX), :], sup_ref.at[slot], sems.at[slot]
        ).wait()

    slot = lax.rem(p, 2)

    @pl.when(p == 0)
    def _():
        fetch(0, off0)

    @pl.when(p + 1 < num_p)
    def _():
        fetch(1 - slot, (starts_ref[p + 1] // _ALIGN) * _ALIGN)

    wait(slot)
    buf = sup_ref[slot]  # [KMAX, 128] u32

    if LEVEL == "A":
        # consume the buffer without real compute: broadcast-OR one row's
        # mask words into the tile (wrong results, right memory traffic)
        row = buf[0:1, 1 : W + 1]  # [1, W]
        out_ref[:] = blocks_ref[:] | (row * _u32(0))  # keep DMA live, no-op OR
        return

    base = jnp.uint32(p * R)
    rl = (buf[:, 0:1] - base).astype(jnp.int32)
    colsR = lax.broadcasted_iota(jnp.int32, (KMAX, R), 1)
    ohf = jnp.where(rl == colsR, jnp.float32(1), jnp.float32(0))
    oh = ohf.astype(jnp.bfloat16)
    m = buf[:, 1 : W + 1]
    col512 = lax.broadcasted_iota(jnp.int32, (KMAX, W * 32), 1)
    rep = jnp.concatenate([m] * 32, axis=1)
    bits = (rep >> (col512 // W).astype(jnp.uint32)) & _u32(1)
    bitsf = bits.astype(jnp.int32).astype(jnp.float32).astype(jnp.bfloat16)

    if LEVEL == "B":
        # use oh + bits trivially: one matmul column-sum to keep both live
        colsum = lax.dot_general(
            oh, bitsf, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [R, 512]
        cheap = jnp.min(colsum, axis=1, keepdims=True)  # [R, 1]
        out_ref[:] = blocks_ref[:] | (
            cheap.astype(jnp.int32).astype(jnp.uint32) * _u32(0)
        )
        return

    # LEVEL == "C": merge-free delta — oh^T @ bits counts per (row, plane),
    # plane > 0 -> bit set; pack planes to words via exact matmuls.
    cnt = lax.dot_general(
        oh, bitsf, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [R, W*32] exact counts (f32 acc of 0/1 products)
    present = jnp.where(cnt > 0, jnp.float32(1), jnp.float32(0)).astype(
        jnp.bfloat16
    )
    ccol = lax.broadcasted_iota(jnp.int32, (W * 32, 4 * W), 0)
    hcol = lax.broadcasted_iota(jnp.int32, (W * 32, 4 * W), 1)
    b_of_c = ccol // W
    w_of_c = lax.rem(ccol, W)
    pack_w = jnp.where(
        (w_of_c + (b_of_c // 8) * W) == hcol,
        (1 << lax.rem(b_of_c, 8)).astype(jnp.float32),
        jnp.float32(0),
    ).astype(jnp.bfloat16)
    quarters = lax.dot_general(
        present, pack_w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [R, 4W] 8-bit quarters
    qcol = lax.broadcasted_iota(jnp.int32, (4 * W, W), 0)
    wcol = lax.broadcasted_iota(jnp.int32, (4 * W, W), 1)
    q_of = qcol // W
    w_of = lax.rem(qcol, W)
    comb_lo = jnp.where(
        (w_of == wcol) & (q_of < 2),
        jnp.where(q_of == 0, jnp.float32(1), jnp.float32(256)),
        jnp.float32(0),
    ).astype(jnp.bfloat16)
    comb_hi = jnp.where(
        (w_of == wcol) & (q_of >= 2),
        jnp.where(q_of == 2, jnp.float32(1), jnp.float32(256)),
        jnp.float32(0),
    ).astype(jnp.bfloat16)
    qb = quarters.astype(jnp.bfloat16)
    lo = lax.dot_general(
        qb, comb_lo, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    hi = lax.dot_general(
        qb, comb_hi, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    delta = lo.astype(jnp.int32).astype(jnp.uint32) | (
        hi.astype(jnp.int32).astype(jnp.uint32) << _u32(16)
    )
    out_ref[:] = blocks_ref[:] | delta


def run_variant(level, starts, upd):
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(P,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((R, W), lambda p, *_: (p, 0)),
        ],
        out_specs=pl.BlockSpec((R, W), lambda p, *_: (p, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, KMAX, 128), jnp.uint32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    fn = pl.pallas_call(
        functools.partial(_ablate_kernel, R=R, KMAX=KMAX, W=W, LEVEL=level),
        out_shape=jax.ShapeDtypeStruct((NB, W), jnp.uint32),
        grid_spec=grid_spec,
        input_output_aliases={2: 0},
    )

    def step(state, upd, starts):
        out = fn(starts, upd, state)
        return out, jnp.sum(out[:: NB // 64], dtype=jnp.uint32)

    jit = jax.jit(step, donate_argnums=(0,))
    state = jnp.zeros((NB, W), jnp.uint32)
    t0 = time.perf_counter()
    state, carry = jit(state, upd, starts)
    carry.block_until_ready()
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(STEPS):
        state, carry = jit(state, upd, starts)
    carry.block_until_ready()
    dt = (time.perf_counter() - t0) / STEPS
    print(
        json.dumps(
            {
                "variant": level,
                "ms": round(dt * 1e3, 3),
                "us_per_partition": round(dt / P * 1e6, 3),
                "keys_per_sec": round(B / dt),
                "compile_s": round(compile_s, 1),
            }
        ),
        flush=True,
    )
    return state


def build_stream(keys):
    blk, bit = blocked.block_positions(
        keys, lengths, n_blocks=NB, block_bits=BB, k=K, seed=config.seed,
        block_hash=config.block_hash,
    )
    blk = blk.astype(jnp.uint32)
    cols, nbits, packed = _pack_positions(bit, BB, K)
    sorted_cols = lax.sort((blk,) + cols, num_keys=1)
    bs = sorted_cols[0].astype(jnp.int32)
    bit_sorted = _unpack_positions(sorted_cols[1:], BB, K, nbits, packed)
    masks = blocked.build_masks(bit_sorted, W)
    starts, upd = _stream_scaffold(bs, NB, P, R, KMAX)
    upd = upd.at[:B, 1 : W + 1].set(masks)
    return starts, upd


def main():
    print(json.dumps({"R": R, "KMAX": KMAX, "P": P, "B": B}), flush=True)
    rng = np.random.default_rng(0)
    keys = jax.device_put(rng.integers(0, 256, (B, KEY_LEN), np.uint8))
    starts, upd = jax.jit(build_stream)(keys)
    starts.block_until_ready()
    for level in ("A", "B", "C"):
        run_variant(level, starts, upd)

    # D: the shipping kernel (no presence), same stream
    def step(state, upd, starts):
        out = sweep_insert(
            state, upd, starts, R=R, KMAX=KMAX, interpret=False,
            with_presence=False,
        )
        return out, jnp.sum(out[:: NB // 64], dtype=jnp.uint32)

    jit = jax.jit(step, donate_argnums=(0,))
    state = jnp.zeros((NB, W), jnp.uint32)
    state, carry = jit(state, upd, starts)
    carry.block_until_ready()
    t0 = time.perf_counter()
    for _ in range(STEPS):
        state, carry = jit(state, upd, starts)
    carry.block_until_ready()
    dt = (time.perf_counter() - t0) / STEPS
    print(
        json.dumps(
            {
                "variant": "D (shipping kernel)",
                "ms": round(dt * 1e3, 3),
                "us_per_partition": round(dt / P * 1e6, 3),
                "keys_per_sec": round(B / dt),
            }
        ),
        flush=True,
    )

    # C correctness cross-check vs D on the same stream
    state_c = run_variant("C", starts, upd)
    ok = bool(
        jnp.array_equal(
            state_c[:: NB // 4096], state[:: NB // 4096]
        )
    )
    print(json.dumps({"C_vs_D_sampled_equal": ok}), flush=True)


if __name__ == "__main__":
    main()
