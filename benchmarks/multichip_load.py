#!/usr/bin/env python
"""Multichip data-parallel serving load generator (ISSUE 11).

Makes aggregate **keys/sec/POD** the headline number: a real subprocess
server whose JAX backend exposes an N-device mesh (a real TPU slice, or
the ``--xla_force_host_platform_device_count=8`` CPU mesh this CI image
uses), one ``ShardedBloomFilter`` spanning every device, and the PR-10
ingestion coalescer feeding it — the 4.2× connection-scaling multiplied
by N-device data parallelism instead of fenced off from it (the PR-10
exclusion this PR lifts).

What the numbers mean:

* ``keys_per_sec_pod`` — the headline: aggregate end-to-end insert rate
  over ``CONNECTIONS`` concurrent connections into ONE mesh-sharded
  filter through the coalescer (gRPC + decode + coalesce + ONE
  ``shard_map`` launch per flush);
* ``single_conn_keys_per_sec`` — one connection's ping-pong rate
  against the same server (every request pays the full per-request cost
  serially, plus the coalesce window);
* ``per_request_keys_per_sec`` — the SAME aggregate load against a
  second server WITHOUT the coalescer: every RPC runs its own
  ``shard_map`` launch under the filter's op lock. This is what
  "sharded filters are excluded from the staged/packed paths" used to
  cost;
* ``scaling_vs_single`` — aggregate / single. GATE ``>= 2.0``;
* ``scaling_vs_per_request`` — aggregate / per-request. GATE ``>= 1.0``
  (coalesced sharded ingest must not lose to the per-request path);
* ``requests_per_flush`` — anti-gaming assert (``> 1.5``): the gates
  must not pass without actual coalescing.

All gates re-measure ONCE with a doubled window before failing (the
cluster_smoke / ingest_load discipline — a scheduler hiccup inside a
2 s window on a small shared runner must not read as a code defect).

Servers run on a forced 8-device CPU mesh by default so the bench runs
anywhere; ``--native-backend`` drops the forcing for a real TPU slice.
When the child backend still exposes fewer than 2 devices the run
reports ``{"skipped": ...}`` instead of failing (skip-clean, like
cluster_smoke on backends without what it needs).

Run directly (prints one JSON line) or via tier-1
(``tests/test_multichip.py::test_multichip_load_smoke``).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)
sys.path.insert(0, os.path.dirname(_HERE))  # repo root (script runs)

import ingest_load  # noqa: E402 — shared _hammer/_free_port/BATCH helpers

#: devices the forced CPU mesh exposes (and the shard count of the
#: served filter — one shard row per device).
DEVICES = 8
CONNECTIONS = 8
GATE_MULTI = 2.0  # aggregate vs one connection
GATE_VS_PER_REQUEST = 1.0  # coalesced vs the per-request sharded path

#: native-backend child: NO platform pin (ingest_load._CHILD hard-pins
#: cpu, which would turn a --native-backend run on a real TPU slice
#: into a silently-skipped 1-device CPU run)
_CHILD_NATIVE = """\
import sys
from tpubloom.server.service import main
main(sys.argv[1:])
"""


def _spawn(tmpdir: str, idx: int, extra_args: list, *, native: bool):
    # this bench GATES a ~1.3x coalesced-vs-per-request margin; the CI
    # chaos shard's armed lock tracker (TPUBLOOM_LOCK_CHECK=1, inherited
    # by subprocesses) taxes the coalescer's queue-condition churn far
    # more than the per-request path and measurably flips the
    # comparison — a perf gate must not measure the debug tracker.
    # Chaos/lock coverage for this path lives in tests/test_ingest.py.
    drop = ("TPUBLOOM_LOCK_CHECK", "TPUBLOOM_LOCK_CHECK_DIR")
    if native:
        return ingest_load._spawn(
            tmpdir, idx, extra_args, child_src=_CHILD_NATIVE,
            env_drop=drop + ("JAX_PLATFORMS",),
        )
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (
            flags + f" --xla_force_host_platform_device_count={DEVICES}"
        ).strip()
    return ingest_load._spawn(
        tmpdir, idx, extra_args,
        env_extra={"XLA_FLAGS": flags}, env_drop=drop,
    )


def _setup_filter(client, name: str, n_devices: int) -> None:
    """One mesh-spanning blocked512 sharded filter + jit-bucket warm-up
    (merged flush sizes pad to powers of two in [BATCH, C*BATCH]; each
    new padded shape is a fresh shard_map compile — without the warm-up
    the window measures XLA, not ingest)."""
    client.create_filter(
        name, capacity=1_000_000, error_rate=0.01,
        shards=n_devices, block_bits=512,
    )
    ingest_load._warm_buckets(client, name)


def _measure(addr: str, name: str, duration_s: float, stats_client) -> dict:
    """ingest_load's measurement with this bench's headline name: the
    aggregate over the mesh IS keys/sec/pod."""
    m = ingest_load._measure(addr, name, duration_s, stats_client)
    m["keys_per_sec_pod"] = m.pop("aggregate_keys_per_sec")
    m.pop("scaling_vs_linear", None)
    return m


def run_load(
    duration_s: float = 2.0,
    *,
    native: bool = False,
    coalesce_args: tuple = ("--coalesce-max-keys", "16384",
                            "--coalesce-max-wait-us", "2000"),
) -> dict:
    from tpubloom.server.client import BloomClient

    tmpdir = tempfile.mkdtemp(prefix="tpubloom-multichip-load-")
    procs: list = []
    out: dict = {
        "connections": CONNECTIONS, "batch": ingest_load.BATCH,
        "duration_s": duration_s,
    }
    try:
        proc, addr = _spawn(tmpdir, 0, list(coalesce_args), native=native)
        procs.append(proc)
        boot = BloomClient(addr)
        boot.wait_ready(timeout=240.0)
        health = boot.health()
        n_devices = len(health.get("devices") or ())
        out["devices"] = n_devices
        if n_devices < 2:
            # skip-clean: this backend cannot host a mesh (parity with
            # cluster_smoke's behavior on unsupported backends)
            out["skipped"] = (
                f"backend {health.get('backend')!r} exposes {n_devices} "
                f"device(s); multichip serving needs >= 2"
            )
            boot.close()
            return out
        _setup_filter(boot, "pod", n_devices)

        # the per-request control: same mesh, NO coalescer — every RPC
        # is its own shard_map launch under the filter op lock
        dproc, daddr = _spawn(tmpdir, 1, [], native=native)
        procs.append(dproc)
        direct = BloomClient(daddr)
        direct.wait_ready(timeout=240.0)
        _setup_filter(direct, "pod", n_devices)

        def measure_both(window: float) -> None:
            out.update(_measure(addr, "pod", window, boot))
            out["per_request_keys_per_sec"] = round(
                ingest_load._hammer(daddr, "pod", CONNECTIONS, window)
            )
            out["scaling_vs_per_request"] = round(
                out["keys_per_sec_pod"] / out["per_request_keys_per_sec"], 3
            )

        measure_both(duration_s)
        if (
            out["scaling_vs_single"] < GATE_MULTI
            or out["scaling_vs_per_request"] < GATE_VS_PER_REQUEST
            or out["requests_per_flush"] <= 1.5
        ):
            # one re-measure with a doubled window before failing (the
            # cluster_smoke discipline: zero-margin comparisons on a
            # 2-vCPU shared runner deserve a second look, not a red CI)
            out["remeasured"] = True
            measure_both(duration_s * 2)
        boot.close()
        direct.close()
        assert out["scaling_vs_single"] >= GATE_MULTI, (
            f"coalesced mesh aggregate ({out['keys_per_sec_pod']} keys/s "
            f"over {CONNECTIONS} connections, {n_devices} devices) is only "
            f"{out['scaling_vs_single']}x one connection "
            f"({out['single_conn_keys_per_sec']}) — gate {GATE_MULTI}x"
        )
        assert out["scaling_vs_per_request"] >= GATE_VS_PER_REQUEST, (
            f"coalesced sharded ingest ({out['keys_per_sec_pod']} keys/s) "
            f"lost to the per-request sharded path "
            f"({out['per_request_keys_per_sec']} keys/s) — the coalescer "
            f"must FEED the mesh, not slow it down"
        )
        assert out["requests_per_flush"] > 1.5, (
            f"only {out['requests_per_flush']} requests/flush — the gates "
            f"passed without actual coalescing"
        )
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.send_signal(signal.SIGKILL)
        for proc in procs:
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                pass
    return out


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    native = "--native-backend" in sys.argv[1:]
    print(json.dumps(run_load(native=native)))
