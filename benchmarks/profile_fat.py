#!/usr/bin/env python
"""Per-stage breakdown of the FAT fused sweep pipeline (VERDICT r3 #2).

The round-3 profiler (profile_sweep.py) measured the legacy kernel; this
one measures the shipping fat-row pipeline (ops.sweep.apply_fat_updates)
at the north-star shape (m=2^32, k=7, B=4M, blocked512, fat storage),
with TO-VALUE timing (block_until_ready can lie on this stack — see
benchmarks/RESULTS_r3.md §1): every loop forces a host value from a
carry that depends on all outputs, >= 16 chained steps.

Stages (cumulative prefixes; deltas reported at the end):

  P0 keygen       device RNG [B, 16] u8
  P1 +hash        block_positions (3x murmur + fnv over 16B keys)
  P2 +sort        skey + pack positions + 4-col lax.sort (presence) /
                  3-col (insert-only)
  P3 +masks       unpack + build_masks [B, W]
  P4 +stream      _fat_stream ([Btot, 128] buffer) + searchsorted starts
  P5 +kernel      fat_sweep_insert (Pallas fat grid sweep)
  P6 full         apply_fat_updates (+ presence unsort + overflow cond)

Also: kernel-only on a prebuilt stream, and lax.sort operand scaling.
Run: timeout 2400 python -m benchmarks.profile_fat [--insert-only] [--b8m]
Writes benchmarks/out/profile_fat_r5.json — or profile_fat_b8m_r5.json
with --b8m (B=8M, the shipping bench batch) — one JSON object per line.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tpubloom.config import FilterConfig
from tpubloom.ops import blocked
from tpubloom.ops.sweep import (
    _fat_stream,
    _fat_unsort_presence,
    _fat_window_overflow,
    _pack_positions,
    _packed_rows,
    _unpack_positions,
    apply_fat_updates,
    choose_fat_params,
    fat_pack,
    fat_sweep_insert,
)

LOG2M = 32
B8M = "--b8m" in sys.argv  # shipping bench batch; drives B AND the out path
B = 1 << 23 if B8M else 1 << 22
KEY_LEN = 16
STEPS = 16
PRESENCE = "--insert-only" not in sys.argv

config = FilterConfig(m=1 << LOG2M, k=7, key_len=KEY_LEN, block_bits=512)
NB, W, K, BB = config.n_blocks, config.words_per_block, config.k, config.block_bits
PARAMS = choose_fat_params(NB, B, W, presence=PRESENCE)
J, R8, S, KJ, KBJ = PARAMS
PACK = fat_pack(W, PRESENCE)
KJP = _packed_rows(KJ, PACK)
NBJ = NB // J
P8 = NBJ // R8
FAT_SHAPE = (NB * W // 128, 128)
lengths = jnp.full((B,), KEY_LEN, jnp.int32)

OUT_PATH = os.path.join(
    os.path.dirname(__file__), "out",
    "profile_fat_b8m_r5.json" if B8M else "profile_fat_r5.json",
)
_rows = []


def emit(obj):
    print(json.dumps(obj), flush=True)
    _rows.append(obj)


def _u32(x):
    return jnp.asarray(x, jnp.uint32)


def keygen(carry, i):
    return jax.random.bits(
        jax.random.key(i ^ (carry & 0xFFFF)), (B, KEY_LEN), jnp.uint8
    )


def _positions(keys):
    return blocked.block_positions(
        keys, lengths, n_blocks=NB, block_bits=BB, k=K, seed=config.seed,
        block_hash=config.block_hash,
    )


def _skey(blk, valid):
    blkv = jnp.where(valid, blk, NB)
    j_of = (blkv % J).astype(jnp.uint32)
    rf_of = (blkv // J).astype(jnp.uint32)
    return jnp.where(valid, j_of * NBJ + rf_of, _u32(J * NBJ))


def _sorted_cols(keys):
    blk, bit = _positions(keys)
    valid = jnp.ones((B,), bool)
    skey = _skey(blk, valid)
    cols, nbits, packed = _pack_positions(bit, BB, K)
    extra = (jnp.arange(1, B + 1, dtype=jnp.uint32),) if PRESENCE else ()
    sorted_cols = lax.sort((skey,) + cols + extra, num_keys=1)
    return sorted_cols, nbits, packed


def _stream(keys):
    sorted_cols, nbits, packed = _sorted_cols(keys)
    ss = sorted_cols[0]
    pcols = sorted_cols[1:-1] if PRESENCE else sorted_cols[1:]
    bit_sorted = _unpack_positions(pcols, BB, K, nbits, packed)
    masks = blocked.build_masks(bit_sorted, W)
    idx_sorted = sorted_cols[-1] if PRESENCE else None
    return _fat_stream(
        ss, masks, idx_sorted, J=J, NBJ=NBJ, P8=P8, R8=R8, KBJ=KBJ, W=W,
        pack=PACK,
    )


def p0(state, carry, i):
    keys = keygen(carry, i)
    return state, jnp.sum(keys, dtype=jnp.uint32)


def p1(state, carry, i):
    keys = keygen(carry, i)
    blk, bit = _positions(keys)
    return state, jnp.sum(blk.astype(jnp.uint32)) + jnp.sum(bit)


def p2(state, carry, i):
    keys = keygen(carry, i)
    sorted_cols, _, _ = _sorted_cols(keys)
    return state, sum(jnp.sum(c) for c in sorted_cols)


def p3(state, carry, i):
    keys = keygen(carry, i)
    sorted_cols, nbits, packed = _sorted_cols(keys)
    pcols = sorted_cols[1:-1] if PRESENCE else sorted_cols[1:]
    bit_sorted = _unpack_positions(pcols, BB, K, nbits, packed)
    masks = blocked.build_masks(bit_sorted, W)
    return state, jnp.sum(masks) + jnp.sum(sorted_cols[0])


def p4(state, carry, i):
    keys = keygen(carry, i)
    upd, starts = _stream(keys)
    return state, jnp.sum(upd, dtype=jnp.uint32) + jnp.sum(starts).astype(
        jnp.uint32
    )


def p5(state, carry, i):
    keys = keygen(carry, i)
    upd, starts = _stream(keys)
    if PRESENCE:
        new_fat, presb = fat_sweep_insert(
            state, upd, starts, J=J, R8=R8, S=S, KJ=KJ, KBJ=KBJ, W=W,
            with_presence=True, pack=PACK,
        )
        return new_fat, jnp.sum(presb, dtype=jnp.uint32)
    new_fat = fat_sweep_insert(
        state, upd, starts, J=J, R8=R8, S=S, KJ=KJ, KBJ=KBJ, W=W, pack=PACK
    )
    return new_fat, jnp.sum(new_fat[:: max(1, FAT_SHAPE[0] // 64)], dtype=jnp.uint32)


def p6(state, carry, i):
    keys = keygen(carry, i)
    blk, bit = _positions(keys)
    valid = jnp.ones((B,), bool)
    if PRESENCE:
        idx0 = jnp.arange(1, B + 1, dtype=jnp.uint32)
        new_fat, present = apply_fat_updates(
            state, blk, bit, valid, block_bits=BB, params=PARAMS,
            idx=idx0, storage_fat=True,
        )
        return new_fat, jnp.sum(present.astype(jnp.uint32))
    new_fat = apply_fat_updates(
        state, blk, bit, valid, block_bits=BB, params=PARAMS, storage_fat=True,
    )
    return new_fat, jnp.sum(new_fat[:: max(1, FAT_SHAPE[0] // 64)], dtype=jnp.uint32)


def run(name, step, steps=STEPS):
    state0 = jnp.zeros(FAT_SHAPE, jnp.uint32)
    jit = jax.jit(step, donate_argnums=(0,))
    t0 = time.perf_counter()
    state, carry = jit(state0, _u32(0), 0)
    int(np.asarray(carry))  # to-value: compile + first step
    compile_s = time.perf_counter() - t0
    state, carry = jit(state, carry, 1)
    int(np.asarray(carry))  # warm second call (jit cache, donation path)
    t0 = time.perf_counter()
    for i in range(2, 2 + steps):
        state, carry = jit(state, carry, i)
    val = int(np.asarray(carry))  # ONE host fetch after the chained loop
    dt = (time.perf_counter() - t0) / steps
    emit({
        "stage": name,
        "ms_per_step": round(dt * 1e3, 3),
        "ns_per_key": round(dt / B * 1e9, 3),
        "compile_s": round(compile_s, 1),
        "carry": val & 0xFFFF,
    })
    del state, carry
    return dt


def kernel_only():
    keys = jax.device_put(
        np.random.default_rng(0).integers(0, 256, (B, KEY_LEN), np.uint8)
    )
    upd, starts = jax.jit(_stream)(keys)
    int(np.asarray(starts[0]))

    def step(state, upd, starts):
        if PRESENCE:
            new_fat, presb = fat_sweep_insert(
                state, upd, starts, J=J, R8=R8, S=S, KJ=KJ, KBJ=KBJ, W=W,
                with_presence=True, pack=PACK,
            )
            return new_fat, jnp.sum(presb, dtype=jnp.uint32)
        new_fat = fat_sweep_insert(
            state, upd, starts, J=J, R8=R8, S=S, KJ=KJ, KBJ=KBJ, W=W, pack=PACK
        )
        return new_fat, jnp.sum(
            new_fat[:: max(1, FAT_SHAPE[0] // 64)], dtype=jnp.uint32
        )

    jit = jax.jit(step, donate_argnums=(0,))
    state = jnp.zeros(FAT_SHAPE, jnp.uint32)
    state, carry = jit(state, upd, starts)
    int(np.asarray(carry))
    t0 = time.perf_counter()
    for _ in range(STEPS):
        state, carry = jit(state, upd, starts)
    int(np.asarray(carry))
    dt = (time.perf_counter() - t0) / STEPS
    emit({
        "stage": f"kernel_only(prebuilt stream, presence={PRESENCE})",
        "ms_per_step": round(dt * 1e3, 3),
        "ns_per_key": round(dt / B * 1e9, 3),
    })


def unsort_only():
    """The presence unsort in isolation (single-column vkey sort)."""
    if not PRESENCE:
        return
    P = P8 // S
    presb = jax.random.bits(jax.random.key(3), (P * PACK * KJP, 128), jnp.uint32)
    keys = jax.device_put(
        np.random.default_rng(0).integers(0, 256, (B, KEY_LEN), np.uint8)
    )
    _, starts = jax.jit(_stream)(keys)

    def step(presb, carry):
        pres = _fat_unsort_presence(
            presb ^ carry, starts, B, J=J, NBJ=NBJ, P8=P8, R8=R8, S=S,
            KJ=PACK * KJP, KBJ=KBJ,
        )
        return jnp.sum(pres.astype(jnp.uint32))

    jit = jax.jit(step)
    carry = jit(presb, _u32(0))
    int(np.asarray(carry))
    t0 = time.perf_counter()
    for _ in range(STEPS):
        carry = jit(presb, carry)
    int(np.asarray(carry))
    dt = (time.perf_counter() - t0) / STEPS
    emit({
        "stage": "unsort_only(vkey single-col sort)",
        "ms_per_step": round(dt * 1e3, 3),
        "ns_per_key": round(dt / B * 1e9, 3),
        "rows_sorted": J * P8 * PACK * KJP,
    })


def sort_scaling():
    cols = [jax.random.bits(jax.random.fold_in(jax.random.key(7), i), (B,),
                            jnp.uint32) for i in range(5)]
    for nc in (1, 2, 3, 4):
        def step(carry, nc=nc):
            key0 = cols[0] ^ carry
            out = lax.sort(tuple([key0] + cols[1:nc]), num_keys=1)
            return sum(jnp.sum(c) for c in out).astype(jnp.uint32)

        jit = jax.jit(step)
        carry = jit(_u32(0))
        int(np.asarray(carry))
        t0 = time.perf_counter()
        for _ in range(STEPS):
            carry = jit(carry)
        int(np.asarray(carry))
        dt = (time.perf_counter() - t0) / STEPS
        emit({
            "stage": f"lax.sort {nc} u32 cols, B={B >> 20}M",
            "ms_per_step": round(dt * 1e3, 3),
            "ns_per_key": round(dt / B * 1e9, 3),
        })


def hash_parts():
    """Hash front-end in isolation: murmur passes vs position assembly."""
    from tpubloom.ops import hashing

    def h_only(carry, i):
        keys = keygen(carry, i)
        h = hashing.murmur3_32(keys, lengths, config.seed)
        return jnp.sum(h)

    def pos_only(carry, i):
        keys = keygen(carry, i)
        blk, bit = _positions(keys)
        return jnp.sum(blk.astype(jnp.uint32)) + jnp.sum(bit)

    for name, fn in [("murmur3 x1", h_only), ("block_positions", pos_only)]:
        jit = jax.jit(fn)
        carry = jit(_u32(0), 0)
        int(np.asarray(carry))
        t0 = time.perf_counter()
        for i in range(STEPS):
            carry = jit(carry, i)
        int(np.asarray(carry))
        dt = (time.perf_counter() - t0) / STEPS
        emit({
            "stage": f"hash:{name}",
            "ms_per_step": round(dt * 1e3, 3),
            "ns_per_key": round(dt / B * 1e9, 3),
        })


def main():
    emit({
        "shape": {
            "m": config.m, "k": K, "B": B, "block_bits": BB, "n_blocks": NB,
            "W": W, "J": J, "R8": R8, "S": S, "KJ": KJ, "KBJ": KBJ, "pack": PACK,
            "presence": PRESENCE,
            "platform": jax.default_backend(),
            "device": str(jax.devices()[0]),
            "timing": "to-value (int(np.asarray(carry)) after chained loop)",
        }
    })
    prev = 0.0
    deltas = {}
    for name, fn in [
        ("P0 keygen", p0),
        ("P1 +hash", p1),
        ("P2 +sort", p2),
        ("P3 +masks", p3),
        ("P4 +stream", p4),
        ("P5 +kernel", p5),
        ("P6 full fused", p6),
    ]:
        dt = run(name, fn)
        deltas[name] = dt - prev
        prev = dt
    emit({
        "deltas_ms": {k: round(v * 1e3, 3) for k, v in deltas.items()},
        "fused_keys_per_sec": round(B / prev),
    })
    kernel_only()
    unsort_only()
    sort_scaling()
    hash_parts()
    os.makedirs(os.path.dirname(OUT_PATH), exist_ok=True)
    with open(OUT_PATH, "w") as f:
        for r in _rows:
            f.write(json.dumps(r) + "\n")


if __name__ == "__main__":
    main()
