#!/usr/bin/env python
"""Per-stage breakdown of the fused sweep step (VERDICT r1 task 1).

Measures cumulative prefixes of the fused test-and-insert pipeline at the
north-star shape (m=2^32, k=7, B=4M, blocked512) with honest chained
timing (carry-fed seeds + block_until_ready once per loop; see
.claude/skills/verify/SKILL.md benchmarking rules), then reports
per-stage deltas. Stages:

  P0 keygen       device RNG [B, 16] u8
  P1 +hash        block_positions (3x murmur/fnv over 16B keys)
  P2 +sort        pack positions + 4-column lax.sort (blk, lo, hi, idx)
  P3 +masks       unpack + build_masks [B, W]
  P4 +stream      searchsorted + [B+pad, 128] u32 update buffer build
  P5 +kernel      sweep_insert with_presence (the Pallas grid sweep)
  P6 full         + presence unsort + overflow cond (make_sweep_insert_fn)

Also measured: kernel-only (pre-built stream, re-applied each step) to
split stream-build cost from in-kernel DMA+MXU cost.

Prints one JSON line per measurement; run via
  timeout 900 python benchmarks/profile_sweep.py
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tpubloom.config import FilterConfig
from tpubloom.ops import blocked
from tpubloom.ops.sweep import (
    _pack_positions,
    _stream_scaffold,
    _unpack_positions,
    choose_params,
    make_sweep_insert_fn,
    sweep_insert,
)

LOG2M = 32
B = 1 << 22
KEY_LEN = 16
STEPS = 8

config = FilterConfig(m=1 << LOG2M, k=7, key_len=KEY_LEN, block_bits=512)
NB, W, K, BB = config.n_blocks, config.words_per_block, config.k, config.block_bits
R, KMAX = choose_params(NB, B)
P = NB // R
lengths = jnp.full((B,), KEY_LEN, jnp.int32)


def _u32(x):
    return jnp.asarray(x, jnp.uint32)


def keygen(seed_carry, i):
    return jax.random.bits(
        jax.random.key(i ^ (seed_carry & 0xFFFF)), (B, KEY_LEN), jnp.uint8
    )


def p0(state, carry, i):
    keys = keygen(carry, i)
    return state, jnp.sum(keys.astype(jnp.uint32))


def p1(state, carry, i):
    keys = keygen(carry, i)
    blk, bit = blocked.block_positions(
        keys, lengths, n_blocks=NB, block_bits=BB, k=K, seed=config.seed,
        block_hash=config.block_hash,
    )
    return state, jnp.sum(blk.astype(jnp.uint32)) + jnp.sum(bit)


def _sorted_cols(keys):
    blk, bit = blocked.block_positions(
        keys, lengths, n_blocks=NB, block_bits=BB, k=K, seed=config.seed,
        block_hash=config.block_hash,
    )
    cols, nbits, packed = _pack_positions(bit, BB, K)
    idx0 = jnp.arange(1, B + 1, dtype=jnp.uint32)
    sorted_cols = lax.sort((blk.astype(jnp.uint32),) + cols + (idx0,), num_keys=1)
    return sorted_cols, nbits, packed


def p2(state, carry, i):
    keys = keygen(carry, i)
    sorted_cols, _, _ = _sorted_cols(keys)
    return state, sum(jnp.sum(c) for c in sorted_cols)


def p3(state, carry, i):
    keys = keygen(carry, i)
    sorted_cols, nbits, packed = _sorted_cols(keys)
    bit_sorted = _unpack_positions(sorted_cols[1:-1], BB, K, nbits, packed)
    masks = blocked.build_masks(bit_sorted, W)
    return state, jnp.sum(masks) + jnp.sum(sorted_cols[0])


def _stream(keys):
    sorted_cols, nbits, packed = _sorted_cols(keys)
    bs = sorted_cols[0].astype(jnp.int32)
    bit_sorted = _unpack_positions(sorted_cols[1:-1], BB, K, nbits, packed)
    masks = blocked.build_masks(bit_sorted, W)
    starts, upd = _stream_scaffold(bs, NB, P, R, KMAX)
    upd = upd.at[:B, 1 : W + 1].set(masks)
    upd = upd.at[:B, W + 1].set(sorted_cols[-1])
    return starts, upd


def p4(state, carry, i):
    keys = keygen(carry, i)
    starts, upd = _stream(keys)
    return state, jnp.sum(upd, dtype=jnp.uint32)[()] + jnp.sum(starts).astype(
        jnp.uint32
    )


def p5(state, carry, i):
    keys = keygen(carry, i)
    starts, upd = _stream(keys)
    new_blocks, pres = sweep_insert(
        state, upd, starts, R=R, KMAX=KMAX, interpret=False, with_presence=True
    )
    return new_blocks, jnp.sum(pres, dtype=jnp.uint32)


_full_fn = make_sweep_insert_fn(config, interpret=False, with_presence=True)


def p6(state, carry, i):
    keys = keygen(carry, i)
    new_blocks, present = _full_fn(state, keys, lengths)
    return new_blocks, jnp.sum(present.astype(jnp.uint32))


def run(name, step, donate=True, steps=STEPS):
    state0 = jnp.zeros((NB, W), jnp.uint32)
    jit = jax.jit(step, donate_argnums=(0,) if donate else ())
    t0 = time.perf_counter()
    state, carry = jit(state0, _u32(0), 0)
    carry.block_until_ready()
    compile_s = time.perf_counter() - t0
    state, carry = jit(state, carry, 1)  # warm
    t0 = time.perf_counter()
    for i in range(2, 2 + steps):
        state, carry = jit(state, carry, i)
    carry.block_until_ready()
    dt = (time.perf_counter() - t0) / steps
    out = {
        "stage": name,
        "ms_per_step": round(dt * 1e3, 3),
        "ns_per_key": round(dt / B * 1e9, 3),
        "compile_s": round(compile_s, 1),
    }
    print(json.dumps(out), flush=True)
    del state, carry
    return dt


def kernel_only():
    """Sweep kernel on a pre-built stream: isolates DMA + MXU from the
    stream build. Chained via the donated blocks state; the stream is
    rebuilt-free (same updates re-applied — ORs are idempotent, counts
    of work identical)."""
    keys = jax.device_put(
        np.random.default_rng(0).integers(0, 256, (B, KEY_LEN), np.uint8)
    )
    starts, upd = jax.jit(_stream)(keys)
    starts.block_until_ready()

    def step(state, upd, starts):
        new_blocks, pres = sweep_insert(
            state, upd, starts, R=R, KMAX=KMAX, interpret=False, with_presence=True
        )
        return new_blocks, jnp.sum(pres, dtype=jnp.uint32)

    jit = jax.jit(step, donate_argnums=(0,))
    state = jnp.zeros((NB, W), jnp.uint32)
    t0 = time.perf_counter()
    state, carry = jit(state, upd, starts)
    carry.block_until_ready()
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(STEPS):
        state, carry = jit(state, upd, starts)
    carry.block_until_ready()
    dt = (time.perf_counter() - t0) / STEPS
    print(
        json.dumps(
            {
                "stage": "kernel_only(prebuilt stream, with_presence)",
                "ms_per_step": round(dt * 1e3, 3),
                "ns_per_key": round(dt / B * 1e9, 3),
                "compile_s": round(compile_s, 1),
            }
        ),
        flush=True,
    )
    # and without presence (pure insert sweep)
    def step2(state, upd, starts):
        nb = sweep_insert(
            state, upd, starts, R=R, KMAX=KMAX, interpret=False, with_presence=False
        )
        return nb, jnp.sum(nb[:: NB // 64], dtype=jnp.uint32)

    jit2 = jax.jit(step2, donate_argnums=(0,))
    state = jnp.zeros((NB, W), jnp.uint32)
    state, carry = jit2(state, upd, starts)
    carry.block_until_ready()
    t0 = time.perf_counter()
    for _ in range(STEPS):
        state, carry = jit2(state, upd, starts)
    carry.block_until_ready()
    dt2 = (time.perf_counter() - t0) / STEPS
    print(
        json.dumps(
            {
                "stage": "kernel_only(prebuilt stream, insert only)",
                "ms_per_step": round(dt2 * 1e3, 3),
                "ns_per_key": round(dt2 / B * 1e9, 3),
            }
        ),
        flush=True,
    )


def sort_scaling():
    """lax.sort cost vs payload column count at B=4M."""
    rng = jax.random.key(7)
    cols = [jax.random.bits(jax.random.fold_in(rng, i), (B,), jnp.uint32)
            for i in range(5)]

    for nc in (1, 2, 4, 5):
        def step(carry, i, nc=nc):
            key0 = cols[0] ^ carry
            out = lax.sort(tuple([key0] + cols[1:nc]), num_keys=1)
            return sum(jnp.sum(c) for c in out).astype(jnp.uint32)

        jit = jax.jit(step)
        carry = jit(_u32(0), 0)
        carry.block_until_ready()
        t0 = time.perf_counter()
        for i in range(STEPS):
            carry = jit(carry, i)
        carry.block_until_ready()
        dt = (time.perf_counter() - t0) / STEPS
        print(
            json.dumps(
                {
                    "stage": f"lax.sort {nc} u32 cols, B=4M",
                    "ms_per_step": round(dt * 1e3, 3),
                    "ns_per_key": round(dt / B * 1e9, 3),
                }
            ),
            flush=True,
        )


def main():
    print(
        json.dumps(
            {
                "shape": {
                    "m": config.m, "k": K, "B": B, "block_bits": BB,
                    "n_blocks": NB, "W": W, "R": R, "KMAX": KMAX, "P": P,
                    "platform": jax.default_backend(),
                    "device": str(jax.devices()[0]),
                }
            }
        ),
        flush=True,
    )
    prev = 0.0
    deltas = {}
    for name, fn in [
        ("P0 keygen", p0),
        ("P1 +hash", p1),
        ("P2 +sort", p2),
        ("P3 +masks", p3),
        ("P4 +stream", p4),
        ("P5 +kernel", p5),
        ("P6 full fused", p6),
    ]:
        dt = run(name, fn)
        deltas[name] = dt - prev
        prev = dt
    print(
        json.dumps(
            {
                "deltas_ms": {k: round(v * 1e3, 3) for k, v in deltas.items()},
                "deltas_ns_per_key": {
                    k: round(v / B * 1e9, 3) for k, v in deltas.items()
                },
            }
        ),
        flush=True,
    )
    kernel_only()
    sort_scaling()


if __name__ == "__main__":
    main()
