#!/usr/bin/env python
"""L4 transport-path throughput in ISOLATION (VERDICT r4 Missing #5).

Every prior e2e number rode the axon tunnel (0.2–20 MB/s weather), so
the repo had no honest figure for what the gRPC+msgpack+service layer
itself costs. This measures it on loopback with a CPU-backend filter,
three layers deep so the costs separate:

  L0 filter-only    BlockedBloomFilter.insert_batch / include_batch
                    (the jitted CPU kernel work, no serialization)
  L1 +service       BloomService.InsertBatch(req dict) in-process
                    (adds msgpack encode/decode of the SAME batches)
  L2 +gRPC          BloomClient against a loopback grpc.Server
                    (adds HTTP/2 framing + socket + thread hop)

The transport overhead of interest is (L2 - L1) and the encode cost
(L1 - L0), reported per batch size. Single-core host: client and server
share the core, which is the honest worst case for loopback.

Run: JAX_PLATFORMS=cpu PYTHONPATH=/root/repo:$PYTHONPATH python benchmarks/grpc_path.py
Writes benchmarks/out/grpc_path_r5.json (one JSON object per line).
"""

from __future__ import annotations

import json
import os
import time

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from tpubloom.config import FilterConfig  # noqa: E402
from tpubloom.filter import BlockedBloomFilter  # noqa: E402
from tpubloom.server import protocol  # noqa: E402
from tpubloom.server.client import BloomClient  # noqa: E402
from tpubloom.server.service import BloomService, build_server  # noqa: E402

KEY_LEN = 16
BATCHES = (4_096, 65_536, 524_288)
REPS = {4_096: 16, 65_536: 8, 524_288: 4}

OUT_PATH = os.path.join(os.path.dirname(__file__), "out", "grpc_path_r5.json")
_rows = []


def emit(obj):
    print(json.dumps(obj), flush=True)
    _rows.append(obj)
    os.makedirs(os.path.dirname(OUT_PATH), exist_ok=True)
    with open(OUT_PATH, "w") as f:
        for r in _rows:
            f.write(json.dumps(r) + "\n")


def _config():
    # m=2^24 blocked512: big enough that the sweep/scatter choice is the
    # normal one, small enough that CPU kernel time doesn't swamp L1-L0
    return FilterConfig(m=1 << 24, k=7, key_len=KEY_LEN, block_bits=512)


def _keys(rng, n):
    return [rng.bytes(KEY_LEN) for _ in range(n)]


def main():
    emit({
        "shape": {
            "m": 1 << 24, "k": 7, "key_len": KEY_LEN,
            "layers": ["L0 filter", "L1 +msgpack service", "L2 +gRPC loopback"],
            "platform": jax.default_backend(),
            "note": "single host core; client+server share it (honest loopback)",
        }
    })

    # L2 server (also hosts the L1 service object so state is comparable)
    service = BloomService()
    server, port = build_server(service, "127.0.0.1:0")
    server.start()
    client = BloomClient(f"127.0.0.1:{port}")
    client.wait_ready()

    rng = np.random.default_rng(7)
    for B in BATCHES:
        reps = REPS[B]
        keys = _keys(rng, B)
        payload_mb = B * KEY_LEN / 1e6

        # ---- L0: filter only ----
        f0 = BlockedBloomFilter(_config())
        f0.insert_batch(keys)  # warm the jit caches
        f0.include_batch(keys)
        t0 = time.perf_counter()
        for _ in range(reps):
            f0.insert_batch(keys)
        ins0 = (time.perf_counter() - t0) / reps
        t0 = time.perf_counter()
        for _ in range(reps):
            f0.include_batch(keys)
        qry0 = (time.perf_counter() - t0) / reps

        # ---- L1: in-process service (msgpack encode/decode, no socket).
        # Requests are msgpack-encoded exactly as the wire would carry
        # them, then decoded by the service — protocol.dumps/loads is the
        # same codec _wrap uses.
        name1 = f"b{B}-l1"
        service.CreateFilter({
            "name": name1,
            "config": {
                "m": 1 << 24, "k": 7, "key_len": KEY_LEN, "block_bits": 512,
            },
        })
        req = protocol.encode({"name": name1, "keys": keys})
        service.InsertBatch(protocol.decode(req))  # warm
        protocol.encode(service.QueryBatch(protocol.decode(req)))
        t0 = time.perf_counter()
        for _ in range(reps):
            protocol.encode(service.InsertBatch(protocol.decode(req)))
        ins1 = (time.perf_counter() - t0) / reps
        t0 = time.perf_counter()
        for _ in range(reps):
            protocol.encode(service.QueryBatch(protocol.decode(req)))
        qry1 = (time.perf_counter() - t0) / reps

        # ---- L2: full loopback RPC ----
        name2 = f"b{B}-l2"
        client.create_filter(
            name2,
            config={
                "m": 1 << 24, "k": 7, "key_len": KEY_LEN, "block_bits": 512,
            },
        )
        client.insert_batch(name2, keys)  # warm
        client.include_batch(name2, keys)
        t0 = time.perf_counter()
        for _ in range(reps):
            client.insert_batch(name2, keys)
        ins2 = (time.perf_counter() - t0) / reps
        t0 = time.perf_counter()
        for _ in range(reps):
            hits = client.include_batch(name2, keys)
        qry2 = (time.perf_counter() - t0) / reps
        assert bool(np.asarray(hits).all())

        emit({
            "batch": B,
            "payload_mb": round(payload_mb, 2),
            "insert_keys_per_sec": {
                "L0_filter": round(B / ins0),
                "L1_service": round(B / ins1),
                "L2_grpc": round(B / ins2),
            },
            "query_keys_per_sec": {
                "L0_filter": round(B / qry0),
                "L1_service": round(B / qry1),
                "L2_grpc": round(B / qry2),
            },
            "insert_overhead_ms": {
                "msgpack_service": round((ins1 - ins0) * 1e3, 2),
                "grpc_transport": round((ins2 - ins1) * 1e3, 2),
            },
            "query_overhead_ms": {
                "msgpack_service": round((qry1 - qry0) * 1e3, 2),
                "grpc_transport": round((qry2 - qry1) * 1e3, 2),
            },
            "l2_insert_mb_per_sec": round(payload_mb / ins2, 1),
            "reps": reps,
        })

    client.close()
    server.stop(grace=1)


if __name__ == "__main__":
    main()
