#!/usr/bin/env python
"""Chaos smoke: boot the server stack on CPU, inject faults, and assert
the ISSUE-2 robustness surface end to end.

What it drives (fast: small filters, ephemeral ports, < ~20s on CPU):

* checkpoint two generations, **corrupt the newest on disk**, restart
  the service — restore must fall back a generation, quarantine the
  corpse to ``<dir>/corrupt/``, report ``DEGRADED`` with a
  ``checkpoint_corrupt:*`` reason, keep serving reads AND writes, then
  walk back to ``SERVING`` after the next good checkpoint;
* an injected ``ckpt.fsync`` fault mid-save — the tmp+rename invariant
  must leave no partial ``.ckpt`` visible;
* an in-flight cap of 2 with artificially slow handlers under
  concurrent clients — excess requests shed with ``RESOURCE_EXHAUSTED``
  + ``retry_after_ms`` and every retrying call still completes, with
  **zero double-applied deletes** (rid dedup);
* the injection counters land in the obs layer (a chaos run is
  auditable from /metrics).

Run directly (``python benchmarks/faults_smoke.py`` — prints one JSON
line) or via tier-1 (``tests/test_faults.py::test_faults_smoke`` imports
:func:`run_smoke`). CI runs both paths so the fault hooks cannot rot.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time


def run_smoke() -> dict:
    """Drive the chaos scenario; returns summary facts (raises on any
    failure)."""
    import numpy as np

    from tpubloom import checkpoint as ckpt
    from tpubloom import faults
    from tpubloom.obs import counters as obs_counters
    from tpubloom.server.client import BloomClient
    from tpubloom.server.protocol import BloomServiceError
    from tpubloom.server.service import BloomService, build_server

    ckpt_dir = tempfile.mkdtemp(prefix="tpubloom-faults-smoke-")
    sink_factory = lambda config: ckpt.FileSink(ckpt_dir)  # noqa: E731
    faults.reset()
    out: dict = {}

    # -- phase 1: corrupt-newest restore walk --------------------------------
    service = BloomService(sink_factory=sink_factory)
    server, port = build_server(service, "127.0.0.1:0")
    server.start()
    client = BloomClient(f"127.0.0.1:{port}")
    client.wait_ready()
    rng = np.random.default_rng(0)
    durable = [rng.bytes(16) for _ in range(1000)]
    client.create_filter("smoke", capacity=50_000, error_rate=0.01)
    client.insert_batch("smoke", durable)
    client.checkpoint("smoke", wait=True)  # generation A (good)
    client.insert_batch("smoke", [rng.bytes(16) for _ in range(200)])
    client.checkpoint("smoke", wait=True)  # generation B (to corrupt)

    # fsync fault mid-save: no partial file may appear
    faults.arm("ckpt.fsync", "once")
    try:
        client.checkpoint("smoke", wait=True)
        raise AssertionError("fsync fault did not surface")
    except BloomServiceError as e:
        assert e.code == "CKPT_FAILED", e
    faults.reset()
    assert not any(
        fn.endswith(".tmp") for fn in os.listdir(ckpt_dir)
    ), "partial checkpoint visible after injected fsync fault"

    client.close()
    server.stop(grace=None)
    del service

    sink = ckpt.FileSink(ckpt_dir)
    newest = sink.list_seqs("smoke")[0]
    path = sink._path("smoke", newest)
    blob = bytearray(open(path, "rb").read())
    blob[-5] ^= 0xFF  # payload bit rot
    open(path, "wb").write(bytes(blob))

    service = BloomService(sink_factory=sink_factory)
    server, port = build_server(service, "127.0.0.1:0")
    server.start()
    client = BloomClient(f"127.0.0.1:{port}")
    client.wait_ready()
    client.create_filter(
        "smoke", capacity=50_000, error_rate=0.01, exist_ok=True
    )
    assert client.include_batch("smoke", durable).all(), (
        "fallback generation lost checkpointed keys"
    )
    health = client.health()
    assert health["status"] == "DEGRADED", health
    assert any(r.startswith("checkpoint_corrupt") for r in health["reasons"])
    out["restored_past_corruption"] = True
    out["quarantined"] = sorted(
        os.listdir(os.path.join(ckpt_dir, "corrupt"))
    )
    client.insert_batch("smoke", [b"post-corruption0"])  # writes still work
    client.checkpoint("smoke", wait=True)  # a good generation heals
    assert client.health()["status"] == "SERVING"
    out["health_recovered"] = True
    client.close()
    server.stop(grace=None)
    del service

    # -- phase 2: overload shed + retry, zero double-deletes -----------------
    service = BloomService(
        sink_factory=sink_factory, max_in_flight=2, retry_after_ms=20
    )
    orig_delete = service.DeleteBatch

    def slow_delete(req):
        time.sleep(0.1)
        return orig_delete(req)

    service.DeleteBatch = slow_delete
    server, port = build_server(service, "127.0.0.1:0")
    server.start()
    client = BloomClient(f"127.0.0.1:{port}")
    client.wait_ready()
    client.create_filter(
        "cnt", capacity=20_000, error_rate=0.01, counting=True
    )
    keys = [b"smoke-dup-%06d" % i for i in range(32)]
    client.insert_batch("cnt", keys)
    client.insert_batch("cnt", keys)  # every key at count 2

    failures: list = []

    def delete_chunk(chunk):
        try:
            c = BloomClient(
                f"127.0.0.1:{port}", max_retries=10, backoff_base=0.02
            )
            try:
                c.delete_batch("cnt", chunk)
            finally:
                c.close()
        except Exception as e:  # noqa: BLE001
            failures.append(e)

    threads = [
        threading.Thread(target=delete_chunk, args=(keys[i::6],))
        for i in range(6)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not failures, failures
    sheds = service.metrics.snapshot()["counters"].get("requests_shed", 0)
    assert sheds > 0, "cap 2 with 6 concurrent slow deletes never shed"
    out["sheds"] = sheds
    # exactly-once deletes: every key went 2 -> 1, so all still present
    double_applied = int((~client.include_batch("cnt", keys)).sum())
    assert double_applied == 0, f"{double_applied} deletes double-applied"
    out["deletes_double_applied"] = 0
    client.close()
    server.stop(grace=None)

    out["faults_injected"] = obs_counters.get("faults_injected")
    out["ckpt_corrupt_detected"] = obs_counters.get("ckpt_corrupt_detected")
    assert out["faults_injected"] >= 1
    assert out["ckpt_corrupt_detected"] >= 1
    return out


def main() -> int:
    if os.environ.get("JAX_PLATFORMS") is None:
        os.environ["JAX_PLATFORMS"] = "cpu"
    # runnable as `python benchmarks/faults_smoke.py` from a checkout
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    result = run_smoke()
    print(json.dumps({"ok": True, **result}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
