#!/usr/bin/env python
"""Blocked-counting insert/delete rate on the fat packed kernel
(VERDICT r3 #4 "done =" clause: counting insert/delete rate, measured
against the 26.1M ops/s round-1 narrow-tile figure), plus the counting
QUERY rate (ADVICE r4: record the measurement justifying the k-pass
masked-reduce in fat_blocked_counting_membership).

m=2^30 counters (BASELINE config 4), k=7, blocked512, fat storage,
B=4M device-generated keys, to-value timing, alternating insert/delete
steps so the counter array stays bounded. Writes
benchmarks/out/counting_rate_r5.json.
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpubloom.config import FilterConfig
from tpubloom.filter import (
    blocked_device_shape,
    blocked_storage_fat,
    make_blocked_counter_fn,
)

B = 1 << 22
KEY_LEN = 16
STEPS = 16
OUT_PATH = os.path.join(
    os.path.dirname(__file__), "out", "counting_rate_r5.json"
)


def main():
    config = FilterConfig(
        m=1 << 30, k=7, key_len=KEY_LEN, counting=True, block_bits=512
    )
    lengths = jnp.full((B,), KEY_LEN, jnp.int32)
    fat = blocked_storage_fat(config)  # matches blocked_device_shape
    ins = make_blocked_counter_fn(config, increment=True, storage_fat=fat)
    dele = make_blocked_counter_fn(config, increment=False, storage_fat=fat)

    def step(state, carry, i):
        # seed depends ONLY on i // 2 so step 2n+1 deletes exactly the
        # keys step 2n inserted (counters return to 0; no saturation
        # drift). carry is the to-value fence, not a seed input — mixing
        # it in would desynchronize the insert/delete key pairs.
        keys = jax.random.bits(jax.random.key(i // 2), (B, KEY_LEN), jnp.uint8)
        # even steps insert, odd steps delete the same keys — counters
        # return to ~0, so saturation never bounds the run
        state = jax.lax.cond(
            i % 2 == 0,
            lambda s: ins(s, keys, lengths),
            lambda s: dele(s, keys, lengths),
            state,
        )
        return state, carry ^ jnp.sum(state[0], dtype=jnp.uint32)

    jit = jax.jit(step, donate_argnums=(0,))
    state = jnp.zeros(blocked_device_shape(config), jnp.uint32)
    t0 = time.perf_counter()
    state, carry = jit(state, jnp.uint32(0), 0)
    int(np.asarray(carry))
    compile_s = time.perf_counter() - t0
    state, carry = jit(state, carry, 1)
    int(np.asarray(carry))
    t0 = time.perf_counter()
    for i in range(2, 2 + STEPS):
        state, carry = jit(state, carry, i)
    int(np.asarray(carry))
    dt = (time.perf_counter() - t0) / STEPS
    # -- query rate (ADVICE r4 #3): fat_blocked_counting_membership runs
    # k dense [B, 128] masked-reduce passes (take_along_axis scalarizes
    # on TPU; two hashes may share a word, so a single combined lane
    # select is incorrect). Measure it so the loop is justified by a
    # number, like the other kernels.
    from tpubloom.filter import make_blocked_counting_query_fn

    qry = make_blocked_counting_query_fn(config, storage_fat=fat)

    def qstep(state, carry, i):
        keys = jax.random.bits(
            jax.random.key(i ^ 0x5EED), (B, KEY_LEN), jnp.uint8
        )
        hits = qry(state, keys, lengths)
        return carry ^ jnp.sum(hits.astype(jnp.uint32))

    qjit = jax.jit(qstep)
    carry = qjit(state, jnp.uint32(0), 0)
    int(np.asarray(carry))
    t0 = time.perf_counter()
    for i in range(1, 1 + STEPS):
        carry = qjit(state, carry, i)
    int(np.asarray(carry))
    qdt = (time.perf_counter() - t0) / STEPS

    row = {
        "metric": "blocked counting insert/delete ops/sec (fat packed kernel)",
        "m_counters": config.m,
        "k": config.k,
        "B": B,
        "ms_per_step": round(dt * 1e3, 2),
        "ops_per_sec": round(B / dt),
        "vs_round1_narrow_tile": round(B / dt / 26.1e6, 2),
        "query_ms_per_step": round(qdt * 1e3, 2),
        "query_keys_per_sec": round(B / qdt),
        "query_note": (
            "fat_blocked_counting_membership: row gather + k dense "
            "[B,128] masked-reduce word selects (ADVICE r4 #3 benchmark)"
        ),
        "compile_s": round(compile_s, 1),
        "platform": jax.default_backend(),
        "timing": "to-value, 16 chained alternating insert/delete steps",
    }
    print(json.dumps(row), flush=True)
    os.makedirs(os.path.dirname(OUT_PATH), exist_ok=True)
    with open(OUT_PATH, "w") as f:
        f.write(json.dumps(row) + "\n")


if __name__ == "__main__":
    main()
