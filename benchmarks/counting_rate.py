#!/usr/bin/env python
"""Blocked-counting insert/delete rate on the fat packed kernel
(VERDICT r3 #4 "done =" clause: counting insert/delete rate, measured
against the 26.1M ops/s round-1 narrow-tile figure).

m=2^30 counters (BASELINE config 4), k=7, blocked512, fat storage,
B=4M device-generated keys, to-value timing, alternating insert/delete
steps so the counter array stays bounded. Writes
benchmarks/out/counting_rate_r4.json.
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpubloom.config import FilterConfig
from tpubloom.filter import (
    blocked_device_shape,
    blocked_storage_fat,
    make_blocked_counter_fn,
)

B = 1 << 22
KEY_LEN = 16
STEPS = 16
OUT_PATH = os.path.join(
    os.path.dirname(__file__), "out", "counting_rate_r4.json"
)


def main():
    config = FilterConfig(
        m=1 << 30, k=7, key_len=KEY_LEN, counting=True, block_bits=512
    )
    lengths = jnp.full((B,), KEY_LEN, jnp.int32)
    fat = blocked_storage_fat(config)  # matches blocked_device_shape
    ins = make_blocked_counter_fn(config, increment=True, storage_fat=fat)
    dele = make_blocked_counter_fn(config, increment=False, storage_fat=fat)

    def step(state, carry, i):
        # seed depends ONLY on i // 2 so step 2n+1 deletes exactly the
        # keys step 2n inserted (counters return to 0; no saturation
        # drift). carry is the to-value fence, not a seed input — mixing
        # it in would desynchronize the insert/delete key pairs.
        keys = jax.random.bits(jax.random.key(i // 2), (B, KEY_LEN), jnp.uint8)
        # even steps insert, odd steps delete the same keys — counters
        # return to ~0, so saturation never bounds the run
        state = jax.lax.cond(
            i % 2 == 0,
            lambda s: ins(s, keys, lengths),
            lambda s: dele(s, keys, lengths),
            state,
        )
        return state, carry ^ jnp.sum(state[0], dtype=jnp.uint32)

    jit = jax.jit(step, donate_argnums=(0,))
    state = jnp.zeros(blocked_device_shape(config), jnp.uint32)
    t0 = time.perf_counter()
    state, carry = jit(state, jnp.uint32(0), 0)
    int(np.asarray(carry))
    compile_s = time.perf_counter() - t0
    state, carry = jit(state, carry, 1)
    int(np.asarray(carry))
    t0 = time.perf_counter()
    for i in range(2, 2 + STEPS):
        state, carry = jit(state, carry, i)
    int(np.asarray(carry))
    dt = (time.perf_counter() - t0) / STEPS
    row = {
        "metric": "blocked counting insert/delete ops/sec (fat packed kernel)",
        "m_counters": config.m,
        "k": config.k,
        "B": B,
        "ms_per_step": round(dt * 1e3, 2),
        "ops_per_sec": round(B / dt),
        "vs_round1_narrow_tile": round(B / dt / 26.1e6, 2),
        "compile_s": round(compile_s, 1),
        "platform": jax.default_backend(),
        "timing": "to-value, 16 chained alternating insert/delete steps",
    }
    print(json.dumps(row), flush=True)
    os.makedirs(os.path.dirname(OUT_PATH), exist_ok=True)
    with open(OUT_PATH, "w") as f:
        f.write(json.dumps(row) + "\n")


if __name__ == "__main__":
    main()
