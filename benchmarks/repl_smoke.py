#!/usr/bin/env python
"""Replication smoke: boot a primary + read replica on CPU and assert
the ISSUE-3 surface end to end (fast: small filters, ephemeral ports).

What it drives:

* a primary with an op log (``--repl-log-dir`` equivalent) takes writes
  into a **counting** filter (counts exactly 1 per key — the
  double-apply litmus);
* a read-only replica full-resyncs over ``ReplStream``, catches up to
  ``repl_lag_seq == 0``, and answers ``QueryBatch`` with membership
  identical to the primary; a write against it gets ``READONLY``;
* an injected ``repl.stream_send`` fault kills the stream mid-batch;
  the replica reconnects (partial resync) and the counting counts prove
  **zero double-applies** — one delete round empties every key;
* a ``Monitor`` subscription sees live ops (MONITOR parity);
* the primary restarts and replays its op log over the (absent)
  checkpoints — AOF parity: acked writes survive.

Run directly (``python benchmarks/repl_smoke.py`` — prints one JSON
line) or via tier-1 (``tests/test_repl.py::test_repl_smoke``). CI runs
both paths so the replication hooks cannot rot.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time


def run_smoke() -> dict:
    """Drive the replication scenario; returns summary facts (raises on
    any failure)."""
    import numpy as np

    from tpubloom import checkpoint as ckpt
    from tpubloom import faults
    from tpubloom.repl import OpLog
    from tpubloom.repl.replica import ReplicaApplier
    from tpubloom.server.client import BloomClient
    from tpubloom.server.protocol import BloomServiceError
    from tpubloom.server.service import BloomService, build_server

    faults.reset()
    out: dict = {}
    ckpt_dir = tempfile.mkdtemp(prefix="tpubloom-repl-smoke-ckpt-")
    log_dir = tempfile.mkdtemp(prefix="tpubloom-repl-smoke-log-")
    cleanup: list = []  # run LIFO even on assert failure — a leaked grpc
    # server's non-daemon threads would hang the process at exit

    try:
        # -- primary + replica -----------------------------------------------
        oplog = OpLog(log_dir)
        cleanup.append(oplog.close)
        psvc = BloomService(
            sink_factory=lambda config: ckpt.FileSink(ckpt_dir), oplog=oplog
        )
        psrv, pport = build_server(psvc, "127.0.0.1:0")
        psrv.start()
        cleanup.append(lambda: psrv.stop(grace=None))
        pc = BloomClient(f"127.0.0.1:{pport}")
        cleanup.append(pc.close)
        pc.wait_ready()
        rng = np.random.default_rng(0)
        keys = [rng.bytes(16) for _ in range(1000)]
        pc.create_filter(
            "smoke", capacity=50_000, error_rate=0.01, counting=True
        )
        pc.insert_batch("smoke", keys)  # every count exactly 1

        rsvc = BloomService(read_only=True)
        rsrv, rport = build_server(rsvc, "127.0.0.1:0")
        rsrv.start()
        cleanup.append(lambda: rsrv.stop(grace=None))
        applier = ReplicaApplier(
            rsvc, f"127.0.0.1:{pport}", reconnect_base=0.05
        ).start()
        cleanup.append(applier.stop)
        rc = BloomClient(f"127.0.0.1:{rport}")
        cleanup.append(rc.close)
        assert applier.wait_for_seq(oplog.last_seq, 30), applier.status()
        out["replica_caught_up"] = True
        out["full_syncs"] = applier.full_syncs

        assert rc.include_batch("smoke", keys).all(), "replica lost members"
        absent = [rng.bytes(16) for _ in range(1000)]
        assert (
            rc.include_batch("smoke", absent)
            == pc.include_batch("smoke", absent)
        ).all(), "replica membership diverged from primary"
        # raw call: the stock client would transparently FOLLOW the
        # READONLY redirect to the primary (a feature — but here the
        # rejection itself is under test)
        try:
            rc._call_once(
                "InsertBatch", {"name": "smoke", "keys": [b"nope"]}
            )
            raise AssertionError("replica accepted a write")
        except BloomServiceError as e:
            assert e.code == "READONLY", e
        out["readonly_enforced"] = True

        # -- monitor parity --------------------------------------------------
        mon = pc.monitor("smoke")
        cleanup.append(mon.cancel)
        mon_iter = iter(mon)
        assert next(mon_iter)["kind"] == "hello"

        # -- kill the stream mid-batch, prove exactly-once -------------------
        faults.arm("repl.stream_send", "once")
        pc.insert_batch("smoke", [rng.bytes(16) for _ in range(200)])
        deadline = time.monotonic() + 30
        while applier.partial_syncs == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert applier.partial_syncs >= 1, (
            f"stream never reconnected: {applier.status()}"
        )
        assert applier.wait_for_seq(oplog.last_seq, 30), applier.status()
        pc.delete_batch("smoke", keys)  # 1 - 1 = 0 ... unless double-applied
        assert applier.wait_for_seq(oplog.last_seq, 30), applier.status()
        double_applied = int(rc.include_batch("smoke", keys).sum())
        assert double_applied == 0, f"{double_applied} keys double-applied"
        out["double_applied"] = 0
        out["partial_syncs"] = applier.partial_syncs
        out["records_applied"] = applier.records_applied

        mon_events = 0
        for msg in mon_iter:
            if msg["kind"] == "op":
                mon_events += 1
            if mon_events >= 1:
                break
        out["monitor_events"] = mon_events
    finally:
        for fn in reversed(cleanup):
            try:
                fn()
            except Exception:
                pass

    # -- AOF parity: restart the primary from log alone ----------------------
    oplog2 = OpLog(log_dir)
    psvc2 = BloomService(
        sink_factory=lambda config: ckpt.FileSink(ckpt_dir), oplog=oplog2
    )
    stats = psvc2.replay_oplog()
    assert stats["failed"] == 0, stats
    hits = psvc2.QueryBatch({"name": "smoke", "keys": keys})
    survivors = int(
        np.unpackbits(np.frombuffer(hits["hits"], np.uint8), count=hits["n"]).sum()
    )
    assert survivors == 0, (
        f"replayed deletes lost: {survivors} keys resurrected"
    )
    out["replayed"] = stats
    psvc2.shutdown()
    oplog2.close()
    return out


def main() -> int:
    if os.environ.get("JAX_PLATFORMS") is None:
        os.environ["JAX_PLATFORMS"] = "cpu"
    # runnable as `python benchmarks/repl_smoke.py` from a checkout
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    result = run_smoke()
    print(json.dumps({"ok": True, **result}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
