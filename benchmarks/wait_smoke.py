#!/usr/bin/env python
"""Sync-replication smoke: per-batch commit-barrier overhead (ISSUE 5),
wired into tier-1 (``tests/test_sync_repl.py::test_wait_smoke``) and CI.

What it drives:

* an in-process primary (op log) + two streaming replicas with live
  ``ReplAck`` channels;
* a writer pushes counting-filter ``InsertBatch`` rounds at
  ``min_replicas=0`` (async — the pre-ISSUE-5 behavior), ``1`` and
  ``2`` (full quorum), measuring per-batch wall time after a jit
  warm-up round;
* the report is the **latency price of each durability level** —
  ``overhead_ms`` vs the async baseline — plus a ``Wait`` probe
  proving both replicas acknowledge the final seq;
* nothing here may regress ``repl_smoke``/``ha_smoke``: the barrier is
  strictly additive (min_replicas=0 writes never touch it).

Run directly (``python benchmarks/wait_smoke.py`` — prints one JSON
line) or via tier-1.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

BATCHES = 20
BATCH_SIZE = 50
LEVELS = (0, 1, 2)


def run_smoke() -> dict:
    """Measure commit-barrier overhead at min_replicas=0/1/2; returns
    summary facts (raises on any failure)."""
    from tpubloom import faults
    from tpubloom.repl import OpLog, ReplicaApplier
    from tpubloom.server.client import BloomClient
    from tpubloom.server.service import BloomService, build_server

    faults.reset()
    out: dict = {"batches": BATCHES, "batch_size": BATCH_SIZE}
    cleanup: list = []

    try:
        oplog = OpLog(tempfile.mkdtemp(prefix="tpubloom-wait-smoke-"))
        psvc = BloomService(oplog=oplog)
        psrv, pport = build_server(psvc, "127.0.0.1:0")
        psrv.start()
        psvc.listen_address = f"127.0.0.1:{pport}"
        cleanup.append(lambda: psrv.stop(grace=None))
        cleanup.append(oplog.close)

        client = BloomClient(f"127.0.0.1:{pport}")
        cleanup.append(client.close)
        client.wait_ready()
        client.create_filter(
            "wsmoke", capacity=50_000, error_rate=0.01, counting=True
        )

        appliers = []
        for _ in range(2):
            rsvc = BloomService(read_only=True)
            rsrv, rport = build_server(rsvc, "127.0.0.1:0")
            rsrv.start()
            app = ReplicaApplier(
                rsvc, f"127.0.0.1:{pport}", reconnect_base=0.05
            ).start()
            appliers.append(app)
            cleanup.append(lambda s=rsrv: s.stop(grace=None))
            cleanup.append(app.stop)
        for app in appliers:
            assert app.wait_for_seq(oplog.last_seq, 30), app.status()

        # warm the replicas' jit (first counting-insert apply compiles)
        # AND the barrier path, so the measurement is steady-state
        client.insert_batch(
            "wsmoke", [b"warm-%03d" % j for j in range(BATCH_SIZE)],
            min_replicas=2, min_replicas_timeout_ms=30_000,
        )

        out["mean_ms"], out["p_max_ms"], out["overhead_ms"] = {}, {}, {}
        # two passes per level; only the second is measured — the first
        # pass of the first level otherwise pays residual warm-up and
        # reports a NEGATIVE barrier overhead
        for rnd in (0, 1):  # 0 = warm, 1 = measured
            for level in LEVELS:
                lat = []
                for i in range(BATCHES):
                    keys = [b"w%d%d-%03d-%03d" % (rnd, level, i, j)
                            for j in range(BATCH_SIZE)]
                    t0 = time.perf_counter()
                    client.insert_batch(
                        "wsmoke", keys,
                        min_replicas=level or None,
                        min_replicas_timeout_ms=30_000 if level else None,
                    )
                    lat.append(time.perf_counter() - t0)
                    # drain the replicas OUTSIDE the timed region:
                    # everything runs in one process here, so an async
                    # writer otherwise measures the GIL contention of
                    # replicas applying its backlog — not its own path
                    for app in appliers:
                        app.wait_for_seq(oplog.last_seq, 30)
                if rnd:
                    out["mean_ms"][str(level)] = round(
                        1e3 * sum(lat) / len(lat), 3
                    )
                    out["p_max_ms"][str(level)] = round(1e3 * max(lat), 3)
        base = out["mean_ms"]["0"]
        for level in LEVELS[1:]:
            out["overhead_ms"][str(level)] = round(
                out["mean_ms"][str(level)] - base, 3
            )

        # WAIT probe: both replicas must acknowledge the final write
        out["wait_nreplicas"] = client.wait(2, timeout_ms=10_000)
        assert out["wait_nreplicas"] == 2, out
        # the obs surface actually carried the barrier: the wait-latency
        # histogram observed the quorum waits and the blocked-waiters
        # gauge exists (0 now — nothing is mid-wait)
        from tpubloom.obs.exposition import parse_families, render_service

        fam = parse_families(render_service(psvc))
        hist_n = fam.get("tpubloom_wait_barrier_seconds_count", {}).get((), 0)
        assert hist_n > 0, "wait histogram never observed a barrier"
        out["wait_barrier_observations"] = int(hist_n)
        gauge = fam.get("tpubloom_wait_blocked_current")
        assert gauge is not None, "wait_blocked_current gauge missing"
        out["wait_blocked_gauge_seen"] = True
    finally:
        for fn in reversed(cleanup):
            try:
                fn()
            except Exception:  # noqa: BLE001
                pass
    return out


def main() -> int:
    if os.environ.get("JAX_PLATFORMS") is None:
        os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    result = run_smoke()
    print(json.dumps({"ok": True, **result}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
