#!/usr/bin/env python
"""Radix-sort PLACEMENT prototype — the build-or-kill evidence for the
twice-deferred Pallas radix sort (VERDICT r4 Missing #2 / task 2).

A radix/bucket sort has two halves:

  COUNT  per-bucket histograms + prefix sums. Cheap on TPU — one-hot
         matmuls count 4M 8-bit digits in ~1 ms (query_probe.py
         "radix hist" row), and a 32k-bucket prefix sum is trivial.
  PLACE  move each element to its computed destination. This is a
         data-dependent permutation write, and it is the entire
         difficulty: every mechanism this chip offers is measured
         here or in a sibling probe.

Mechanisms for data-dependent placement, with their measured rates:

  1. XLA scatter: ~100 ns/row (query_probe.py "scatter" row; also the
     round-1 finding that motivated the sweep kernel). 4M rows
     -> ~400 ms. lax.sort does the whole job in ~12 ms.
  2. In-kernel dynamic DMA, one row per destination: THIS prototype.
     A sequential-grid Pallas kernel walks update tiles and issues
     one make_async_copy per row to ``out[dst[i]]``. Expectation from
     r4's dma_ablate (a dynamic DMA loop defeats Mosaic pipelining at
     ZERO iterations, +86%): latency-bound at ~1 us/row -> 100x too
     slow. The measurement pins it.
  3. One-hot permutation matmuls: out = P @ in with P a [B, B] one-hot
     — O(B^2) MACs = 1.4e13 at B=4M per u32 column. Two decades over
     the MXU budget of the whole kernel; arithmetic, no probe needed.

The kill criterion: placement must beat ~350M rows/s (4M rows in the
11.8 ms the 4-col lax.sort takes end-to-end) to be worth building.
Anything under ~30M rows/s is not even worth hybridizing.

Run: PYTHONPATH=/root/repo:$PYTHONPATH timeout 1800 python benchmarks/radix_place_proto.py
Writes benchmarks/out/radix_place_r5.json.
"""

from __future__ import annotations

import functools
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

B = 1 << 18  # 256k rows is plenty to pin a per-row latency; dst fits SMEM
T = 1 << 10  # rows per grid step
STEPS = 8

OUT_PATH = os.path.join(os.path.dirname(__file__), "out", "radix_place_r5.json")
_rows = []


def emit(obj):
    print(json.dumps(obj), flush=True)
    _rows.append(obj)
    os.makedirs(os.path.dirname(OUT_PATH), exist_ok=True)
    with open(OUT_PATH, "w") as f:
        for r in _rows:
            f.write(json.dumps(r) + "\n")


def _place_kernel(dst_ref, src_ref, out_ref, buf_ref, sems, *, T: int, ROWS: int):
    """Per-row dynamic-destination DMA placement: tile t copies its T
    rows (already in VMEM via the auto-pipelined src block) to
    ``out[dst[i]]`` one 8-row-aligned DMA at a time.

    Mosaic constraint stack-up, for the record: DMA sublane offsets and
    shapes must be 8-aligned, so a TRUE 1-row placement is not even
    expressible — each "row" here is an 8-row slab (dst pre-multiplied
    by 8), which FAVORS the prototype (8x fewer DMAs than a real
    permutation would need). It still loses by ~two decades.
    """
    t = pl.program_id(0)
    buf_ref[:] = src_ref[:]  # stage the tile (VMEM->VMEM, cheap)

    def body(i, _):
        d = dst_ref[t * ROWS + i]
        cp = pltpu.make_async_copy(
            buf_ref.at[pl.ds(i * 8, 8), :],
            out_ref.at[pl.ds(d * 8, 8), :],
            sems.at[lax.rem(i, 4)],
        )
        cp.start()

        @pl.when(i >= 3)
        def _():
            pltpu.make_async_copy(
                buf_ref.at[pl.ds(0, 8), :],
                out_ref.at[pl.ds(0, 8), :],
                sems.at[lax.rem(i - 3, 4)],
            ).wait()

        return 0

    lax.fori_loop(0, ROWS, body, 0)
    # drain the last in-flight copies
    def drain(i, _):
        pltpu.make_async_copy(
            buf_ref.at[pl.ds(0, 8), :],
            out_ref.at[pl.ds(0, 8), :],
            sems.at[lax.rem(ROWS - 3 + i, 4)],
        ).wait()
        return 0

    lax.fori_loop(0, 3, drain, 0)


def place(src, dst8):
    """src: [B, 128] u32 in 8-row slabs (B/8 slabs); dst8: [B/8] i32 slab
    permutation. Returns src permuted by slabs via per-slab dynamic DMA."""
    nslab = src.shape[0] // 8
    rows_per_tile = T // 8
    grid = nslab // rows_per_tile
    fn = pl.pallas_call(
        functools.partial(_place_kernel, T=T, ROWS=rows_per_tile),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(grid,),
            in_specs=[pl.BlockSpec((T, 128), lambda t, *_: (t, 0))],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[
                pltpu.VMEM((T, 128), jnp.uint32),
                pltpu.SemaphoreType.DMA((4,)),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct(src.shape, jnp.uint32),
    )
    return fn(dst8, src)


def main():
    emit({
        "shape": {
            "B_rows": B, "tile": T,
            "platform": jax.default_backend(),
            "device": str(jax.devices()[0]),
            "timing": "to-value (int(np.asarray(...)) after chained loop)",
            "note": "8-row-slab placement (1-row DMA not expressible); "
                    "slab granularity FAVORS the prototype 8x",
        }
    })
    rng = np.random.default_rng(0)
    src = jnp.asarray(rng.integers(0, 2**32, (B, 128), np.uint32).astype(np.uint32))
    perm = jnp.asarray(rng.permutation(B // 8).astype(np.int32))

    jit = jax.jit(lambda s, d: jnp.sum(place(s, d)[:, 0], dtype=jnp.uint32))
    t0 = time.perf_counter()
    carry = jit(src, perm)
    int(np.asarray(carry))
    compile_s = time.perf_counter() - t0

    # correctness first: the permutation must actually permute
    out = jax.jit(place)(src, perm)
    out_np = np.asarray(out).reshape(B // 8, 8, 128)
    src_np = np.asarray(src).reshape(B // 8, 8, 128)
    perm_np = np.asarray(perm)
    ok = bool((out_np[perm_np] == src_np).all())
    emit({"stage": "correctness", "slab_permutation_exact": ok})

    t0 = time.perf_counter()
    for i in range(STEPS):
        carry = jit(src + carry, perm)
    int(np.asarray(carry))
    dt = (time.perf_counter() - t0) / STEPS
    rows_per_sec = B / dt
    emit({
        "stage": "dynamic-DMA placement",
        "ms_per_step": round(dt * 1e3, 3),
        "rows_per_sec": round(rows_per_sec),
        "slabs_per_sec": round(rows_per_sec / 8),
        "compile_s": round(compile_s, 1),
        "vs_laxsort_rows_per_sec": 355_000_000,
        "verdict_beats_sort": rows_per_sec > 355e6,
    })


if __name__ == "__main__":
    main()
