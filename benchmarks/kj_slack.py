#!/usr/bin/env python
"""Window-slack sweep for the fused presence kernel (round 5).

choose_fat_params sizes presence windows at KJ = lambda + max(16,
8*sqrt(lambda)) — an 8-sigma Poisson slack. Every slack slot costs
twice: the kernel processes KJP packed rows per window, and the unsort
sorts J*P8*KJ slot rows. At the B=8M shipping geometry (256, 2,
lambda=256) the 8-sigma window is KJ=384 = 1.5x occupancy.

This probe re-times the full fused step at slack multipliers m in
{8, 6, 4} (KJ = lambda + max(16, m*sqrt(lambda))), same keys, with the
in-step replay assert (every replayed key must report present) as the
correctness fence. Overflowing windows route the batch to the scatter
fallback — correct but slow — so the probe also reports the overflow
probability arithmetic per batch.

Writes benchmarks/out/kj_slack_r5.json.
"""

from __future__ import annotations

import functools
import json
import math
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpubloom.config import FilterConfig
from tpubloom.filter import blocked_storage_fat, make_blocked_test_insert_fn
from tpubloom.ops import sweep

B = 1 << 23
KEY_LEN = 16
STEPS = 8
OUT_PATH = os.path.join(os.path.dirname(__file__), "out", "kj_slack_r5.json")
_rows = []


def emit(obj):
    print(json.dumps(obj), flush=True)
    _rows.append(obj)


_orig_choose = sweep.choose_fat_params


def _patched_choose(slack_mult, kind="presence"):
    """Override the slack for ONE kernel kind, leaving the shipping
    slack for the others (presence ships 6 sigma since this probe's
    first run; insert/counting ship 8)."""

    @functools.wraps(_orig_choose)
    def choose(nb, batch, words_per_block=16, *, presence=False,
               counting=False):
        out = _orig_choose(
            nb, batch, words_per_block, presence=presence, counting=counting
        )
        this_kind = (
            "presence" if presence else "counting" if counting else "insert"
        )
        if out is None or this_kind != kind:
            return out
        J, R8, S, KJ, KBJ = out
        lam = batch * R8 // nb
        kj = max(16, (lam + max(16, int(slack_mult * math.sqrt(lam))) + 7)
                 // 8 * 8)
        kbj = ((lam * S + kj + 64 + 7) // 8) * 8
        # The override bypasses the chooser's fences (volume caps, VMEM
        # estimate, non-v5e probe compile), which all ran at the
        # SHIPPING KJ — re-check them here so a probed slack can't hand
        # the kernel a shape the chooser would reject (a wider KJ at a
        # geometry near a cap would otherwise OOM at runtime and be
        # recorded as an opaque error row).
        pk = sweep.fat_pack(words_per_block, presence)
        bodies = S * J * pk
        volume = bodies * sweep._packed_rows(kj, pk) * R8
        cap_v = 3_500_000 if presence else 2_200_000 if counting else 4_300_000
        if presence and bodies > 64:
            cap_v = 2_200_000
        sup_rows = sweep._packed_rows(kbj, pk)
        vmem_ok = (
            2 * J * sup_rows * 128 * 4 + 4 * (S * R8 * 128 * 4)
            <= 9 * 1024 * 1024
        )
        if kj > 1024 or volume > cap_v or not vmem_ok:
            raise ValueError(
                f"slack={slack_mult} pushes geometry out of validated "
                f"caps (KJ={kj}, volume={volume}, vmem_ok={vmem_ok}) — "
                f"refusing to probe an un-fenced shape"
            )
        return J, R8, S, kj, kbj

    return choose


def run(slack_mult, kind="presence"):
    sweep.choose_fat_params = _patched_choose(slack_mult, kind)
    try:
        counting = kind == "counting"
        config = FilterConfig(
            m=1 << (30 if counting else 32), k=7, key_len=KEY_LEN,
            block_bits=512, counting=counting,
        )
        nb = config.n_blocks
        geom = sweep.choose_fat_params(
            nb, B, config.words_per_block, presence=kind == "presence",
            counting=counting,
        )
        J, R8, S, KJ, KBJ = geom
        lam = B * R8 // nb
        # per-window overflow tail (Poisson upper bound) x window count
        sig = math.sqrt(lam)
        z = (KJ - lam) / sig
        # Chernoff/normal tail approx — reported for context, not proof
        p_tail = math.exp(-z * z / 2)
        n_windows = J * (nb // J // R8)
        lengths = jnp.full((B,), KEY_LEN, jnp.int32)
        fat_rows = nb * config.words_per_block // 128
        state = jnp.zeros((fat_rows, 128), jnp.uint32)

        if kind == "presence":
            fn = make_blocked_test_insert_fn(config, storage_fat=True)
            assert blocked_storage_fat(config)

            def step(state, seed):
                keys = jax.random.bits(
                    jax.random.key(seed), (B, KEY_LEN), jnp.uint8
                )
                state, present = fn(state, keys, lengths)
                return state, jnp.sum(present.astype(jnp.uint32))
        elif kind == "insert":
            from tpubloom.filter import make_blocked_insert_fn

            ins = make_blocked_insert_fn(config, storage_fat=True)

            def step(state, seed):
                keys = jax.random.bits(
                    jax.random.key(seed), (B, KEY_LEN), jnp.uint8
                )
                state = ins(state, keys, lengths)
                return state, jnp.sum(
                    state[:: max(1, state.shape[0] // 64)], dtype=jnp.uint32
                )
        else:  # counting: alternating insert/delete, as counting_rate.py
            from tpubloom.filter import make_blocked_counter_fn

            ins = make_blocked_counter_fn(
                config, increment=True, storage_fat=True
            )
            dele = make_blocked_counter_fn(
                config, increment=False, storage_fat=True
            )

            def step(state, seed):
                keys = jax.random.bits(
                    jax.random.key(seed // 2), (B, KEY_LEN), jnp.uint8
                )
                state = jax.lax.cond(
                    seed % 2 == 0,
                    lambda s: ins(s, keys, lengths),
                    lambda s: dele(s, keys, lengths),
                    state,
                )
                return state, jnp.sum(state[0], dtype=jnp.uint32)

        jit = jax.jit(step, donate_argnums=0)
        t0 = time.perf_counter()
        state, carry = jit(state, 0)
        n0 = int(np.asarray(carry))
        compile_s = time.perf_counter() - t0
        if kind == "presence":
            # replay fence: same keys again must ALL report present
            state, carry = jit(state, 0)
            assert int(np.asarray(carry)) == B, "replay must be fully present"
        t0 = time.perf_counter()
        for i in range(1, 1 + STEPS):
            state, carry = jit(state, i)
        int(np.asarray(carry))
        dt = (time.perf_counter() - t0) / STEPS
        row = {
            "kind": kind,
            "slack_mult": slack_mult,
            "geom": {"J": J, "R8": R8, "S": S, "KJ": KJ, "KBJ": KBJ},
            "lambda": lam,
            "window_fill": round(lam / KJ, 3),
            "overflow_z_sigma": round(z, 1),
            "per_batch_overflow_approx": f"{n_windows} windows x "
                                         f"exp(-z^2/2)={p_tail:.1e}",
            "ms_per_step": round(dt * 1e3, 2),
            "compile_s": round(compile_s, 1),
        }
        if kind == "presence":
            # field names match the original presence rows in the
            # artifact (append mode must not mix schemas)
            row["first_batch_presence_hits"] = n0
            row["fused_keys_per_sec"] = round(B / dt)
        else:
            row["first_batch_carry"] = n0
            row["keys_per_sec"] = round(B / dt)
        emit(row)
    except Exception as e:  # noqa: BLE001
        emit({"kind": kind, "slack_mult": slack_mult, "error": str(e)[:300]})
    finally:
        sweep.choose_fat_params = _orig_choose


def main():
    import sys

    kinds = sys.argv[1:] or ["presence"]
    timing = f"to-value, {STEPS} chained steps"
    if "presence" in kinds:
        timing += "; presence replay-asserted"
    emit({
        "shape": f"m=2^32 (2^30 counting) k=7 blocked512 fat, B={B}",
        "kinds": kinds,
        "platform": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "timing": timing,
    })
    for kind in kinds:
        for m in (8, 6, 4) if kind == "presence" else (8, 6):
            run(m, kind)
    os.makedirs(os.path.dirname(OUT_PATH), exist_ok=True)
    with open(OUT_PATH, "a") as f:
        for r in _rows:
            f.write(json.dumps(r) + "\n")


if __name__ == "__main__":
    main()
