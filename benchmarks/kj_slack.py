#!/usr/bin/env python
"""Window-slack sweep for the fused presence kernel (round 5).

choose_fat_params sizes presence windows at KJ = lambda + max(16,
8*sqrt(lambda)) — an 8-sigma Poisson slack. Every slack slot costs
twice: the kernel processes KJP packed rows per window, and the unsort
sorts J*P8*KJ slot rows. At the B=8M shipping geometry (256, 2,
lambda=256) the 8-sigma window is KJ=384 = 1.5x occupancy.

This probe re-times the full fused step at slack multipliers m in
{8, 6, 4} (KJ = lambda + max(16, m*sqrt(lambda))), same keys, with the
in-step replay assert (every replayed key must report present) as the
correctness fence. Overflowing windows route the batch to the scatter
fallback — correct but slow — so the probe also reports the overflow
probability arithmetic per batch.

Writes benchmarks/out/kj_slack_r5.json.
"""

from __future__ import annotations

import functools
import json
import math
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpubloom.config import FilterConfig
from tpubloom.filter import blocked_storage_fat, make_blocked_test_insert_fn
from tpubloom.ops import sweep

B = 1 << 23
KEY_LEN = 16
STEPS = 8
OUT_PATH = os.path.join(os.path.dirname(__file__), "out", "kj_slack_r5.json")
_rows = []


def emit(obj):
    print(json.dumps(obj), flush=True)
    _rows.append(obj)


_orig_choose = sweep.choose_fat_params


def _patched_choose(slack_mult):
    @functools.wraps(_orig_choose)
    def choose(nb, batch, words_per_block=16, *, presence=False,
               counting=False):
        out = _orig_choose(
            nb, batch, words_per_block, presence=presence, counting=counting
        )
        if out is None or not presence or slack_mult == 8:
            return out
        J, R8, S, KJ, KBJ = out
        lam = batch * R8 // nb
        kj = max(16, (lam + max(16, int(slack_mult * math.sqrt(lam))) + 7)
                 // 8 * 8)
        kbj = ((lam * S + kj + 64 + 7) // 8) * 8
        return J, R8, S, kj, kbj

    return choose


def run(slack_mult):
    sweep.choose_fat_params = _patched_choose(slack_mult)
    try:
        config = FilterConfig(m=1 << 32, k=7, key_len=KEY_LEN, block_bits=512)
        nb = config.n_blocks
        geom = sweep.choose_fat_params(nb, B, 16, presence=True)
        J, R8, S, KJ, KBJ = geom
        lam = B * R8 // nb
        # per-window overflow tail (Poisson upper bound) x window count
        sig = math.sqrt(lam)
        z = (KJ - lam) / sig
        # Chernoff/normal tail approx — reported for context, not proof
        p_tail = math.exp(-z * z / 2)
        n_windows = J * (nb // J // R8)
        fn = make_blocked_test_insert_fn(config, storage_fat=True)
        assert blocked_storage_fat(config)
        lengths = jnp.full((B,), KEY_LEN, jnp.int32)
        fat_rows = nb * 16 // 128
        state = jnp.zeros((fat_rows, 128), jnp.uint32)

        def step(state, seed):
            keys = jax.random.bits(jax.random.key(seed), (B, KEY_LEN), jnp.uint8)
            state, present = fn(state, keys, lengths)
            return state, jnp.sum(present.astype(jnp.uint32))

        jit = jax.jit(step, donate_argnums=0)
        t0 = time.perf_counter()
        state, carry = jit(state, 0)
        n0 = int(np.asarray(carry))
        compile_s = time.perf_counter() - t0
        # replay fence: same keys again must ALL report present
        state, carry = jit(state, 0)
        assert int(np.asarray(carry)) == B, "replay must be fully present"
        t0 = time.perf_counter()
        for i in range(1, 1 + STEPS):
            state, carry = jit(state, i)
        int(np.asarray(carry))
        dt = (time.perf_counter() - t0) / STEPS
        emit({
            "slack_mult": slack_mult,
            "geom": {"J": J, "R8": R8, "S": S, "KJ": KJ, "KBJ": KBJ},
            "lambda": lam,
            "window_fill": round(lam / KJ, 3),
            "overflow_z_sigma": round(z, 1),
            "per_batch_overflow_approx": f"{n_windows} windows x "
                                         f"exp(-z^2/2)={p_tail:.1e}",
            "first_batch_presence_hits": n0,
            "ms_per_step": round(dt * 1e3, 2),
            "fused_keys_per_sec": round(B / dt),
            "compile_s": round(compile_s, 1),
        })
    except Exception as e:  # noqa: BLE001
        emit({"slack_mult": slack_mult, "error": str(e)[:300]})
    finally:
        sweep.choose_fat_params = _orig_choose


def main():
    emit({
        "shape": f"m=2^32 k=7 blocked512 fat fused, B={B}",
        "platform": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "timing": f"to-value, {STEPS} chained steps, replay-asserted",
    })
    for m in (8, 6, 4):
        run(m)
    os.makedirs(os.path.dirname(OUT_PATH), exist_ok=True)
    with open(OUT_PATH, "w") as f:
        for r in _rows:
            f.write(json.dumps(r) + "\n")


if __name__ == "__main__":
    main()
