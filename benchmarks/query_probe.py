#!/usr/bin/env python
"""Round-5 probes: query-path decomposition, unsort-gather, radix kill data.

Three questions this answers on hardware (VERDICT r4 Missing #2/#3,
Weak #4), all at the north-star shape (m=2^32, k=7, blocked512 fat,
B=4M):

1. WHERE does the 28.7M keys/s query rate go? Cumulative prefixes of
   the gather-query path: keygen -> +hash -> +masks+fold -> +gather ->
   full compare. The gather of [B] 512-byte fat rows from the 512 MB
   array is the suspected floor (random HBM reads).
2. Can the presence unsort's first stage be a GATHER? The kernel's
   slot-tile verdicts live at host-computable flat offsets; if a 1-D
   ``flat[idx]`` take of B elements is fast, the unsort becomes
   take + one B-sized single-column sort instead of one 2.1x-larger
   slot sort.
3. Radix-sort kill data (VERDICT r4 #2): a TPU radix/bucket sort needs
   data-dependent PLACEMENT. The three known mechanisms are measured
   here against ``lax.sort``: XLA row scatter (~100 ns/row documented),
   1-D take-based permutation apply, and the sort itself at both the
   B=4M (front sort) and slot-count (unsort) sizes. Pallas-side
   placement via dynamic per-element DMA is already dead: r4 measured
   +86% kernel time from a dynamic DMA loop at ZERO iterations
   (benchmarks/RESULTS_r4.md §5, dma_ablate).

Timing: TO-VALUE (int(np.asarray(carry)) after a chained loop) — bur
can lie on this stack (benchmarks/RESULTS_r3.md §1).
Run: PYTHONPATH=/root/repo:$PYTHONPATH timeout 1800 python benchmarks/query_probe.py
Writes benchmarks/out/query_probe_r5.json (one JSON object per line).
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tpubloom.config import FilterConfig
from tpubloom.ops import blocked

LOG2M = 32
B = 1 << 22
KEY_LEN = 16
STEPS = 12

config = FilterConfig(m=1 << LOG2M, k=7, key_len=KEY_LEN, block_bits=512)
NB, W, K, BB = config.n_blocks, config.words_per_block, config.k, config.block_bits
J = 128 // W
NBJ = NB // J
FAT_SHAPE = (NBJ, 128)
lengths = jnp.full((B,), KEY_LEN, jnp.int32)

OUT_PATH = os.path.join(os.path.dirname(__file__), "out", "query_probe_r5.json")
_rows = []


def emit(obj):
    print(json.dumps(obj), flush=True)
    _rows.append(obj)


def _u32(x):
    return jnp.asarray(x, jnp.uint32)


def keygen(carry, i):
    return jax.random.bits(
        jax.random.key(i ^ (carry & 0xFFFF)), (B, KEY_LEN), jnp.uint8
    )


def _positions(keys):
    return blocked.block_positions(
        keys, lengths, n_blocks=NB, block_bits=BB, k=K, seed=config.seed,
        block_hash=config.block_hash,
    )


def run(name, step, *operands, steps=STEPS, extra=None):
    """Chained to-value loop over ``step(carry, i, *operands) -> carry``.

    Large arrays MUST ride as ``operands``: a closed-over device array
    becomes an HLO constant and the axon remote-compile request rejects
    bodies past ~100 MB (HTTP 413)."""
    jit = jax.jit(step)
    carry = jit(_u32(0), 0, *operands)
    int(np.asarray(carry))
    carry = jit(carry, 1, *operands)
    int(np.asarray(carry))
    t0 = time.perf_counter()
    for i in range(2, 2 + steps):
        carry = jit(carry, i, *operands)
    int(np.asarray(carry))
    dt = (time.perf_counter() - t0) / steps
    row = {
        "stage": name,
        "ms_per_step": round(dt * 1e3, 3),
        "ns_per_key": round(dt / B * 1e9, 3),
    }
    if extra:
        row.update(extra)
    emit(row)
    return dt


def main():
    emit({
        "shape": {
            "m": config.m, "k": K, "B": B, "block_bits": BB, "n_blocks": NB,
            "J": J, "NBJ": NBJ,
            "platform": jax.default_backend(),
            "device": str(jax.devices()[0]),
            "timing": "to-value (int(np.asarray(carry)) after chained loop)",
        }
    })

    # a ~6%-fill fat array (north-star operating point) so compares see
    # realistic bit density; contents do not affect gather/compare cost
    fill = jax.random.bits(jax.random.key(99), FAT_SHAPE, jnp.uint32)
    fat = jnp.asarray(fill & fill >> 1 & fill >> 2 & fill >> 3 & _u32(0x11111111))

    # ---- 1. query-path decomposition (cumulative prefixes) ----
    def q0(carry, i):
        keys = keygen(carry, i)
        return jnp.sum(keys, dtype=jnp.uint32)

    def q1(carry, i):
        keys = keygen(carry, i)
        blk, bit = _positions(keys)
        return jnp.sum(blk.astype(jnp.uint32)) + jnp.sum(bit)

    def q2(carry, i):
        keys = keygen(carry, i)
        blk, bit = _positions(keys)
        masks = blocked.build_masks(bit, W)
        return jnp.sum(masks) + jnp.sum(blk.astype(jnp.uint32))

    def q3(carry, i):
        keys = keygen(carry, i)
        blk, bit = _positions(keys)
        masks = blocked.build_masks(bit, W)
        frow, m128 = blocked.fat_fold_masks(blk, masks, J)
        return jnp.sum(m128) + jnp.sum(frow.astype(jnp.uint32))

    def q4(carry, i, fat):
        keys = keygen(carry, i)
        blk, bit = _positions(keys)
        masks = blocked.build_masks(bit, W)
        frow, m128 = blocked.fat_fold_masks(blk, masks, J)
        rows128 = fat[frow]
        # reduce ALL 128 lanes: summing one column would let XLA fold the
        # slice into the gather and narrow the 512B-row fetch to 4B/row
        return jnp.sum(rows128, dtype=jnp.uint32) + jnp.sum(m128[:, 0])

    def q5(carry, i, fat):
        keys = keygen(carry, i)
        blk, bit = _positions(keys)
        masks = blocked.build_masks(bit, W)
        hits = blocked.fat_blocked_query(fat, blk, masks)
        return jnp.sum(hits.astype(jnp.uint32))

    prev = 0.0
    deltas = {}
    for name, fn in [
        ("q0 keygen", q0),
        ("q1 +hash", q1),
        ("q2 +masks", q2),
        ("q3 +fold", q3),
        ("q4 +gather", q4),
        ("q5 full query", q5),
    ]:
        ops = (fat,) if name in ("q4 +gather", "q5 full query") else ()
        dt = run(name, fn, *ops)
        deltas[name] = dt - prev
        prev = dt
    emit({
        "query_deltas_ms": {k: round(v * 1e3, 3) for k, v in deltas.items()},
        "query_keys_per_sec": round(B / prev),
    })

    # gather in ISOLATION (no hash chain): random fat-row gather + touch
    def g_only(carry, i, fat):
        h = jax.random.bits(
            jax.random.key(i ^ (carry & 0xFFFF)), (B,), jnp.uint32
        )
        frow = (h & _u32(NBJ - 1)).astype(jnp.int32)
        rows = fat[frow]
        # full-row reduce pins the gather at its real 512B/row width
        return jnp.sum(rows, dtype=jnp.uint32)

    run("gather_only [B] x 512B fat rows", g_only, fat,
        extra={"bytes_gathered": B * 512})

    # compare in ISOLATION: rows already gathered, fold + compare only
    rows_pre = jax.device_put(
        np.random.default_rng(1).integers(0, 2**32, (B, 128), np.uint32).astype(
            np.uint32
        )
    )

    def c_only(carry, i, rows_pre):
        keys = keygen(carry, i)
        blk, bit = _positions(keys)
        masks = blocked.build_masks(bit, W)
        _, m128 = blocked.fat_fold_masks(blk, masks, J)
        r = rows_pre | carry
        return jnp.sum(
            jnp.all((r & m128) == m128, axis=-1).astype(jnp.uint32)
        )

    run("compare_only (hash+masks+fold+allcmp, no gather)", c_only, rows_pre)

    # ---- 2. unsort-gather probes ----
    flat_src = jax.random.bits(jax.random.key(5), (4 * B,), jnp.uint32)

    def take1d(carry, i, flat_src):
        idx = (
            jax.random.bits(jax.random.key(i ^ (carry & 0xFFFF)), (B,), jnp.uint32)
            & _u32(4 * B - 1)
        ).astype(jnp.int32)
        return jnp.sum(flat_src[idx])

    run("take1d: flat[idx] B from 16.8M u32", take1d, flat_src)

    # ---- 3. radix kill data ----
    def scatter_rows(carry, i):
        idx = (
            jax.random.bits(jax.random.key(i ^ (carry & 0xFFFF)), (B,), jnp.uint32)
            & _u32(B - 1)
        ).astype(jnp.int32)
        v = idx.astype(jnp.uint32) ^ carry
        out = jnp.zeros((B,), jnp.uint32).at[idx].set(v)
        return jnp.sum(out)

    run("scatter: zeros(B).at[idx].set (4M u32)", scatter_rows, steps=4)

    def sort1(carry, i, src):
        (s,) = lax.sort((src ^ carry,), num_keys=1)
        return jnp.sum(s)

    for n, lab in [(B, "4M"), (2 * B, "8.4M-ish")]:
        src = jax.random.bits(jax.random.key(11), (n,), jnp.uint32)
        run(f"lax.sort 1 u32 col, n={lab}", sort1, src)

    def sort4(carry, i, s0, s1, s2, s3):
        out = lax.sort((s0 ^ carry, s1, s2, s3), num_keys=1)
        return sum(jnp.sum(c) for c in out).astype(jnp.uint32)

    src4 = [
        jax.random.bits(jax.random.fold_in(jax.random.key(13), i), (B,), jnp.uint32)
        for i in range(4)
    ]
    run("lax.sort 4 u32 cols, n=4M", sort4, *src4)

    # histogram via one-hot matmul (the radix COUNT pass, for the record:
    # counting is cheap — placement is what kills the radix sort)
    def hist_mm(carry, i):
        h = jax.random.bits(
            jax.random.key(i ^ (carry & 0xFFFF)), (B,), jnp.uint32
        )
        b = (h & _u32(255)).astype(jnp.int32).reshape(-1, 512)
        oh = jnp.where(
            b[:, :, None] == jnp.arange(256, dtype=jnp.int32)[None, None, :],
            jnp.float32(1), jnp.float32(0),
        ).astype(jnp.bfloat16)
        cnt = jnp.sum(
            lax.dot_general(
                jnp.ones((b.shape[0], 512), jnp.bfloat16), oh,
                (((1,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            ),
            axis=0,
        )
        return jnp.sum(cnt).astype(jnp.uint32)

    run("radix hist: 8-bit one-hot matmul counts", hist_mm, steps=4)

    os.makedirs(os.path.dirname(OUT_PATH), exist_ok=True)
    with open(OUT_PATH, "w") as f:
        for r in _rows:
            f.write(json.dumps(r) + "\n")


if __name__ == "__main__":
    main()
