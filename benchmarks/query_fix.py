#!/usr/bin/env python
"""Isolated A/B of fat blocked query variants (round 5).

bench r5 exposed the shipping fold-free query at 18.9M keys/s (222 ms /
4M step) — ~3x slower than the component arithmetic predicted. The
suspect: STATIC lane slices ``rows128[:, j*w:(j+1)*w]`` are themselves
cross-lane relayouts on this chip, paid J=8 times, just like the
lane-concat the r5 fold fix removed (query_probe_r5 q3: ~47 ms for one
[B, W] -> [B, 128] concat).

Variants, same keys / same fat array / to-value timing:
  A "slices"      — shipping r5 path: J static slices + narrow compares
  B "matmul_fold" — replicate masks to 128 lanes via 4 exact
                    byte-quarter matmuls (constant [W, 128] 0/1 weights,
                    values <= 255 are bf16-exact), select owning group,
                    ONE full-width compare + all-reduce
  C "concat_fold" — r4 path: lane-concat fold (the known 47 ms relayout)
  D "gather_only" — row gather + trivial reduce (floor for any variant)

Writes benchmarks/out/query_fix_r5.json.
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tpubloom.config import FilterConfig
from tpubloom.ops import blocked

B = 1 << 22
KEY_LEN = 16
STEPS = 8
OUT_PATH = os.path.join(os.path.dirname(__file__), "out", "query_fix_r5.json")
_rows = []


def emit(obj):
    print(json.dumps(obj), flush=True)
    _rows.append(obj)


def _u32(x):
    return jnp.asarray(x, jnp.uint32)


# variant B measures the SHIPPING helper (so re-runs track the tree);
# variant C pins the historical r4 lane-concat inline (fat_fold_masks
# itself now uses the matmul replication, so calling it would measure
# B twice)
replicate_matmul = blocked._replicate_masks_128


def concat_fold_r4(blk, masks, J):
    """The r4 fat_fold_masks body, pinned verbatim: lane-concat
    replication (the measured ~47 ms relayout at B=4M)."""
    B_, w = masks.shape
    lane = lax.broadcasted_iota(jnp.int32, (B_, 128), 1)
    sel = (lane // w) == (blk % J).astype(jnp.int32)[:, None]
    rep = jnp.concatenate([masks] * J, axis=1)
    return (blk // J).astype(jnp.int32), jnp.where(sel, rep, _u32(0))


def main():
    config = FilterConfig(m=1 << 32, k=7, key_len=KEY_LEN, block_bits=512)
    nb, bb, w = config.n_blocks, config.block_bits, config.words_per_block
    J = 128 // w
    fat_rows = nb * w // 128
    lengths = jnp.full((B,), KEY_LEN, jnp.int32)

    # a filled-ish array so compares aren't trivially short-circuitable
    state = jax.random.bits(jax.random.key(7), (fat_rows, 128), jnp.uint32)

    def front(seed):
        keys = jax.random.bits(jax.random.key(seed), (B, KEY_LEN), jnp.uint8)
        blk, bit = blocked.block_positions(
            keys, lengths, n_blocks=nb, block_bits=bb, k=config.k,
            seed=config.seed, block_hash=config.block_hash,
        )
        return blk, blocked.build_masks(bit, w)

    def q_slices(state, carry, seed):
        blk, masks = front(seed)
        frow = (blk // J).astype(jnp.int32)
        rows128 = state[frow]
        g = (blk % J).astype(jnp.int32)
        hit = jnp.zeros(blk.shape, bool)
        for j in range(J):
            rj = rows128[..., j * w:(j + 1) * w]
            hit = hit | ((g == j) & jnp.all((rj & masks) == masks, axis=-1))
        return carry ^ jnp.sum(hit.astype(jnp.uint32))

    def q_matmul(state, carry, seed):
        blk, masks = front(seed)
        frow = (blk // J).astype(jnp.int32)
        rows128 = state[frow]
        lane = lax.broadcasted_iota(jnp.int32, (B, 128), 1)
        sel = (lane // w) == (blk % J).astype(jnp.int32)[:, None]
        m128 = jnp.where(sel, replicate_matmul(masks), _u32(0))
        hit = jnp.all((rows128 & m128) == m128, axis=-1)
        return carry ^ jnp.sum(hit.astype(jnp.uint32))

    def q_concat(state, carry, seed):
        blk, masks = front(seed)
        frow, m128 = concat_fold_r4(blk, masks, J)
        rows128 = state[frow]
        hit = jnp.all((rows128 & m128) == m128, axis=-1)
        return carry ^ jnp.sum(hit.astype(jnp.uint32))

    def q_matmul_ornot(state, carry, seed):
        # like B, but the verdict is "no missing bit": one and-not pass +
        # a single OR-reduce (fewer [B, 128] intermediates than
        # compare-eq + all-reduce)
        blk, masks = front(seed)
        frow = (blk // J).astype(jnp.int32)
        rows128 = state[frow]
        lane = lax.broadcasted_iota(jnp.int32, (B, 128), 1)
        sel = (lane // w) == (blk % J).astype(jnp.int32)[:, None]
        m128 = jnp.where(sel, replicate_matmul(masks), _u32(0))
        missing = jnp.bitwise_and(jnp.bitwise_not(rows128), m128)
        hit = lax.reduce(
            missing, _u32(0), lax.bitwise_or, (1,)
        ) == _u32(0)
        return carry ^ jnp.sum(hit.astype(jnp.uint32))

    def q_gather(state, carry, seed):
        blk, masks = front(seed)
        frow = (blk // J).astype(jnp.int32)
        rows128 = state[frow]
        return carry ^ (
            jnp.sum(rows128[:, ::64], dtype=jnp.uint32)
            ^ jnp.sum(masks, dtype=jnp.uint32)
        )

    variants = [
        ("A slices", q_slices),
        ("B matmul_fold", q_matmul),
        ("C concat_fold", q_concat),
        ("E matmul_ornot", q_matmul_ornot),
        ("D gather_only", q_gather),
    ]
    emit({
        "shape": f"m=2^32 k=7 blocked512 fat query, B={B}",
        "platform": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "timing": f"to-value, {STEPS} chained steps",
    })
    ref = None
    for name, fn in variants:
        jit = jax.jit(fn)
        t0 = time.perf_counter()
        carry = jit(state, jnp.uint32(0), 0)
        v0 = int(np.asarray(carry))
        compile_s = time.perf_counter() - t0
        # correctness cross-check: variants A-C must agree on the carry
        if name != "D gather_only":
            if ref is None:
                ref = v0
            elif v0 != ref:
                emit({"variant": name, "MISMATCH": [ref, v0]})
                continue
        carry = jit(state, carry, 1)
        int(np.asarray(carry))
        t0 = time.perf_counter()
        for i in range(2, 2 + STEPS):
            carry = jit(state, carry, i)
        int(np.asarray(carry))
        dt = (time.perf_counter() - t0) / STEPS
        emit({
            "variant": name,
            "ms_per_step": round(dt * 1e3, 2),
            "keys_per_sec": round(B / dt),
            "compile_s": round(compile_s, 1),
        })
    os.makedirs(os.path.dirname(OUT_PATH), exist_ok=True)
    with open(OUT_PATH, "w") as f:
        for r in _rows:
            f.write(json.dumps(r) + "\n")


if __name__ == "__main__":
    main()
