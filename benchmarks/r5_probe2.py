#!/usr/bin/env python
"""Round-5 probe batch 2: insert-only kernel geometry + gather ordering.

1. INSERT-ONLY GEOMETRY. presence_geom_r5.json showed the fused kernel
   is per-window-overhead-bound (R8=512 beats R8=256 despite 2x the
   placement MACs). The insert-only kernel ships at the r4-validated
   (R8=256, S=4); this probes larger tiles with the same
   compile/verify/time protocol. Results feed choose_fat_params'
   insert-only lambda target and volume cap.

2. GATHER ORDERING. The random [B] 512B-row gather costs 12.3 ns/row
   (query_probe_r5.json). If XLA's row gather runs at HBM bandwidth
   when the indices are ASCENDING, a sort->gather->unsort query path
   beats both the random gather and a dedicated sweep query kernel.
   Measured here: the same gather on (a) fixed random and (b) fixed
   ascending row indices.

Run: PYTHONPATH=/root/repo:$PYTHONPATH timeout 3000 python benchmarks/r5_probe2.py
Writes benchmarks/out/r5_probe2.json.
"""

from __future__ import annotations

import json
import math
import os
import time
import traceback

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tpubloom.config import FilterConfig
from tpubloom.ops import blocked
from tpubloom.ops.sweep import (
    _fat_stream,
    _pack_positions,
    _packed_rows,
    _unpack_positions,
    fat_pack,
    fat_sweep_insert,
)

LOG2M = 32
B = 1 << 22
KEY_LEN = 16
STEPS = 8

config = FilterConfig(m=1 << LOG2M, k=7, key_len=KEY_LEN, block_bits=512)
NB, W, K, BB = config.n_blocks, config.words_per_block, config.k, config.block_bits
J = 128 // W
NBJ = NB // J
FAT_SHAPE = (NBJ, 128)
PACK = fat_pack(W, False)

CANDIDATES = [  # (R8, S) for insert-only
    (256, 4),   # shipping r4-validated geometry
    (256, 8),
    (512, 2),
    (512, 4),
    (1024, 1),
    (1024, 2),
]

OUT_PATH = os.path.join(os.path.dirname(__file__), "out", "r5_probe2.json")
_rows = []


def emit(obj):
    print(json.dumps(obj), flush=True)
    _rows.append(obj)
    os.makedirs(os.path.dirname(OUT_PATH), exist_ok=True)
    with open(OUT_PATH, "w") as f:
        for r in _rows:
            f.write(json.dumps(r) + "\n")


def _u32(x):
    return jnp.asarray(x, jnp.uint32)


def _kj_kbj(R8, S):
    lam = B * R8 // NB
    kj = max(16, (lam + max(16, int(8 * math.sqrt(lam))) + 7) // 8 * 8)
    kbj = ((lam * S + kj + 64 + 7) // 8) * 8
    return kj, kbj


def _stream_for(R8, KBJ, keys):
    lengths = jnp.full((B,), KEY_LEN, jnp.int32)
    blk, bit = blocked.block_positions(
        keys, lengths, n_blocks=NB, block_bits=BB, k=K, seed=config.seed,
        block_hash=config.block_hash,
    )
    P8 = NBJ // R8
    j_of = (blk % J).astype(jnp.uint32)
    rf_of = (blk // J).astype(jnp.uint32)
    skey = j_of * NBJ + rf_of
    cols, nbits, packed = _pack_positions(bit, BB, K)
    sorted_cols = lax.sort((skey,) + cols, num_keys=1)
    ss = sorted_cols[0]
    bit_sorted = _unpack_positions(sorted_cols[1:], BB, K, nbits, packed)
    masks = blocked.build_masks(bit_sorted, W)
    return _fat_stream(
        ss, masks, None, J=J, NBJ=NBJ, P8=P8, R8=R8, KBJ=KBJ, W=W, pack=PACK,
    )


def insert_geometry(keys):
    ref_fat = None
    for R8, S in CANDIDATES:
        P8 = NBJ // R8
        if P8 % S or (P8 // S) < 2:
            emit({"probe": "insert-geom", "R8": R8, "S": S, "skip": "grid"})
            continue
        KJ, KBJ = _kj_kbj(R8, S)
        row = {
            "probe": "insert-geom", "R8": R8, "S": S, "KJ": KJ, "KBJ": KBJ,
            "bodies": S * J * PACK,
            "volume": S * J * PACK * _packed_rows(KJ, PACK) * R8,
        }
        try:
            upd, starts = jax.jit(
                lambda k, R8=R8, KBJ=KBJ: _stream_for(R8, KBJ, k)
            )(keys)

            def step(state, u, st):
                new_fat = fat_sweep_insert(
                    state, u, st, J=J, R8=R8, S=S, KJ=KJ, KBJ=KBJ, W=W,
                    pack=PACK,
                )
                return new_fat, jnp.sum(
                    new_fat[:: max(1, NBJ // 64)], dtype=jnp.uint32
                )

            jit = jax.jit(step, donate_argnums=(0,))
            t0 = time.perf_counter()
            state, acc = jit(jnp.zeros(FAT_SHAPE, jnp.uint32), upd, starts)
            int(np.asarray(acc))
            row["compile_s"] = round(time.perf_counter() - t0, 1)
            if ref_fat is None:
                ref_fat = np.asarray(state)
                row["bits_vs_ref"] = "is-ref"
            else:
                row["bits_vs_ref"] = bool((np.asarray(state) == ref_fat).all())
            t0 = time.perf_counter()
            for i in range(STEPS):
                state, acc = jit(state, upd, starts)
            int(np.asarray(acc))
            dt = (time.perf_counter() - t0) / STEPS
            row["ms_per_step"] = round(dt * 1e3, 3)
            row["keys_per_sec"] = round(B / dt)
            row["ok"] = row["bits_vs_ref"] in (True, "is-ref")
            del state
        except Exception as e:
            row["error"] = "".join(
                traceback.format_exception_only(type(e), e)
            )[:300]
            row["ok"] = False
        emit(row)


def gather_ordering():
    fill = jax.random.bits(jax.random.key(99), FAT_SHAPE, jnp.uint32)
    fat = jnp.asarray(fill & fill >> 1 & fill >> 2 & _u32(0x11111111))
    rng = np.random.default_rng(3)
    idx_rand = rng.integers(0, NBJ, B).astype(np.int32)
    idx_sort = np.sort(idx_rand)
    for name, idx in [("random", idx_rand), ("ascending", idx_sort)]:
        idx_d = jax.device_put(jnp.asarray(idx))

        def step(carry, i, fat, idx_d):
            # carry threads the chain (device executes serially; the
            # to-value sync at the end is the only timing fence needed)
            rows = fat[idx_d]
            return jnp.sum(rows, dtype=jnp.uint32) + carry

        jit = jax.jit(step)
        carry = jit(_u32(0), 0, fat, idx_d)
        int(np.asarray(carry))
        t0 = time.perf_counter()
        for i in range(STEPS):
            carry = jit(carry, i, fat, idx_d)
        int(np.asarray(carry))
        dt = (time.perf_counter() - t0) / STEPS
        emit({
            "probe": "gather-order", "order": name,
            "ms_per_step": round(dt * 1e3, 3),
            "ns_per_row": round(dt / B * 1e9, 3),
            "gb_per_sec": round(B * 512 / dt / 1e9, 1),
        })


def main():
    emit({
        "shape": {
            "m": config.m, "k": K, "B": B, "block_bits": BB, "J": J,
            "pack": PACK, "platform": jax.default_backend(),
            "device": str(jax.devices()[0]),
            "timing": "to-value chained loop, donated state",
        }
    })
    keys = jax.device_put(
        np.random.default_rng(0).integers(0, 256, (B, KEY_LEN), np.uint8)
    )
    insert_geometry(keys)
    gather_ordering()


if __name__ == "__main__":
    main()
