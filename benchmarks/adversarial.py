#!/usr/bin/env python
"""Adversarial-skew verification on REAL Mosaic (VERDICT r2 #9).

Interpret-mode passing is weak evidence for this kernel family (Mosaic
has miscompiled lane/sublane patterns silently before — see
ops/sweep.py), so this drives the actual TPU kernel:

  1. uniform 4M keys through the fat sweep — bit-exact vs the XLA
     sorted-scatter path, fused presence replay-verified;
  2. a duplicate-heavy batch (4M = 4096 copies of 1024 keys) — window
     overflow must trip the host-side lax.cond fallback and still be
     bit-exact vs scatter, presence included;
  3. timings for both (the fallback's cost is the documented price of
     adversarial skew).

Prints one JSON line per check. Exit code 1 on any mismatch.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpubloom.config import FilterConfig
from tpubloom.filter import make_blocked_insert_fn, make_blocked_test_insert_fn
from tpubloom.ops import blocked

LOG2M = 32
B = 1 << 22
config = FilterConfig(m=1 << LOG2M, k=7, key_len=16, block_bits=512)
NB, W = config.n_blocks, config.words_per_block
lengths = jnp.full((B,), 16, jnp.int32)


def scatter_ref(keys):
    blk, bit = blocked.block_positions(
        keys, lengths, n_blocks=NB, block_bits=512, k=config.k,
        seed=config.seed, block_hash=config.block_hash,
    )
    masks = blocked.build_masks(bit, W)
    return blocked.blocked_insert(
        jnp.zeros((NB, W), jnp.uint32), blk, masks, jnp.ones((B,), bool)
    )


def main() -> int:
    ok_all = True
    ti = jax.jit(make_blocked_test_insert_fn(config), donate_argnums=0)
    ref_jit = jax.jit(scatter_ref)

    for name, mk in (
        ("uniform", lambda rng: rng.integers(0, 256, (B, 16), np.uint8)),
        (
            "duplicate-skew 4096x1024",
            lambda rng: np.tile(
                rng.integers(0, 256, (1024, 16), np.uint8), (B // 1024, 1)
            ),
        ),
    ):
        rng = np.random.default_rng(0)
        keys = jax.device_put(mk(rng))
        ref = ref_jit(keys)
        ref.block_until_ready()
        t0 = time.perf_counter()
        st, p1 = ti(jnp.zeros((NB, W), jnp.uint32), keys, lengths)
        n1 = int(np.asarray(p1.sum()))
        dt1 = time.perf_counter() - t0
        bitexact = bool(jnp.array_equal(st, ref))
        t0 = time.perf_counter()
        st, p2 = ti(st, keys, lengths)
        n2 = int(np.asarray(p2.sum()))
        dt2 = time.perf_counter() - t0
        ok = bitexact and n1 == 0 and n2 == B
        ok_all &= ok
        print(
            json.dumps(
                {
                    "check": name,
                    "bit_exact_vs_scatter": bitexact,
                    "pres_pass1": n1,
                    "pres_pass2": n2,
                    "expect_pass2": B,
                    "first_pass_s": round(dt1, 3),
                    "second_pass_s": round(dt2, 3),
                    "ok": ok,
                }
            ),
            flush=True,
        )
    return 0 if ok_all else 1


if __name__ == "__main__":
    sys.exit(main())
