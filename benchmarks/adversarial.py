#!/usr/bin/env python
"""Adversarial-skew verification on REAL Mosaic (VERDICT r2 #9, r3 #5).

Interpret-mode passing is weak evidence for this kernel family (Mosaic
has miscompiled lane/sublane patterns silently before — see
ops/sweep.py), so this drives the actual TPU kernels across the shapes
the filters really use:

  * block_bits in {256, 512, 1024} — covers pack=4 (W=8, 16) AND the
    pack=1 fallback (W=32: 1+32+1 lanes exceed the 32-lane stride);
  * storage_fat=True (the entry path persistent filters take) and the
    logical [NB, W] entry;
  * uniform 4M keys (bit-exact vs the XLA sorted-scatter path, fused
    presence replay-verified) and a duplicate-heavy batch (4096 copies
    of 1024 keys — window overflow must trip the host-side lax.cond
    fallback and still be bit-exact, presence included);
  * a small-filter point (m=2^28) so choose_fat_params picks a
    different (R8, S) corner;
  * the fat COUNTING kernel: insert + delete vs the flat-counting
    scatter ref, saturation included.

Prints one JSON line per check and writes them all to
benchmarks/out/adversarial_r4.json. Exit code 1 on any mismatch.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpubloom.config import FilterConfig
from tpubloom.filter import make_blocked_test_insert_fn
from tpubloom.ops import blocked, counting
from tpubloom.ops.sweep import choose_fat_params, fat_pack

OUT_PATH = os.path.join(os.path.dirname(__file__), "out", "adversarial_r5.json")
_rows = []


def emit(obj):
    print(json.dumps(obj), flush=True)
    _rows.append(obj)
    # incremental write: a timeout mid-run must not lose recorded checks
    os.makedirs(os.path.dirname(OUT_PATH), exist_ok=True)
    with open(OUT_PATH, "w") as f:
        for r in _rows:
            f.write(json.dumps(r) + "\n")


def _batches(B):
    rng = np.random.default_rng(0)
    return (
        ("uniform", rng.integers(0, 256, (B, 16), np.uint8)),
        (
            "duplicate-skew",
            np.tile(rng.integers(0, 256, (1024, 16), np.uint8), (B // 1024, 1)),
        ),
    )


def check_bits(log2m, block_bits, storage_fat, B) -> bool:
    config = FilterConfig(m=1 << log2m, k=7, key_len=16, block_bits=block_bits)
    NB, W = config.n_blocks, config.words_per_block
    lengths = jnp.full((B,), 16, jnp.int32)
    params = choose_fat_params(NB, B, W, presence=True)
    shape = (NB * W // 128, 128) if storage_fat else (NB, W)

    def scatter_ref(keys):
        blk, bit = blocked.block_positions(
            keys, lengths, n_blocks=NB, block_bits=block_bits, k=config.k,
            seed=config.seed, block_hash=config.block_hash,
        )
        masks = blocked.build_masks(bit, W)
        return blocked.blocked_insert(
            jnp.zeros((NB, W), jnp.uint32), blk, masks, jnp.ones((B,), bool)
        )

    ti = jax.jit(
        make_blocked_test_insert_fn(config, storage_fat=storage_fat),
        donate_argnums=0,
    )
    ref_jit = jax.jit(scatter_ref)
    ok_cfg = True
    for name, kh in _batches(B):
        keys = jax.device_put(kh)
        ref = np.asarray(ref_jit(keys))
        t0 = time.perf_counter()
        st, p1 = ti(jnp.zeros(shape, jnp.uint32), keys, lengths)
        n1 = int(np.asarray(p1.sum()))
        dt1 = time.perf_counter() - t0
        bitexact = bool(np.array_equal(np.asarray(st).reshape(NB, W), ref))
        t0 = time.perf_counter()
        st, p2 = ti(st, keys, lengths)
        n2 = int(np.asarray(p2.sum()))
        dt2 = time.perf_counter() - t0
        ok = bitexact and n1 == 0 and n2 == B
        ok_cfg &= ok
        emit({
            "check": f"bits m=2^{log2m} bb={block_bits} fat={storage_fat} {name}",
            "pack": fat_pack(W, True),
            "fat_params": params,
            "bit_exact_vs_scatter": bitexact,
            "pres_pass1": n1,
            "pres_pass2": n2,
            "expect_pass2": B,
            "first_pass_s": round(dt1, 3),
            "second_pass_s": round(dt2, 3),
            "ok": ok,
        })
    return ok_cfg


def check_counting(B, log2m=30, block_bits=512) -> bool:
    """Fat counting kernel vs flat-counting scatter ref on real Mosaic."""
    config = FilterConfig(
        m=1 << log2m, k=7, key_len=16, block_bits=block_bits, counting=True
    )
    NB, W = config.n_blocks, config.words_per_block
    cpb = config.counters_per_block
    lengths = jnp.full((B,), 16, jnp.int32)
    from tpubloom.ops.sweep import make_sweep_counter_fn

    def ref_update(blocks, keys, increment):
        blk, cpos = blocked.block_positions(
            keys, lengths, n_blocks=NB, block_bits=cpb, k=config.k,
            seed=config.seed, block_hash=config.block_hash,
        )
        gpos = (blk[:, None] * cpb + cpos.astype(jnp.int32)).astype(jnp.int32)
        vk = jnp.ones(gpos.shape, bool)
        out = counting.counter_update(
            blocks.reshape(-1), gpos.ravel(), vk.ravel(), increment=increment
        )
        return out.reshape(NB, W)

    ins = jax.jit(
        make_sweep_counter_fn(config, increment=True, storage_fat=True),
        donate_argnums=0,
    )
    dele = jax.jit(
        make_sweep_counter_fn(config, increment=False, storage_fat=True),
        donate_argnums=0,
    )
    ref_ins = jax.jit(lambda b, k_: ref_update(b, k_, True))
    ref_del = jax.jit(lambda b, k_: ref_update(b, k_, False))
    fat_shape = (NB * W // 128, 128)
    ok_all = True
    for name, kh in _batches(B):
        keys = jax.device_put(kh)
        t0 = time.perf_counter()
        st = ins(jnp.zeros(fat_shape, jnp.uint32), keys, lengths)
        st = ins(st, keys, lengths)  # second insert: counters reach 2 (or sat)
        ref = ref_ins(ref_ins(jnp.zeros((NB, W), jnp.uint32), keys), keys)
        exact_i = bool(
            np.array_equal(np.asarray(st).reshape(NB, W), np.asarray(ref))
        )
        st = dele(st, keys, lengths)
        ref = ref_del(ref, keys)
        exact_d = bool(
            np.array_equal(np.asarray(st).reshape(NB, W), np.asarray(ref))
        )
        dt = time.perf_counter() - t0
        ok = exact_i and exact_d
        ok_all &= ok
        emit({
            "check": f"counting m=2^{log2m} bb={block_bits} fat=True {name}",
            "pack": fat_pack(W, False),
            "insert_x2_exact": exact_i,
            "delete_exact": exact_d,
            "total_s": round(dt, 3),
            "ok": ok,
        })
    return ok_all


def main() -> int:
    emit({
        "platform": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "note": "bit-exactness vs XLA scatter on REAL Mosaic; presence replay",
    })
    B = 1 << 22
    ok = True
    ok &= check_bits(32, 512, True, B)  # the shipping entry path, pack=4
    ok &= check_bits(32, 512, False, B)  # logical entry
    ok &= check_bits(32, 256, True, B)  # W=8, pack=4
    ok &= check_bits(32, 1024, True, B)  # W=32, pack=1 fallback
    ok &= check_bits(28, 512, True, 1 << 20)  # small filter: other (R8, S)
    ok &= check_counting(B)
    # bb=256 (J=16) counting: the shape whose plane expansions OOMed the
    # pre-bound chooser (RESULTS_r4 §3); small B keeps the slow scatter
    # REFERENCE affordable
    ok &= check_counting(1 << 19, log2m=29, block_bits=256)
    emit({"all_ok": ok})
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
