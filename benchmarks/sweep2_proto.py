#!/usr/bin/env python
"""sweep2 prototype — HISTORICAL (round-3 evidence; superseded by the
pack-4 fat kernel in tpubloom/ops/sweep.py — do not use for current
numbers, see benchmarks/RESULTS_r4.md).

Acting on the round-2/3 ablation data (VERDICT r3 #1).

Measured facts at the north-star shape (B=4M, m=2^32, bb=512, R=512,
KMAX=384, P=16384) from kernel_ablate on this chip:

  A stream-only   46.6ms  -> ~90M keys/s ceiling of the CURRENT structure
  C merge-free    72.6ms  (delta == shipping kernel, bit-identical)
  D shipping      76.6ms

so ~60% of the kernel is the A-floor (grid steps + update-stream DMA),
and the merge machinery costs ~4ms once the delta is merge-free. The
attacks, each a flag here so their contribution is measured separately:

  * narrow update rows: [Btot, 32] lanes instead of [Btot, 128] — the
    stream only carries block id + W mask words + idx = 18 words, so
    128 lanes is 7x DMA waste (2GB/batch instead of 0.5GB).
  * big grid tiles + sub-tiles: R_dma rows per grid step (fewer steps,
    one big window DMA per step) while the one-hot placement matmul
    keeps its own R_sub granularity (total MACs = NB*bb*KMAX_sub do
    NOT grow with R_dma) via dynamic sublane slices of the window.
  * int8 MXU for the placement matmul (operands are 0/1; v5e runs int8
    at 2x bf16 rate).

Insert-only (no presence), no overflow-chunk loop: the host asserts no
sub-window overflows its KMAX_sub fetch window (uniform benchmark keys;
the production port keeps the chunk loop). Every variant's final state
is checked bit-identical (sampled) to the shipping sweep kernel.

Run: PYTHONPATH=/root/repo:$PYTHONPATH python benchmarks/sweep2_proto.py
"""

from __future__ import annotations

import functools
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpubloom.config import FilterConfig
from tpubloom.ops import blocked
from tpubloom.ops.sweep import (
    _ALIGN,
    _pack_positions,
    _stream_scaffold,
    _unpack_positions,
    choose_params,
    sweep_insert,
)

LOG2M = 32
B = 1 << 22
KEY_LEN = 16
STEPS = 32

config = FilterConfig(m=1 << LOG2M, k=7, key_len=KEY_LEN, block_bits=512)
NB, W, K, BB = config.n_blocks, config.words_per_block, config.k, config.block_bits
lengths = jnp.full((B,), KEY_LEN, jnp.int32)


def _u32(x):
    return jnp.asarray(x, jnp.uint32)


def _delta_merge_free(sub, base, R_SUB, KMAX, W, int8: bool, oh_f32=None,
                      bits=None):
    """uint32[R_SUB, W] OR-delta of update window ``sub`` ([KMAX, LANES]:
    col 0 block id, cols 1..W masks) against rows [base, base+R_SUB).
    ``oh_f32``/``bits`` let callers share the one-hot row match and the
    mask bit-plane expansion."""
    if oh_f32 is None:
        rl = (sub[:, 0:1] - base).astype(jnp.int32)
        colsR = lax.broadcasted_iota(jnp.int32, (KMAX, R_SUB), 1)
        oh_f32 = jnp.where(rl == colsR, jnp.float32(1), jnp.float32(0))
    if bits is None:
        m = sub[:, 1 : W + 1]
        colC = lax.broadcasted_iota(jnp.int32, (KMAX, W * 32), 1)
        rep = jnp.concatenate([m] * 32, axis=1)
        bits = (rep >> (colC // W).astype(jnp.uint32)) & _u32(1)
    if int8:
        oh = oh_f32.astype(jnp.int8)
        bits8 = bits.astype(jnp.int8)
        cnt = lax.dot_general(
            oh, bits8, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # [R_SUB, W*32]
        present = jnp.where(cnt > 0, jnp.float32(1), jnp.float32(0)).astype(
            jnp.bfloat16
        )
    else:
        oh = oh_f32.astype(jnp.bfloat16)
        bitsf = bits.astype(jnp.int32).astype(jnp.float32).astype(jnp.bfloat16)
        cnt = lax.dot_general(
            oh, bitsf, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        present = jnp.where(cnt > 0, jnp.float32(1), jnp.float32(0)).astype(
            jnp.bfloat16
        )
    # pack 512 bit-planes -> 4W 8-bit quarters -> W u32 words (all exact)
    ccol = lax.broadcasted_iota(jnp.int32, (W * 32, 4 * W), 0)
    hcol = lax.broadcasted_iota(jnp.int32, (W * 32, 4 * W), 1)
    b_of_c = ccol // W
    w_of_c = lax.rem(ccol, W)
    pack_w = jnp.where(
        (w_of_c + (b_of_c // 8) * W) == hcol,
        (1 << lax.rem(b_of_c, 8)).astype(jnp.float32),
        jnp.float32(0),
    ).astype(jnp.bfloat16)
    quarters = lax.dot_general(
        present, pack_w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(jnp.bfloat16)
    qcol = lax.broadcasted_iota(jnp.int32, (4 * W, W), 0)
    wcol = lax.broadcasted_iota(jnp.int32, (4 * W, W), 1)
    q_of = qcol // W
    w_of = lax.rem(qcol, W)
    comb_lo = jnp.where(
        (w_of == wcol) & (q_of < 2),
        jnp.where(q_of == 0, jnp.float32(1), jnp.float32(256)),
        jnp.float32(0),
    ).astype(jnp.bfloat16)
    comb_hi = jnp.where(
        (w_of == wcol) & (q_of >= 2),
        jnp.where(q_of == 2, jnp.float32(1), jnp.float32(256)),
        jnp.float32(0),
    ).astype(jnp.bfloat16)
    lo = lax.dot_general(
        quarters, comb_lo, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    hi = lax.dot_general(
        quarters, comb_hi, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return lo.astype(jnp.int32).astype(jnp.uint32) | (
        hi.astype(jnp.int32).astype(jnp.uint32) << _u32(16)
    )


def _presence_of(sub, oh_f32, tile, m, KMAX, W):
    """f32[KMAX, 1] pre-update membership of each slot: extract the slot's
    OLD row one 8-bit quarter at a time (bf16-exact) and test
    (row & mask) == mask across all W words."""
    oh = oh_f32.astype(jnp.bfloat16)
    acc_ok = None
    for q in range(4):
        tq = (
            ((tile >> _u32(8 * q)) & _u32(0xFF))
            .astype(jnp.int32)
            .astype(jnp.float32)
            .astype(jnp.bfloat16)
        )
        rq = lax.dot_general(
            oh, tq, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        rq_u = rq.astype(jnp.int32).astype(jnp.uint32)
        mq = (m >> _u32(8 * q)) & _u32(0xFF)
        ok = jnp.where((mq & rq_u) == mq, jnp.float32(1), jnp.float32(0))
        acc_ok = ok if acc_ok is None else acc_ok * ok
    return jnp.min(acc_ok, axis=1, keepdims=True)


def _pack_pres(v, KMAX, LANES_OUT=128):
    """[KMAX, 1] u32 slot values -> [8, LANES_OUT] tile via 4 exact byte
    matmuls (slot j at (j % 8, j // 8); columns >= KMAX//8 are zero
    padding so the output block stays 128-lane aligned — a 48-lane
    output block measurably serializes the out stream). Mosaic has no
    sublane->lane reshape, hence the matmuls."""
    jj8 = lax.broadcasted_iota(jnp.int32, (KMAX, 8), 0)
    aa8 = lax.broadcasted_iota(jnp.int32, (KMAX, 8), 1)
    oh_a = jnp.where(jj8 % 8 == aa8, jnp.float32(1), jnp.float32(0))
    jjc = lax.broadcasted_iota(jnp.int32, (KMAX, LANES_OUT), 0)
    ccc = lax.broadcasted_iota(jnp.int32, (KMAX, LANES_OUT), 1)
    oh_b = jnp.where(jjc // 8 == ccc, jnp.float32(1), jnp.float32(0)).astype(
        jnp.bfloat16
    )
    pres = jnp.zeros((8, LANES_OUT), jnp.uint32)
    for q in range(4):
        vb = ((v >> _u32(8 * q)) & _u32(0xFF)).astype(jnp.int32).astype(
            jnp.float32
        )
        left = (oh_a * vb).astype(jnp.bfloat16)
        outq = lax.dot_general(
            left, oh_b, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        pres = pres | (outq.astype(jnp.int32).astype(jnp.uint32) << _u32(8 * q))
    return pres


def _expand_bits(m, KMAX, W):
    """[KMAX, W] packed words -> [KMAX, W*32] 0/1 bit-planes, b-major
    (column c = b*W + w holds bit b of word w)."""
    colC = lax.broadcasted_iota(jnp.int32, (KMAX, W * 32), 1)
    rep = jnp.concatenate([m] * 32, axis=1)
    return (rep >> (colC // W).astype(jnp.uint32)) & _u32(1)


def _kernel2(
    starts_ref,  # SMEM [P_sub + 1] i32
    upd_ref,  # ANY [Btot, LANES]
    blocks_ref,  # VMEM [R_DMA, W]
    *rest,  # out_ref [, pres_ref], sup_ref, sems
    R_SUB: int,
    S: int,
    KMAX_SUB: int,
    KMAX_BIG: int,
    W: int,
    INT8: bool,
    LEVEL: str = "full",  # "A" stream only | "B" +onehot+bits | "full"
    PRES: bool = False,
    PRESV3: bool = False,
):
    if PRES:
        out_ref, pres_ref, sup_ref, sems = rest
    else:
        out_ref, sup_ref, sems = rest
        pres_ref = None
    p = pl.program_id(0)
    num_p = pl.num_programs(0)

    def off_big(pp):
        return (starts_ref[pp * S] // _ALIGN) * _ALIGN

    def fetch(slot, pp):
        pltpu.make_async_copy(
            upd_ref.at[pl.ds(off_big(pp), KMAX_BIG), :],
            sup_ref.at[slot],
            sems.at[slot],
        ).start()

    def wait(slot):
        pltpu.make_async_copy(
            upd_ref.at[pl.ds(0, KMAX_BIG), :], sup_ref.at[slot], sems.at[slot]
        ).wait()

    slot = lax.rem(p, 2)

    @pl.when(p == 0)
    def _():
        fetch(0, 0)

    @pl.when(p + 1 < num_p)
    def _():
        fetch(1 - slot, p + 1)

    wait(slot)
    if LEVEL == "A":
        row = sup_ref[slot, 0:1, 1 : W + 1]
        out_ref[:] = blocks_ref[:] | (row * _u32(0))
        return
    o_big = off_big(p)
    pres_acc = (
        jnp.zeros((KMAX_SUB, 128), jnp.uint32) if (PRES and PRESV3) else None
    )
    for t in range(S):
        q = p * S + t
        rel = (starts_ref[q] // _ALIGN) * _ALIGN - o_big
        sub = sup_ref[slot, pl.ds(rel, KMAX_SUB), :]
        base = (_u32(p) * _u32(S * R_SUB)) + _u32(t * R_SUB)
        sl = pl.ds(t * R_SUB, R_SUB)
        if LEVEL == "B":
            rl = (sub[:, 0:1] - base).astype(jnp.int32)
            colsR = lax.broadcasted_iota(jnp.int32, (KMAX_SUB, R_SUB), 1)
            m = sub[:, 1 : W + 1]
            colC = lax.broadcasted_iota(jnp.int32, (KMAX_SUB, W * 32), 1)
            rep = jnp.concatenate([m] * 32, axis=1)
            bits = (rep >> (colC // W).astype(jnp.uint32)) & _u32(1)
            oh = jnp.where(rl == colsR, jnp.float32(1), jnp.float32(0))
            cheap = jnp.min(oh, axis=1, keepdims=True) + jnp.min(
                bits.astype(jnp.int32).astype(jnp.float32), axis=1, keepdims=True
            )
            out_ref[sl, :] = blocks_ref[sl, :] | (
                cheap.astype(jnp.int32).astype(jnp.uint32) * _u32(0)
            )
            continue
        rl = (sub[:, 0:1] - base).astype(jnp.int32)
        colsR = lax.broadcasted_iota(jnp.int32, (KMAX_SUB, R_SUB), 1)
        oh_f32 = jnp.where(rl == colsR, jnp.float32(1), jnp.float32(0))
        bits0 = _expand_bits(sub[:, 1 : W + 1], KMAX_SUB, W) if (
            PRES and PRESV3
        ) else None
        delta = _delta_merge_free(sub, base, R_SUB, KMAX_SUB, W, INT8,
                                  oh_f32=oh_f32, bits=bits0)
        if PRES and PRESV3:
            # presence without per-slot extraction matmuls: ONE big int8
            # matmul projects each slot's OLD row bits (oh @ tilebits),
            # then VPU row-sums decide all-mask-bits-present. The 8
            # small matmuls of the v1 scheme cost ~50ms/pass in launch
            # overhead; this is 1 launch + VPU.
            bits = bits0
            tilebits = _expand_bits(blocks_ref[sl, :], R_SUB, W)
            proj = lax.dot_general(
                oh_f32.astype(jnp.int8), tilebits.astype(jnp.int8),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )  # [KMAX, 512] old-row bits per slot (0/1)
            bi = bits.astype(jnp.int32)
            hit = jnp.sum(bi * proj, axis=1, keepdims=True)
            npos = jnp.sum(bi, axis=1, keepdims=True)
            idxp1 = sub[:, W + 1 : W + 2]
            a_q = o_big + rel
            ipos = lax.broadcasted_iota(jnp.int32, (KMAX_SUB, 1), 0) + a_q
            real = (
                (ipos >= starts_ref[q]) & (ipos < starts_ref[q + 1]) & (idxp1 > 0)
            )
            hbit = jnp.where(hit == npos, _u32(0x80000000), _u32(0))
            v = jnp.where(real, idxp1 | hbit, _u32(0))
            # slot values ride column t of the per-step [KMAX, 128] tile
            colp = lax.broadcasted_iota(jnp.int32, (KMAX_SUB, 128), 1)
            pres_acc = pres_acc | jnp.where(colp == t, v, _u32(0))
        elif PRES:
            m = sub[:, 1 : W + 1]
            hit0 = _presence_of(sub, oh_f32, blocks_ref[sl, :], m, KMAX_SUB, W)
            idxp1 = sub[:, W + 1 : W + 2]
            a_q = o_big + rel
            ipos = lax.broadcasted_iota(jnp.int32, (KMAX_SUB, 1), 0) + a_q
            real = (
                (ipos >= starts_ref[q]) & (ipos < starts_ref[q + 1]) & (idxp1 > 0)
            )
            hbit = jnp.where(hit0 > 0.5, _u32(0x80000000), _u32(0))
            v = jnp.where(real, idxp1 | hbit, _u32(0))
            pres_ref[pl.ds(t * 8, 8), :] = _pack_pres(v, KMAX_SUB)
        out_ref[sl, :] = blocks_ref[sl, :] | delta
    if PRES and PRESV3:
        pres_ref[:] = pres_acc


def sweep2_insert(
    blocks, upd, starts, *, R_SUB, S, KMAX_SUB, KMAX_BIG, INT8,
    LEVEL="full", PRES=False, PRESV3=False,
):
    NB_, W_ = blocks.shape
    R_DMA = R_SUB * S
    P = NB_ // R_DMA
    LANES = upd.shape[1]
    out_shape = jax.ShapeDtypeStruct((NB_, W_), jnp.uint32)
    out_spec = pl.BlockSpec((R_DMA, W_), lambda p, *_: (p, 0))
    if PRES and PRESV3:
        # per-step [KMAX_SUB, 128] tile: slot j of sub-tile t at (j, t)
        out_shape = (
            out_shape,
            jax.ShapeDtypeStruct((P * KMAX_SUB, 128), jnp.uint32),
        )
        out_spec = (
            out_spec,
            pl.BlockSpec((KMAX_SUB, 128), lambda p, *_: (p, 0)),
        )
    elif PRES:
        # 128-lane-padded presence tiles (slots live in cols < KMAX_SUB//8)
        out_shape = (
            out_shape,
            jax.ShapeDtypeStruct((P * S * 8, 128), jnp.uint32),
        )
        out_spec = (
            out_spec,
            pl.BlockSpec((S * 8, 128), lambda p, *_: (p, 0)),
        )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(P,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((R_DMA, W_), lambda p, *_: (p, 0)),
        ],
        out_specs=out_spec,
        scratch_shapes=[
            pltpu.VMEM((2, KMAX_BIG, LANES), jnp.uint32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    fn = pl.pallas_call(
        functools.partial(
            _kernel2,
            R_SUB=R_SUB, S=S, KMAX_SUB=KMAX_SUB, KMAX_BIG=KMAX_BIG,
            W=W_, INT8=INT8, LEVEL=LEVEL, PRES=PRES, PRESV3=PRESV3,
        ),
        out_shape=out_shape,
        grid_spec=grid_spec,
        input_output_aliases={2: 0},
    )
    return fn(starts, upd, blocks)


def build_stream(keys, R_sub, KMAX_big, lanes):
    """Sorted update stream (with idx column) + R_sub-granular partition
    boundaries."""
    P_sub = NB // R_sub
    blk, bit = blocked.block_positions(
        keys, lengths, n_blocks=NB, block_bits=BB, k=K, seed=config.seed,
        block_hash=config.block_hash,
    )
    blk = blk.astype(jnp.uint32)
    cols, nbits, packed = _pack_positions(bit, BB, K)
    idx0 = jnp.arange(1, B + 1, dtype=jnp.uint32)
    sorted_cols = lax.sort((blk,) + cols + (idx0,), num_keys=1)
    bs = sorted_cols[0].astype(jnp.int32)
    bit_sorted = _unpack_positions(sorted_cols[1:-1], BB, K, nbits, packed)
    masks = blocked.build_masks(bit_sorted, W)
    starts = jnp.searchsorted(
        bs, (jnp.arange(P_sub + 1, dtype=jnp.int32) * R_sub).astype(jnp.int32)
    ).astype(jnp.int32)
    pad = KMAX_big + _ALIGN
    upd = jnp.zeros((B + pad, lanes), jnp.uint32)
    upd = upd.at[:, 0].set(
        jnp.concatenate(
            [bs.astype(jnp.uint32), jnp.full((pad,), NB, jnp.uint32)]
        )
    )
    upd = upd.at[:B, 1 : W + 1].set(masks)
    upd = upd.at[:B, W + 1].set(sorted_cols[-1])
    return starts, upd


def check_windows(starts, S, KMAX_sub, KMAX_big):
    """No sub-window or big window may overflow its fetch (proto-only:
    the production port keeps the overflow chunk loop instead)."""
    s = np.asarray(starts).astype(np.int64)
    P_sub = len(s) - 1
    a = (s[:-1] // _ALIGN) * _ALIGN  # aligned sub-window starts
    sub_span = s[1:] - a  # rows each sub-window must cover
    o_big = np.repeat((s[0:P_sub:S] // _ALIGN) * _ALIGN, S)
    big_need = a + KMAX_sub - o_big  # KMAX_sub rows are read at offset a
    return int(sub_span.max()), int(big_need.max())


def run_variant(name, starts, upd, *, R_SUB, S, KMAX_SUB, KMAX_BIG, INT8,
                ref_state=None, LEVEL="full", PRES=False, PRESV3=False):
    def step(state, upd, starts):
        out = sweep2_insert(
            state, upd, starts,
            R_SUB=R_SUB, S=S, KMAX_SUB=KMAX_SUB, KMAX_BIG=KMAX_BIG, INT8=INT8,
            LEVEL=LEVEL, PRES=PRES, PRESV3=PRESV3,
        )
        if PRES:
            out, presb = out
            return out, jnp.sum(out[:: NB // 64], dtype=jnp.uint32) + jnp.sum(
                presb[:: max(1, presb.shape[0] // 64)], dtype=jnp.uint32
            )
        return out, jnp.sum(out[:: NB // 64], dtype=jnp.uint32)

    jit = jax.jit(step, donate_argnums=(0,))
    state = jnp.zeros((NB, W), jnp.uint32)
    t0 = time.perf_counter()
    state, carry = jit(state, upd, starts)
    _ = int(np.asarray(carry))  # force a host value: bur alone can LIE here
    compile_s = time.perf_counter() - t0
    ok = None
    if ref_state is not None:
        ok = bool(
            jnp.array_equal(state[:: NB // 4096], ref_state[:: NB // 4096])
        ) and bool(
            jnp.array_equal(state[1 :: NB // 1024], ref_state[1 :: NB // 1024])
        )
    # TIMING RECIPE (measured 2026-07-30): on this axon stack
    # block_until_ready can return WITHOUT waiting for plain-XLA work
    # (a chained 8192^3 matmul "measured" 25,649 TFLOP/s = 130x peak).
    # Only a long chained loop forced to a HOST VALUE is trustworthy;
    # the first to-value sync also carries a large one-time cost, so
    # steps must amortize it.
    t0 = time.perf_counter()
    for _ in range(STEPS):
        state, carry = jit(state, upd, starts)
    carry.block_until_ready()
    bur_dt = (time.perf_counter() - t0) / STEPS
    _ = int(np.asarray(carry))
    dt = (time.perf_counter() - t0) / STEPS
    P = NB // (R_SUB * S)
    implausible = (2 * NB * W * 4 / dt) > 900e9
    print(
        json.dumps(
            {
                "variant": name,
                "timing_implausible": implausible,
                "bur_ms": round(bur_dt * 1e3, 3),
                "R_sub": R_SUB, "S": S, "KMAX_sub": KMAX_SUB,
                "KMAX_big": KMAX_BIG, "lanes": int(upd.shape[1]),
                "int8": INT8, "grid": P,
                "ms": round(dt * 1e3, 3),
                "us_per_grid_step": round(dt / P * 1e6, 3),
                "keys_per_sec": round(B / dt),
                "compile_s": round(compile_s, 1),
                "first_pass_matches_shipping": ok,
            }
        ),
        flush=True,
    )
    del state


def main():
    rng = np.random.default_rng(0)
    keys = jax.device_put(rng.integers(0, 256, (B, KEY_LEN), np.uint8))

    # reference final state: ONE pass of the shipping kernel on the same keys
    R0, KMAX0 = choose_params(NB, B)
    blk, bit = blocked.block_positions(
        keys, lengths, n_blocks=NB, block_bits=BB, k=K, seed=config.seed,
        block_hash=config.block_hash,
    )
    from tpubloom.ops.sweep import apply_blocked_updates

    ref_state = jax.jit(
        lambda b, bl, bi: apply_blocked_updates(
            b, bl, bi, jnp.ones((B,), bool), block_bits=BB, interpret=False
        )
    )(jnp.zeros((NB, W), jnp.uint32), blk, bit)
    ref_state.block_until_ready()

    # lanes are pinned to 128: Mosaic rejects DMA slices whose lane dim is
    # not 128-aligned ("Slice shape along dimension 1 must be aligned to
    # tiling (128), but is 32" — measured 2026-07-30), so a [Btot, 32]
    # stream cannot be window-fetched. The A-floor is per-grid-step
    # overhead, not bytes, so wide rows + big S is the attack.
    variants = [
        # (name, R_sub, S, lanes, int8, level, pres, presv3)
        ("S8 int8 presV3", 512, 8, 128, True, "full", True, True),
        ("S4 int8 presV3", 512, 4, 128, True, "full", True, True),
        ("S8 R256 int8 presV3", 256, 8, 128, True, "full", True, True),
        ("S16 int8 presV3", 512, 16, 128, True, "full", True, True),
    ]
    built = {}
    for name, r_sub, s, lanes, int8, level, pres, presv3 in variants:
        lam_sub = B * r_sub // NB
        KMAX_sub = min(1024, max(16, (lam_sub + max(16, int(8 * lam_sub**0.5)) + 7) // 8 * 8))
        lam_big = lam_sub * s
        KMAX_big = (
            KMAX_sub if s == 1
            else ((lam_big + KMAX_sub + 256 + 7) // 8) * 8
        )
        key_ = (r_sub, KMAX_big, lanes)
        if key_ not in built:
            starts, upd = jax.jit(
                lambda kk: build_stream(kk, r_sub, KMAX_big, lanes)
            )(keys)
            starts.block_until_ready()
            built[key_] = (starts, upd)
        starts, upd = built[key_]
        sub_max, big_need = check_windows(starts, s, KMAX_sub, KMAX_big)
        if sub_max > KMAX_sub or big_need > KMAX_big:
            print(json.dumps({"variant": name, "skip": "window overflow",
                              "sub_max": sub_max, "big_need": big_need}),
                  flush=True)
            continue
        try:
            run_variant(
                name, starts, upd,
                R_SUB=r_sub, S=s, KMAX_SUB=KMAX_sub, KMAX_BIG=KMAX_big,
                INT8=int8, ref_state=ref_state if level == "full" else None,
                LEVEL=level, PRES=pres, PRESV3=presv3,
            )
        except Exception as e:
            print(json.dumps({"variant": name, "error": repr(e)[:400]}),
                  flush=True)


if __name__ == "__main__":
    main()
