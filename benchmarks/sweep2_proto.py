#!/usr/bin/env python
"""sweep2 prototype — acting on the round-2/3 ablation data (VERDICT r3 #1).

Measured facts at the north-star shape (B=4M, m=2^32, bb=512, R=512,
KMAX=384, P=16384) from kernel_ablate on this chip:

  A stream-only   46.6ms  -> ~90M keys/s ceiling of the CURRENT structure
  C merge-free    72.6ms  (delta == shipping kernel, bit-identical)
  D shipping      76.6ms

so ~60% of the kernel is the A-floor (grid steps + update-stream DMA),
and the merge machinery costs ~4ms once the delta is merge-free. The
attacks, each a flag here so their contribution is measured separately:

  * narrow update rows: [Btot, 32] lanes instead of [Btot, 128] — the
    stream only carries block id + W mask words + idx = 18 words, so
    128 lanes is 7x DMA waste (2GB/batch instead of 0.5GB).
  * big grid tiles + sub-tiles: R_dma rows per grid step (fewer steps,
    one big window DMA per step) while the one-hot placement matmul
    keeps its own R_sub granularity (total MACs = NB*bb*KMAX_sub do
    NOT grow with R_dma) via dynamic sublane slices of the window.
  * int8 MXU for the placement matmul (operands are 0/1; v5e runs int8
    at 2x bf16 rate).

Insert-only (no presence), no overflow-chunk loop: the host asserts no
sub-window overflows its KMAX_sub fetch window (uniform benchmark keys;
the production port keeps the chunk loop). Every variant's final state
is checked bit-identical (sampled) to the shipping sweep kernel.

Run: PYTHONPATH=/root/repo:$PYTHONPATH python benchmarks/sweep2_proto.py
"""

from __future__ import annotations

import functools
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpubloom.config import FilterConfig
from tpubloom.ops import blocked
from tpubloom.ops.sweep import (
    _ALIGN,
    _pack_positions,
    _stream_scaffold,
    _unpack_positions,
    choose_params,
    sweep_insert,
)

LOG2M = 32
B = 1 << 22
KEY_LEN = 16
STEPS = 8

config = FilterConfig(m=1 << LOG2M, k=7, key_len=KEY_LEN, block_bits=512)
NB, W, K, BB = config.n_blocks, config.words_per_block, config.k, config.block_bits
lengths = jnp.full((B,), KEY_LEN, jnp.int32)


def _u32(x):
    return jnp.asarray(x, jnp.uint32)


def _delta_merge_free(sub, base, R_SUB, KMAX, W, int8: bool):
    """uint32[R_SUB, W] OR-delta of update window ``sub`` ([KMAX, LANES]:
    col 0 block id, cols 1..W masks) against rows [base, base+R_SUB)."""
    rl = (sub[:, 0:1] - base).astype(jnp.int32)
    colsR = lax.broadcasted_iota(jnp.int32, (KMAX, R_SUB), 1)
    m = sub[:, 1 : W + 1]
    colC = lax.broadcasted_iota(jnp.int32, (KMAX, W * 32), 1)
    rep = jnp.concatenate([m] * 32, axis=1)
    bits = (rep >> (colC // W).astype(jnp.uint32)) & _u32(1)
    if int8:
        oh = jnp.where(rl == colsR, 1, 0).astype(jnp.int8)
        bits8 = bits.astype(jnp.int8)
        cnt = lax.dot_general(
            oh, bits8, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # [R_SUB, W*32]
        present = jnp.where(cnt > 0, jnp.float32(1), jnp.float32(0)).astype(
            jnp.bfloat16
        )
    else:
        oh = jnp.where(rl == colsR, jnp.float32(1), jnp.float32(0)).astype(
            jnp.bfloat16
        )
        bitsf = bits.astype(jnp.int32).astype(jnp.float32).astype(jnp.bfloat16)
        cnt = lax.dot_general(
            oh, bitsf, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        present = jnp.where(cnt > 0, jnp.float32(1), jnp.float32(0)).astype(
            jnp.bfloat16
        )
    # pack 512 bit-planes -> 4W 8-bit quarters -> W u32 words (all exact)
    ccol = lax.broadcasted_iota(jnp.int32, (W * 32, 4 * W), 0)
    hcol = lax.broadcasted_iota(jnp.int32, (W * 32, 4 * W), 1)
    b_of_c = ccol // W
    w_of_c = lax.rem(ccol, W)
    pack_w = jnp.where(
        (w_of_c + (b_of_c // 8) * W) == hcol,
        (1 << lax.rem(b_of_c, 8)).astype(jnp.float32),
        jnp.float32(0),
    ).astype(jnp.bfloat16)
    quarters = lax.dot_general(
        present, pack_w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(jnp.bfloat16)
    qcol = lax.broadcasted_iota(jnp.int32, (4 * W, W), 0)
    wcol = lax.broadcasted_iota(jnp.int32, (4 * W, W), 1)
    q_of = qcol // W
    w_of = lax.rem(qcol, W)
    comb_lo = jnp.where(
        (w_of == wcol) & (q_of < 2),
        jnp.where(q_of == 0, jnp.float32(1), jnp.float32(256)),
        jnp.float32(0),
    ).astype(jnp.bfloat16)
    comb_hi = jnp.where(
        (w_of == wcol) & (q_of >= 2),
        jnp.where(q_of == 2, jnp.float32(1), jnp.float32(256)),
        jnp.float32(0),
    ).astype(jnp.bfloat16)
    lo = lax.dot_general(
        quarters, comb_lo, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    hi = lax.dot_general(
        quarters, comb_hi, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return lo.astype(jnp.int32).astype(jnp.uint32) | (
        hi.astype(jnp.int32).astype(jnp.uint32) << _u32(16)
    )


def _kernel2(
    starts_ref,  # SMEM [P_sub + 1] i32
    upd_ref,  # ANY [Btot, LANES]
    blocks_ref,  # VMEM [R_DMA, W]
    out_ref,  # VMEM [R_DMA, W]
    sup_ref,  # VMEM [2, KMAX_BIG, LANES]
    sems,
    *,
    R_SUB: int,
    S: int,
    KMAX_SUB: int,
    KMAX_BIG: int,
    W: int,
    INT8: bool,
):
    p = pl.program_id(0)
    num_p = pl.num_programs(0)

    def off_big(pp):
        return (starts_ref[pp * S] // _ALIGN) * _ALIGN

    def fetch(slot, pp):
        pltpu.make_async_copy(
            upd_ref.at[pl.ds(off_big(pp), KMAX_BIG), :],
            sup_ref.at[slot],
            sems.at[slot],
        ).start()

    def wait(slot):
        pltpu.make_async_copy(
            upd_ref.at[pl.ds(0, KMAX_BIG), :], sup_ref.at[slot], sems.at[slot]
        ).wait()

    slot = lax.rem(p, 2)

    @pl.when(p == 0)
    def _():
        fetch(0, 0)

    @pl.when(p + 1 < num_p)
    def _():
        fetch(1 - slot, p + 1)

    wait(slot)
    o_big = off_big(p)
    for t in range(S):
        q = p * S + t
        rel = (starts_ref[q] // _ALIGN) * _ALIGN - o_big
        sub = sup_ref[slot, pl.ds(rel, KMAX_SUB), :]
        base = (_u32(p) * _u32(S * R_SUB)) + _u32(t * R_SUB)
        delta = _delta_merge_free(sub, base, R_SUB, KMAX_SUB, W, INT8)
        sl = pl.ds(t * R_SUB, R_SUB)
        out_ref[sl, :] = blocks_ref[sl, :] | delta


def sweep2_insert(blocks, upd, starts, *, R_SUB, S, KMAX_SUB, KMAX_BIG, INT8):
    NB_, W_ = blocks.shape
    R_DMA = R_SUB * S
    P = NB_ // R_DMA
    LANES = upd.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(P,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((R_DMA, W_), lambda p, *_: (p, 0)),
        ],
        out_specs=pl.BlockSpec((R_DMA, W_), lambda p, *_: (p, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, KMAX_BIG, LANES), jnp.uint32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    fn = pl.pallas_call(
        functools.partial(
            _kernel2,
            R_SUB=R_SUB, S=S, KMAX_SUB=KMAX_SUB, KMAX_BIG=KMAX_BIG,
            W=W_, INT8=INT8,
        ),
        out_shape=jax.ShapeDtypeStruct((NB_, W_), jnp.uint32),
        grid_spec=grid_spec,
        input_output_aliases={2: 0},
    )
    return fn(starts, upd, blocks)


def build_stream(keys, R_sub, KMAX_big, lanes):
    """Sorted narrow update stream + R_sub-granular partition boundaries."""
    P_sub = NB // R_sub
    blk, bit = blocked.block_positions(
        keys, lengths, n_blocks=NB, block_bits=BB, k=K, seed=config.seed,
        block_hash=config.block_hash,
    )
    blk = blk.astype(jnp.uint32)
    cols, nbits, packed = _pack_positions(bit, BB, K)
    sorted_cols = lax.sort((blk,) + cols, num_keys=1)
    bs = sorted_cols[0].astype(jnp.int32)
    bit_sorted = _unpack_positions(sorted_cols[1:], BB, K, nbits, packed)
    masks = blocked.build_masks(bit_sorted, W)
    starts = jnp.searchsorted(
        bs, (jnp.arange(P_sub + 1, dtype=jnp.int32) * R_sub).astype(jnp.int32)
    ).astype(jnp.int32)
    pad = KMAX_big + _ALIGN
    upd = jnp.zeros((B + pad, lanes), jnp.uint32)
    upd = upd.at[:, 0].set(
        jnp.concatenate(
            [bs.astype(jnp.uint32), jnp.full((pad,), NB, jnp.uint32)]
        )
    )
    upd = upd.at[:B, 1 : W + 1].set(masks)
    return starts, upd


def check_windows(starts, S, KMAX_sub, KMAX_big):
    """No sub-window or big window may overflow its fetch (proto-only:
    the production port keeps the overflow chunk loop instead)."""
    s = np.asarray(starts).astype(np.int64)
    P_sub = len(s) - 1
    a = (s[:-1] // _ALIGN) * _ALIGN  # aligned sub-window starts
    sub_span = s[1:] - a  # rows each sub-window must cover
    o_big = np.repeat((s[0:P_sub:S] // _ALIGN) * _ALIGN, S)
    big_need = a + KMAX_sub - o_big  # KMAX_sub rows are read at offset a
    return int(sub_span.max()), int(big_need.max())


def run_variant(name, starts, upd, *, R_SUB, S, KMAX_SUB, KMAX_BIG, INT8,
                ref_state=None):
    def step(state, upd, starts):
        out = sweep2_insert(
            state, upd, starts,
            R_SUB=R_SUB, S=S, KMAX_SUB=KMAX_SUB, KMAX_BIG=KMAX_BIG, INT8=INT8,
        )
        return out, jnp.sum(out[:: NB // 64], dtype=jnp.uint32)

    jit = jax.jit(step, donate_argnums=(0,))
    state = jnp.zeros((NB, W), jnp.uint32)
    t0 = time.perf_counter()
    state, carry = jit(state, upd, starts)
    carry.block_until_ready()
    compile_s = time.perf_counter() - t0
    ok = None
    if ref_state is not None:
        ok = bool(
            jnp.array_equal(state[:: NB // 4096], ref_state[:: NB // 4096])
        ) and bool(
            jnp.array_equal(state[1 :: NB // 1024], ref_state[1 :: NB // 1024])
        )
    t0 = time.perf_counter()
    for _ in range(STEPS):
        state, carry = jit(state, upd, starts)
    carry.block_until_ready()
    dt = (time.perf_counter() - t0) / STEPS
    P = NB // (R_SUB * S)
    # blocks stream alone is 2 * NB * W * 4 bytes; faster than HBM can
    # move it means the axon timing anomaly hit (see r_sweep_r3 notes)
    implausible = (2 * NB * W * 4 / dt) > 900e9
    print(
        json.dumps(
            {
                "variant": name,
                "timing_implausible": implausible,
                "R_sub": R_SUB, "S": S, "KMAX_sub": KMAX_SUB,
                "KMAX_big": KMAX_BIG, "lanes": int(upd.shape[1]),
                "int8": INT8, "grid": P,
                "ms": round(dt * 1e3, 3),
                "us_per_grid_step": round(dt / P * 1e6, 3),
                "keys_per_sec": round(B / dt),
                "compile_s": round(compile_s, 1),
                "first_pass_matches_shipping": ok,
            }
        ),
        flush=True,
    )
    del state


def main():
    rng = np.random.default_rng(0)
    keys = jax.device_put(rng.integers(0, 256, (B, KEY_LEN), np.uint8))

    # reference final state: ONE pass of the shipping kernel on the same keys
    R0, KMAX0 = choose_params(NB, B)
    blk, bit = blocked.block_positions(
        keys, lengths, n_blocks=NB, block_bits=BB, k=K, seed=config.seed,
        block_hash=config.block_hash,
    )
    from tpubloom.ops.sweep import apply_blocked_updates

    ref_state = jax.jit(
        lambda b, bl, bi: apply_blocked_updates(
            b, bl, bi, jnp.ones((B,), bool), block_bits=BB, interpret=False
        )
    )(jnp.zeros((NB, W), jnp.uint32), blk, bit)
    ref_state.block_until_ready()

    # lanes are pinned to 128: Mosaic rejects DMA slices whose lane dim is
    # not 128-aligned ("Slice shape along dimension 1 must be aligned to
    # tiling (128), but is 32" — measured 2026-07-30), so a [Btot, 32]
    # stream cannot be window-fetched. The A-floor is per-grid-step
    # overhead, not bytes, so wide rows + big S is the attack.
    variants = [
        # (name, R_sub, S, lanes, int8)
        ("wide128 R512 S1 (C repro)", 512, 1, 128, False),
        ("wide128 R512 S4", 512, 4, 128, False),
        ("wide128 R512 S8", 512, 8, 128, False),
        ("wide128 R256 S16", 256, 16, 128, False),
        ("wide128 R512 S8 int8", 512, 8, 128, True),
        ("wide128 R256 S16 int8", 256, 16, 128, True),
        ("wide128 R128 S32 int8", 128, 32, 128, True),
        ("wide128 R1024 S4", 1024, 4, 128, False),
    ]
    built = {}
    for name, r_sub, s, lanes, int8 in variants:
        lam_sub = B * r_sub // NB
        KMAX_sub = min(1024, max(16, (lam_sub + max(16, int(8 * lam_sub**0.5)) + 7) // 8 * 8))
        lam_big = lam_sub * s
        KMAX_big = (
            KMAX_sub if s == 1
            else ((lam_big + KMAX_sub + 256 + 7) // 8) * 8
        )
        key_ = (r_sub, KMAX_big, lanes)
        if key_ not in built:
            starts, upd = jax.jit(
                lambda kk: build_stream(kk, r_sub, KMAX_big, lanes)
            )(keys)
            starts.block_until_ready()
            built[key_] = (starts, upd)
        starts, upd = built[key_]
        sub_max, big_need = check_windows(starts, s, KMAX_sub, KMAX_big)
        if sub_max > KMAX_sub or big_need > KMAX_big:
            print(json.dumps({"variant": name, "skip": "window overflow",
                              "sub_max": sub_max, "big_need": big_need}),
                  flush=True)
            continue
        try:
            run_variant(
                name, starts, upd,
                R_SUB=r_sub, S=s, KMAX_SUB=KMAX_sub, KMAX_BIG=KMAX_big,
                INT8=int8, ref_state=ref_state,
            )
        except Exception as e:
            print(json.dumps({"variant": name, "error": repr(e)[:400]}),
                  flush=True)


if __name__ == "__main__":
    main()
