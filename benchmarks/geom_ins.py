#!/usr/bin/env python
"""Insert/counting lambda sweep at B=8M (round 5 follow-up to geom8m).

The presence kernel measured monotone-in-lambda across its feasible
range (geom8m_r5.json); insert and counting still target lambda~128.
Feasible-under-caps candidates at B=8M, m=2^32 (counting m=2^30):

  insert:   (128, 8, KJ=224) lam=128 [shipping], (256, 4, KJ=384)
            lam=256, (512, 1, KJ=648) lam=512
  counting: (128, 4, KJ=224) lam=128 [shipping], (256, 2, KJ=384)
            lam=256   ((512, 1) is cap-excluded at 2.88M volume)

Each geometry is FORCED explicitly (reproducible under any future
chooser), run to-value over 8 chained steps; counting alternates
insert/delete so counters stay bounded. Writes
benchmarks/out/geom_ins_r5.json.
"""

from __future__ import annotations

import functools
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpubloom.config import FilterConfig
from tpubloom.ops import sweep

B = 1 << 23
KEY_LEN = 16
STEPS = 8
OUT_PATH = os.path.join(os.path.dirname(__file__), "out", "geom_ins_r5.json")
_rows = []


def emit(obj):
    print(json.dumps(obj), flush=True)
    _rows.append(obj)


_orig_choose = sweep.choose_fat_params


def _force(kind, geom):
    @functools.wraps(_orig_choose)
    def choose(nb, batch, words_per_block=16, *, presence=False,
               counting=False):
        this = "presence" if presence else "counting" if counting else "insert"
        if this == kind and geom is not None:
            return geom
        return _orig_choose(
            nb, batch, words_per_block, presence=presence, counting=counting
        )

    return choose


def run_insert(tag, geom):
    from tpubloom.filter import make_blocked_insert_fn

    sweep.choose_fat_params = _force("insert", geom)
    try:
        config = FilterConfig(m=1 << 32, k=7, key_len=KEY_LEN, block_bits=512)
        ins = make_blocked_insert_fn(config, storage_fat=True)
        lengths = jnp.full((B,), KEY_LEN, jnp.int32)
        state = jnp.zeros((config.n_blocks * 16 // 128, 128), jnp.uint32)

        def step(state, seed):
            keys = jax.random.bits(jax.random.key(seed), (B, KEY_LEN), jnp.uint8)
            state = ins(state, keys, lengths)
            return state, jnp.sum(
                state[:: max(1, state.shape[0] // 64)], dtype=jnp.uint32
            )

        jit = jax.jit(step, donate_argnums=0)
        t0 = time.perf_counter()
        state, carry = jit(state, 0)
        int(np.asarray(carry))
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        for i in range(1, 1 + STEPS):
            state, carry = jit(state, i)
        int(np.asarray(carry))
        dt = (time.perf_counter() - t0) / STEPS
        emit({"kind": "insert", "variant": tag, "geom": list(geom) if geom
              else None, "ms_per_step": round(dt * 1e3, 2),
              "keys_per_sec": round(B / dt), "compile_s": round(compile_s, 1)})
    except Exception as e:  # noqa: BLE001
        emit({"kind": "insert", "variant": tag, "error": str(e)[:300]})
    finally:
        sweep.choose_fat_params = _orig_choose


def run_counting(tag, geom):
    from tpubloom.filter import blocked_device_shape, make_blocked_counter_fn

    sweep.choose_fat_params = _force("counting", geom)
    try:
        config = FilterConfig(
            m=1 << 30, k=7, key_len=KEY_LEN, counting=True, block_bits=512
        )
        ins = make_blocked_counter_fn(config, increment=True, storage_fat=True)
        dele = make_blocked_counter_fn(
            config, increment=False, storage_fat=True
        )
        lengths = jnp.full((B,), KEY_LEN, jnp.int32)

        def step(state, carry, i):
            keys = jax.random.bits(jax.random.key(i // 2), (B, KEY_LEN),
                                   jnp.uint8)
            state = jax.lax.cond(
                i % 2 == 0,
                lambda s: ins(s, keys, lengths),
                lambda s: dele(s, keys, lengths),
                state,
            )
            return state, carry ^ jnp.sum(state[0], dtype=jnp.uint32)

        jit = jax.jit(step, donate_argnums=0)
        state = jnp.zeros(blocked_device_shape(config), jnp.uint32)
        t0 = time.perf_counter()
        state, carry = jit(state, jnp.uint32(0), 0)
        int(np.asarray(carry))
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        for i in range(1, 1 + STEPS):
            state, carry = jit(state, carry, i)
        int(np.asarray(carry))
        dt = (time.perf_counter() - t0) / STEPS
        emit({"kind": "counting", "variant": tag, "geom": list(geom) if geom
              else None, "ms_per_step": round(dt * 1e3, 2),
              "ops_per_sec": round(B / dt), "compile_s": round(compile_s, 1)})
    except Exception as e:  # noqa: BLE001
        emit({"kind": "counting", "variant": tag, "error": str(e)[:300]})
    finally:
        sweep.choose_fat_params = _orig_choose


def main():
    emit({
        "shape": f"insert m=2^32 / counting m=2^30, k=7, blocked512 fat, B={B}",
        "platform": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "timing": f"to-value, {STEPS} chained steps",
    })
    run_insert("lam=128 shipping (128,8,224)", (8, 128, 8, 224, 1312))
    run_insert("lam=256 (256,4,384)", (8, 256, 4, 384, 1472))
    run_insert("lam=512 (512,1,648)", (8, 512, 1, 648, 1224))
    run_counting("lam=128 shipping (128,4,224)", (8, 128, 4, 224, 800))
    run_counting("lam=256 (256,2,384)", (8, 256, 2, 384, 960))
    os.makedirs(os.path.dirname(OUT_PATH), exist_ok=True)
    with open(OUT_PATH, "w") as f:
        for r in _rows:
            f.write(json.dumps(r) + "\n")


if __name__ == "__main__":
    main()
