#!/usr/bin/env python
"""DMA-floor ablation: is the 2.7us/partition stream fetch bandwidth-bound
or per-DMA-overhead-bound? (follow-up to kernel_ablate.py variant A)

  A0    no update DMA at all (grid + blocks auto-pipeline only)
  A48   fetch 48 rows/partition  (24KB)
  A96   fetch 96 rows/partition  (48KB)
  A384  fetch 384 rows/partition (196KB; = variant A)
  A384x2 same bytes in TWO parallel DMAs on separate sems

Timings only (results are wrong on purpose for the small windows).
"""

from __future__ import annotations

import functools
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpubloom.config import FilterConfig
from tpubloom.ops.sweep import _ALIGN, choose_params

LOG2M = 32
B = 1 << 22
STEPS = 8

config = FilterConfig(m=1 << LOG2M, k=7, key_len=16, block_bits=512)
NB, W = config.n_blocks, config.words_per_block
R, KMAX = choose_params(NB, B)
P = NB // R


def _u32(x):
    return jnp.asarray(x, jnp.uint32)


def _kernel(
    starts_ref, upd_ref, blocks_ref, out_ref, sup_ref, sems,
    *, FETCH, NSPLIT,
):
    p = pl.program_id(0)
    num_p = pl.num_programs(0)
    off0 = (starts_ref[p] // _ALIGN) * _ALIGN

    def fetch(slot, off):
        if NSPLIT == 1:
            pltpu.make_async_copy(
                upd_ref.at[pl.ds(off, FETCH), :],
                sup_ref.at[slot, pl.ds(0, FETCH)],
                sems.at[slot, 0],
            ).start()
        else:
            step = FETCH // NSPLIT
            for i in range(NSPLIT):
                pltpu.make_async_copy(
                    upd_ref.at[pl.ds(off + i * step, step), :],
                    sup_ref.at[slot, pl.ds(i * step, step)],
                    sems.at[slot, i],
                ).start()

    def wait(slot):
        if NSPLIT == 1:
            pltpu.make_async_copy(
                upd_ref.at[pl.ds(0, FETCH), :],
                sup_ref.at[slot, pl.ds(0, FETCH)],
                sems.at[slot, 0],
            ).wait()
        else:
            step = FETCH // NSPLIT
            for i in range(NSPLIT):
                pltpu.make_async_copy(
                    upd_ref.at[pl.ds(0, step), :],
                    sup_ref.at[slot, pl.ds(0, step)],
                    sems.at[slot, i],
                ).wait()

    if FETCH:
        slot = lax.rem(p, 2)

        @pl.when(p == 0)
        def _():
            fetch(0, off0)

        @pl.when(p + 1 < num_p)
        def _():
            fetch(1 - slot, (starts_ref[p + 1] // _ALIGN) * _ALIGN)

        wait(slot)
        # REALLY consume the fetched window (no *0 — Mosaic must not be
        # able to fold the use away and DCE the DMAs): OR one real row of
        # the buffer into the tile. Results are wrong; traffic is right.
        row = sup_ref[slot][0:1, 1 : W + 1]
        out_ref[:] = blocks_ref[:] | row
    else:
        out_ref[:] = blocks_ref[:] | _u32(starts_ref[p])


def run(name, FETCH, NSPLIT=1):
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(P,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((R, W), lambda p, *_: (p, 0)),
        ],
        out_specs=pl.BlockSpec((R, W), lambda p, *_: (p, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, max(FETCH, 8), 128), jnp.uint32),
            pltpu.SemaphoreType.DMA((2, max(NSPLIT, 1))),
        ],
    )
    fn = pl.pallas_call(
        functools.partial(_kernel, FETCH=FETCH, NSPLIT=NSPLIT),
        out_shape=jax.ShapeDtypeStruct((NB, W), jnp.uint32),
        grid_spec=grid_spec,
        input_output_aliases={2: 0},
    )
    starts, upd = _DATA

    def step(state, upd, starts):
        out = fn(starts, upd, state)
        return out, jnp.sum(out[:: NB // 64], dtype=jnp.uint32)

    jit = jax.jit(step, donate_argnums=(0,))
    state = jnp.zeros((NB, W), jnp.uint32)
    state, carry = jit(state, upd, starts)
    carry.block_until_ready()
    t0 = time.perf_counter()
    for _ in range(STEPS):
        state, carry = jit(state, upd, starts)
    carry.block_until_ready()
    dt = (time.perf_counter() - t0) / STEPS
    print(
        json.dumps(
            {
                "variant": name,
                "fetch_rows": FETCH,
                "nsplit": NSPLIT,
                "ms": round(dt * 1e3, 3),
                "us_per_partition": round(dt / P * 1e6, 3),
                "eff_GBps": round(
                    (FETCH * 128 * 4 * P) / dt / 1e9, 1
                ) if FETCH else None,
            }
        ),
        flush=True,
    )


_DATA = None


def _build_real_stream():
    """The real sorted update stream from kernel_ablate (block-sorted ids
    + masks), so fetch offsets/values match production."""
    from benchmarks.kernel_ablate import build_stream

    rng = np.random.default_rng(0)
    keys = jax.device_put(rng.integers(0, 256, (B, 16), np.uint8))
    starts, upd = jax.jit(build_stream)(keys)
    starts.block_until_ready()
    return starts, upd


def main():
    global _DATA
    print(json.dumps({"R": R, "KMAX": KMAX, "P": P}), flush=True)
    _DATA = _build_real_stream()
    run("A0 no update DMA", 0)
    run("A48", 48)
    run("A96", 96)
    run("A384", 384)
    run("A384 split2", 384, 2)
    run("A384 split4", 384, 4)


if __name__ == "__main__":
    main()
