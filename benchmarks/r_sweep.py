#!/usr/bin/env python
"""R (partition-size) sweep of the sweep kernel at the north-star shape.

The per-partition merge matmuls scale ~KMAX^2 * block_bits and keys per
partition ~lambda = B*R/n_blocks, so per-key MXU work shrinks with
lambda. This measures kernel-only rates for R in {128, 256, 512, 1024}
at B=4M to find the sweet spot (VERDICT r1 task 1 follow-up).
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tpubloom.config import FilterConfig
from tpubloom.ops import blocked
from tpubloom.ops.sweep import (
    _pack_positions,
    _stream_scaffold,
    _unpack_positions,
    choose_params,
    sweep_insert,
)

LOG2M = 32
B = 1 << 22
KEY_LEN = 16
STEPS = 8

config = FilterConfig(m=1 << LOG2M, k=7, key_len=KEY_LEN, block_bits=512)
NB, W, K, BB = config.n_blocks, config.words_per_block, config.k, config.block_bits
lengths = jnp.full((B,), KEY_LEN, jnp.int32)


def build_stream(keys, R, KMAX):
    P = NB // R
    blk, bit = blocked.block_positions(
        keys, lengths, n_blocks=NB, block_bits=BB, k=K, seed=config.seed,
        block_hash=config.block_hash,
    )
    blk = blk.astype(jnp.uint32)
    cols, nbits, packed = _pack_positions(bit, BB, K)
    idx0 = jnp.arange(1, B + 1, dtype=jnp.uint32)
    sorted_cols = lax.sort((blk,) + cols + (idx0,), num_keys=1)
    bs = sorted_cols[0].astype(jnp.int32)
    bit_sorted = _unpack_positions(sorted_cols[1:-1], BB, K, nbits, packed)
    masks = blocked.build_masks(bit_sorted, W)
    starts, upd = _stream_scaffold(bs, NB, P, R, KMAX)
    upd = upd.at[:B, 1 : W + 1].set(masks)
    upd = upd.at[:B, W + 1].set(sorted_cols[-1])
    return starts, upd


def main():
    import sys

    rng = np.random.default_rng(0)
    keys = jax.device_put(rng.integers(0, 256, (B, KEY_LEN), np.uint8))
    # R values from argv (fresh-process measurement: same-process runs
    # after a 64k-step grid have produced impossible timings on axon)
    r_list = tuple(int(a) for a in sys.argv[1:]) or (128, 256, 512, 1024)
    for R in r_list:
        lam = B // (NB // R)
        _, KMAX = choose_params(NB, B, R=R)
        try:
            starts, upd = jax.jit(lambda k: build_stream(k, R, KMAX))(keys)
            starts.block_until_ready()

            for pres in (True, False):
                def step(state, upd, starts):
                    out = sweep_insert(
                        state, upd, starts, R=R, KMAX=KMAX,
                        interpret=False, with_presence=pres,
                    )
                    if pres:
                        nb2, presb = out
                        return nb2, jnp.sum(presb, dtype=jnp.uint32)
                    return out, jnp.sum(out[:: NB // 64], dtype=jnp.uint32)

                jit = jax.jit(step, donate_argnums=(0,))
                state = jnp.zeros((NB, W), jnp.uint32)
                t0 = time.perf_counter()
                state, carry = jit(state, upd, starts)
                carry.block_until_ready()
                compile_s = time.perf_counter() - t0
                t0 = time.perf_counter()
                for _ in range(STEPS):
                    state, carry = jit(state, upd, starts)
                carry.block_until_ready()
                dt = (time.perf_counter() - t0) / STEPS
                print(
                    json.dumps(
                        {
                            "R": R, "KMAX": KMAX, "lambda": lam,
                            "with_presence": pres,
                            "ms": round(dt * 1e3, 3),
                            "ns_per_key": round(dt / B * 1e9, 3),
                            "keys_per_sec": round(B / dt),
                            "compile_s": round(compile_s, 1),
                        }
                    ),
                    flush=True,
                )
            del state, carry, starts, upd
        except Exception as e:
            print(json.dumps({"R": R, "error": repr(e)[:300]}), flush=True)


if __name__ == "__main__":
    main()
