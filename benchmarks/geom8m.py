#!/usr/bin/env python
"""B=8M presence-geometry probe: does lambda=512 pay at the shipping
batch? (round 5 follow-up to presence_geom.py, which swept B=4M.)

At B=8M the chooser's lambda~256 target picks (R8=256, S=2, KJ=352).
The untested candidate is (R8=512, S=1, KJ=648): half the windows
(16384 -> 8192 per batch) on a kernel measured to be per-window-
overhead-bound, and KJ/lambda drops 1.375 -> 1.27 (fewer unsort rows)
— at the price of 2x placement MACs per key. S=2 at R8=512 is
cap-excluded (5.77M volume). Same keys, replay-asserted, to-value.

Writes benchmarks/out/geom8m_r5.json.
"""

from __future__ import annotations

import functools
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpubloom.config import FilterConfig
from tpubloom.filter import make_blocked_test_insert_fn
from tpubloom.ops import sweep

B = 1 << 23
KEY_LEN = 16
STEPS = 8
OUT_PATH = os.path.join(os.path.dirname(__file__), "out", "geom8m_r5.json")
_rows = []


def emit(obj):
    print(json.dumps(obj), flush=True)
    _rows.append(obj)


_orig_choose = sweep.choose_fat_params


def _force(geom):
    @functools.wraps(_orig_choose)
    def choose(nb, batch, words_per_block=16, *, presence=False,
               counting=False):
        if presence and geom is not None:
            return geom
        return _orig_choose(
            nb, batch, words_per_block, presence=presence, counting=counting
        )

    return choose


def run(tag, geom):
    sweep.choose_fat_params = _force(geom)
    try:
        config = FilterConfig(m=1 << 32, k=7, key_len=KEY_LEN, block_bits=512)
        used = sweep.choose_fat_params(config.n_blocks, B, 16, presence=True)
        fn = make_blocked_test_insert_fn(config, storage_fat=True)
        lengths = jnp.full((B,), KEY_LEN, jnp.int32)
        state = jnp.zeros((config.n_blocks * 16 // 128, 128), jnp.uint32)

        def step(state, seed):
            keys = jax.random.bits(jax.random.key(seed), (B, KEY_LEN), jnp.uint8)
            state, present = fn(state, keys, lengths)
            return state, jnp.sum(present.astype(jnp.uint32))

        jit = jax.jit(step, donate_argnums=0)
        t0 = time.perf_counter()
        state, carry = jit(state, 0)
        int(np.asarray(carry))
        compile_s = time.perf_counter() - t0
        state, carry = jit(state, 0)
        assert int(np.asarray(carry)) == B, "replay must be fully present"
        t0 = time.perf_counter()
        for i in range(1, 1 + STEPS):
            state, carry = jit(state, i)
        int(np.asarray(carry))
        dt = (time.perf_counter() - t0) / STEPS
        emit({
            "variant": tag,
            "geom": list(used),
            "ms_per_step": round(dt * 1e3, 2),
            "fused_keys_per_sec": round(B / dt),
            "compile_s": round(compile_s, 1),
        })
    except Exception as e:  # noqa: BLE001
        emit({"variant": tag, "geom": list(geom) if geom else None,
              "error": str(e)[:300]})
    finally:
        sweep.choose_fat_params = _orig_choose


def main():
    emit({
        "shape": f"m=2^32 k=7 blocked512 fat fused, B={B}",
        "platform": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "timing": f"to-value, {STEPS} chained steps, replay-asserted",
    })
    # Both geometries are FORCED so the comparison stays reproducible:
    # after this probe's result landed, the shipping chooser itself
    # prefers the largest feasible lambda, so the lambda=256 baseline
    # must be pinned explicitly (passing None would measure lambda=512
    # twice and mislabel one row).
    run("lambda=256 baseline (256,2,KJ=352)", (8, 256, 2, 352, 928))
    # lambda=512: KJ = 512 + 6*sqrt(512) ~ 648, KBJ = 512*1 + 648 + 64
    run("lambda=512 (512,1,KJ=648)", (8, 512, 1, 648, 1224))
    os.makedirs(os.path.dirname(OUT_PATH), exist_ok=True)
    with open(OUT_PATH, "w") as f:
        for r in _rows:
            f.write(json.dumps(r) + "\n")


if __name__ == "__main__":
    main()
