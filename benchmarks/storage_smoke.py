#!/usr/bin/env python
"""Multi-tenant paging smoke gate (ISSUE 14).

N ≫ budget tenants round-robin through a small HBM residency budget on
a REAL subprocess server (so the measurement includes gRPC, decode,
hydration futures, eviction checkpoints — everything a production
client pays):

* ``--max-resident-filters 4`` serves ``N_TENANTS`` (64) tenants
  correctly under concurrent load — every write is read back through
  an evict/re-hydrate cycle, with a small HOT set hammered throughout
  (it staying resident is gated indirectly: the hot worker runs ~100x
  the cold op rate, so hot-set thrash would blow the hydrations-per-op
  bound);
* the warm pool is squeezed (``--storage-warm-bytes``) so a share of
  hydrations restore from the COLD (checkpoint) tier, not just host
  RAM;
* gates: zero readback misses, resident count ≤ budget (Health),
  ``storage_hydrations_total`` > 0 with the hydration-latency
  histogram populated (Stats), and an aggregate end-to-end throughput
  floor (``MIN_OPS_PER_SEC``, re-measured once with a doubled window
  before failing — the cluster_smoke discipline for 2-vCPU runners).

Run directly (prints one JSON line) or via tier-1
(``tests/test_storage.py::test_storage_load_smoke``).
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np

N_TENANTS = 64
BUDGET = 4
HOT = 2  # tenants hammered continuously — must stay resident
THREADS = 2
ROUNDS = 1  # full cold-tenant round-robins per measured window

#: aggregate end-to-end ops/sec backstop (one op = insert-1 + readback
#: query, typically paying a hydration in this deliberate-thrash shape
#: — 64 tenants over 2 effective residency slots; measured 2.0 on this
#: image, floor at half). The SHARPER gate is MAX_HYDRATIONS_PER_OP:
#: pure min-heat eviction thrashed concurrent workers' in-progress
#: tenants at ~20 hydrations/op; the banded-LRU rank measures ~3 —
#: a policy regression shows up there long before the wall clock.
MIN_OPS_PER_SEC = 1.0
MAX_HYDRATIONS_PER_OP = 8.0

_SERVER_CHILD = """\
import sys
import jax
jax.config.update("jax_platforms", "cpu")
from tpubloom.server.service import main
main(sys.argv[1:])
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn(workdir: str, port: int) -> subprocess.Popen:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }
    # the perf gate must not measure the debug lock tracker (the armed
    # chaos suites cover that surface; see multichip_load's precedent)
    env.pop("TPUBLOOM_LOCK_CHECK", None)
    script = os.path.join(workdir, "server_child.py")
    with open(script, "w") as f:
        f.write(_SERVER_CHILD)
    return subprocess.Popen(
        [
            sys.executable, script, str(port),
            os.path.join(workdir, "ckpt"),
            "--repl-log-dir", os.path.join(workdir, "oplog"),
            "--max-resident-filters", str(BUDGET),
            "--storage-warm-bytes", str(64 * 1024),
        ],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
    )


def _measure(client_factory, names, hot_names, rounds) -> dict:
    """One measured window: THREADS workers round-robin the cold set
    (insert-1 + strict readback), one worker hammers the hot set."""
    errors: list = []
    misses: list = []
    ops = [0]
    ops_lock = threading.Lock()
    stop = threading.Event()

    def cold_worker(t):
        try:
            with client_factory() as c:
                mine = names[t::THREADS]
                for rnd in range(rounds):
                    for n in mine:
                        key = b"%s-r%d-t%d" % (n.encode(), rnd, t)
                        c.insert_batch(n, [key])
                        if not c.include_batch(n, [key])[0]:
                            misses.append((n, key))
                        with ops_lock:
                            ops[0] += 1
        except BaseException as e:  # noqa: BLE001
            errors.append(repr(e))

    def hot_worker():
        try:
            with client_factory() as c:
                i = 0
                while not stop.is_set():
                    n = hot_names[i % len(hot_names)]
                    c.insert_batch(n, [b"hot-%d" % i])
                    i += 1
                    time.sleep(0.002)
        except BaseException as e:  # noqa: BLE001
            errors.append(repr(e))

    threads = [
        threading.Thread(target=cold_worker, args=(t,)) for t in range(THREADS)
    ]
    ht = threading.Thread(target=hot_worker, daemon=True)
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    ht.start()
    for t in threads:
        t.join(timeout=600)
    elapsed = time.perf_counter() - t0
    stop.set()
    ht.join(timeout=10)
    return {
        "errors": errors,
        "misses": len(misses),
        "ops": ops[0],
        "elapsed_s": round(elapsed, 3),
        "ops_per_sec": round(ops[0] / max(elapsed, 1e-9), 1),
    }


def main() -> dict:
    import tempfile

    from tpubloom.server.client import BloomClient

    workdir = tempfile.mkdtemp(prefix="tpubloom-storage-smoke-")
    port = _free_port()
    proc = _spawn(workdir, port)
    report: dict = {"ok": False, "tenants": N_TENANTS, "budget": BUDGET}
    try:
        with BloomClient(f"127.0.0.1:{port}") as admin:
            admin.wait_ready(timeout=120)
            names = [f"sm-{i:03d}" for i in range(N_TENANTS)]
            hot_names = [f"hot-{i}" for i in range(HOT)]
            for n in hot_names + names:
                admin.create_filter(n, capacity=4000, error_rate=0.01)

            factory = lambda: BloomClient(f"127.0.0.1:{port}")  # noqa: E731
            run = _measure(factory, names, hot_names, ROUNDS)
            if not run["errors"] and run["ops_per_sec"] < MIN_OPS_PER_SEC:
                # 2-vCPU-runner discipline: re-measure once, doubled
                # window, before calling it a regression
                run = _measure(factory, names, hot_names, 2 * ROUNDS)
                run["remeasured"] = True
            report.update(run)

            health = admin.health()
            stats = admin.stats()
            storage = health.get("storage") or {}
            counters = stats.get("process_counters") or {}
            report["resident"] = storage.get("resident")
            report["cold"] = storage.get("cold")
            report["hydrations_total"] = counters.get(
                "storage_hydrations_total", 0
            )
            report["evictions_total"] = counters.get(
                "storage_evictions_total", 0
            )
            report["hydration_hist"] = stats.get("hydration") or {}
            # NOTE on the hot set: per-tenant residency is not exposed,
            # but the no_thrash gate below covers it — the hot worker
            # runs ~100x the cold op rate, so hot tenants falling out
            # of residency would blow hydrations_total far past the
            # per-cold-op bound

            gates = {
                "no_errors": not run["errors"],
                "no_readback_misses": run["misses"] == 0,
                "all_ops_ran": run["ops"] >= N_TENANTS * ROUNDS,
                "budget_held": (storage.get("resident") or 99) <= BUDGET + 1,
                "hydrated": report["hydrations_total"] > 0,
                "cold_tier_exercised": (storage.get("cold") or 0) > 0,
                "hydration_hist_filled": (
                    report["hydration_hist"].get("n", 0) > 0
                ),
                "throughput_floor": (
                    run["ops_per_sec"] >= MIN_OPS_PER_SEC
                ),
                "no_thrash": (
                    report["hydrations_total"]
                    <= MAX_HYDRATIONS_PER_OP * max(run["ops"], 1)
                ),
            }
            report["gates"] = gates
            report["ok"] = all(gates.values())
    finally:
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
    return report


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    out = main()
    print(json.dumps(out))
    sys.exit(0 if out["ok"] else 1)
