#!/usr/bin/env python
"""Config-3 streaming at scale (VERDICT r2 #6).

Part 1 — device-generated stream with checkpoints: insert >= 100M
device-generated keys into an m=2^30 blocked filter in B-key fused
steps (``--batch-log2``, default 4M — the value every r2-r5 artifact
row was measured at; pass 23 for the r5 bench-optimum 8M, which the
m=2^34 52.0M row in streaming_r5.json used), once without checkpoints
and once with the AsyncCheckpointer triggering every
``--ckpt-every-steps * B`` keys (default 8 steps; double-buffered HBM
snapshot + async D2H + background sink write). Reports the checkpoint-induced STALL on the
insert loop (the D2H itself rides the transfer engine and the writes a
background thread; only the HBM copy + scheduling contention can stall
inserts). Target: < 5%.

Part 2 — host-fed pack->H2D->insert with and without the pipeline's
prefetch overlap (background packing thread + early device_put). The
axon tunnel's H2D is the wall here (MB/s, not GB/s); the gain reported
is the overlap's, honestly bounded by transport.

One JSON line per measurement; timings force host values (bur lies on
this stack — benchmarks/RESULTS_r3.md §1).
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpubloom import checkpoint as ckpt
from tpubloom.config import FilterConfig
from tpubloom.filter import BlockedBloomFilter, make_blocked_insert_fn
from tpubloom.parallel.pipeline import StreamInserter

_ap = argparse.ArgumentParser()
_ap.add_argument("--log2m", type=int, default=30)
_ap.add_argument("--total-mkeys", type=int, default=128)
_ap.add_argument("--ckpt-every-steps", type=int, default=8)
_ap.add_argument("--skip-host-fed", action="store_true")
_ap.add_argument("--batch-log2", type=int, default=22, help="device batch size (2^N keys); default 4M reproduces the r2-r5 artifact rows, 23 (=8M) is the r5 bench optimum")
_ap.add_argument(
    "--no-ckpt-only", action="store_true",
    help="run only the no-checkpoint device stream (the m=2^34 spec "
    "point: the 2 GiB filter fits this chip's HBM and streams at speed; "
    "snapshot stalls are tunnel-bound and already characterized by the "
    "8->512 MB payload curve — RESULTS_r4 §7 / r5)",
)
_ARGS = _ap.parse_args()

LOG2M = _ARGS.log2m
B = 1 << _ARGS.batch_log2
TOTAL = _ARGS.total_mkeys * (1 << 20)
CKPT_EVERY_STEPS = _ARGS.ckpt_every_steps  # default 8 steps = 8 * B keys

config = FilterConfig(
    m=1 << LOG2M, k=7, key_len=16, block_bits=512, key_name="stream-bench"
)


def device_stream(with_checkpoints: bool, tmpdir: str) -> dict:
    from tpubloom.filter import blocked_storage_fat

    f = BlockedBloomFilter(config)
    # the class holds FAT storage since r4 — the raw insert fn must match
    insert = make_blocked_insert_fn(
        config, storage_fat=blocked_storage_fat(config)
    )
    lengths = jnp.full((B,), 16, jnp.int32)

    def step(state, seed):
        keys = jax.random.bits(jax.random.key(seed), (B, 16), jnp.uint8)
        return insert(state, keys, lengths)

    jit = jax.jit(step, donate_argnums=0)
    f.words = jit(f.words, 0)
    _ = int(np.asarray(f.words[0, 0]))  # compile + sync
    cp = None
    if with_checkpoints:
        cp = ckpt.AsyncCheckpointer(
            f, ckpt.FileSink(tmpdir), every_n_inserts=CKPT_EVERY_STEPS * B
        )
    steps = TOTAL // B
    t0 = time.perf_counter()
    for i in range(1, 1 + steps):
        f.words = jit(f.words, i)
        if cp:
            cp.notify_inserts(B)
    _ = int(np.asarray(f.words[0, 0]))
    dt = time.perf_counter() - t0
    written = 0
    flush_s = 0.0
    if cp:
        t1 = time.perf_counter()
        ok = cp.close(final_checkpoint=False)
        flush_s = time.perf_counter() - t1
        written = cp.checkpoints_written
        assert ok or written > 0, cp.last_error
    return {
        "keys": steps * B,
        "insert_loop_s": round(dt, 3),
        "keys_per_sec": round(steps * B / dt),
        "checkpoints_written": written,
        "final_flush_s": round(flush_s, 3),
    }


def host_fed(prefetch: int, n_keys: int = 1 << 21) -> dict:
    f = BlockedBloomFilter(config)
    rng = np.random.default_rng(0)
    # pre-generate raw key bytes so generation cost is not measured
    raw = [rng.bytes(16) for _ in range(n_keys)]
    ins = StreamInserter(f, batch_size=1 << 17, prefetch=prefetch)
    t0 = time.perf_counter()
    stats = ins.run(iter(raw))
    _ = int(np.asarray(f.words[0, 0]))
    dt = time.perf_counter() - t0
    return {
        "host_fed_keys": stats["inserted"],
        "prefetch": prefetch,
        "seconds": round(dt, 3),
        "keys_per_sec": round(stats["inserted"] / dt),
    }


def main():
    with tempfile.TemporaryDirectory() as tmp:
        shape = {"log2m": LOG2M, "total_keys": TOTAL,
                 "snapshot_mb": (1 << LOG2M) // 8 // (1 << 20),
                 "ckpt_every_keys": CKPT_EVERY_STEPS * B}
        print(json.dumps({"mode": "shape", **shape}), flush=True)
        base = device_stream(False, tmp)
        print(json.dumps({"mode": "device-stream no-ckpt", **base}), flush=True)
        if _ARGS.no_ckpt_only:
            return
        with_ck = device_stream(True, tmp)
        print(json.dumps({"mode": "device-stream ckpt", **with_ck}), flush=True)
        stall = (
            with_ck["insert_loop_s"] - base["insert_loop_s"]
        ) / base["insert_loop_s"]
        print(
            json.dumps(
                {
                    "mode": "checkpoint stall",
                    "stall_pct": round(100 * stall, 2),
                    "target_pct": 5.0,
                    "ok": stall < 0.05,
                }
            ),
            flush=True,
        )
    if not _ARGS.skip_host_fed:
        for pf in (0, 4):
            print(json.dumps({"mode": "host-fed", **host_fed(pf)}), flush=True)


if __name__ == "__main__":
    main()
