#!/usr/bin/env python
"""Per-stage breakdown + trace-annotation harness for the QUERY pipeline
(ISSUE 12 — the profiler pass ROADMAP item 2 asks for).

Two jobs in one tool:

1. **Stage deltas** (the profile_fat.py methodology, read path edition):
   cumulative-prefix steps with TO-VALUE timing (block_until_ready can
   lie on this stack — benchmarks/RESULTS_r3.md §1), so each stage's
   delta is honest wall time:

     Q0 keygen       device RNG [B, 16] u8
     Q1 +hash        block_positions (3x murmur + fnv)
     Q2 +sort        skey + packed positions + idx (4-col lax.sort)
     Q3 +masks       unpack + build_masks [B, W]
     Q4 +stream      _fat_stream ([BtotP, 128] buffer) + starts
     Q5 +kernel      fat_sweep_query (read-only Pallas sweep)
     Q6 full query   apply_fat_query (+ unsort + overflow cond)
     G  gather ref   the XLA row-gather query (the path Q6 replaces)

   plus kernel-only on a prebuilt stream and the unsort in isolation.
   Every run carries a per-step ``TraceAnnotation`` (the stage name +
   step index), so with ``--profile-dir`` the stages are findable in
   the Perfetto/XProf timeline next to the XLA ops they dispatched —
   this is the occupancy evidence for the r05 ``kernel_s`` 1.87→2.92 s
   batch-doubling regression: compare the per-window device occupancy
   of two traces taken at ``--b4m`` vs the default B=8M.

2. **Bit-exactness**: the harness VERIFIES Q6's verdicts against the
   gather reference on the same keys/state before timing anything — a
   profiling run can never report a fast wrong kernel.

CPU-runnable (interpret mode, reduced shape) so CI and dev boxes can
exercise the harness; the real numbers come from a TPU run at the
north-star shape. Run:

    timeout 2400 python -m benchmarks.profile_query [--b4m] \
        [--profile-dir /tmp/qtrace]

Writes ``benchmarks/out/profile_query_<backend>.json`` (one JSON object
per line); ``--profile-dir`` additionally dumps a loadable
``jax.profiler`` trace per stage group.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tpubloom.config import FilterConfig
from tpubloom.filter import make_blocked_query_fn
from tpubloom.ops import blocked
from tpubloom.ops.sweep import (
    _fat_stream,
    _fat_unsort_presence,
    _pack_positions,
    _packed_rows,
    _unpack_positions,
    apply_fat_query,
    choose_fat_query_params,
    fat_pack,
    fat_sweep_query,
)
from tpubloom.utils import tracing

ON_TPU = jax.default_backend() == "tpu"
if ON_TPU:
    LOG2M = 32
    B = 1 << 22 if "--b4m" in sys.argv else 1 << 23
    STEPS = 16
else:
    # CPU harness shape: big enough that choose_fat_query_params
    # qualifies, small enough that interpret mode finishes in seconds
    LOG2M = 22  # NB = 8192 at bb=512
    B = 1 << 13
    STEPS = 2
KEY_LEN = 16
PROFILE_DIR = None
if "--profile-dir" in sys.argv:
    PROFILE_DIR = os.path.abspath(sys.argv[sys.argv.index("--profile-dir") + 1])

config = FilterConfig(m=1 << LOG2M, k=7, key_len=KEY_LEN, block_bits=512)
NB, W, K, BB = config.n_blocks, config.words_per_block, config.k, config.block_bits
PARAMS = choose_fat_query_params(NB, B, W)
assert PARAMS is not None, f"query chooser rejected the harness shape NB={NB} B={B}"
J, R8, S, KJ, KBJ = PARAMS
PACK = fat_pack(W, True)  # query streams carry the idx column
KJP = _packed_rows(KJ, PACK)
NBJ = NB // J
P8 = NBJ // R8
FAT_SHAPE = (NB * W // 128, 128)
INTERP = not ON_TPU
lengths = jnp.full((B,), KEY_LEN, jnp.int32)

OUT_PATH = os.path.join(
    os.path.dirname(__file__), "out",
    f"profile_query_{jax.default_backend()}.json",
)
_rows = []


def emit(obj):
    print(json.dumps(obj), flush=True)
    _rows.append(obj)


def _u32(x):
    return jnp.asarray(x, jnp.uint32)


def _maybe_trace(name):
    if PROFILE_DIR is None:
        return contextlib.nullcontext()
    return tracing.trace(os.path.join(PROFILE_DIR, name))


def keygen(carry, i):
    return jax.random.bits(
        jax.random.key(i ^ (carry & 0xFFFF)), (B, KEY_LEN), jnp.uint8
    )


def _positions(keys):
    return blocked.block_positions(
        keys, lengths, n_blocks=NB, block_bits=BB, k=K, seed=config.seed,
        block_hash=config.block_hash,
    )


def _sorted_cols(keys):
    blk, bit = _positions(keys)
    valid = jnp.ones((B,), bool)
    blkv = jnp.where(valid, blk, NB)
    j_of = (blkv % J).astype(jnp.uint32)
    rf_of = (blkv // J).astype(jnp.uint32)
    skey = jnp.where(valid, j_of * NBJ + rf_of, _u32(J * NBJ))
    cols, nbits, packed = _pack_positions(bit, BB, K)
    idx0 = jnp.arange(1, B + 1, dtype=jnp.uint32)
    return lax.sort((skey,) + cols + (idx0,), num_keys=1), nbits, packed


def _stream(keys):
    sorted_cols, nbits, packed = _sorted_cols(keys)
    ss = sorted_cols[0]
    bit_sorted = _unpack_positions(sorted_cols[1:-1], BB, K, nbits, packed)
    masks = blocked.build_masks(bit_sorted, W)
    return _fat_stream(
        ss, masks, sorted_cols[-1], J=J, NBJ=NBJ, P8=P8, R8=R8, KBJ=KBJ,
        W=W, pack=PACK,
    )


def q0(state, carry, i):
    keys = keygen(carry, i)
    return jnp.sum(keys, dtype=jnp.uint32)


def q1(state, carry, i):
    keys = keygen(carry, i)
    blk, bit = _positions(keys)
    return jnp.sum(blk.astype(jnp.uint32)) + jnp.sum(bit)


def q2(state, carry, i):
    keys = keygen(carry, i)
    sorted_cols, _, _ = _sorted_cols(keys)
    return sum(jnp.sum(c) for c in sorted_cols)


def q3(state, carry, i):
    keys = keygen(carry, i)
    sorted_cols, nbits, packed = _sorted_cols(keys)
    bit_sorted = _unpack_positions(sorted_cols[1:-1], BB, K, nbits, packed)
    masks = blocked.build_masks(bit_sorted, W)
    return jnp.sum(masks) + jnp.sum(sorted_cols[0])


def q4(state, carry, i):
    keys = keygen(carry, i)
    upd, starts = _stream(keys)
    return jnp.sum(upd, dtype=jnp.uint32) + jnp.sum(starts).astype(jnp.uint32)


def q5(state, carry, i):
    keys = keygen(carry, i)
    upd, starts = _stream(keys)
    presb = fat_sweep_query(
        state, upd, starts, J=J, R8=R8, S=S, KJ=KJ, KBJ=KBJ, W=W,
        interpret=INTERP, pack=PACK,
    )
    return jnp.sum(presb, dtype=jnp.uint32)


def q6(state, carry, i):
    keys = keygen(carry, i)
    blk, bit = _positions(keys)
    hits = apply_fat_query(
        state, blk, bit, jnp.ones((B,), bool),
        block_bits=BB, params=PARAMS, interpret=INTERP, storage_fat=True,
    )
    return jnp.sum(hits.astype(jnp.uint32))


_gather_query = make_blocked_query_fn(
    config.replace(query_path="gather"), storage_fat=True
)


def gref(state, carry, i):
    keys = keygen(carry, i)
    hits = _gather_query(state, keys, lengths)
    return jnp.sum(hits.astype(jnp.uint32))


def run(name, step, state, steps=STEPS):
    """Chained to-value loop with one TraceAnnotation per step — the
    annotation is the handle that correlates this stage's host dispatch
    with its device ops in a --profile-dir trace."""
    jit = jax.jit(step)
    t0 = time.perf_counter()
    carry = jit(state, _u32(0), 0)
    int(np.asarray(carry))  # to-value: compile + first step
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for i in range(1, 1 + steps):
        with tracing.annotate(name, i=i, batch=B):
            carry = jit(state, carry, i)
    val = int(np.asarray(carry))  # ONE host fetch after the chained loop
    dt = (time.perf_counter() - t0) / steps
    emit({
        "stage": name,
        "ms_per_step": round(dt * 1e3, 3),
        "ns_per_key": round(dt / B * 1e9, 3),
        "compile_s": round(compile_s, 1),
        "carry": val & 0xFFFF,
    })
    return dt


def kernel_only(state):
    keys = jax.device_put(
        np.random.default_rng(0).integers(0, 256, (B, KEY_LEN), np.uint8)
    )
    upd, starts = jax.jit(_stream)(keys)
    int(np.asarray(starts[0]))

    def step(state, upd, starts):
        presb = fat_sweep_query(
            state, upd, starts, J=J, R8=R8, S=S, KJ=KJ, KBJ=KBJ, W=W,
            interpret=INTERP, pack=PACK,
        )
        return jnp.sum(presb, dtype=jnp.uint32)

    jit = jax.jit(step)
    carry = jit(state, upd, starts)
    int(np.asarray(carry))
    t0 = time.perf_counter()
    for i in range(STEPS):
        with tracing.annotate("kernel_only", i=i):
            carry = jit(state, upd, starts)
    int(np.asarray(carry))
    dt = (time.perf_counter() - t0) / STEPS
    emit({
        "stage": "kernel_only(prebuilt stream)",
        "ms_per_step": round(dt * 1e3, 3),
        "ns_per_key": round(dt / B * 1e9, 3),
    })


def unsort_only():
    P = P8 // S
    presb = jax.random.bits(jax.random.key(3), (P * PACK * KJP, 128), jnp.uint32)
    keys = jax.device_put(
        np.random.default_rng(0).integers(0, 256, (B, KEY_LEN), np.uint8)
    )
    _, starts = jax.jit(_stream)(keys)

    def step(presb, carry):
        pres = _fat_unsort_presence(
            presb ^ carry, starts, B, J=J, NBJ=NBJ, P8=P8, R8=R8, S=S,
            KJ=PACK * KJP, KBJ=KBJ,
        )
        return jnp.sum(pres.astype(jnp.uint32))

    jit = jax.jit(step)
    carry = jit(presb, _u32(0))
    int(np.asarray(carry))
    t0 = time.perf_counter()
    for i in range(STEPS):
        with tracing.annotate("unsort_only", i=i):
            carry = jit(presb, carry)
    int(np.asarray(carry))
    dt = (time.perf_counter() - t0) / STEPS
    emit({
        "stage": "unsort_only(vkey single-col sort)",
        "ms_per_step": round(dt * 1e3, 3),
        "ns_per_key": round(dt / B * 1e9, 3),
        "rows_sorted": J * P8 * PACK * KJP,
    })


def verify(state):
    """Bit-exactness gate BEFORE any timing: Q6 vs the gather reference
    on the same keys/state (uniform + duplicate-skew). A profiling run
    must never report a fast wrong kernel."""
    rng = np.random.default_rng(7)
    for tag, arr in (
        ("uniform", rng.integers(0, 256, (B, KEY_LEN), np.uint8)),
        ("dup-skew", np.tile(
            rng.integers(0, 256, (16, KEY_LEN), np.uint8), (B // 16, 1)
        )),
    ):
        keys = jnp.asarray(arr)
        blk, bit = _positions(keys)
        got = apply_fat_query(
            state, blk, bit, jnp.ones((B,), bool),
            block_bits=BB, params=PARAMS, interpret=INTERP, storage_fat=True,
        )
        want = _gather_query(state, keys, lengths)
        assert bool((np.asarray(got) == np.asarray(want)).all()), (
            f"query kernel verdicts diverge from the gather reference ({tag})"
        )
    emit({"verified": "sweep query bit-exact vs gather (uniform + dup-skew)"})


def main():
    emit({
        "shape": {
            "m": config.m, "k": K, "B": B, "block_bits": BB, "n_blocks": NB,
            "W": W, "J": J, "R8": R8, "S": S, "KJ": KJ, "KBJ": KBJ,
            "pack": PACK, "lambda": B * R8 // NB,
            "platform": jax.default_backend(),
            "device": str(jax.devices()[0]),
            "interpret": INTERP,
            "timing": "to-value (int(np.asarray(carry)) after chained loop)",
        }
    })
    # a ~quarter-full filter so verdicts are a hit/miss mix (an all-zero
    # array answers every probe False and hides compare work)
    rng = np.random.default_rng(0)
    state = jnp.asarray(
        rng.integers(0, 1 << 32, FAT_SHAPE, np.uint64).astype(np.uint32)
        & rng.integers(0, 1 << 32, FAT_SHAPE, np.uint64).astype(np.uint32)
        & rng.integers(0, 1 << 32, FAT_SHAPE, np.uint64).astype(np.uint32)
    )
    verify(state)
    prev = 0.0
    deltas = {}
    stages = [
        ("Q0 keygen", q0), ("Q1 +hash", q1), ("Q2 +sort", q2),
        ("Q3 +masks", q3), ("Q4 +stream", q4), ("Q5 +kernel", q5),
        ("Q6 full query", q6),
    ]
    with _maybe_trace("stages"):
        for name, fn in stages:
            dt = run(name, fn, state)
            deltas[name] = dt - prev
            prev = dt
        gdt = run("G gather reference", gref, state)
    emit({
        "deltas_ms": {k: round(v * 1e3, 3) for k, v in deltas.items()},
        "query_keys_per_sec": round(B / prev),
        "gather_keys_per_sec": round(B / gdt),
        "speedup_vs_gather": round(gdt / prev, 3),
    })
    with _maybe_trace("kernel"):
        kernel_only(state)
        unsort_only()
    os.makedirs(os.path.dirname(OUT_PATH), exist_ok=True)
    with open(OUT_PATH, "w") as f:
        for r in _rows:
            f.write(json.dumps(r) + "\n")
    if PROFILE_DIR:
        emit({"profile_dir": PROFILE_DIR})


if __name__ == "__main__":
    if not ON_TPU:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    main()
