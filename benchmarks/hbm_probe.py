#!/usr/bin/env python
"""HBM bandwidth / grid-step overhead probe (trustworthy-timing edition).

A plain Pallas copy kernel over the north-star block array (512 MiB) at
several tile sizes R separates the two costs in time(R) = P*c_step +
bytes/BW: small R exposes per-step overhead, large R approaches the DMA
bandwidth ceiling. Timing uses the only recipe this axon stack honors —
long chained loops forced to a host VALUE (block_until_ready can return
early for plain XLA work; see benchmarks/RESULTS_r2.md).
"""

from __future__ import annotations

import functools
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NB, W = 1 << 23, 16  # 512 MiB of u32
STEPS = 32


def _copy_kernel(x_ref, o_ref):
    o_ref[:] = x_ref[:] + jnp.uint32(1)


def run(R, alias: bool):
    P = NB // R
    fn = pl.pallas_call(
        _copy_kernel,
        out_shape=jax.ShapeDtypeStruct((NB, W), jnp.uint32),
        grid=(P,),
        in_specs=[pl.BlockSpec((R, W), lambda p: (p, 0))],
        out_specs=pl.BlockSpec((R, W), lambda p: (p, 0)),
        input_output_aliases={0: 0} if alias else {},
    )

    def step(x):
        y = fn(x)
        return y

    jit = jax.jit(step, donate_argnums=(0,) if alias else ())
    x = jnp.zeros((NB, W), jnp.uint32)
    x = jit(x)
    _ = int(np.asarray(x[0, 0]))
    t0 = time.perf_counter()
    for _i in range(STEPS):
        x = jit(x)
    v = int(np.asarray(x[0, 0]))
    dt = (time.perf_counter() - t0) / STEPS
    print(
        json.dumps(
            {
                "R": R, "P": P, "alias": alias,
                "ms": round(dt * 1e3, 3),
                "us_per_step": round(dt / P * 1e6, 3),
                "GBps_rw": round(2 * NB * W * 4 / dt / 1e9, 1),
                "check": v,
            }
        ),
        flush=True,
    )


def run_fat(R8, alias=True):
    """Same 512 MiB viewed as [NB/8, 128]: full-lane tiles. The (8, 128)
    DMA tiling makes 16-lane tiles waste 8x of the transfer."""
    NB8 = NB // 8
    P = NB8 // R8
    fn = pl.pallas_call(
        _copy_kernel,
        out_shape=jax.ShapeDtypeStruct((NB8, 128), jnp.uint32),
        grid=(P,),
        in_specs=[pl.BlockSpec((R8, 128), lambda p: (p, 0))],
        out_specs=pl.BlockSpec((R8, 128), lambda p: (p, 0)),
        input_output_aliases={0: 0} if alias else {},
    )
    jit = jax.jit(lambda x: fn(x), donate_argnums=(0,) if alias else ())
    x = jnp.zeros((NB8, 128), jnp.uint32)
    x = jit(x)
    _ = int(np.asarray(x[0, 0]))
    t0 = time.perf_counter()
    for _i in range(STEPS):
        x = jit(x)
    v = int(np.asarray(x[0, 0]))
    dt = (time.perf_counter() - t0) / STEPS
    print(
        json.dumps(
            {
                "fat_R8": R8, "P": P, "alias": alias,
                "ms": round(dt * 1e3, 3),
                "GBps_rw": round(2 * NB * W * 4 / dt / 1e9, 1),
                "check": v,
            }
        ),
        flush=True,
    )


def main():
    for R8 in (64, 512, 4096):
        run_fat(R8)
    for R in (512, 2048, 8192):
        run(R, alias=True)
    run(8192, alias=False)


if __name__ == "__main__":
    main()
