#!/usr/bin/env python
"""Query-path load gate (ISSUE 12) — the read-side twin of ingest_load.

Three acceptance checks in one CPU-runnable tool:

1. **Path selection**: ``resolve_query_path`` must pick the dedicated
   read-only query sweep kernel for the north-star shape on a TPU
   backend (the chooser math is backend-independent; the probe compile
   no-ops off-TPU, so this asserts the CHOOSER, which is what decides
   on hardware).
2. **Bit-exactness**: the query kernel (interpret mode on CPU) must
   answer verdict-identical membership to the XLA gather reference —
   uniform keys, duplicate-skew keys (the overflow→gather fallback),
   and tail padding.
3. **Served read throughput**: a real subprocess server with the
   ingestion coalescer, hammered with concurrent ``QueryBatch``
   traffic, must beat the per-request path (a second server without
   the coalescer) — re-measured once with a doubled window before
   failing, with a requests/flush anti-gaming assert so the gate can't
   pass without actual query coalescing (the query-only flushes land
   in ``ingest_query_flushes``).

Run directly (prints one JSON line) or via tier-1
(``tests/test_query_kernel.py::test_query_load_smoke``).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)
sys.path.insert(0, os.path.dirname(_HERE))  # repo root (script runs)

import ingest_load  # noqa: E402 — shared _spawn/_free_port/BATCH helpers

#: concurrent query connections in each aggregate phase.
CONNECTIONS = 8
BATCH = 64
#: the coalesced read path must AT LEAST match the per-request path
#: (ISSUE 12 acceptance); on this CPU image it clears it comfortably —
#: every per-request query pays decode + lock + jit dispatch alone.
GATE = 1.0
#: keys preloaded into the filter so query verdicts are a hit/miss mix.
POPULATION = 1 << 14


def _kernel_path_checks() -> dict:
    """Sections 1 + 2: chooser selection + bit-exactness (in-process)."""
    import jax.numpy as jnp

    from tpubloom.config import FilterConfig
    from tpubloom.ops import blocked, sweep

    # 1. the north-star shape must resolve to the query kernel on TPU
    north = FilterConfig(m=1 << 32, k=7, key_len=16, block_bits=512)
    path = sweep.resolve_query_path(north, 1 << 23, backend="tpu")
    assert path == "sweep", (
        f"north-star shape resolved query_path={path!r} — the dedicated "
        f"query kernel must be selected for served QueryBatch traffic"
    )
    params = sweep.choose_fat_query_params(north.n_blocks, 1 << 23, 16)

    # 2. bit-exactness at a CPU-sized shape (interpret mode)
    nb, bb, k, b = 8192, 512, 7, 8192
    cfg = FilterConfig(m=nb * bb, k=k, key_len=16, block_bits=bb)
    w = cfg.words_per_block
    rng = np.random.default_rng(0)
    keys = jnp.asarray(rng.integers(0, 256, (b, 16), np.uint8))
    lengths = jnp.full((b,), 16, jnp.int32)
    blk, bit = blocked.block_positions(
        keys, lengths, n_blocks=nb, block_bits=bb, k=k, seed=cfg.seed,
        block_hash=cfg.block_hash,
    )
    masks = blocked.build_masks(bit, w)
    state = blocked.blocked_insert(
        jnp.zeros((nb, w), jnp.uint32), blk, masks, jnp.arange(b) < b // 2
    )
    small = sweep.choose_fat_query_params(nb, b, w)
    assert small is not None
    cases = {"uniform": (keys, lengths)}
    dup = jnp.asarray(np.tile(rng.integers(0, 256, (16, 16), np.uint8), (b // 16, 1)))
    cases["dup-skew"] = (dup, lengths)
    cases["tail-pad"] = (keys, lengths.at[b - 64:].set(-1))
    for tag, (ks, ls) in cases.items():
        kb, kbit = blocked.block_positions(
            ks, jnp.maximum(ls, 0), n_blocks=nb, block_bits=bb, k=k,
            seed=cfg.seed, block_hash=cfg.block_hash,
        )
        got = sweep.apply_fat_query(
            state, kb, kbit, ls >= 0, block_bits=bb, params=small,
            interpret=True,
        )
        m = blocked.build_masks(kbit, w)
        want = (jnp.all((state[kb] & m) == m, axis=-1)) & (ls >= 0)
        assert bool((np.asarray(got) == np.asarray(want)).all()), (
            f"query kernel verdicts diverge from the gather reference ({tag})"
        )
    return {
        "north_star_query_path": path,
        "north_star_query_geometry": list(params) if params else None,
        "bit_exact_cases": sorted(cases),
    }


def _query_hammer(addr: str, name: str, threads: int, duration_s: float) -> float:
    """Aggregate keys/sec of `threads` query CONNECTIONS (one client =
    one channel each) probing a 50/50 present/absent key mix."""
    from tpubloom.server.client import BloomClient

    clients = [BloomClient(addr) for _ in range(threads)]
    for c in clients:  # negotiate + warm the channel outside the window
        c.include_batch(name, np.arange(BATCH, dtype=np.uint64))
    stop = time.monotonic() + duration_s
    counts = [0] * threads

    def worker(t):
        c = clients[t]
        present = np.arange(BATCH // 2, dtype=np.uint64) + (
            (t * 131) % (POPULATION // BATCH)
        ) * BATCH
        absent = np.arange(BATCH - BATCH // 2, dtype=np.uint64) + (1 << 50)
        base = np.concatenate([present, absent])
        i = 0
        while time.monotonic() < stop:
            c.include_batch(name, base + (i % 7))
            counts[t] += BATCH
            i += 1

    ts = [threading.Thread(target=worker, args=(t,)) for t in range(threads)]
    t0 = time.perf_counter()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    rate = sum(counts) / (time.perf_counter() - t0)
    for c in clients:
        c.close()
    return rate


def _prime(client, name: str) -> None:
    """Create + populate the filter and compile every query jit bucket a
    coalesced flush can produce (merged sizes pad to powers of two in
    [BATCH, CONNECTIONS*BATCH]) — without this the window eats one XLA
    compile per new shape and the gate measures compile time."""
    client.create_filter(name, capacity=1_000_000, error_rate=0.01)
    pop = np.arange(POPULATION, dtype=np.uint64)
    for off in range(0, POPULATION, 8192):
        client.insert_batch(name, pop[off: off + 8192])
    size = BATCH
    while size <= CONNECTIONS * BATCH:
        client.include_batch(name, np.arange(size, dtype=np.uint64))
        size *= 2


def _counters(client) -> tuple:
    c = client.stats()["counters"]
    return (
        c.get("ingest_query_flushes", 0),
        c.get("ingest_requests_coalesced", 0),
    )


def _measure(addr_coal, addr_direct, name, duration_s, stats_client) -> dict:
    direct = _query_hammer(addr_direct, name, CONNECTIONS, duration_s)
    f0, r0 = _counters(stats_client)
    coalesced = _query_hammer(addr_coal, name, CONNECTIONS, duration_s)
    f1, r1 = _counters(stats_client)
    return {
        "per_request_keys_per_sec": round(direct),
        "coalesced_keys_per_sec": round(coalesced),
        "coalesced_vs_per_request": round(coalesced / direct, 3),
        "query_flushes": f1 - f0,
        "requests_per_flush": round((r1 - r0) / max(f1 - f0, 1), 2),
    }


def run_load(
    duration_s: float = 2.0,
    *,
    coalesce_args: tuple = ("--coalesce-max-keys", "16384",
                            "--coalesce-max-wait-us", "2000"),
) -> dict:
    import tempfile

    from tpubloom.server.client import BloomClient

    out: dict = {
        "connections": CONNECTIONS, "batch": BATCH,
        "duration_s": duration_s,
    }
    out.update(_kernel_path_checks())

    tmpdir = tempfile.mkdtemp(prefix="tpubloom-query-load-")
    procs: list = []
    # this bench GATES a coalesced-vs-per-request margin; the CI chaos
    # shard's armed lock tracker (TPUBLOOM_LOCK_CHECK=1, inherited by
    # subprocesses) taxes the coalescer's queue-condition churn far more
    # than the per-request path — a perf gate must not measure the
    # debug tracker (multichip_load's lesson). Chaos/lock coverage for
    # the query-flush path lives in tests/test_ingest.py.
    drop = ("TPUBLOOM_LOCK_CHECK", "TPUBLOOM_LOCK_CHECK_DIR")
    try:
        cproc, caddr = ingest_load._spawn(
            tmpdir, 0, list(coalesce_args), env_drop=drop
        )
        procs.append(cproc)
        dproc, daddr = ingest_load._spawn(tmpdir, 1, [], env_drop=drop)
        procs.append(dproc)
        cboot = BloomClient(caddr)
        cboot.wait_ready(timeout=180.0)
        dboot = BloomClient(daddr)
        dboot.wait_ready(timeout=180.0)
        _prime(cboot, "q")
        _prime(dboot, "q")
        dboot.close()

        out.update(_measure(caddr, daddr, "q", duration_s, cboot))
        if (
            out["coalesced_vs_per_request"] < GATE
            or out["requests_per_flush"] <= 1.5
        ):
            # one re-measure with a doubled window before failing: on a
            # small shared CI runner a scheduler hiccup inside a 2s
            # window can flip the comparison with no code defect
            out["remeasured"] = True
            out.update(_measure(caddr, daddr, "q", duration_s * 2, cboot))
        cboot.close()
        assert out["coalesced_vs_per_request"] >= GATE, (
            f"coalesced query aggregate ({out['coalesced_keys_per_sec']} "
            f"keys/s over {CONNECTIONS} connections) is only "
            f"{out['coalesced_vs_per_request']}x the per-request path "
            f"({out['per_request_keys_per_sec']}) — query flushes must "
            f"amortize per-request decode+launch (gate {GATE}x)"
        )
        assert out["requests_per_flush"] > 1.5, (
            f"only {out['requests_per_flush']} requests/flush — the "
            f"aggregate gate passed without actual query coalescing"
        )
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.send_signal(signal.SIGKILL)
        for proc in procs:
            try:
                proc.wait(timeout=30)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
    return out


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    print(json.dumps(run_load()))
