#!/usr/bin/env python
"""DMA bandwidth vs lane width (extends hbm_probe's 16-vs-128 finding).

The fat sweep's update stream is [Btot, 128] u32 purely for DMA tile
alignment — only 18 lanes carry data. If 32- or 64-lane arrays DMA at
a usable fraction of the 128-lane rate, the stream can shrink 4x/2x
(both the host-side build write and the in-kernel window fetches).
This probe copies the same 256 MiB through a double-buffered manual-DMA
Pallas kernel at lane widths 16/32/64/128, to-value timing.

Run: PYTHONPATH=/root/repo:/root/.axon_site timeout 900 python benchmarks/lane_probe.py
Writes benchmarks/out/lane_probe_r4.json.
"""

from __future__ import annotations

import functools
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TOTAL_BYTES = 256 << 20
STEPS = 16
OUT_PATH = os.path.join(os.path.dirname(__file__), "out", "lane_probe_r4.json")
_rows = []


def emit(obj):
    print(json.dumps(obj), flush=True)
    _rows.append(obj)


def _copy_kernel(src_ref, out_ref, buf_ref, sems, *, rows_per_step: int, L: int):
    # manual double-buffered DMA: HBM src -> VMEM buf -> HBM out, like the
    # sweep kernel's window fetches (the auto-pipelined path would hide
    # the manual-DMA constraint we actually care about)
    p = pl.program_id(0)
    num_p = pl.num_programs(0)
    slot = lax.rem(p, 2)

    def fetch(s, pp):
        pltpu.make_async_copy(
            src_ref.at[pl.ds(pp * rows_per_step, rows_per_step), :],
            buf_ref.at[s],
            sems.at[s],
        ).start()

    @pl.when(p == 0)
    def _():
        fetch(0, 0)

    @pl.when(p + 1 < num_p)
    def _():
        fetch(1 - slot, p + 1)

    pltpu.make_async_copy(
        src_ref.at[pl.ds(0, rows_per_step), :], buf_ref.at[slot], sems.at[slot]
    ).wait()
    out_ref[...] = buf_ref[slot] + jnp.uint32(1)


def run_width(L: int):
    n_rows = TOTAL_BYTES // 4 // L
    rows_per_step = min(2048 * 128 // L, n_rows)
    while n_rows % rows_per_step:
        rows_per_step //= 2
    grid = n_rows // rows_per_step
    x = jnp.arange(n_rows * L, dtype=jnp.uint32).reshape(n_rows, L)

    fn = pl.pallas_call(
        functools.partial(_copy_kernel, rows_per_step=rows_per_step, L=L),
        grid=(grid,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec((rows_per_step, L), lambda p: (p, 0)),
        out_shape=jax.ShapeDtypeStruct((n_rows, L), jnp.uint32),
        scratch_shapes=[
            pltpu.VMEM((2, rows_per_step, L), jnp.uint32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )

    def step(x):
        return fn(x)

    jit = jax.jit(step, donate_argnums=0)
    t0 = time.perf_counter()
    x = jit(x)
    int(np.asarray(x[0, 0]))
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(STEPS):
        x = jit(x)
    int(np.asarray(x[0, 0]))
    dt = (time.perf_counter() - t0) / STEPS
    gbps = 2 * TOTAL_BYTES / dt / 1e9  # read + write
    emit({
        "lanes": L,
        "rows_per_step": rows_per_step,
        "ms": round(dt * 1e3, 2),
        "GBps_rw": round(gbps, 1),
        "compile_s": round(compile_s, 1),
    })


def main():
    emit({
        "probe": "manual-DMA copy bandwidth vs lane width",
        "bytes": TOTAL_BYTES,
        "platform": jax.default_backend(),
        "device": str(jax.devices()[0]),
    })
    for L in (128, 64, 32, 16, 128):  # repeat 128 to bracket drift
        try:
            run_width(L)
        except Exception as e:  # noqa: BLE001 — record the Mosaic refusal
            msg = str(e)
            key = "Slice shape along dimension 1 must be aligned"
            emit({
                "lanes": L,
                "error": (
                    "Mosaic rejects manual-DMA slices of sub-128-lane "
                    "arrays (it pads their HBM layout to 128 lanes, then "
                    "the slice is misaligned) — narrow update streams "
                    "are impossible; pack multiple updates per 128-lane "
                    "row instead"
                    if key in msg
                    else msg[:300]
                ),
            })
    os.makedirs(os.path.dirname(OUT_PATH), exist_ok=True)
    with open(OUT_PATH, "w") as f:
        for r in _rows:
            f.write(json.dumps(r) + "\n")


if __name__ == "__main__":
    main()
