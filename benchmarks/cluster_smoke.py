#!/usr/bin/env python
"""Cluster smoke: 3-node aggregate throughput vs a single primary
(ISSUE 9), wired into tier-1 (``tests/test_cluster.py::test_cluster_smoke``)
and CI.

What it drives:

* a baseline single-primary **subprocess** server and N cluster
  subprocess servers (``--cluster``) — real processes, so the cluster
  actually buys parallel decode+insert instead of sharing one GIL;
* ``python -m tpubloom.cluster init`` equivalent seeding (even slot
  ranges pushed to every node), filters spread across the shards;
* T writer threads hammering ``InsertBatch`` through the routed
  :class:`tpubloom.cluster.ClusterClient` vs the same load on the
  single primary — aggregate keys/sec both ways;
* the acceptance gate: the cluster's aggregate throughput must beat
  the single-primary baseline — horizontal write scaling is the whole
  point of the subsystem.

Run directly (``python benchmarks/cluster_smoke.py`` — prints one JSON
line) or via tier-1.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

_CHILD = """\
import sys
import jax
jax.config.update("jax_platforms", "cpu")
from tpubloom.server.service import main
main(sys.argv[1:])
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn(tmpdir: str, idx: int, extra_args: list) -> tuple:
    port = _free_port()
    script = os.path.join(tmpdir, f"child-{idx}.py")
    with open(script, "w") as f:
        f.write(_CHILD)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }
    proc = subprocess.Popen(
        [sys.executable, script, str(port), *extra_args],
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT, env=env,
    )
    return proc, f"127.0.0.1:{port}"


def _hammer(insert_fn, names: list, duration_s: float, threads: int,
            batch: int) -> float:
    """Aggregate keys/sec of `threads` writers round-robining filters."""
    stop = time.monotonic() + duration_s
    counts = [0] * threads

    def worker(t):
        i = 0
        while time.monotonic() < stop:
            name = names[(t + i) % len(names)]
            keys = [b"%d-%d-%d" % (t, i, j) for j in range(batch)]
            insert_fn(name, keys)
            counts[t] += batch
            i += 1

    ts = [threading.Thread(target=worker, args=(t,)) for t in range(threads)]
    t0 = time.perf_counter()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    return sum(counts) / (time.perf_counter() - t0)


def run_smoke(
    nodes: int = 3,
    n_filters: int = 6,
    threads: int = 6,
    duration_s: float = 2.0,
    batch: int = 400,
) -> dict:
    import tempfile

    from tpubloom.cluster import slots as S
    from tpubloom.cluster.client import ClusterClient
    from tpubloom.cluster.rebalance import even_ranges
    from tpubloom.server.client import BloomClient

    tmpdir = tempfile.mkdtemp(prefix="tpubloom-cluster-smoke-")
    procs: list = []
    out: dict = {"nodes": nodes, "filters": n_filters, "threads": threads,
                 "duration_s": duration_s, "batch": batch}
    try:
        # spawn everything concurrently — JAX cold start dominates boot
        base_proc, base_addr = _spawn(tmpdir, 99, [])
        procs.append(base_proc)
        shard_addrs = []
        for i in range(nodes):
            proc, addr = _spawn(tmpdir, i, ["--cluster"])
            procs.append(proc)
            shard_addrs.append(addr)
        boot_deadline = 180.0
        BloomClient(base_addr).wait_ready(timeout=boot_deadline)
        for addr in shard_addrs:
            BloomClient(addr).wait_ready(timeout=boot_deadline)

        ranges = even_ranges(shard_addrs)
        for addr in shard_addrs:
            BloomClient(addr).cluster_set_slot(assign=ranges, epoch=1)

        owners = S.expand_ranges(ranges)
        # filter names spread across the shards (greedy round-robin)
        names: list = []
        per_shard = {a: 0 for a in shard_addrs}
        i = 0
        while len(names) < n_filters:
            cand = f"smoke-{i}"
            i += 1
            owner = owners[S.key_slot(cand)]
            if per_shard[owner] <= min(per_shard.values()):
                per_shard[owner] += 1
                names.append(cand)
        out["filters_per_shard"] = dict(per_shard)

        base = BloomClient(base_addr)
        cc = ClusterClient(startup_nodes=shard_addrs)
        for name in names:
            base.create_filter(name, capacity=2_000_000, error_rate=0.01)
            cc.create_filter(name, capacity=2_000_000, error_rate=0.01)
        # warm-up: the first insert per filter pays the jit compile —
        # use the REAL batch shape or the compile lands inside the
        # measurement window instead
        warm = [b"warm-%d" % j for j in range(batch)]
        for name in names:
            base.insert_batch(name, warm)
            cc.insert_batch(name, warm)

        out["baseline_keys_per_sec"] = _hammer(
            base.insert_batch, names, duration_s, threads, batch
        )
        out["cluster_keys_per_sec"] = _hammer(
            cc.insert_batch, names, duration_s, threads, batch
        )
        if out["cluster_keys_per_sec"] <= out["baseline_keys_per_sec"]:
            # one re-measure with a longer window before failing the
            # gate: on small shared CI runners a scheduler hiccup in a
            # 2s window can flip the comparison with no code defect
            out["remeasured"] = True
            out["baseline_keys_per_sec"] = _hammer(
                base.insert_batch, names, duration_s * 2, threads, batch
            )
            out["cluster_keys_per_sec"] = _hammer(
                cc.insert_batch, names, duration_s * 2, threads, batch
            )
        out["speedup"] = (
            out["cluster_keys_per_sec"] / out["baseline_keys_per_sec"]
        )
        assert out["cluster_keys_per_sec"] > out["baseline_keys_per_sec"], (
            f"cluster aggregate throughput "
            f"({out['cluster_keys_per_sec']:.0f} keys/s) did not beat the "
            f"single-primary baseline ({out['baseline_keys_per_sec']:.0f}) "
            f"— horizontal scaling is the acceptance gate"
        )
        base.close()
        cc.close()
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.send_signal(signal.SIGKILL)
        for proc in procs:
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                pass
    return out


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    print(json.dumps(run_smoke()))
