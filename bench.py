#!/usr/bin/env python
"""tpubloom benchmark — BASELINE north-star metric.

Measures batched insert+query throughput at m=2^32, k=7 (BASELINE.json
north_star: >= 1e9 keys/sec/chip on TPU v5e at <= 1% FPR) and prints ONE
JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

Honest-measurement notes (SURVEY.md §6 feasibility):

* Keys are generated ON DEVICE inside the jitted step (jax.random.bits) —
  the host in this image has one CPU core and PCIe could never feed 1B
  16-byte keys/sec, so host->device ingestion is excluded by design and
  reported separately as `e2e_keys_per_sec` for a host-fed batch.
* One unit of work = one key inserted AND queried (the insert+query pair),
  matching the metric name "insert+query keys/sec".
* The TPU attempt runs in a subprocess with a hard timeout: the axon TPU
  tunnel in this image can hang indefinitely at client init (see
  .claude/skills/verify/SKILL.md); on timeout/failure the benchmark falls
  back to CPU and says so in the JSON (`platform` field) rather than
  printing nothing.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BASELINE_TARGET = 1e9  # keys/sec/chip, BASELINE.json north_star

TPU_TIMEOUT_S = int(os.environ.get("TPUBLOOM_BENCH_TPU_TIMEOUT", "900"))
CPU_TIMEOUT_S = int(os.environ.get("TPUBLOOM_BENCH_CPU_TIMEOUT", "600"))


def _run_bench(platform: str) -> dict:
    """Child-process body: the actual measurement."""
    import jax

    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from tpubloom.config import FilterConfig
    from tpubloom.filter import (
        make_blocked_insert_fn,
        make_blocked_query_fn,
        make_blocked_test_insert_fn,
        make_insert_fn,
        make_query_fn,
    )

    on_tpu = jax.default_backend() not in ("cpu",)
    # North-star scale on TPU; reduced on the 1-core CPU fallback so the
    # benchmark terminates, with the scale reported in the JSON.
    if on_tpu:
        # B = 8M is the measured optimum of the clean r5 batch sweep
        # (benchmarks/out/b_sweep_r5.json: 40.5M keys/s vs 38.6M at 4M
        # and an axon-compile wall at 16M); larger B amortizes the
        # whole-array stream and the per-window fixed costs
        log2m, B, steps, key_len = 32, 1 << 23, 16, 16
    else:
        log2m, B, steps, key_len = 26, 1 << 16, 8, 16

    lengths = jnp.full((B,), key_len, jnp.int32)

    def measure(insert, query, state0, steps):
        """Fused insert+query step chain on device-generated keys.

        Returns (keys/sec, compile_s, kernel_s, final_state)."""

        def step(state, seed):
            keys = jax.random.bits(jax.random.key(seed), (B, key_len), jnp.uint8)
            state = insert(state, keys, lengths)
            hits = query(state, keys, lengths)
            return state, jnp.sum(hits.astype(jnp.uint32))

        step_jit = jax.jit(step, donate_argnums=0)
        t0 = time.perf_counter()
        state, hits = step_jit(state0, 0)
        # TIMING RECIPE (measured 2026-07-30, benchmarks/RESULTS_r2.md):
        # on this axon stack block_until_ready can return WITHOUT waiting
        # for the work (a chained 8192^3 matmul "measured" 130x peak), so
        # every timing fence must force a HOST VALUE off the carry.
        n_hits = int(np.asarray(hits))
        compile_s = time.perf_counter() - t0
        assert n_hits == B, "keys inserted in-step must all be found"
        state, _ = step_jit(state, 1)
        t0 = time.perf_counter()
        acc = None
        for i in range(2, 2 + steps):
            state, acc = step_jit(state, i)
        _ = int(np.asarray(acc))
        kernel_s = time.perf_counter() - t0
        return B * steps / kernel_s, compile_s, kernel_s, state

    # -- flagship: blocked (cache-line) layout, FUSED test-and-insert —
    # one device pass per batch performs the insert AND answers pre-batch
    # membership per key (the insert+query pair of the metric; the
    # reference's Lua add script has the same fused semantics).
    blk_config = FilterConfig(m=1 << log2m, k=7, key_len=key_len, block_bits=512)
    # fat [NB/J, 128] storage — the layout persistent filters actually
    # hold; the logical [NB, W] entry pays a real reshape copy per pass
    # (~26 ms at m=2^32, benchmarks/RESULTS_r3.md §2)
    from tpubloom.filter import blocked_device_shape, blocked_storage_fat

    blk_fat = blocked_storage_fat(blk_config)
    blk_insert = make_blocked_insert_fn(blk_config, storage_fat=blk_fat)
    blk_query = make_blocked_query_fn(blk_config, storage_fat=blk_fat)
    blk_ti = make_blocked_test_insert_fn(blk_config, storage_fat=blk_fat)
    blk_state0 = jnp.zeros(blocked_device_shape(blk_config), jnp.uint32)

    def fused_step(state, seed):
        keys = jax.random.bits(jax.random.key(seed), (B, key_len), jnp.uint8)
        state, present = blk_ti(state, keys, lengths)
        return state, jnp.sum(present.astype(jnp.uint32))

    fused_jit = jax.jit(fused_step, donate_argnums=0)
    t0 = time.perf_counter()
    blk_state, n_pre = fused_jit(blk_state0, 0)
    _ = int(np.asarray(n_pre))  # host value: bur alone can lie (see above)
    blk_compile = time.perf_counter() - t0
    # sanity: replaying the same keys must report every key present
    blk_state, n_rep = fused_jit(blk_state, 0)
    assert int(np.asarray(n_rep)) == B, "replayed batch must be fully present"
    t0 = time.perf_counter()
    acc = None
    for i in range(1, 1 + steps):
        blk_state, acc = fused_jit(blk_state, i)
    _ = int(np.asarray(acc))
    blk_kernel = time.perf_counter() - t0
    blk_rate = B * steps / blk_kernel

    # split (separate insert step + query step) rate, for comparison.
    # >= 8 steps: the to-value sync carries a large one-time cost on the
    # axon tunnel and short sections over-report per-step time.
    split_steps = max(8, steps // 2)
    split_rate, _, _, blk_state = measure(
        blk_insert, blk_query, blk_state, split_steps
    )

    # each half on its own (VERDICT r5: the fused headline plus both
    # single-op rates so the presence/query costs are visible)
    def ins_step(state, seed):
        keys = jax.random.bits(jax.random.key(seed), (B, key_len), jnp.uint8)
        state = blk_insert(state, keys, lengths)
        return state, jnp.sum(
            state[:: max(1, state.shape[0] // 64)], dtype=jnp.uint32
        )

    ins_jit = jax.jit(ins_step, donate_argnums=0)
    blk_state, acc = ins_jit(blk_state, 999)
    _ = int(np.asarray(acc))
    half_steps = max(8, steps // 2)
    t0 = time.perf_counter()
    for i in range(1000, 1000 + half_steps):
        blk_state, acc = ins_jit(blk_state, i)
    _ = int(np.asarray(acc))
    insert_only_rate = B * half_steps / (time.perf_counter() - t0)

    def qry_step(state, carry, seed):
        keys = jax.random.bits(
            jax.random.key(seed ^ (carry & 0xFF)), (B, key_len), jnp.uint8
        )
        hits = blk_query(state, keys, lengths)
        return jnp.sum(hits.astype(jnp.uint32))

    qry_jit = jax.jit(qry_step)
    carry = qry_jit(blk_state, jnp.uint32(0), 0)
    _ = int(np.asarray(carry))
    # --profile-dir (ISSUE 12): dump a jax.profiler trace of the
    # query-only loop with per-step TraceAnnotations — the occupancy
    # evidence ROADMAP item 2 asks for (open in Perfetto/XProf; the
    # per-PHASE stage breakdown lives in benchmarks/profile_query.py).
    # Profiling adds tracer overhead, so the profiled loop's rate is
    # flagged rather than silently recorded as a clean number.
    profile_dir = os.environ.get("TPUBLOOM_BENCH_PROFILE_DIR")
    t0 = time.perf_counter()
    if profile_dir:
        from tpubloom.utils import tracing

        with tracing.trace(os.path.join(profile_dir, "query_only")):
            for i in range(1, 1 + half_steps):
                with tracing.annotate("query_only_step", i=i, batch=B):
                    carry = qry_jit(blk_state, carry, i)
            _ = int(np.asarray(carry))
    else:
        for i in range(1, 1 + half_steps):
            carry = qry_jit(blk_state, carry, i)
        _ = int(np.asarray(carry))
    kernel_query_s = time.perf_counter() - t0
    query_only_rate = B * half_steps / kernel_query_s

    # -- reference-compatible flat layout (the Redis-bitmap position spec)
    config = FilterConfig(m=1 << log2m, k=7, key_len=key_len)
    insert = make_insert_fn(config)
    query = make_query_fn(config)
    flat_steps = max(6, steps // 3)  # flat is the slow path; sample it
    flat_rate, _, _, _ = measure(
        insert, query, jnp.zeros((config.n_words,), jnp.uint32), flat_steps
    )

    # end-to-end rate with host-packed keys (the gRPC-server ingest path),
    # on the flagship blocked path. Fixed 1M host batch regardless of the
    # device batch B: this measures host ingestion on the 1-core host, and
    # a larger sample only burns untimed setup inside the subprocess
    # timeout without changing the rate. The per-phase split uses the
    # same phase names as the server's /metrics breakdown
    # (host_prep / h2d / kernel / d2h — tpubloom.obs.context), so a
    # transport-bound regression (h2d ballooning with tunnel weather)
    # reads the same in both places.
    from tpubloom.utils.packing import pack_keys

    Bh = min(B, 1 << 20)
    rng = np.random.default_rng(0)
    raw_keys = [rng.bytes(key_len) for _ in range(Bh)]
    insert_jit = jax.jit(blk_insert, donate_argnums=0)
    query_jit = jax.jit(blk_query)
    phases = {}
    t0 = time.perf_counter()
    ku8, kl = pack_keys(raw_keys, key_len)
    phases["host_prep_s"] = time.perf_counter() - t0
    blk_state = insert_jit(blk_state, ku8, kl)  # compile for this path
    t0 = time.perf_counter()
    ku8_d, kl_d = jnp.asarray(ku8), jnp.asarray(kl)
    jax.block_until_ready((ku8_d, kl_d))
    phases["h2d_s"] = time.perf_counter() - t0
    t0 = time.perf_counter()
    blk_state = insert_jit(blk_state, ku8_d, kl_d)
    hits = query_jit(blk_state, ku8_d, kl_d)
    jax.block_until_ready(hits)
    phases["kernel_s"] = time.perf_counter() - t0
    t0 = time.perf_counter()
    hits_np = np.asarray(hits)  # D2H of the verdicts is part of e2e
    phases["d2h_s"] = time.perf_counter() - t0
    # e2e keeps its historical definition (h2d + kernel + d2h — what the
    # rounds-1..5 records measured) so the number stays comparable;
    # host_prep is reported in the phase breakdown only
    e2e_s = phases["h2d_s"] + phases["kernel_s"] + phases["d2h_s"]
    assert bool(hits_np.all())

    # FPR sanity at the end state of the flagship chain. Distinct-key
    # accounting: fused chain used seeds 0..steps; the split re-measure
    # runs seeds 0..split_steps+1 (on the CPU fallback, steps=8, that
    # reaches past the fused chain's seeds — count the excess); the
    # insert-only loop added 1 + half_steps batches at fresh seeds
    # (999, 1000..); the query-only loop inserts nothing.
    n_inserted = (
        B * (1 + steps)
        + B * max(0, split_steps + 1 - steps)
        + Bh
        + B * (1 + half_steps)
    )
    probe = jax.random.bits(jax.random.key(10_000_019), (B, key_len), jnp.uint8)
    fpr = float(np.asarray(query_jit(blk_state, probe, lengths)).mean())

    from tpubloom.ops.sweep import effective_query_path, resolve_insert_path

    insert_path = resolve_insert_path(blk_config, B)
    query_path = effective_query_path(blk_config, B)
    return {
        "metric": f"batched insert+query keys/sec/chip @ m=2^{log2m}, k=7",
        "value": round(blk_rate),
        "unit": "keys/sec",
        "vs_baseline": round(blk_rate / BASELINE_TARGET, 6),
        "platform": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "layout": "blocked512",
        "op": "fused test-and-insert (pre-batch membership + insert per key)",
        "insert_path": insert_path,
        "query_path": query_path,
        "split_keys_per_sec": round(split_rate),
        "insert_only_keys_per_sec": round(insert_only_rate),
        # the read-path trajectory (ISSUE 12): BENCH rounds track the
        # query-only rate and its loop time from r06 on, so the query
        # kernel's effect is a first-class number next to kernel_s
        "query_only_keys_per_sec": round(query_only_rate),
        "kernel_query_s": round(kernel_query_s, 4),
        "query_profiled": bool(profile_dir),
        "m": blk_config.m,
        "k": blk_config.k,
        "batch": B,
        "steps": steps,
        "compile_s": round(blk_compile, 2),
        "kernel_s": round(blk_kernel, 4),
        "flat_keys_per_sec": round(flat_rate),
        "e2e_keys_per_sec": round(Bh / e2e_s),
        "e2e_phases": {k: round(v, 5) for k, v in phases.items()},
        "e2e_phases_note": (
            "same phase vocabulary as the server's "
            "tpubloom_rpc_phase_seconds /metrics histogram "
            "(host_prep/h2d/kernel/d2h; bench has no decode/encode)"
        ),
        "e2e_note": (
            "host-fed rate is axon-tunnel transport-bound, NOT code-bound: "
            "H2D over this tunnel varies 0.2-20 MB/s across rounds "
            "(r1 240k, r2 126k, r3 110k keys/s were tunnel weather); "
            "compare split_keys_per_sec for the device-side rate"
        ),
        "observed_fpr": fpr,
        "n_inserted": n_inserted,
    }


def _child_main() -> None:
    platform = sys.argv[2]
    result = _run_bench(platform)
    print("TPUBLOOM_RESULT " + json.dumps(result), flush=True)


def _spawn(platform: str, timeout: int):
    env = dict(os.environ)
    if platform == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child", platform],
            capture_output=True,
            text=True,
            timeout=timeout,
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return None, f"timeout after {timeout}s"
    for line in proc.stdout.splitlines():
        if line.startswith("TPUBLOOM_RESULT "):
            return json.loads(line[len("TPUBLOOM_RESULT "):]), None
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-3:]
    return None, f"exit {proc.returncode}: {' | '.join(tail)}"


def main() -> None:
    if len(sys.argv) > 2 and sys.argv[1] == "--child":
        _child_main()
        return
    # --profile-dir <path>: capture a jax.profiler trace of the measured
    # loops (per-step TraceAnnotations; benchmarks/profile_query.py has
    # the per-STAGE harness). Passed to the child via the environment so
    # the subprocess isolation keeps working unchanged.
    if "--profile-dir" in sys.argv:
        i = sys.argv.index("--profile-dir")
        if i + 1 >= len(sys.argv):
            print("--profile-dir needs a path", file=sys.stderr)
            raise SystemExit(2)
        os.environ["TPUBLOOM_BENCH_PROFILE_DIR"] = os.path.abspath(
            sys.argv[i + 1]
        )
    attempts = []
    result, err = _spawn("tpu", TPU_TIMEOUT_S)
    if result is None and not err.startswith("timeout after"):
        # one retry: the axon tunnel's compile service intermittently
        # drops connections ("response body closed", HTTP 500) — a
        # transient failure must not record a CPU number for the round.
        # Timeouts are NOT retried: a hang repeats and would double the
        # time to the CPU fallback.
        attempts.append({"platform": "tpu", "error": err})
        result, err = _spawn("tpu", TPU_TIMEOUT_S)
    if result is None:
        attempts.append({"platform": "tpu", "error": err})
        result, err = _spawn("cpu", CPU_TIMEOUT_S)
    if result is None:
        attempts.append({"platform": "cpu", "error": err})
        result = {
            "metric": "batched insert+query keys/sec/chip @ m=2^32, k=7",
            "value": 0,
            "unit": "keys/sec",
            "vs_baseline": 0.0,
            "error": attempts,
        }
    elif attempts:
        result["fallback_from"] = attempts
    print(json.dumps(result))


if __name__ == "__main__":
    main()
