"""CPU reference oracle — the correctness ground truth for every TPU kernel.

A vectorized NumPy bloom filter (plain + counting) implementing the exact
position spec of :mod:`tpubloom.ops.hashing`; the BASELINE metric is
"FPR drift vs CPU ref", so every device kernel result (bit positions,
membership booleans, FPR) is cross-checked against this module in tests
(SURVEY.md §4.2 item 1).

Parity: this plays the role of the reference's ``:ruby`` driver — the
client-side, non-accelerated implementation that defines semantics
(SURVEY.md §2.1; BASELINE config 1 "pure-Ruby driver (CPU ref)"). The hash
hot path optionally dispatches to the C++ native library
(``tpubloom/native``) when built, mirroring how the reference leans on a
native component (Redis) for the heavy lifting.
"""

from __future__ import annotations

import math
from typing import Sequence

import numpy as np

from tpubloom import native
from tpubloom.config import FilterConfig
from tpubloom.ops.hashing import SEED_XOR_GB, SEED_XOR_HB
from tpubloom.utils.packing import pack_keys, redis_bitmap_to_words, words_to_redis_bitmap

_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)
_FNV_OFFSET = np.uint32(0x811C9DC5)
_FNV_PRIME = np.uint32(0x01000193)


def _rotl32(x: np.ndarray, r: int) -> np.ndarray:
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))


def murmur3_32_np(keys: np.ndarray, lengths: np.ndarray, seed: int) -> np.ndarray:
    """Vectorized MurmurHash3_x86_32 — mirrors tpubloom.ops.hashing.murmur3_32."""
    keys = np.asarray(keys, dtype=np.uint8)
    lengths = np.asarray(lengths, dtype=np.int32)
    L = keys.shape[-1]
    kb = keys.astype(np.uint32)
    blocks = (
        kb[..., 0::4]
        | (kb[..., 1::4] << np.uint32(8))
        | (kb[..., 2::4] << np.uint32(16))
        | (kb[..., 3::4] << np.uint32(24))
    )
    h = np.full(lengths.shape, np.uint32(seed), dtype=np.uint32)
    for i in range(L // 4):
        kk = blocks[..., i] * _C1
        kk = _rotl32(kk, 15)
        kk = kk * _C2
        rem = lengths - 4 * i
        h_full = _rotl32(h ^ kk, 13) * np.uint32(5) + np.uint32(0xE6546B64)
        h_tail = h ^ kk
        h = np.where(rem >= 4, h_full, np.where(rem > 0, h_tail, h))
    h = h ^ lengths.astype(np.uint32)
    h = h ^ (h >> np.uint32(16))
    h = h * np.uint32(0x85EBCA6B)
    h = h ^ (h >> np.uint32(13))
    h = h * np.uint32(0xC2B2AE35)
    h = h ^ (h >> np.uint32(16))
    return h


def fnv1a_32_np(keys: np.ndarray, lengths: np.ndarray) -> np.ndarray:
    """Vectorized FNV-1a 32 — mirrors tpubloom.ops.hashing.fnv1a_32."""
    keys = np.asarray(keys, dtype=np.uint8)
    lengths = np.asarray(lengths, dtype=np.int32)
    L = keys.shape[-1]
    h = np.full(lengths.shape, _FNV_OFFSET, dtype=np.uint32)
    kb = keys.astype(np.uint32)
    for j in range(L):
        h_next = (h ^ kb[..., j]) * _FNV_PRIME
        h = np.where(j < lengths, h_next, h)
    return h


def positions_np(
    keys: np.ndarray, lengths: np.ndarray, *, m: int, k: int, seed: int
) -> np.ndarray:
    """The k positions per key as ``uint64[B, k]`` (exact spec arithmetic)."""
    h_a = murmur3_32_np(keys, lengths, seed).astype(np.uint64)
    if (m & (m - 1)) == 0:
        h_b = murmur3_32_np(keys, lengths, seed ^ SEED_XOR_HB).astype(np.uint64)
        g_a = fnv1a_32_np(keys, lengths).astype(np.uint64)
        g_b = murmur3_32_np(keys, lengths, seed ^ SEED_XOR_GB).astype(np.uint64)
        H1 = (h_b << np.uint64(32)) | h_a
        H2 = ((g_b << np.uint64(32)) | g_a) | np.uint64(1)
        i = np.arange(k, dtype=np.uint64)
        with np.errstate(over="ignore"):
            pos = H1[..., None] + i * H2[..., None]  # u64 wrap == mod 2^64
        return pos & np.uint64(m - 1)
    if m >= (1 << 31):
        raise ValueError("non-power-of-two m must be < 2^31")
    g_a = fnv1a_32_np(keys, lengths) | np.uint32(1)
    i = np.arange(k, dtype=np.uint32)
    with np.errstate(over="ignore"):
        pos32 = h_a.astype(np.uint32)[..., None] + i * g_a[..., None]
    return (pos32 % np.uint32(m)).astype(np.uint64)


def blocked_positions_np(
    keys: np.ndarray,
    lengths: np.ndarray,
    *,
    n_blocks: int,
    block_bits: int,
    k: int,
    seed: int,
    block_hash: str = "ap",
) -> tuple[np.ndarray, np.ndarray]:
    """Blocked-spec coordinates (mirrors tpubloom.ops.blocked.block_positions,
    both in-block variants): returns ``(blk int64[B], bit uint32[B, k])``."""
    h_a = murmur3_32_np(keys, lengths, seed)
    g_a = fnv1a_32_np(keys, lengths)
    g_b = murmur3_32_np(keys, lengths, seed ^ SEED_XOR_GB)
    blk = (h_a & np.uint32(n_blocks - 1)).astype(np.int64)
    mask = np.uint32(block_bits - 1)
    if block_hash == "chunk":
        nb = (block_bits - 1).bit_length()
        if k * nb > 96:
            raise ValueError(
                f"chunk in-block hash needs k*log2(block_bits) <= 96 "
                f"(k={k}, {nb} bits/position)"
            )
        h_b = murmur3_32_np(keys, lengths, seed ^ SEED_XOR_HB)
        pool = (h_b, g_a, g_b)
        cols = []
        for i in range(k):
            sh = i * nb
            w, off = sh >> 5, sh & 31
            v = pool[w] >> np.uint32(off)
            if off + nb > 32:
                v = v | (pool[w + 1] << np.uint32(32 - off))
            cols.append(v & mask)
        return blk, np.stack(cols, axis=-1)
    if block_hash != "ap":
        raise ValueError(f"block_hash must be 'chunk' or 'ap', got {block_hash!r}")
    stride = g_b | np.uint32(1)
    i = np.arange(k, dtype=np.uint32)
    with np.errstate(over="ignore"):
        p = g_a[..., None] + i * stride[..., None]  # u32 wrap == mod 2^32
    return blk, p & mask


class CPUBlockedBloomFilter:
    """NumPy oracle for the blocked layout (tpubloom.ops.blocked spec).

    Like CPUBloomFilter, optionally dispatches the fused hot loop to the
    C++ native library; ``use_native=False`` pins pure NumPy (parity tests
    compare the two bit for bit).
    """

    def __init__(self, config: FilterConfig, *, use_native: bool | None = None):
        if not config.block_bits:
            config = config.replace(block_bits=512)
        self.config = config
        self.n_inserted = 0
        if use_native is None:
            use_native = native.available()
        self.use_native = use_native
        self.words = np.zeros(
            (config.n_blocks, config.words_per_block), dtype=np.uint32
        )

    def _packed(self, keys: Sequence[bytes | str]):
        return pack_keys(
            keys, self.config.key_len, key_policy=self.config.key_policy
        )

    def _spec_kwargs(self) -> dict:
        # the one definition of the blocked-spec parameter set, shared by
        # the native dispatch and the NumPy path
        return dict(
            n_blocks=self.config.n_blocks,
            block_bits=self.config.block_bits,
            k=self.config.k,
            seed=self.config.seed,
            block_hash=self.config.block_hash,
        )

    def _coords(self, keys: Sequence[bytes | str]):
        keys_u8, lengths = self._packed(keys)
        blk, bit = blocked_positions_np(keys_u8, lengths, **self._spec_kwargs())
        word = (bit >> np.uint32(5)).astype(np.int64)
        mask = np.uint32(1) << (bit & np.uint32(31))
        return blk, word, mask

    def insert_batch(self, keys: Sequence[bytes | str]) -> None:
        if self.use_native:
            keys_u8, lengths = self._packed(keys)
            native.blocked_insert(
                self.words, keys_u8, lengths, **self._spec_kwargs()
            )
        else:
            blk, word, mask = self._coords(keys)
            k = self.config.k
            np.bitwise_or.at(
                self.words, (np.repeat(blk, k), word.ravel()), mask.ravel()
            )
        self.n_inserted += len(keys)

    def include_batch(self, keys: Sequence[bytes | str]) -> np.ndarray:
        if self.use_native:
            keys_u8, lengths = self._packed(keys)
            return native.blocked_query(
                self.words, keys_u8, lengths, **self._spec_kwargs()
            ).astype(bool)
        blk, word, mask = self._coords(keys)
        vals = self.words[blk[:, None], word]
        return np.all((vals & mask) == mask, axis=-1)

    def insert(self, key: bytes | str) -> None:
        self.insert_batch([key])

    def include(self, key: bytes | str) -> bool:
        return bool(self.include_batch([key])[0])

    def clear(self) -> None:
        self.words[:] = 0
        self.n_inserted = 0

    def fill_ratio(self) -> float:
        set_bits = int(np.unpackbits(self.words.view(np.uint8)).sum())
        return set_bits / self.config.m

    def _set_words(self, words) -> None:
        """Replace storage from a flat array (checkpoint restore)."""
        self.words = (
            np.asarray(words, dtype=np.uint32).reshape(self.words.shape).copy()
        )

    def to_bytes(self) -> bytes:
        return self.words.astype("<u4").tobytes()

    @classmethod
    def from_bytes(cls, config: FilterConfig, data: bytes) -> "CPUBlockedBloomFilter":
        f = cls(config)
        arr = np.frombuffer(data, dtype="<u4").astype(np.uint32)
        f.words = arr.reshape(f.config.n_blocks, f.config.words_per_block)
        return f


class CPUBloomFilter:
    """NumPy bloom filter (plain or counting) with the framework's semantics.

    API parity with the reference front-end: ``insert`` / ``include`` /
    ``clear`` plus the batch forms the BASELINE north star adds
    (``insert_batch`` / ``include_batch``).
    """

    def __init__(self, config: FilterConfig, *, use_native: bool | None = None):
        """``use_native=None`` (default) auto-enables the C++ hot path for
        plain filters when libbloomhash builds; False forces pure NumPy
        (the parity tests pin the two paths bit-for-bit)."""
        self.config = config
        self.n_inserted = 0
        if use_native is None:
            use_native = not config.counting and native.available()
        if use_native and config.counting:
            raise ValueError("native path covers plain filters only")
        self.use_native = use_native
        if config.counting:
            self.words = np.zeros(config.n_counter_words, dtype=np.uint32)
        else:
            self.words = np.zeros(config.n_words, dtype=np.uint32)

    # -- packing -----------------------------------------------------------

    def _pack(self, keys: Sequence[bytes | str]) -> tuple[np.ndarray, np.ndarray]:
        return pack_keys(keys, self.config.key_len, key_policy=self.config.key_policy)

    def _positions(self, keys_u8: np.ndarray, lengths: np.ndarray) -> np.ndarray:
        return positions_np(
            keys_u8, lengths, m=self.config.m, k=self.config.k, seed=self.config.seed
        )

    # -- plain-filter ops ---------------------------------------------------

    def insert_batch(self, keys: Sequence[bytes | str]) -> None:
        keys_u8, lengths = self._pack(keys)
        if self.use_native:
            native.hash_insert(
                self.words, keys_u8, lengths,
                m=self.config.m, k=self.config.k, seed=self.config.seed,
            )
        else:
            pos = self._positions(keys_u8, lengths).ravel()
            if self.config.counting:
                self._counter_add(pos, +1)
            else:
                word = (pos >> np.uint64(5)).astype(np.int64)
                bit = (pos & np.uint64(31)).astype(np.uint32)
                np.bitwise_or.at(self.words, word, np.uint32(1) << bit)
        self.n_inserted += len(keys)

    def include_batch(self, keys: Sequence[bytes | str]) -> np.ndarray:
        keys_u8, lengths = self._pack(keys)
        if self.use_native:
            return native.hash_query(
                self.words, keys_u8, lengths,
                m=self.config.m, k=self.config.k, seed=self.config.seed,
            ).astype(bool)
        pos = self._positions(keys_u8, lengths)
        if self.config.counting:
            vals = self._counter_get(pos)
            return np.all(vals > 0, axis=-1)
        word = (pos >> np.uint64(5)).astype(np.int64)
        bit = (pos & np.uint64(31)).astype(np.uint32)
        hits = (self.words[word] >> bit) & np.uint32(1)
        return np.all(hits == 1, axis=-1)

    def insert(self, key: bytes | str) -> None:
        self.insert_batch([key])

    def include(self, key: bytes | str) -> bool:
        return bool(self.include_batch([key])[0])

    def clear(self) -> None:
        self.words[:] = 0
        self.n_inserted = 0

    def _set_words(self, words) -> None:
        """Replace storage from a flat array (checkpoint restore)."""
        self.words = (
            np.asarray(words, dtype=np.uint32).reshape(self.words.shape).copy()
        )

    # -- counting-filter ops ------------------------------------------------

    def delete_batch(self, keys: Sequence[bytes | str]) -> None:
        if not self.config.counting:
            raise ValueError("delete requires a counting filter")
        keys_u8, lengths = self._pack(keys)
        pos = self._positions(keys_u8, lengths).ravel()
        self._counter_add(pos, -1)
        self.n_inserted = max(0, self.n_inserted - len(keys))

    def delete(self, key: bytes | str) -> None:
        self.delete_batch([key])

    def _counter_add(self, pos: np.ndarray, delta: int) -> None:
        """Sequential saturating nibble add/sub — the semantic ground truth
        the device scatter-add kernel must reproduce (increments saturate at
        15; decrements floor at 0)."""
        word = (pos >> np.uint64(3)).astype(np.int64)
        nib = (pos & np.uint64(7)).astype(np.uint32)
        for w, n in zip(word, nib):
            shift = np.uint32(4) * n
            val = (self.words[w] >> shift) & np.uint32(15)
            new = min(15, int(val) + delta) if delta > 0 else max(0, int(val) + delta)
            self.words[w] = (self.words[w] & ~(np.uint32(15) << shift)) | (
                np.uint32(new) << shift
            )

    def _counter_get(self, pos: np.ndarray) -> np.ndarray:
        word = (pos >> np.uint64(3)).astype(np.int64)
        nib = (pos & np.uint64(7)).astype(np.uint32)
        return (self.words[word] >> (np.uint32(4) * nib)) & np.uint32(15)

    # -- introspection / persistence ----------------------------------------

    def fill_ratio(self) -> float:
        if self.config.counting:
            raise ValueError("fill_ratio is for plain filters")
        set_bits = int(np.unpackbits(self.words.view(np.uint8)).sum())
        return set_bits / self.config.m

    def estimated_fpr(self) -> float:
        return self.fill_ratio() ** self.config.k

    def to_redis_bitmap(self) -> bytes:
        if self.config.counting:
            raise ValueError("Redis bitmap export is for plain filters")
        return words_to_redis_bitmap(self.words, self.config.m)

    @classmethod
    def from_redis_bitmap(cls, config: FilterConfig, data: bytes) -> "CPUBloomFilter":
        f = cls(config)
        f.words = redis_bitmap_to_words(data, config.m)
        return f
