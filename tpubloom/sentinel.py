"""``python -m tpubloom.sentinel --watch host:port --peers ...``

Thin entry point for the failover watcher; the implementation lives in
:mod:`tpubloom.ha.sentinel` (quorum votes, most-caught-up promotion,
survivor re-pointing, stale-primary fencing).
"""

from tpubloom.ha.sentinel import Sentinel, main

__all__ = ["Sentinel", "main"]

if __name__ == "__main__":
    main()
