"""Multi-tenant filter paging (ISSUE 14): HBM as a cache over host RAM
over checkpoints.

Before this subsystem every filter lived in device HBM for the process
lifetime, so tenant count was capped by device memory rather than by
checkpoint storage. :class:`TenantStore` splits the flat server registry
into a registry/storage pair: each tenant is **RESIDENT** (device arrays
live, in ``service._filters``), **WARM** (serialized via
``ckpt.snapshot_blob`` into a bounded host-RAM pool), or **COLD**
(checkpoint/op-log only). Cold-ranked residents are evicted under a
configurable HBM budget and lazily re-hydrated on first RPC; concurrent
requests to an evicting/hydrating tenant block on a hydration future so
nobody ever sees a torn filter.

See :mod:`tpubloom.storage.residency` for the design notes (durability
invariants, lock ranks, the shed-path quota story).
"""

from tpubloom.storage.residency import StorageConfig, TenantStore

__all__ = ["StorageConfig", "TenantStore"]
